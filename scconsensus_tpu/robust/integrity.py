"""Computation-integrity sentinels: silent-corruption defense (round 18).

Every recovery layer before this one assumes a device that fails
LOUDLY: the r10 numeric sentinels stop at NaN/Inf, the r14 elastic mesh
evicts chips that die. Nothing catches a device (or a shape-dependent
code path) that returns a wrong-but-finite answer. This module is the
layer that proves the pipeline's own arithmetic, in three tiers behind
the registered ``SCC_INTEGRITY`` flag (``off | audit | enforce`` — the
residency-auditor mode pattern):

**(a) Algebraic invariant checks** fused at stage boundaries, each
O(output) and device-resident until the one scalar residual crosses to
host (declared ``integrity_check`` boundary):

  * ``wilcox_conservation`` — rank-sum conservation per ladder window:
    midranks over the M pooled cells of a pair sum to M(M+1)/2, so the
    Mann-Whitney U = rs1 − n1(n1+1)/2 must lie in [0, n1·n2] for every
    (pair, gene), the pooled tie term Σ(t³−t) in [0, M³−M], and log p
    ≤ 0 — rank mass can neither appear nor vanish without breaking one
    of these bounds;
  * ``bh_monotonic`` — BH-threshold monotonicity: adjusted q ≥ raw p
    (the cummin-from-the-right never lowers a p below itself when the
    multiplicity n ≥ rank) and q ≤ 1, elementwise over finite entries;
  * ``pca_orthonormal`` — the randomized-subspace basis must satisfy
    ‖V·Vᵀ − I‖∞ ≤ tol (computed inside the same jit as the scores);
  * ``landmark_occupancy`` — landmark occupancy conservation: the
    segment-sum of per-landmark occupancies equals the assigned-cell
    count, and every assignment indexes a live landmark;
  * ``contingency_sums`` — contingency-table row/col sums equal the
    input cluster sizes (and the grand total equals N).

Violations ride the ambient span (``integrity_violations`` counter) and
the run's integrity log; in **enforce** mode they raise
:class:`InvariantViolation` — typed, classified ``silent_corruption``
by ``robust.retry``, whose recovery is recompute-the-unit.

**(b) Sampled ghost-replay.** A deterministic, seeded sample of units —
one ladder window per rung (window width), one landmark block, one
streaming chunk per run, one serving batch per server — is recomputed
through an independent reference path (host float64 oracle: scipy
midranks + the R normal-approximation arithmetic for the rank test;
float64 matmul/argmin for the landmark and classify paths) and compared
within per-check tolerance bands. A mismatch raises
:class:`GhostReplayMismatch` (enforce) or records it (audit). Repeated
mismatch at one site feeds the elastic supervisor: after
``SCC_INTEGRITY_EVICT_THRESHOLD`` consecutive detections the retry
policy runs its ``on_device_loss`` hook — a chip that computes wrong
gets evicted like one that died (the mesh shrinks deterministically
onto survivors and the unit recomputes there).

**(c) Evidence.** The validated ``integrity`` run-record section
(checks planned/run/passed, violations, ghost-replay counters,
mismatches, recomputes — a section claiming ``all_checks_passed`` with
``checks_run < checks_planned`` is REJECTED naming the rule), ledger
manifest stamps, and the heartbeat panel ``tools/tail_run.py`` renders.

Import discipline: module import stays jax-free (``validate_run_record``
and the bench orchestrator load it); jax/scipy are imported inside the
check/replay functions only. The injected test vectors live in
``robust.faults`` (the ``corruption`` in-computation fault class).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from scconsensus_tpu.config import env_flag

__all__ = [
    "MODES",
    "IntegrityError",
    "InvariantViolation",
    "GhostReplayMismatch",
    "mode",
    "enabled",
    "enforcing",
    "begin_run",
    "current",
    "section",
    "live_summary",
    "validate_integrity",
    "TOLERANCES",
]

MODES = ("off", "audit", "enforce")

# Per-check tolerance bands (BASELINE.md "Integrity policy" documents
# them). Scaled by SCC_INTEGRITY_TOL_SCALE; the float32 kernels earn a
# real band — counts are exact below 2^24, but log-space p-values and
# projected scores round.
TOLERANCES: Dict[str, float] = {
    # invariant residuals (absolute)
    "wilcox_conservation": 0.51,   # U/ties bound slack: f32 half-ranks
    "bh_monotonic": 1e-3,          # log-space slack for q >= p, q <= 1
    "pca_orthonormal": 1e-3,       # max |V.Vt - I| after QR in f32
    "landmark_occupancy": 0.0,     # integer conservation is exact
    "contingency_sums": 0.0,       # integer conservation is exact
    # ghost-replay comparison bands (absolute, on the named quantity)
    "replay_wilcox_logp": 5e-2,    # f32 log-p vs float64 oracle
    "replay_wilcox_u": 0.51,       # U is half-integer-exact in f64
    "replay_landmark_d2": 1e-3,    # relative distance-tie slack
    "replay_classify_d2": 1e-3,
    "replay_pca": 1e-2,            # relative, on sampled score rows
}


class IntegrityError(RuntimeError):
    """Base of every typed integrity failure. Classified as the fifth
    error class ``silent_corruption`` by ``robust.retry`` (precedence
    device_lost > silent_corruption > disk > resource > transient);
    recovery is recompute-the-unit."""

    def __init__(self, msg: str, check: str = "", site: str = "",
                 magnitude: float = 0.0, tol: float = 0.0):
        super().__init__(msg)
        self.check = check
        self.site = site
        self.magnitude = float(magnitude)
        self.tol = float(tol)


class InvariantViolation(IntegrityError):
    """An algebraic invariant failed at a stage boundary (enforce mode):
    the computation produced output that no correct run of the algorithm
    can produce — rank mass created or destroyed, a non-orthonormal
    basis, occupancy that does not conserve cells."""


class GhostReplayMismatch(IntegrityError):
    """A sampled unit, recomputed through the independent float64 host
    oracle, disagreed with the device result beyond the check's
    tolerance band — silent corruption, detected."""


def mode() -> str:
    m = str(env_flag("SCC_INTEGRITY") or "off").lower()
    return m if m in MODES else "off"


def enabled() -> bool:
    return mode() != "off"


def enforcing() -> bool:
    return mode() == "enforce"


def tol(check: str) -> float:
    return TOLERANCES.get(check, 0.0) * float(
        env_flag("SCC_INTEGRITY_TOL_SCALE")
    )


# capped like robust.record's lists: a corruption storm must not grow a
# record without bound (counts stay exact; only event lists truncate)
_LIST_CAP = 64


class IntegrityLog:
    """Per-run integrity trail (thread-safe: the serving driver's worker
    thread and the heartbeat sampler both touch it)."""

    def __init__(self) -> None:
        self.mode = mode()
        # check name -> [planned, run, passed]
        self.checks: Dict[str, List[int]] = {}
        self.violations: List[Dict[str, Any]] = []
        self.replays_planned = 0
        self.replays_run = 0
        self.replays_passed = 0
        self.mismatches: List[Dict[str, Any]] = []
        self.recomputes = 0
        self.consumed_s = 0.0
        self.last_replay_unix: Optional[float] = None
        self._replayed_units: set = set()
        # thread id -> the (kind, key) most recently armed by
        # want_replay on that thread: the replay call follows the
        # arming synchronously, so a mismatch can re-arm exactly the
        # unit it caught (see note_mismatch)
        self._armed_by_thread: Dict[int, Any] = {}
        self._site_streak: Dict[str, int] = {}
        self._n_dropped = 0
        self._lock = threading.Lock()

    # -- counters ----------------------------------------------------------
    def _bucket(self, check: str) -> List[int]:
        return self.checks.setdefault(check, [0, 0, 0])

    def plan(self, check: str, n: int = 1) -> None:
        with self._lock:
            self._bucket(check)[0] += int(n)

    def note_check(self, check: str, site: str, ok: bool,
                   magnitude: float, tolerance: float) -> None:
        with self._lock:
            b = self._bucket(check)
            b[1] += 1
            if ok:
                b[2] += 1
                self._site_streak.pop(site, None)
            else:
                self._site_streak[site] = \
                    self._site_streak.get(site, 0) + 1
                item = {"check": check, "site": site,
                        "magnitude": round(float(magnitude), 6),
                        "tol": round(float(tolerance), 6)}
                if len(self.violations) < _LIST_CAP:
                    self.violations.append(item)
                else:
                    self._n_dropped += 1

    def note_mismatch(self, check: str, site: str, unit: str,
                      magnitude: float, tolerance: float) -> None:
        with self._lock:
            self.replays_run += 1
            self._site_streak[site] = self._site_streak.get(site, 0) + 1
            # re-arm the unit this thread just replayed: the
            # silent_corruption recovery recomputes it, and the
            # recomputed answer must be re-verified by the same replay
            # (otherwise corruption only the replay can catch would
            # survive the recompute unchecked — and single-unit sites
            # could never accumulate the eviction streak)
            armed = self._armed_by_thread.pop(
                threading.get_ident(), None)
            if armed is not None:
                self._replayed_units.discard(armed)
            item = {"check": check, "site": site, "unit": unit,
                    "magnitude": round(float(magnitude), 6),
                    "tol": round(float(tolerance), 6)}
            if len(self.mismatches) < _LIST_CAP:
                self.mismatches.append(item)
            else:
                self._n_dropped += 1
            self.last_replay_unix = time.time()

    def note_replay_ok(self, site: str) -> None:
        with self._lock:
            self.replays_run += 1
            self.replays_passed += 1
            self._site_streak.pop(site, None)
            self._armed_by_thread.pop(threading.get_ident(), None)
            self.last_replay_unix = time.time()

    def note_recompute(self) -> None:
        """A silent_corruption retry recovered: the corrupted unit was
        recomputed (robust.retry / the ladder recovery bump this)."""
        with self._lock:
            self.recomputes += 1

    def site_streak(self, site: str) -> int:
        with self._lock:
            return self._site_streak.get(site, 0)

    def reset_streak(self, site: str) -> None:
        with self._lock:
            self._site_streak.pop(site, None)

    def want_replay(self, kind: str, key) -> bool:
        """Deterministic unit sampling: the FIRST unit of each
        (kind, key) per run is the seeded sample — one ladder window per
        rung (key = window width), one landmark block, one streaming
        chunk, one serving batch per run. Also counts the plan. A
        mismatch re-arms the unit (note_mismatch), so the recomputed
        answer is verified by the same replay on the retry."""
        with self._lock:
            k = (kind, key)
            if k in self._replayed_units:
                return False
            self._replayed_units.add(k)
            self._armed_by_thread[threading.get_ident()] = k
            self.replays_planned += 1
            return True

    def add_consumed(self, dt: float) -> None:
        with self._lock:
            self.consumed_s += max(float(dt), 0.0)

    # -- section / live feed ----------------------------------------------
    def empty(self) -> bool:
        with self._lock:
            return not (self.checks or self.replays_planned
                        or self.mismatches or self.recomputes)

    def section(self) -> Optional[Dict[str, Any]]:
        """The run record's ``integrity`` section, or None when the layer
        never engaged (absence IS the off-mode signal — zero bytes of
        record overhead on an unaudited run)."""
        with self._lock:
            if not (self.checks or self.replays_planned
                    or self.mismatches or self.recomputes):
                return None
            planned = sum(b[0] for b in self.checks.values())
            run = sum(b[1] for b in self.checks.values())
            passed = sum(b[2] for b in self.checks.values())
            out: Dict[str, Any] = {
                "mode": self.mode,
                "checks": {"planned": planned, "run": run,
                           "passed": passed},
                "per_check": {
                    name: {"planned": b[0], "run": b[1], "passed": b[2]}
                    for name, b in sorted(self.checks.items())
                },
                "violations": [dict(v) for v in self.violations],
                "ghost": {
                    "planned": self.replays_planned,
                    "run": self.replays_run,
                    "passed": self.replays_passed,
                    "mismatches": [dict(m) for m in self.mismatches],
                    "recomputes": self.recomputes,
                },
                # COMPUTED, never asserted: all checks passed only when
                # every planned check ran, every run check passed, and
                # every ghost replay agreed (the validator rejects a
                # record claiming this with less)
                "all_checks_passed": bool(
                    run == planned and passed == run
                    and not self.violations
                    and self.replays_run == self.replays_planned
                    and self.replays_passed == self.replays_run
                ),
                "consumed_s": round(self.consumed_s, 4),
            }
            if self._n_dropped:
                out["events_dropped"] = self._n_dropped
            return out

    def live_summary(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            if not (self.checks or self.replays_planned
                    or self.mismatches):
                return None
            planned = sum(b[0] for b in self.checks.values())
            run = sum(b[1] for b in self.checks.values())
            passed = sum(b[2] for b in self.checks.values())
            out: Dict[str, Any] = {
                "mode": self.mode,
                "checks_planned": planned,
                "checks_run": run,
                "checks_passed": passed,
                "violations": len(self.violations),
                "replays_run": self.replays_run,
                "replays_planned": self.replays_planned,
                "mismatches": len(self.mismatches),
                "recomputes": self.recomputes,
            }
            if self.last_replay_unix is not None:
                # ghost-replay lag: how stale the newest oracle
                # comparison is — a long lag on a long run means the
                # sampled coverage stopped keeping up
                out["replay_age_s"] = round(
                    max(time.time() - self.last_replay_unix, 0.0), 1
                )
            return out


_RUN: Optional[IntegrityLog] = None


def begin_run() -> IntegrityLog:
    """Fresh integrity log for a new run (refine()/server entry)."""
    global _RUN
    _RUN = IntegrityLog()
    return _RUN


def current() -> IntegrityLog:
    global _RUN
    if _RUN is None:
        _RUN = IntegrityLog()
    return _RUN


def section() -> Optional[Dict[str, Any]]:
    return _RUN.section() if _RUN is not None else None


def live_summary() -> Optional[Dict[str, Any]]:
    return _RUN.live_summary() if _RUN is not None else None


class timed:
    """``with timed():`` accumulates the block's THREAD-CPU time onto
    the layer's self-measured overhead — the <2% audit-mode guard reads
    it. Thread CPU, not wall (the r15 serve-driver precedent): the
    checks' scalar fetch BLOCKS on the bucket kernel that was going to
    run anyway, and charging that wait here would bill the workload's
    own compute to the integrity layer (measured: 86% "overhead" by
    wall vs a ~0% differential — the stage-boundary sync pays the same
    wait a moment later)."""

    def __enter__(self):
        self._t0 = time.thread_time()
        return self

    def __exit__(self, *exc):
        current().add_consumed(time.thread_time() - self._t0)
        return False


def _span_violation(check: str, site: str) -> None:
    """Violations ride spans: bump the ambient span's counter so the
    trace/heartbeat sees WHERE integrity tripped."""
    try:
        from scconsensus_tpu.obs import trace as obs_trace

        sp = obs_trace.current_span()
        if sp is not None:
            sp.metrics.counter("integrity_violations").add(1)
            sp.attrs.setdefault("integrity_trips", []).append(
                f"{check}@{site}"
            )
    except Exception:
        pass


def _settle(check: str, site: str, residual: float,
            kind: str = "invariant", unit: str = "") -> None:
    """Record one check outcome; in enforce mode a violation raises the
    typed error (classified silent_corruption → recompute-the-unit)."""
    band = tol(check)
    ok = float(residual) <= band
    log = current()
    if kind == "replay":
        if ok:
            log.note_replay_ok(site)
            return
        log.note_mismatch(check, site, unit, residual, band)
    else:
        log.note_check(check, site, ok, residual, band)
        if ok:
            return
    _span_violation(check, site)
    from scconsensus_tpu.utils.logging import get_logger

    get_logger().warning(
        "integrity: %s %s at %s (unit %r): residual %.6g > tol %.6g",
        check, "ghost-replay MISMATCH" if kind == "replay"
        else "invariant VIOLATED", site, unit or site, residual, band,
    )
    if enforcing():
        cls = GhostReplayMismatch if kind == "replay" \
            else InvariantViolation
        raise cls(
            f"silent corruption: {check} at {site}"
            + (f" (unit {unit})" if unit else "")
            + f": residual {residual:.6g} exceeds the tolerance band "
            f"{band:.6g} — the computation produced an answer the "
            "algorithm cannot produce",
            check=check, site=site, magnitude=residual, tol=band,
        )


def should_evict(site: str) -> bool:
    """True when ``site`` accumulated SCC_INTEGRITY_EVICT_THRESHOLD
    consecutive silent-corruption detections: the retry policy escalates
    to its device-loss hook (mesh shrink) instead of another same-mesh
    recompute — a chip that computes wrong gets evicted like one that
    died."""
    thr = max(int(env_flag("SCC_INTEGRITY_EVICT_THRESHOLD")), 1)
    return current().site_streak(site) >= thr


# --------------------------------------------------------------------------
# (a) invariant checks — device-resident reductions, one scalar crosses
# --------------------------------------------------------------------------

def check_wilcox_bucket(site: str, log_p, u, ties, n1, n2) -> None:
    """Rank-sum conservation for one ladder bucket. ``log_p/u/ties`` are
    the kernel's (Gc, P) DEVICE outputs, ``n1/n2`` host (P,) group
    sizes. Midranks over the M = n1+n2 pooled cells sum to M(M+1)/2, so
    U ∈ [0, n1·n2], Σ(t³−t) ∈ [0, M³−M], and log p ≤ 0; the residual is
    the worst bound violation across the whole bucket — one fused
    device reduction, one scalar fetch."""
    if not enabled():
        return
    with timed():
        current().plan("wilcox_conservation")
        import jax
        import jax.numpy as jnp

        from scconsensus_tpu.obs.residency import boundary

        jn1 = jnp.asarray(np.asarray(n1, np.float32))
        jn2 = jnp.asarray(np.asarray(n2, np.float32))
        m = jn1 + jn2
        umax = jn1 * jn2
        tmax = m * m * m - m
        # Scale-aware slack: the kernel accumulates U and Σ(t³−t) in
        # float32, whose rounding at M³ ≈ 1e10 is O(relative), so each
        # bound earns max(band, 4e-6·bound) of slack — a real
        # corruption (1.5× scale, a sign flip) overshoots by ORDERS,
        # while honest f32 rounding stays inside. The residual is the
        # worst violation re-expressed in band units.
        band = max(tol("wilcox_conservation"), 1e-12)
        slack_u = jnp.maximum(band, 4e-6 * umax)[None, :]
        slack_t = jnp.maximum(band, 4e-6 * tmax)[None, :]
        # NaN entries (degenerate/untested) compare False and drop out
        # of the max via nan_to_num — legitimate NaN is the r10 numeric
        # sentinels' territory, not a conservation violation
        r_u = jnp.maximum(-u, u - umax[None, :]) / slack_u
        r_t = jnp.maximum(-ties, ties - tmax[None, :]) / slack_t
        r_p = log_p / jnp.float32(max(1e-3, band))
        resid = jnp.maximum(
            jnp.max(jnp.nan_to_num(r_u, nan=-jnp.inf)),
            jnp.maximum(
                jnp.max(jnp.nan_to_num(r_t, nan=-jnp.inf)),
                jnp.max(jnp.nan_to_num(r_p, nan=-jnp.inf)),
            ),
        )
        with boundary("integrity_check"):
            residual = float(jax.device_get(resid)) * band
    _settle("wilcox_conservation", site, residual)


def check_wilcox_host(site: str, lp: np.ndarray, u: np.ndarray,
                      n1, n2) -> None:
    """Host twin of :func:`check_wilcox_bucket` for blocks that already
    crossed (the streaming runner's per-chunk (P, Gb) fetch): U ∈
    [0, n1·n2] and log p ≤ 0, pure numpy, no device traffic."""
    if not enabled():
        return
    with timed():
        current().plan("wilcox_conservation")
        n1 = np.asarray(n1, np.float64)
        n2 = np.asarray(n2, np.float64)
        band = max(tol("wilcox_conservation"), 1e-12)
        umax = (n1 * n2)[:, None]
        slack_u = np.maximum(band, 4e-6 * umax)
        uu = np.asarray(u, np.float64)
        r_u = np.maximum(-uu, uu - umax) / slack_u
        lpp = np.asarray(lp, np.float64) / max(1e-3, band)
        resid = max(
            float(np.nanmax(r_u, initial=-np.inf)),
            float(np.nanmax(lpp, initial=-np.inf)),
        ) * band
        if not np.isfinite(resid):
            resid = 0.0
    _settle("wilcox_conservation", site, resid)


def check_bh(site: str, log_p, log_q) -> None:
    """BH-threshold monotonicity over finite entries: q ≥ p (the cummin
    never lowers a p below itself while n ≥ rank) and q ≤ 1. One fused
    device reduction over the (P, G) log arrays."""
    if not enabled():
        return
    with timed():
        current().plan("bh_monotonic")
        import jax
        import jax.numpy as jnp

        from scconsensus_tpu.obs.residency import boundary

        lp = jnp.asarray(log_p)
        lq = jnp.asarray(log_q)
        both = jnp.isfinite(lp) & jnp.isfinite(lq)
        # r1: q must not undercut p  (log_p - log_q <= 0)
        r1 = jnp.where(both, lp - lq, -jnp.inf)
        # r2: q <= 1  (log_q <= 0)
        r2 = jnp.where(jnp.isfinite(lq), lq, -jnp.inf)
        resid = jnp.maximum(jnp.max(r1), jnp.max(r2))
        with boundary("integrity_check"):
            residual = float(jax.device_get(resid))
    if not np.isfinite(residual):
        residual = 0.0  # nothing finite to check (all-NaN slab)
    _settle("bh_monotonic", site, residual)


def check_pca_basis(site: str, residual) -> None:
    """Orthonormality residual ‖V·Vᵀ − I‖∞ of the randomized-subspace
    basis — computed inside the scores jit (ops.pca), one scalar."""
    if not enabled():
        return
    with timed():
        current().plan("pca_orthonormal")
        import jax

        from scconsensus_tpu.obs.residency import boundary

        with boundary("integrity_check"):
            r = float(jax.device_get(residual))
    _settle("pca_orthonormal", site, r)


def check_landmark_occupancy(site: str, assign: np.ndarray,
                             k: int, n_cells: int) -> None:
    """Landmark occupancy conservation: the segment-sum of per-landmark
    occupancies equals the assigned-cell count, and every assignment
    indexes a live landmark. Host ints (the assignment is a host output
    by construction) — exact, zero-tolerance."""
    if not enabled():
        return
    with timed():
        current().plan("landmark_occupancy")
        a = np.asarray(assign)
        # out-of-range indices are counted FIRST and excluded from the
        # bincount: np.bincount raises on negatives, and an untyped
        # ValueError here would be exactly the corruption this check
        # exists to convert into a typed violation
        bad_idx = int((a < 0).sum() + (a >= int(k)).sum())
        good = a[(a >= 0) & (a < int(k))]
        occ = np.bincount(good, minlength=int(k)) if good.size else \
            np.zeros(int(k), np.int64)
        residual = float(abs(int(occ.sum()) - int(n_cells)) + bad_idx)
    _settle("landmark_occupancy", site, residual)


def check_contingency(site: str, mat: np.ndarray, ridx: np.ndarray,
                      cidx: np.ndarray) -> None:
    """Contingency-table conservation: row sums equal the first
    labeling's cluster sizes, col sums the second's, the grand total N.
    ``ridx``/``cidx`` are the unique-inverse index vectors the table was
    built from — the independent count."""
    if not enabled():
        return
    with timed():
        current().plan("contingency_sums")
        m = np.asarray(mat, np.int64)
        want_rows = np.bincount(np.asarray(ridx), minlength=m.shape[0])
        want_cols = np.bincount(np.asarray(cidx), minlength=m.shape[1])
        residual = float(
            np.abs(m.sum(axis=1) - want_rows).sum()
            + np.abs(m.sum(axis=0) - want_cols).sum()
            + abs(int(m.sum()) - int(np.asarray(ridx).size))
        )
    _settle("contingency_sums", site, residual)


# --------------------------------------------------------------------------
# (b) ghost replay — the independent float64 host oracle
# --------------------------------------------------------------------------

def _midranks64(x: np.ndarray) -> Tuple[np.ndarray, float]:
    """Float64 midranks + pooled tie term Σ(t³−t) — the r6 host
    contraction forms' reference arithmetic, scipy-ranked."""
    from scipy.stats import rankdata

    r = rankdata(x.astype(np.float64), method="average")
    _, counts = np.unique(x.astype(np.float64), return_counts=True)
    t = counts.astype(np.float64)
    return r, float(np.sum(t * t * t - t))


def wilcox_oracle_pair(vals: np.ndarray, cids: np.ndarray,
                       n1: int, n2: int, i: int, j: int,
                       pad_zeros: bool = True) -> Tuple[float, float]:
    """R's normal-approximation rank-sum for ONE (gene, pair) in pure
    float64 — the independent reference path the device ladder is
    replayed against. With ``pad_zeros`` (compacted windows) ``vals``
    holds only the gene's stored POSITIVE entries and absent cells are
    implicit zeros, padded here to the full group sizes ``n1``/``n2``;
    without it (full dense rows) every cell is explicit and values pass
    through as-is. Returns (log_p, U); degenerate slices return
    (nan, U) exactly like the kernel."""
    import math

    from scipy.stats import norm

    v = np.asarray(vals, np.float64)
    c = np.asarray(cids)
    if pad_zeros:
        g1 = v[(c == i) & (v > 0)]
        g2 = v[(c == j) & (v > 0)]
        g1 = np.concatenate([g1, np.zeros(max(int(n1) - g1.size, 0))])
        g2 = np.concatenate([g2, np.zeros(max(int(n2) - g2.size, 0))])
    else:
        g1 = v[c == i]
        g2 = v[c == j]
    pooled = np.concatenate([g1, g2])
    ranks, tie_sum = _midranks64(pooled)
    rs1 = float(ranks[: g1.size].sum())
    u = rs1 - n1 * (n1 + 1.0) / 2.0
    z = u - n1 * n2 / 2.0
    z = z - math.copysign(0.5, z) if z != 0.0 else 0.0
    m = float(n1 + n2)
    sigma2 = (n1 * n2 / 12.0) * (
        (m + 1.0) - tie_sum / max(m * (m - 1.0), 1.0)
    )
    if n1 < 1 or n2 < 1 or sigma2 <= 0.0:
        return float("nan"), u
    log_p = min(math.log(2.0) + float(norm.logcdf(-abs(z / math.sqrt(sigma2)))),
                0.0)
    return log_p, u


def _sample_idx(n: int, k: int) -> np.ndarray:
    """Deterministic spread sample of ``k`` indices over [0, n)."""
    if n <= k:
        return np.arange(n)
    return np.unique(np.linspace(0, n - 1, k).astype(np.int64))


def replay_wilcox_window(
    site: str, unit: str,
    vals: np.ndarray,            # (Rows, W) host window values
    cids,                        # (W,) or (Rows, W) host cluster ids
    n_of: np.ndarray,            # (K,) full group sizes
    pair_i: np.ndarray, pair_j: np.ndarray,
    out_lp, out_u,               # (Rows, P) DEVICE kernel outputs
    n_rows: int,
    full_rows: bool = False,     # True: vals rows hold ALL cells (dense)
    n_genes_sample: int = 3, n_pairs_sample: int = 3,
) -> None:
    """Ghost-replay one sampled ladder window: recompute a seeded
    (genes × pairs) sample through :func:`wilcox_oracle_pair` and
    compare log-p / U within the tolerance bands. Compacted windows
    arrive as host arrays (the pre-upload vals/cids), so the only
    crossing is the sampled output rows; dense-device buckets
    additionally fetch the sampled INPUT rows — both ride the declared
    ``integrity_check`` boundary."""
    if not enabled():
        return
    with timed():
        import jax
        import jax.numpy as jnp

        from scconsensus_tpu.obs.residency import boundary

        g_sel = _sample_idx(int(n_rows), n_genes_sample)
        ok_pairs = np.nonzero(
            (np.asarray(n_of)[pair_i] >= 1)
            & (np.asarray(n_of)[pair_j] >= 1)
        )[0]
        if not g_sel.size or not ok_pairs.size:
            current().note_replay_ok(site)
            return
        p_sel = ok_pairs[_sample_idx(int(ok_pairs.size), n_pairs_sample)]
        with boundary("integrity_check"):
            lp_dev, u_dev = jax.device_get((
                jnp.asarray(out_lp)[jnp.asarray(g_sel)][
                    :, jnp.asarray(p_sel)],
                jnp.asarray(out_u)[jnp.asarray(g_sel)][
                    :, jnp.asarray(p_sel)],
            ))
            if not isinstance(vals, np.ndarray):
                vals = np.asarray(jax.device_get(
                    jnp.asarray(vals)[jnp.asarray(g_sel)]
                ))
                g_sel_local = np.arange(vals.shape[0])
            else:
                vals = vals[g_sel]
                g_sel_local = np.arange(vals.shape[0])
            if not (isinstance(cids, np.ndarray)
                    or isinstance(cids, (list, tuple))):
                if getattr(cids, "ndim", 1) == 2:
                    cids = np.asarray(jax.device_get(
                        jnp.asarray(cids)[jnp.asarray(g_sel)]
                    ))
                else:
                    cids = np.asarray(jax.device_get(cids))
            elif np.asarray(cids).ndim == 2:
                cids = np.asarray(cids)[g_sel]
        # one dimensionless residual: each delta normalized by its own
        # band, the worst carried; _settle re-scales onto the logp band
        # so the recorded magnitude/tol pair stays interpretable
        worst_norm = 0.0
        tol_p = max(tol("replay_wilcox_logp"), 1e-12)
        tol_u = max(tol("replay_wilcox_u"), 1e-12)
        cids = np.asarray(cids)
        for gi in g_sel_local:
            row = np.asarray(vals[gi], np.float64)
            crow = cids[gi] if cids.ndim == 2 else cids
            for pi, p in enumerate(p_sel):
                i, j = int(pair_i[p]), int(pair_j[p])
                n1, n2 = int(n_of[i]), int(n_of[j])
                if full_rows:
                    sel = (crow == i) | (crow == j)
                    lp_ref, u_ref = wilcox_oracle_pair(
                        row[sel], crow[sel], n1, n2, i, j,
                        pad_zeros=False,
                    )
                else:
                    lp_ref, u_ref = wilcox_oracle_pair(
                        row, crow, n1, n2, i, j
                    )
                lp_d, u_d = float(lp_dev[gi, pi]), float(u_dev[gi, pi])
                if np.isnan(lp_ref) != np.isnan(lp_d):
                    worst_norm = max(worst_norm, float("inf"))
                    continue
                if not np.isnan(lp_ref):
                    # absolute band near 0, relative (2 %) for the huge
                    # negative log-p where f32 logcdf rounding grows
                    band = max(tol_p, 0.02 * abs(lp_ref))
                    worst_norm = max(worst_norm,
                                     abs(lp_ref - lp_d) / band)
                worst_norm = max(worst_norm, abs(u_ref - u_d) / tol_u)
        worst = worst_norm * tol("replay_wilcox_logp")
    _settle("replay_wilcox_logp", site, worst, kind="replay", unit=unit)


def replay_stream_chunk(site: str, unit: str, block, cids: np.ndarray,
                        n_of: np.ndarray, pair_i: np.ndarray,
                        pair_j: np.ndarray, lp: np.ndarray,
                        u: np.ndarray, n_genes_sample: int = 3,
                        n_pairs_sample: int = 3) -> None:
    """Ghost-replay one streaming chunk: a seeded (genes × pairs)
    sample of the chunk's (P, Gb) host outputs recomputed through the
    float64 oracle from the CSR slab's own rows — entirely host-side
    (the block and its outputs already crossed on the stream
    boundaries), so the replay adds zero device traffic."""
    if not enabled():
        return
    with timed():
        gb = int(block.shape[0])
        g_sel = _sample_idx(gb, n_genes_sample)
        ok_pairs = np.nonzero(
            (np.asarray(n_of)[pair_i] >= 1)
            & (np.asarray(n_of)[pair_j] >= 1)
        )[0]
        if not g_sel.size or not ok_pairs.size:
            current().note_replay_ok(site)
            return
        p_sel = ok_pairs[_sample_idx(int(ok_pairs.size), n_pairs_sample)]
        rows = np.asarray(block[g_sel].toarray(), np.float64)
        worst_norm = 0.0
        tol_p = max(tol("replay_wilcox_logp"), 1e-12)
        tol_u = max(tol("replay_wilcox_u"), 1e-12)
        lp = np.asarray(lp)
        u = np.asarray(u)
        for gi, g in enumerate(g_sel):
            for p in p_sel:
                i, j = int(pair_i[p]), int(pair_j[p])
                n1, n2 = int(n_of[i]), int(n_of[j])
                sel = (cids == i) | (cids == j)
                lp_ref, u_ref = wilcox_oracle_pair(
                    rows[gi][sel], np.asarray(cids)[sel], n1, n2, i, j,
                    pad_zeros=False,
                )
                lp_d, u_d = float(lp[p, g]), float(u[p, g])
                if np.isnan(lp_ref) != np.isnan(lp_d):
                    worst_norm = max(worst_norm, float("inf"))
                    continue
                if not np.isnan(lp_ref):
                    band = max(tol_p, 0.02 * abs(lp_ref))
                    worst_norm = max(worst_norm,
                                     abs(lp_ref - lp_d) / band)
                worst_norm = max(worst_norm, abs(u_ref - u_d) / tol_u)
        worst = worst_norm * tol("replay_wilcox_logp")
    _settle("replay_wilcox_logp", site, worst, kind="replay", unit=unit)


def replay_landmark_block(site: str, x_rows, cent: np.ndarray,
                          assign_rows: np.ndarray, unit: str = "block0",
                          ) -> None:
    """Ghost-replay one landmark-assignment block: float64 nearest-
    landmark argmin vs the device assignment, tie-tolerant (a device
    pick is wrong only if the oracle's choice is STRICTLY closer beyond
    the relative band — f32 ties may break either way). Device
    ``x_rows`` fetch on the declared boundary."""
    if not enabled():
        return
    with timed():
        if not isinstance(x_rows, np.ndarray):
            import jax

            from scconsensus_tpu.obs.residency import boundary

            with boundary("integrity_check"):
                x_rows = np.asarray(jax.device_get(x_rows))
        x = np.asarray(x_rows, np.float64)
        c = np.asarray(cent, np.float64)
        a = np.asarray(assign_rows)
        d2 = (
            np.sum(x * x, axis=1, keepdims=True)
            - 2.0 * x @ c.T
            + np.sum(c * c, axis=1)[None, :]
        )
        best = np.min(d2, axis=1)
        chosen = d2[np.arange(a.size), np.clip(a, 0, c.shape[0] - 1)]
        scale = np.maximum(np.abs(best), 1e-9)
        bad_idx = (a < 0) | (a >= c.shape[0])
        worst = float(np.max(np.where(
            bad_idx, np.inf, (chosen - best) / scale
        ))) if a.size else 0.0
    _settle("replay_landmark_d2", site, worst, kind="replay", unit=unit)


def replay_pca_rows(site: str, x, mean, components, scores,
                    n_rows: int, unit: str = "rows",
                    n_sample: int = 4) -> None:
    """Ghost-replay sampled embedding rows: float64
    (x − mean) @ componentsᵀ vs the device scores, relative band. ``x``
    and ``scores`` may be device arrays — the seeded sample rows (plus
    the small mean/basis) are the only crossing, on the declared
    boundary."""
    if not enabled():
        return
    with timed():
        import jax
        import jax.numpy as jnp

        from scconsensus_tpu.obs.residency import boundary

        sel = _sample_idx(int(n_rows), n_sample)
        if not sel.size:
            current().note_replay_ok(site)
            return
        with boundary("integrity_check"):
            xr, sr, mu, vt = jax.device_get((
                jnp.asarray(x)[jnp.asarray(sel)],
                jnp.asarray(scores)[jnp.asarray(sel)],
                jnp.asarray(mean), jnp.asarray(components),
            ))
        xh = np.asarray(xr, np.float64)
        ref = (xh - np.asarray(mu, np.float64)[None, :]) \
            @ np.asarray(vt, np.float64).T
        got = np.asarray(sr, np.float64)
        scale = max(float(np.max(np.abs(ref))), 1e-6)
        worst = float(np.max(np.abs(ref - got))) / scale
    _settle("replay_pca", site, worst, kind="replay", unit=unit)


def replay_classify(site: str, x: np.ndarray, labels: np.ndarray,
                    model, unit: str = "batch") -> None:
    """Ghost-replay one serving batch: the frozen model's float64 host
    mirror (classify_host) vs the device labels, distance-tie-tolerant.
    A disagreement beyond the band means the device path answered with
    labels its own model cannot produce."""
    if not enabled():
        return
    with timed():
        ref_lab, _ = model.classify_host(np.asarray(x))
        got = np.asarray(labels)
        if got.shape != ref_lab.shape:
            worst = float("inf")
        else:
            diff = got != ref_lab
            if not diff.any():
                worst = 0.0
            else:
                # tie tolerance: a differing label is a true mismatch
                # only when the oracle's landmark is strictly closer
                # than the device's beyond the relative band
                xp = model._gather_panel(np.asarray(x)).astype(np.float64)
                proj = (xp - model.pca_mean.astype(np.float64)) @ \
                    model.pca_components.astype(np.float64).T
                c = model.centroids.astype(np.float64)
                d2 = (
                    np.sum(proj * proj, axis=1, keepdims=True)
                    - 2.0 * proj @ c.T
                    + np.sum(c * c, axis=1)[None, :]
                )
                best = np.min(d2, axis=1)
                lab_to_cent: Dict[int, np.ndarray] = {}
                clab = model.centroid_labels.astype(np.int64)
                worst = 0.0
                for r in np.nonzero(diff)[0]:
                    lr = int(got[r])
                    cands = lab_to_cent.setdefault(
                        lr, np.nonzero(clab == lr)[0]
                    )
                    chosen = float(np.min(d2[r, cands])) if cands.size \
                        else float("inf")
                    worst = max(
                        worst,
                        (chosen - float(best[r]))
                        / max(abs(float(best[r])), 1e-9),
                    )
    _settle("replay_classify_d2", site, worst, kind="replay", unit=unit)


# --------------------------------------------------------------------------
# schema validation (stdlib — validate_run_record dispatches here)
# --------------------------------------------------------------------------

def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"integrity section: {msg}")


def _nonneg(v: Any, name: str) -> int:
    _require(isinstance(v, int) and v >= 0,
             f"{name} must be an int >= 0, got {v!r}")
    return v


def validate_integrity(ig: Dict[str, Any]) -> None:
    """Structural validation of a record's ``integrity`` section. The
    load-bearing rule (the perf-gate smoke pins it): a section claiming
    ``all_checks_passed`` must have run every check it planned, passed
    every check it ran, and matched every ghost replay — claims must
    carry evidence."""
    _require(isinstance(ig, dict), "must be an object")
    _require(ig.get("mode") in ("audit", "enforce"),
             f"mode must be 'audit' or 'enforce', got {ig.get('mode')!r}")
    ch = ig.get("checks")
    _require(isinstance(ch, dict), "checks must be an object")
    planned = _nonneg(ch.get("planned"), "checks.planned")
    run = _nonneg(ch.get("run"), "checks.run")
    passed = _nonneg(ch.get("passed"), "checks.passed")
    _require(run <= planned,
             f"checks.run ({run}) exceeds checks.planned ({planned})")
    _require(passed <= run,
             f"checks.passed ({passed}) exceeds checks.run ({run})")
    violations = ig.get("violations", [])
    _require(isinstance(violations, list), "violations must be a list")
    for i, v in enumerate(violations):
        _require(isinstance(v, dict) and bool(v.get("check"))
                 and bool(v.get("site")),
                 f"violations[{i}] needs check and site")
    per = ig.get("per_check", {})
    _require(isinstance(per, dict), "per_check must be an object")
    for name, b in per.items():
        _require(isinstance(b, dict), f"per_check[{name}] must be an "
                                      "object")
        p_, r_, s_ = (_nonneg(b.get(k), f"per_check[{name}].{k}")
                      for k in ("planned", "run", "passed"))
        _require(s_ <= r_ <= p_,
                 f"per_check[{name}] counters must satisfy "
                 "passed <= run <= planned")
    gh = ig.get("ghost")
    _require(isinstance(gh, dict), "ghost must be an object")
    g_planned = _nonneg(gh.get("planned"), "ghost.planned")
    g_run = _nonneg(gh.get("run"), "ghost.run")
    g_passed = _nonneg(gh.get("passed"), "ghost.passed")
    _require(g_run <= g_planned,
             f"ghost.run ({g_run}) exceeds ghost.planned ({g_planned})")
    _require(g_passed <= g_run,
             f"ghost.passed ({g_passed}) exceeds ghost.run ({g_run})")
    mms = gh.get("mismatches", [])
    _require(isinstance(mms, list), "ghost.mismatches must be a list")
    _require(len(mms) <= max(g_run - g_passed, 0),
             f"ghost.mismatches lists {len(mms)} entries but only "
             f"{max(g_run - g_passed, 0)} replays failed — a mismatch "
             "that never ran is fabricated evidence")
    recomputes = _nonneg(gh.get("recomputes", 0), "ghost.recomputes")
    if ig.get("all_checks_passed"):
        _require(
            run == planned,
            "all_checks_passed claimed with checks_run < checks_planned "
            f"({run} < {planned}) — a check that never ran proves "
            "nothing, and claiming otherwise is the exact failure this "
            "layer exists to catch",
        )
        _require(passed == run and not violations,
                 "all_checks_passed claimed with failed checks or "
                 "recorded violations — the claim contradicts its own "
                 "evidence")
        _require(g_run == g_planned and g_passed == g_run,
                 "all_checks_passed claimed with unmatched or unrun "
                 "ghost replays")
    if recomputes:
        _require(
            len(mms) >= 1 or g_run > g_passed or passed < run
            or bool(violations),
            "recomputes claimed with no recorded detection (no "
            "mismatch, no violation) — a recompute without a detection "
            "is a phantom corruption",
        )
