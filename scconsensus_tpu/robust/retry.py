"""The one typed retry/degradation policy engine.

Every recovery path in the repo — the devcache upload's evict-and-retry
(formerly a bare try/except), the wilcox ladder's adaptive degrade, the
embed stage, the pipeline's stage-boundary recovery — runs through
:meth:`RetryPolicy.call`:

  1. classify the exception: ``transient`` (backend/RPC hiccup — retry
     as-is), ``resource`` (allocation failure — run the caller's
     ``degrade`` hook, then retry), ``disk`` (round 17 — ENOSPC/EIO,
     torn or checksum-failed chunks/artifacts: the ``degrade`` hook runs
     too, because the right retry is a *different* write — sweep
     reclaimable files, shrink checkpoint granularity — while the
     quarantine machinery has already isolated anything torn),
     ``silent_corruption`` (round 18 — a computation-integrity
     detection, robust.integrity: an invariant violated at a stage
     boundary or a ghost-replay mismatch against the float64 oracle.
     The recovery is recompute-the-unit — a plain retry, because the
     corrupted VALUES never left the unit; the degrade hook does NOT
     run (there is nothing to free or shrink — the answer was wrong,
     not big). REPEATED detection at one site escalates: once
     ``integrity.should_evict`` trips, the retry runs the caller's
     ``on_device_loss`` hook instead, so a chip that computes wrong
     gets evicted like one that died),
     ``device_lost`` (a lost/preempted
     device or a mesh whose device set no longer exists — run the
     caller's ``on_device_loss`` hook, which rebuilds the mesh on
     survivors (robust.elastic), then retry; without a hook the class
     is FATAL, because retrying against a dead mesh just loops),
     ``fatal`` (everything else — re-raise immediately, a ValueError
     must never burn retry budget);
  2. respect the per-run retry budget (``SCC_ROBUST_BUDGET``) — a retry
     storm converts to a clean failure, not an unbounded loop;
  3. back off exponentially with deterministic jitter (seeded by the
     site name, so runs reproduce);
  4. record every attempt: a ``robust_retry`` span event on the ambient
     tracer, a ``robust_retries`` counter on the enclosing span, and an
     entry in the run's robustness log (-> the validated ``robustness``
     run-record section).

``KeyboardInterrupt``/``SystemExit`` are never caught: an operator's
ctrl-C (and the artifact-resume tests that simulate it) must keep its
existing semantics.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Callable, Optional

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.robust import faults, record

__all__ = [
    "ERROR_CLASSES",
    "classify_exception",
    "classify_text",
    "RetryPolicy",
    "call",
    "default_policy",
]

ERROR_CLASSES = ("transient", "resource", "disk", "silent_corruption",
                 "device_lost", "fatal")

# Message fragments, lowercase. Matched against str(exc) / raw text; the
# XLA runtime stringifies device failures with their gRPC-style status
# names, so text is the one classification surface that works for real
# XlaRuntimeError, injected faults, and a dead worker's stderr tail alike.
_RESOURCE_PAT = (
    "resource_exhausted", "resource exhausted", "out of memory", "oom",
    "allocation fail", "failed to allocate", "memoryerror",
    "cannot allocate",
)
_TRANSIENT_PAT = (
    "unavailable", "deadline_exceeded", "deadline exceeded", "aborted",
    "connection reset", "connection refused", "broken pipe", "timed out",
    "transient", "socket closed", "internal: failed to connect",
)
# Disk-fault signatures (round 17, the out-of-core streaming layer):
# what the OS and the artifact layer actually say when the DISK — not the
# device, not the allocator — failed: ENOSPC/EIO strerror text, and the
# artifact/chunk checksum layer's torn-write diagnoses. Classified as
# their own class because the right adaptation is disk-shaped (sweep
# reclaimable files, shrink checkpoint granularity, quarantine-and-
# recompute the torn chunk) — neither a mesh rebuild nor an HBM degrade
# helps a full filesystem.
_DISK_PAT = (
    "enospc", "no space left on device",
    "input/output error", "disk i/o error",
    "read-only file system",
    "checksum mismatch", "torn chunk", "unparseable npz",
    "sidecar unreadable",
)
# Silent-corruption signatures (round 18, robust.integrity): the typed
# integrity errors stringify with these — and a remote worker's stderr
# tail carrying them classifies the same way. Loses only to device_lost
# (a dead chip may also miscompute on the way down, and only a mesh
# rebuild helps); wins over disk/resource/transient because the right
# retry is a RECOMPUTE of the unit, not a different write, a smaller
# shape, or an unchanged re-dispatch of the program that just proved it
# computes wrong.
_SILENT_CORRUPTION_PAT = (
    "silent corruption", "silent_corruption",
    "ghost replay mismatch", "ghost-replay mismatch",
    "integrity violation", "invariant violated",
)
# Device-loss signatures: what the XLA/PJRT runtime actually prints when
# a chip dies or is preempted mid-program, plus the JAX-level errors a
# Mesh raises once its device set no longer matches the live client
# (a preempted TPU slice re-enumerates with fresh device objects).
_DEVICE_LOST_PAT = (
    "device lost", "device is lost", "device was lost",
    "device preempted", "preemption", "worker preempted",
    # NOTE deliberately absent: "halted by previous error" — XLA emits it
    # as follow-on noise after ANY prior failure (an OOM's aftermath most
    # commonly), and classifying it device_lost would trigger the
    # exactly-wrong adaptation (shrink the mesh instead of degrade)
    "device not found", "no such device", "device has been removed",
    "chip is unhealthy", "device unhealthy",
    "data_loss", "failed_precondition: device",
    "failed precondition: device",
    "device assignment", "mesh should contain", "mismatched devices",
    "not addressable",
)


def classify_text(text: Optional[str]) -> Optional[str]:
    """'device_lost' | 'silent_corruption' | 'disk' | 'resource' |
    'transient' | None (no signature recognized) for raw text — stderr
    tails, TUNNEL_LOG probe errors, heartbeat post-mortems. Device-loss
    wins over everything (a dead chip often also prints UNAVAILABLE,
    and only a mesh rebuild helps); silent_corruption wins over
    disk/resource/transient (an integrity detection names the wrongness
    of the ANSWER — recompute-the-unit is the only retry that can fix
    it); disk wins over resource/transient (an ENOSPC strerror also
    says "error", and retrying a full filesystem unchanged loops);
    resource wins over transient (degrading is the safer adaptation — a
    transient retry of a genuinely too-big shape loops)."""
    if not text:
        return None
    low = str(text).lower()
    if any(p in low for p in _DEVICE_LOST_PAT):
        return "device_lost"
    if any(p in low for p in _SILENT_CORRUPTION_PAT):
        return "silent_corruption"
    if any(p in low for p in _DISK_PAT):
        return "disk"
    if any(p in low for p in _RESOURCE_PAT):
        return "resource"
    if any(p in low for p in _TRANSIENT_PAT):
        return "transient"
    return None


def classify_exception(exc: BaseException) -> str:
    """Error class of an exception: type first (MemoryError, the injected
    fault types, OSError errno for the disk family), then message text,
    else fatal."""
    if isinstance(exc, faults.InjectedDeviceLoss):
        return "device_lost"
    # the typed integrity errors classify BEFORE their message is
    # consulted (type-first, like the injected fault family): the
    # signature matrix test pins tolerance-band mismatch, float64-oracle
    # disagreement, and injected bit-flip all landing here
    from scconsensus_tpu.robust import integrity as _integrity

    if isinstance(exc, _integrity.IntegrityError):
        return "silent_corruption"
    if isinstance(exc, faults.InjectedDiskFault):
        return "disk"
    if isinstance(exc, (MemoryError, faults.InjectedResourceExhausted)):
        return "resource"
    if isinstance(exc, faults.InjectedTransientError):
        return "transient"
    if isinstance(exc, OSError) and getattr(exc, "errno", None) in (
            28, 5, 30):  # ENOSPC, EIO, EROFS — the disk family by number
        return "disk"
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return "transient"
    return classify_text(f"{type(exc).__name__}: {exc}") or "fatal"


def _jitter(site: str, attempt: int) -> float:
    """Deterministic jitter fraction in [0, 1): hash-derived so retry
    timing reproduces run-to-run (no Date/random dependence)."""
    h = hashlib.sha256(f"{site}:{attempt}".encode()).digest()
    return int.from_bytes(h[:4], "big") / 2**32


class RetryPolicy:
    """Retry policy for one call site family.

    ``max_attempts`` counts the first try (3 = up to 2 retries);
    ``backoff_base`` defaults to ``SCC_ROBUST_BACKOFF_S``. The per-run
    budget is shared across every policy instance (record.RunLog), so a
    pathological run cannot multiply site-level retries without bound.
    """

    def __init__(self, max_attempts: int = 3,
                 backoff_base: Optional[float] = None,
                 backoff_cap: float = 30.0):
        self.max_attempts = int(max_attempts)
        self.backoff_base = (
            float(env_flag("SCC_ROBUST_BACKOFF_S"))
            if backoff_base is None else float(backoff_base)
        )
        self.backoff_cap = float(backoff_cap)

    def backoff_s(self, site: str, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based): exponential with
        +0-50% deterministic jitter."""
        base = min(self.backoff_base * 2 ** (attempt - 1), self.backoff_cap)
        return base * (1.0 + 0.5 * _jitter(site, attempt))

    def call(self, fn: Callable[[], Any], site: str,
             degrade: Optional[Callable[[int], Any]] = None,
             classify: Callable[[BaseException], str] = classify_exception,
             on_device_loss: Optional[Callable[[int], Any]] = None,
             ) -> Any:
        """Run ``fn`` under this policy. ``degrade(attempt)`` runs before
        a resource-class retry (evict caches, halve a chunk ladder —
        whatever makes the retry *different*); ``on_device_loss(attempt)``
        runs before a device_lost-class retry (rebuild the mesh on
        surviving devices — robust.elastic wires the supervisor in here;
        without the hook device_lost is FATAL, since re-running the same
        program against a dead mesh can only fail again); a fault plan's
        injection for ``site`` fires at each attempt's entry, so an
        injected fault is recovered by the very machinery it tests."""
        from scconsensus_tpu.obs import trace as obs_trace

        run = record.current_run()
        attempt = 1
        backoff_total = 0.0
        while True:
            try:
                faults.fault_point(site)
                out = fn()
                if attempt > 1:
                    record.note_retry(site, err_class, attempt,
                                      recovered=True,
                                      backoff_s=backoff_total)
                    if err_class == "silent_corruption":
                        # the corrupted unit was recomputed clean — the
                        # integrity section's recovery evidence
                        from scconsensus_tpu.robust import (
                            integrity as _integrity,
                        )

                        _integrity.current().note_recompute()
                        _integrity.current().reset_streak(site)
                return out
            except Exception as e:
                err_class = classify(e)
                if err_class == "fatal" or (
                    err_class == "device_lost" and on_device_loss is None
                ):
                    raise
                if attempt >= self.max_attempts or not run.budget_take():
                    record.note_retry(site, err_class, attempt,
                                      recovered=False,
                                      backoff_s=backoff_total)
                    raise
                backoff = self.backoff_s(site, attempt)
                backoff_total += backoff
                # the attempt as a span event + counter: visible in the
                # span tree, Chrome traces, and the heartbeat stream
                sp = obs_trace.current_span()
                if sp is not None:
                    sp.metrics.counter("robust_retries").add(1)
                with obs_trace.span(
                    "robust_retry", site=site, error_class=err_class,
                    attempt=attempt, backoff_s=round(backoff, 4),
                ):
                    if err_class == "device_lost":
                        # the adaptation IS the recovery here: shrink the
                        # mesh onto survivors before re-entering the stage
                        on_device_loss(attempt)
                    elif err_class == "silent_corruption":
                        # recompute-the-unit: a plain retry, UNLESS the
                        # site keeps miscomputing — repeated detections
                        # past the eviction threshold run the device-
                        # loss hook, so a chip that computes wrong gets
                        # evicted like one that died (the shrunk mesh
                        # excludes it and the unit recomputes there)
                        from scconsensus_tpu.robust import (
                            integrity as _integrity,
                        )

                        # streak keyed on the DETECTION's own site (the
                        # ladder bucket, the serve device call), which a
                        # propagated error carries — the stage-level
                        # guard must escalate on the inner site's record
                        det_site = getattr(e, "site", "") or site
                        if (on_device_loss is not None
                                and _integrity.should_evict(det_site)):
                            _integrity.current().reset_streak(det_site)
                            try:
                                on_device_loss(attempt)
                                record.note_degradation(
                                    det_site,
                                    "evict-miscomputing-device",
                                    "repeated silent-corruption "
                                    "detections — mesh shrunk off the "
                                    "suspect chip before the recompute",
                                )
                            except Exception:
                                # no smaller mesh (serial run, floor
                                # reached): eviction is unavailable —
                                # the bounded recompute ladder is still
                                # the best remaining move, so keep
                                # retrying rather than converting a
                                # detected corruption into a crash
                                record.note_degradation(
                                    det_site, "eviction-unavailable",
                                    "repeated silent-corruption "
                                    "detections but no smaller mesh to "
                                    "shrink to; continuing recompute "
                                    "attempts",
                                )
                    elif degrade is not None and err_class in ("resource",
                                                               "disk"):
                        # both classes demand a DIFFERENT retry: resource
                        # frees memory, disk frees/shrinks what it writes
                        # (sweep reclaimable files, coarsen checkpoint
                        # granularity) — the caller's hook knows which
                        degrade(attempt)
                    time.sleep(backoff)
                attempt += 1


_DEFAULT: Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = RetryPolicy()
    return _DEFAULT


def call(fn: Callable[[], Any], site: str,
         degrade: Optional[Callable[[int], Any]] = None,
         policy: Optional[RetryPolicy] = None,
         on_device_loss: Optional[Callable[[int], Any]] = None) -> Any:
    """Module-level convenience: ``robust.call(fn, site=...)`` under the
    default policy."""
    return (policy or default_policy()).call(
        fn, site, degrade=degrade, on_device_loss=on_device_loss
    )
