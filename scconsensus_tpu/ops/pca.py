"""Truncated PCA via randomized subspace iteration — matmul-only, MXU-native.

Replaces ``irlba::prcomp_irlba(x, n=min(|U|,15), center=TRUE, scale.=FALSE)``
(R/reclusterDEConsensus.R:234, R/reclusterDEConsensusFast.R:398). Lanczos
recurrences are latency-bound on TPU; randomized subspace iteration is pure
matmuls and converges to the same leading subspace (power iterations with QR
re-orthogonalization; Halko et al. 2011).

Signs of components are arbitrary (as with irlba); downstream consumers
(euclidean distance, Ward linkage) are sign-invariant.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from scconsensus_tpu.obs.graphs import instrument as _passport

__all__ = ["pca_scores", "pca_scores_audited", "pca_basis"]


def _subspace_basis(x, n_components: int, n_oversample: int, n_iter: int,
                    seed: int):
    """The one randomized-subspace-iteration body behind both public
    entry points: returns ``(mean (F,), vt (n_components, F), xc)``.
    Shared so the serving guarantee — a frozen model's persisted basis
    reproduces the pipeline's scores — holds by construction, not by
    keeping two copies of this loop in sync."""
    n, f = x.shape
    k = min(n_components + n_oversample, f, n)
    mean = jnp.mean(x, axis=0)
    xc = x - mean[None, :]
    omega = jax.random.normal(jax.random.PRNGKey(seed), (f, k), dtype=x.dtype)
    y = xc @ omega                       # (N, k)
    q, _ = jnp.linalg.qr(y)
    for _ in range(n_iter):
        z = xc.T @ q                     # (F, k)
        w, _ = jnp.linalg.qr(z)
        y = xc @ w                       # (N, k)
        q, _ = jnp.linalg.qr(y)
    b = q.T @ xc                         # (k, F)
    _, _, vt = jnp.linalg.svd(b, full_matrices=False)
    return mean, vt[:n_components], xc


@partial(jax.jit, static_argnames=("n_components", "n_oversample", "n_iter"))
def pca_scores(
    x: jnp.ndarray,
    n_components: int,
    n_oversample: int = 10,
    n_iter: int = 4,
    seed: int = 0,
) -> jnp.ndarray:
    """Principal-component scores of the rows of ``x``.

    Args:
      x: (N, F) matrix (cells × DE-gene union), centered internally per column.
      n_components: number of PCs (reference: min(|union|, 15)).

    Returns (N, n_components) scores = centered x projected onto the top PCs,
    matching ``prcomp_irlba(...)$x`` up to column signs.
    """
    _, vt, xc = _subspace_basis(x, n_components, n_oversample, n_iter, seed)
    return xc @ vt.T                     # (N, n_components)


@partial(jax.jit, static_argnames=("n_components", "n_oversample", "n_iter"))
def pca_scores_audited(
    x: jnp.ndarray,
    n_components: int,
    n_oversample: int = 10,
    n_iter: int = 4,
    seed: int = 0,
):
    """:func:`pca_scores` plus the integrity layer's verification
    outputs, from ONE fused program (robust.integrity, round 18):

    Returns ``(scores, ortho_residual, mean, components)`` where
    ``ortho_residual = ‖V·Vᵀ − I‖∞`` is the basis-orthonormality
    invariant (any correct run of the subspace iteration ends in an SVD
    whose right-singular rows are orthonormal — a residual past the
    float32 band means the basis, and therefore every downstream
    distance, is corrupt), and ``mean``/``components`` feed the sampled
    float64 ghost replay of score rows. The extra work over
    ``pca_scores`` is one (k, k) gram — noise next to the iteration's
    (N, F) matmuls — and the residual stays on device until the
    integrity layer fetches its one scalar.
    """
    mean, vt, xc = _subspace_basis(x, n_components, n_oversample, n_iter,
                                   seed)
    scores = xc @ vt.T
    g = vt @ vt.T
    resid = jnp.max(jnp.abs(g - jnp.eye(g.shape[0], dtype=g.dtype)))
    return scores, resid, mean, vt


@partial(jax.jit, static_argnames=("n_components", "n_oversample", "n_iter"))
def pca_basis(
    x: jnp.ndarray,
    n_components: int,
    n_oversample: int = 10,
    n_iter: int = 4,
    seed: int = 0,
):
    """The EXPLICIT projection basis behind :func:`pca_scores`.

    Returns ``(mean (F,), components (n_components, F))`` from the same
    subspace iteration (one shared body, same seed), so
    ``(x - mean) @ components.T`` reproduces the training embedding —
    the piece a frozen consensus model must persist to project NEW cells
    into the space its landmarks live in (``pca_scores`` alone discards
    it, which is fine for batch runs that never see another cell).
    """
    mean, vt, _ = _subspace_basis(x, n_components, n_oversample, n_iter,
                                  seed)
    return mean, vt


# graph passports (obs.graphs, SCC_GRAPHS): the rSVD embed stage programs
pca_scores = _passport("embed.pca_scores", pca_scores)
pca_scores_audited = _passport("embed.pca_scores_audited", pca_scores_audited)
pca_basis = _passport("embed.pca_basis", pca_basis)
