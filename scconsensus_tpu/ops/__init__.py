"""Batched statistical / linear-algebra kernels (the framework's "ops" layer).

Everything here is shape-static, mask-based, and jit/vmap-friendly: ragged
cluster sizes are handled with validity masks, never dynamic shapes, so XLA
can tile the work onto the TPU's MXU/VPU (SURVEY.md §7 design stance).
"""

from scconsensus_tpu.ops.ranks import masked_midranks, rank_sum_groups
from scconsensus_tpu.ops.multipletests import bh_adjust, bh_adjust_masked
from scconsensus_tpu.ops.wilcoxon import wilcoxon_from_ranks, wilcoxon_exact_host

__all__ = [
    "masked_midranks",
    "rank_sum_groups",
    "bh_adjust",
    "bh_adjust_masked",
    "wilcoxon_from_ranks",
    "wilcoxon_exact_host",
]
