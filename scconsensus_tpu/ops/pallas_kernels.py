"""Pallas TPU kernels for the distance hot path.

The silhouette / ring statistic Σ_{j∈cluster} ‖x_i − x_j‖ is the package's
HBM-bandwidth hot op (SURVEY.md §5.7: the N×N distance work). XLA computes it
as three kernels (matmul → elementwise sqrt → matmul) with the (B, N) distance
tile round-tripping through HBM between them. The Pallas kernel fuses the
whole pipeline — norms, cross matmul (MXU), sqrt (VPU), and the ×onehot
reduction matmul (MXU) — so the distance tile lives only in VMEM and HBM
traffic drops from O(N²) to O(N·(d+K)) per sweep.

Measured verdict (v5e, 26k×15, K=22, round 2→3): the fused kernel runs at
0.92× the XLA fallback — XLA's own fusion already keeps the tile pipeline
HBM-efficient at this shape, and the kernel's fixed 256-tile grid leaves MXU
idle on the skinny (d=15, K≈22) operands. ``backend="auto"`` therefore
selects **XLA everywhere**; the Pallas kernel remains an explicit opt-in
(``backend="pallas"``).

Roofline note (round 4) on the fat-K hope (e.g. the 100k × 15, K=4096
pooled-centroid geometry): both backends execute the identical dominant
matmul — dist(B, N) @ onehot(N, K) is 2·N²·K ≈ 82 TFLOP at that shape,
~1.7 s of v5e f32 MXU time — while the d-tile HBM round trip XLA pays and
the fusion saves is only ~80 GB ≈ 0.1 s. A ≥1.15× fused-kernel win is
therefore structurally unavailable at either the skinny or the fat shape;
the kernel stays an opt-in demonstration unless a future shape breaks this
arithmetic (bench.py's pallas_vs_xla probe records both shapes whenever a
TPU run happens, so the claim stays falsifiable).

Grid: (N/TM, N/TN); the (TM, K) output block is revisited across the j axis
and accumulated in place (zeroed at j == 0) — the standard Pallas reduction
pattern. Feature and cluster axes are zero-padded to the 128-lane tile
constraint on host; padded cells carry zero one-hot rows so they contribute
to no cluster, and padded output rows are sliced off.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["distance_cluster_sums", "pallas_available"]

_TM = 256
_TN = 256
_LANE = 128


def _kernel(xi_ref, xj_ref, ohj_ref, out_ref):
    from jax.experimental import pallas as pl

    xi = xi_ref[:]                      # (TM, dpad)
    xj = xj_ref[:]                      # (TN, dpad)
    a2 = jnp.sum(xi * xi, axis=1, keepdims=True)
    b2 = jnp.sum(xj * xj, axis=1, keepdims=True)
    cross = jnp.dot(xi, xj.T, preferred_element_type=jnp.float32)  # MXU
    d = jnp.sqrt(jnp.maximum(a2 + b2.T - 2.0 * cross, 0.0))        # VPU
    part = jnp.dot(d, ohj_ref[:], preferred_element_type=jnp.float32)  # MXU

    jj = pl.program_id(1)

    @pl.when(jj == 0)
    def _():
        out_ref[:] = part

    @pl.when(jj != 0)
    def _():
        out_ref[:] = out_ref[:] + part


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401

        return True
    except ImportError:  # pragma: no cover
        return False


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dist_sums_pallas(xp: jnp.ndarray, ohp: jnp.ndarray, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, dpad = xp.shape
    k = ohp.shape[1]
    grid = (n // _TM, n // _TN)
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((n, k), jnp.float32),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (_TM, dpad), lambda i, j: (i, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (_TN, dpad), lambda i, j: (j, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (_TN, k), lambda i, j: (j, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec(
                (_TM, k), lambda i, j: (i, 0), memory_space=pltpu.VMEM
            ),
        ),
        interpret=interpret,
    )(xp, xp, ohp)


def _pad_to(x, axis: int, multiple: int):
    n = x.shape[axis]
    pad = (-n) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    # jnp.pad for device arrays (np.pad would silently fetch to host)
    return (jnp.pad if isinstance(x, jax.Array) else np.pad)(x, widths)


def distance_cluster_sums(
    x: np.ndarray,
    onehot: np.ndarray,
    backend: str = "auto",
    block: int = 4096,
    device_out: bool = False,
) -> np.ndarray:
    """(N, K) Σ distances from every point to every cluster's members.

    backend: 'pallas' (TPU fused kernel — explicit opt-in; measured 0.92×
    the fallback at the flagship shape, see module docstring),
    'pallas_interpret' (CPU-debuggable kernel, slow — tests only), 'xla'
    (blocked matmul fallback), or 'auto' (xla: the measured winner).

    ``x``/``onehot`` may be device arrays (no host round-trip);
    ``device_out=True`` returns the device array (callers benchmarking the
    kernel must not pay a multi-GB fetch inside the timed region).
    """
    if not isinstance(x, jax.Array):
        x = np.ascontiguousarray(x, np.float32)
    if not isinstance(onehot, jax.Array):
        onehot = np.ascontiguousarray(onehot, np.float32)
    n, _d = x.shape
    k = onehot.shape[1]
    if backend == "auto":
        backend = "xla"

    if backend in ("pallas", "pallas_interpret"):
        tile = max(_TM, _TN)
        xp = _pad_to(_pad_to(x, 0, tile), 1, _LANE)
        ohp = _pad_to(_pad_to(onehot, 0, tile), 1, _LANE)
        out = _dist_sums_pallas(
            jnp.asarray(xp), jnp.asarray(ohp),
            interpret=(backend == "pallas_interpret"),
        )[:n, :k]
        if device_out:
            return out
        from scconsensus_tpu.obs.residency import boundary

        with boundary("silhouette_slab_fetch"):  # declared (N, K) fetch
            return np.asarray(out)

    if backend == "xla":
        jx = jnp.asarray(x)
        joh = jnp.asarray(onehot)
        # Blocks dispatch async and concatenate on device: ONE host fetch at
        # the end (per-block np.asarray cost a blocking round-trip each
        # through the slow device→host tunnel).
        parts = [
            _xla_block_sums(jx[s : min(s + block, n)], jx, joh)
            for s in range(0, n, block)
        ]
        out = jnp.concatenate(parts, axis=0)
        if device_out:
            return out
        from scconsensus_tpu.obs.residency import boundary

        with boundary("silhouette_slab_fetch"):  # declared (N, K) fetch
            return np.asarray(out)

    raise ValueError(f"unknown backend {backend!r}")


@jax.jit
def _xla_block_sums(xb: jnp.ndarray, x_all: jnp.ndarray, oh: jnp.ndarray):
    """One fused (block, N) distance tile × one-hot reduction (the XLA
    fallback's per-block program — jitted so the tile never round-trips)."""
    from scconsensus_tpu.ops.distance import distance_tile

    return distance_tile(xb, x_all) @ oh
