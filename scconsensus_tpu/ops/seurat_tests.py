"""Seurat-style DE test kernels for the fast path: bimod LRT, Welch t, AUC.

Reference: the ``switch`` dispatch inside ComputePairWiseDE
(R/reclusterDEConsensusFast.R:306-333) with test bodies at :93-133 (bimod),
:185-196 (t), :135-182 (roc). Note the reference's bimod and roc branches are
dead on arrival — they call Seurat helpers (`MinMax`, `ExpMean`, `pblapply`)
defined nowhere (SURVEY.md §2c) — so these kernels implement the *intended*
published semantics (Seurat's zero-inflated-normal LRT, McDavid et al. 2013;
R ``t.test`` Welch default; AUC as the normalized Mann-Whitney statistic).

All kernels are moment-based masked reductions over a (B, G, W) tile — no
sorts — so they are strictly cheaper than the rank-sum path and batch the
same way (pairs × genes on the MXU-friendly reduction axis).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import jax.scipy.special as jsp

__all__ = [
    "bimod_lrt_tile", "welch_t_tile",
    "bimod_lrt_pairs", "welch_t_pairs", "auc_from_u",
]

_PI_CLIP_LO = 1e-5  # Seurat's MinMax(…, 1e-5, 1-1e-5) on the positive fraction


def _zinorm_loglik_stats(n, n_pos, s, ss):
    """Seurat bimodLikData from sufficient statistics: n masked cells, n_pos
    positives, s = Σ positives, ss = Σ positives². sd uses the n−1
    denominator (R ``sd``) and falls back to 1 below 2 positive cells."""
    n_zero = n - n_pos
    frac = jnp.clip(
        n_pos / jnp.maximum(n, 1.0), _PI_CLIP_LO, 1.0 - _PI_CLIP_LO
    )
    mean = s / jnp.maximum(n_pos, 1.0)
    var = (ss - n_pos * mean * mean) / jnp.maximum(n_pos - 1.0, 1.0)
    sd = jnp.where(n_pos < 2.0, 1.0, jnp.sqrt(jnp.maximum(var, 1e-30)))
    # Σ log N(x; mean, sd) over positives, from the same moments:
    # −n_pos·log(sd·√2π) − (ss − 2·mean·s + n_pos·mean²)/(2 sd²)
    quad = ss - 2.0 * mean * s + n_pos * mean * mean
    lik_pos = (
        n_pos * jnp.log(frac)
        - n_pos * (jnp.log(sd) + 0.5 * jnp.log(2.0 * jnp.pi))
        - quad / (2.0 * sd * sd)
    )
    lik_zero = n_zero * jnp.log1p(-frac)
    return lik_zero + lik_pos


def _zero_inflated_loglik(vals, mask, xmin: float):
    """Per-cell-tile form of ``_zinorm_loglik_stats`` (vals/mask (..., W);
    positives are entries > xmin among masked cells)."""
    pos = mask & (vals > xmin)
    n = jnp.sum(mask, axis=-1).astype(jnp.float32)
    n_pos = jnp.sum(pos, axis=-1).astype(jnp.float32)
    vp = jnp.where(pos, vals, 0.0)
    s = jnp.sum(vp, axis=-1)
    ss = jnp.sum(vp * vp, axis=-1)
    return _zinorm_loglik_stats(n, n_pos, s, ss)


def bimod_lrt_tile(
    vals: jnp.ndarray,
    m1: jnp.ndarray,
    m2: jnp.ndarray,
    xmin: float = 0.0,
) -> jnp.ndarray:
    """Likelihood-ratio test of separate vs pooled zero-inflated normal fits,
    χ² with 3 df (DifferentialLRT, R/reclusterDEConsensusFast.R:110-133).

    vals: (B, G, W); m1/m2: (B, W) (broadcast over genes). Returns (B, G)
    log p-values.
    """
    m1e = m1[:, None, :]
    m2e = m2[:, None, :]
    ll1 = _zero_inflated_loglik(vals, m1e, xmin)
    ll2 = _zero_inflated_loglik(vals, m2e, xmin)
    ll_pooled = _zero_inflated_loglik(vals, m1e | m2e, xmin)
    lrt = 2.0 * (ll1 + ll2 - ll_pooled)
    lrt = jnp.maximum(lrt, 0.0)
    # log P(χ²₃ > lrt) = log Γ_upper-reg(3/2, lrt/2)
    log_p = jnp.log(jnp.maximum(jsp.gammaincc(1.5, lrt / 2.0), 1e-38))
    n1 = jnp.sum(m1, axis=-1)[:, None]
    n2 = jnp.sum(m2, axis=-1)[:, None]
    return jnp.where((n1 < 1) | (n2 < 1), jnp.nan, log_p)


def welch_t_tile(
    vals: jnp.ndarray, m1: jnp.ndarray, m2: jnp.ndarray
) -> jnp.ndarray:
    """Two-sided Welch t-test (R ``t.test`` default, var.equal=FALSE;
    reference per-gene loop R/reclusterDEConsensusFast.R:185-196).

    vals: (B, G, W); m1/m2: (B, W). Returns (B, G) log p-values via the
    incomplete-beta tail of the t distribution with Welch–Satterthwaite df.
    """
    m1e = m1[:, None, :]
    m2e = m2[:, None, :]

    def moments(mask):
        n = jnp.sum(mask, axis=-1).astype(jnp.float32)
        v = jnp.where(mask, vals, 0.0)
        s = jnp.sum(v, axis=-1)
        ss = jnp.sum(v * v, axis=-1)
        mean = s / jnp.maximum(n, 1.0)
        var = (ss - n * mean * mean) / jnp.maximum(n - 1.0, 1.0)
        return n, mean, jnp.maximum(var, 0.0)

    n1, mu1, v1 = moments(m1e)
    n2, mu2, v2 = moments(m2e)
    se1 = v1 / jnp.maximum(n1, 1.0)
    se2 = v2 / jnp.maximum(n2, 1.0)
    se = se1 + se2
    t = (mu1 - mu2) / jnp.sqrt(jnp.maximum(se, 1e-30))
    df = se * se / jnp.maximum(
        se1 * se1 / jnp.maximum(n1 - 1.0, 1.0)
        + se2 * se2 / jnp.maximum(n2 - 1.0, 1.0),
        1e-30,
    )
    # two-sided p = I_{df/(df+t²)}(df/2, 1/2)
    x = df / (df + t * t)
    log_p = jnp.log(jnp.maximum(jsp.betainc(df / 2.0, 0.5, x), 1e-38))
    bad = (n1 < 2) | (n2 < 2) | (se <= 0.0)
    return jnp.where(bad, jnp.nan, log_p)


@jax.jit
def bimod_lrt_pairs(agg, pair_i: jnp.ndarray, pair_j: jnp.ndarray) -> jnp.ndarray:
    """All-pairs bimod LRT straight from per-cluster aggregates.

    The zero-inflated-normal fit needs only {n, n_pos, Σx, Σx²} per group,
    and the pooled group's statistics are the sums of the two clusters' —
    so every pair's test is a gather over the (G, K) aggregate tensors
    (xmin = 0 semantics: positives are x > 0, hence n_pos = nnz and the
    positive sums equal the full sums for non-negative log data).
    Returns (P, G) log p-values.
    """
    def stats(k):  # -> each (P, G)
        return (
            agg.counts[k][:, None],
            agg.nnz[:, k].T,
            agg.sum_log[:, k].T,
            agg.sum_sq[:, k].T,
        )

    n1, p1, s1, ss1 = stats(pair_i)
    n2, p2, s2, ss2 = stats(pair_j)
    ll1 = _zinorm_loglik_stats(n1, p1, s1, ss1)
    ll2 = _zinorm_loglik_stats(n2, p2, s2, ss2)
    ll_pooled = _zinorm_loglik_stats(n1 + n2, p1 + p2, s1 + s2, ss1 + ss2)
    lrt = jnp.maximum(2.0 * (ll1 + ll2 - ll_pooled), 0.0)
    log_p = jnp.log(jnp.maximum(jsp.gammaincc(1.5, lrt / 2.0), 1e-38))
    return jnp.where((n1 < 1) | (n2 < 1), jnp.nan, log_p)


@jax.jit
def welch_t_pairs(agg, pair_i: jnp.ndarray, pair_j: jnp.ndarray) -> jnp.ndarray:
    """All-pairs two-sided Welch t from per-cluster aggregates (mean and
    variance per group from {n, Σx, Σx²}). Returns (P, G) log p-values."""
    def moments(k):
        n = agg.counts[k][:, None]                       # (P, 1)
        s = agg.sum_log[:, k].T                          # (P, G)
        ss = agg.sum_sq[:, k].T
        mean = s / jnp.maximum(n, 1.0)
        var = (ss - n * mean * mean) / jnp.maximum(n - 1.0, 1.0)
        return n, mean, jnp.maximum(var, 0.0)

    n1, mu1, v1 = moments(pair_i)
    n2, mu2, v2 = moments(pair_j)
    se1 = v1 / jnp.maximum(n1, 1.0)
    se2 = v2 / jnp.maximum(n2, 1.0)
    se = se1 + se2
    t = (mu1 - mu2) / jnp.sqrt(jnp.maximum(se, 1e-30))
    df = se * se / jnp.maximum(
        se1 * se1 / jnp.maximum(n1 - 1.0, 1.0)
        + se2 * se2 / jnp.maximum(n2 - 1.0, 1.0),
        1e-30,
    )
    x = df / (df + t * t)
    log_p = jnp.log(jnp.maximum(jsp.betainc(df / 2.0, 0.5, x), 1e-38))
    bad = (n1 < 2) | (n2 < 2) | (se <= 0.0)
    return jnp.where(bad, jnp.nan, log_p)


def auc_from_u(
    u: jnp.ndarray, n1: jnp.ndarray, n2: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """AUC and Seurat's marker 'power' from the Mann-Whitney U statistic
    (the ROCR AUC of the reference's roc branch equals U/(n1·n2) — SURVEY.md
    §2b N9; power = 2|AUC − 0.5|, R/reclusterDEConsensusFast.R:144-150)."""
    auc = u / jnp.maximum(n1 * n2, 1.0)
    return auc, 2.0 * jnp.abs(auc - 0.5)
