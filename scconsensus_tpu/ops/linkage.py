"""Ward.D2 agglomerative clustering via the nearest-neighbor-chain algorithm.

Replaces ``fastcluster::hclust(d, "ward.D2")`` (R/reclusterDEConsensus.R:242-246).
Rather than consuming an N×N distance matrix, clusters are represented by
(centroid, size) and the Ward.D2 dissimilarity is computed on the fly:

    D(A, B) = sqrt(2·|A||B| / (|A|+|B|)) · ‖c_A − c_B‖

which reproduces R's ward.D2 heights on euclidean input exactly (it is the
Lance–Williams recurrence in closed form). Memory is O(N·d) instead of O(N²),
which is what makes the 1M-cell approximate path possible (SURVEY.md §7).

Ward dissimilarity is reducible, so NN-chain merges are globally optimal and,
after a stable sort by height, yield an hclust-compatible (merge, height,
order) triple that dynamicTreeCut can consume.

A C++ implementation of the same chain loop lives in ``native/ward.cpp``
(ctypes-loaded); this numpy version is the always-available fallback and the
golden reference for it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

__all__ = ["HClustTree", "ward_linkage", "cut_tree_k"]


@dataclasses.dataclass
class HClustTree:
    """R hclust-compatible tree.

    merge: (N-1, 2) int32; negative = −(singleton index+1), positive = 1-based
      row of a prior merge (R convention, consumed by the tree cutter).
    height: (N-1,) float64 non-decreasing merge heights.
    order: (N,) leaf permutation for crossing-free dendrogram drawing.
    """

    merge: np.ndarray
    height: np.ndarray
    order: np.ndarray

    @property
    def n_leaves(self) -> int:
        return self.merge.shape[0] + 1


def _nn_of(cent, size, active_idx, u):
    """Index (into active_idx) of the Ward-nearest active cluster to u."""
    c = cent[active_idx]
    du = c - cent[u]
    sq = np.einsum("ij,ij->i", du, du)
    s = size[active_idx] * size[u] / (size[active_idx] + size[u])
    d2 = 2.0 * s * sq
    # self-distance excluded by caller (u not in active_idx)
    k = int(np.argmin(d2))
    return k, d2[k]


def ward_linkage(
    points: np.ndarray,
    use_native: bool = True,
    weights: Optional[np.ndarray] = None,
) -> HClustTree:
    """Ward.D2 linkage of the rows of ``points`` (N, d).

    ``weights`` (N,) treats each point as a pre-merged cluster of that many
    observations (the centroid-pooling approximate path); default 1.
    """
    points = np.ascontiguousarray(points, dtype=np.float64)
    n = points.shape[0]
    if n < 2:
        raise ValueError("need at least 2 points")
    w = (
        np.ones(n, np.float64)
        if weights is None
        else np.ascontiguousarray(weights, np.float64)
    )
    if use_native:
        try:
            from scconsensus_tpu.native import ward_native

            raw_pairs, raw_h = ward_native(points, w)
            return _to_hclust(raw_pairs, raw_h, n)
        except Exception:
            pass  # fall back to numpy chain below

    cap = 2 * n - 1
    cent = np.zeros((cap, points.shape[1]), np.float64)
    cent[:n] = points
    size = np.zeros(cap, np.float64)
    size[:n] = w
    active = np.ones(cap, bool)
    active[n:] = False

    raw_pairs = np.zeros((n - 1, 2), np.int64)
    raw_h = np.zeros(n - 1, np.float64)
    next_slot = n
    chain = []
    n_active = n
    while n_active > 1:
        if not chain:
            chain.append(int(np.nonzero(active)[0][0]))
        while True:
            u = chain[-1]
            active[u] = False
            act = np.nonzero(active)[0]
            active[u] = True
            k, d2 = _nn_of(cent, size, act, u)
            v = int(act[k])
            if len(chain) > 1 and v == chain[-2]:
                break
            chain.append(v)
        u = chain.pop()
        v = chain.pop()
        h = np.sqrt(max(d2, 0.0))
        raw_pairs[next_slot - n] = (u, v)
        raw_h[next_slot - n] = h
        su, sv = size[u], size[v]
        cent[next_slot] = (su * cent[u] + sv * cent[v]) / (su + sv)
        size[next_slot] = su + sv
        active[u] = active[v] = False
        active[next_slot] = True
        next_slot += 1
        n_active -= 1
    return _to_hclust(raw_pairs, raw_h, n)


def _to_hclust(raw_pairs: np.ndarray, raw_h: np.ndarray, n: int) -> HClustTree:
    """Order raw merges by height and rewrite slot ids into R hclust merge
    codes.

    The ordering is a height-prioritized topological (Kahn) pass rather than
    a plain argsort: a merge becomes eligible only once both child rows are
    placed. For reducible linkages (NN-chain Ward) parent heights dominate
    children, so this reproduces the stable height sort exactly; for
    candidate-restricted agglomerations (ops.knn_linkage) a parent can sit
    BELOW a child (an inversion — legal in hclust trees, cf. centroid
    linkage), and a plain height sort would emit a row referencing a later
    row: a structurally invalid tree."""
    import heapq

    m = n - 1
    dep_count = np.zeros(m, np.int32)
    dependents: list = [[] for _ in range(m)]
    for r in range(m):
        for slot in (int(raw_pairs[r, 0]), int(raw_pairs[r, 1])):
            if slot >= n:
                dep_count[r] += 1
                dependents[slot - n].append(r)
    heap = [(float(raw_h[r]), r) for r in range(m) if dep_count[r] == 0]
    heapq.heapify(heap)
    order_rows = np.empty(m, np.int64)
    rank_of_raw = np.empty(m, np.int64)
    placed = 0
    while heap:
        _, r = heapq.heappop(heap)
        order_rows[placed] = r
        rank_of_raw[r] = placed
        placed += 1
        for d in dependents[r]:
            dep_count[d] -= 1
            if dep_count[d] == 0:
                heapq.heappush(heap, (float(raw_h[d]), d))
    if placed != m:  # a cycle would mean corrupt input, not a bad sort
        raise ValueError("merge list is not a forest")

    def code(slot: int, _rank=rank_of_raw, _n=n) -> int:
        if slot < _n:
            return -(slot + 1)
        return int(_rank[slot - _n]) + 1

    merge = np.zeros((n - 1, 2), np.int32)
    height = raw_h[order_rows]
    for new_row, raw_row in enumerate(order_rows):
        a = code(int(raw_pairs[raw_row, 0]))
        b = code(int(raw_pairs[raw_row, 1]))
        # Normalize rows: singletons (negative) before clusters; within a kind,
        # ascending |code|. (Cosmetic; consumers only need structural validity.)
        if (a > 0 and b < 0) or (a < 0 and b < 0 and a < b) or (a > 0 and b > 0 and a > b):
            a, b = b, a
        merge[new_row] = (a, b)

    # Leaf order: DFS over the final merge rows (left child first).
    order = np.zeros(n, np.int64)
    pos = 0
    stack = [n - 2]  # root = last row
    while stack:
        node = stack.pop()
        if node < 0:
            order[pos] = -node - 1
            pos += 1
            continue
        a, b = merge[node]
        ca = int(a) - 1 if a > 0 else int(a)
        cb = int(b) - 1 if b > 0 else int(b)
        stack.append(cb)
        stack.append(ca)
    return HClustTree(merge=merge, height=height, order=order)


def cut_tree_k(tree: HClustTree, k: int) -> np.ndarray:
    """Flat cut into k clusters (R ``cutree`` analog), labels 1..k by order of
    first appearance. Test utility for cross-checking linkage correctness."""
    n = tree.n_leaves
    parent = {}
    for row in range(n - 1 - (k - 1)):
        a, b = tree.merge[row]
        for c in (int(a), int(b)):
            parent[c] = row + 1
    # union-find style resolution: leaf -> top surviving component
    labels = np.zeros(n, np.int64)
    comp_of = {}
    next_label = 1

    def resolve(code: int) -> int:
        while code in parent:
            code = parent[code]
        return code

    for leaf in range(n):
        top = resolve(-(leaf + 1))
        if top not in comp_of:
            nonlocal_label = next_label
            comp_of[top] = nonlocal_label
            next_label += 1
        labels[leaf] = comp_of[top]
    return labels
