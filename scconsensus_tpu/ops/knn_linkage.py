"""Approximate Ward.D2 linkage restricted to a device-computed kNN graph.

The exact NN-chain (ops.linkage) scans every active cluster per step —
O(N²) time — and the centroid-pooling path (ops.pooling) trades leaf-level
resolution for scale. This path sits between them (SURVEY.md §7 stage 6's
"k-NN graph path"): the mesh ring engine (parallel.ring.ring_knn — ICI
ppermute rotation, no N×N tile) computes each cell's k nearest neighbours
on device, and the host agglomerates with merges restricted to
graph-adjacent clusters.

Ward dissimilarity in centroid form is exact under merging,

    D²(A, B) = 2·|A||B| / (|A|+|B|) · ‖c_A − c_B‖²,

so the only approximation is the candidate restriction: a merge the exact
algorithm would make is missed only when the clusters share no kNN edge —
rare below the cluster scale for reasonable k. Graph components that never
connect are finished exactly (ward_linkage over the surviving component
centroids), so the output is always a complete hclust-compatible tree that
dynamicTreeCut can cut.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Set

import numpy as np

from scconsensus_tpu.ops.linkage import HClustTree, _to_hclust, ward_linkage

__all__ = ["knn_ward_linkage"]


def _ward_d2(cent, size, u, v) -> float:
    du = cent[u] - cent[v]
    return float(
        2.0 * size[u] * size[v] / (size[u] + size[v]) * np.dot(du, du)
    )


def knn_ward_linkage(
    x: np.ndarray,
    k: int = 15,
    mesh=None,
    weights: Optional[np.ndarray] = None,
) -> HClustTree:
    """Ward tree of the rows of x (N, d) over the kNN-graph restriction.

    ``mesh``: optional device mesh for the ring kNN sweep (defaults to all
    visible devices — a 1-device mesh is valid). ``weights`` treats rows as
    pre-merged clusters (composable with the pooling path).
    """
    from scconsensus_tpu.parallel.ring import ring_knn

    x = np.ascontiguousarray(x, np.float64)
    n = x.shape[0]
    if n < 2:
        raise ValueError("need at least 2 points")
    k = min(k, n - 1)
    _, nbr = ring_knn(x.astype(np.float32), k, mesh)

    cap = 2 * n - 1
    cent = np.zeros((cap, x.shape[1]), np.float64)
    cent[:n] = x
    size = np.zeros(cap, np.float64)
    size[:n] = 1.0 if weights is None else np.asarray(weights, np.float64)
    active = np.zeros(cap, bool)
    active[:n] = True

    adj: List[Set[int]] = [set() for _ in range(cap)]
    for i in range(n):
        for j in nbr[i]:
            j = int(j)
            if j >= 0 and j != i:
                adj[i].add(j)
                adj[j].add(i)

    heap = []
    for i in range(n):
        for j in adj[i]:
            if j > i:
                heapq.heappush(heap, (_ward_d2(cent, size, i, j), i, j))

    raw_pairs = np.zeros((n - 1, 2), np.int64)
    raw_h = np.zeros(n - 1, np.float64)
    next_slot = n
    n_merges = 0

    while heap and n_merges < n - 1:
        d2, u, v = heapq.heappop(heap)
        if not (active[u] and active[v]):
            continue  # stale entry: one endpoint was merged away
        s = next_slot
        raw_pairs[n_merges] = (u, v)
        raw_h[n_merges] = np.sqrt(max(d2, 0.0))
        su, sv = size[u], size[v]
        cent[s] = (su * cent[u] + sv * cent[v]) / (su + sv)
        size[s] = su + sv
        active[u] = active[v] = False
        active[s] = True
        neighbors = (adj[u] | adj[v]) - {u, v}
        adj[s] = set()
        for w in neighbors:
            adj[w].discard(u)
            adj[w].discard(v)
            if active[w]:
                adj[s].add(w)
                adj[w].add(s)
                heapq.heappush(heap, (_ward_d2(cent, size, s, w), min(s, w),
                                      max(s, w)))
        adj[u] = adj[v] = set()
        next_slot = s + 1
        n_merges += 1

    # Disconnected components: finish exactly over their centroids.
    rest = np.nonzero(active)[0]
    if rest.size > 1:
        sub = ward_linkage(cent[rest], use_native=rest.size > 64,
                           weights=size[rest])
        # sub's merge codes reference its own leaf/row numbering; remap onto
        # our slot space (leaf m -> rest[m], row r -> the slot it created).
        slot_of_row = np.zeros(rest.size - 1, np.int64)
        for r in range(rest.size - 1):
            a, b = int(sub.merge[r, 0]), int(sub.merge[r, 1])
            ua = rest[-a - 1] if a < 0 else slot_of_row[a - 1]
            ub = rest[-b - 1] if b < 0 else slot_of_row[b - 1]
            raw_pairs[n_merges] = (ua, ub)
            raw_h[n_merges] = sub.height[r]
            s = next_slot
            sua, sub_ = size[ua], size[ub]
            cent[s] = (sua * cent[ua] + sub_ * cent[ub]) / (sua + sub_)
            size[s] = sua + sub_
            slot_of_row[r] = s
            next_slot = s + 1
            n_merges += 1

    assert n_merges == n - 1, (n_merges, n - 1)
    return _to_hclust(raw_pairs, raw_h, n)
