"""edgeR-equivalent negative-binomial DE kernels (the north-star workload).

Replaces the reference's edgeR pipeline ``DGEList → estimateCommonDisp →
estimateTagwiseDisp → calcNormFactors("none") → exactTest``
(R/reclusterDEConsensus.R:133-156; SURVEY.md §2b N1) with batched JAX kernels
re-derived from the published qCML method (Robinson & Smyth 2008) and the NB
exact test (Robinson & Smyth 2008, "doubling the smaller tail"):

  * library-size equalization by NB quantile-to-quantile mapping
    (``q2q_nbinom``: average of normal- and gamma-approximation quantile maps,
    the approximation edgeR's quantile adjustment uses);
  * qCML **common dispersion**: maximize the conditional log-likelihood of
    the pseudo-counts over a dispersion grid (+ quadratic refinement) — the
    reference's ``estimateCommonDisp`` two-phase scheme: equalize at a pilot
    dispersion, estimate, re-equalize at the estimate;
  * **tagwise dispersion**: weighted-likelihood empirical Bayes shrinkage of
    per-gene conditional likelihood toward the common curve
    (``estimateTagwiseDisp`` with trend="none" semantics; prior.df = 10);
  * **exact test**: the conditional distribution of one group's sum given the
    total is Beta-Binomial(s, n1/φ, n2/φ); two-sided p doubles the smaller
    tail. Tails are computed from cumulative log pmf-ratios (no large-argument
    lgamma cancellation) for s ≤ ``s_max`` and by a moment-matched normal
    approximation with continuity correction above.

All kernels are float32-stable by construction: every lgamma enters through
``lgamma_shift(y, r) = lgamma(y+r) − lgamma(r)``, which switches to a Stirling
expansion for large ``r`` where naive subtraction loses all precision.

The statistical arithmetic is re-derived, not translated: no edgeR source was
available or consulted (R absent from the environment; SURVEY.md §4).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import jax.scipy.special as jsp

__all__ = [
    "lgamma_shift",
    "nb_cond_log_lik",
    "one_group_nb_rate",
    "q2q_nbinom",
    "q2q_normal",
    "q2q_normal_raw",
    "q2q_gamma_raw",
    "equalize_pseudo",
    "common_dispersion_grid",
    "tagwise_dispersion",
    "nb_exact_test_logp",
    "nb_exact_test_logp_normal",
    "DEFAULT_DELTA_GRID_SIZE",
    "TAGWISE_GRID_EXPONENTS",
]

DEFAULT_DELTA_GRID_SIZE = 64
# estimateTagwiseDisp grid: dispersion = common * 2^linspace(-6, 6, 11)
TAGWISE_GRID_EXPONENTS = jnp.linspace(-6.0, 6.0, 11)
_STIRLING_SWITCH = 30.0


def _stirling_corr(x):
    """1/(12x) − 1/(360x³): first Stirling series corrections."""
    inv = 1.0 / x
    return inv / 12.0 - (inv * inv * inv) / 360.0


def lgamma_shift(y: jnp.ndarray, r: jnp.ndarray) -> jnp.ndarray:
    """lgamma(y + r) − lgamma(r), stable for large r.

    Naive subtraction loses ~eps·|lgamma(r)| absolute precision (catastrophic
    in float32 once r ≳ 1e3). For r above a switch point use the Stirling
    form  (r−½)·log1p(y/r) + y·log(r+y) − y + Δcorr,  whose terms are all
    O(y·log r). y ≥ 0 required.
    """
    y = jnp.asarray(y, jnp.float32)
    r = jnp.asarray(r, jnp.float32)
    naive = jsp.gammaln(y + r) - jsp.gammaln(r)
    rs = jnp.maximum(r, _STIRLING_SWITCH)  # keep the unused branch finite
    stirling = (
        (rs - 0.5) * jnp.log1p(y / rs)
        + y * jnp.log(rs + y)
        - y
        + _stirling_corr(rs + y)
        - _stirling_corr(rs)
    )
    return jnp.where(r < _STIRLING_SWITCH, naive, stirling)


def nb_cond_log_lik(
    y: jnp.ndarray, mask: jnp.ndarray, r: jnp.ndarray
) -> jnp.ndarray:
    """Conditional log-likelihood of one group's counts given their sum,
    for NB with common size r = 1/dispersion (Robinson & Smyth 2008 qCML):

        Σ_j [lgamma(y_j+r) − lgamma(r)] − [lgamma(z+nr) − lgamma(nr)]

    (terms independent of r dropped — callers only compare across r).

    y: (..., W) counts; mask: (..., W) group membership; r broadcastable to
    the leading axes. Returns (...) log-likelihood.
    """
    ym = jnp.where(mask, y, 0.0)
    z = jnp.sum(ym, axis=-1)
    n = jnp.sum(mask, axis=-1).astype(jnp.float32)
    per_obs = jnp.sum(
        jnp.where(mask, lgamma_shift(ym, r[..., None]), 0.0), axis=-1
    )
    return per_obs - lgamma_shift(z, n * r)


def one_group_nb_rate(
    y: jnp.ndarray,
    lib: jnp.ndarray,
    mask: jnp.ndarray,
    dispersion: jnp.ndarray,
    n_iter: int = 8,
) -> jnp.ndarray:
    """MLE of the per-library rate λ for one group under NB with log link and
    library-size offsets: μ_j = λ·lib_j (edgeR's mglmOneGroup role).

    Newton on β = log λ with the Poisson MLE start (the exact solution as
    dispersion → 0). y/lib/mask: (..., W); dispersion broadcastable to (...).
    Returns λ (...).
    """
    ym = jnp.where(mask, y, 0.0)
    libm = jnp.where(mask, lib, 0.0)
    tot_y = jnp.sum(ym, axis=-1)
    tot_lib = jnp.maximum(jnp.sum(libm, axis=-1), 1e-30)
    beta0 = jnp.log(jnp.maximum(tot_y, 1e-10) / tot_lib)
    r = 1.0 / jnp.maximum(dispersion, 1e-10)

    def body(_, beta):
        mu = jnp.exp(beta)[..., None] * libm
        w = mu * (ym + r[..., None]) / (mu + r[..., None])
        f = jnp.sum(jnp.where(mask, ym - w, 0.0), axis=-1)
        df = -jnp.sum(
            jnp.where(
                mask,
                mu * r[..., None] * (ym + r[..., None]) / jnp.square(mu + r[..., None]),
                0.0,
            ),
            axis=-1,
        )
        step = jnp.clip(f / jnp.minimum(df, -1e-12), -2.0, 2.0)
        return beta - step

    beta = jax.lax.fori_loop(0, n_iter, body, beta0)
    # All-zero groups have no signal: rate 0.
    return jnp.where(tot_y > 0, jnp.exp(beta), 0.0)


def _qgamma(p: jnp.ndarray, shape: jnp.ndarray, n_iter: int = 3) -> jnp.ndarray:
    """Gamma(shape, scale=1) quantile via Wilson–Hilferty start + Newton on
    the regularized incomplete gamma (no gammaincinv in jax.scipy).

    ``gammainc`` is ~60× a ``gammaln`` on this backend and dominates the
    whole q2q map (the NB engine's hottest phase), so iterations are
    precious: measured against scipy's exact ``gammaincinv`` over the
    realistic (λ·lib, φ) domain, 3 Newton steps from the WH start give the
    same p99/aggregate pseudo-count error as the previous 6 (the clamped
    steps converge slowly in the extreme-shape tails either way; at φ=2.5
    the 3-step aggregate error is actually LOWER, 2.3e-2 vs 3.5e-2 — see
    ROUND5_NOTES.md; 2 steps shaved engine↔oracle DE agreement in the
    high-dispersion stress regime below its 0.98 gate, so 3 it is).
    ``gammaln(shape)`` is loop-invariant and hoisted."""
    z = jsp.ndtri(jnp.clip(p, 1e-7, 1.0 - 1e-7))
    c = 1.0 / (9.0 * jnp.maximum(shape, 1e-6))
    x0 = shape * (1.0 - c + z * jnp.sqrt(c)) ** 3
    x0 = jnp.maximum(x0, 1e-8)
    log_norm = jsp.gammaln(shape)

    def body(_, x):
        f = jsp.gammainc(shape, x) - p
        logpdf = (shape - 1.0) * jnp.log(x) - x - log_norm
        pdf = jnp.exp(logpdf)
        step = f / jnp.maximum(pdf, 1e-30)
        x_new = x - jnp.clip(step, -0.5 * x, 0.5 * x + 1.0)
        return jnp.maximum(x_new, 1e-10)

    return jax.lax.fori_loop(0, n_iter, body, x0)


def q2q_normal(
    x: jnp.ndarray,
    mu_in: jnp.ndarray,
    mu_out: jnp.ndarray,
    dispersion: jnp.ndarray,
) -> jnp.ndarray:
    """Normal-approximation half of the NB quantile map: exact z-score
    transfer between the two moment-matched normals (~10 flops/element, no
    transcendentals beyond one sqrt).

    Used for full-matrix library equalization where only group *sums* of the
    pseudo-counts are consumed downstream (the skewness correction the gamma
    map adds is zero-mean across cells and washes out of sums; the full
    two-map average ``q2q_nbinom`` is reserved for the dispersion-estimation
    subsample where per-value shape matters).
    """
    mu_in = jnp.maximum(mu_in, 1e-10)
    mu_out = jnp.maximum(mu_out, 1e-10)
    v_in = mu_in + dispersion * mu_in * mu_in
    v_out = mu_out + dispersion * mu_out * mu_out
    return jnp.maximum(mu_out + (x - mu_in) * jnp.sqrt(v_out / v_in), 0.0)


def q2q_normal_raw(
    x: jnp.ndarray,
    mu_in: jnp.ndarray,
    mu_out: jnp.ndarray,
    dispersion: jnp.ndarray,
) -> jnp.ndarray:
    """Unclamped normal half of the NB quantile map (z-score transfer).
    Shared by ``q2q_nbinom`` and the zero-compacted table builder in
    de.edger so the two paths stay arithmetically identical."""
    mu_in = jnp.maximum(mu_in, 1e-10)
    mu_out = jnp.maximum(mu_out, 1e-10)
    v_in = mu_in + dispersion * mu_in * mu_in
    v_out = mu_out + dispersion * mu_out * mu_out
    return mu_out + (x - mu_in) * jnp.sqrt(v_out / v_in)


def q2q_gamma_raw(
    x: jnp.ndarray,
    mu_in: jnp.ndarray,
    mu_out: jnp.ndarray,
    dispersion: jnp.ndarray,
) -> jnp.ndarray:
    """Gamma half of the NB quantile map: moment-matched shapes, lower-tail
    quantile transfer. x = 0 maps to EXACTLY 0: the continuous gamma
    approximation places no mass below 0, so the transferred quantile of a
    zero count is the 0-quantile — the previous behavior (clip p to 1e-7,
    invert) returned the 1e-7-quantile, a pure clip artifact. This is also
    what lets the table builder skip the ~60×-a-gammaln ``gammainc`` chain
    on the zero entries entirely (they dominate expression matrices)."""
    mu_in = jnp.maximum(mu_in, 1e-10)
    mu_out = jnp.maximum(mu_out, 1e-10)
    v_in = mu_in + dispersion * mu_in * mu_in
    v_out = mu_out + dispersion * mu_out * mu_out
    shape_in = mu_in * mu_in / v_in
    scale_in = v_in / mu_in
    shape_out = mu_out * mu_out / v_out
    scale_out = v_out / mu_out
    p = jsp.gammainc(shape_in, jnp.maximum(x, 0.0) / scale_in)
    q_gamma = _qgamma(p, shape_out) * scale_out
    return jnp.where(x > 0, q_gamma, 0.0)


def q2q_nbinom(
    x: jnp.ndarray,
    mu_in: jnp.ndarray,
    mu_out: jnp.ndarray,
    dispersion: jnp.ndarray,
) -> jnp.ndarray:
    """Quantile-to-quantile NB mapping: observed count x at mean mu_in →
    equivalent (continuous) pseudo-count at mean mu_out, matching quantiles.

    The average of a normal-approximation map (exact z-score transfer) and a
    gamma-approximation map — the same two-approximation average edgeR's
    quantile adjustment is built on. Inputs broadcast; dispersion ≥ 0.
    """
    q_norm = q2q_normal_raw(x, mu_in, mu_out, dispersion)
    q_gamma = q2q_gamma_raw(x, mu_in, mu_out, dispersion)
    return jnp.maximum(0.5 * (q_norm + q_gamma), 0.0)


class PseudoCounts(NamedTuple):
    pseudo: jnp.ndarray   # (..., W) equalized continuous counts
    rate1: jnp.ndarray    # (...) group-1 rate λ
    rate2: jnp.ndarray


def equalize_pseudo(
    y: jnp.ndarray,
    lib: jnp.ndarray,
    m1: jnp.ndarray,
    m2: jnp.ndarray,
    common_lib: jnp.ndarray,
    dispersion: jnp.ndarray,
) -> PseudoCounts:
    """equalizeLibSizes for a two-group tile: fit each group's NB rate, then
    quantile-map every observation from its own library size to the common
    library size (geometric mean), preserving the group rate.

    y: (..., W); lib: (..., W); m1/m2: (..., W); common_lib, dispersion: (...).
    """
    r1 = one_group_nb_rate(y, lib, m1, dispersion)
    r2 = one_group_nb_rate(y, lib, m2, dispersion)
    rate = r1[..., None] * m1 + r2[..., None] * m2
    rate = jnp.maximum(rate, 1e-10)
    mu_in = rate * lib
    mu_out = rate * common_lib[..., None]
    pseudo = q2q_nbinom(y, mu_in, mu_out, dispersion[..., None])
    return PseudoCounts(jnp.where(m1 | m2, pseudo, 0.0), r1, r2)


def delta_grid(n: int = DEFAULT_DELTA_GRID_SIZE) -> jnp.ndarray:
    """δ = φ/(1+φ) grid on edgeR's optimize interval (1e-4, 100/101),
    log-spaced in φ."""
    log_phi = jnp.linspace(jnp.log(1e-4), jnp.log(100.0), n)
    phi = jnp.exp(log_phi)
    return phi / (1.0 + phi)


def common_dispersion_grid(
    ll_grid_sum: jnp.ndarray, deltas: jnp.ndarray
) -> jnp.ndarray:
    """Given summed conditional LL over genes at each δ grid point (..., D),
    return the maximizing dispersion φ with quadratic refinement in log φ."""
    phi = deltas / (1.0 - deltas)
    log_phi = jnp.log(phi)
    i = jnp.argmax(ll_grid_sum, axis=-1)
    i = jnp.clip(i, 1, deltas.shape[0] - 2)
    take = lambda a, off: jnp.take_along_axis(
        a, (i + off)[..., None], axis=-1
    )[..., 0]
    y0, y1, y2 = (take(ll_grid_sum, -1), take(ll_grid_sum, 0), take(ll_grid_sum, 1))
    x0, x1, x2 = (
        jnp.take(log_phi, i - 1),
        jnp.take(log_phi, i),
        jnp.take(log_phi, i + 1),
    )
    # Vertex of the parabola through three (possibly non-uniform) points,
    # Newton form: f(x) = y0 + s01·(x−x0) + c·(x−x0)(x−x1) with
    # s01 = Δy/Δx on the left interval and c the divided second difference;
    # f'(x*) = 0 at x* = (x0+x1)/2 − s01/(2c).
    s01 = (y1 - y0) / jnp.maximum(x1 - x0, 1e-12)
    s12 = (y2 - y1) / jnp.maximum(x2 - x1, 1e-12)
    c = (s12 - s01) / jnp.maximum(x2 - x0, 1e-12)
    x_star = 0.5 * (x0 + x1) - s01 / jnp.where(
        jnp.abs(c) > 1e-12, 2.0 * c, jnp.inf
    )
    shift = jnp.clip(x_star - x1, x0 - x1, x2 - x1)
    return jnp.exp(x1 + shift)


def tagwise_dispersion(
    ll_grid: jnp.ndarray,
    common_dispersion: jnp.ndarray,
    prior_n: jnp.ndarray,
    gene_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Weighted-likelihood EB tagwise dispersion (trend="none").

    ll_grid: (..., G, T) per-gene conditional LL at dispersions
    common·2^TAGWISE_GRID_EXPONENTS; prior_n: prior weight
    (= prior.df / (n_samples − n_groups)); gene_mask: (..., G) genes entering
    the shared-likelihood average. Returns (..., G) dispersions.
    """
    w = gene_mask[..., None].astype(ll_grid.dtype)
    shared = jnp.sum(ll_grid * w, axis=-2) / jnp.maximum(
        jnp.sum(w, axis=-2), 1.0
    )  # (..., T)
    wl = ll_grid + prior_n[..., None, None] * shared[..., None, :]
    t = TAGWISE_GRID_EXPONENTS.shape[0]
    i = jnp.clip(jnp.argmax(wl, axis=-1), 1, t - 2)
    take = lambda off: jnp.take_along_axis(wl, (i + off)[..., None], axis=-1)[..., 0]
    y0, y1, y2 = take(-1), take(0), take(1)
    denom = y0 - 2.0 * y1 + y2
    h = TAGWISE_GRID_EXPONENTS[1] - TAGWISE_GRID_EXPONENTS[0]
    shift = jnp.where(jnp.abs(denom) > 1e-12, 0.5 * (y0 - y2) / denom * h, 0.0)
    shift = jnp.clip(shift, -h, h)
    expo = jnp.take(TAGWISE_GRID_EXPONENTS, i) + shift
    return common_dispersion[..., None] * jnp.exp2(expo)


def _normal_tails(s1r, s, alpha, beta):
    """Moment-matched Beta-Binomial normal tails with continuity correction
    (the large-total branch of the exact test)."""
    ab = alpha + beta
    m = s * alpha / ab
    var = s * alpha * beta * (ab + s) / (ab * ab * (ab + 1.0))
    sd = jnp.sqrt(jnp.maximum(var, 1e-30))
    log_pl = jax.scipy.stats.norm.logcdf((s1r + 0.5 - m) / sd)
    log_pu = jax.scipy.stats.norm.logcdf(-(s1r - 0.5 - m) / sd)
    return log_pl, log_pu


@jax.jit
def nb_exact_test_logp_normal(
    s1: jnp.ndarray,
    s2: jnp.ndarray,
    n1: jnp.ndarray,
    n2: jnp.ndarray,
    dispersion: jnp.ndarray,
) -> jnp.ndarray:
    """Two-sided log p via the normal branch only — for (pair, gene) entries
    whose totals exceed the exact-tail budget (callers route small totals to
    ``nb_exact_test_logp``; same doubling/guard semantics)."""
    s1r = jnp.round(s1)
    s2r = jnp.round(s2)
    s = s1r + s2r
    phi = jnp.maximum(dispersion, 1e-10)
    log_pl, log_pu = _normal_tails(
        s1r, s, n1.astype(jnp.float32) / phi, n2.astype(jnp.float32) / phi
    )
    log_p = jnp.minimum(jnp.log(2.0) + jnp.minimum(log_pl, log_pu), 0.0)
    log_p = jnp.where(s <= 0, 0.0, log_p)
    bad = (n1 < 1) | (n2 < 1)
    return jnp.where(bad, jnp.nan, log_p)


@partial(jax.jit, static_argnames=("s_max",))
def nb_exact_test_logp(
    s1: jnp.ndarray,
    s2: jnp.ndarray,
    n1: jnp.ndarray,
    n2: jnp.ndarray,
    dispersion: jnp.ndarray,
    s_max: int = 4096,
) -> jnp.ndarray:
    """Two-sided log p of the NB exact test, doubling the smaller tail.

    Conditional on s = s1+s2, the group-1 sum is Beta-Binomial(s, α=n1/φ,
    β=n2/φ) (the NB split identity). For s < s_max the tails are exact sums
    via cumulative log pmf-ratios
        pmf(a+1)/pmf(a) = (s−a)(a+α) / ((a+1)(s−a−1+β)),
    which never form large-argument lgamma differences; for s ≥ s_max a
    moment-matched normal approximation with continuity correction.

    s1/s2: group pseudo-count sums (rounded internally, edgeR-style);
    n1/n2: group sizes; all broadcastable to the gene axis.
    """
    s1r = jnp.round(s1)
    s2r = jnp.round(s2)
    s = s1r + s2r
    phi = jnp.maximum(dispersion, 1e-10)
    alpha = n1.astype(jnp.float32) / phi
    beta = n2.astype(jnp.float32) / phi

    # --- exact branch (s < s_max) ---
    a = jnp.arange(s_max, dtype=jnp.float32)  # candidate group-1 sums
    sc = jnp.minimum(s, float(s_max))[..., None]
    ratio_num = (sc - a) * (a + alpha[..., None])
    ratio_den = (a + 1.0) * (sc - a - 1.0 + beta[..., None])
    # one log of the ratio, not log(num)−log(den): the transcendental count
    # is the cost of this sweep, and both operands are far from f32
    # overflow (≤ s_max·(s_max+α) ≲ 1e9)
    log_ratio = jnp.log(
        jnp.maximum(ratio_num, 1e-37) / jnp.maximum(ratio_den, 1e-37)
    )
    # u(a) = log pmf(a) − log pmf(0); valid for a ≤ s.
    u = jnp.concatenate(
        [jnp.zeros_like(log_ratio[..., :1]), jnp.cumsum(log_ratio, axis=-1)[..., :-1]],
        axis=-1,
    )
    valid = a <= sc
    u = jnp.where(valid, u, -jnp.inf)
    # One exp sweep serves Z and both tails (three masked logsumexps each
    # paid their own max+exp pass over the support — the exp is the cost).
    # Tails are linear-space relative to the mode: a tail whose mass is
    # below ~e^-87 of the mode underflows to the 1e-40 floor, i.e. log p
    # saturates near -87 instead of tracking arbitrarily far — far beyond
    # any DE threshold, and BH compares in log space unaffected.
    m = jnp.max(u, axis=-1, keepdims=True)
    e = jnp.where(valid, jnp.exp(u - m), 0.0)
    z = jnp.sum(e, axis=-1)
    lower = a <= s1r[..., None]
    upper = a >= s1r[..., None]
    pl_lin = jnp.sum(jnp.where(lower, e, 0.0), axis=-1)
    pu_lin = jnp.sum(jnp.where(upper, e, 0.0), axis=-1)
    log_z = jnp.log(jnp.maximum(z, 1e-40))
    log_pl_exact = jnp.log(jnp.maximum(pl_lin, 1e-40)) - log_z
    log_pu_exact = jnp.log(jnp.maximum(pu_lin, 1e-40)) - log_z

    # --- normal branch (s >= s_max) ---
    log_pl_norm, log_pu_norm = _normal_tails(s1r, s, alpha, beta)

    small = s < float(s_max)
    log_pl = jnp.where(small, log_pl_exact, log_pl_norm)
    log_pu = jnp.where(small, log_pu_exact, log_pu_norm)
    log_p = jnp.log(2.0) + jnp.minimum(log_pl, log_pu)
    log_p = jnp.minimum(log_p, 0.0)
    # Zero total → the conditional distribution is a point mass: p = 1.
    log_p = jnp.where(s <= 0, 0.0, log_p)
    # An empty group means there is no test at all → NaN (R's untestable-pair
    # semantics), which BH propagates as NaN q — callers must mask, not rank.
    bad = (n1 < 1) | (n2 < 1)
    return jnp.where(bad, jnp.nan, log_p)
