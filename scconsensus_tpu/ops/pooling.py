"""Centroid pre-pooling for approximate hierarchical clustering at scale.

The reference's scaling wall is the dense N×N distance + O(N²) Ward linkage
(R/reclusterDEConsensus.R:236-246): impossible at N=1M (SURVEY.md §5.7). The
approximate path pools cells onto m ≪ N centroids with device k-means
(matmul-dominated Lloyd iterations — MXU work), runs exact Ward.D2 on the
centroids, and broadcasts cut labels back through the pool assignment —
the Secuer-style anchor strategy (PAPERS.md) realized on TPU.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scconsensus_tpu.ops.linkage import HClustTree, ward_linkage

__all__ = ["kmeans_pool", "pooled_ward_linkage"]


# Point-block width for the assignment sweep: bounds the live (block, m)
# distance tile so 1M×4096 never materializes (16 GB would blow v5e HBM).
_LLOYD_BLOCK = 65_536


@partial(jax.jit, static_argnames=("n_iter",))
def _lloyd(points: jnp.ndarray, centroids: jnp.ndarray, n_iter: int = 10):
    """Blocked Lloyd iterations; returns (centroids, assignment).

    Callers pass only real rows: padding to a multiple of the block width
    happens internally, with an internal validity mask giving pad rows zero
    weight in the centroid update.
    """
    n, d = points.shape
    m = centroids.shape[0]
    nb = n // _LLOYD_BLOCK if n % _LLOYD_BLOCK == 0 else n // _LLOYD_BLOCK + 1
    pad = nb * _LLOYD_BLOCK - n
    pts = jnp.pad(points, ((0, pad), (0, 0)))
    valid = jnp.pad(jnp.ones((n,), points.dtype), (0, pad))
    pb = pts.reshape(nb, _LLOYD_BLOCK, d)
    vb = valid.reshape(nb, _LLOYD_BLOCK)

    def assign_block(cent, block, vmask):
        dist = (
            jnp.sum(block * block, axis=1, keepdims=True)
            - 2.0 * block @ cent.T
            + jnp.sum(cent * cent, axis=1)[None, :]
        )
        a = jnp.argmin(dist, axis=1)
        oh = jax.nn.one_hot(a, m, dtype=block.dtype) * vmask[:, None]
        return a, jnp.sum(oh, axis=0), oh.T @ block

    def step(cent, _):
        def fold(carry, inp):
            counts, sums = carry
            block, vmask = inp
            _, c, s = assign_block(cent, block, vmask)
            return (counts + c, sums + s), None

        (counts, sums), _ = jax.lax.scan(
            fold,
            (jnp.zeros((m,), pts.dtype), jnp.zeros((m, d), pts.dtype)),
            (pb, vb),
        )
        new = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], cent
        )
        return new, None

    cent, _ = jax.lax.scan(step, centroids, None, length=n_iter)

    def final(carry, inp):
        block, vmask = inp
        a, _, _ = assign_block(cent, block, vmask)
        return carry, a

    _, assign = jax.lax.scan(final, None, (pb, vb))
    return cent, assign.reshape(-1)[:n]


def kmeans_pool(
    x: np.ndarray, n_centroids: int, n_iter: int = 10, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Pool rows of x (N, d) onto ``n_centroids`` k-means centroids.
    Returns (centroids (m, d), assignment (N,)); empty centroids are dropped."""
    n = x.shape[0]
    m = min(n_centroids, n)
    rng = np.random.default_rng(seed)
    init = x[rng.choice(n, size=m, replace=False)]
    from scconsensus_tpu.obs.residency import boundary

    with boundary("tree_pool_fetch"):
        cent, assign = _lloyd(jnp.asarray(x, jnp.float32),
                              jnp.asarray(init, jnp.float32), n_iter=n_iter)
        cent = np.asarray(cent, np.float64)
        assign = np.asarray(assign)
    used = np.unique(assign)
    remap = -np.ones(m, np.int64)
    remap[used] = np.arange(used.size)
    return cent[used], remap[assign]


def pooled_ward_linkage(
    x: np.ndarray, n_centroids: int = 4096, n_iter: int = 10, seed: int = 0
) -> Tuple[HClustTree, np.ndarray, np.ndarray]:
    """Ward tree over k-means centroids, weighted by pool occupancy so heights
    approximate full-data Ward.D2. Returns (tree, assignment (N,), centroids).
    Cut labels computed on the tree apply to cells via ``labels[assign]``."""
    cent, assign = kmeans_pool(x, n_centroids, n_iter, seed)
    counts = np.bincount(assign, minlength=cent.shape[0]).astype(np.float64)
    tree = ward_linkage(cent, weights=counts)
    return tree, assign, cent
