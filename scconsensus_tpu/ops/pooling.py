"""Centroid pre-pooling for approximate hierarchical clustering at scale.

The reference's scaling wall is the dense N×N distance + O(N²) Ward linkage
(R/reclusterDEConsensus.R:236-246): impossible at N=1M (SURVEY.md §5.7). The
approximate path pools cells onto m ≪ N centroids with device k-means
(matmul-dominated Lloyd iterations — MXU work), runs exact Ward.D2 on the
centroids, and broadcasts cut labels back through the pool assignment —
the Secuer-style anchor strategy (PAPERS.md) realized on TPU.

Two pooling engines live here:

* :func:`kmeans_pool` / :func:`pooled_ward_linkage` — the r4 full-data
  Lloyd: every iteration sweeps ALL N points and accumulates the centroid
  update through an explicit (block, m) one-hot matmul. Numerically frozen
  (the sub-threshold approximate path is pinned byte-identical across
  rounds); at 1M cells its 11 full sweeps were 396 s of the 676 s pipe —
  the r7 bottleneck.

* :func:`landmark_pool` / :func:`landmark_ward_linkage` — the r7 landmark
  recluster engine (ROADMAP item 1, Secuer's anchor argument taken
  seriously): fit k = clamp(c·√N, k_min, k_max) landmarks by device Lloyd
  over a seeded SKETCH of the data (k-means centroids need a sample, not
  the population), then ONE blocked device pass assigns every cell to its
  nearest landmark — argmin + ``segment_sum``, no (block, k) one-hot ever
  materializes. Host traffic is the (k, d) centroids and the (N,)
  assignment; Ward runs on the k weighted landmarks. 1M×15 on 2 CPU
  cores: 396 s → ~22 s.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scconsensus_tpu.obs.graphs import instrument as _passport
from scconsensus_tpu.ops.distance import _sq_dists_raw
from scconsensus_tpu.ops.linkage import HClustTree, ward_linkage

__all__ = [
    "kmeans_pool",
    "pooled_ward_linkage",
    "landmark_k_policy",
    "landmark_sketch_policy",
    "landmark_pool",
    "landmark_ward_linkage",
    "centroid_majority_labels",
]


def _note_pool_build() -> None:
    """Bump the ambient span's ``pool_builds`` counter: every Lloyd fit
    (legacy or landmark) registers here, so the single-pooling contract —
    a landmark-path pipeline run fits exactly ONE pool, which silhouette
    then reuses — is assertable from span metrics alone."""
    from scconsensus_tpu.obs import trace as obs_trace

    span = obs_trace.current_span()
    if span is not None:
        try:
            span.metrics.counter("pool_builds").add(1)
        except Exception:  # metrics must never cost the fit
            pass


# Point-block width for the assignment sweep: bounds the live (block, m)
# distance tile so 1M×4096 never materializes (16 GB would blow v5e HBM).
_LLOYD_BLOCK = 65_536


@partial(jax.jit, static_argnames=("n_iter",))
def _lloyd(points: jnp.ndarray, centroids: jnp.ndarray, n_iter: int = 10):
    """Blocked Lloyd iterations; returns (centroids, assignment).

    Callers pass only real rows: padding to a multiple of the block width
    happens internally, with an internal validity mask giving pad rows zero
    weight in the centroid update.
    """
    n, d = points.shape
    m = centroids.shape[0]
    nb = n // _LLOYD_BLOCK if n % _LLOYD_BLOCK == 0 else n // _LLOYD_BLOCK + 1
    pad = nb * _LLOYD_BLOCK - n
    pts = jnp.pad(points, ((0, pad), (0, 0)))
    valid = jnp.pad(jnp.ones((n,), points.dtype), (0, pad))
    pb = pts.reshape(nb, _LLOYD_BLOCK, d)
    vb = valid.reshape(nb, _LLOYD_BLOCK)

    def assign_block(cent, block, vmask):
        dist = (
            jnp.sum(block * block, axis=1, keepdims=True)
            - 2.0 * block @ cent.T
            + jnp.sum(cent * cent, axis=1)[None, :]
        )
        a = jnp.argmin(dist, axis=1)
        oh = jax.nn.one_hot(a, m, dtype=block.dtype) * vmask[:, None]
        return a, jnp.sum(oh, axis=0), oh.T @ block

    def step(cent, _):
        def fold(carry, inp):
            counts, sums = carry
            block, vmask = inp
            _, c, s = assign_block(cent, block, vmask)
            return (counts + c, sums + s), None

        (counts, sums), _ = jax.lax.scan(
            fold,
            (jnp.zeros((m,), pts.dtype), jnp.zeros((m, d), pts.dtype)),
            (pb, vb),
        )
        new = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], cent
        )
        return new, None

    cent, _ = jax.lax.scan(step, centroids, None, length=n_iter)

    def final(carry, inp):
        block, vmask = inp
        a, _, _ = assign_block(cent, block, vmask)
        return carry, a

    _, assign = jax.lax.scan(final, None, (pb, vb))
    return cent, assign.reshape(-1)[:n]


def kmeans_pool(
    x: np.ndarray, n_centroids: int, n_iter: int = 10, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Pool rows of x (N, d) onto ``n_centroids`` k-means centroids.
    Returns (centroids (m, d), assignment (N,)); empty centroids are dropped."""
    n = x.shape[0]
    m = min(n_centroids, n)
    rng = np.random.default_rng(seed)
    init = x[rng.choice(n, size=m, replace=False)]
    from scconsensus_tpu.obs.residency import boundary

    _note_pool_build()
    with boundary("tree_pool_fetch"):
        cent, assign = _lloyd(jnp.asarray(x, jnp.float32),
                              jnp.asarray(init, jnp.float32), n_iter=n_iter)
        cent = np.asarray(cent, np.float64)
        assign = np.asarray(assign)
    used = np.unique(assign)
    remap = -np.ones(m, np.int64)
    remap[used] = np.arange(used.size)
    return cent[used], remap[assign]


def pooled_ward_linkage(
    x: np.ndarray, n_centroids: int = 4096, n_iter: int = 10, seed: int = 0
) -> Tuple[HClustTree, np.ndarray, np.ndarray]:
    """Ward tree over k-means centroids, weighted by pool occupancy so heights
    approximate full-data Ward.D2. Returns (tree, assignment (N,), centroids).
    Cut labels computed on the tree apply to cells via ``labels[assign]``."""
    cent, assign = kmeans_pool(x, n_centroids, n_iter, seed)
    counts = np.bincount(assign, minlength=cent.shape[0]).astype(np.float64)
    tree = ward_linkage(cent, weights=counts)
    return tree, assign, cent


# --------------------------------------------------------------------------
# landmark recluster engine (r7, ROADMAP item 1)
# --------------------------------------------------------------------------

def landmark_k_policy(
    n: int, c: float = 2.0, k_min: int = 512, k_max: int = 4096
) -> int:
    """N-scaled landmark count: ``clamp(c·√N, k_min, k_max)`` rounded up to
    a multiple of 128 (the MXU lane width — the (block, k) distance tile is
    a matmul and full lanes are free). The caps win over the rounding:
    never exceeds k_max or N."""
    k = int(math.ceil(c * math.sqrt(max(n, 1))))
    k = min(max(k, int(k_min), 2), int(k_max))
    if k > 128:
        k = min(((k + 127) // 128) * 128, int(k_max))
    return min(k, n)


def landmark_sketch_policy(n: int, k: int) -> int:
    """Sketch size the landmark Lloyd fits on: enough points per landmark
    for stable centroids (~32·k), floored for tiny k, capped so the fit
    never re-approaches a full sweep. Always ≥ k and ≤ N."""
    return int(min(n, max(32 * k, 16_384, k), 131_072))


@partial(jax.jit, static_argnames=("n_iter",))
def _lloyd_sketch(pb, vb, cent, n_iter: int = 10):
    """Blocked Lloyd over a sketch, centroid update via ``segment_sum``.

    Unlike the legacy ``_lloyd`` the per-block (block, k) one-hot never
    materializes: the distance tile feeds an argmin and the update is two
    segment reductions — half the FLOPs and none of the one-hot memory
    traffic (the r6 1M profile showed the one-hot stream dominating).
    Pad rows carry segment id k and fall off the ``[:k]`` slice.
    """
    m = cent.shape[0]

    def assign_block(c, block, vmask):
        d2 = _sq_dists_raw(block, c)
        a = jnp.argmin(d2, axis=1)
        return jnp.where(vmask > 0, a, m)

    def step(c, _):
        def fold(carry, inp):
            counts, sums = carry
            block, vmask = inp
            a = assign_block(c, block, vmask)
            counts = counts + jax.ops.segment_sum(
                vmask, a, num_segments=m + 1
            )[:m]
            sums = sums + jax.ops.segment_sum(
                block * vmask[:, None], a, num_segments=m + 1
            )[:m]
            return (counts, sums), None

        (counts, sums), _ = jax.lax.scan(
            fold,
            (jnp.zeros((m,), pb.dtype), jnp.zeros((m, pb.shape[-1]),
                                                  pb.dtype)),
            (pb, vb),
        )
        new = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], c
        )
        return new, None

    cent, _ = jax.lax.scan(step, cent, None, length=n_iter)
    return cent


@jax.jit
def _assign_blocks(pb, cent):
    """One nearest-landmark pass over blocked points: the jitted device
    form of cut propagation (1-NN over landmarks — the degenerate kNN the
    ring engine generalizes). Only the (nb, block) int32 argmins leave the
    scan; the (block, k) distance tile lives and dies on device."""
    def fold(carry, block):
        d2 = _sq_dists_raw(block, cent)
        return carry, jnp.argmin(d2, axis=1).astype(jnp.int32)

    _, a = jax.lax.scan(fold, None, pb)
    return a


# graph passports (obs.graphs, SCC_GRAPHS): the landmark-assign stage
# programs (sketch fit, legacy full-data Lloyd, cut-propagation 1-NN)
_lloyd = _passport("landmark.lloyd", _lloyd)
_lloyd_sketch = _passport("landmark.lloyd_sketch", _lloyd_sketch)
_assign_blocks = _passport("landmark.assign_blocks", _assign_blocks)


def landmark_pool(
    x: np.ndarray,
    n_landmarks: Optional[int] = None,
    sketch: Optional[int] = None,
    n_iter: int = 10,
    seed: int = 0,
    c: float = 2.0,
    k_min: int = 512,
    k_max: int = 4096,
    charge=None,
) -> Tuple[np.ndarray, np.ndarray, Dict]:
    """Pool rows of x (N, d) onto k ≪ N landmarks: sketch-fitted device
    Lloyd + one full blocked assignment pass.

    Returns (centroids (k', d), assignment (N,), info) with empty landmarks
    dropped (k' ≤ k) and ``info`` carrying the policy telemetry the quality
    section stamps (k requested/used, sketch size, iterations).

    A device-resident input stays resident: padding/reshaping and the
    sketch/init gathers are jnp ops, so the only crossings are the one h2d
    staging of a HOST input and the (k, d) + (N,) results coming back.

    ``charge(nbytes, what)`` (optional): the out-of-core runner's budget
    accountant hook — called with the staging footprint BEFORE the
    device upload, so a streaming run's host-memory ledger prices the
    landmark fit's (N, d) staging like every other buffer (a breach
    raises typed HostBudgetExceeded here, before the allocation, rather
    than OOMing mid-Lloyd).
    """
    n, d = x.shape
    k = int(n_landmarks) if n_landmarks else landmark_k_policy(
        n, c=c, k_min=k_min, k_max=k_max
    )
    k = min(k, n)
    s = int(sketch) if sketch else landmark_sketch_policy(n, k)
    s = min(max(s, k), n)
    rng = np.random.default_rng(seed)
    sk_idx = rng.choice(n, size=s, replace=False) if s < n else np.arange(n)
    init_idx = rng.choice(s, size=k, replace=False)

    from scconsensus_tpu.obs.residency import boundary
    from scconsensus_tpu.obs.trace import span as obs_span

    _note_pool_build()
    if charge is not None:
        # (N, d) f32 staging + the padded block view: the dominant host
        # cost of the fit/assign pass, priced before it exists
        charge(int(n) * int(d) * 4, "landmark_staging")
    nb = (n + _LLOYD_BLOCK - 1) // _LLOYD_BLOCK
    pad = nb * _LLOYD_BLOCK - n
    snb = (s + _LLOYD_BLOCK - 1) // _LLOYD_BLOCK
    spad = snb * _LLOYD_BLOCK - s
    with boundary("landmark_assign_fetch"):
        # one h2d staging of a host input (no-op for device input), then
        # the two intended d2h crossings: (k, d) centroids, (N,) assignment
        with obs_span("landmark_fit", sync=True, k=k, sketch=s):
            xd = jnp.asarray(x, jnp.float32)
            sk = xd[jnp.asarray(sk_idx)] if s < n else xd
            init = sk[jnp.asarray(init_idx)]
            spb = jnp.pad(sk, ((0, spad), (0, 0))).reshape(
                snb, _LLOYD_BLOCK, d
            )
            svb = jnp.pad(jnp.ones((s,), jnp.float32), (0, spad)).reshape(
                snb, _LLOYD_BLOCK
            )
            cent_d = _lloyd_sketch(spb, svb, init, n_iter=n_iter)
        with obs_span("landmark_assign", sync=True, n_cells=n):
            pb = jnp.pad(xd, ((0, pad), (0, 0))).reshape(
                nb, _LLOYD_BLOCK, d
            )
            assign = np.asarray(_assign_blocks(pb, cent_d)).reshape(-1)[:n]
            cent = np.asarray(cent_d, np.float64)
    # Computation-integrity tier (robust.integrity, r18): the injected
    # in-computation corruption site, the occupancy-conservation
    # invariant (segment-sum of occupancies == assigned-cell count,
    # every index live), and — once per run — the float64 ghost replay
    # of one seeded assignment block against the fetched centroids. A
    # detection raises typed silent_corruption inside the tree stage's
    # guard, so the unit recomputes before any artifact persists.
    from scconsensus_tpu.robust import integrity as robust_integrity
    from scconsensus_tpu.robust.faults import corrupt_value

    assign = corrupt_value("landmark_assign", assign)
    if robust_integrity.enabled():
        robust_integrity.check_landmark_occupancy(
            "landmark_assign", assign, k, n
        )
        if robust_integrity.current().want_replay("landmark", 0):
            blk = robust_integrity._sample_idx(n, 256)
            robust_integrity.replay_landmark_block(
                "landmark_assign",
                x[blk] if isinstance(x, np.ndarray)
                else xd[jnp.asarray(blk)],
                cent, assign[blk], unit="block0",
            )
    used = np.unique(assign)
    remap = -np.ones(k, np.int64)
    remap[used] = np.arange(used.size)
    info = {
        "k_requested": int(k),
        "k_used": int(used.size),
        "sketch": int(s),
        "n_iter": int(n_iter),
    }
    return cent[used], remap[assign], info


def landmark_ward_linkage(
    x: np.ndarray,
    n_landmarks: Optional[int] = None,
    sketch: Optional[int] = None,
    n_iter: int = 10,
    seed: int = 0,
    c: float = 2.0,
    k_min: int = 512,
    k_max: int = 4096,
    linkage: str = "exact",
    knn_k: int = 15,
    mesh=None,
    charge=None,
) -> Tuple[HClustTree, np.ndarray, np.ndarray, Dict]:
    """Landmark recluster tree: occupancy-weighted Ward.D2 over the
    landmark centroids of :func:`landmark_pool`.

    ``linkage="exact"`` runs the native NN-chain on the k centroids (k ≤
    4096 keeps it sub-second); ``"knn"`` routes through
    ``ops.knn_linkage.knn_ward_linkage`` (ring-kNN candidate graph on
    device with ``knn_k`` neighbors per landmark, ``parallel.ring``) for
    configurations that push k far past that. Returns (tree, assignment
    (N,), centroids, info); cut labels on the tree propagate to cells via
    ``labels[assign]``.
    """
    from scconsensus_tpu.obs.trace import span as obs_span

    if linkage not in ("exact", "knn"):
        raise ValueError(
            f"landmark linkage must be 'exact' or 'knn', got {linkage!r}"
        )
    cent, assign, info = landmark_pool(
        x, n_landmarks=n_landmarks, sketch=sketch, n_iter=n_iter,
        seed=seed, c=c, k_min=k_min, k_max=k_max, charge=charge,
    )
    counts = np.bincount(assign, minlength=cent.shape[0]).astype(np.float64)
    with obs_span("landmark_linkage", k=int(cent.shape[0])):
        if linkage == "knn":
            from scconsensus_tpu.ops.knn_linkage import knn_ward_linkage

            tree = knn_ward_linkage(cent, k=knn_k, mesh=mesh,
                                    weights=counts)
        else:
            tree = ward_linkage(cent, weights=counts)
    info["linkage"] = linkage
    return tree, assign, cent, info


def centroid_majority_labels(
    assign: np.ndarray, labels: np.ndarray, k: int
) -> np.ndarray:
    """Per-landmark cluster labels by occupancy-weighted majority vote.

    ``assign`` (N,) maps cells to landmarks, ``labels`` (N,) carries the
    cells' cluster labels with 0 = unassigned (the dynamic-cut
    convention); unassigned cells never vote. Returns (k,) int64 labels,
    0 for a landmark whose members are all unassigned (or empty). Ties
    break to the SMALLEST label — deterministic, so a frozen consensus
    model exports identically run-to-run.
    """
    assign = np.asarray(assign, np.int64)
    labels = np.asarray(labels, np.int64)
    if assign.shape != labels.shape:
        raise ValueError(
            f"assign {assign.shape} and labels {labels.shape} differ"
        )
    out = np.zeros(int(k), np.int64)
    voting = labels > 0
    if not voting.any():
        return out
    a, lab = assign[voting], labels[voting]
    n_lab = int(lab.max()) + 1
    votes = np.zeros((int(k), n_lab), np.int64)
    np.add.at(votes, (a, lab), 1)
    winners = np.argmax(votes, axis=1)  # argmax ties -> smallest label
    has_votes = votes.sum(axis=1) > 0
    out[has_votes] = winners[has_votes]
    return out
