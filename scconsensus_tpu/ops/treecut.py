"""Dynamic hybrid tree cut (``dynamicTreeCut::cutreeDynamic`` equivalent).

Replaces the reference's calls at R/reclusterDEConsensus.R:254-260 /
R/reclusterDEConsensusFast.R:421-427 (``pamStage=FALSE``, deepSplit 0–4,
``minClusterSize``; 0 = unassigned → 'grey').

Implementation note (SURVEY.md §7 hard part #3): this is a re-derivation of
the *hybrid* algorithm of Langfelder, Zhang & Horvath (2008) — "Defining
clusters from a hierarchical cluster tree" — not a transcription of the R
source. The shape of the algorithm:

  1. Reference heights: refHeight = the 5%-quantile merge height; cutHeight
     defaults to refHeight + 0.99·(max height − refHeight). Merges above
     cutHeight are never joined.
  2. deepSplit ∈ {0..4} sets the shape criteria via the canonical constants:
     maxCoreScatter interpolated over (0.64, 0.73, 0.82, 0.91, 0.95) and
     minGap = (1 − maxCoreScatter)·3/4, both mapped to absolute scale over
     [refHeight, cutHeight].
  3. Merges are processed bottom-up, growing branches (ordered singleton lists
     with join heights). When two branches meet, each is tested as a basic
     cluster: size ≥ minClusterSize, core scatter (mean pairwise distance of
     the first CoreSize members) ≤ maxAbsCoreScatter, and gap (death height −
     core completion height) ≥ minAbsGap. Both pass → both are emitted as
     clusters and the union continues as a composite; otherwise the branches
     fuse and keep growing.
  4. Surviving root branches are evaluated at cutHeight. Remaining objects are
     unassigned (label 0). The optional PAM stage assigns them to the nearest
     cluster by mean distance (bounded by cutHeight).

Because the upstream R source is not consultable in this environment, exact
tie-level parity with dynamicTreeCut is *not* guaranteed; fidelity is enforced
behaviorally (planted-structure recovery, deepSplit monotonicity — see
tests/test_treecut.py) and the constants/structure above follow the published
description.

Distances are taken from the embedding on demand (core sets are small); the
PAM stage streams device-computed distance blocks. No N×N materialization.
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from scconsensus_tpu.ops.linkage import HClustTree

__all__ = ["cutree_hybrid", "core_size", "DEEP_SPLIT_CORE_SCATTER"]

DEEP_SPLIT_CORE_SCATTER = (0.64, 0.73, 0.82, 0.91, 0.95)


def core_size(branch_size: int, min_cluster_size: int) -> int:
    """Size of the branch 'core' (its earliest-joining members):
    min(minClusterSize/2 + 1 + sqrt(size − that), size)."""
    base = min_cluster_size / 2.0 + 1.0
    if base < branch_size:
        return int(base + np.sqrt(branch_size - base))
    return int(branch_size)


@dataclasses.dataclass
class _Branch:
    singletons: List[int]
    heights: List[float]
    composite: bool = False


def _merge_sorted(b1: _Branch, b2: _Branch) -> _Branch:
    """Fuse two branches, interleaving members by join height.

    Ties keep b1's members first (matches the general interleave below).
    Both inputs are consumed by the caller (popped from the branch table /
    fresh singletons), so the fast paths mutate and return one of them: a
    singleton joining a branch is one bisect + two C-level list.insert
    memmoves, not a Python re-interleave of the whole branch (40 % of the
    26k-cell cut's time)."""
    a_s, a_h, b_s, b_h = b1.singletons, b1.heights, b2.singletons, b2.heights
    if not a_s:
        return b2
    if not b_s:
        return b1
    if len(b_s) == 1:
        pos = bisect.bisect_right(a_h, b_h[0])  # a first on ties
        a_s.insert(pos, b_s[0]); a_h.insert(pos, b_h[0])
        return b1
    if len(a_s) == 1:
        pos = bisect.bisect_left(b_h, a_h[0])   # a first on ties
        b_s.insert(pos, a_s[0]); b_h.insert(pos, a_h[0])
        return b2
    if a_h[-1] <= b_h[0]:  # disjoint height ranges: plain concat
        return _Branch(a_s + b_s, a_h + b_h)
    if b_h[-1] < a_h[0]:   # symmetric case (strict: a first on ties)
        return _Branch(b_s + a_s, b_h + a_h)
    s: List[int] = []
    h: List[float] = []
    i = j = 0
    while i < len(a_s) and j < len(b_s):
        if a_h[i] <= b_h[j]:
            s.append(a_s[i]); h.append(a_h[i]); i += 1
        else:
            s.append(b_s[j]); h.append(b_h[j]); j += 1
    s.extend(a_s[i:]); h.extend(a_h[i:])
    s.extend(b_s[j:]); h.extend(b_h[j:])
    return _Branch(s, h)


def _core_scatter(embedding: np.ndarray, members: Sequence[int]) -> float:
    pts = embedding[np.asarray(members)]
    m = pts.shape[0]
    if m < 2:
        return 0.0
    sq = np.sum(pts * pts, axis=1)
    d2 = np.maximum(sq[:, None] + sq[None, :] - 2.0 * pts @ pts.T, 0.0)
    # mean over off-diagonal pairs: the matrix is symmetric with a zero
    # diagonal, so sum/ (m(m-1)) — no triu_indices materialization (was
    # ~20 % of a 26k-cell cut)
    return float(np.sqrt(d2).sum() / (m * (m - 1)))


def _qualifies(
    branch: _Branch,
    death_height: float,
    embedding: np.ndarray,
    min_cluster_size: int,
    max_abs_core_scatter: float,
    min_abs_gap: float,
    weights: Optional[np.ndarray] = None,
) -> bool:
    if weights is None:
        size = len(branch.singletons)
        if size < min_cluster_size:
            return False
        n_core = core_size(size, min_cluster_size)
    else:
        # Centroid-weighted semantics (the landmark recluster path): each
        # leaf stands for weights[leaf] cells, so the size criterion and
        # the core-size formula run in CELL units — minClusterSize keeps
        # its reference meaning at any pooling ratio — and the core is
        # the earliest-joining leaves whose cumulative weight reaches the
        # cell-unit core size.
        w = weights[np.asarray(branch.singletons)]
        size = float(w.sum())
        if size < min_cluster_size:
            return False
        cum = np.cumsum(w)
        n_core = int(
            np.searchsorted(cum, core_size(size, min_cluster_size),
                            side="left")
        ) + 1
        n_core = min(n_core, len(branch.singletons))
    scatter = _core_scatter(embedding, branch.singletons[:n_core])
    if scatter > max_abs_core_scatter:
        return False
    gap = death_height - branch.heights[n_core - 1]
    return gap >= min_abs_gap


def cutree_hybrid(
    tree: HClustTree,
    embedding: np.ndarray,
    deep_split: int = 1,
    min_cluster_size: int = 10,
    cut_height: Optional[float] = None,
    pam_stage: bool = False,
    max_pam_dist: Optional[float] = None,
    weights: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Hybrid dynamic cut of an hclust tree.

    Args:
      tree: Ward tree over the embedding's rows.
      embedding: (N, d) points the tree was built on (distance source).
      deep_split: 0 (conservative) .. 4 (aggressive splitting).
      pam_stage: assign unlabeled objects to nearest cluster afterwards.
      weights: optional (N,) per-leaf observation counts (landmark/pooled
        trees: leaves are centroids standing for ``weights[i]`` cells).
        Branch sizes, ``min_cluster_size``, and core sizes then run in
        cell units; cluster numbering orders by total cell weight.

    Returns (N,) int labels: 1..K by decreasing cluster size, 0 = unassigned.
    """
    if not 0 <= int(deep_split) <= 4:
        raise ValueError(f"deep_split must be in 0..4, got {deep_split}")
    if weights is not None:
        weights = np.ascontiguousarray(weights, np.float64)
        if weights.shape != (tree.n_leaves,):
            raise ValueError(
                f"weights shape {weights.shape} != (n_leaves,) "
                f"({tree.n_leaves},)"
            )
    n = tree.n_leaves
    heights = tree.height
    n_merge = n - 1
    ref_merge = max(int(round(0.05 * n_merge)), 1)
    ref_height = float(heights[ref_merge - 1])
    max_height = float(heights[-1])
    if cut_height is None:
        cut_height = 0.99 * (max_height - ref_height) + ref_height
    cut_height = min(cut_height, max_height)

    max_core_scatter = DEEP_SPLIT_CORE_SCATTER[int(deep_split)]
    min_gap = (1.0 - max_core_scatter) * 3.0 / 4.0
    max_abs_core_scatter = ref_height + max_core_scatter * (cut_height - ref_height)
    min_abs_gap = min_gap * (cut_height - ref_height)

    embedding = np.ascontiguousarray(embedding, np.float64)
    branch_of_row: dict = {}
    clusters: List[List[int]] = []

    def resolve(code: int, h: float) -> _Branch:
        """Child code -> branch (singletons become 1-element branches)."""
        if code < 0:
            return _Branch([-code - 1], [h])
        return branch_of_row.pop(code - 1)

    for row in range(n_merge):
        h = float(heights[row])
        if h > cut_height:
            continue  # children stay roots
        a, b = int(tree.merge[row, 0]), int(tree.merge[row, 1])
        # Missing child => child merge was above cutHeight (can't happen with
        # monotone heights) or already consumed; guard anyway.
        ba = resolve(a, h)
        bb = resolve(b, h)
        if ba.composite or bb.composite:
            for other in (ba, bb):
                if not other.composite and _qualifies(
                    other, h, embedding, min_cluster_size,
                    max_abs_core_scatter, min_abs_gap, weights,
                ):
                    clusters.append(list(other.singletons))
            branch_of_row[row] = _Branch([], [], composite=True)
            continue
        if len(ba.singletons) > 1 and len(bb.singletons) > 1:
            qa = _qualifies(ba, h, embedding, min_cluster_size,
                            max_abs_core_scatter, min_abs_gap, weights)
            qb = _qualifies(bb, h, embedding, min_cluster_size,
                            max_abs_core_scatter, min_abs_gap, weights)
            if qa and qb:
                clusters.append(list(ba.singletons))
                clusters.append(list(bb.singletons))
                branch_of_row[row] = _Branch([], [], composite=True)
                continue
        branch_of_row[row] = _merge_sorted(ba, bb)

    # Roots remaining below/at cutHeight: evaluate at cutHeight.
    for branch in branch_of_row.values():
        if branch.composite:
            continue
        if _qualifies(branch, cut_height, embedding, min_cluster_size,
                      max_abs_core_scatter, min_abs_gap, weights):
            clusters.append(list(branch.singletons))

    labels = np.zeros(n, np.int64)
    if weights is None:
        clusters.sort(key=len, reverse=True)
    else:
        clusters.sort(key=lambda m: float(weights[np.asarray(m)].sum()),
                      reverse=True)
    for cid, members in enumerate(clusters, start=1):
        labels[np.asarray(members)] = cid

    if pam_stage and clusters:
        labels = _pam_assign(embedding, labels,
                             max_pam_dist if max_pam_dist is not None else cut_height,
                             weights=weights)
    return labels


def _pam_assign(embedding: np.ndarray, labels: np.ndarray, max_dist: float,
                weights: Optional[np.ndarray] = None) -> np.ndarray:
    """Assign unlabeled objects to the cluster with smallest mean distance,
    when that distance is within ``max_dist``. With ``weights`` (landmark
    trees) the mean is occupancy-weighted — each candidate cluster's
    distance is the mean over its CELLS, each priced at its landmark, so
    the cell-unit cut semantics extend through the PAM stage."""
    un = np.nonzero(labels == 0)[0]
    if un.size == 0:
        return labels
    k = labels.max()
    onehot = np.zeros((embedding.shape[0], k), np.float64)
    w = (np.ones(embedding.shape[0], np.float64)
         if weights is None else weights)
    for c in range(1, k + 1):
        m = labels == c
        onehot[m, c - 1] = w[m]
    counts = onehot.sum(axis=0)
    pts = embedding[un]
    sq = np.sum(pts * pts, axis=1)[:, None]
    sq_all = np.sum(embedding * embedding, axis=1)[None, :]
    d = np.sqrt(np.maximum(sq + sq_all - 2.0 * pts @ embedding.T, 0.0))
    mean_d = (d @ onehot) / np.maximum(counts, 1.0)
    best = np.argmin(mean_d, axis=1)
    best_d = mean_d[np.arange(un.size), best]
    out = labels.copy()
    assign = best_d <= max_dist
    out[un[assign]] = best[assign] + 1
    return out
