"""DE feature gates, computed for all cluster pairs from per-cluster aggregates.

TPU-first design: instead of slicing cells per pair (reference:
R/reclusterDEConsensusFast.R:229-291 recomputes pct/logFC per pair per worker),
we reduce the (genes × cells) matrix against a (cells × clusters) one-hot once
— three MXU matmuls — and derive every pair's gates from the (genes × clusters)
aggregates. Gates are masks, never ragged selections.

Two gate conventions exist in the reference and both are supported:
  * fast path (Seurat): pct filter, Seurat log-mean logFC, count-space mean
    gate, |logFC| threshold (R/reclusterDEConsensusFast.R:229-291).
  * slow path: logFC = difference of log-means, mixed-space mean gate
    (R/reclusterDEConsensus.R:105,109-113; quirk §2d-3).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from scconsensus_tpu.obs.graphs import instrument as _passport

__all__ = [
    "ClusterAggregates", "compute_aggregates", "compute_aggregates_cid",
    "pair_gates_fast", "pair_gates_slow",
]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ClusterAggregates:
    """Per-cluster sufficient statistics, all (G, K) except counts (K,).

    These four matmuls carry every moment the fast-path tests consume:
    gates (pct/logFC), Welch t (mean/var), and the bimod zero-inflated-normal
    LRT (positive-fraction, positive mean/var) — so the per-pair statistical
    tests never touch per-cell data again (SURVEY.md §7 stage 2)."""

    sum_log: jnp.ndarray      # Σ x (x = log-normalized input)
    sum_expm1: jnp.ndarray    # Σ expm1(x)
    sum_sq: jnp.ndarray       # Σ x²
    nnz: jnp.ndarray          # Σ [x > 0]
    counts: jnp.ndarray       # cells per cluster (K,)

    @property
    def mean_log(self) -> jnp.ndarray:
        return self.sum_log / jnp.maximum(self.counts, 1.0)[None, :]

    @property
    def mean_expm1(self) -> jnp.ndarray:
        return self.sum_expm1 / jnp.maximum(self.counts, 1.0)[None, :]

    @property
    def pct(self) -> jnp.ndarray:
        """Percent of cells expressing, Seurat's pct.1/pct.2 scale (0-100)."""
        return 100.0 * self.nnz / jnp.maximum(self.counts, 1.0)[None, :]


@jax.jit
def compute_aggregates(data: jnp.ndarray, onehot: jnp.ndarray) -> ClusterAggregates:
    """data: (G, N) log-normalized; onehot: (N, K) float cluster membership.

    HIGHEST precision: these sums feed Welch/bimod variances via
    ss − n·mean², where TPU bf16 matmul passes would wreck the cancellation
    (and diverge from the exact-fp32 sparse host path)."""
    hi = jax.lax.Precision.HIGHEST
    counts = jnp.sum(onehot, axis=0)
    sum_log = jnp.dot(data, onehot, precision=hi)
    sum_expm1 = jnp.dot(jnp.expm1(data), onehot, precision=hi)
    sum_sq = jnp.dot(data * data, onehot, precision=hi)
    nnz = jnp.dot((data > 0).astype(data.dtype), onehot, precision=hi)
    return ClusterAggregates(sum_log, sum_expm1, sum_sq, nnz, counts)


@partial(jax.jit, static_argnames=("n_clusters",))
def compute_aggregates_cid(
    data: jnp.ndarray, cid: jnp.ndarray, n_clusters: int
) -> ClusterAggregates:
    """``compute_aggregates`` straight from the (N,) per-cell cluster-id
    vector (−1 = excluded) — no host (N, K) one-hot ever built or uploaded.

    On CPU each statistic is a segment sum over cells (scatter-add at the
    cell's cluster id): O(G·N) work independent of K, where the one-hot
    matmul form prices O(G·N·K) — at the tm100k shape (G = 12k, N = 100k,
    K = 80 refined clusters) that is an 80× flop cut on the stage the r5
    artifact measured at 93.5 s. On TPU the one-hot is built ON DEVICE
    (the K-shaped matmul is MXU work and stays the faster form there) —
    which still folds away the host-side (N, K) rebuild + upload that the
    subsampled test-aggregate path used to pay a second time."""
    K = n_clusters
    hi = jax.lax.Precision.HIGHEST
    if jax.default_backend() == "cpu":
        safe = jnp.where(cid >= 0, cid, K)                  # (N,)
        counts = jnp.zeros((K + 1,), jnp.float32).at[safe].add(1.0)[:K]

        def seg(x: jnp.ndarray) -> jnp.ndarray:             # (G, N) → (G, K)
            z = jnp.zeros((x.shape[0], K + 1), jnp.float32)
            return z.at[:, safe].add(x)[:, :K]

        return ClusterAggregates(
            seg(data), seg(jnp.expm1(data)), seg(data * data),
            seg((data > 0).astype(jnp.float32)), counts,
        )
    onehot = (
        cid[:, None] == jnp.arange(K, dtype=cid.dtype)[None, :]
    ).astype(jnp.float32)                                   # (N, K), device
    counts = jnp.sum(onehot, axis=0)
    return ClusterAggregates(
        jnp.dot(data, onehot, precision=hi),
        jnp.dot(jnp.expm1(data), onehot, precision=hi),
        jnp.dot(data * data, onehot, precision=hi),
        jnp.dot((data > 0).astype(data.dtype), onehot, precision=hi),
        counts,
    )


@partial(
    jax.jit,
    static_argnames=(
        "min_pct", "min_diff_pct", "log_fc_thrs", "mean_exprs_thrs",
        "pseudocount", "only_pos",
    ),
)
def pair_gates_fast(
    agg: ClusterAggregates,
    pair_i: jnp.ndarray,
    pair_j: jnp.ndarray,
    min_pct: float,
    min_diff_pct: float,
    log_fc_thrs: float,
    mean_exprs_thrs: float,
    pseudocount: float = 1.0,
    only_pos: bool = False,
):
    """Seurat-convention gates for a batch of pairs.

    Args: pair_i/pair_j (P,) cluster indices.
    Returns (gate_mask (P, G) bool, log_fc (P, G), pct1, pct2).
    log_fc = log(mean(expm1 x)+pc) − log(mean(expm1 y)+pc)
    (ComputePairWiseDE mean.fxn, R/reclusterDEConsensusFast.R:259-272).
    """
    pct = agg.pct  # (G, K)
    pct1 = pct[:, pair_i].T  # (P, G)
    pct2 = pct[:, pair_j].T
    alpha_min = jnp.maximum(pct1, pct2)
    alpha_diff = alpha_min - jnp.minimum(pct1, pct2)

    me = agg.mean_expm1
    obj1 = jnp.log(me[:, pair_i].T + pseudocount)
    obj2 = jnp.log(me[:, pair_j].T + pseudocount)
    log_fc = obj1 - obj2

    gate = alpha_min > min_pct
    if min_diff_pct > -jnp.inf:
        gate &= alpha_diff > min_diff_pct
    # mean gate: expm1(obj) > thrs (R/reclusterDEConsensusFast.R:274-275)
    gate &= (jnp.expm1(obj1) > mean_exprs_thrs) | (jnp.expm1(obj2) > mean_exprs_thrs)
    if only_pos:
        gate &= log_fc > log_fc_thrs
    else:
        gate &= jnp.abs(log_fc) > log_fc_thrs
    return gate, log_fc, pct1, pct2


@partial(jax.jit, static_argnames=("mixed_spaces",))
def pair_gates_slow(
    agg: ClusterAggregates,
    pair_i: jnp.ndarray,
    pair_j: jnp.ndarray,
    mean_exprs_thrs: float,
    mixed_spaces: bool = True,
):
    """Slow-path mean-expression gate + logFC (difference of log-means).

    ``mixed_spaces=True`` reproduces the reference's literal arithmetic:
    mean-of-log values compared against log(count-space threshold)
    (R/reclusterDEConsensus.R:109-113; quirk §2d-3). ``False`` compares the
    count-space cluster mean against the count-space threshold.

    Returns (mean_gate (P, G) bool, log_fc (P, G)).
    """
    ml = agg.mean_log
    m1 = ml[:, pair_i].T
    m2 = ml[:, pair_j].T
    log_fc = m1 - m2
    if mixed_spaces:
        thr = jnp.log(mean_exprs_thrs)
        gate = (m1 > thr) | (m2 > thr)
    else:
        me = agg.mean_expm1
        gate = (me[:, pair_i].T > mean_exprs_thrs) | (me[:, pair_j].T > mean_exprs_thrs)
    return gate, log_fc


# graph passports (obs.graphs, SCC_GRAPHS): the gate-funnel stage programs
compute_aggregates = _passport("gates.compute_aggregates", compute_aggregates)
compute_aggregates_cid = _passport(
    "gates.compute_aggregates_cid", compute_aggregates_cid
)
pair_gates_fast = _passport("gates.pair_gates_fast", pair_gates_fast)
pair_gates_slow = _passport("gates.pair_gates_slow", pair_gates_slow)
