"""Wilcoxon rank-sum test with R ``wilcox.test`` semantics.

Device path (`wilcoxon_from_ranks`): normal approximation with tie and
continuity correction — the branch R takes whenever a group has ≥50 samples or
any ties exist, i.e. essentially always on scRNA data. Batched over
genes × cluster-pairs; p-values are returned in log-space (float32 underflows
around 1e-38 but the orderings the pipeline needs survive in log-space).

Host path (`wilcoxon_exact_host`): R's exact branch (both n < 50, no ties)
via the Gaussian-binomial counting DP behind ``pwilcox`` — used only for the
rare tiny-cluster case, and for golden tests.

Reference behavior being replaced: per-gene `wilcox.test` calls at
R/reclusterDEConsensus.R:99-100 and R/reclusterDEConsensusFast.R:84-89.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Tuple

import jax.numpy as jnp
import jax.scipy.stats as jstats
import numpy as np

__all__ = [
    "wilcoxon_from_ranks",
    "wilcoxon_pairs_tile",
    "wilcoxon_exact_host",
    "EXACT_N_LIMIT",
]

# R: exact branch iff n.x < 50 && n.y < 50 (and no ties).
EXACT_N_LIMIT = 50


def wilcoxon_from_ranks(
    rank_sum_1: jnp.ndarray,
    tie_sum: jnp.ndarray,
    n1: jnp.ndarray,
    n2: jnp.ndarray,
    continuity: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two-sided normal-approximation p from group-1 rank sums.

    Args are broadcastable arrays: rank_sum_1 = Σ midranks of group 1 in the
    pooled sample; tie_sum = Σ(t³−t); n1/n2 = group sizes.

    Returns (log_p, U) where U is the Mann-Whitney statistic for group 1
    (R's ``STATISTIC``). Degenerate inputs (empty group or zero variance)
    give log_p = NaN, matching R's NaN p-value.
    """
    n1 = n1.astype(jnp.float32)
    n2 = n2.astype(jnp.float32)
    u = rank_sum_1 - n1 * (n1 + 1.0) / 2.0
    z = u - n1 * n2 / 2.0
    if continuity:
        z = z - jnp.sign(z) * 0.5
    n = n1 + n2
    tie_term = tie_sum / jnp.maximum(n * (n - 1.0), 1.0)
    sigma2 = (n1 * n2 / 12.0) * ((n + 1.0) - tie_term)
    sigma = jnp.sqrt(jnp.maximum(sigma2, 0.0))
    zs = z / sigma  # sigma==0 -> ±inf/NaN, handled below
    log_p = jnp.log(2.0) + jstats.norm.logcdf(-jnp.abs(zs))
    log_p = jnp.minimum(log_p, 0.0)  # cap p at 1 (2*cdf(0) = 1)
    bad = (n1 < 1) | (n2 < 1) | (sigma <= 0.0)
    log_p = jnp.where(bad, jnp.nan, log_p)
    return log_p, u


def wilcoxon_pairs_tile(
    data_chunk: "jnp.ndarray",  # (Gc, N) gene-chunk of the expression matrix
    idx: "jnp.ndarray",         # (B, W) gather indices of each pair's cells
    m1: "jnp.ndarray",          # (B, W) group-1 membership among gathered cells
    m2: "jnp.ndarray",
    n1: "jnp.ndarray",          # (B,) group sizes
    n2: "jnp.ndarray",
):
    """Rank-sum test for one (gene-chunk × pair-bucket) tile.

    The single implementation behind the serial engine, the gene-sharded
    path, and the fused step (no collectives inside — safe under shard_map).
    Returns (log_p, u, tie_sum): (B, Gc), (B, Gc), (B, Gc).
    """
    from scconsensus_tpu.ops.ranks import masked_midranks

    vals = jnp.take(data_chunk, idx, axis=1)          # (Gc, B, W)
    vals = jnp.swapaxes(vals, 0, 1)                   # (B, Gc, W)
    pooled = (m1 | m2)[:, None, :]                    # (B, 1, W)
    B, Gc, W = vals.shape
    flat = vals.reshape(B * Gc, W)
    flat_mask = jnp.broadcast_to(pooled, (B, Gc, W)).reshape(B * Gc, W)
    ranks, tie_sum = masked_midranks(flat, flat_mask)
    ranks = ranks.reshape(B, Gc, W)
    tie_sum = tie_sum.reshape(B, Gc)
    rs1 = jnp.sum(jnp.where(m1[:, None, :], ranks, 0.0), axis=-1)  # (B, Gc)
    log_p, u = wilcoxon_from_ranks(rs1, tie_sum, n1[:, None], n2[:, None])
    return log_p, u, tie_sum


@lru_cache(maxsize=512)
def _wilcox_pmf(m: int, n: int) -> np.ndarray:
    """PMF of the Mann-Whitney U distribution for group sizes (m, n):
    coefficients of the Gaussian binomial [m+n choose m]_q, normalized.
    Float64 counts — same rounding regime as R's ``cwilcox`` doubles."""
    size = m * n + 1
    c = np.zeros(size, dtype=np.float64)
    c[0] = 1.0
    for i in range(1, m + 1):
        # multiply by (1 - q^(n+i))
        d = c.copy()
        if n + i < size:
            d[n + i :] -= c[: size - (n + i)]
        # divide by (1 - q^i): running sum with stride i
        for u in range(i, size):
            d[u] += d[u - i]
        c = d
    total = c.sum()
    return c / total


def wilcoxon_exact_host(u_stat: np.ndarray, n1: int, n2: int) -> np.ndarray:
    """Two-sided exact p for U statistics (no ties), R's exact branch:
    p = min(2 * tail, 1) with the smaller tail doubled
    (stats::wilcox.test exact two.sided arithmetic)."""
    pmf = _wilcox_pmf(int(n1), int(n2))
    cdf = np.cumsum(pmf)
    u = np.asarray(u_stat)
    w = np.rint(u).astype(np.int64)
    mid = n1 * n2 / 2.0
    upper = np.clip(w, 1, None)
    # upper tail: P(U >= w) = 1 - cdf[w-1]; lower tail: P(U <= w) = cdf[w]
    p_upper = 1.0 - np.where(w >= 1, cdf[np.clip(w - 1, 0, len(cdf) - 1)], 0.0)
    p_lower = cdf[np.clip(w, 0, len(cdf) - 1)]
    p = np.where(w > mid, p_upper, p_lower)
    del upper
    return np.minimum(2.0 * p, 1.0)
