"""WGCNA-style label→color mapping.

``WGCNA::labels2colors`` (called at R/reclusterDEConsensus.R:261) maps integer
cluster ids onto the canonical WGCNA module-color sequence with 0 → "grey"
(unassigned). The downstream grey-exclusion logic
(R/reclusterDEConsensus.R:48-49) depends on this naming, so the table ships
with the framework (SURVEY.md §2b N7). Beyond the named palette, labels cycle
with a numeric suffix, keeping names unique and never colliding with 'grey'.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

__all__ = ["labels_to_colors", "STANDARD_COLORS"]

# Canonical leading sequence of WGCNA standardColors().
STANDARD_COLORS = [
    "turquoise", "blue", "brown", "yellow", "green", "red", "black", "pink",
    "magenta", "purple", "greenyellow", "tan", "salmon", "cyan",
    "midnightblue", "lightcyan", "grey60", "lightgreen", "lightyellow",
    "royalblue", "darkred", "darkgreen", "darkturquoise", "darkgrey",
    "orange", "darkorange", "white", "skyblue", "saddlebrown", "steelblue",
    "paleturquoise", "violet", "darkolivegreen", "darkmagenta",
    "sienna3", "yellowgreen", "skyblue3", "plum1", "orangered4", "mediumpurple3",
    "lightsteelblue1", "lightcyan1", "ivory", "floralwhite", "darkorange2",
    "brown4", "bisque4", "darkslateblue", "plum2", "thistle2",
]


def labels_to_colors(labels: Sequence[int]) -> np.ndarray:
    """Map integer cluster ids to color names; 0 (and negatives) → 'grey'."""
    lab = np.asarray(labels, dtype=np.int64)
    out = np.empty(lab.shape, dtype=object)
    n_std = len(STANDARD_COLORS)
    for i, v in enumerate(lab.ravel()):
        if v <= 0:
            out.ravel()[i] = "grey"
        else:
            idx = int(v) - 1
            cycle, pos = divmod(idx, n_std)
            name = STANDARD_COLORS[pos]
            out.ravel()[i] = name if cycle == 0 else f"{name}.{cycle}"
    return out.astype(str)
