"""Batched midrank computation with tie statistics.

TPU-native replacement for the reference's per-gene interpreted-R ranking
inside ``wilcox.test`` loops (R/reclusterDEConsensus.R:90-106,
R/reclusterDEConsensusFast.R:78-91 — ≈3.5M individual calls on 26k PBMC).
Here one `vmap`'d sort ranks a whole (genes × cells) block at once.

Ties are resolved to midranks exactly as R's ``rank()``: every member of a
tie run gets the average of the ranks the run spans. Tie sizes also feed the
variance correction Σ(t³−t) used by the normal-approximation Wilcoxon test.

Invalid (padded) entries are sorted to the end via +inf and excluded from the
tie statistics, so ragged cluster pairs batch with static shapes.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["masked_midranks", "rank_sum_groups"]

_BIG = jnp.inf


def _midranks_1d(values: jnp.ndarray, mask: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Midranks of the valid entries of one row.

    Returns (ranks, tie_sum): ranks[i] is the 1-based midrank of values[i]
    among valid entries (0 where invalid); tie_sum = Σ over tie runs of
    (t³ − t), the R ``NTIES`` correction term.
    """
    n = values.shape[0]
    v = jnp.where(mask, values, _BIG)
    order = jnp.argsort(v)
    sv = v[order]
    pos = jnp.arange(n)
    # First/last occurrence of each sorted value -> tie-run extent.
    first = jnp.searchsorted(sv, sv, side="left")
    last = jnp.searchsorted(sv, sv, side="right") - 1
    midrank_sorted = 0.5 * (first + last).astype(jnp.float32) + 1.0
    valid_sorted = mask[order]
    # Σ(t³−t) = Σ_elements (t²−1), t = element's run size; padded runs excluded.
    t = (last - first + 1).astype(jnp.float32)
    tie_sum = jnp.sum(jnp.where(valid_sorted, t * t - 1.0, 0.0))
    ranks = jnp.zeros(n, jnp.float32).at[order].set(
        jnp.where(valid_sorted, midrank_sorted, 0.0)
    )
    return ranks, tie_sum


# (B, n) batched over rows.
masked_midranks = jax.vmap(_midranks_1d, in_axes=(0, 0), out_axes=(0, 0))


def rank_sum_groups(
    values: jnp.ndarray, group1_mask: jnp.ndarray, group2_mask: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rank-sum of group 1 within the union of both groups, batched over rows.

    Args:
      values: (B, n) data rows (e.g. genes × pair-cells).
      group1_mask / group2_mask: (B, n) or (n,) boolean membership; disjoint.

    Returns:
      (rank_sum_1, tie_sum): (B,) each. rank_sum_1 is Σ of midranks of group-1
      entries among the pooled valid entries — R's ``sum(r[seq_along(x)])``.
    """
    if group1_mask.ndim == 1:
        group1_mask = jnp.broadcast_to(group1_mask, values.shape)
    if group2_mask.ndim == 1:
        group2_mask = jnp.broadcast_to(group2_mask, values.shape)
    pooled = group1_mask | group2_mask
    ranks, tie_sum = masked_midranks(values, pooled)
    rs1 = jnp.sum(jnp.where(group1_mask, ranks, 0.0), axis=-1)
    return rs1, tie_sum
