"""Cell–cell distances as sharded/blocked matmuls.

Replaces ``stats::dist`` (euclidean, R/reclusterDEConsensus.R:236) and the
commented-out Pearson alternative (:238-239) that BASELINE.json's north star
names. The N×N matrix is never required in one piece: consumers (silhouette,
tree-cut core scatter, linkage argmins) stream row-blocks, the ring pattern
that scales across ICI for large N (SURVEY.md §5.7).
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scconsensus_tpu.obs.graphs import instrument as _passport

__all__ = [
    "euclidean_distance_matrix",
    "pearson_distance_matrix",
    "distance_row_blocks",
    "distance_tile",
]


def distance_tile(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(Na, Nb) euclidean distance tile — the shared kernel behind the ring
    engine and the fused step (one MXU matmul + elementwise)."""
    return jnp.sqrt(_sq_dists_raw(a, b))


def _sq_dists_raw(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a2 = jnp.sum(a * a, axis=1, keepdims=True)
    b2 = jnp.sum(b * b, axis=1, keepdims=True)
    return jnp.maximum(a2 + b2.T - 2.0 * (a @ b.T), 0.0)


# graph passport (obs.graphs, SCC_GRAPHS): the distance-stream tile kernel
_sq_dists = _passport("distance.sq_dists", jax.jit(_sq_dists_raw))


def euclidean_distance_matrix(x: jnp.ndarray) -> jnp.ndarray:
    """Full (N, N) euclidean distance matrix (use only when N² fits in HBM;
    26k cells ≈ 2.7 GB fp32 — fine on one v5e core)."""
    d = jnp.sqrt(_sq_dists(x, x))
    # exact zero diagonal despite fp cancellation
    return d * (1.0 - jnp.eye(x.shape[0], dtype=x.dtype))


@jax.jit
def pearson_distance_matrix(cols: jnp.ndarray) -> jnp.ndarray:
    """1 − Pearson correlation between columns of ``cols`` (genes × cells) —
    the reference's commented-out alternative distance
    (R/reclusterDEConsensus.R:238-239), kept as a first-class option."""
    x = cols - jnp.mean(cols, axis=0, keepdims=True)
    norm = jnp.sqrt(jnp.sum(x * x, axis=0, keepdims=True))
    xn = x / jnp.maximum(norm, 1e-12)
    return 1.0 - xn.T @ xn


pearson_distance_matrix = _passport(
    "distance.pearson_distance_matrix", pearson_distance_matrix
)


def distance_row_blocks(
    x: np.ndarray, block: int = 4096
) -> Iterator[Tuple[int, int, np.ndarray]]:
    """Stream (start, stop, D[start:stop, :]) euclidean row-blocks of the
    distance matrix without materializing N×N on host at once."""
    from scconsensus_tpu.obs.residency import boundary

    with boundary("silhouette_slab_fetch"):
        jx = jnp.asarray(x)
    n = x.shape[0]
    for s in range(0, n, block):
        e = min(s + block, n)
        with boundary("silhouette_slab_fetch"):
            # declared crossing (TODO(item-2)): host consumers stream the
            # slab today; the device-resident graph keeps the reduction on
            # device
            d = np.array(jnp.sqrt(_sq_dists(jx[s:e], jx)))
        d[np.arange(e - s), np.arange(s, e)] = 0.0  # exact zero self-distance
        yield s, e, d
