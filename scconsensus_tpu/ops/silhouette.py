"""Silhouette widths over blocked distance tiles.

The reference computes per-deepSplit mean silhouette at O(N²) host cost and
then discards it (R/reclusterDEConsensusFast.R:415-433; quirk §2d-6). Here it
is a device reduction over distance row-blocks — the N×N matrix is never
materialized — and the pipeline *returns* it.

Semantics match ``cluster::silhouette``: a(i) = mean distance to own cluster's
other members; b(i) = min over other clusters of mean distance; s(i) =
(b−a)/max(a,b); singleton clusters get s = 0. The reported scalar is the mean
of per-cluster average widths (the reference's ``clus.avg.widths`` mean).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["silhouette_widths", "mean_cluster_silhouette"]


@jax.jit
def _block_widths(x_block, x_all, onehot, counts, own):
    """Silhouette widths for a row-block.

    x_block: (B, d); x_all: (N, d); onehot: (N, K); counts: (K,);
    own: (B,) cluster index of each block row.
    """
    a2 = jnp.sum(x_block * x_block, axis=1, keepdims=True)
    b2 = jnp.sum(x_all * x_all, axis=1, keepdims=True)
    d = jnp.sqrt(jnp.maximum(a2 + b2.T - 2.0 * (x_block @ x_all.T), 0.0))  # (B, N)
    sums = d @ onehot  # (B, K) summed distance to each cluster
    k = onehot.shape[1]
    own_oh = jax.nn.one_hot(own, k, dtype=x_block.dtype)  # (B, K)
    n_own = jnp.sum(own_oh * counts[None, :], axis=1)  # (B,)
    sum_own = jnp.sum(own_oh * sums, axis=1)
    a = sum_own / jnp.maximum(n_own - 1.0, 1.0)  # d(i,i)=0 excluded
    mean_other = sums / jnp.maximum(counts[None, :], 1.0)
    mean_other = jnp.where(own_oh > 0, jnp.inf, mean_other)
    b = jnp.min(mean_other, axis=1)
    s = (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30)
    s = jnp.where(n_own <= 1.0, 0.0, s)  # singleton clusters: s = 0
    return s


def silhouette_widths(
    x: np.ndarray, labels: np.ndarray, block: int = 4096
) -> np.ndarray:
    """Per-cell silhouette widths from the embedding (N, d) and integer labels.
    Cells with label < 0 are excluded (width NaN)."""
    labels = np.asarray(labels)
    valid = labels >= 0
    uniq, inv = np.unique(labels[valid], return_inverse=True)
    k = uniq.size
    n = x.shape[0]
    out = np.full(n, np.nan, np.float32)
    if k < 2:
        return out
    xv = np.ascontiguousarray(x[valid], np.float32)
    onehot = np.zeros((xv.shape[0], k), np.float32)
    onehot[np.arange(xv.shape[0]), inv] = 1.0
    counts = onehot.sum(axis=0)
    jx = jnp.asarray(xv)
    joh = jnp.asarray(onehot)
    jc = jnp.asarray(counts)
    widths = np.empty(xv.shape[0], np.float32)
    for s in range(0, xv.shape[0], block):
        e = min(s + block, xv.shape[0])
        widths[s:e] = np.asarray(
            _block_widths(jx[s:e], jx, joh, jc, jnp.asarray(inv[s:e]))
        )
    out[valid] = widths
    return out


def mean_cluster_silhouette(
    x: np.ndarray, labels: np.ndarray, block: int = 4096
) -> Tuple[float, Dict[int, float]]:
    """Mean of per-cluster average widths (reference's reported SI,
    R/reclusterDEConsensusFast.R:433) plus the per-cluster breakdown."""
    w = silhouette_widths(x, labels, block)
    labels = np.asarray(labels)
    per: Dict[int, float] = {}
    for u in np.unique(labels[labels >= 0]):
        per[int(u)] = float(np.nanmean(w[labels == u]))
    if not per:
        return float("nan"), per
    return float(np.mean(list(per.values()))), per
