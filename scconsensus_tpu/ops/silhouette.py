"""Silhouette widths from per-cluster distance sums.

The reference computes per-deepSplit mean silhouette at O(N²) host cost and
then discards it (R/reclusterDEConsensusFast.R:415-433; quirk §2d-6). Here it
is a device reduction — the N×N matrix is never materialized — and the
pipeline *returns* it.

The sufficient statistic is S (N, K) = Σ_{j∈cluster k} d(i, j), produced by
one of three interchangeable engines: the fused Pallas kernel (TPU), blocked
XLA matmuls, or the mesh-sharded ring (parallel.ring). The width arithmetic
(`widths_from_cluster_sums`) is shared by all three.

Semantics match ``cluster::silhouette``: a(i) = mean distance to own
cluster's other members; b(i) = min over other clusters of mean distance;
s(i) = (b−a)/max(a,b); singleton clusters get s = 0. The reported scalar is
the mean of per-cluster average widths (the reference's ``clus.avg.widths``).
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

__all__ = [
    "silhouette_widths",
    "mean_cluster_silhouette",
    "multi_cut_silhouette",
    "pooled_multi_cut_silhouette",
    "pooled_mean_cluster_silhouette",
    "widths_from_cluster_sums",
]


def widths_from_cluster_sums(
    sums: np.ndarray, counts: np.ndarray, own: np.ndarray
) -> np.ndarray:
    """Per-point silhouette widths from S (N, K), cluster sizes (K,), and
    each point's own-cluster index (N,). Self-distance is zero, so the
    own-cluster mean divides by (n_own − 1)."""
    n = sums.shape[0]
    idx = np.arange(n)
    sum_own = sums[idx, own]
    n_own = counts[own]
    a = sum_own / np.maximum(n_own - 1.0, 1.0)
    mean_other = sums / np.maximum(counts[None, :], 1.0)
    mean_other[idx, own] = np.inf
    b = mean_other.min(axis=1)
    s = (b - a) / np.maximum(np.maximum(a, b), 1e-30)
    return np.where(n_own <= 1.0, 0.0, s).astype(np.float32)


def silhouette_widths(
    x: np.ndarray,
    labels: np.ndarray,
    block: int = 4096,
    backend: str = "auto",
) -> np.ndarray:
    """Per-cell silhouette widths from the embedding (N, d) and integer
    labels. Cells with label < 0 are excluded (width NaN).

    ``backend`` selects the distance-sums engine (see
    ops.pallas_kernels.distance_cluster_sums): 'auto' fuses on TPU via
    Pallas and falls back to blocked XLA elsewhere.
    """
    from scconsensus_tpu.ops.pallas_kernels import distance_cluster_sums

    labels = np.asarray(labels)
    valid = labels >= 0
    uniq, inv = np.unique(labels[valid], return_inverse=True)
    k = uniq.size
    n = x.shape[0]
    out = np.full(n, np.nan, np.float32)
    if k < 2:
        return out
    xv = np.ascontiguousarray(x[valid], np.float32)
    onehot = np.zeros((xv.shape[0], k), np.float32)
    onehot[np.arange(xv.shape[0]), inv] = 1.0
    sums = distance_cluster_sums(xv, onehot, backend=backend, block=block)
    counts = onehot.sum(axis=0)
    out[valid] = widths_from_cluster_sums(sums, counts, inv)
    return out


def multi_cut_silhouette(
    x: np.ndarray,
    labels_list,
    block: int = 4096,
    backend: str = "auto",
) -> list:
    """``mean_cluster_silhouette`` for several labelings of the SAME points
    in one distance pass.

    The pipeline scores every deepSplit cut against one embedding
    (R/reclusterDEConsensusFast.R:415-433 recomputes the O(N²) distances per
    cut); here the per-cut one-hots concatenate along the cluster axis, so
    the N² distance tiles stream through HBM once for all cuts. Cells with
    label < 0 in a cut simply have a zero one-hot row there — rows are
    shared, validity is per cut. Returns [(mean_si, per_cluster_dict), …].
    """
    from scconsensus_tpu.ops.pallas_kernels import distance_cluster_sums

    n = x.shape[0]
    cuts = []
    blocks = []
    for labels in labels_list:
        labels = np.asarray(labels)
        valid = labels >= 0
        uniq, inv = np.unique(labels[valid], return_inverse=True)
        onehot = np.zeros((n, uniq.size), np.float32)
        onehot[np.nonzero(valid)[0], inv] = 1.0
        cuts.append((labels, valid, uniq, inv))
        blocks.append(onehot)
    onehot_cat = np.concatenate(blocks, axis=1)
    sums_all = distance_cluster_sums(
        np.ascontiguousarray(x, np.float32), onehot_cat,
        backend=backend, block=block,
    )
    out = []
    c0 = 0
    for (labels, valid, uniq, inv), onehot in zip(cuts, blocks):
        k = uniq.size
        sums = sums_all[valid, c0 : c0 + k]
        c0 += k
        w = np.full(n, np.nan, np.float32)
        if k >= 2:
            counts = onehot.sum(axis=0)
            w[valid] = widths_from_cluster_sums(sums, counts, inv)
        out.append(_aggregate_widths(w, labels))
    return out


def pooled_multi_cut_silhouette(
    x: np.ndarray,
    labels_list,
    n_centroids: int = 2048,
    seed: int = 0,
    block: int = 65536,
    centroids: np.ndarray = None,
    assign: np.ndarray = None,
    sample: int = None,
) -> list:
    """Pooled silhouette estimator — O(N·m) instead of O(N²).

    Every cluster's distance sum S(i, k) = Σ_{j∈k} d(i, j) is estimated by
    collapsing the j side onto m k-means pool centroids (ops.pooling):

        S(i, k) ≈ Σ_p count[p, k] · d(x_i, c_p)  −  d(x_i, c_{p(i)})·[k=own]

    i.e. each candidate neighbor j is priced at its pool centroid; the own-
    cluster sum drops one self term (the exact formulation excludes
    d(i, i) = 0, so i's own pooled representation must not be counted).
    The i side is exact — every evaluated cell uses its true coordinates —
    so the only error is within-pool spread on the j side, which shrinks as
    m grows (Secuer's anchor argument, PAPERS.md; the estimator-vs-exact
    error is pinned by tests/test_scale_pooled.py at small N).

    All cuts share the one (N, m) distance stream (the pooled analog of
    ``multi_cut_silhouette``); ``centroids``/``assign`` reuse the tree
    stage's existing pool when the pipeline already built one — the 1M
    path pays ZERO extra k-means. ``sample`` > 0 evaluates widths on a
    seeded row subset (per-cluster means stay unbiased; cluster sizes and
    count tables always use the full population). Returns
    [(mean_si, per_cluster_dict), …] like ``multi_cut_silhouette``.
    """
    from scconsensus_tpu.ops.pooling import kmeans_pool

    x = np.ascontiguousarray(x, np.float32)
    n = x.shape[0]
    if centroids is None or assign is None:
        centroids, assign = kmeans_pool(x, n_centroids, seed=seed)
    centroids = np.asarray(centroids, np.float32)
    assign = np.asarray(assign)
    m = centroids.shape[0]

    if sample is not None and sample < n:
        rng = np.random.default_rng(seed)
        eval_idx = np.sort(rng.choice(n, size=int(sample), replace=False))
    else:
        eval_idx = np.arange(n)

    # per-cut membership tables from the FULL population
    cuts = []
    for labels in labels_list:
        labels = np.asarray(labels)
        valid = labels >= 0
        uniq, inv = np.unique(labels[valid], return_inverse=True)
        k = uniq.size
        cm = np.zeros((m, max(k, 1)), np.float32)     # count[p, cluster]
        np.add.at(cm, (assign[valid], inv), 1.0)
        counts = cm.sum(axis=0)                        # (k,) full sizes
        own = np.full(n, -1, np.int64)
        own[valid] = inv
        cuts.append((labels, k, cm, counts, own,
                     np.full(n, np.nan, np.float32)))

    c2 = np.sum(centroids * centroids, axis=1)[None, :]
    for b0 in range(0, eval_idx.size, block):
        rows = eval_idx[b0 : b0 + block]
        xb = x[rows]
        d2 = (
            np.sum(xb * xb, axis=1)[:, None]
            - 2.0 * xb @ centroids.T
            + c2
        )
        np.maximum(d2, 0.0, out=d2)
        d = np.sqrt(d2, out=d2)                        # (b, m)
        d_self = d[np.arange(rows.size), assign[rows]]
        for labels, k, cm, counts, own, w in cuts:
            if k < 2:
                continue
            ob = own[rows]
            ok = ob >= 0
            sums = d @ cm                              # (b, k)
            sums[np.nonzero(ok)[0], ob[ok]] -= d_self[ok]
            wb = widths_from_cluster_sums(
                sums[ok], counts, ob[ok]
            )
            w[rows[ok]] = wb
    return [
        _aggregate_widths(w, labels) for labels, _, _, _, _, w in cuts
    ]


def pooled_mean_cluster_silhouette(
    x: np.ndarray, labels: np.ndarray, n_centroids: int = 2048,
    seed: int = 0, **kw,
) -> Tuple[float, Dict[int, float]]:
    """Single-cut form of ``pooled_multi_cut_silhouette`` (same aggregation
    convention as ``mean_cluster_silhouette``)."""
    return pooled_multi_cut_silhouette(
        x, [np.asarray(labels)], n_centroids=n_centroids, seed=seed, **kw
    )[0]


def _aggregate_widths(w: np.ndarray, labels: np.ndarray
                      ) -> Tuple[float, Dict[int, float]]:
    """Per-cluster mean widths + mean-of-means (the reference's reported SI)
    — shared by the single-cut, multi-cut, and mesh paths so the aggregation
    convention cannot diverge between them."""
    per: Dict[int, float] = {}
    for u in np.unique(labels[labels >= 0]):
        wu = w[labels == u]
        if not np.any(np.isfinite(wu)):
            # row-sampled estimator: a cluster none of whose cells were
            # evaluated has no width estimate — leaving it out reports the
            # mean over covered clusters instead of NaN-poisoning it
            continue
        per[int(u)] = float(np.nanmean(wu))
    if not per:
        return float("nan"), per
    return float(np.mean(list(per.values()))), per


def mean_cluster_silhouette(
    x: np.ndarray, labels: np.ndarray, block: int = 4096,
    backend: str = "auto", mesh=None,
) -> Tuple[float, Dict[int, float]]:
    """Mean of per-cluster average widths (reference's reported SI,
    R/reclusterDEConsensusFast.R:433) plus the per-cluster breakdown.

    ``mesh``: optional device mesh — widths come from the ring engine
    (parallel.ring), each device holding 1/n_shards of the distance work."""
    if mesh is not None:
        from scconsensus_tpu.parallel.ring import sharded_silhouette_widths

        w = sharded_silhouette_widths(x, labels, mesh)
    else:
        w = silhouette_widths(x, labels, block, backend=backend)
    return _aggregate_widths(w, np.asarray(labels))
