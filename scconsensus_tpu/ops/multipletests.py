"""Benjamini–Hochberg adjustment, batched and mask-aware.

Matches R ``p.adjust(method="BH")`` including the explicit-``n`` form the
reference uses (``n = nrow(cellDatai)``, R/reclusterDEConsensus.R:117-121)
and the fast path's adjust-over-survivors form
(R/reclusterDEConsensusFast.R:347-350) via ``bh_adjust_masked``.

Computed in log-space so p-values far below float32's subnormal range keep
their ordering on device.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["bh_adjust", "bh_adjust_masked"]


def _bh_1d(logp: jnp.ndarray, mask: jnp.ndarray, n_override: Optional[jnp.ndarray]):
    m = logp.shape[0]
    big = jnp.float32(jnp.inf)
    lp = jnp.where(mask, logp, big)
    order = jnp.argsort(lp)  # ascending p
    lp_sorted = lp[order]
    n_valid = jnp.sum(mask)
    n = n_valid if n_override is None else n_override
    rank = jnp.arange(1, m + 1, dtype=jnp.float32)
    adj = lp_sorted + jnp.log(n.astype(jnp.float32)) - jnp.log(rank)
    # Cumulative min from the right (over valid entries; inf padding is inert).
    adj_rev_cummin = jax.lax.cummin(adj[::-1])[::-1]
    adj_rev_cummin = jnp.minimum(adj_rev_cummin, 0.0)  # cap q at 1
    out = jnp.full(m, big).at[order].set(adj_rev_cummin)
    return jnp.where(mask, out, jnp.nan)


def bh_adjust(logp: jnp.ndarray, n: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """BH-adjust log p-values along the last axis. ``n`` overrides the
    multiplicity count (R's explicit-n quirk); default = #finite entries.
    Returns log q-values."""
    mask = jnp.isfinite(logp)
    return _bh_vmapped(logp, mask, _broadcast_n(n, logp))


def bh_adjust_masked(
    logp: jnp.ndarray, mask: jnp.ndarray, n: Optional[jnp.ndarray] = None
) -> jnp.ndarray:
    """BH over only the ``mask``-selected entries (fast-path semantics:
    adjust across surviving features). Masked-out entries return NaN."""
    mask = mask & jnp.isfinite(logp)
    return _bh_vmapped(logp, mask, _broadcast_n(n, logp))


def _broadcast_n(n, logp):
    if n is None:
        return None
    n = jnp.asarray(n)
    if n.ndim == 0 and logp.ndim > 1:
        n = jnp.broadcast_to(n, logp.shape[:-1])
    return n


def _bh_vmapped(logp, mask, n):
    if logp.ndim == 1:
        return _bh_1d(logp, mask, n)
    flat_lp = logp.reshape(-1, logp.shape[-1])
    flat_mask = mask.reshape(-1, logp.shape[-1])
    if n is None:
        out = jax.vmap(lambda a, b: _bh_1d(a, b, None))(flat_lp, flat_mask)
    else:
        out = jax.vmap(_bh_1d)(flat_lp, flat_mask, n.reshape(-1))
    return out.reshape(logp.shape)
