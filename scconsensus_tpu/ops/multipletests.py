"""Benjamini–Hochberg adjustment, batched and mask-aware.

Matches R ``p.adjust(method="BH")`` including the explicit-``n`` form the
reference uses (``n = nrow(cellDatai)``, R/reclusterDEConsensus.R:117-121)
and the fast path's adjust-over-survivors form
(R/reclusterDEConsensusFast.R:347-350) via ``bh_adjust_masked``.

Computed in log-space so p-values far below float32's subnormal range keep
their ordering on device.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["bh_adjust", "bh_adjust_masked"]


def _bh_batch(logp: jnp.ndarray, mask: jnp.ndarray,
              n_override: Optional[jnp.ndarray]):
    """Batched BH over the last axis via two variadic sorts — the sort
    carries an iota so the un-sort is another sort on that key, replacing
    the gather + scatter of the textbook formulation (vmapped
    gathers/scatters lower catastrophically on CPU: 90 s for a
    (276, 3000) adjust; this form is sort-bound on every backend)."""
    m = logp.shape[-1]
    big = jnp.float32(jnp.inf)
    lp = jnp.where(mask, logp, big)
    iota = jnp.broadcast_to(
        jnp.arange(m, dtype=jnp.int32), lp.shape
    )
    lp_sorted, idx_sorted = jax.lax.sort(
        (lp, iota), dimension=lp.ndim - 1, num_keys=1
    )
    n_valid = jnp.sum(mask, axis=-1)
    n = n_valid if n_override is None else n_override
    rank = jnp.arange(1, m + 1, dtype=jnp.float32)
    adj = lp_sorted + jnp.log(n.astype(jnp.float32))[..., None] - jnp.log(rank)
    # Cumulative min from the right (over valid entries; inf padding is inert).
    adj = jax.lax.cummin(adj, axis=lp.ndim - 1, reverse=True)
    adj = jnp.minimum(adj, 0.0)  # cap q at 1
    _, out = jax.lax.sort(
        (idx_sorted, adj), dimension=lp.ndim - 1, num_keys=1
    )
    return jnp.where(mask, out, jnp.nan)


def bh_adjust(logp: jnp.ndarray, n: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """BH-adjust log p-values along the last axis. ``n`` overrides the
    multiplicity count (R's explicit-n quirk); default = #finite entries.
    Returns log q-values."""
    mask = jnp.isfinite(logp)
    return _bh_vmapped(logp, mask, _broadcast_n(n, logp))


def bh_adjust_masked(
    logp: jnp.ndarray, mask: jnp.ndarray, n: Optional[jnp.ndarray] = None
) -> jnp.ndarray:
    """BH over only the ``mask``-selected entries (fast-path semantics:
    adjust across surviving features). Masked-out entries return NaN."""
    mask = mask & jnp.isfinite(logp)
    return _bh_vmapped(logp, mask, _broadcast_n(n, logp))


def _broadcast_n(n, logp):
    if n is None:
        return None
    n = jnp.asarray(n)
    if logp.ndim == 1:
        return n.reshape(())  # scalar or shape-(1,): the row's own n
    if n.ndim == 0:
        n = jnp.broadcast_to(n, logp.shape[:-1])
    return n


def _bh_vmapped(logp, mask, n):
    if logp.ndim == 1:
        return _bh_batch(logp, mask, n)
    flat_lp = logp.reshape(-1, logp.shape[-1])
    flat_mask = mask.reshape(-1, logp.shape[-1])
    out = _bh_batch(flat_lp, flat_mask, None if n is None else n.reshape(-1))
    return out.reshape(logp.shape)
