"""All-pairs Mann-Whitney U from one global sort per gene — no per-pair tiles.

The round-2 engine gathered a (pairs × genes × cells) tile per pair bucket;
at the 26k-cell flagship that is ~56 TB of gather traffic (231+ pairs each
re-reading its ~2-4k cells for every gene) and was measured HBM-bound at
~86 s. This module replaces it with a formulation whose cost is independent
of the number of cluster pairs:

For one gene, sort all N cells once. With C the (K, N) cluster indicator in
sorted order and S its inclusive cumsum along cells, every cell x at sorted
position p knows, for every cluster k:

    L[k, p]  = # cells of k strictly below x   (cumsum at the start of x's
               tie run, broadcast forward across the run),
    E[k, p]  = # cells of k equal to x         (run totals, broadcast
               backward from the run end).

The Mann-Whitney statistic of cluster i vs cluster j is then one
contraction over cells:

    U[i, j] = Σ_p C[i, p] · (L[j, p] + ½ E[j, p])

and the pooled tie correction Σ_runs(t³−t) for pair (i, j) reduces to the
run-moment matrix B[k,l] = Σ_runs r_k² r_l (r = per-run cluster counts):

    tie(i,j) = B[i,i] + B[j,j] + 3·(B[i,j] + B[j,i]) − n_i − n_j,
    B[k,l]   = Σ_p C[k,p] · e(p) · E[l,p],

with e(p) = E[c_p, p] the cell's own-run count (each run's k-cells
contribute r_k·r_k·r_l). Everything the K(K−1)/2 pair tests need therefore
falls out of one sort, one cumsum, a cummax/cummin fill pair, and two MXU
contractions per gene; the p-value itself is
``ops.wilcoxon.wilcoxon_from_ranks`` (R normal-approximation semantics with
tie and continuity correction), so arithmetic cannot drift from the
per-pair formulation it replaces.

TPU mechanics (measured on v5e, round 3): tensors are laid out (genes,
clusters, cells) so the long cell axis rides the 128-lane minor dimension —
the (…, cells, K) layout pads K to 128 lanes and tripled HBM traffic. The
run-start/run-end lookups exploit the monotonicity of cumsum values at run
boundaries: a forward `cummax` of masked start values and a reverse
`cummin` of masked end values replace both `take_along_axis` gathers (a
(Gc, N, K) gather measured ~700 ms/chunk against tens of ms for the scan)
and flag-carrying segmented `associative_scan`s. Per-pair extraction from
the (K, K) statistic matrices is a one-hot contraction, not a gather.

Replaces the per-gene `wilcox.test` loops at R/reclusterDEConsensus.R:90-106
and R/reclusterDEConsensusFast.R:78-91 (≈3.5M interpreted calls at flagship
scale) with O(G·N·K) MXU work.

Counts are exact in float32 (N < 2²⁴); the contractions run at HIGHEST
precision because bf16 mantissas cannot hold rank sums.

Round-6 CPU restructuring (occupancy-probe-driven, PROFILE_r06_wilcox_1m):

  * ``cid`` may now be (Gc, W) — one cluster-id row PER GENE — which is what
    lets the engine feed PRE-COMPACTED windows built straight from CSR
    storage (only a gene's stored entries enter the sort; the 1M-cell
    sparse run previously paid a full-N sort per gene because the window
    ladder required a dense device matrix to measure nnz).
  * On the CPU backend the K²-shaped contractions collapse to O(W·K)
    scatter/gather forms: the one-hot axis of C/Cu is exploited as a
    scatter index (u_mat rows are segment sums over each cluster's cells),
    and the tied-run table einsums — whose cost was STATIC table height ×
    K² regardless of how many runs actually existed, the "table thrash" at
    wide windows — become per-cell gathers of the table rows. TPU keeps
    the MXU einsum forms (measured faster there; scatters are not).
  * Per-pair extraction from the (K, K) statistic matrices is a flat
    gather on CPU (pair_i·K+pair_j) instead of the (P, K²) one-hot
    contraction — the latter is K²·P work, ~1.5e12 flops at the tm100k
    shape (K=80, P=3160, G=12000). TPU keeps the one-hot contraction
    (gathers measured slower there, see _pairs_finish).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from scconsensus_tpu.obs.graphs import instrument as _passport
from scconsensus_tpu.ops.wilcoxon import wilcoxon_from_ranks

__all__ = [
    "allpairs_ranksum_chunk", "allpairs_ranksum_runspace_chunk",
    "ranksum_body", "ranksum_body_runspace", "chunk_genes_for_budget",
    "sort_probe", "RUN_CAP",
]

_HIGHEST = jax.lax.Precision.HIGHEST

# Element budget for the (Gc, K, N) working tensors (~6 live at once).
_ALLPAIRS_ELEM_BUDGET = 320_000_000

# Upper bound on the tied-run table height (a memory guard, not a tuning
# knob). The effective height is pow2(W/2) — the most size-≥2 runs a
# W-wide window can physically hold — so overflow is IMPOSSIBLE for
# windows up to 2·RUN_CAP and the scan-kernel redo path only exists for
# wider-than-128k windows (≥256k cells in one window). A fixed 2048 cap
# was tried first: the 26k flagship fits (p50 = 224 / max ≈ 1100 tied
# runs per gene) but the 100k-cell tm100k config measures thousands of
# tied runs per gene — every gene overflowed and the wasted pass + redo
# made the cold wilcox 3737 s vs the scan kernel's ~3100 (ROUND5_NOTES.md).
# The table is scatter-filled (cost independent of height); the height
# only prices the (Gc, T, K) per-run einsums and their memory.
RUN_CAP = 65536


def chunk_genes_for_budget(n_cells: int, n_clusters: int,
                           budget: int = _ALLPAIRS_ELEM_BUDGET) -> int:
    """Gene-chunk width keeping Gc·N·K under the working-set budget."""
    gc = max(8, budget // max(n_cells * n_clusters, 1))
    return max(8, 1 << (int(gc).bit_length() - 1))  # floor power of two


def _use_cpu_forms() -> bool:
    """Trace-time backend probe selecting the scatter/gather contraction
    forms (CPU) over the MXU einsum/one-hot forms (TPU). Evaluated when a
    kernel first compiles — the backend is fixed for the process, so the
    jit caches stay coherent."""
    return jax.default_backend() == "cpu"


def _cid_rows(chunk: jnp.ndarray, cid: jnp.ndarray) -> jnp.ndarray:
    """Per-gene cluster-id rows: a shared (N,) vector broadcasts across the
    chunk; a pre-compacted (Gc, W) array passes through (each gene's window
    carries its own cells)."""
    if cid.ndim == 2:
        return cid
    return jnp.broadcast_to(cid, chunk.shape)


@jax.jit
def sort_probe(chunk: jnp.ndarray, cid: jnp.ndarray):
    """The kernels' first stage — the variadic value+cluster-id sort — alone.
    The engine's occupancy probe (SCC_WILCOX_PROBE=1) times it separately
    per bucket so sort cost splits out of the contraction attribution."""
    return jax.lax.sort(
        (-chunk, _cid_rows(chunk, cid)), dimension=1, num_keys=1
    )


def ranksum_body(
    chunk: jnp.ndarray,     # (Gc, N) gene rows (padded rows are all-zero)
    cid: jnp.ndarray,       # (N,) or (Gc, N) int32 cluster index, -1 = excluded
    n_of: jnp.ndarray,      # (K,) cluster sizes (int32)
    pair_i: jnp.ndarray,    # (P,) cluster index of group 1 per pair
    pair_j: jnp.ndarray,    # (P,)
    n_clusters: int,
    window: int = 0,
    cpu_forms: bool = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Rank-sum log-p for every (gene, pair) of one gene chunk.

    ``cpu_forms``: None probes the backend (`_use_cpu_forms`); the mesh
    path passes False — the scatter forms' mixed advanced indexing is
    rejected inside shard_map on jax 0.4.x, and a sharded program is the
    MXU-form case by design anyway.

    Returns (log_p, u, tie_sum), each (Gc, P). Excluded cells (cid = -1,
    dropped clusters or subsampled-out cells) occupy sorted positions but
    contribute to no cluster count. Pure local compute (no collectives) —
    safe to shard_map over the gene axis.

    ``window`` > 0 enables the zero-block decomposition for sparse rows
    (expression data is mostly zeros): values sort DESCENDING so the ≤
    ``window`` positive entries land in a prefix window, the (Gc, K, ·)
    scan/contraction machinery runs at the window width instead of N, and
    the giant all-zero tie block enters through closed-form corrections —
    with z_k the per-gene zero count of cluster k and U′ the above-or-tied
    dominance count among window cells,

        U[i,j]  = n_i·n_j − (U′[i,j] + z_i·nnz_j + z_i·z_j/2),
        B[k,l]  = B′[k,l] + z_k²·z_l        (zero run of the tie moments).

    Requires every gene in the chunk to have ≤ ``window`` positive cells
    and no negative values (log-normalized expression); callers bucket
    genes by nnz (see engine._run_wilcox_device). ``window`` may equal (or
    exceed) the chunk width for PRE-COMPACTED input — rows holding only a
    gene's stored CSR entries with a matching (Gc, W) ``cid`` — where every
    absent cell is an implicit zero handled by the same corrections.
    """
    Gc, N = chunk.shape
    K = n_clusters
    sparse_mode = window > 0
    use_cpu = _use_cpu_forms() if cpu_forms is None else bool(cpu_forms)
    w_eff = min(window, N) if sparse_mode else N
    # One variadic sort carries the cluster ids along with the values.
    # Sparse mode sorts the negated values: positives first, zeros last.
    key = -chunk if sparse_mode else chunk
    sv, scid = jax.lax.sort(
        (key, _cid_rows(chunk, cid)), dimension=1, num_keys=1
    )
    if sparse_mode:
        sv = sv[:, :w_eff]
        scid = jnp.where(sv < 0, scid[:, :w_eff], -1)  # window zeros inert
    W = sv.shape[1]
    # (Gc, K, W): cells on the minor (lane) axis.
    C = (scid[:, None, :] == jnp.arange(K, dtype=jnp.int32)[None, :, None]
         ).astype(jnp.float32)
    S = jnp.cumsum(C, axis=-1)                              # inclusive

    new_run = jnp.concatenate(
        [jnp.ones((Gc, 1), bool), sv[:, 1:] != sv[:, :-1]], axis=1
    )[:, None, :]                                           # (Gc, 1, W)
    is_end = jnp.concatenate(
        [new_run[:, :, 1:], jnp.ones((Gc, 1, 1), bool)], axis=2
    )

    # Segmented fills without gathers or flag-carrying scans: the cumsum's
    # run-start (and run-end) values are monotone along the cell axis, so a
    # plain cummax of the start values masked to −1 forward-fills the
    # strictly-below counts, and a reverse cummin of the end values masked
    # to +big backward-fills the through-run totals.
    L = jax.lax.cummax(jnp.where(new_run, S - C, -1.0), axis=2)
    T = jax.lax.cummin(
        jnp.where(is_end, S, jnp.float32(W + 1)), axis=2, reverse=True
    )
    E = T - L                                               # equal counts

    V = 0.5 * (L + T)                                       # L + E/2
    if use_cpu:
        # C is one-hot along k: u_mat[i, :] is the segment sum of V columns
        # over cluster i's cells — an O(W·K) scatter-add instead of the
        # O(W·K²) einsum (row K is the trash row for excluded cells).
        gidx = jnp.arange(Gc, dtype=jnp.int32)[:, None]
        scid_s = jnp.where(scid >= 0, scid, K)              # (Gc, W)
        Vt = jnp.swapaxes(V, 1, 2)                          # (Gc, W, K)
        u_mat = jnp.zeros((Gc, K + 1, K), jnp.float32).at[gidx, scid_s].add(
            Vt
        )[:, :K, :]
        own_eq = jnp.sum(C * E, axis=1)                     # (Gc, W)
        eEt = jnp.swapaxes(E, 1, 2) * own_eq[:, :, None]    # (Gc, W, K)
        B = jnp.zeros((Gc, K + 1, K), jnp.float32).at[gidx, scid_s].add(
            eEt
        )[:, :K, :]
    else:
        u_mat = jnp.einsum("gkn,gln->gkl", C, V, precision=_HIGHEST)
        # Tie correction Σ_runs(t³−t) per pair from one run-moment
        # contraction: B[k,l] = Σ_runs r_k² r_l = Σ_p C[k,p]·e(p)·E[l,p]
        # with e(p) the cell's own-run count (Σ_p C_k e E_l sums
        # r_k·r_k·r_l over each run's k-cells).
        own_eq = jnp.sum(C * E, axis=1)                     # (Gc, W)
        B = jnp.einsum(
            "gkn,gln->gkl", C * own_eq[:, None, :], E, precision=_HIGHEST
        )

    nnz_k = jnp.sum(C, axis=-1)                             # (Gc, K)
    return _pairs_finish(u_mat, B, nnz_k, n_of, pair_i, pair_j, n_clusters,
                         sparse_mode, use_cpu)


def _pairs_finish(u_mat, B, nnz_k, n_of, pair_i, pair_j, n_clusters: int,
                  sparse_mode: bool, use_cpu: bool):
    """Shared tail of the scan and run-space kernels: per-pair extraction
    from the (K, K) statistic matrices, zero-block corrections (sparse
    mode), and the p-value — one implementation so the two formulations
    cannot drift.

    Per-pair extraction is tiny matmuls on TPU (gathers on (Gc, K, K) with
    a 1k-wide pair list measured slower than the one-hot contraction
    there); on CPU it is a flat gather at pair_i·K+pair_j — the one-hot
    form is O(Gc·K²·P) flops, which at K=80 / P=3160 / G=12000 (tm100k)
    is ~1.5e12 flops of pure extraction, dwarfing the statistic itself."""
    Gc = u_mat.shape[0]
    K = n_clusters
    P = pair_i.shape[0]
    b_diag = jnp.einsum("gkk->gk", B)
    if use_cpu:
        flat_ij = pair_i * K + pair_j                       # (P,)
        flat_ji = pair_j * K + pair_i
        u = jnp.take(u_mat.reshape(Gc, K * K), flat_ij, axis=1)
        b_ij = jnp.take(B.reshape(Gc, K * K), flat_ij, axis=1)
        b_ji = jnp.take(B.reshape(Gc, K * K), flat_ji, axis=1)
        d_i = jnp.take(b_diag, pair_i, axis=1)              # (Gc, P)
        d_j = jnp.take(b_diag, pair_j, axis=1)
    else:
        sel_i = jax.nn.one_hot(pair_i, K, dtype=jnp.float32)  # (P, K)
        sel_j = jax.nn.one_hot(pair_j, K, dtype=jnp.float32)
        sel_ij = (sel_i[:, :, None] * sel_j[:, None, :]).reshape(P, K * K)
        sel_ji = (sel_j[:, :, None] * sel_i[:, None, :]).reshape(P, K * K)
        u = jnp.dot(u_mat.reshape(Gc, K * K), sel_ij.T, precision=_HIGHEST)
        b_ij = jnp.dot(B.reshape(Gc, K * K), sel_ij.T, precision=_HIGHEST)
        b_ji = jnp.dot(B.reshape(Gc, K * K), sel_ji.T, precision=_HIGHEST)
        d_i = jnp.dot(b_diag, sel_i.T, precision=_HIGHEST)  # (Gc, P)
        d_j = jnp.dot(b_diag, sel_j.T, precision=_HIGHEST)

    n1 = n_of[pair_i].astype(jnp.float32)                   # (P,)
    n2 = n_of[pair_j].astype(jnp.float32)

    if sparse_mode:
        # Zero-block corrections. nnz/z per (gene, cluster) from the window
        # counts; pair columns via the same extraction as the statistics.
        z_k = jnp.maximum(n_of.astype(jnp.float32)[None, :] - nnz_k, 0.0)
        if use_cpu:
            nnz_j = jnp.take(nnz_k, pair_j, axis=1)         # (Gc, P)
            z_i = jnp.take(z_k, pair_i, axis=1)
            z_j = jnp.take(z_k, pair_j, axis=1)
        else:
            nnz_j = jnp.dot(nnz_k, sel_j.T, precision=_HIGHEST)
            z_i = jnp.dot(z_k, sel_i.T, precision=_HIGHEST)
            z_j = jnp.dot(z_k, sel_j.T, precision=_HIGHEST)
        # u currently holds U′ (descending order = above-or-tied dominance)
        u = n1[None, :] * n2[None, :] - (
            u + z_i * nnz_j + 0.5 * z_i * z_j
        )
        # zero-run tie moments: B_full[k,l] = B′[k,l] + z_k²·z_l
        d_i = d_i + z_i * z_i * z_i
        d_j = d_j + z_j * z_j * z_j
        b_ij = b_ij + z_i * z_i * z_j
        b_ji = b_ji + z_j * z_j * z_i

    tie_sum = d_i + d_j + 3.0 * (b_ij + b_ji) - (n1 + n2)[None, :]
    rs1 = u + n1 * (n1 + 1.0) / 2.0
    log_p, u_out = wilcoxon_from_ranks(rs1, tie_sum, n1, n2)
    return log_p, u_out, tie_sum


def ranksum_body_runspace(
    chunk: jnp.ndarray,
    cid: jnp.ndarray,
    n_of: jnp.ndarray,
    pair_i: jnp.ndarray,
    pair_j: jnp.ndarray,
    n_clusters: int,
    window: int = 0,
    run_cap: int = RUN_CAP,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Tied-run formulation of ``ranksum_body`` — one cumsum, no fills.

    The scan kernel's cummax/cummin fills (~87 of its ~100 ns/element on
    this backend's log-depth scan lowering) exist only to spread run-start
    and run-end values across TIE runs. But a position p in a size-1 run
    satisfies, for every other cluster j,

        L_j(p) + E_j(p)/2 = S_j(p) − C_j(p)

    directly from the inclusive cumsum S — no fill needed — and positions
    in size-≥2 runs can be routed through a tiny per-RUN table instead:
    with R[k, t] = # cells of cluster k in tied run t and
    Lg[j, t] = # j-cells strictly before the run (S − C at the run start),

        U[i, j] = Σ_{p untied} C_i(S_j − C_j) + Σ_t R_i·(Lg_j + R_j/2),
        B[k, l] = diag(# untied positions of k) + Σ_t R_k²·R_l,

    which is exactly the scan kernel's statistic (size-1 runs contribute
    t³−t = 0 to the tie moments). The table height is pow2(W/2) — the
    most size-≥2 runs a window can physically hold — so no data can
    overflow it at any window up to 2·RUN_CAP; the table is filled by
    scatter-add, whose cost is height-independent. (Two capped variants
    were tried and beaten by real data first: a 32-slot TOTAL-run table —
    per-cell normalized values are mostly distinct, every flagship gene
    overflowed — and a 2048-slot tied-run table — the 100k-cell tm100k
    config measures thousands of tied runs per gene. ROUND5_NOTES.md
    tells the story; the overflow redo each time cost more than the
    kernel saved.)

    Cost: one sort + one (Gc, K, W) cumsum (~13 ns/elem) + scatter-built
    per-run tables + per-cell table gathers — the fills are gone, and (on
    CPU, r6) so are the (Gc, T, K)² table einsums: those priced the STATIC
    table height T = pow2(W/2) at K² flops per row whether or not a run
    existed, which is what made wide windows "thrash" (at W = 2¹⁷,
    T = 65536 → ~4e8 flops per gene of mostly-empty table work). The
    replacement gathers each tied cell's table row (O(W·K)) and scatters
    the products by the cell's own cluster — identical arithmetic, cost
    proportional to CELLS, not table capacity. Returns
    (log_p, u, tie_sum, n_tied_runs); entries whose ``n_tied_runs >
    run_cap`` had tail runs merged and are INVALID — the caller re-routes
    those genes to ``ranksum_body`` (engine._run_wilcox_device does).
    Accepts pre-compacted (Gc, W) ``cid`` rows like ``ranksum_body``.
    """
    Gc, N = chunk.shape
    K = n_clusters
    sparse_mode = window > 0
    w_eff = min(window, N) if sparse_mode else N
    key = -chunk if sparse_mode else chunk
    sv, scid = jax.lax.sort(
        (key, _cid_rows(chunk, cid)), dimension=1, num_keys=1
    )
    if sparse_mode:
        sv = sv[:, :w_eff]
        scid = jnp.where(sv < 0, scid[:, :w_eff], -1)
    W = sv.shape[1]

    oh_k = (scid[:, :, None] == jnp.arange(K, dtype=jnp.int32)[None, None, :]
            ).astype(jnp.float32)                           # (Gc, W, K)
    S = jnp.cumsum(oh_k, axis=1)                            # inclusive
    SmC = S - oh_k                                          # strictly-before

    same_prev = jnp.concatenate(
        [jnp.zeros((Gc, 1), bool), sv[:, 1:] == sv[:, :-1]], axis=1
    )
    same_next = jnp.concatenate(
        [same_prev[:, 1:], jnp.zeros((Gc, 1), bool)], axis=1
    )
    tied = same_prev | same_next                            # (Gc, W)
    if sparse_mode:
        # the window's all-zero tail (sv == 0; every such position is
        # already excluded, scid = -1) would otherwise count as one tied
        # run per gene — wasting a table slot and over-reporting n_truns
        # by one at the overflow boundary. Positives are strictly sv < 0
        # here, so this cannot touch a live cell's run membership.
        tied = tied & (sv < 0)
    tstart = tied & ~same_prev
    tid_raw = jnp.cumsum(tstart.astype(jnp.int32), axis=1) - 1
    n_truns = tid_raw[:, -1] + 1                            # tied runs/gene
    # table height: a window of W holds at most W/2 size-≥2 runs, so this
    # never overflows unless W > 2·run_cap
    T = int(min(run_cap, 1 << max(W // 2 - 1, 1).bit_length()))
    tid = jnp.clip(tid_raw, 0, T - 1)
    # Per-run tables by scatter-add (cost ~ one (Gc, W, K) pass, independent
    # of T — a one-hot einsum at T=2048 would materialize a 17 GB tensor).
    gidx = jnp.arange(Gc, dtype=jnp.int32)[:, None]         # (Gc, 1)
    tied_f = tied[:, :, None].astype(jnp.float32)
    R = jnp.zeros((Gc, T, K), jnp.float32).at[gidx, tid].add(
        oh_k * tied_f
    )                                                       # (Gc, T, K)
    # j-cells strictly before each tied run: S−C at the run-start position
    Lg = jnp.zeros((Gc, T, K), jnp.float32).at[gidx, tid].add(
        SmC * tstart[:, :, None].astype(jnp.float32)
    )
    Cu = oh_k * (1.0 - tied_f)                              # untied one-hot
    untied_k = jnp.sum(Cu, axis=1)                          # (Gc, K)
    use_cpu = _use_cpu_forms()
    if use_cpu:
        # O(W·K) contraction: the one-hot k axis of Cu/oh_k becomes a
        # scatter index (row K = trash for tied/excluded cells), and the
        # per-RUN table factors are gathered back per CELL —
        #   Σ_t R[t,i]·X[t,j] = Σ_{tied w, scid_w=i} X[tid_w, j]
        #   Σ_t R[t,k]²·R[t,l] = Σ_{tied w, scid_w=k} R[tid_w,k]·R[tid_w,l]
        # so no arithmetic ever touches an empty table row.
        valid = scid >= 0
        tied_valid = tied & valid
        idx_un = jnp.where(valid & ~tied, scid, K)          # (Gc, W)
        idx_t = jnp.where(tied_valid, scid, K)
        tidb = jnp.broadcast_to(tid[:, :, None], (Gc, W, K))
        Xg = jnp.take_along_axis(Lg + 0.5 * R, tidb, axis=1)  # (Gc, W, K)
        u_mat = (
            jnp.zeros((Gc, K + 1, K), jnp.float32)
            .at[gidx, idx_un].add(SmC)
            .at[gidx, idx_t].add(Xg)
        )[:, :K, :]
        Rg = jnp.take_along_axis(R, tidb, axis=1)           # (Gc, W, K)
        r_own = jnp.sum(Rg * oh_k, axis=2)                  # (Gc, W)
        B = jnp.zeros((Gc, K + 1, K), jnp.float32).at[gidx, idx_t].add(
            Rg * r_own[:, :, None]
        )[:, :K, :]
    else:
        u_mat = (
            jnp.einsum("gwi,gwj->gij", Cu, SmC, precision=_HIGHEST)
            + jnp.einsum("gti,gtj->gij", R, Lg + 0.5 * R, precision=_HIGHEST)
        )
        B = jnp.einsum("gtk,gtl->gkl", R * R, R, precision=_HIGHEST)
    B = B + untied_k[:, :, None] * jnp.eye(K, dtype=jnp.float32)[None]
    nnz_k = S[:, -1, :]
    log_p, u_out, tie_sum = _pairs_finish(
        u_mat, B, nnz_k, n_of, pair_i, pair_j, n_clusters, sparse_mode,
        use_cpu,
    )
    # overflow contract: callers test `> run_cap`, so a gene exceeding the
    # EFFECTIVE table height T (possibly < run_cap at small windows) must
    # read as over the cap too
    n_truns = jnp.where(n_truns > T, jnp.maximum(n_truns, run_cap + 1),
                        n_truns)
    return log_p, u_out, tie_sum, n_truns


# Single-device jitted entries; the sharded form lives in
# parallel.sharded_de.sharded_allpairs_ranksum and shard_maps the scan body.
# Wrapped for graph passports (obs.graphs, SCC_GRAPHS): the wilcox-ladder
# stage programs, incl. the CSR-window runspace form.
allpairs_ranksum_chunk = _passport("wilcox.allpairs_ranksum_chunk", jax.jit(
    ranksum_body, static_argnames=("n_clusters", "window", "cpu_forms")
))
allpairs_ranksum_runspace_chunk = _passport(
    "wilcox.allpairs_ranksum_runspace_chunk", jax.jit(
        ranksum_body_runspace,
        static_argnames=("n_clusters", "window", "run_cap"),
    )
)
sort_probe = _passport("wilcox.sort_probe", sort_probe)
