"""All-pairs Mann-Whitney U from one global sort per gene — no per-pair tiles.

The round-2 engine gathered a (pairs × genes × cells) tile per pair bucket;
at the 26k-cell flagship that is ~56 TB of gather traffic (231+ pairs each
re-reading its ~2-4k cells for every gene) and was measured HBM-bound at
~86 s. This module replaces it with a formulation whose cost is independent
of the number of cluster pairs:

For one gene, sort all N cells once. With C the (K, N) cluster indicator in
sorted order and S its inclusive cumsum along cells, every cell x at sorted
position p knows, for every cluster k:

    L[k, p]  = # cells of k strictly below x   (cumsum at the start of x's
               tie run, broadcast forward across the run),
    E[k, p]  = # cells of k equal to x         (run totals, broadcast
               backward from the run end).

The Mann-Whitney statistic of cluster i vs cluster j is then one
contraction over cells:

    U[i, j] = Σ_p C[i, p] · (L[j, p] + ½ E[j, p])

and the pooled tie correction Σ_runs(t³−t) for pair (i, j) reduces to the
run-moment matrix B[k,l] = Σ_runs r_k² r_l (r = per-run cluster counts):

    tie(i,j) = B[i,i] + B[j,j] + 3·(B[i,j] + B[j,i]) − n_i − n_j,
    B[k,l]   = Σ_p C[k,p] · e(p) · E[l,p],

with e(p) = E[c_p, p] the cell's own-run count (each run's k-cells
contribute r_k·r_k·r_l). Everything the K(K−1)/2 pair tests need therefore
falls out of one sort, one cumsum, a cummax/cummin fill pair, and two MXU
contractions per gene; the p-value itself is
``ops.wilcoxon.wilcoxon_from_ranks`` (R normal-approximation semantics with
tie and continuity correction), so arithmetic cannot drift from the
per-pair formulation it replaces.

TPU mechanics (measured on v5e, round 3): tensors are laid out (genes,
clusters, cells) so the long cell axis rides the 128-lane minor dimension —
the (…, cells, K) layout pads K to 128 lanes and tripled HBM traffic. The
run-start/run-end lookups exploit the monotonicity of cumsum values at run
boundaries: a forward `cummax` of masked start values and a reverse
`cummin` of masked end values replace both `take_along_axis` gathers (a
(Gc, N, K) gather measured ~700 ms/chunk against tens of ms for the scan)
and flag-carrying segmented `associative_scan`s. Per-pair extraction from
the (K, K) statistic matrices is a one-hot contraction, not a gather.

Replaces the per-gene `wilcox.test` loops at R/reclusterDEConsensus.R:90-106
and R/reclusterDEConsensusFast.R:78-91 (≈3.5M interpreted calls at flagship
scale) with O(G·N·K) MXU work.

Counts are exact in float32 (N < 2²⁴); the contractions run at HIGHEST
precision because bf16 mantissas cannot hold rank sums.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from scconsensus_tpu.ops.wilcoxon import wilcoxon_from_ranks

__all__ = ["allpairs_ranksum_chunk", "ranksum_body", "chunk_genes_for_budget"]

_HIGHEST = jax.lax.Precision.HIGHEST

# Element budget for the (Gc, K, N) working tensors (~6 live at once).
_ALLPAIRS_ELEM_BUDGET = 320_000_000


def chunk_genes_for_budget(n_cells: int, n_clusters: int,
                           budget: int = _ALLPAIRS_ELEM_BUDGET) -> int:
    """Gene-chunk width keeping Gc·N·K under the working-set budget."""
    gc = max(8, budget // max(n_cells * n_clusters, 1))
    return max(8, 1 << (int(gc).bit_length() - 1))  # floor power of two


def ranksum_body(
    chunk: jnp.ndarray,     # (Gc, N) gene rows (padded rows are all-zero)
    cid: jnp.ndarray,       # (N,) int32 cluster index, -1 = excluded cell
    n_of: jnp.ndarray,      # (K,) cluster sizes (int32)
    pair_i: jnp.ndarray,    # (P,) cluster index of group 1 per pair
    pair_j: jnp.ndarray,    # (P,)
    n_clusters: int,
    window: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Rank-sum log-p for every (gene, pair) of one gene chunk.

    Returns (log_p, u, tie_sum), each (Gc, P). Excluded cells (cid = -1,
    dropped clusters or subsampled-out cells) occupy sorted positions but
    contribute to no cluster count. Pure local compute (no collectives) —
    safe to shard_map over the gene axis.

    ``window`` > 0 enables the zero-block decomposition for sparse rows
    (expression data is mostly zeros): values sort DESCENDING so the ≤
    ``window`` positive entries land in a prefix window, the (Gc, K, ·)
    scan/contraction machinery runs at the window width instead of N, and
    the giant all-zero tie block enters through closed-form corrections —
    with z_k the per-gene zero count of cluster k and U′ the above-or-tied
    dominance count among window cells,

        U[i,j]  = n_i·n_j − (U′[i,j] + z_i·nnz_j + z_i·z_j/2),
        B[k,l]  = B′[k,l] + z_k²·z_l        (zero run of the tie moments).

    Requires every gene in the chunk to have ≤ ``window`` positive cells
    and no negative values (log-normalized expression); callers bucket
    genes by nnz (see engine._run_wilcox_device).
    """
    Gc, N = chunk.shape
    K = n_clusters
    sparse_mode = 0 < window < N
    # One variadic sort carries the cluster ids along with the values.
    # Sparse mode sorts the negated values: positives first, zeros last.
    key = -chunk if sparse_mode else chunk
    sv, scid = jax.lax.sort(
        (key, jnp.broadcast_to(cid, chunk.shape)), dimension=1, num_keys=1
    )
    if sparse_mode:
        sv = sv[:, :window]
        scid = jnp.where(sv < 0, scid[:, :window], -1)  # window zeros inert
    W = sv.shape[1]
    # (Gc, K, W): cells on the minor (lane) axis.
    C = (scid[:, None, :] == jnp.arange(K, dtype=jnp.int32)[None, :, None]
         ).astype(jnp.float32)
    S = jnp.cumsum(C, axis=-1)                              # inclusive

    new_run = jnp.concatenate(
        [jnp.ones((Gc, 1), bool), sv[:, 1:] != sv[:, :-1]], axis=1
    )[:, None, :]                                           # (Gc, 1, W)
    is_end = jnp.concatenate(
        [new_run[:, :, 1:], jnp.ones((Gc, 1, 1), bool)], axis=2
    )

    # Segmented fills without gathers or flag-carrying scans: the cumsum's
    # run-start (and run-end) values are monotone along the cell axis, so a
    # plain cummax of the start values masked to −1 forward-fills the
    # strictly-below counts, and a reverse cummin of the end values masked
    # to +big backward-fills the through-run totals.
    L = jax.lax.cummax(jnp.where(new_run, S - C, -1.0), axis=2)
    T = jax.lax.cummin(
        jnp.where(is_end, S, jnp.float32(W + 1)), axis=2, reverse=True
    )
    E = T - L                                               # equal counts

    V = 0.5 * (L + T)                                       # L + E/2
    u_mat = jnp.einsum("gkn,gln->gkl", C, V, precision=_HIGHEST)

    # Tie correction Σ_runs(t³−t) per pair from one run-moment contraction:
    # B[k,l] = Σ_runs r_k² r_l = Σ_p C[k,p]·e(p)·E[l,p] with e(p) the cell's
    # own-run count (Σ_p C_k e E_l sums r_k·r_k·r_l over each run's k-cells).
    own_eq = jnp.sum(C * E, axis=1)                         # (Gc, W)
    B = jnp.einsum(
        "gkn,gln->gkl", C * own_eq[:, None, :], E, precision=_HIGHEST
    )

    # Per-pair extraction as tiny matmuls (TPU gathers on (Gc, K, K) with a
    # 1k-wide pair list measured slower than the one-hot contraction).
    P = pair_i.shape[0]
    sel_i = jax.nn.one_hot(pair_i, K, dtype=jnp.float32)    # (P, K)
    sel_j = jax.nn.one_hot(pair_j, K, dtype=jnp.float32)
    sel_ij = (sel_i[:, :, None] * sel_j[:, None, :]).reshape(P, K * K)
    sel_ji = (sel_j[:, :, None] * sel_i[:, None, :]).reshape(P, K * K)
    u = jnp.dot(u_mat.reshape(Gc, K * K), sel_ij.T, precision=_HIGHEST)
    b_diag = jnp.einsum("gkk->gk", B)
    b_ij = jnp.dot(B.reshape(Gc, K * K), sel_ij.T, precision=_HIGHEST)
    b_ji = jnp.dot(B.reshape(Gc, K * K), sel_ji.T, precision=_HIGHEST)
    d_i = jnp.dot(b_diag, sel_i.T, precision=_HIGHEST)      # (Gc, P)
    d_j = jnp.dot(b_diag, sel_j.T, precision=_HIGHEST)

    n1 = n_of[pair_i].astype(jnp.float32)                   # (P,)
    n2 = n_of[pair_j].astype(jnp.float32)

    if sparse_mode:
        # Zero-block corrections. nnz/z per (gene, cluster) from the window
        # counts; pair columns via the same one-hot contractions.
        nnz_k = jnp.sum(C, axis=-1)                         # (Gc, K)
        z_k = jnp.maximum(n_of.astype(jnp.float32)[None, :] - nnz_k, 0.0)
        nnz_j = jnp.dot(nnz_k, sel_j.T, precision=_HIGHEST)  # (Gc, P)
        z_i = jnp.dot(z_k, sel_i.T, precision=_HIGHEST)
        z_j = jnp.dot(z_k, sel_j.T, precision=_HIGHEST)
        # u currently holds U′ (descending order = above-or-tied dominance)
        u = n1[None, :] * n2[None, :] - (
            u + z_i * nnz_j + 0.5 * z_i * z_j
        )
        # zero-run tie moments: B_full[k,l] = B′[k,l] + z_k²·z_l
        d_i = d_i + z_i * z_i * z_i
        d_j = d_j + z_j * z_j * z_j
        b_ij = b_ij + z_i * z_i * z_j
        b_ji = b_ji + z_j * z_j * z_i

    tie_sum = d_i + d_j + 3.0 * (b_ij + b_ji) - (n1 + n2)[None, :]
    rs1 = u + n1 * (n1 + 1.0) / 2.0
    log_p, u_out = wilcoxon_from_ranks(rs1, tie_sum, n1, n2)
    return log_p, u_out, tie_sum


# Single-device jitted entry; the sharded form lives in
# parallel.sharded_de.sharded_allpairs_ranksum and shard_maps the same body.
allpairs_ranksum_chunk = jax.jit(
    ranksum_body, static_argnames=("n_clusters", "window")
)
