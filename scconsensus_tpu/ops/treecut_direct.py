"""Naive spec-level twin of ``ops.treecut.cutree_hybrid`` (test oracle).

Role: the production cut (`ops/treecut.py`) carries hand-tuned fast paths —
bisect-based branch interleaves, triu-free core scatter, C-speed list
surgery — that are exactly where a silent indexing/tie/ordering bug could
hide. This module re-expresses the same published algorithm (Langfelder,
Zhang & Horvath 2008, "Defining clusters from a hierarchical cluster tree";
reference call sites R/reclusterDEConsensus.R:254-260) with the simplest
possible machinery: full stable re-sorts instead of interleaves, scipy
pdist for scatter, per-object loops in the PAM stage. ``tests/test_treecut.py``
asserts label-identical output across randomized geometries, deepSplits,
size floors, and PAM settings — the same consumed-oracle treatment the NB
engine gets from ``de/edger_direct.py``.

Honesty note: both implementations derive from the same reading of the
published description (the upstream R source is not consultable here), so
agreement rules out implementation divergence, not a shared
misinterpretation; the latter is what ``parity_kit/gen_treecut_fixtures.R``
exists to settle offline.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from scconsensus_tpu.ops.linkage import HClustTree
from scconsensus_tpu.ops.treecut import DEEP_SPLIT_CORE_SCATTER

__all__ = ["cutree_hybrid_direct"]


def _core_size_direct(branch_size: int, min_cluster_size: int) -> int:
    """Independent expression of the published CoreSize formula:
    min(minClusterSize/2 + 1 + sqrt(size − (minClusterSize/2 + 1)), size).
    Deliberately NOT imported from ops.treecut — the oracle must not share
    logic with the module under test (constants are fine, code is not)."""
    base = min_cluster_size / 2.0 + 1.0
    if base >= branch_size:
        return int(branch_size)
    return int(base + np.sqrt(branch_size - base))


def _pairwise_mean_distance(pts: np.ndarray) -> float:
    """Mean euclidean distance over unordered pairs (== off-diagonal mean)."""
    m = pts.shape[0]
    if m < 2:
        return 0.0
    from scipy.spatial.distance import pdist

    return float(np.mean(pdist(pts)))


def _qualifies_direct(
    members: List[Tuple[float, int]],
    death_height: float,
    embedding: np.ndarray,
    min_cluster_size: int,
    max_abs_core_scatter: float,
    min_abs_gap: float,
) -> bool:
    """members: (join_height, leaf) tuples in join order."""
    size = len(members)
    if size < min_cluster_size:
        return False
    cs = _core_size_direct(size, min_cluster_size)
    core_leaves = [leaf for _h, leaf in members[:cs]]
    if _pairwise_mean_distance(embedding[np.asarray(core_leaves)]) > (
        max_abs_core_scatter
    ):
        return False
    return (death_height - members[cs - 1][0]) >= min_abs_gap


def cutree_hybrid_direct(
    tree: HClustTree,
    embedding: np.ndarray,
    deep_split: int = 1,
    min_cluster_size: int = 10,
    cut_height: Optional[float] = None,
    pam_stage: bool = False,
    max_pam_dist: Optional[float] = None,
) -> np.ndarray:
    """Reference-naive hybrid cut; signature mirrors ``cutree_hybrid``."""
    if not 0 <= int(deep_split) <= 4:
        raise ValueError(f"deep_split must be in 0..4, got {deep_split}")
    n = tree.n_leaves
    heights = np.asarray(tree.height, np.float64)
    n_merge = n - 1
    ref_height = float(heights[max(int(round(0.05 * n_merge)), 1) - 1])
    max_height = float(heights[-1])
    if cut_height is None:
        cut_height = 0.99 * (max_height - ref_height) + ref_height
    cut_height = min(cut_height, max_height)

    max_core_scatter = DEEP_SPLIT_CORE_SCATTER[int(deep_split)]
    min_gap = (1.0 - max_core_scatter) * 3.0 / 4.0
    max_abs_core_scatter = ref_height + max_core_scatter * (
        cut_height - ref_height
    )
    min_abs_gap = min_gap * (cut_height - ref_height)

    embedding = np.ascontiguousarray(embedding, np.float64)

    # Branch = list of (join_height, leaf), kept in join order via a full
    # STABLE sort (key = height only) of the concatenation after every
    # fuse: stability makes the first child's members precede the second's
    # on exact height ties while preserving each branch's internal order —
    # the published "members ordered by joining height" rule.
    branches: Dict[int, List[Tuple[float, int]]] = {}
    composite: Dict[int, bool] = {}
    clusters: List[List[int]] = []

    for row in range(n_merge):
        h = float(heights[row])
        if h > cut_height:
            continue
        out: List[Tuple[float, int]] = []
        comp = False
        sides = []
        for code in (int(tree.merge[row, 0]), int(tree.merge[row, 1])):
            if code < 0:
                sides.append(([(h, -code - 1)], False))
            else:
                sides.append((branches.pop(code - 1),
                              composite.pop(code - 1)))
        (ma, ca), (mb, cb) = sides
        if ca or cb:
            for members, is_comp in sides:
                if not is_comp and _qualifies_direct(
                    members, h, embedding, min_cluster_size,
                    max_abs_core_scatter, min_abs_gap,
                ):
                    clusters.append([leaf for _h, leaf in members])
            comp = True
        elif len(ma) > 1 and len(mb) > 1 and _qualifies_direct(
            ma, h, embedding, min_cluster_size,
            max_abs_core_scatter, min_abs_gap,
        ) and _qualifies_direct(
            mb, h, embedding, min_cluster_size,
            max_abs_core_scatter, min_abs_gap,
        ):
            clusters.append([leaf for _h, leaf in ma])
            clusters.append([leaf for _h, leaf in mb])
            comp = True
        else:
            out = sorted(ma + mb, key=lambda t: t[0])  # stable: a first on ties
        branches[row] = out
        composite[row] = comp

    for row, members in branches.items():
        if composite[row]:
            continue
        if _qualifies_direct(members, cut_height, embedding,
                             min_cluster_size, max_abs_core_scatter,
                             min_abs_gap):
            clusters.append([leaf for _h, leaf in members])

    labels = np.zeros(n, np.int64)
    clusters.sort(key=len, reverse=True)
    for cid, members in enumerate(clusters, start=1):
        labels[np.asarray(members)] = cid

    if pam_stage and clusters:
        limit = cut_height if max_pam_dist is None else max_pam_dist
        out_labels = labels.copy()
        for obj in np.nonzero(labels == 0)[0]:
            best_c, best_d = 0, np.inf
            for c in range(1, labels.max() + 1):
                pts = embedding[labels == c]
                d = float(np.mean(
                    np.sqrt(np.sum((pts - embedding[obj]) ** 2, axis=1))
                ))
                if d < best_d:
                    best_c, best_d = c, d
            if best_d <= limit:
                out_labels[obj] = best_c
        labels = out_labels
    return labels
