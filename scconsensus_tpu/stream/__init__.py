"""Out-of-core streaming execution (round 17, ROADMAP item 5).

The 10M-cell layer: disk-resident chunked CSR input
(:class:`~scconsensus_tpu.stream.store.ChunkedCSRStore`), a hard
host-memory budget (:class:`~scconsensus_tpu.stream.budget.
HostBudgetAccountant`), and a per-shard refine pipeline
(:func:`~scconsensus_tpu.stream.runner.streaming_refine`) whose every
stage operates chunk-at-a-time with durable, checksummed progress — a
SIGKILL mid-run resumes from the last fsynced chunk to byte-identical
labels, a torn chunk quarantines and recomputes, ENOSPC degrades
checkpoint granularity before failing typed, and a budget breach halves
the streaming window.

Import discipline: this ``__init__`` re-exports lazily so jax-free
consumers (``validate_run_record`` → ``stream.record``) never pull the
compute stack in.
"""

from __future__ import annotations

__all__ = [
    "ChunkedCSRStore",
    "ChunkCorrupt",
    "HostBudgetAccountant",
    "HostBudgetExceeded",
    "streaming_refine",
    "validate_streaming",
]


def __getattr__(name):
    if name in ("ChunkedCSRStore", "ChunkCorrupt"):
        from scconsensus_tpu.stream import store as _m

        return getattr(_m, name)
    if name in ("HostBudgetAccountant", "HostBudgetExceeded"):
        from scconsensus_tpu.stream import budget as _m

        return getattr(_m, name)
    if name == "streaming_refine":
        from scconsensus_tpu.stream.runner import streaming_refine

        return streaming_refine
    if name == "validate_streaming":
        from scconsensus_tpu.stream.record import validate_streaming

        return validate_streaming
    raise AttributeError(name)
