"""Out-of-core streaming refine: the full pipeline over disk chunks.

``streaming_refine(store, labels, config)`` runs DE → union → embed →
tree → cuts → silhouette → nodg against a :class:`ChunkedCSRStore`
under a hard host-memory budget (stream.budget): chunks load → compute
→ drop, every per-shard result lands in a resumable ArtifactStore stage
keyed by content, and a SIGKILL at ANY point resumes from the last
durable chunk to byte-identical labels.

Per-shard strategy (why chunking the GENE axis is exact, not
approximate):

  * **DE** — rank tests, gates, and BH are per-gene: each chunk's
    (Gb, N) CSR slab runs the SAME window ladder as the in-memory
    engine (de.engine.streaming_wilcox_block) and produces the same
    per-gene columns; per-cluster aggregates are gene-sliced sums
    accumulated chunk-at-a-time. The (P, G) statistics are small (P
    pairs, not N cells) and assemble on host.
  * **embed** — two regimes under one budget. When the dense (N, |U|)
    cell matrix fits the staged budget, the SAME randomized subspace
    iteration as the in-memory pipeline runs on the same bytes — the
    embedding, and therefore every downstream label, is BIT-identical
    to ``refine()``'s (the mid-size ARI==1.0 pin measures exactly
    this). Past the budget the run degrades (recorded) to the
    (|U|, |U|) gene-space Gram eigenbasis computed from the union
    rows' sparse slab: no dense (N, |U|) ever exists, the scores come
    from one sparse-times-dense product, and the result is
    deterministic per seed (resumes and reruns reproduce bit-for-bit)
    though its noise-subspace basis differs from the randomized one.
  * **tree / cuts / silhouette** — the r12 landmark engine already
    splits sketch-fit from full assign; above the landmark threshold
    the fit sees a budget-priced sketch and cut labels propagate via
    the blocked device assign. Below the thresholds the exact branches
    run unchanged (identity with ``refine()`` by construction).
  * **nodg** — per-cell detected-gene counts accumulate over chunks.

Recovery ladders (all typed, all recorded on the robustness trail):
a ``HostBudgetExceeded`` halves the streaming gene window (floor 1 row,
then the typed error propagates); a disk-class stage-checkpoint write
failure doubles the checkpoint granularity (fewer, coarser durability
points — trading resume granularity for disk) before failing typed; a
torn chunk quarantines and recomputes through the store's generator.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from scconsensus_tpu.config import ReclusterConfig
from scconsensus_tpu.stream.budget import (
    HostBudgetAccountant,
    HostBudgetExceeded,
)
from scconsensus_tpu.stream.store import ChunkedCSRStore
from scconsensus_tpu.stream import record as stream_record
from scconsensus_tpu.utils.artifacts import ArtifactStore
from scconsensus_tpu.utils.logging import StageTimer, get_logger

__all__ = ["streaming_refine"]


def _labels_sha(labels) -> str:
    # hash the unicode array's raw buffer (dtype stamped, since the
    # UCS4 width depends on the longest label): one O(N) pass, no
    # per-cell Python strings — a .tolist()+join here would spike
    # hundreds of MB of host objects inside the bounded-memory layer
    lab = np.ascontiguousarray(np.asarray(labels).astype(str))
    h = hashlib.sha256(str(lab.dtype).encode())
    h.update(lab.tobytes())
    return h.hexdigest()[:16]


def _chunk_key(i: int, g0: int, g1: int, n_cells: int, groups_sha: str
               ) -> str:
    """Content-addressed per-chunk DE stage name: rows + cell-group
    fingerprint, so a resume with different labels/subsampling can never
    adopt the wrong block."""
    h = hashlib.sha256(
        f"{i}:{g0}:{g1}:{n_cells}:{groups_sha}".encode()
    ).hexdigest()[:16]
    return f"stream_de_{h}"


class _StreamState:
    """One run's mutable streaming bookkeeping (window ladder, checkpoint
    granularity, resume counts) — what the validated section is built
    from at the end."""

    def __init__(self, window_rows: int):
        self.window_initial = int(window_rows)
        self.window_rows = int(window_rows)
        self.halvings = 0
        self.ckpt_initial = 1
        self.ckpt_every = 1
        self.de_resumed = 0

    def halve_window(self, why: str) -> None:
        from scconsensus_tpu.robust import record as robust_record

        if self.window_rows <= 1:
            raise HostBudgetExceeded(
                "staged", 0, 0, 0,
                f"window ladder floor reached (1 row) — {why}",
            )
        self.window_rows = max(self.window_rows // 2, 1)
        self.halvings += 1
        robust_record.note_degradation(
            "stream_stage", "halve-window",
            f"{why}; streaming window now {self.window_rows} rows",
        )

    def coarsen_ckpt(self, why: str) -> None:
        from scconsensus_tpu.robust import record as robust_record

        self.ckpt_every *= 2
        robust_record.note_degradation(
            "stream_stage", "shrink-ckpt-granularity",
            f"{why}; per-chunk checkpoints now every "
            f"{self.ckpt_every} chunk(s)",
        )


def streaming_refine(
    store: ChunkedCSRStore,
    labels: Sequence,
    config: ReclusterConfig,
    gene_names: Optional[Sequence[str]] = None,
    stage_dir: Optional[str] = None,
    accountant: Optional[HostBudgetAccountant] = None,
    regen: Optional[Callable[[int, int], Any]] = None,
    timer: Optional[StageTimer] = None,
):
    """Run the refine pipeline out-of-core against ``store``.

    ``stage_dir`` (default ``<store.root>/stages``) holds the resumable
    per-shard progress; ``regen(g0, g1)`` regenerates quarantined
    chunks (the synthetic benches pass their seeded generator; real
    ingested data without one fails typed on a torn chunk).
    Returns a :class:`~scconsensus_tpu.models.pipeline.ReclusterResult`
    whose ``metrics`` additionally carry the validated ``streaming``
    section. Only the fast wilcox DE path is supported out-of-core
    (``config.method`` must be ``wilcox``) — the NB/edgeR family holds
    per-pair cell slabs the disk format does not shard yet.
    """
    import jax.numpy as jnp

    from scconsensus_tpu.robust import record as robust_record
    from scconsensus_tpu.robust import retry as robust_retry

    if config.method.lower() not in ("wilcox",):
        raise NotImplementedError(
            f"streaming_refine supports method='wilcox' only (got "
            f"{config.method!r}) — the NB/edgeR path is not sharded "
            "out-of-core yet"
        )
    robust_record.begin_run()
    from scconsensus_tpu.robust import integrity as robust_integrity

    robust_integrity.begin_run()
    logger = get_logger()
    timer = timer or StageTimer(logger)
    G, N = store.shape
    lab = np.asarray(labels).astype(str)
    if lab.size != N:
        raise ValueError(
            f"labels have {lab.size} entries for a {N}-cell chunk store"
        )

    stages = ArtifactStore(stage_dir or f"{store.root.rstrip('/')}/stages")
    state = _StreamState(store.row_window)
    acct = accountant or HostBudgetAccountant()
    run_log = robust_record.current_run()

    groups_sha = _labels_sha(lab) + f":{config.min_cluster_size}" \
        f":{config.min_cells_group}:{config.max_cells_per_ident}" \
        f":{config.random_seed}"
    stages.check_config(config.to_json(), inputs={
        "stream_manifest": {k: store.manifest()[k] for k in
                            ("n_genes", "n_cells", "row_window")},
        "groups_sha": groups_sha,
    })
    # retry-budget persistence: same kill-proof ratchet as the in-memory
    # pipeline (a killed streaming run must not resurrect with a fresh
    # retry allowance)
    try:
        _, rb_meta = stages.load("robust_state")
        if rb_meta.get("budget_used"):
            run_log.restore_budget(int(rb_meta["budget_used"]))
    except ValueError:
        pass
    run_log.set_budget_persist(
        lambda used: stages.save("robust_state",
                                 meta={"budget_used": used})
    )

    def _guard(fn, site="stream_stage", degrade=None):
        return robust_retry.call(fn, site, degrade=degrade)

    with acct:
        result = _streaming_impl(
            store, lab, config, gene_names, timer, stages, state, acct,
            regen, _guard, groups_sha,
        )

    # -- the validated streaming section ---------------------------------
    c = store.counters
    completed = c["fresh"] + c["resumed"]
    bud = acct.budget_fields()
    section = stream_record.build_streaming_section(
        planned=store.n_chunks, fresh=c["fresh"], resumed=c["resumed"],
        recomputed=c["recomputed"], quarantined=c["quarantined"],
        window_initial=state.window_initial,
        window_final=state.window_rows, halvings=state.halvings,
        ckpt_initial=state.ckpt_initial, ckpt_final=state.ckpt_every,
        limit_mb=bud["limit_mb"], stage_limit_mb=bud["stage_limit_mb"],
        baseline_rss_mb=bud["baseline_rss_mb"],
        peak_rss_mb=bud["peak_rss_mb"],
        peak_staged_mb=bud["peak_staged_mb"],
        complete=(completed == store.n_chunks),
    )
    stream_record.validate_streaming(section)  # the emitter self-checks
    result.metrics["streaming"] = section
    rb = robust_record.section()
    if rb is not None:
        result.metrics["robustness"] = rb
    ig = robust_integrity.section()
    if ig is not None:
        result.metrics["integrity"] = ig
    try:
        stages.save("robust_state", meta={"budget_used": 0})
    except Exception:
        pass
    return result


def _gram_pca_streamed(store, union, acct, n_pcs: int,
                       load_part) -> np.ndarray:
    """Fully-streamed PCA via the (|U|, |U|) gene-space Gram matrix:
    eigenvectors of the centered Gram ARE the principal axes, the Gram
    accumulates from PAIRWISE chunk joins (two chunks' union rows in
    memory at a time — never the whole slab), and the (N, p) scores
    accumulate chunk-at-a-time from one sparse-times-dense product per
    chunk. The only O(N) buffer is the scores array, budget-charged.
    Deterministic (LAPACK eigh + a fixed sign convention), so resumes
    and reruns reproduce bit-for-bit. IO cost: the union-bearing chunks
    load O(u_chunks) times each for the joins — the price of the
    degraded path, paid only when the dense embed would bust the
    budget."""
    n_cells = store.shape[1]
    u = int(np.asarray(union).size)
    with_rows = []
    for i in range(store.n_chunks):
        g0, g1 = store.chunk_rows(i)
        uni = np.asarray(union)
        if np.any((uni >= g0) & (uni < g1)):
            with_rows.append(i)
    gram = np.zeros((u, u), np.float64)
    msum = np.zeros(u, np.float64)
    for ai, a in enumerate(with_rows):
        xa, sel_a = load_part(a)
        acct.charge(xa.data.nbytes * 3, "gram_join")
        try:
            msum[sel_a] = np.asarray(xa.sum(axis=1), np.float64).ravel()
            gram[np.ix_(sel_a, sel_a)] = (xa @ xa.T).toarray()
            for b in with_rows[ai + 1:]:
                xb, sel_b = load_part(b)
                blockc = np.asarray((xa @ xb.T).toarray(), np.float64)
                gram[np.ix_(sel_a, sel_b)] = blockc
                gram[np.ix_(sel_b, sel_a)] = blockc.T
                del xb
        finally:
            acct.release(xa.data.nbytes * 3, "gram_join")
            del xa
    m = msum / n_cells
    gram -= n_cells * np.outer(m, m)
    evals, evecs = np.linalg.eigh(gram)
    order = np.argsort(evals)[::-1][:n_pcs]
    v = evecs[:, order]
    # deterministic sign convention (eigh signs are arbitrary):
    # largest-|loading| component positive
    flip = v[np.argmax(np.abs(v), axis=0), np.arange(v.shape[1])] < 0
    v[:, flip] *= -1.0
    v32 = np.ascontiguousarray(v, np.float32)
    acct.charge(n_cells * n_pcs * 4, "scores")
    scores = np.zeros((n_cells, n_pcs), np.float32)
    for a in with_rows:
        xa, sel_a = load_part(a)
        try:
            scores += np.asarray(xa.T.dot(v32[sel_a]), np.float32)
        finally:
            del xa
    return scores - (m @ v).astype(np.float32)[None, :]


def _chunk_aggregates(block, cid: np.ndarray, K: int) -> Dict[str, Any]:
    """Per-cluster sufficient statistics of one (Gb, N) CSR slab as
    nnz-bound host scatter-adds — no (N, K) one-hot ever materializes
    (at 10M cells that one-hot alone would eat the whole budget)."""
    gb = block.shape[0]
    data, indices, indptr = block.data, block.indices, block.indptr
    rows = np.repeat(np.arange(gb, dtype=np.int64), np.diff(indptr))
    k = cid[indices]
    m = k >= 0
    rows, k, vals = rows[m], k[m], data[m].astype(np.float64)
    out = {
        "sum_log": np.zeros((gb, K), np.float64),
        "sum_expm1": np.zeros((gb, K), np.float64),
        "sum_sq": np.zeros((gb, K), np.float64),
        "nnz": np.zeros((gb, K), np.float64),
    }
    np.add.at(out["sum_log"], (rows, k), vals)
    np.add.at(out["sum_expm1"], (rows, k), np.expm1(vals))
    np.add.at(out["sum_sq"], (rows, k), vals * vals)
    np.add.at(out["nnz"], (rows, k), (vals > 0).astype(np.float64))
    return out


def _streaming_impl(store, lab, config, gene_names, timer, stages, state,
                    acct, regen, _guard, groups_sha):
    import jax
    import jax.numpy as jnp

    from scconsensus_tpu.de.engine import (
        _all_pairs,
        filter_clusters,
        de_gene_union,
        streaming_wilcox_block,
        PairwiseDEResult,
    )
    from scconsensus_tpu.models.pipeline import ReclusterResult
    from scconsensus_tpu.obs import residency
    from scconsensus_tpu.obs.live import active_recorder
    from scconsensus_tpu.ops.colors import labels_to_colors
    from scconsensus_tpu.ops.gates import ClusterAggregates, pair_gates_fast
    from scconsensus_tpu.ops.linkage import HClustTree, ward_linkage
    from scconsensus_tpu.ops.multipletests import bh_adjust_masked
    from scconsensus_tpu.ops.treecut import cutree_hybrid
    from scconsensus_tpu.robust import record as robust_record
    from scconsensus_tpu.stream.store import ChunkCorrupt

    logger = timer.logger
    G, N = store.shape

    # ---- cluster groups (host, O(N)) -----------------------------------
    with timer.stage("cluster_filter"):
        names, cell_idx = filter_clusters(
            lab, config.min_cluster_size, config.drop_grey
        )
        K = len(names)
        if K < 2:
            raise ValueError(
                f"need >= 2 clusters above min_cluster_size="
                f"{config.min_cluster_size}, got {K}"
            )
        cell_idx_of = [np.nonzero(cell_idx == k)[0].astype(np.int32)
                       for k in range(K)]
        if config.max_cells_per_ident is not None:
            rng = np.random.default_rng(config.random_seed)
            cap = config.max_cells_per_ident
            cell_idx_of = [
                rng.choice(ci, size=cap, replace=False)
                if ci.size > cap else ci for ci in cell_idx_of
            ]
        pair_i, pair_j = _all_pairs(K)
        P = int(pair_i.size)
        n_of = np.array([ci.size for ci in cell_idx_of], np.int32)
        pair_ok = (n_of[pair_i] >= config.min_cells_group) & (
            n_of[pair_j] >= config.min_cells_group
        )
        skip_reasons = [
            f"{names[i]} vs {names[j]}: group sizes ({n_of[i]}, {n_of[j]})"
            f" below min_cells_group={config.min_cells_group}"
            for i, j in zip(pair_i[~pair_ok], pair_j[~pair_ok])
        ]
        if not pair_ok.any():
            raise ValueError(
                "every cluster pair has a group below min_cells_group="
                f"{config.min_cells_group}; nothing to test"
            )
        acct.charge(cell_idx.nbytes, "cell_groups")

    # ---- DE: chunk-at-a-time wilcox + aggregates ------------------------
    def _process_chunk(i: int, g0: int, g1: int):
        """One chunk's (P, Gb) log-p/U + (Gb, K) aggregate slabs, from
        the durable stage artifact when present (the resume path), else
        computed under the window-halving ladder and checkpointed."""
        key = _chunk_key(i, g0, g1, N, groups_sha)
        if stages.has(key):
            try:
                arrays, _ = stages.load(key)
                state.de_resumed += 1
                return arrays
            except ValueError as e:  # quarantined: recompute below
                logger.warning("stream de chunk %d unusable (%s); "
                               "recomputing", i, e)
        est_chunk = store.chunk_host_bytes(i)
        acct.charge(est_chunk, "chunk")
        try:
            try:
                block = store.ensure_chunk(i, regen)
            except ChunkCorrupt:
                # no generator: the typed corruption propagates (the
                # store already quarantined the files)
                raise
            gb = block.shape[0]
            lp_rows: List[np.ndarray] = []
            u_rows: List[np.ndarray] = []
            agg_parts: List[Dict[str, Any]] = []
            r0 = 0
            while r0 < gb:
                w = max(min(state.window_rows, gb - r0), 1)
                sub = block[r0:r0 + w]
                # working-set estimate for this sub-window: the (P, w)
                # outputs (×2, lp+u, f32 device+host copies) plus the
                # compacted window staging (nnz-bound) — what halving
                # actually shrinks
                est = w * P * 4 * 4 + int(sub.nnz) * 12
                try:
                    acct.charge(est, "de_window")
                except HostBudgetExceeded as e:
                    state.halve_window(str(e).splitlines()[0][:140])
                    continue
                try:
                    lp_d, u_d = streaming_wilcox_block(
                        sub, cell_idx_of, pair_i, pair_j
                    )
                    with residency.boundary("stream_block_fetch"):
                        lp_h, u_h = jax.device_get((lp_d, u_d))
                    lp_h = np.asarray(lp_h, np.float32)
                    u_h = np.asarray(u_h, np.float32)
                    # integrity tier (robust.integrity, r18): the
                    # injected stream_block corruption site, the
                    # conservation invariant over the fetched block,
                    # and one host-side ghost replay per run — a
                    # detection raises typed silent_corruption inside
                    # this chunk's guard, so recompute-the-unit re-runs
                    # THIS chunk before it persists
                    from scconsensus_tpu.de.engine import (
                        _cid_from_groups,
                    )
                    from scconsensus_tpu.robust import (
                        integrity as robust_integrity,
                    )
                    from scconsensus_tpu.robust.faults import (
                        corrupt_value,
                    )

                    lp_h, u_h = corrupt_value("stream_block",
                                              (lp_h, u_h))
                    if robust_integrity.enabled():
                        robust_integrity.check_wilcox_host(
                            "stream_block", lp_h, u_h,
                            n_of[pair_i], n_of[pair_j],
                        )
                        if robust_integrity.current().want_replay(
                                "stream_chunk", 0):
                            robust_integrity.replay_stream_chunk(
                                "stream_block", f"chunk:{i}", sub,
                                _cid_from_groups(cell_idx_of, N),
                                n_of, pair_i, pair_j, lp_h, u_h,
                            )
                    lp_rows.append(lp_h)
                    u_rows.append(u_h)
                    agg_parts.append(_chunk_aggregates(sub, cell_idx, K))
                finally:
                    acct.release(est, "de_window")
                r0 += w
                rec = active_recorder()
                if rec is not None:
                    rec.touch()
            arrays = {
                "lp": np.concatenate(lp_rows, axis=1),
                "u": np.concatenate(u_rows, axis=1),
            }
            for f in ("sum_log", "sum_expm1", "sum_sq", "nnz"):
                arrays[f] = np.concatenate(
                    [a[f] for a in agg_parts], axis=0
                ).astype(np.float32)
            if i % state.ckpt_every == 0:
                def _save():
                    stages.save(key, arrays, meta={"g0": g0, "g1": g1})

                def _ckpt_degrade(_attempt):
                    # ENOSPC on a durability write: coarsen granularity
                    # (fewer checkpoints = less disk) before retrying —
                    # durability must never become the failure mode
                    state.coarsen_ckpt(
                        "disk fault writing per-chunk DE checkpoint"
                    )
                try:
                    _guard(_save, site="stream_chunk_write",
                           degrade=_ckpt_degrade)
                except Exception as e:
                    robust_record.note_degradation(
                        "stream_chunk_write", "ckpt-skip",
                        f"checkpoint write failed typed ({e!r}); "
                        "continuing without durability for this chunk",
                    )
            return arrays
        finally:
            acct.release(est_chunk, "chunk")

    with timer.stage("de", n_clusters=K, n_pairs=P) as de_rec:
        # ensure every chunk is durable first (resumable ingest — the
        # generator-backed benches materialize here; pre-ingested stores
        # just count their durable chunks, so a full-resume run still
        # reports completed == planned)
        if regen is not None:
            store.ingest(regen)
        else:
            store.adopt_durable()
        lp_parts: List[np.ndarray] = []
        u_parts: List[np.ndarray] = []
        agg_acc: Dict[str, List[np.ndarray]] = {
            "sum_log": [], "sum_expm1": [], "sum_sq": [], "nnz": [],
        }
        for i in range(store.n_chunks):
            g0, g1 = store.chunk_rows(i)
            arrays = _guard(lambda i=i, g0=g0, g1=g1:
                            _process_chunk(i, g0, g1))
            lp_parts.append(arrays["lp"])
            u_parts.append(arrays["u"])
            for f in agg_acc:
                agg_acc[f].append(np.asarray(arrays[f], np.float64))
            acct.note_progress(stage="de", chunks_done=i + 1,
                               chunks_planned=store.n_chunks,
                               halvings=state.halvings)
        if state.de_resumed:
            robust_record.note_resume_point(
                "stream_de", "chunk", state.de_resumed, store.n_chunks
            )
        log_p = np.concatenate(lp_parts, axis=1)      # (P, G) f32
        del lp_parts, u_parts  # U rides the chunk artifacts for resume
        # identity; the fast-path DE call never consumes it
        agg_host = {f: np.concatenate(v, axis=0) for f, v in
                    agg_acc.items()}
        del agg_acc
        de_rec["chunks"] = store.n_chunks
        de_rec["resumed_chunks"] = state.de_resumed

        counts = np.zeros(K, np.float64)
        for k in range(K):
            counts[k] = float(np.sum(cell_idx == k))
        agg = ClusterAggregates(
            sum_log=jnp.asarray(agg_host["sum_log"], jnp.float32),
            sum_expm1=jnp.asarray(agg_host["sum_expm1"], jnp.float32),
            sum_sq=jnp.asarray(agg_host["sum_sq"], jnp.float32),
            nnz=jnp.asarray(agg_host["nnz"], jnp.float32),
            counts=jnp.asarray(counts, jnp.float32),
        )
        pi, pj = jnp.asarray(pair_i), jnp.asarray(pair_j)
        j_ok = jnp.asarray(pair_ok)
        gate, log_fc, pct1, pct2 = pair_gates_fast(
            agg, pi, pj,
            min_pct=config.min_pct,
            min_diff_pct=config.min_diff_pct,
            log_fc_thrs=config.log_fc_thrs,
            mean_exprs_thrs=config.mean_exprs_thrs,
            pseudocount=config.pseudocount,
            only_pos=config.only_pos,
        )
        tested = gate & j_ok[:, None]
        jlp = jnp.where(tested, jnp.asarray(log_p), jnp.nan)
        log_q = bh_adjust_masked(jlp, tested)
        log_thr = float(np.log(np.float32(config.q_val_thrs)))
        de_mask = tested & (log_q < log_thr) & ~jnp.isnan(log_q)
        de_res = PairwiseDEResult(
            cluster_names=names,
            pair_i=pair_i, pair_j=pair_j,
            log_p=jlp, log_q=log_q, log_fc=log_fc,
            tested=tested, de_mask=de_mask,
            pair_skipped=~pair_ok,
            pct1=pct1, pct2=pct2,
            aux={"funnel_gate_full": jnp.sum(gate, axis=1)},
            skip_reasons=skip_reasons or None,
        )

    # ---- union ----------------------------------------------------------
    with timer.stage("union") as rec:
        union = _guard(lambda: stages.cached(
            "union",
            lambda: {"idx": de_gene_union(de_res, config.n_top_de_genes)},
        ))["idx"]
        rec["union_size"] = int(union.size)
        rec["per_pair_de_counts"] = de_res.de_counts().tolist()
    if union.size < 2:
        raise ValueError(
            f"DE gene union has {union.size} genes — nothing to "
            "re-embed. Loosen q_val_thrs/log_fc_thrs or check cluster "
            "labels."
        )

    # ---- embed: streamed union gather + Gram PCA ------------------------
    with timer.stage("embed") as rec:
        n_pcs = min(int(union.size), config.n_pcs)
        rec["n_pcs"] = n_pcs

        def _union_rows_of(i: int):
            """(local row ids, global union positions) of chunk i."""
            g0, g1 = store.chunk_rows(i)
            uni = np.asarray(union)
            sel = np.nonzero((uni >= g0) & (uni < g1))[0]
            return (uni[sel] - g0), sel

        def _load_union_slab_part(i: int):
            """This chunk's union rows as a CSR part (transient chunk
            charge; the caller owns the part's lifetime)."""
            est = store.chunk_host_bytes(i)
            acct.charge(est, "chunk")
            try:
                block = store.ensure_chunk(i, regen)
                rows, sel = _union_rows_of(i)
                return block[rows], sel
            finally:
                acct.release(est, "chunk")

        def _embed():
            import scipy.sparse as sp

            if config.distance != "euclidean":
                raise NotImplementedError(
                    "streaming_refine supports distance='euclidean' "
                    f"only (got {config.distance!r})"
                )
            # Exact-twin path first: when the dense (N, |U|) cell matrix
            # fits the staged budget, run THE SAME randomized subspace
            # iteration as the in-memory pipeline on the same bytes —
            # the embedding, and therefore every downstream label, is
            # BIT-identical to refine()'s (the mid-size ARI==1.0 pin
            # measures exactly this). Past the budget the run degrades
            # (recorded) to the fully-streamed gene-space Gram path.
            # the reservation covers the dense matrix AND the largest
            # transient chunk load the gather will charge on top of it —
            # otherwise a dense plan that "fits" dies mid-gather on the
            # first chunk charge
            dense_bytes = int(N) * int(union.size) * 4 * 3 + max(
                store.chunk_host_bytes(i) for i in range(store.n_chunks)
            )
            try:
                acct.charge(dense_bytes, "embed_dense")
            except HostBudgetExceeded:
                robust_record.note_degradation(
                    "stream_stage", "gram-pca-embed",
                    f"dense (N={N}, |U|={union.size}) embed would pass "
                    "the staged budget; using the streamed gene-space "
                    "Gram eigenbasis (deterministic, subspace-equal "
                    "for separated spectra)",
                )
                # regeneration of a torn chunk during the joins rides
                # load_part's closure over regen
                return {"scores": _gram_pca_streamed(
                    store, union, acct, n_pcs, _load_union_slab_part,
                )}
            try:
                from scconsensus_tpu.ops.pca import pca_scores

                parts = [None] * store.n_chunks
                for i in range(store.n_chunks):
                    if _union_rows_of(i)[0].size:
                        parts[i] = _load_union_slab_part(i)[0]
                xs = sp.vstack([p for p in parts if p is not None]
                               ).tocsr()  # (|U|, N), union order
                del parts
                cells = xs.toarray().T.astype(np.float32)   # (N, |U|)
                del xs
                scores = pca_scores(jnp.asarray(cells), n_pcs)
                del cells
                with residency.boundary("embed_scores_fetch"):
                    acct.charge(N * n_pcs * 4, "scores")
                    return {"scores": np.asarray(scores)}
            finally:
                acct.release(dense_bytes, "embed_dense")

        embedding = _guard(lambda: stages.cached("embed", _embed))["scores"]

    # ---- tree (mirrors models.pipeline's branch policy) -----------------
    with timer.stage("tree", n_cells=N) as rec:
        approx = N > config.approx_threshold
        rec["approx"] = approx
        lm_policy = (
            config.landmark_policy(N)
            if approx and config.approx_method == "pool" else None
        )

        def _tree():
            if approx and config.approx_method == "knn":
                from scconsensus_tpu.ops.knn_linkage import knn_ward_linkage

                t = knn_ward_linkage(embedding, k=config.knn_graph_k,
                                     mesh=None)
                return {"merge": t.merge, "height": t.height,
                        "order": t.order}
            if lm_policy is not None:
                from scconsensus_tpu.ops.pooling import (
                    landmark_ward_linkage,
                )

                t, assign, cents, info = landmark_ward_linkage(
                    embedding,
                    n_landmarks=lm_policy["k"],
                    sketch=lm_policy["sketch"],
                    seed=config.random_seed,
                    c=lm_policy["c"],
                    k_min=lm_policy["k_min"],
                    k_max=lm_policy["k_max"],
                    linkage=lm_policy["linkage"],
                    knn_k=lm_policy["knn_k"],
                    mesh=None,
                    charge=lambda nb, what: acct.charge(nb, what) and
                    acct.release(nb, what),
                )
                return {"merge": t.merge, "height": t.height,
                        "order": t.order, "pool_assign": assign,
                        "pool_centroids": cents,
                        "landmark_k": np.asarray(info["k_used"]),
                        "landmark_sketch": np.asarray(info["sketch"])}
            if approx:
                from scconsensus_tpu.ops.pooling import pooled_ward_linkage

                t, assign, cents = pooled_ward_linkage(
                    embedding, n_centroids=config.n_pool_centroids,
                    seed=config.random_seed,
                )
                return {"merge": t.merge, "height": t.height,
                        "order": t.order, "pool_assign": assign,
                        "pool_centroids": cents}
            t = ward_linkage(embedding)
            return {"merge": t.merge, "height": t.height, "order": t.order}

        tree_arrays = _guard(lambda: stages.cached("tree", _tree))
        tree = HClustTree(merge=tree_arrays["merge"],
                          height=tree_arrays["height"],
                          order=tree_arrays["order"])
        pool_assign = tree_arrays.get("pool_assign")
        pool_centroids = tree_arrays.get("pool_centroids")
        landmark_used = "landmark_k" in tree_arrays
        if landmark_used:
            rec["landmark"] = True
            rec["landmark_k"] = int(tree_arrays["landmark_k"])

    # ---- cuts -----------------------------------------------------------
    dynamic_colors: Dict[str, np.ndarray] = {}
    dynamic_labels: Dict[str, np.ndarray] = {}
    deep_split_info: List[Dict] = []
    with timer.stage("cuts"):
        cut_weights = None
        if pool_assign is None:
            cut_points, cut_min_size = embedding, config.min_cluster_size
        elif landmark_used:
            cut_points = pool_centroids
            cut_min_size = config.min_cluster_size
            cut_weights = np.bincount(
                pool_assign, minlength=pool_centroids.shape[0]
            ).astype(np.float64)
        else:
            avg_pool = max(N / pool_centroids.shape[0], 1.0)
            cut_points = pool_centroids
            cut_min_size = max(
                2, int(round(config.min_cluster_size / avg_pool))
            )

        def _cuts():
            out = {}
            for dsv in config.deep_split_values:
                cut_labels = cutree_hybrid(
                    tree, cut_points, deep_split=int(dsv),
                    min_cluster_size=cut_min_size,
                    pam_stage=config.pam_stage,
                    weights=cut_weights,
                )
                if pool_assign is not None:
                    cut_labels = cut_labels[pool_assign]
                out[f"ds{dsv}"] = cut_labels
            return out

        cut_arrays = _guard(lambda: stages.cached("cuts", _cuts))
        for dsv in config.deep_split_values:
            cut_labels = cut_arrays[f"ds{dsv}"]
            key = f"deepsplit: {dsv}"
            dynamic_labels[key] = cut_labels
            dynamic_colors[key] = labels_to_colors(cut_labels)
            deep_split_info.append({
                "deep_split": int(dsv),
                "n_clusters": int(
                    len(set(cut_labels[cut_labels > 0].tolist()))
                ),
            })

    # ---- silhouette (pooled estimator above threshold, exact below) -----
    if config.compat.return_silhouette:
        with timer.stage("silhouette") as sil_rec:
            labs = [
                np.where(dynamic_labels[f"deepsplit: {dsv}"] > 0,
                         dynamic_labels[f"deepsplit: {dsv}"], -1)
                for dsv in config.deep_split_values
            ]

            def _silhouette():
                if N > config.approx_threshold:
                    from scconsensus_tpu.ops.silhouette import (
                        pooled_multi_cut_silhouette,
                    )

                    sil_rec["method"] = "pooled-estimator"
                    sil_rec["pool_reused"] = pool_centroids is not None
                    for info, (si, _per) in zip(
                        deep_split_info,
                        pooled_multi_cut_silhouette(
                            embedding, labs,
                            n_centroids=config.silhouette_pool_centroids,
                            seed=config.random_seed,
                            centroids=pool_centroids,
                            assign=pool_assign,
                            sample=config.silhouette_sample,
                        ),
                    ):
                        info["silhouette"] = si
                        info["silhouette_method"] = "pooled-estimator"
                else:
                    from scconsensus_tpu.ops.silhouette import (
                        multi_cut_silhouette,
                    )

                    for info, (si, _per) in zip(
                        deep_split_info,
                        multi_cut_silhouette(embedding, labs),
                    ):
                        info["silhouette"] = si

            _guard(_silhouette)

    # ---- nodg: streamed per-cell detected-gene counts -------------------
    with timer.stage("nodg"):
        def _nodg():
            acc = np.zeros(N, np.int64)
            for i in range(store.n_chunks):
                est = store.chunk_host_bytes(i)
                acct.charge(est, "chunk")
                try:
                    block = store.ensure_chunk(i, regen)
                    acc += np.bincount(
                        block.indices[block.data > 0], minlength=N
                    )
                finally:
                    acct.release(est, "chunk")
            return {"nodg": acc}

        nodg = _guard(lambda: stages.cached("nodg", _nodg))["nodg"]

    union_names = (
        np.asarray(gene_names)[union] if gene_names is not None
        else union.copy()
    )
    acct.sample_rss()
    return ReclusterResult(
        de_gene_union=union_names,
        de_gene_union_idx=union,
        cell_tree=tree,
        dynamic_colors=dynamic_colors,
        dynamic_labels=dynamic_labels,
        deep_split_info=deep_split_info,
        nodg=nodg,
        embedding=embedding,
        de=de_res,
        metrics=timer.as_dict(),
    )
