"""The validated ``streaming`` run-record section + its live feed.

One additive schema-v1 section per out-of-core run::

    streaming: {
      chunks: {planned, completed, fresh, resumed, recomputed,
               quarantined},
      window: {initial_rows, final_rows, halvings},
      ckpt:   {initial_every, final_every},      # ENOSPC degradation
      budget: {limit_mb, stage_limit_mb, baseline_rss_mb, peak_rss_mb,
               peak_staged_mb, within_budget},
      complete: bool,
    }

Validation contract (the perf-gate smoke pins it):

  * **bounded memory needs evidence** — ``budget.within_budget: true``
    without a numeric ``peak_rss_mb``, or with ``peak_rss_mb`` OVER
    ``limit_mb``, is REJECTED: a record cannot *claim* a memory bound
    the kernel's high-water mark contradicts (the peak comes from
    ``ru_maxrss`` via obs.device.host_peak_rss_bytes — the same number
    the heartbeat stream and tail_run panel show);
  * **chunk counts must sum** — ``completed`` must equal
    ``fresh + resumed`` exactly (a chunk was either computed this run or
    adopted from a durable checkpoint; anything else is a lost or
    double-counted chunk), ``recomputed`` must not exceed
    ``quarantined`` (a recompute without a quarantine is a phantom
    corruption) and implies ``fresh >= 1``, and ``complete: true``
    requires ``completed == planned``.

Import discipline: stdlib only (``validate_run_record`` and the bench
orchestrator load this without jax) — the robust.record precedent.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

__all__ = [
    "build_streaming_section",
    "validate_streaming",
    "set_active",
    "live_summary",
]


def build_streaming_section(
    planned: int, fresh: int, resumed: int, recomputed: int,
    quarantined: int, window_initial: int, window_final: int,
    halvings: int, ckpt_initial: int, ckpt_final: int,
    limit_mb: float, stage_limit_mb: float,
    baseline_rss_mb: Optional[float], peak_rss_mb: Optional[float],
    peak_staged_mb: float, complete: bool,
) -> Dict[str, Any]:
    """Assemble one schema-conforming section (the single construction
    point, so the field list cannot drift from the validator).
    ``within_budget`` is COMPUTED here, never asserted by the caller — a
    run with no peak evidence gets ``within_budget: false`` by
    construction."""
    peak_ok = isinstance(peak_rss_mb, (int, float))
    return {
        "chunks": {
            "planned": int(planned),
            "completed": int(fresh) + int(resumed),
            "fresh": int(fresh),
            "resumed": int(resumed),
            "recomputed": int(recomputed),
            "quarantined": int(quarantined),
        },
        "window": {
            "initial_rows": int(window_initial),
            "final_rows": int(window_final),
            "halvings": int(halvings),
        },
        "ckpt": {
            "initial_every": int(ckpt_initial),
            "final_every": int(ckpt_final),
        },
        "budget": {
            "limit_mb": round(float(limit_mb), 3),
            "stage_limit_mb": round(float(stage_limit_mb), 3),
            "baseline_rss_mb": (round(float(baseline_rss_mb), 3)
                                if baseline_rss_mb is not None else None),
            "peak_rss_mb": (round(float(peak_rss_mb), 3)
                            if peak_ok else None),
            "peak_staged_mb": round(float(peak_staged_mb), 3),
            "within_budget": bool(
                peak_ok and float(peak_rss_mb) <= float(limit_mb)
            ),
        },
        "complete": bool(complete),
    }


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"streaming section: {msg}")


def _nonneg_int(v: Any, name: str) -> int:
    _require(isinstance(v, int) and v >= 0,
             f"{name} must be an int >= 0, got {v!r}")
    return v


def validate_streaming(sm: Dict[str, Any]) -> None:
    """Structural validation of a record's ``streaming`` section;
    ``export.validate_run_record`` dispatches here. The two load-bearing
    rules — bounded-memory-needs-evidence and chunk-counts-must-sum —
    are spelled out in the module docstring; their rejection messages
    name the rule so the perf-gate smoke can pin them."""
    _require(isinstance(sm, dict), "must be an object")
    ch = sm.get("chunks")
    _require(isinstance(ch, dict), "chunks must be an object")
    planned = _nonneg_int(ch.get("planned"), "chunks.planned")
    completed = _nonneg_int(ch.get("completed"), "chunks.completed")
    fresh = _nonneg_int(ch.get("fresh"), "chunks.fresh")
    resumed = _nonneg_int(ch.get("resumed"), "chunks.resumed")
    recomputed = _nonneg_int(ch.get("recomputed"), "chunks.recomputed")
    quarantined = _nonneg_int(ch.get("quarantined"), "chunks.quarantined")
    _require(
        completed == fresh + resumed,
        "chunk counts do not sum: completed must equal fresh + resumed "
        f"(got completed={completed}, fresh={fresh}, resumed={resumed}) "
        "— a chunk was either computed this run or adopted from a "
        "durable checkpoint, anything else is a lost chunk",
    )
    _require(completed <= planned,
             f"chunk counts do not sum: completed ({completed}) exceeds "
             f"planned ({planned})")
    _require(recomputed <= quarantined,
             f"chunk counts do not sum: recomputed ({recomputed}) exceeds "
             f"quarantined ({quarantined}) — a recompute without a "
             "quarantine is a phantom corruption")
    if recomputed:
        _require(fresh >= 1,
                 "chunk counts do not sum: recomputed chunks claimed "
                 "with fresh == 0 — every recompute is fresh work")
    if sm.get("complete"):
        _require(completed == planned,
                 "complete claimed with completed != planned "
                 f"({completed} != {planned})")
    win = sm.get("window")
    _require(isinstance(win, dict), "window must be an object")
    wi = _nonneg_int(win.get("initial_rows"), "window.initial_rows")
    wf = _nonneg_int(win.get("final_rows"), "window.final_rows")
    _require(wi >= 1 and wf >= 1, "window rows must be >= 1")
    _require(wf <= wi, "window.final_rows must be <= initial_rows "
                       "(recovery only ever shrinks the window)")
    _nonneg_int(win.get("halvings"), "window.halvings")
    ck = sm.get("ckpt")
    _require(isinstance(ck, dict), "ckpt must be an object")
    ci = _nonneg_int(ck.get("initial_every"), "ckpt.initial_every")
    cf = _nonneg_int(ck.get("final_every"), "ckpt.final_every")
    _require(cf >= ci >= 1, "ckpt granularity only ever coarsens "
                            "(final_every >= initial_every >= 1)")
    bud = sm.get("budget")
    _require(isinstance(bud, dict), "budget must be an object")
    lim = bud.get("limit_mb")
    _require(isinstance(lim, (int, float)) and lim > 0,
             "budget.limit_mb must be a positive number")
    peak = bud.get("peak_rss_mb")
    _require(peak is None or (isinstance(peak, (int, float)) and peak >= 0),
             "budget.peak_rss_mb must be a number >= 0 or null")
    if bud.get("within_budget"):
        _require(
            isinstance(peak, (int, float)),
            "within_budget claimed without RSS evidence (peak_rss_mb "
            "missing) — a record claiming bounded memory must carry the "
            "peak it is bounded BY",
        )
        _require(
            float(peak) <= float(lim),
            f"within_budget claimed with peak RSS over budget "
            f"(peak_rss_mb={peak} > limit_mb={lim}) — the claim "
            "contradicts its own evidence",
        )


# --------------------------------------------------------------------------
# live feed (heartbeat panel)
# --------------------------------------------------------------------------

_LOCK = threading.Lock()
_ACTIVE_FN: Optional[Callable[[], Optional[Dict[str, Any]]]] = None


def set_active(summary_fn: Optional[Callable[[], Optional[Dict[str, Any]]]]
               ) -> None:
    """Register the live streaming summary source (the runner's
    accountant registers on entry, clears on exit); obs.live snapshots
    it onto every heartbeat tick as the ``streaming`` panel."""
    global _ACTIVE_FN
    with _LOCK:
        _ACTIVE_FN = summary_fn


def live_summary() -> Optional[Dict[str, Any]]:
    fn = _ACTIVE_FN
    if fn is None:
        return None
    try:
        return fn()
    except Exception:
        return None
