"""Disk-resident chunked CSR store — the out-of-core input format.

A :class:`ChunkedCSRStore` holds a (G, N) sparse expression matrix as
fixed-row-window CSR blocks on disk::

    <root>/stream_manifest.json            # shape, window, chunk count
    <root>/chunk_00000.npz                 # data f32, indices i64, indptr i64
    <root>/chunk_00000.json                # {g0, g1, nnz, _integrity:{sha256, size}}
    ...

Every chunk is written through the shared mkstemp+fsync+``os.replace``
primitive (obs.export.atomic_write) and sha256-stamped via the same
``_integrity`` sidecar convention as the ArtifactStore, so "verified"
means the same thing for a streamed chunk and a stage artifact
(utils.artifacts.file_sha256 is the one hashing function). Loads verify
the stamp; a torn or bit-flipped chunk is QUARANTINED
(``*.quarantined-N``, the shared rename loop) and raises
:class:`ChunkCorrupt` — a subclass of ArtifactCorrupt, so every
existing quarantine-and-recompute consumer treats it identically.

Disk faults are first-class: each write runs under the typed retry
policy at site ``stream_chunk_write`` with a disk-class ``degrade``
hook that sweeps reclaimable bytes (stale temps, quarantined corpses)
before the retry; each load passes the ``stream_chunk_read`` fault
point. A ``kill`` plan at the write site proves mid-ingest durability:
the next process's :meth:`ensure_chunk` adopts every chunk that
finished its fsync+replace and recomputes exactly the rest.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from scconsensus_tpu.obs.export import atomic_write, write_json_atomic
from scconsensus_tpu.utils.artifacts import (
    ArtifactCorrupt,
    file_sha256,
    quarantine_files,
)

__all__ = ["ChunkedCSRStore", "ChunkCorrupt", "MANIFEST_NAME"]

MANIFEST_NAME = "stream_manifest.json"
MANIFEST_SCHEMA = "scc-stream-chunks"
MANIFEST_VERSION = 1


class ChunkCorrupt(ArtifactCorrupt):
    """A stored chunk failed its content checksum or would not parse.
    The offending files are already quarantined when this raises;
    :meth:`ChunkedCSRStore.ensure_chunk` recomputes through the
    caller's generator — the same quarantine-and-recompute contract as
    the ArtifactStore's stage artifacts."""


def _csr_parts(block) -> Dict[str, np.ndarray]:
    return {
        "data": np.asarray(block.data, np.float32),
        "indices": np.asarray(block.indices, np.int64),
        "indptr": np.asarray(block.indptr, np.int64),
    }


class ChunkedCSRStore:
    """Fixed-row-window CSR blocks of one (G, N) matrix on disk."""

    def __init__(self, root: str):
        self.root = root
        self._manifest: Optional[Dict[str, Any]] = None
        # per-run chunk accounting (the validated streaming section's
        # counters): each chunk index is classified ONCE per store
        # instance — "fresh" (computed+written by this run) or "resumed"
        # (adopted from a durable prior write) — so multi-pass reads
        # (ingest, DE, nodg) cannot double-count. A chunk that
        # quarantines AFTER being counted reclassifies resumed → fresh:
        # its durable copy proved unusable and this run recomputed it.
        self.counters: Dict[str, int] = {
            "fresh": 0, "resumed": 0, "recomputed": 0, "quarantined": 0,
        }
        self._counted_as: Dict[int, str] = {}

    # -- manifest ----------------------------------------------------------
    @property
    def manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    @classmethod
    def create(cls, root: str, n_genes: int, n_cells: int,
               row_window: int,
               meta: Optional[Dict[str, Any]] = None) -> "ChunkedCSRStore":
        """Initialize (or re-open) a store for one matrix shape. An
        existing manifest must MATCH — resuming an ingest into a store
        of a different shape would silently interleave datasets."""
        os.makedirs(root, exist_ok=True)
        st = cls(root)
        doc = {
            "schema": MANIFEST_SCHEMA,
            "version": MANIFEST_VERSION,
            "n_genes": int(n_genes),
            "n_cells": int(n_cells),
            "row_window": int(row_window),
            "n_chunks": (int(n_genes) + int(row_window) - 1)
            // int(row_window),
            "meta": dict(meta or {}),
        }
        if os.path.exists(st.manifest_path):
            cur = st.manifest()
            same = all(cur.get(k) == doc[k] for k in
                       ("n_genes", "n_cells", "row_window"))
            if not same:
                raise ValueError(
                    f"chunk store {root!r} already holds a different "
                    f"matrix shape ({cur.get('n_genes')}x"
                    f"{cur.get('n_cells')} window "
                    f"{cur.get('row_window')}) — use a fresh directory"
                )
            return st
        write_json_atomic(st.manifest_path, doc)
        st._manifest = doc
        return st

    def manifest(self) -> Dict[str, Any]:
        if self._manifest is None:
            try:
                with open(self.manifest_path) as f:
                    m = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                raise ValueError(
                    f"chunk store {self.root!r}: manifest unreadable ({e})"
                )
            if m.get("schema") != MANIFEST_SCHEMA:
                raise ValueError(
                    f"chunk store {self.root!r}: unknown manifest schema "
                    f"{m.get('schema')!r}"
                )
            self._manifest = m
        return self._manifest

    @property
    def shape(self) -> Tuple[int, int]:
        m = self.manifest()
        return int(m["n_genes"]), int(m["n_cells"])

    @property
    def row_window(self) -> int:
        return int(self.manifest()["row_window"])

    @property
    def n_chunks(self) -> int:
        return int(self.manifest()["n_chunks"])

    def chunk_rows(self, i: int) -> Tuple[int, int]:
        g, _ = self.shape
        w = self.row_window
        return i * w, min((i + 1) * w, g)

    # -- paths -------------------------------------------------------------
    def _paths(self, i: int) -> Tuple[str, str]:
        stem = os.path.join(self.root, f"chunk_{int(i):05d}")
        return f"{stem}.npz", f"{stem}.json"

    def has_chunk(self, i: int) -> bool:
        npz, js = self._paths(i)
        return os.path.exists(npz) and os.path.exists(js)

    def chunk_host_bytes(self, i: int) -> int:
        """Host-byte estimate of a durable chunk's loaded CSR form (from
        the sidecar's nnz — data f32 + indices i64 + indptr i64), so the
        budget accountant can charge BEFORE the load exists. Falls back
        to a dense-ish bound when the sidecar is unreadable (the load
        will quarantine it anyway)."""
        npz, js = self._paths(i)
        g0, g1 = self.chunk_rows(i)
        try:
            with open(js) as f:
                nnz = int(json.load(f).get("nnz", 0))
        except (OSError, json.JSONDecodeError, ValueError):
            # sidecar unreadable: the load will quarantine-and-recompute
            # anyway, so estimate from the compressed file size (×4 for
            # decompression) rather than a dense bound — at 10M cells a
            # dense (window, N) estimate would bust the staged budget
            # BEFORE ensure_chunk could run the recovery path, turning a
            # recoverable torn sidecar into a fatal budget breach
            try:
                return os.path.getsize(npz) * 4 + (g1 - g0 + 1) * 8
            except OSError:
                return 0  # nothing durable: the generator recomputes
        return nnz * 12 + (g1 - g0 + 1) * 8

    def completed_chunks(self) -> int:
        """Count of durable chunks — the mid-ingest resume point a
        SIGKILLed writer leaves behind."""
        return sum(1 for i in range(self.n_chunks) if self.has_chunk(i))

    # -- write -------------------------------------------------------------
    def write_chunk(self, i: int, block) -> None:
        """Atomically persist chunk ``i`` (a scipy CSR block of exactly
        this chunk's rows) with its sha256 integrity stamp. Runs under
        the typed retry policy at ``stream_chunk_write``: a disk-class
        failure (real ENOSPC or an injected one) sweeps reclaimable
        bytes and retries; the fault plan's ``kill`` class fires at the
        site, which is the mid-ingest durability test vector."""
        from scconsensus_tpu.robust import faults as _faults
        from scconsensus_tpu.robust import retry as robust_retry

        g0, g1 = self.chunk_rows(i)
        if block.shape[0] != g1 - g0:
            raise ValueError(
                f"chunk {i}: block has {block.shape[0]} rows, expected "
                f"{g1 - g0} (rows [{g0}, {g1}))"
            )
        npz, js = self._paths(i)
        arrays = _csr_parts(block)

        def _write() -> None:
            def _wz(tmp: str) -> None:
                with open(tmp, "wb") as f:
                    np.savez_compressed(f, **arrays)

            def _seal(tmp: str) -> None:
                write_json_atomic(js, {
                    "g0": int(g0), "g1": int(g1),
                    "n_cells": int(block.shape[1]),
                    "nnz": int(block.nnz),
                    "_integrity": {
                        "sha256": file_sha256(tmp),
                        "size": os.path.getsize(tmp),
                    },
                })

            # sidecar (with the checksum of the exact bytes about to
            # land) goes FIRST via _seal, npz replace last: has_chunk()
            # keys on both files, so the only observable intermediate
            # state reads as chunk-not-durable and recomputes
            atomic_write(npz, _wz, inspect_fn=_seal)

        robust_retry.call(_write, site="stream_chunk_write",
                          degrade=lambda attempt: self._sweep_reclaimable())
        # fault plan's post-write corruption hook: a torn chunk models a
        # disk/transport fault AFTER the atomic replace — exactly what
        # the load-time checksum exists for
        _faults.corrupt_artifact("stream_chunk", npz)

    def _sweep_reclaimable(self) -> int:
        """Disk-class degrade hook: delete what the store can regenerate
        or no longer needs — stale atomic-write temps and quarantined
        corpses (their post-mortem value is worth less than completing
        the run that hit ENOSPC). Returns bytes reclaimed."""
        from scconsensus_tpu.obs.export import ATOMIC_TMP_PREFIX
        from scconsensus_tpu.robust import record as robust_record

        freed = 0
        try:
            for e in os.scandir(self.root):
                if not e.is_file():
                    continue
                if (e.name.startswith(ATOMIC_TMP_PREFIX)
                        or ".quarantined-" in e.name):
                    try:
                        freed += e.stat().st_size
                        os.unlink(e.path)
                    except OSError:
                        pass
        except OSError:
            pass
        robust_record.note_degradation(
            "stream_chunk_write", "sweep-reclaimable",
            f"disk fault: freed {freed} bytes of temps/quarantined "
            "corpses before the retry",
        )
        return freed

    # -- read --------------------------------------------------------------
    def load_chunk(self, i: int):
        """Chunk ``i`` as a scipy CSR block. Verifies the sidecar's
        content checksum; a mismatch or unparseable file quarantines
        BOTH files and raises :class:`ChunkCorrupt` — callers recompute
        through :meth:`ensure_chunk`, never resume garbage."""
        import scipy.sparse as sp

        from scconsensus_tpu.robust import faults as _faults
        from scconsensus_tpu.robust import record as robust_record

        _faults.fault_point("stream_chunk_read")
        npz, js = self._paths(i)
        g0, g1 = self.chunk_rows(i)

        def _quarantine(reason: str) -> None:
            quarantine_files([npz, js])
            robust_record.note_degradation(
                f"stream_chunk:{i}", "quarantine", reason
            )

        try:
            with open(js) as f:
                meta = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            _quarantine(f"sidecar unreadable: {e}")
            raise ChunkCorrupt(
                f"chunk {i}: sidecar unreadable ({e}); quarantined"
            )
        integ = meta.get("_integrity") or {}
        actual = file_sha256(npz)
        if actual != integ.get("sha256"):
            _quarantine(
                f"checksum mismatch ({actual[:12]} != "
                f"{str(integ.get('sha256'))[:12]})"
            )
            raise ChunkCorrupt(
                f"chunk {i}: torn chunk — content checksum mismatch; "
                "quarantined"
            )
        try:
            with np.load(npz, allow_pickle=False) as z:
                data = z["data"]
                indices = z["indices"]
                indptr = z["indptr"]
        except Exception as e:  # BadZipFile, truncated stream, ...
            _quarantine(f"unparseable npz: {e!r}")
            raise ChunkCorrupt(
                f"chunk {i}: unparseable npz ({e!r}); quarantined"
            )
        n_cells = int(meta.get("n_cells") or self.shape[1])
        return sp.csr_matrix(
            (data, indices, indptr), shape=(g1 - g0, n_cells)
        )

    def _count(self, i: int, kind: str) -> None:
        prev = self._counted_as.get(i)
        if prev == kind:
            return
        if prev is not None:
            self.counters[prev] -= 1
        self._counted_as[i] = kind
        self.counters[kind] += 1

    def ensure_chunk(self, i: int, compute_fn: Optional[
            Callable[[int, int], Any]] = None):
        """Load chunk ``i``, or compute+persist it via
        ``compute_fn(g0, g1)`` (a scipy CSR block of those rows). A
        corrupt stored chunk has been quarantined by :meth:`load_chunk`
        — with a generator it RECOMPUTES (counted), without one the
        typed ChunkCorrupt propagates (user-ingested data has no
        regeneration story, and silently fabricating rows would be
        worse than failing). The instance's ``counters`` feed the
        validated streaming section."""
        if self.has_chunk(i):
            try:
                block = self.load_chunk(i)
                if i not in self._counted_as:
                    self._count(i, "resumed")
                return block
            except ChunkCorrupt:
                self.counters["quarantined"] += 1
                if compute_fn is None:
                    raise
                # its durable copy proved unusable: whatever this run
                # adopted it as, it is now fresh work
                self.counters["recomputed"] += 1
                self._count(i, "fresh")
        elif compute_fn is None:
            raise ValueError(
                f"chunk store {self.root!r}: chunk {i} absent and no "
                "generator available to compute it"
            )
        g0, g1 = self.chunk_rows(i)
        block = compute_fn(g0, g1)
        self.write_chunk(i, block)
        self._count(i, "fresh")
        return block

    def iter_chunks(self, compute_fn: Optional[
            Callable[[int, int], Any]] = None
            ) -> Iterator[Tuple[int, int, Any]]:
        """Yield ``(g0, g1, csr_block)`` over every chunk in row order,
        loading (or generating) one at a time — the load → use → drop
        streaming contract; the caller owns budget charging because only
        it knows when the block is dropped."""
        for i in range(self.n_chunks):
            g0, g1 = self.chunk_rows(i)
            yield g0, g1, self.ensure_chunk(i, compute_fn)

    def adopt_durable(self) -> int:
        """Count every durable chunk as resumed WITHOUT loading it — a
        pre-ingested store (no generator) opening for a compute pass
        still reports honest section counters (missing chunks stay
        uncounted and fail typed at first access). Returns the count."""
        n = 0
        for i in range(self.n_chunks):
            if self.has_chunk(i):
                if i not in self._counted_as:
                    self._count(i, "resumed")
                n += 1
        return n

    # -- ingest ------------------------------------------------------------
    def ingest(self, compute_fn: Callable[[int, int], Any]) -> int:
        """Materialize every missing chunk from ``compute_fn(g0, g1)``
        (durable, resumable: chunks that already verify are skipped, so
        a SIGKILL mid-ingest resumes from the last fsynced chunk).
        Returns the number of chunks written this call."""
        from scconsensus_tpu.obs import trace as obs_trace
        from scconsensus_tpu.obs.live import active_recorder

        written = 0
        with obs_trace.span("stream_ingest", n_chunks=self.n_chunks):
            for i in range(self.n_chunks):
                if self.has_chunk(i):
                    # durable already: COUNT the resume without paying a
                    # verification read — the compute passes verify on
                    # their own loads (where a torn chunk can actually
                    # hurt), so ingest stays one write pass, not
                    # write+read
                    if i not in self._counted_as:
                        self._count(i, "resumed")
                    continue
                self.ensure_chunk(i, compute_fn)
                written += 1
                rec = active_recorder()
                if rec is not None:
                    rec.touch()  # ingest opens no sub-spans; mark progress
        return written
