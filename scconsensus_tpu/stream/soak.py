"""Runnable streaming-soak worker: the chaos harness's out-of-core
workload, and the brain10m bench's synthetic generator.

    python -m scconsensus_tpu.stream.soak --dir DIR [--cells N]
        [--genes G] [--clusters K] [--seed S] [--window W]
        [--budget-mb MB] [--stage-budget-mb MB] [--summary PATH]
        [--fresh]

Builds (or resumes) a deterministic chunked synthetic dataset under
``DIR/chunks`` — every chunk is a pure function of (seed, row range),
so a quarantined chunk regenerates byte-identically and a killed ingest
resumes into the same matrix — then runs the full out-of-core
``streaming_refine`` with ``DIR/stages`` as the resumable progress
store, and writes one summary JSON. The exit code IS the chaos
contract:

  0  the run completed all chunks, the run record (streaming +
     robustness sections included) validates, and labels were produced
     for every deepSplit;
  1  the contract broke.

Because generation, chunking, and every stage are seeded and
deterministic, ``labels_sha`` is a pure function of (seed, shape): the
kill/torn-chunk chaos plans pin a resumed or quarantine-recomputed
run's sha equal to an uninterrupted reference run's.

:func:`chunk_generator` is also the **brain10m generator** — bench.py
scales the same planted-marker shape to 10M cells without ever holding
more than one gene window in memory.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import sys
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

__all__ = ["chunk_generator", "truth_labels", "run_stream_soak", "main"]


def truth_labels(n_cells: int, n_clusters: int, seed: int) -> np.ndarray:
    """Planted per-cell cluster assignment (int, 0..K-1) — O(N) memory,
    deterministic, shared by the generator and the consensus input."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0xCE11]))
    return rng.integers(0, n_clusters, size=n_cells).astype(np.int32)


def chunk_generator(
    n_genes: int, n_cells: int, n_clusters: int, seed: int,
    density: float = 0.25, marker_frac: float = 0.6,
) -> Callable[[int, int], Any]:
    """``fn(g0, g1) -> scipy CSR block`` of planted-marker expression.

    Gene ``g`` is a marker of cluster ``g % K``: background entries at
    ``density/2`` over all cells, elevated entries over ``marker_frac``
    of the marker cluster's cells. Each ROW's randomness is seeded by
    ``(seed, g)`` alone — a chunk (and therefore the whole matrix) is a
    pure function of the seed and the row range, independent of chunk
    boundaries, so window halvings, resumes, and quarantine recomputes
    all regenerate byte-identical rows.
    """
    import scipy.sparse as sp

    truth = truth_labels(n_cells, n_clusters, seed)
    cells_of = [np.nonzero(truth == k)[0] for k in range(n_clusters)]

    def gen(g0: int, g1: int):
        rows, cols, vals = [], [], []
        for g in range(g0, g1):
            rng = np.random.default_rng(np.random.SeedSequence([seed, g]))
            n_bg = max(int(n_cells * density * 0.5), 4)
            bg_cols = rng.integers(0, n_cells, size=n_bg)
            bg_vals = rng.gamma(2.0, 0.4, size=n_bg).astype(np.float32)
            own = cells_of[g % n_clusters]
            n_hi = max(int(own.size * marker_frac), 1)
            hi_cols = rng.choice(own, size=min(n_hi, own.size),
                                 replace=False)
            hi_vals = (1.0 + rng.gamma(3.0, 0.8, size=hi_cols.size)
                       ).astype(np.float32)
            r = g - g0
            rows.append(np.full(bg_cols.size + hi_cols.size, r, np.int64))
            cols.append(np.concatenate([bg_cols, hi_cols]))
            vals.append(np.concatenate([bg_vals, hi_vals]))
        m = sp.coo_matrix(
            (np.concatenate(vals),
             (np.concatenate(rows), np.concatenate(cols))),
            shape=(g1 - g0, n_cells),
        ).tocsr()
        m.sum_duplicates()
        return m

    return gen


def consensus_input(n_cells: int, n_clusters: int, seed: int) -> np.ndarray:
    """The noisy consensus labeling handed to the refine (string labels,
    5 % flips off the planted truth — the same shape the other bench
    configs feed)."""
    from scconsensus_tpu.utils.synthetic import noisy_labeling

    return noisy_labeling(truth_labels(n_cells, n_clusters, seed),
                          0.05, seed=seed + 1)


def _labels_sha(dynamic_labels: Dict[str, np.ndarray]) -> str:
    h = hashlib.sha256()
    for key in sorted(dynamic_labels):
        h.update(key.encode())
        h.update(np.asarray(dynamic_labels[key], np.int64).tobytes())
    return h.hexdigest()


def run_stream_soak(
    workdir: str, n_cells: int = 4000, n_genes: int = 160,
    n_clusters: int = 4, seed: int = 7, window: Optional[int] = None,
    budget_mb: Optional[float] = None,
    stage_budget_mb: Optional[float] = None,
    fresh: bool = False,
) -> Dict[str, Any]:
    """One deterministic out-of-core run; returns the summary dict (see
    module doc)."""
    from scconsensus_tpu.config import ReclusterConfig, env_flag
    from scconsensus_tpu.obs.export import (
        build_run_record,
        validate_run_record,
    )
    from scconsensus_tpu.stream.budget import HostBudgetAccountant
    from scconsensus_tpu.stream.runner import streaming_refine
    from scconsensus_tpu.stream.store import ChunkedCSRStore

    chunks_dir = os.path.join(workdir, "chunks")
    stages_dir = os.path.join(workdir, "stages")
    if fresh:
        for d in (chunks_dir, stages_dir):
            shutil.rmtree(d, ignore_errors=True)
    win = int(window if window is not None else
              min(int(env_flag("SCC_STREAM_WINDOW")), 32))
    store = ChunkedCSRStore.create(chunks_dir, n_genes, n_cells, win)
    gen = chunk_generator(n_genes, n_cells, n_clusters, seed)
    labels = consensus_input(n_cells, n_clusters, seed)
    config = ReclusterConfig(
        method="wilcox", q_val_thrs=0.1, log_fc_thrs=0.25, min_pct=5.0,
        deep_split_values=(1, 2), min_cluster_size=10,
        n_top_de_genes=20, random_seed=seed,
    )
    acct = HostBudgetAccountant(budget_mb=budget_mb,
                                stage_budget_mb=stage_budget_mb)
    t0 = time.perf_counter()
    result = streaming_refine(
        store, labels, config, stage_dir=stages_dir, accountant=acct,
        regen=gen,
    )
    wall = time.perf_counter() - t0
    section = result.metrics["streaming"]
    rb = result.metrics.get("robustness")
    rec = build_run_record(
        metric=f"stream soak: {n_cells}-cell out-of-core refine",
        value=round(wall, 3), unit="seconds",
        extra={"config": "stream-soak", "platform": "cpu",
               "n_cells": n_cells, "n_genes": n_genes},
        spans=result.metrics.get("spans") or [],
        streaming=section,
        robustness=rb,
    )
    accounting_ok = True
    invalid = None
    try:
        validate_run_record(rec)
    except ValueError as e:
        accounting_ok = False
        invalid = str(e)
    have_all_cuts = all(
        f"deepsplit: {d}" in result.dynamic_labels
        for d in config.deep_split_values
    )
    ok = bool(accounting_ok and section.get("complete") and have_all_cuts)
    return {
        "ok": ok,
        "invalid": invalid,
        "wall_s": round(wall, 3),
        "labels_sha": _labels_sha(result.dynamic_labels),
        "chunks": section["chunks"],
        "halvings": section["window"]["halvings"],
        "window_final": section["window"]["final_rows"],
        "ckpt_final": section["ckpt"]["final_every"],
        "within_budget": section["budget"]["within_budget"],
        "peak_rss_mb": section["budget"]["peak_rss_mb"],
        "de_resumed": bool((rb or {}).get("resume_points")),
        "record": rec,
    }


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description="streaming soak worker")
    ap.add_argument("--dir", required=True, help="work directory")
    ap.add_argument("--cells", type=int, default=4000)
    ap.add_argument("--genes", type=int, default=160)
    ap.add_argument("--clusters", type=int, default=4)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--window", type=int, default=None)
    ap.add_argument("--budget-mb", type=float, default=None)
    ap.add_argument("--stage-budget-mb", type=float, default=None)
    ap.add_argument("--summary", default=None)
    ap.add_argument("--fresh", action="store_true")
    args = ap.parse_args(argv)

    summary_path = args.summary or os.path.join(args.dir,
                                                "STREAM_SOAK_SUMMARY.json")
    os.makedirs(args.dir, exist_ok=True)
    summary = run_stream_soak(
        args.dir, n_cells=args.cells, n_genes=args.genes,
        n_clusters=args.clusters, seed=args.seed, window=args.window,
        budget_mb=args.budget_mb, stage_budget_mb=args.stage_budget_mb,
        fresh=args.fresh,
    )
    with open(summary_path, "w") as f:
        json.dump(summary, f, indent=1, default=str)
    print(json.dumps({
        "ok": summary["ok"],
        "chunks": summary["chunks"],
        "halvings": summary["halvings"],
        "within_budget": summary["within_budget"],
        "labels_sha": summary["labels_sha"][:16],
    }))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
