"""The host-memory budget accountant for out-of-core streaming.

Two budgets, one ledger:

  * ``SCC_STREAM_HOST_BUDGET_MB`` — the bound the RECORD is judged by:
    peak process RSS (kernel high-water mark, the same
    ``host_peak_rss_bytes`` the heartbeat stream and tail_run show)
    must stay under it for the run's ``streaming.budget.within_budget``
    claim to validate. Sampled on every charge; a breach raises typed
    :class:`HostBudgetExceeded` BEFORE the next allocation.
  * ``SCC_STREAM_STAGE_BUDGET_MB`` — the bound the streaming LAYER
    enforces on its own buffers (loaded CSR chunks, dense gene-window
    staging, the (N, n_pcs) score accumulator): every such buffer is
    ``charge()``d before allocation and ``release()``d when dropped, so
    a charge that would exceed the budget raises before the memory
    exists. This is the budget the window-halving degradation ladder
    converges against — it bounds what streaming ADDS to a process,
    independent of the interpreter/jax baseline the RSS budget must
    also cover.

The residency auditor's transfer events feed the ledger
(obs.residency.add_transfer_listener): staged bytes the audit saw cross
at ``input_staging``/``stream_block_fetch`` are tallied per boundary as
evidence that chunk staging actually follows the load → device → drop
contract. Self-measured (``consumed_s``) so the <2% zero-fault overhead
guard prices the accounting itself.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from scconsensus_tpu.config import env_flag

__all__ = ["MB", "HostBudgetExceeded", "HostBudgetAccountant"]

MB = 1 << 20


class HostBudgetExceeded(RuntimeError):
    """A typed streaming budget breach. ``kind`` says which bound broke:
    ``"staged"`` (the streaming layer's own buffers — recoverable by
    halving the window) or ``"rss"`` (whole-process high-water mark —
    recoverable the same way while the floor holds, then fatal).
    Carries the numbers so the recovery ladder can log an attributable
    degradation."""

    def __init__(self, kind: str, need_bytes: int, used_bytes: int,
                 limit_bytes: int, what: str = ""):
        self.kind = kind
        self.need_bytes = int(need_bytes)
        self.used_bytes = int(used_bytes)
        self.limit_bytes = int(limit_bytes)
        self.what = what
        super().__init__(
            f"host budget exceeded ({kind}): charging {need_bytes >> 20} "
            f"MB for {what or 'a streaming buffer'} on top of "
            f"{used_bytes >> 20} MB would pass the {limit_bytes >> 20} MB "
            "budget — halve the streaming window or raise "
            "SCC_STREAM_HOST_BUDGET_MB / SCC_STREAM_STAGE_BUDGET_MB"
        )


class HostBudgetAccountant:
    """Charge/release ledger for the streaming layer's host buffers.

    Thread-safe (the heartbeat sampler reads live). Use as a context
    manager: entry registers the live heartbeat feed + the residency
    transfer listener, exit deregisters both.
    """

    def __init__(self, budget_mb: Optional[float] = None,
                 stage_budget_mb: Optional[float] = None):
        from scconsensus_tpu.obs.device import host_peak_rss_bytes

        self.limit_bytes = int(
            float(budget_mb if budget_mb is not None
                  else env_flag("SCC_STREAM_HOST_BUDGET_MB")) * MB
        )
        self.stage_limit_bytes = int(
            float(stage_budget_mb if stage_budget_mb is not None
                  else env_flag("SCC_STREAM_STAGE_BUDGET_MB")) * MB
        )
        self.baseline_rss = host_peak_rss_bytes() or 0
        self.peak_rss = self.baseline_rss
        self.staged = 0
        self.peak_staged = 0
        self.charges: Dict[str, int] = {}
        self.transfers_by_boundary: Dict[str, Dict[str, int]] = {}
        self.consumed_s = 0.0
        self._progress: Dict[str, Any] = {}
        self._lock = threading.Lock()

    # -- the ledger --------------------------------------------------------
    def charge(self, nbytes: int, what: str) -> int:
        """Account ``nbytes`` of host memory about to be allocated for
        ``what``. Raises :class:`HostBudgetExceeded` BEFORE the caller
        allocates when either bound would break; on success returns the
        new staged total."""
        t0 = time.perf_counter()
        try:
            nbytes = int(nbytes)
            with self._lock:
                if self.staged + nbytes > self.stage_limit_bytes:
                    raise HostBudgetExceeded(
                        "staged", nbytes, self.staged,
                        self.stage_limit_bytes, what,
                    )
                self._sample_rss_locked()
                # enforcement reads the CURRENT rss (what halving can
                # actually lower); the record's within_budget claim is
                # judged by the monotone high-water mark sampled above —
                # in a dedicated worker process the two meet at the
                # streaming peak, in a long-lived host process only the
                # current value is actionable
                cur = self._current_rss()
                if cur + nbytes > self.limit_bytes:
                    raise HostBudgetExceeded(
                        "rss", nbytes, cur, self.limit_bytes, what,
                    )
                self.staged += nbytes
                self.peak_staged = max(self.peak_staged, self.staged)
                self.charges[what] = self.charges.get(what, 0) + nbytes
                return self.staged
        finally:
            self.consumed_s += time.perf_counter() - t0

    def release(self, nbytes: int, what: str) -> None:
        t0 = time.perf_counter()
        try:
            with self._lock:
                self.staged = max(self.staged - int(nbytes), 0)
                left = self.charges.get(what, 0) - int(nbytes)
                if left > 0:
                    self.charges[what] = left
                else:
                    self.charges.pop(what, None)
        finally:
            self.consumed_s += time.perf_counter() - t0

    def _sample_rss_locked(self) -> int:
        from scconsensus_tpu.obs.device import host_peak_rss_bytes

        rss = host_peak_rss_bytes() or 0
        self.peak_rss = max(self.peak_rss, rss)
        return rss

    @staticmethod
    def _current_rss() -> int:
        from scconsensus_tpu.obs.device import host_rss_bytes

        return host_rss_bytes() or 0

    def sample_rss(self) -> int:
        """Update (and return) the peak-RSS evidence — called at stage
        boundaries so the record's peak is the kernel's, not a tick
        sample's."""
        with self._lock:
            return self._sample_rss_locked()

    # -- residency feed ----------------------------------------------------
    def note_transfer(self, direction: str, nbytes: int,
                      boundary: Optional[str]) -> None:
        """Residency-auditor listener: tally audited transfer bytes per
        boundary — the evidence that staged chunks actually crossed to
        device and were dropped, not accumulated."""
        with self._lock:
            b = self.transfers_by_boundary.setdefault(
                boundary or "<undeclared>",
                {"to_device_bytes": 0, "to_host_bytes": 0},
            )
            key = ("to_host_bytes" if direction == "d2h"
                   else "to_device_bytes")
            b[key] += int(nbytes)

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self) -> "HostBudgetAccountant":
        from scconsensus_tpu.obs import residency
        from scconsensus_tpu.stream import record as stream_record

        residency.add_transfer_listener(self.note_transfer)
        stream_record.set_active(self.live_summary)
        return self

    def __exit__(self, *exc) -> None:
        from scconsensus_tpu.obs import residency
        from scconsensus_tpu.stream import record as stream_record

        residency.remove_transfer_listener(self.note_transfer)
        stream_record.set_active(None)

    # -- views -------------------------------------------------------------
    def live_summary(self) -> Dict[str, Any]:
        """Compact counters for one heartbeat tick (the tail_run
        streaming panel's feed); the runner annotates chunk progress in
        via :meth:`note_progress`."""
        with self._lock:
            out: Dict[str, Any] = {
                "staged_bytes": self.staged,
                "peak_staged_bytes": self.peak_staged,
                "peak_rss_bytes": self.peak_rss,
                "budget_bytes": self.limit_bytes,
            }
            out.update(self._progress)
            return out

    def note_progress(self, **kw: Any) -> None:
        """Runner hook: chunk counters for the live panel
        (chunks_done/chunks_planned/halvings/stage)."""
        with self._lock:
            self._progress.update(kw)

    def budget_fields(self) -> Dict[str, Any]:
        """The section builder's budget inputs (stream.record)."""
        with self._lock:
            self._sample_rss_locked()
            return {
                "limit_mb": self.limit_bytes / MB,
                "stage_limit_mb": self.stage_limit_bytes / MB,
                "baseline_rss_mb": self.baseline_rss / MB,
                "peak_rss_mb": self.peak_rss / MB,
                "peak_staged_mb": self.peak_staged / MB,
            }
