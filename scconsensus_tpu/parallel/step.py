"""One fused, jittable, mesh-sharded refinement step.

This is the framework's "training step" analog: every device-side stage of
`refine()` — per-cluster aggregates (cells `psum`ed over ICI), pair gates,
gene-sharded Wilcoxon, BH + DE call, and the ring silhouette over the
embedding — composed into a single jitted program over a `Mesh`. The driver's
`dryrun_multichip` compiles and runs exactly this on an N-virtual-device mesh;
the benchmark path runs it on real hardware.

One step body serves both forms: `distributed_refine_step` passes the
shard_map'd kernels, `fused_refine_step` the plain-jnp ones — so the
single-device and mesh paths cannot diverge.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from scconsensus_tpu.obs import trace as obs_trace
from scconsensus_tpu.ops.distance import distance_tile
from scconsensus_tpu.ops.gates import ClusterAggregates, compute_aggregates, pair_gates_fast
from scconsensus_tpu.ops.multipletests import bh_adjust_masked
from scconsensus_tpu.ops.pca import pca_scores
from scconsensus_tpu.ops.wilcoxon import wilcoxon_pairs_tile
from scconsensus_tpu.parallel.mesh import CELL_AXIS
from scconsensus_tpu.parallel.ring import _ring_sums_local
from scconsensus_tpu.parallel.sharded_de import _agg_local, _wilcox_local
from scconsensus_tpu.utils.jax_compat import shard_map

__all__ = ["distributed_refine_step", "fused_refine_step", "build_step_inputs"]


def _build_step(agg_fn, wilcox_fn, sil_fn, *, min_pct, log_fc_thrs, q_val_thrs, n_pcs):
    """The one step body. Kernel slots:
    agg_fn(data, onehot) -> ClusterAggregates;
    wilcox_fn(data, idx, m1, m2, n1, n2) -> log_p (B, G);
    sil_fn(scores, onehot) -> (N, K) per-cluster distance sums."""

    def step(data, onehot, pair_i, pair_j, idx, m1, m2, n1, n2):
        # 1. per-cluster aggregates (three matmuls against the one-hot)
        agg = agg_fn(data, onehot)
        # 2. gates for every pair (small replicated tensors)
        gate, log_fc, pct1, pct2 = pair_gates_fast(
            agg, pair_i, pair_j,
            min_pct=min_pct, min_diff_pct=-jnp.inf,
            log_fc_thrs=log_fc_thrs, mean_exprs_thrs=0.0,
        )
        # 3. rank-sum test (genes embarrassingly parallel)
        log_p = wilcox_fn(data, idx, m1, m2, n1, n2)
        # 4. BH over surviving genes + DE call (G-sized sort per pair)
        log_q = bh_adjust_masked(log_p, gate)
        de = gate & (log_q < jnp.log(jnp.float32(q_val_thrs)))
        # 5. embed on a fixed-size panel of the strongest DE genes — the
        #    static-shape stand-in for the data-dependent union, ranked by
        #    the pipeline's own criterion (per-gene best |logFC| among DE
        #    calls, de_gene_union's ordering); genes with no DE call rank
        #    after every DE gene. The real pipeline re-gathers on the exact
        #    union host-side between steps.
        de_score = jnp.max(jnp.where(de, jnp.abs(log_fc), -jnp.inf), axis=0)
        # Non-DE genes rank below every DE gene but among themselves by
        # expression (no-DE regimes must not embed an arbitrary index-order
        # panel); the +10 offset dominates the [0, 1) variance tiebreak.
        var = agg.sum_expm1.sum(axis=1)
        var_rank = var / (jnp.max(var) + 1e-30)
        score = jnp.where(jnp.isfinite(de_score), de_score + 10.0, var_rank)
        _, top_idx = jax.lax.top_k(score, min(64, data.shape[0]))
        scores = pca_scores(data[top_idx].T, n_pcs)
        # 6. silhouette sufficient statistics over the embedding
        sil_sums = sil_fn(scores, onehot)
        return {
            "de_mask": de,
            "log_q": log_q,
            "log_fc": log_fc,
            "de_counts": de.sum(axis=1),
            "scores": scores,
            "sil_sums": sil_sums,
            "counts": agg.counts,
        }

    jitted = jax.jit(step)

    def traced_step(*args, **kw):
        # one span per step invocation (submitted wall = dispatch; a
        # 'stage'-sync tracer leaves inner spans unsynced, so the jitted
        # program's async pipelining is untouched)
        with obs_trace.span("refine_step") as sp:
            # plan-injectable fault site (robust.faults): elastic/chaos
            # plans can kill the fused mesh program between steps
            from scconsensus_tpu.robust.faults import fault_point

            fault_point("refine_step")
            out = jitted(*args, **kw)
            sp["n_outputs"] = len(out)
            return out

    # preserve the jit surface the driver's compile checks use
    traced_step.lower = jitted.lower
    traced_step.__wrapped__ = jitted
    return traced_step


def fused_refine_step(
    *,
    min_pct: float = 20.0,
    log_fc_thrs: float = 0.5,
    q_val_thrs: float = 0.1,
    n_pcs: int = 8,
):
    """Single-device form — plain-jnp kernels in the step body. This is the
    flagship jittable forward step the driver compile-checks via
    ``__graft_entry__.entry``."""
    return _build_step(
        compute_aggregates,
        lambda data, idx, m1, m2, n1, n2: wilcoxon_pairs_tile(
            data, idx, m1, m2, n1, n2
        )[0],
        lambda scores, onehot: distance_tile(scores, scores) @ onehot,
        min_pct=min_pct, log_fc_thrs=log_fc_thrs,
        q_val_thrs=q_val_thrs, n_pcs=n_pcs,
    )


def distributed_refine_step(
    mesh: Mesh,
    axis_name: str = CELL_AXIS,
    *,
    min_pct: float = 20.0,
    log_fc_thrs: float = 0.5,
    q_val_thrs: float = 0.1,
    n_pcs: int = 8,
):
    """Mesh-sharded form. Returns step(data, onehot, pair_i, pair_j, idx,
    m1, m2, n1, n2) -> dict of device outputs.

    Shardings (all over the one mesh axis):
      data (G, N): genes for the test stage, cells for the aggregate stage —
        XLA inserts the single resharding collective between the two;
      onehot (N, K): cells; pair/bucket tensors: replicated;
      silhouette embedding: cells (ring ppermute).
    """
    n_shards = int(mesh.devices.size)

    raw_agg = shard_map(
        partial(_agg_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(None, axis_name), P(axis_name)),
        out_specs=(P(None),) * 5,
    )
    wilcox_fn = shard_map(
        _wilcox_local,
        mesh=mesh,
        in_specs=(P(axis_name), P(None), P(None), P(None), P(None), P(None)),
        out_specs=P(None, axis_name),
    )
    sil_fn = shard_map(
        partial(_ring_sums_local, axis_name=axis_name, n_shards=n_shards),
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name)),
        out_specs=P(axis_name),
    )

    def agg_fn(data, onehot):
        return ClusterAggregates(*raw_agg(data, onehot))

    return _build_step(
        agg_fn, wilcox_fn, sil_fn,
        min_pct=min_pct, log_fc_thrs=log_fc_thrs,
        q_val_thrs=q_val_thrs, n_pcs=n_pcs,
    )


def build_step_inputs(
    n_cells: int,
    n_genes: int,
    n_clusters: int,
    n_shards: int,
    pair_width: int = 32,
    seed: int = 0,
) -> Dict[str, np.ndarray]:
    """Tiny synthetic, shard-divisible inputs for compile checks/dry runs."""
    rng = np.random.default_rng(seed)
    n = n_cells + ((-n_cells) % n_shards)
    g = n_genes + ((-n_genes) % n_shards)
    data = np.log1p(
        rng.poisson(1.0, size=(g, n)).astype(np.float32)
    )
    labels = rng.integers(0, n_clusters, size=n)
    onehot = np.zeros((n, n_clusters), np.float32)
    onehot[np.arange(n), labels] = 1.0
    pi, pj = np.triu_indices(n_clusters, k=1)
    B = pi.size
    idx = np.zeros((B, pair_width), np.int32)
    m1 = np.zeros((B, pair_width), bool)
    m2 = np.zeros((B, pair_width), bool)
    n1 = np.zeros(B, np.int32)
    n2 = np.zeros(B, np.int32)
    for b in range(B):
        ci = np.nonzero(labels == pi[b])[0][: pair_width // 2]
        cj = np.nonzero(labels == pj[b])[0][: pair_width - pair_width // 2]
        idx[b, : ci.size] = ci
        idx[b, ci.size : ci.size + cj.size] = cj
        m1[b, : ci.size] = True
        m2[b, ci.size : ci.size + cj.size] = True
        n1[b], n2[b] = ci.size, cj.size
    return {
        "data": data,
        "onehot": onehot,
        "pair_i": pi.astype(np.int32),
        "pair_j": pj.astype(np.int32),
        "idx": idx,
        "m1": m1,
        "m2": m2,
        "n1": n1,
        "n2": n2,
    }
