"""Mesh construction and shape-padding helpers.

One 1-D mesh axis (default name ``"cells"``) covers every collective in the
package: cell-sharded reductions and ring distance rotation use it directly;
gene-sharded test batches reuse the same devices under the alias spec. On a
multi-host slice the same axis simply spans hosts (ICI within, DCN across);
nothing in the call sites changes — that is the point of mesh-based SPMD.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "make_mesh", "auto_mesh", "drain_if_cpu_mesh", "pad_axis_to_multiple",
    "pad_and_shard", "put_sharded", "require_dense", "CELL_AXIS",
    "mesh_shape_meta", "mesh_device_ids",
]

CELL_AXIS = "cells"


def mesh_device_ids(mesh: Optional[Mesh]) -> list:
    """Sorted device ids of a mesh (``[0]`` for the serial ``None`` path —
    the 1-device mesh equivalent, which is what a mesh run shrinks to)."""
    if mesh is None:
        return [0]
    return sorted(int(d.id) for d in mesh.devices.flat)


def mesh_shape_meta(mesh: Optional[Mesh],
                    axis_name: str = CELL_AXIS) -> dict:
    """JSON-able mesh-shape stamp for checkpoint/artifact sidecars — the
    provenance a shape-polymorphic resume reads to know which mesh the
    bytes were computed on (robust.elastic compares it against the
    resuming run's mesh and records the shrink as a mesh transition).
    ``None`` stamps the serial path as a 1-device shape."""
    if mesh is None:
        return {"n_devices": 1, "device_ids": [0], "axis": axis_name,
                "platform": None}
    devs = list(mesh.devices.flat)
    return {
        "n_devices": len(devs),
        "device_ids": sorted(int(d.id) for d in devs),
        "axis": str(mesh.axis_names[0]) if mesh.axis_names else axis_name,
        "platform": devs[0].platform if devs else None,
    }


def put_sharded(x, mesh: Mesh, spec):
    """device_put ``x`` with a NamedSharding over ``mesh``.

    The multi-host-correct upload: every process passes the same host value
    and receives the global array holding only its addressable shards —
    ``jnp.asarray`` would commit to local device 0, which a cross-process
    mesh cannot consume. Single-process it is equivalent (and pre-lays the
    data so jit skips a resharding copy)."""
    from jax.sharding import NamedSharding, PartitionSpec

    if isinstance(spec, str):  # a bare axis name is one axis, not characters
        spec = PartitionSpec(spec)
    elif not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec)
    return jax.device_put(x, NamedSharding(mesh, spec))


def pad_and_shard(x, mesh: Mesh, spec, shard_axis: int) -> Tuple[object, int]:
    """Lay ``x`` out sharded over ``mesh``, padding ``shard_axis`` up to the
    device count. Host numpy pads on host and uploads; a device-resident
    ``jax.Array`` pads and redistributes ON DEVICE via ``device_put`` with
    the target NamedSharding — no host round-trip, so the device-resident
    input path stays device-resident through the mesh engines (ADVICE r4).
    Returns (sharded, n_pad)."""
    import jax.numpy as jnp

    n_shards = int(mesh.devices.size)
    if isinstance(x, jax.Array) and not isinstance(x, np.ndarray):
        # Device-resident input never round-trips through host: pad/cast
        # stay jnp ops. The explicit sharded device_put is a single-process
        # optimization only — device_put of a committed array to a sharding
        # spanning non-addressable devices is rejected by JAX, so on a
        # multi-process mesh the global array is returned as-is and the
        # jitted shard_map lays it out (exactly the pre-existing device
        # path of sharded_allpairs_ranksum).
        n_pad = (-x.shape[shard_axis]) % n_shards
        if n_pad:
            widths = [(0, 0)] * x.ndim
            widths[shard_axis] = (0, n_pad)
            x = jnp.pad(x, widths)
        if x.dtype != jnp.float32:
            x = x.astype(jnp.float32)
        if jax.process_count() == 1:
            x = put_sharded(x, mesh, spec)
        return x, n_pad
    xp, n_pad = pad_axis_to_multiple(
        np.asarray(x, np.float32), shard_axis, n_shards
    )
    return put_sharded(xp, mesh, spec), n_pad


def auto_mesh(axis_name: str = CELL_AXIS) -> Optional[Mesh]:
    """The product pipeline's mesh policy: a 1-D mesh over every visible
    device when there is more than one, else None (serial single-device
    path). ``refine(mesh="auto")`` resolves through this."""
    devs = jax.devices()
    if len(devs) < 2:
        return None
    return Mesh(np.asarray(devs), (axis_name,))


def make_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = CELL_AXIS,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D device mesh over the first ``n_devices`` devices (default: all)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def drain_if_cpu_mesh(mesh: Mesh, *arrays) -> None:
    """Block until ``arrays`` are ready when the mesh devices are CPU.

    On virtual-CPU meshes (N devices emulated on few physical cores) XLA's
    in-process collectives can DEADLOCK when several collective programs are
    in flight: device threads blocked in one program's rendezvous starve the
    threads that would run the others' participants (observed: a 4000-cell
    mesh refine wedged in an 8-way all-gather with 4 arrivals; raising the
    rendezvous timeout only converts the abort into a hang). Draining after
    each sharded launch keeps at most one collective program in flight.
    Real accelerator meshes are untouched — async dispatch there is the
    point, and each device owns its core."""
    if mesh.devices.size and mesh.devices.flat[0].platform == "cpu":
        jax.block_until_ready(arrays)


def require_dense(*arrays) -> None:
    """The mesh-parallel entry points operate on device-resident dense
    arrays; reject scipy sparse input with a pointer to the serial engine
    (which densifies one gene chunk at a time) instead of letting np.asarray
    fail with an opaque ValueError."""
    from scconsensus_tpu.io.sparsemat import is_sparse

    for x in arrays:
        if is_sparse(x):
            raise TypeError(
                "mesh-parallel entry points require dense arrays; got a scipy "
                "sparse matrix — densify the relevant slice first, or use the "
                "serial engine (scconsensus_tpu.de.pairwise_de), which handles "
                "sparse input by densifying one gene chunk at a time"
            )


def pad_axis_to_multiple(
    x: np.ndarray, axis: int, multiple: int, fill=0
) -> Tuple[np.ndarray, int]:
    """Pad ``x`` along ``axis`` up to the next multiple. Returns (padded, n_pad)."""
    n = x.shape[axis]
    n_pad = (-n) % multiple
    if n_pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, n_pad)
    return np.pad(x, widths, constant_values=fill), n_pad
