"""Mesh-vs-serial equivalence contract, shared by the CI test
(tests/test_parallel.py) and the driver dry run (__graft_entry__) so the
two checks cannot drift apart."""

from __future__ import annotations

import numpy as np

__all__ = ["assert_mesh_equals_serial"]


def assert_mesh_equals_serial(mesh_res, serial_res) -> None:
    """Assert a mesh `refine()` result matches the serial run: test
    statistics to float tolerance, every discrete decision exactly."""
    np.testing.assert_allclose(
        mesh_res.de.log_p, serial_res.de.log_p, rtol=1e-4, atol=1e-4
    )
    assert np.array_equal(mesh_res.de.de_mask, serial_res.de.de_mask)
    assert np.array_equal(
        mesh_res.de_gene_union_idx, serial_res.de_gene_union_idx
    )
    for key in mesh_res.dynamic_labels:
        assert np.array_equal(
            mesh_res.dynamic_labels[key], serial_res.dynamic_labels[key]
        )
    # silhouette rode the ring engine on the mesh run; values must agree
    for a, b in zip(mesh_res.deep_split_info, serial_res.deep_split_info):
        if "silhouette" in a:
            assert abs(a["silhouette"] - b["silhouette"]) < 1e-4
