"""Ring-rotation distance collectives (the ring-attention pattern for cells).

The reference's scaling wall is the dense N×N distance matrix
(R/reclusterDEConsensus.R:236; SURVEY.md §5.7). Here the matrix never exists:
cells are sharded into blocks across the mesh, and each step of a ring loop
computes one (local block × visiting block) distance tile, folds it into a
running per-cluster accumulator, and `ppermute`s the visiting block to the
next device over ICI. Communication volume per device is O(N·d) total —
independent of N² — and compute overlaps the permute under XLA's scheduler.

The accumulator here is the silhouette sufficient statistic Σ_j∈cluster d(i,j)
(reference N8, cluster::silhouette, R/reclusterDEConsensusFast.R:433); other
consumers (k-NN for approximate linkage) reuse the same ring with a different
fold.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from scconsensus_tpu.parallel.mesh import (
    CELL_AXIS,
    make_mesh,
    pad_axis_to_multiple,
    require_dense,
)

__all__ = [
    "ring_cluster_distance_sums",
    "sharded_silhouette_widths",
    "ring_knn",
]


def _vary(x, axis_name: str):
    """Mark a freshly-created carry as device-varying for shard_map's
    varying-manual-axes check (loop carries must match output types)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis_name,), to="varying")
    if hasattr(jax.lax, "pvary"):  # pragma: no cover - older API name
        return jax.lax.pvary(x, (axis_name,))
    return x  # pragma: no cover - very old JAX without the check


from scconsensus_tpu.ops.distance import distance_tile as _dist_tile
from scconsensus_tpu.utils.jax_compat import shard_map


def _ring_sums_local(x_loc, oh_loc, axis_name: str, n_shards: int):
    """Per-device body: accumulate Σ_cluster distances from local cells to ALL
    cells by rotating (block, onehot) around the ring ``n_shards`` times."""
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def body(_, carry):
        y, oy, acc = carry
        acc = acc + _dist_tile(x_loc, y) @ oy
        y = jax.lax.ppermute(y, axis_name, perm)
        oy = jax.lax.ppermute(oy, axis_name, perm)
        return (y, oy, acc)

    acc0 = _vary(jnp.zeros((x_loc.shape[0], oh_loc.shape[1]), x_loc.dtype), axis_name)
    _, _, acc = jax.lax.fori_loop(0, n_shards, body, (x_loc, oh_loc, acc0))
    return acc


def ring_cluster_distance_sums(
    x: np.ndarray,
    onehot: np.ndarray,
    mesh: Optional[Mesh] = None,
    axis_name: str = CELL_AXIS,
) -> np.ndarray:
    """(N, K) summed distance from every cell to every cluster, cell-sharded.

    x: (N, d) embedding; onehot: (N, K) membership (zero rows allowed — e.g.
    padding or unassigned cells contribute to no cluster).
    """
    require_dense(x, onehot)
    mesh = mesh or make_mesh(axis_name=axis_name)
    # mid-engine fault site: a device_loss here models a chip dying in
    # the ring rotation (the silhouette stage guard's supervisor recovers)
    from scconsensus_tpu.robust.faults import fault_point

    fault_point("ring:distance_sums")
    n_shards = mesh.devices.size
    n = x.shape[0]
    xp, _ = pad_axis_to_multiple(np.asarray(x, np.float32), 0, n_shards)
    op, _ = pad_axis_to_multiple(np.asarray(onehot, np.float32), 0, n_shards)
    sums = _jitted_ring_sums(mesh, axis_name)(jnp.asarray(xp), jnp.asarray(op))
    return np.asarray(sums)[:n]


@lru_cache(maxsize=32)
def _jitted_ring_sums(mesh: Mesh, axis_name: str):
    """Jitted ring-sum wrapper, cached per (mesh, axis) so repeat calls hit
    the jit cache instead of re-tracing and re-compiling."""
    n_shards = mesh.devices.size
    return jax.jit(
        shard_map(
            partial(_ring_sums_local, axis_name=axis_name, n_shards=n_shards),
            mesh=mesh,
            in_specs=(P(axis_name), P(axis_name)),
            out_specs=P(axis_name),
        )
    )


def sharded_silhouette_widths(
    x: np.ndarray,
    labels: np.ndarray,
    mesh: Optional[Mesh] = None,
    axis_name: str = CELL_AXIS,
) -> np.ndarray:
    """Per-cell silhouette widths via the ring engine; label < 0 → NaN.

    Matches ops.silhouette.silhouette_widths (cluster::silhouette semantics)
    but scales across the mesh: no device ever holds more than N/n_shards
    rows of distance work.
    """
    require_dense(x)
    labels = np.asarray(labels)
    n = labels.shape[0]
    valid = labels >= 0
    out = np.full(n, np.nan, np.float32)
    uniq, inv_all = np.unique(labels[valid], return_inverse=True)
    k = uniq.size
    if k < 2:
        return out
    onehot = np.zeros((n, k), np.float32)
    onehot[np.nonzero(valid)[0], inv_all] = 1.0
    sums = ring_cluster_distance_sums(x, onehot, mesh, axis_name)  # (N, K)
    counts = onehot.sum(axis=0)  # (K,)
    from scconsensus_tpu.ops.silhouette import widths_from_cluster_sums

    iv = np.nonzero(valid)[0]
    out[iv] = widths_from_cluster_sums(sums[iv], counts, inv_all)
    return out


def _ring_knn_local(x_loc, idx_loc, kk: int, axis_name: str, n_shards: int):
    """Per-device body: running k-NN (distances, global indices) over the ring."""
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    nl = x_loc.shape[0]
    big = jnp.float32(jnp.inf)

    def body(_, carry):
        y, yidx, best_d, best_i = carry
        d = _dist_tile(x_loc, y)  # (Nl, Nb)
        # exclude self-pairs (same global index)
        same = idx_loc[:, None] == yidx[None, :]
        d = jnp.where(same, big, d)
        cat_d = jnp.concatenate([best_d, d], axis=1)
        cat_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(yidx[None, :], d.shape)], axis=1
        )
        top_d, top_pos = jax.lax.top_k(-cat_d, kk)
        new_d = -top_d
        new_i = jnp.take_along_axis(cat_i, top_pos, axis=1)
        y = jax.lax.ppermute(y, axis_name, perm)
        yidx = jax.lax.ppermute(yidx, axis_name, perm)
        return (y, yidx, new_d, new_i)

    best_d0 = _vary(jnp.full((nl, kk), big), axis_name)
    best_i0 = _vary(jnp.full((nl, kk), -1, jnp.int32), axis_name)
    _, _, bd, bi = jax.lax.fori_loop(
        0, n_shards, body, (x_loc, idx_loc, best_d0, best_i0)
    )
    return bd, bi


def ring_knn(
    x: np.ndarray,
    k: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = CELL_AXIS,
) -> Tuple[np.ndarray, np.ndarray]:
    """k nearest neighbors of every row of x (N, d) via the ring engine.

    Returns (distances (N, k), indices (N, k)); feeds the approximate-linkage
    path at 1M-cell scale (SURVEY.md §7 stage 6). Padding rows are excluded
    from results; self-neighbors are excluded. ``k`` must be < N (each row
    has only N−1 real neighbors).
    """
    require_dense(x)
    mesh = mesh or make_mesh(axis_name=axis_name)
    n_shards = mesh.devices.size
    n = x.shape[0]
    if k >= n:
        raise ValueError(f"k={k} must be < n_points={n} (self excluded)")
    xp, n_pad = pad_axis_to_multiple(np.asarray(x, np.float32), 0, n_shards)
    # padded rows carry index -2 (never matches a real self index) and +inf
    # coordinates would poison tiles; instead give them huge coordinates so
    # they are never anyone's neighbor.
    if n_pad:
        xp[n:] = 1e30
    gidx = np.arange(xp.shape[0], dtype=np.int32)
    gidx[n:] = -2
    bd, bi = _jitted_ring_knn(mesh, axis_name, int(k))(
        jnp.asarray(xp), jnp.asarray(gidx)
    )
    return np.asarray(bd)[:n], np.asarray(bi)[:n]


@lru_cache(maxsize=32)
def _jitted_ring_knn(mesh: Mesh, axis_name: str, kk: int):
    n_shards = mesh.devices.size
    return jax.jit(
        shard_map(
            partial(_ring_knn_local, kk=kk, axis_name=axis_name, n_shards=n_shards),
            mesh=mesh,
            in_specs=(P(axis_name), P(axis_name)),
            out_specs=(P(axis_name), P(axis_name)),
        )
    )
