"""Sharded DE engine stages: cell-sharded aggregates, gene-sharded tests.

Two sharding roles over the same 1-D mesh:
  * aggregates — the (G, N)·(N, K) reductions shard the contracted cells axis;
    each device reduces its cell block, `psum` over ICI completes it (the
    collective XLA would insert for a pjit with these shardings, written
    explicitly so multi-host behavior is pinned).
  * statistical tests — genes are embarrassingly parallel (the reference runs
    them in per-worker R loops, R/reclusterDEConsensusFast.R:78-91); sharding
    the gene-chunk axis keeps every device's sort local. BH afterwards needs a
    global sort over genes, so the per-device log-p slices are all-gathered.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from scconsensus_tpu.obs import trace as obs_trace
from scconsensus_tpu.obs.cost import attach_cost
from scconsensus_tpu.robust.faults import fault_point
from scconsensus_tpu.ops.gates import ClusterAggregates
from scconsensus_tpu.ops.wilcoxon import wilcoxon_pairs_tile
from scconsensus_tpu.parallel.mesh import (
    CELL_AXIS,
    drain_if_cpu_mesh,
    make_mesh,
    pad_and_shard,
    require_dense,
)
from scconsensus_tpu.utils.jax_compat import shard_map

__all__ = [
    "sharded_aggregates", "sharded_wilcox_logp", "sharded_allpairs_ranksum",
]


def _agg_local(data_loc, onehot_loc, axis_name: str):
    """data_loc (G, Nl), onehot_loc (Nl, K): partial reductions + psum.
    HIGHEST precision — the sums feed variance cancellations downstream."""
    hi = jax.lax.Precision.HIGHEST
    counts = jax.lax.psum(jnp.sum(onehot_loc, axis=0), axis_name)
    sum_log = jax.lax.psum(
        jnp.dot(data_loc, onehot_loc, precision=hi), axis_name
    )
    sum_expm1 = jax.lax.psum(
        jnp.dot(jnp.expm1(data_loc), onehot_loc, precision=hi), axis_name
    )
    sum_sq = jax.lax.psum(
        jnp.dot(data_loc * data_loc, onehot_loc, precision=hi), axis_name
    )
    nnz = jax.lax.psum(
        jnp.dot((data_loc > 0).astype(data_loc.dtype), onehot_loc,
                precision=hi),
        axis_name,
    )
    return sum_log, sum_expm1, sum_sq, nnz, counts


def sharded_aggregates(
    data: np.ndarray,
    onehot: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
    axis_name: str = CELL_AXIS,
    cid: Optional[np.ndarray] = None,
    n_clusters: Optional[int] = None,
) -> ClusterAggregates:
    """Cell-sharded ClusterAggregates (same result as ops.gates.compute_aggregates).

    data: (G, N) log-normalized; onehot: (N, K). Padding cells (zero onehot
    rows, zero data columns) do not perturb any statistic.

    Alternatively pass ``cid`` (N,) int32 per-cell cluster ids (−1 =
    excluded) + ``n_clusters`` instead of ``onehot``: each shard builds its
    local one-hot slice ON DEVICE, so the host never materializes or
    uploads the (N, K) membership matrix — the r6 fold of the engine's
    one-hot rebuild, mesh form (ids are 4 bytes/cell vs 4·K).
    """
    require_dense(data)
    mesh = mesh or make_mesh(axis_name=axis_name)
    with obs_trace.span(
        "sharded_aggregates", n_shards=int(mesh.devices.size),
    ) as sp:
        # plan-injectable mid-engine fault site (robust.faults): a
        # device_loss here models a chip dying inside the psum, and
        # propagates to the stage guard whose supervisor rebuilds the mesh
        fault_point("sharded:aggregates")
        # pad_and_shard keeps a device-resident jax.Array on device (pad +
        # redistribute in HBM); host numpy pads on host and uploads sharded
        # — on a multi-process mesh each process uploads only its
        # addressable cell blocks
        dp, _ = pad_and_shard(data, mesh, P(None, axis_name), 1)
        if cid is not None:
            if onehot is not None:
                raise ValueError("pass either onehot or cid, not both")
            if n_clusters is None:
                raise ValueError("cid form requires n_clusters")
            from scconsensus_tpu.parallel.mesh import put_sharded

            # pad with −1 (excluded), NOT 0 — a zero-padded id would count
            # the phantom cells into cluster 0
            cid_h = np.asarray(jax.device_get(cid), np.int32).ravel()
            n_pad = (-cid_h.size) % int(mesh.devices.size)
            if n_pad:
                cid_h = np.concatenate(
                    [cid_h, np.full(n_pad, -1, np.int32)]
                )
            cp = put_sharded(cid_h, mesh, P(axis_name))
            jitted = _jitted_aggregates_cid(mesh, axis_name, int(n_clusters))
            attach_cost(sp, jitted, dp, cp)
            out = jitted(dp, cp)
        else:
            require_dense(onehot)
            op, _ = pad_and_shard(onehot, mesh, P(axis_name), 0)
            jitted = _jitted_aggregates(mesh, axis_name)
            attach_cost(sp, jitted, dp, op)
            out = jitted(dp, op)
        drain_if_cpu_mesh(mesh, *out)
        return ClusterAggregates(*out)


@lru_cache(maxsize=32)
def _jitted_aggregates(mesh: Mesh, axis_name: str):
    """Cached jitted wrapper — repeat calls hit the jit cache, not a rebuild."""
    return jax.jit(
        shard_map(
            partial(_agg_local, axis_name=axis_name),
            mesh=mesh,
            in_specs=(P(None, axis_name), P(axis_name)),
            out_specs=(P(None),) * 5,
        )
    )


def _agg_local_cid(data_loc, cid_loc, axis_name: str, n_clusters: int):
    """cid form of ``_agg_local``: the local one-hot slice materializes on
    device only (Nl·K), never on host."""
    oh = (
        cid_loc[:, None] == jnp.arange(n_clusters, dtype=cid_loc.dtype)[None, :]
    ).astype(data_loc.dtype)
    return _agg_local(data_loc, oh, axis_name)


@lru_cache(maxsize=32)
def _jitted_aggregates_cid(mesh: Mesh, axis_name: str, n_clusters: int):
    return jax.jit(
        shard_map(
            partial(_agg_local_cid, axis_name=axis_name,
                    n_clusters=n_clusters),
            mesh=mesh,
            in_specs=(P(None, axis_name), P(axis_name)),
            out_specs=(P(None),) * 5,
        )
    )


def _wilcox_local(chunk_loc, idx, m1, m2, n1, n2):
    """Gene-sharded rank-sum: chunk_loc (Gl, N) local gene slice; pair-bucket
    tensors replicated. Pure local compute — genes never talk to each other."""
    log_p, _u, _ties = wilcoxon_pairs_tile(chunk_loc, idx, m1, m2, n1, n2)
    return log_p  # (B, Gl)


def sharded_allpairs_ranksum(
    chunk: jnp.ndarray,
    cid: jnp.ndarray,
    n_of: jnp.ndarray,
    pair_i: jnp.ndarray,
    pair_j: jnp.ndarray,
    n_clusters: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = CELL_AXIS,
    window: int = 0,
):
    """Gene-sharded all-pairs rank-sum (ops.ranksum_allpairs.ranksum_body
    shard_mapped over the gene-chunk axis; cid/pair tensors replicated).

    chunk: (Gc, N); returns (log_p, u, tie_sum), each (Gc, P) — identical to
    the single-device ``allpairs_ranksum_chunk``. The gene axis is padded to
    the shard count; padded all-zero rows produce NaN and are sliced off.
    ``window``: zero-block decomposition width (see ranksum_body) — genes
    are local to a shard, so the sparse-window mode shards unchanged. A 2-D
    pre-compacted (Gc, W) ``cid`` (CSR windows, r6) rides the same gene
    sharding as the chunk; a shared (N,) vector replicates. Gene-axis
    padding rows carry cid −1 (excluded) and all-zero values, so they are
    doubly inert: 2-D cid implies window mode, where zero-valued positions
    are masked out of every cluster before any statistic.
    """
    mesh = mesh or make_mesh(axis_name=axis_name)
    gc = chunk.shape[0]
    with obs_trace.span(
        "sharded_ranksum", n_shards=int(mesh.devices.size),
        n_genes=int(gc), window=int(window),
    ):
        # mid-engine fault site: fires per bucket, so a device_loss plan
        # can kill the mesh between completed (checkpointed) buckets
        fault_point("sharded:ranksum")
        # host input pads+uploads; device-resident input pads+redistributes
        # in HBM — either way the jitted shard_map sees a pre-laid-out
        # operand
        chunk, _ = pad_and_shard(chunk, mesh, P(axis_name, None), 0)
        cid_2d = getattr(cid, "ndim", 1) == 2
        if cid_2d:
            # int-preserving pad + upload: pad_and_shard casts to float32
            # (its data-tensor contract), which would hand the kernel float
            # cluster ids — pad the gene axis with −1 (excluded) rows and
            # shard as int32
            from scconsensus_tpu.parallel.mesh import put_sharded

            cid_h = np.asarray(jax.device_get(cid), np.int32)
            n_pad = (-cid_h.shape[0]) % int(mesh.devices.size)
            if n_pad:
                cid_h = np.pad(
                    cid_h, ((0, n_pad), (0, 0)), constant_values=-1
                )
            cid = put_sharded(cid_h, mesh, P(axis_name, None))
        jitted = _jitted_allpairs(mesh, axis_name, n_clusters, window,
                                  cid_2d)
        attach_cost(None, jitted, chunk, cid, n_of, pair_i, pair_j)
        lp, u, ts = jitted(chunk, cid, n_of, pair_i, pair_j)
        # virtual-CPU meshes deadlock with >1 collective program in flight
        drain_if_cpu_mesh(mesh, lp, u, ts)
        return lp[:gc], u[:gc], ts[:gc]


@lru_cache(maxsize=32)
def _jitted_allpairs(mesh: Mesh, axis_name: str, n_clusters: int,
                     window: int = 0, cid_2d: bool = False):
    from scconsensus_tpu.ops.ranksum_allpairs import ranksum_body

    def local(chunk_loc, cid, n_of, pair_i, pair_j):
        # cpu_forms=False: the scatter forms' mixed advanced indexing is
        # rejected inside shard_map on jax 0.4.x, and a sharded program is
        # the einsum-form case by design (TPU meshes) anyway
        return ranksum_body(chunk_loc, cid, n_of, pair_i, pair_j, n_clusters,
                            window=window, cpu_forms=False)

    cid_spec = P(axis_name, None) if cid_2d else P(None)
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis_name, None), cid_spec, P(None), P(None),
                      P(None)),
            out_specs=(P(axis_name, None),) * 3,
        )
    )


def sharded_wilcox_logp(
    data: np.ndarray,
    idx: np.ndarray,
    m1: np.ndarray,
    m2: np.ndarray,
    n1: np.ndarray,
    n2: np.ndarray,
    mesh: Optional[Mesh] = None,
    axis_name: str = CELL_AXIS,
) -> np.ndarray:
    """Rank-sum log-p for one pair bucket, genes sharded across the mesh.

    data: (G, N); idx/m1/m2: (B, W) gathered pair-cells; n1/n2: (B,).
    Returns (B, G) log p-values.
    """
    require_dense(data)
    mesh = mesh or make_mesh(axis_name=axis_name)
    G = data.shape[0]
    with obs_trace.span(
        "sharded_wilcox_logp", n_shards=int(mesh.devices.size),
        n_genes=int(G),
    ) as sp:
        # device-resident input pads/redistributes in HBM; host input
        # uploads
        dp, _ = pad_and_shard(data, mesh, P(axis_name, None), 0)
        # replicated small inputs stay host numpy: uncommitted values
        # replicate onto any mesh, where a jnp.asarray would commit to
        # local device 0 and be rejected by a cross-process jit
        args = (
            dp,
            np.asarray(idx, np.int32),
            np.asarray(m1),
            np.asarray(m2),
            np.asarray(n1, np.int32),
            np.asarray(n2, np.int32),
        )
        jitted = _jitted_wilcox(mesh, axis_name)
        attach_cost(sp, jitted, *args)
        log_p = jitted(*args)
        return np.asarray(log_p)[:, :G]


@lru_cache(maxsize=32)
def _jitted_wilcox(mesh: Mesh, axis_name: str):
    return jax.jit(
        shard_map(
            _wilcox_local,
            mesh=mesh,
            in_specs=(P(axis_name), P(None), P(None), P(None), P(None), P(None)),
            out_specs=P(None, axis_name),
        )
    )
