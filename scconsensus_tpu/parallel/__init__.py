"""Device-mesh parallelism: pjit/shard_map over ICI/DCN collectives.

The reference's entire distributed backend is a local R socket cluster fanning
the outer cluster-pair loop over worker processes
(R/reclusterDEConsensusFast.R:61-65,384; SURVEY.md §2b N10, §5.8). The
TPU-native equivalent is single-program SPMD over a `jax.sharding.Mesh`:

  * cells sharded across devices for aggregate reductions (`psum` over ICI)
    and for the N×N distance work (ring `ppermute` rotation of cell blocks —
    the ring-attention communication pattern with "distance tile + running
    accumulator" in place of "QKᵀ + softmax accumulator", SURVEY.md §5.7);
  * genes sharded for the embarrassingly-parallel statistical tests (the
    analog of the reference's per-worker gene loops);
  * multi-host DCN reuses the same mesh axes (devices spanning hosts).
"""

from scconsensus_tpu.parallel.mesh import make_mesh, pad_axis_to_multiple
from scconsensus_tpu.parallel.ring import (
    ring_cluster_distance_sums,
    sharded_silhouette_widths,
)
from scconsensus_tpu.parallel.sharded_de import (
    sharded_aggregates,
    sharded_wilcox_logp,
)
from scconsensus_tpu.parallel.step import distributed_refine_step

__all__ = [
    "make_mesh",
    "pad_axis_to_multiple",
    "ring_cluster_distance_sums",
    "sharded_silhouette_widths",
    "sharded_aggregates",
    "sharded_wilcox_logp",
    "distributed_refine_step",
]
