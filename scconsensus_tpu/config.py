"""Typed configuration for the refinement pipeline.

The reference scatters tunables across two function signatures with silently
divergent defaults (R/reclusterDEConsensus.R:20-29 vs
R/reclusterDEConsensusFast.R:22-33; SURVEY.md §5.6). Here there is ONE config
type with per-path presets, serializable next to artifacts.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple


# --------------------------------------------------------------------------
# SCC_* environment-flag registry
# --------------------------------------------------------------------------
# Every SCC_ env flag the package (and the bench/tools emitters) reads, in
# one place: name, type, default, one-line doc. Reads go through
# ``env_flag()`` so a typo'd or undeclared flag fails loudly instead of
# silently doing nothing; tests/test_env_registry.py greps the source tree
# and fails on any SCC_ literal not registered here.
#
# Bool parsing: unset/""/"0"/"false"/"off"/"no" → False, anything else →
# True ("SCC_STAGE_SYNC=0" now disables, where the old bare
# ``bool(os.environ.get(...))`` read any nonempty string as truthy).


@dataclasses.dataclass(frozen=True)
class EnvFlag:
    name: str
    type: type
    default: Any
    doc: str


_FALSY = ("", "0", "false", "off", "no", "none")

ENV_FLAGS: Dict[str, EnvFlag] = {
    f.name: f
    for f in [
        # --- observability (obs/) ---
        EnvFlag("SCC_TRACE_SYNC", str, "stage",
                "Tracer device-sync policy: 'stage' (drain at stage-span "
                "boundaries; default), 'all' (every span — diagnosis runs), "
                "'off' (dispatch intervals, the pre-obs behavior)."),
        EnvFlag("SCC_STAGE_SYNC", bool, False,
                "Legacy alias: force at least stage-boundary drains even "
                "when SCC_TRACE_SYNC=off."),
        EnvFlag("SCC_TRACE_DIR", str, None,
                "If set, refine() exports <dir>/run_record.json + "
                "<dir>/trace.json (Chrome trace events; open in Perfetto) "
                "at the end of every pipeline run."),
        EnvFlag("SCC_OBS_TRANSFERS", bool, False,
                "Wrap refine() in obs.device.TransferWatch: count explicit "
                "host<->device transfer bytes and flag oversized host "
                "fetches on the run record."),
        EnvFlag("SCC_OBS_COST", bool, False,
                "Attach XLA cost_analysis (FLOPs/bytes) to jitted kernel "
                "spans at trace time (obs.cost); one memoized AOT compile "
                "per kernel shape. bench.py workers enable it."),
        EnvFlag("SCC_OBS_HEARTBEAT", float, 0.0,
                "Live flight recorder (obs.live): heartbeat tick interval "
                "in seconds (0 = off). Each tick appends one JSONL line "
                "(open-span stack, RSS/HBM, compile stats) to the run's "
                "*_heartbeat.jsonl stream. bench.py workers default it on."),
        EnvFlag("SCC_OBS_STALL_S", float, 0.0,
                "In-process stall watchdog window (seconds; 0 = off): with "
                "no span transition / compile progress for this long, the "
                "recorder dumps all-thread stacks into the heartbeat "
                "stream, bumps the stall counter, and (with "
                "SCC_OBS_STALL_TRACE set) opens a profiler capture."),
        EnvFlag("SCC_OBS_STALL_TRACE", str, None,
                "Directory for on-demand jax.profiler capture windows "
                "(stall escalation and SIGUSR1 both write here; unset = "
                "no capture, stack dumps only)."),
        EnvFlag("SCC_EVIDENCE_DIR", str, None,
                "Evidence-ledger directory override (default <cwd>/evidence"
                "; bench.py anchors it next to itself). The test suite "
                "points it at a tmp dir."),
        EnvFlag("SCC_OBS_RESIDENCY", str, "off",
                "Host<->device residency auditor (obs.residency): 'off' "
                "(default), 'audit' (record every transfer with direction, "
                "bytes, owning span and source site onto the run record's "
                "residency section), 'enforce' (any crossing outside the "
                "declared boundary allowlist raises with the offending "
                "span named; jax.transfer_guard backs the patched entry "
                "points). bench.py workers default it to 'audit'."),
        EnvFlag("SCC_OBS_KERNELS", str, None,
                "Directory for a jax.profiler capture window around the "
                "pipeline (obs.kernels): device-op events are parsed from "
                "the trace, joined to tracer spans, and summarized as the "
                "run record's kernels section (top-K kernels by device "
                "time, achieved rates vs the cost model). Unset = off."),
        EnvFlag("SCC_OBS_NUMERIC", bool, False,
                "Numeric-health sentinels (obs.quality): cheap NaN/Inf "
                "guards at stage boundaries in the pipeline, the DE "
                "engine, and the NB driver. A tripped sentinel records "
                "the offending span + array name + count into span "
                "metrics and the run record's quality section. bench.py "
                "workers and tools/run_sparse_1m.py default it on."),
        EnvFlag("SCC_HOSTPROF", bool, False,
                "Host execution profiler (obs.hostprof): a sampling "
                "stack profiler on the run thread (folded stacks "
                "bucketed per stage span, classified into python / "
                "blocking_wait / compile / serialization causes) plus "
                "gc.callbacks pause accounting and an RSS/HBM memory "
                "timeline — landed as the run record's host_profile and "
                "memory_timeline sections. bench.py workers default it "
                "on."),
        EnvFlag("SCC_HOSTPROF_HZ", float, 50.0,
                "Sampling rate (Hz) of the SCC_HOSTPROF stack/memory "
                "sampler. 50 Hz = one _current_frames walk + one statm "
                "pread every 20 ms; overhead is pinned under the perf "
                "gate's 50 ms noise floor by test."),
        EnvFlag("SCC_COMPILELOG", bool, False,
                "Per-stage JAX compile/retrace telemetry "
                "(obs.compilelog): jax.monitoring compile events stamped "
                "with the ambient stage span and its entry ordinal, "
                "aggregated (compiles, retraces, cache hits, compile "
                "wall) into the run record's compile section. bench.py "
                "workers default it on."),
        EnvFlag("SCC_COMPILELOG_MAX_EVENTS", int, 65536,
                "Cap on buffered compile/cache events per process "
                "(obs.device): past the cap new events are dropped "
                "rather than grow the buffer unboundedly in a "
                "pathological retrace storm."),
        EnvFlag("SCC_GRAPHS", bool, False,
                "Compiled-program observatory (obs.graphs): capture a "
                "graph passport (op census, transfer ops, host "
                "callbacks, donation hits/misses, fusion count, "
                "XLA-estimated buffer bytes) for every instrumented "
                "jitted stage program on its first call per abstract "
                "signature, landed as the run record's graphs section. "
                "bench.py workers default it on; serve never arms it "
                "(capture lowers+compiles an AOT copy of each "
                "program)."),
        EnvFlag("SCC_GRAPHS_MAX_PROGRAMS", int, 256,
                "Cap on captured graph passports per process "
                "(obs.graphs): past the cap further programs are "
                "dropped with a section error note rather than grow "
                "capture cost unboundedly under a retrace storm."),
        # --- tree stage (landmark recluster, ROADMAP item 1) ---
        EnvFlag("SCC_TREE_LANDMARK_THRESHOLD", int, 200_000,
                "Cell count above which the pooled tree stage switches "
                "from the full-data Lloyd to the landmark recluster path "
                "(sketch-fitted k-means, Ward on k ≪ N landmarks, device "
                "nearest-landmark cut propagation). Runs at or below the "
                "threshold keep the pre-r7 byte-identical behavior. "
                "ReclusterConfig.landmark_threshold overrides when set."),
        EnvFlag("SCC_TREE_LANDMARK_K", int, None,
                "Explicit landmark count for the landmark tree path "
                "(unset = the N-scaled policy clamp(c·√N, k_min, k_max); "
                "see SCC_TREE_LANDMARK_C and the BASELINE.md landmark "
                "policy section)."),
        EnvFlag("SCC_TREE_LANDMARK_C", float, None,
                "Landmark k-policy scale factor c in "
                "k = clamp(c·√N, k_min, k_max) when "
                "ReclusterConfig.landmark_c is unset (config wins; "
                "both unset = 2.0)."),
        EnvFlag("SCC_TREE_EXACT", bool, False,
                "Exact-fallback override: disable the landmark tree path "
                "at any N and run the pre-r7 behavior (full-data pooled "
                "Lloyd above approx_threshold, exact Ward below) — the "
                "escape hatch if a landmark cut looks wrong."),
        # --- robustness (robust/) ---
        EnvFlag("SCC_FAULT_PLAN", str, None,
                "Path to a JSON fault-injection plan (robust.faults): "
                "deterministic, seeded injection of named fault classes "
                "(oom|transient|kill|stall|corrupt) at named sites — "
                "pipeline stage boundaries, wilcox ladder buckets, "
                "artifact writes. Unset = no injection (and near-zero "
                "overhead at every fault point)."),
        EnvFlag("SCC_ROBUST_BUDGET", int, 16,
                "Per-run retry budget shared by every robust.retry call "
                "site: once this many retries have been consumed, further "
                "transient/resource failures re-raise instead of "
                "retrying (a retry storm becomes a clean failure)."),
        EnvFlag("SCC_ROBUST_BACKOFF_S", float, 0.05,
                "Base backoff for robust.retry's exponential ladder "
                "(attempt n sleeps base*2^(n-1), capped, with "
                "deterministic +0-50% jitter). Tests shrink it; real "
                "device recovery may want 0.5-2 s."),
        EnvFlag("SCC_ROBUST_CHECKSUM", bool, True,
                "Content checksums on ArtifactStore artifacts: every "
                "save stamps a sha256 into the stage sidecar and every "
                "load verifies it — corrupt/truncated entries are "
                "QUARANTINED (renamed *.quarantined) and recomputed "
                "instead of crashing or silently loading garbage. Set 0 "
                "to skip verification (trusted store, max throughput)."),
        EnvFlag("SCC_ELASTIC", bool, True,
                "Elastic mesh execution (robust.elastic): the pipeline's "
                "sharded paths run under a mesh supervisor that "
                "classifies device-loss failures, rebuilds the mesh on "
                "surviving devices (8 → 4 → 2 → 1 shrink ladder on an "
                "indistinct loss), re-enters the stage from its last "
                "completed checkpoint, and stamps every transition into "
                "the validated robustness section. Set 0 for the "
                "pre-elastic behavior (a lost device kills the run)."),
        EnvFlag("SCC_ELASTIC_MIN_DEVICES", int, 1,
                "Floor of the elastic shrink ladder: a device loss that "
                "would leave fewer devices than this is FATAL instead of "
                "recovered (for workloads whose sharded working set "
                "genuinely needs a minimum aggregate HBM footprint)."),
        EnvFlag("SCC_INTEGRITY", str, "off",
                "Computation-integrity sentinels (robust.integrity, "
                "round 18): 'off' (default), 'audit' (algebraic "
                "invariant checks fused at stage boundaries + a seeded "
                "ghost-replay sample recomputed through the float64 "
                "host oracle, all recorded on the validated integrity "
                "run-record section), 'enforce' (a violation or replay "
                "mismatch raises typed silent_corruption, recovered by "
                "recompute-the-unit; repeated detection at one site "
                "evicts the suspect device via the elastic mesh)."),
        EnvFlag("SCC_INTEGRITY_TOL_SCALE", float, 1.0,
                "Scale factor on every integrity tolerance band "
                "(robust.integrity.TOLERANCES — per-check defaults in "
                "BASELINE.md). Raise it on backends whose float32 "
                "rounding is looser; tests shrink it to force "
                "detections."),
        EnvFlag("SCC_INTEGRITY_EVICT_THRESHOLD", int, 2,
                "Consecutive silent-corruption detections at one site "
                "before the retry policy escalates to its device-loss "
                "hook — the elastic mesh shrinks off the suspect chip "
                "(a chip that computes wrong gets evicted like one "
                "that died)."),
        EnvFlag("SCC_ROBUST_DE_CKPT", bool, True,
                "Mid-stage wilcox checkpointing: with an artifact store "
                "active, each completed window-ladder bucket persists "
                "its (log_p, u, ties) block so a kill mid-stage resumes "
                "from completed buckets instead of recomputing the whole "
                "DE stage. Set 0 to disable (store-less runs are always "
                "unaffected)."),
        # --- out-of-core streaming (stream/) ---
        EnvFlag("SCC_STREAM_HOST_BUDGET_MB", int, 4096,
                "Hard host-memory budget (MB) for out-of-core streaming "
                "runs (stream.budget): peak process RSS past it raises "
                "typed HostBudgetExceeded, recovered by halving the "
                "streaming gene window (floor 1 row, then typed "
                "failure). The run record's streaming section carries "
                "peak RSS vs this budget as the bounded-memory "
                "evidence — a record claiming within_budget without it "
                "is rejected."),
        EnvFlag("SCC_STREAM_STAGE_BUDGET_MB", int, 256,
                "Staged-bytes budget (MB) for the streaming layer's own "
                "host buffers (loaded CSR chunks, dense gene-window "
                "staging, the (N, n_pcs) score accumulator): a charge "
                "past it raises typed HostBudgetExceeded before the "
                "allocation, recovered by the same window-halving "
                "ladder. Tighter than the RSS budget by design — it "
                "bounds what the streaming layer ADDS to a process."),
        EnvFlag("SCC_STREAM_WINDOW", int, 64,
                "Row (gene) window of on-disk ChunkedCSRStore blocks "
                "written by stream ingestion — the durability/resume "
                "granule: a SIGKILL mid-ingest resumes from the last "
                "fully fsynced chunk. Smaller windows = finer resume, "
                "more files."),
        EnvFlag("SCC_STREAM_DIR", str, None,
                "Directory for the brain10m bench's chunked CSR store "
                "(unset = a per-run temp dir). Point it at persistent "
                "scratch to reuse the ingested chunks across bench "
                "runs — the steady-state measurement then prices the "
                "streaming refine, not the synthetic ingest."),
        # --- serving (serve/) ---
        EnvFlag("SCC_SERVE_MAX_BATCH", int, 512,
                "Serving micro-batch cell cap (serve.driver): the worker "
                "coalesces queued requests until this many cells or the "
                "batch window elapses; a single request larger than this "
                "is rejected typed at admission (split it client-side)."),
        EnvFlag("SCC_SERVE_QUEUE_CAP", int, 256,
                "Bounded admission queue capacity in REQUESTS: a submit "
                "at capacity raises typed QueueFull carrying a "
                "retry_after_s hint — backpressure, never unbounded "
                "growth."),
        EnvFlag("SCC_SERVE_BATCH_WINDOW_S", float, 0.002,
                "Micro-batch linger window: after the first request the "
                "worker waits up to this long for concurrent arrivals "
                "before dispatching the batch (latency floor vs "
                "throughput knob)."),
        EnvFlag("SCC_SERVE_DEADLINE_S", float, 30.0,
                "Default per-request deadline: overruns (queue wait or "
                "compute) resolve as typed DeadlineExceeded, never a "
                "silently late answer. Per-request override via "
                "submit(deadline_s=)."),
        EnvFlag("SCC_SERVE_BREAKER_THRESHOLD", int, 3,
                "Circuit breaker trip threshold: this many consecutive "
                "device-class failures (resource/transient/device_lost "
                "per the robust.retry classifier) open the breaker and "
                "route batches to the degraded-flagged host fallback."),
        EnvFlag("SCC_SERVE_BREAKER_COOLDOWN_S", float, 5.0,
                "Seconds an open breaker waits before half-open-probing "
                "the device path again (a probe success closes it, a "
                "failure re-opens and restarts the cooldown)."),
        EnvFlag("SCC_SERVE_DRIFT_FRAC", float, 0.5,
                "Drift-quarantine gate: a request whose fraction of "
                "cells past the model's calibrated foreign-cell distance "
                "threshold reaches this value gets NO labels — it is "
                "appended to the quarantine ledger and flagged "
                "quarantined. Values > 1 disable the gate."),
        EnvFlag("SCC_SERVE_DRIFT_MARGIN", float, 1.5,
                "Export-time drift calibration margin: the foreign-cell "
                "threshold is the training q99 nearest-landmark distance "
                "times this factor (stored in the frozen model)."),
        EnvFlag("SCC_SERVE_LEDGER_DIR", str, None,
                "Writable sidecar directory for the drift quarantine "
                "ledger (+ persisted quarantined-cell batches, the "
                "reconsensus loop's material). Takes precedence over the "
                "model-dir default — REQUIRED for drift evidence when the "
                "model dir is a frozen read-only mount, where the r15 "
                "default would silently leave no ledger at all."),
        EnvFlag("SCC_SERVE_LEDGER_MAX_CELLS", int, 100_000,
                "Cap on quarantined cells persisted to the ledger dir per "
                "server lifetime (ledger LINES keep appending past it; "
                "only the .npy cell payloads stop): the reconsensus "
                "material stays bounded under a drift storm."),
        # --- telemetry plane (serve/slo.py, obs/) ---
        EnvFlag("SCC_OBS_TRACE", bool, True,
                "Request tracing: mint a trace id at the wire front (or "
                "driver admission), propagate it through routing, the "
                "serve_request span, the response header/body "
                "(X-SCC-Trace-Id), the quarantine ledger row, and the "
                "heartbeat stream's recent-request ring — one id "
                "recovers a request's cross-process story (the "
                "postmortem bundle joins on it). Set 0 to run the "
                "plane dark (the obs-overhead gauge's baseline)."),
        EnvFlag("SCC_SLO_AVAIL_TARGET", float, 0.999,
                "Availability SLO target: the good share of non-client-"
                "fault wire outcomes (2xx good, 4xx excluded from the "
                "denominator, 5xx burn the error budget). Stamped onto "
                "the record's slo.objectives so the perf gate reads the "
                "record, never this process's env."),
        EnvFlag("SCC_SLO_P99_MS", float, 250.0,
                "Tail-latency SLO target (ms): the slo section's "
                "latency.met compares the measured p99 against it; the "
                "perf-gate slo lane fails a record whose own target is "
                "missed."),
        EnvFlag("SCC_SLO_WINDOWS_S", str, "300,3600",
                "Comma-separated trailing windows (seconds) for the "
                "multi-window SLO burn rates, computed from the same "
                "cumulative outcome counters the accounting contract "
                "validates (burn 1.0 = consuming the error budget "
                "exactly at the exhaust-by-window-end rate)."),
        EnvFlag("SCC_SLO_BURN_LIMIT", float, 14.4,
                "Burn-rate gate threshold (the classic fast-burn page "
                "level: 14.4x eats a 30-day budget in ~2 days): a "
                "record whose worst window burn exceeds its own "
                "declared limit FAILS the perf-gate slo lane."),
        # --- serving fleet (serve/fleet/) ---
        EnvFlag("SCC_FLEET_REPLICAS", int, 2,
                "Default replica count for serve.fleet.ReplicaPool: N "
                "ConsensusServer workers behind one shared admission "
                "layer with least-depth routing and per-replica circuit "
                "breakers."),
        EnvFlag("SCC_FLEET_WIRE_PORT", int, 0,
                "TCP port for the serve.fleet.wire HTTP front "
                "(0 = ephemeral; the bound port is WireFront.port)."),
        EnvFlag("SCC_FLEET_SWAP_DRAIN_S", float, 30.0,
                "Hot-swap drain budget: after the atomic cutover to the "
                "new model's replicas, each outgoing replica gets this "
                "long to finish its in-flight batches before its worker "
                "join is abandoned (requests still resolve typed)."),
        EnvFlag("SCC_FLEET_RECON_MIN_CELLS", int, 64,
                "Minimum accumulated quarantined cells before "
                "serve.fleet.reconsensus will run the mini-refine and "
                "produce an updated model (below it the loop reports "
                "insufficient evidence and leaves the ledger growing)."),
        # --- traffic control plane (serve/fleet/loadgen + autoscale) ---
        EnvFlag("SCC_LOADGEN_RPS", float, 20.0,
                "Open-loop load generator base arrival rate (requests/s) "
                "— the rate profile's 1.0x level; the spike/ramp peak is "
                "a multiple of it."),
        EnvFlag("SCC_LOADGEN_PROFILE", str, "steady",
                "Load-generator rate profile: steady|diurnal|spike|ramp "
                "(serve.fleet.loadgen.PROFILES)."),
        EnvFlag("SCC_LOADGEN_SEED", int, 7,
                "Seed for the load generator's arrival schedule and "
                "traffic-mix draw — the offered load is a pure function "
                "of (profile, rates, duration, seed)."),
        EnvFlag("SCC_LOADGEN_DURATION_S", float, 8.0,
                "Load-generator run length in seconds (the window the "
                "sustained-RPS-at-SLO headline is measured over)."),
        EnvFlag("SCC_AUTOSCALE_MIN", int, 1,
                "Autoscaler replica floor: scale-down never shrinks the "
                "active group below this many replicas."),
        EnvFlag("SCC_AUTOSCALE_MAX", int, 4,
                "Autoscaler replica ceiling: scale-up never grows the "
                "active group past this many replicas."),
        EnvFlag("SCC_AUTOSCALE_TICK_S", float, 0.25,
                "Autoscaler control-loop cadence in seconds (observe -> "
                "decide -> actuate once per tick)."),
        EnvFlag("SCC_AUTOSCALE_BURN_UP", float, 2.0,
                "Scale-up pressure threshold on the worst multi-window "
                "SLO burn rate (queue pressure is the other trigger; "
                "see serve.fleet.autoscale.AutoscalePolicy)."),
        EnvFlag("SCC_AUTOSCALE_BURN_DOWN", float, 0.25,
                "Scale-down eligibility: the worst burn rate must sit at "
                "or below this (and the queue at or below queue_low) for "
                "down_ticks consecutive ticks."),
        EnvFlag("SCC_AUTOSCALE_UP_TICKS", int, 2,
                "Consecutive pressured ticks before a scale-up actuates "
                "(hysteresis against one-tick blips)."),
        EnvFlag("SCC_AUTOSCALE_DOWN_TICKS", int, 8,
                "Consecutive idle ticks before a scale-down actuates — "
                "deliberately slower than scale-up (capacity is cheap, "
                "a breach is not)."),
        EnvFlag("SCC_AUTOSCALE_COOLDOWN_TICKS", int, 4,
                "Post-actuation cooldown in ticks during which no "
                "further scale action fires (with the streak thresholds, "
                "the no-flap guarantee)."),
        # --- DE engine ---
        EnvFlag("SCC_WILCOX_PROBE", bool, False,
                "Synced per-bucket occupancy DIAGNOSIS of the Wilcoxon "
                "window ladder (serializes dispatch; tied-run counts and a "
                "sort-only timing are fetched per bucket)."),
        EnvFlag("SCC_NO_RUNSPACE", bool, False,
                "Disable the CPU tied-run rank-sum kernel; pin the scan "
                "kernel on every backend (mesh-overhead comparisons)."),
        EnvFlag("SCC_EDGER_PROFILE", bool, False,
                "Per-phase synced wall-clocks for the NB/edgeR driver."),
        # --- bench.py harness ---
        EnvFlag("SCC_BENCH_CONFIG", str, "flagship",
                "Bench config: flagship|pbmc68k|cite8k|tm100k|brain1m|quick."),
        EnvFlag("SCC_BENCH_PLATFORM", str, None,
                "Pin the jax platform for bench runs (cpu|tpu)."),
        EnvFlag("SCC_BENCH_DEGRADED", bool, False,
                "Run the reduced-size CPU fallback shapes."),
        EnvFlag("SCC_BENCH_COLD", bool, False,
                "Report the cold-compile run instead of steady-state."),
        EnvFlag("SCC_BENCH_CELLS", int, None,
                "Override flagship n_cells."),
        EnvFlag("SCC_BENCH_GENES", int, None,
                "Override flagship n_genes."),
        EnvFlag("SCC_BENCH_CLUSTERS", int, None,
                "Override flagship n_clusters."),
        EnvFlag("SCC_BENCH_NO_FORK", bool, False,
                "Run the measurement in-process (no orchestrator)."),
        EnvFlag("SCC_BENCH_CRASH", str, None,
                "Inject a failure into one flagship section "
                "(edger|edger_steady|wilcox|mfu|pallas) — tests the "
                "partial-result contract."),
        EnvFlag("SCC_BENCH_TIMEOUT_SCALE", float, 1.0,
                "Scale every orchestrator attempt timeout (test hook)."),
        EnvFlag("SCC_BENCH_HANG", float, 0.0,
                "Worker sleeps this long before doing anything (test hook "
                "for the stall watchdog)."),
        EnvFlag("SCC_BENCH_STALL_S", float, 1200.0,
                "Abort an attempt after this long without worker progress."),
        EnvFlag("SCC_BENCH_HOST_GEN", bool, False,
                "Opt out of on-device synthetic data generation."),
        EnvFlag("SCC_BENCH_DEVICE_GEN", bool, False,
                "Force on-device synthetic data generation everywhere."),
        EnvFlag("SCC_BENCH_PALLAS", bool, False,
                "Run the pallas-vs-xla probe off-TPU too."),
        EnvFlag("SCC_BENCH_NO_CPU_FALLBACK", bool, False,
                "Accelerator-evidence mode: fail fast instead of rerouting "
                "to the CPU-degraded attempt."),
        EnvFlag("SCC_BENCH_CKPT", str, None,
                "Override the bench checkpoint file path."),
        EnvFlag("SCC_BENCH_LEDGER", bool, True,
                "Ingest the final bench record into the evidence ledger "
                "(set 0 to disable)."),
        EnvFlag("SCC_JAX_CACHE_DIR", str, None,
                "Override the persistent XLA compile-cache dir."),
        EnvFlag("SCC_TUNNEL_LOG", str, None,
                "Override the TUNNEL_LOG.jsonl path read by "
                "tunnel_probe --status and the bench tunnel stamp."),
        # --- tools/ ---
        EnvFlag("SCC_1M_CELLS", int, 1_000_000,
                "run_sparse_1m.py: cell count override (testing)."),
        EnvFlag("SCC_1M_GENES", int, 3000,
                "run_sparse_1m.py: gene count override (testing)."),
        EnvFlag("SCC_1M_PLATFORM", str, "cpu",
                "run_sparse_1m.py: jax platform for the run."),
        EnvFlag("SCC_WATCHER_DEADLINE", float, 0.0,
                "tpu_capture_watcher.sh: epoch-seconds deadline (0 = none)."),
        # --- tests ---
        EnvFlag("SCC_TEST_TPU", bool, False,
                "Run the test suite against the real chip instead of the "
                "CPU-pinned default."),
    ]
}


def env_flag(name: str, env: Optional[Mapping[str, str]] = None) -> Any:
    """Typed read of a registered SCC_* flag (KeyError on unregistered
    names — register in ENV_FLAGS first). Unset flags return the
    registered default; reads are dynamic (no import-time caching), so
    tests can monkeypatch the environment."""
    spec = ENV_FLAGS[name]
    raw = (os.environ if env is None else env).get(name)
    if raw is None:
        return spec.default
    if spec.type is bool:
        return raw.strip().lower() not in _FALSY
    if spec.type in (int, float):
        return spec.type(raw)
    return raw


@dataclasses.dataclass
class CompatFlags:
    """Reference-quirk switches (SURVEY.md §2d). ``True`` reproduces the
    reference's literal arithmetic; ``False`` applies the documented fix."""

    # §2d-1: reference edgeR path drops fold-changes (stored to a dead
    # variable), poisoning the DE mask with NA. Fixed mode uses edgeR's logFC
    # converted from log2 to natural log before thresholding.
    edger_drop_logfc: bool = False
    # §2d-3: slow path compares mean-of-logs against log(count-space threshold)
    # (R/reclusterDEConsensus.R:109-113). Fixed mode compares in one space.
    mean_gate_mixed_spaces: bool = True
    # §2d-4: BH with n = total gene count (slow path) vs n = surviving features
    # (fast path). True keeps each path's literal correction.
    bh_reference_n: bool = True
    # §2d-6: return the per-deepSplit silhouette (reference computes & drops it).
    return_silhouette: bool = True
    # The reference hands the *log-normalized* matrix to DGEList as counts
    # (R/reclusterDEConsensus.R:133). True keeps that literal arithmetic;
    # False tests on expm1(data) (count-scale, the statistically sane input).
    edger_log_counts: bool = True

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ReclusterConfig:
    """Configuration of the DE → embed → recluster refinement pipeline.

    Field provenance (reference defaults):
      slow path R/reclusterDEConsensus.R:20-29, fast path
      R/reclusterDEConsensusFast.R:22-33.
    """

    # --- DE testing ---
    method: str = "wilcox"  # wilcox | edger | bimod | roc | t
    q_val_thrs: float = 0.1
    # Natural-log fold-change threshold. The slow path passes a *ratio*
    # (`fcThrs`, thresholded as log(fcThrs)); we store the log-space value.
    log_fc_thrs: float = 0.5
    mean_scaling_factor: float = 5.0  # slow-path mean-expression gate scale
    mean_exprs_thrs: float = 0.0  # fast-path gate (Seurat MeanExprsThrs)
    min_pct: float = 20.0  # fast path: min % of cells expressing (minPerCent)
    min_diff_pct: float = -float("inf")
    # Pairs where either group has fewer cells are skipped with a recorded
    # reason (the reference's hard per-pair validation error,
    # R/reclusterDEConsensusFast.R:201-226, turned into a skip-and-flag).
    min_cells_group: int = 3
    pseudocount: float = 1.0
    max_cells_per_ident: Optional[int] = None  # subsample per group (seeded)
    random_seed: int = 1
    only_pos: bool = False
    n_top_de_genes: int = 30  # NumbertopDEGenes; slow path hard-codes 30

    # --- cluster filtering ---
    min_cluster_size: int = 10  # strictly-greater filter (§2d-7)
    drop_grey: bool = True  # 'grey' = unclustered (reference :48-49)

    # --- embed + recluster ---
    n_pcs: int = 15
    distance: str = "euclidean"  # euclidean | pearson (reference's commented alt)
    # linkage is always Ward.D2 (the only method the reference uses,
    # R/reclusterDEConsensus.R:242-246) — not a config knob.
    deep_split_values: Tuple[int, ...] = (1, 2, 3, 4)
    pam_stage: bool = False

    # --- scale-out ---
    approx_threshold: int = 100_000  # above this many cells, approximate linkage
    approx_method: str = "pool"  # pool (centroid pre-pooling) | knn (ring-kNN graph Ward)
    n_pool_centroids: int = 4096
    knn_graph_k: int = 15  # neighbors per cell for approx_method="knn"
    # --- landmark recluster (r7, ROADMAP item 1) ---
    # Above max(approx_threshold, landmark_threshold) the "pool" tree path
    # runs the landmark engine: k = clamp(landmark_c·√N, k_min, k_max)
    # landmarks fitted by device Lloyd on a seeded sketch, occupancy-
    # weighted Ward on the landmarks, one jitted nearest-landmark pass
    # propagating every cut to cells. At or below the threshold the
    # pre-r7 full-data Lloyd runs byte-identically. None fields defer to
    # the registered landmark flags in config.ENV_FLAGS.
    landmark_threshold: Optional[int] = None   # None → SCC_TREE_LANDMARK_THRESHOLD
    landmark_k: Optional[int] = None           # None → SCC_TREE_LANDMARK_K / policy
    landmark_c: Optional[float] = None         # None → SCC_TREE_LANDMARK_C / 2.0
    landmark_k_min: int = 512
    landmark_k_max: int = 4096
    landmark_sketch: Optional[int] = None      # None → sketch policy (~32·k)
    landmark_linkage: str = "exact"            # exact (native NN-chain) | knn (ring graph)
    # Diagnostic/test mode: additionally run the exact tree + cuts and
    # stamp per-deepSplit ARI(landmark, exact) into the tree telemetry —
    # the tier-1 accuracy pin reads this. O(N²) — mid-size runs only.
    landmark_verify: bool = False
    # Above approx_threshold the per-deepSplit silhouette switches to the
    # pooled O(N·m) estimator (ops.silhouette.pooled_multi_cut_silhouette,
    # reusing the tree stage's pool when one exists); below it the exact
    # O(N²) path runs unchanged. ``silhouette_sample`` caps the evaluated
    # rows (None = every cell; counts/cluster sizes always use all cells).
    silhouette_pool_centroids: int = 2048
    silhouette_sample: Optional[int] = None

    # --- misc ---
    compat: CompatFlags = dataclasses.field(default_factory=CompatFlags)
    artifact_dir: Optional[str] = None  # stage-keyed checkpoint store; None = off
    plot_name: Optional[str] = None  # DE heatmap output path; None = no plot

    @classmethod
    def slow_path_preset(cls, q_val_thrs: float, fc_thrs: float, **kw) -> "ReclusterConfig":
        """Reference slow-path defaults: method='Wilcoxon', meanScalingFactor=5,
        fcThrs given as a ratio (natural-log threshold = log(fcThrs))."""
        import math

        return cls(
            method=kw.pop("method", "wilcox"),
            q_val_thrs=q_val_thrs,
            log_fc_thrs=math.log(fc_thrs),
            min_pct=kw.pop("min_pct", 0.0),
            **kw,
        )

    @classmethod
    def fast_path_preset(cls, **kw) -> "ReclusterConfig":
        """Reference fast-path defaults (qValThrs=0.1, logFCThrs=0.5, minPerCent=20)."""
        return cls(**kw)

    def landmark_policy(self, n_cells: int) -> Optional[Dict[str, Any]]:
        """Resolved landmark-path decision for a run over ``n_cells``.

        Returns None when the landmark engine must NOT run (at/below the
        threshold, or SCC_TREE_EXACT forces the pre-r7 behavior);
        otherwise the resolved knobs: ``{threshold, k (None = policy at
        fit time), c, k_min, k_max, sketch, linkage}``. Config fields win
        over env flags; env flags fill unset fields; the registered
        defaults fill the rest — one resolution order for the pipeline,
        bench, and the 1M driver.
        """
        if env_flag("SCC_TREE_EXACT"):
            return None
        thr = self.landmark_threshold
        if thr is None:
            thr = env_flag("SCC_TREE_LANDMARK_THRESHOLD")
        thr = int(thr)
        if n_cells <= thr:
            return None
        k = self.landmark_k
        if k is None:
            k = env_flag("SCC_TREE_LANDMARK_K")
        c = self.landmark_c
        if c is None:
            c = env_flag("SCC_TREE_LANDMARK_C")
        if c is None:
            c = 2.0
        return {
            "threshold": thr,
            "k": int(k) if k else None,
            "c": float(c),
            "k_min": int(self.landmark_k_min),
            "k_max": int(self.landmark_k_max),
            "sketch": (int(self.landmark_sketch)
                       if self.landmark_sketch else None),
            "linkage": str(self.landmark_linkage),
            "knn_k": int(self.knn_graph_k),
        }

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["min_diff_pct"] = (
            None if self.min_diff_pct == -float("inf") else self.min_diff_pct
        )
        return json.dumps(d, indent=2, default=str)
