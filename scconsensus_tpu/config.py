"""Typed configuration for the refinement pipeline.

The reference scatters tunables across two function signatures with silently
divergent defaults (R/reclusterDEConsensus.R:20-29 vs
R/reclusterDEConsensusFast.R:22-33; SURVEY.md §5.6). Here there is ONE config
type with per-path presets, serializable next to artifacts.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Sequence, Tuple


@dataclasses.dataclass
class CompatFlags:
    """Reference-quirk switches (SURVEY.md §2d). ``True`` reproduces the
    reference's literal arithmetic; ``False`` applies the documented fix."""

    # §2d-1: reference edgeR path drops fold-changes (stored to a dead
    # variable), poisoning the DE mask with NA. Fixed mode uses edgeR's logFC
    # converted from log2 to natural log before thresholding.
    edger_drop_logfc: bool = False
    # §2d-3: slow path compares mean-of-logs against log(count-space threshold)
    # (R/reclusterDEConsensus.R:109-113). Fixed mode compares in one space.
    mean_gate_mixed_spaces: bool = True
    # §2d-4: BH with n = total gene count (slow path) vs n = surviving features
    # (fast path). True keeps each path's literal correction.
    bh_reference_n: bool = True
    # §2d-6: return the per-deepSplit silhouette (reference computes & drops it).
    return_silhouette: bool = True
    # The reference hands the *log-normalized* matrix to DGEList as counts
    # (R/reclusterDEConsensus.R:133). True keeps that literal arithmetic;
    # False tests on expm1(data) (count-scale, the statistically sane input).
    edger_log_counts: bool = True

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ReclusterConfig:
    """Configuration of the DE → embed → recluster refinement pipeline.

    Field provenance (reference defaults):
      slow path R/reclusterDEConsensus.R:20-29, fast path
      R/reclusterDEConsensusFast.R:22-33.
    """

    # --- DE testing ---
    method: str = "wilcox"  # wilcox | edger | bimod | roc | t
    q_val_thrs: float = 0.1
    # Natural-log fold-change threshold. The slow path passes a *ratio*
    # (`fcThrs`, thresholded as log(fcThrs)); we store the log-space value.
    log_fc_thrs: float = 0.5
    mean_scaling_factor: float = 5.0  # slow-path mean-expression gate scale
    mean_exprs_thrs: float = 0.0  # fast-path gate (Seurat MeanExprsThrs)
    min_pct: float = 20.0  # fast path: min % of cells expressing (minPerCent)
    min_diff_pct: float = -float("inf")
    # Pairs where either group has fewer cells are skipped with a recorded
    # reason (the reference's hard per-pair validation error,
    # R/reclusterDEConsensusFast.R:201-226, turned into a skip-and-flag).
    min_cells_group: int = 3
    pseudocount: float = 1.0
    max_cells_per_ident: Optional[int] = None  # subsample per group (seeded)
    random_seed: int = 1
    only_pos: bool = False
    n_top_de_genes: int = 30  # NumbertopDEGenes; slow path hard-codes 30

    # --- cluster filtering ---
    min_cluster_size: int = 10  # strictly-greater filter (§2d-7)
    drop_grey: bool = True  # 'grey' = unclustered (reference :48-49)

    # --- embed + recluster ---
    n_pcs: int = 15
    distance: str = "euclidean"  # euclidean | pearson (reference's commented alt)
    # linkage is always Ward.D2 (the only method the reference uses,
    # R/reclusterDEConsensus.R:242-246) — not a config knob.
    deep_split_values: Tuple[int, ...] = (1, 2, 3, 4)
    pam_stage: bool = False

    # --- scale-out ---
    approx_threshold: int = 100_000  # above this many cells, approximate linkage
    approx_method: str = "pool"  # pool (centroid pre-pooling) | knn (ring-kNN graph Ward)
    n_pool_centroids: int = 4096
    knn_graph_k: int = 15  # neighbors per cell for approx_method="knn"
    # Above approx_threshold the per-deepSplit silhouette switches to the
    # pooled O(N·m) estimator (ops.silhouette.pooled_multi_cut_silhouette,
    # reusing the tree stage's pool when one exists); below it the exact
    # O(N²) path runs unchanged. ``silhouette_sample`` caps the evaluated
    # rows (None = every cell; counts/cluster sizes always use all cells).
    silhouette_pool_centroids: int = 2048
    silhouette_sample: Optional[int] = None

    # --- misc ---
    compat: CompatFlags = dataclasses.field(default_factory=CompatFlags)
    artifact_dir: Optional[str] = None  # stage-keyed checkpoint store; None = off
    plot_name: Optional[str] = None  # DE heatmap output path; None = no plot

    @classmethod
    def slow_path_preset(cls, q_val_thrs: float, fc_thrs: float, **kw) -> "ReclusterConfig":
        """Reference slow-path defaults: method='Wilcoxon', meanScalingFactor=5,
        fcThrs given as a ratio (natural-log threshold = log(fcThrs))."""
        import math

        return cls(
            method=kw.pop("method", "wilcox"),
            q_val_thrs=q_val_thrs,
            log_fc_thrs=math.log(fc_thrs),
            min_pct=kw.pop("min_pct", 0.0),
            **kw,
        )

    @classmethod
    def fast_path_preset(cls, **kw) -> "ReclusterConfig":
        """Reference fast-path defaults (qValThrs=0.1, logFCThrs=0.5, minPerCent=20)."""
        return cls(**kw)

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["min_diff_pct"] = (
            None if self.min_diff_pct == -float("inf") else self.min_diff_pct
        )
        return json.dumps(d, indent=2, default=str)
