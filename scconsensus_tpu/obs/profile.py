"""Unified per-run profile + residency burn-down (ISSUE 18 tentpole).

The run record grew four disjoint perf sections — stage walls
(obs.trace spans), static FLOPs/bytes (obs.cost), device-kernel
timelines (obs.kernels), and host↔device crossings (obs.residency) —
and no tool joined them, so a regression read as "headline slower"
with the evidence scattered across sections that only a human could
correlate. This module computes the join once, at record-build time:

* :func:`build_profile` — one row per stage span unifying wall time,
  device time, cost-model FLOPs/bytes, achieved rates (vs. an optional
  measured ceiling), and transfer bytes, plus one row per declared
  residency boundary. Attached to records as the ``profile`` section.
* :func:`build_burndown` — the residency burn-down ledger: bytes
  crossed per declared boundary with the ``TODO(item-2)`` boundaries
  (the device-residency refactor's work list) totalled separately, so
  item 1's fusion progress is a ratcheting number, not a TODO grep.
  Attached as the ``residency_burndown`` section.

Both are pure functions of already-collected sections — no new
instrumentation runs, so the attribution overhead is a dict join
(pinned by test inside a noise band). Sections are additive
scc-run-record v1 extensions; ``export.validate_run_record`` calls the
validators here.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from scconsensus_tpu.obs.residency import BOUNDARIES

__all__ = [
    "ITEM2_BOUNDARIES",
    "build_profile",
    "build_burndown",
    "profile_sections_of",
    "validate_profile",
    "validate_residency_burndown",
]

PROFILE_VERSION = 1

# The device-residency refactor's work list: boundaries whose in-code
# justification carries a TODO(item-2) marker. Derived from the
# allowlist itself so declaring (or retiring) a boundary updates the
# burn-down denominator automatically — a hand-kept copy here would rot
# the first time residency.BOUNDARIES moves.
ITEM2_BOUNDARIES = frozenset(
    name for name, why in BOUNDARIES.items() if "TODO(item-2)" in why
)


def _stage_walls(spans: List[Dict[str, Any]]) -> Dict[str, float]:
    """Headline wall per stage name (synced preferred), repeated stages
    summed — mirrors ledger.stage_walls so profile rows and manifest
    stamps can never disagree on what a stage's wall is."""
    out: Dict[str, float] = {}
    for s in spans:
        if not isinstance(s, dict) or s.get("kind") != "stage":
            continue
        name = s.get("name")
        if not isinstance(name, str):
            continue
        wall = s.get("wall_synced_s")
        if wall is None:
            wall = s.get("wall_submitted_s")
        if isinstance(wall, (int, float)) and wall >= 0:
            out[name] = out.get(name, 0.0) + float(wall)
    return out


def build_profile(
    spans: Optional[List[Dict[str, Any]]],
    kernels: Optional[Dict[str, Any]] = None,
    cost: Optional[Dict[str, Dict[str, Any]]] = None,
    residency: Optional[Dict[str, Any]] = None,
    ceilings: Optional[Dict[str, float]] = None,
) -> Optional[Dict[str, Any]]:
    """Join the per-signal sections into one profile, or None when the
    run traced no stage spans (a profile of nothing would validate but
    mislead — absence means "no attribution ran", never zeros).

    ``kernels`` / ``cost`` / ``residency`` are the record sections of
    the same names (``cost`` in ``stage_cost_summary`` shape, i.e. the
    record's ``extra.stage_throughput``); any may be absent and its
    columns are simply omitted per stage. ``ceilings`` is an optional
    ``{"gflops": ..., "gbps": ...}`` measured-peak dict (bench's MFU
    probe); when given, stages with achieved rates gain
    ``pct_peak_flops`` / ``pct_peak_bw``.
    """
    walls = _stage_walls(spans or [])
    if not walls:
        return None
    cost = cost if isinstance(cost, dict) else {}
    vs_cost = {}
    if isinstance(kernels, dict):
        vs = kernels.get("vs_cost_model")
        if isinstance(vs, dict):
            vs_cost = vs
    by_stage_xfer = {}
    by_boundary = {}
    if isinstance(residency, dict):
        bs = residency.get("by_stage")
        if isinstance(bs, dict):
            by_stage_xfer = bs
        bb = residency.get("by_boundary")
        if isinstance(bb, dict):
            by_boundary = bb

    peak_gflops = peak_gbps = None
    if isinstance(ceilings, dict):
        v = ceilings.get("gflops")
        if isinstance(v, (int, float)) and v > 0:
            peak_gflops = float(v)
        v = ceilings.get("gbps")
        if isinstance(v, (int, float)) and v > 0:
            peak_gbps = float(v)

    stages: Dict[str, Dict[str, Any]] = {}
    tot_wall = tot_device = tot_flops = tot_bytes = 0.0
    tot_d2h = tot_h2d = 0
    for name in sorted(walls):
        row: Dict[str, Any] = {"wall_s": round(walls[name], 6)}
        tot_wall += walls[name]
        dev = vs_cost.get(name)
        if isinstance(dev, dict):
            dt = dev.get("device_time_s")
            if isinstance(dt, (int, float)) and dt >= 0:
                row["device_s"] = round(float(dt), 6)
                tot_device += float(dt)
        c = cost.get(name)
        if isinstance(c, dict):
            for k in ("flops", "bytes_accessed", "kernels",
                      "achieved_gflops", "achieved_gbps"):
                v = c.get(k)
                if isinstance(v, (int, float)):
                    row[k] = v
            tot_flops += float(c.get("flops") or 0)
            tot_bytes += float(c.get("bytes_accessed") or 0)
            if peak_gflops and isinstance(row.get("achieved_gflops"),
                                          (int, float)):
                row["pct_peak_flops"] = round(
                    100.0 * row["achieved_gflops"] / peak_gflops, 2
                )
            if peak_gbps and isinstance(row.get("achieved_gbps"),
                                        (int, float)):
                row["pct_peak_bw"] = round(
                    100.0 * row["achieved_gbps"] / peak_gbps, 2
                )
        x = by_stage_xfer.get(name)
        if isinstance(x, dict):
            d2h = int(x.get("to_host_bytes") or 0)
            h2d = int(x.get("to_device_bytes") or 0)
            row["to_host_bytes"] = d2h
            row["to_device_bytes"] = h2d
            row["transfer_calls"] = int(x.get("calls") or 0)
            tot_d2h += d2h
            tot_h2d += h2d
        stages[name] = row

    boundaries: Dict[str, Dict[str, Any]] = {}
    for name in sorted(by_boundary):
        d = by_boundary[name]
        if not isinstance(d, dict):
            continue
        boundaries[name] = {
            "to_host_bytes": int(d.get("to_host_bytes") or 0),
            "to_device_bytes": int(d.get("to_device_bytes") or 0),
            "calls": int(d.get("calls") or 0),
            "todo_item2": name in ITEM2_BOUNDARIES,
        }

    sec: Dict[str, Any] = {
        "version": PROFILE_VERSION,
        "stages": stages,
        "totals": {
            "wall_s": round(tot_wall, 6),
            "device_s": round(tot_device, 6),
            "flops": tot_flops,
            "bytes_accessed": tot_bytes,
            "to_host_bytes": tot_d2h,
            "to_device_bytes": tot_h2d,
        },
    }
    if boundaries:
        sec["boundaries"] = boundaries
    if peak_gflops or peak_gbps:
        ceil: Dict[str, float] = {}
        if peak_gflops:
            ceil["gflops"] = peak_gflops
        if peak_gbps:
            ceil["gbps"] = peak_gbps
        sec["ceilings"] = ceil
    return sec


def build_burndown(residency: Optional[Dict[str, Any]]
                   ) -> Optional[Dict[str, Any]]:
    """Residency burn-down ledger from a record's ``residency`` section:
    bytes crossed per declared boundary, with the ``TODO(item-2)``
    boundaries (the crossings the device-residency refactor exists to
    remove) totalled separately so their sum can only ratchet down.
    None when no audit ran — absence of audit must not read as a
    burn-down of zero bytes."""
    if not isinstance(residency, dict):
        return None
    by_boundary = residency.get("by_boundary")
    if not isinstance(by_boundary, dict) or not by_boundary:
        return None
    rows: Dict[str, Dict[str, Any]] = {}
    total = item2_total = 0
    for name in sorted(by_boundary):
        d = by_boundary[name]
        if not isinstance(d, dict):
            continue
        d2h = int(d.get("to_host_bytes") or 0)
        h2d = int(d.get("to_device_bytes") or 0)
        todo = name in ITEM2_BOUNDARIES
        rows[name] = {
            "bytes": d2h + h2d,
            "to_host_bytes": d2h,
            "to_device_bytes": h2d,
            "calls": int(d.get("calls") or 0),
            "todo_item2": todo,
        }
        total += d2h + h2d
        if todo:
            item2_total += d2h + h2d
    if not rows:
        return None
    return {
        "version": PROFILE_VERSION,
        "boundaries": rows,
        "total_bytes": total,
        "todo_item2_bytes": item2_total,
        "n_boundaries": len(rows),
        "n_todo_item2": sum(1 for r in rows.values() if r["todo_item2"]),
    }


def profile_sections_of(rec: Dict[str, Any]
                        ) -> Dict[str, Optional[Dict[str, Any]]]:
    """Both derived sections from a full run record — the one call
    bench's ``_finalize`` and the diff tooling share, so a profile
    computed at record-build time and one recomputed from a committed
    record can never disagree. Reads the record's existing sections
    (``spans``, ``kernels``, ``residency``, ``extra.stage_throughput``,
    ``extra.mfu`` ceilings) and returns ``{"profile": ...,
    "residency_burndown": ...}`` with None for what can't be built."""
    extra = rec.get("extra") or {}
    ceilings = None
    mfu = extra.get("mfu")
    if isinstance(mfu, dict):
        ceil: Dict[str, float] = {}
        v = mfu.get("measured_gflops")
        if isinstance(v, (int, float)) and v > 0:
            ceil["gflops"] = float(v)
        v = mfu.get("measured_gbps")
        if isinstance(v, (int, float)) and v > 0:
            ceil["gbps"] = float(v)
        ceilings = ceil or None
    return {
        "profile": build_profile(
            rec.get("spans"),
            kernels=rec.get("kernels"),
            cost=extra.get("stage_throughput"),
            residency=rec.get("residency"),
            ceilings=ceilings,
        ),
        "residency_burndown": build_burndown(rec.get("residency")),
    }


# --------------------------------------------------------------------------
# validation
# --------------------------------------------------------------------------

def _require(cond: bool, section: str, msg: str) -> None:
    if not cond:
        raise ValueError(f"{section} section: {msg}")


def _check_boundary_row(d: Any, name: str, section: str) -> None:
    _require(isinstance(d, dict), section,
             f"boundaries[{name!r}] is not an object")
    _require(name in BOUNDARIES, section,
             f"boundaries names undeclared boundary {name!r}")
    for k in ("to_host_bytes", "to_device_bytes", "calls"):
        v = d.get(k)
        _require(isinstance(v, int) and v >= 0, section,
                 f"boundaries[{name!r}].{k} must be an int >= 0")
    _require(d.get("todo_item2") == (name in ITEM2_BOUNDARIES), section,
             f"boundaries[{name!r}].todo_item2 disagrees with the "
             "declared allowlist")


def validate_profile(sec: Dict[str, Any]) -> None:
    """Structural validation of a record's ``profile`` section (additive
    scc-run-record v1 extension; ``export.validate_run_record`` calls
    this)."""
    _require(isinstance(sec, dict), "profile", "must be an object")
    _require(sec.get("version") == PROFILE_VERSION, "profile",
             f"version must be {PROFILE_VERSION}")
    stages = sec.get("stages")
    _require(isinstance(stages, dict) and stages, "profile",
             "stages must be a non-empty object")
    for name, row in stages.items():
        _require(isinstance(row, dict), "profile",
                 f"stages[{name!r}] is not an object")
        w = row.get("wall_s")
        _require(isinstance(w, (int, float)) and w >= 0, "profile",
                 f"stages[{name!r}].wall_s must be a number >= 0")
        for k in ("device_s", "flops", "bytes_accessed",
                  "achieved_gflops", "achieved_gbps"):
            v = row.get(k)
            _require(v is None or (isinstance(v, (int, float)) and v >= 0),
                     "profile", f"stages[{name!r}].{k} must be >= 0")
        for k in ("to_host_bytes", "to_device_bytes", "transfer_calls"):
            v = row.get(k)
            _require(v is None or (isinstance(v, int) and v >= 0),
                     "profile", f"stages[{name!r}].{k} must be an "
                     "int >= 0")
    tot = sec.get("totals")
    _require(isinstance(tot, dict), "profile", "totals must be an object")
    for k in ("wall_s", "device_s", "flops", "bytes_accessed",
              "to_host_bytes", "to_device_bytes"):
        v = tot.get(k)
        _require(isinstance(v, (int, float)) and v >= 0, "profile",
                 f"totals.{k} must be a number >= 0")
    bounds = sec.get("boundaries")
    if bounds is not None:
        _require(isinstance(bounds, dict), "profile",
                 "boundaries must be an object")
        for name, d in bounds.items():
            _check_boundary_row(d, name, "profile")


def validate_residency_burndown(sec: Dict[str, Any]) -> None:
    """Structural validation of a record's ``residency_burndown``
    section. The totals are re-checked against the rows — a burn-down
    whose headline number disagrees with its own table is exactly the
    corruption this section exists to make impossible."""
    _require(isinstance(sec, dict), "residency_burndown",
             "must be an object")
    _require(sec.get("version") == PROFILE_VERSION, "residency_burndown",
             f"version must be {PROFILE_VERSION}")
    rows = sec.get("boundaries")
    _require(isinstance(rows, dict) and rows, "residency_burndown",
             "boundaries must be a non-empty object")
    total = item2 = 0
    for name, d in rows.items():
        _check_boundary_row(d, name, "residency_burndown")
        b = d.get("bytes")
        _require(isinstance(b, int) and b >= 0, "residency_burndown",
                 f"boundaries[{name!r}].bytes must be an int >= 0")
        _require(b == d["to_host_bytes"] + d["to_device_bytes"],
                 "residency_burndown",
                 f"boundaries[{name!r}].bytes != d2h + h2d")
        total += b
        if d["todo_item2"]:
            item2 += b
    _require(sec.get("total_bytes") == total, "residency_burndown",
             "total_bytes disagrees with the per-boundary rows")
    _require(sec.get("todo_item2_bytes") == item2, "residency_burndown",
             "todo_item2_bytes disagrees with the per-boundary rows")
    _require(sec.get("n_boundaries") == len(rows), "residency_burndown",
             "n_boundaries disagrees with the per-boundary rows")
