"""Nested-span tracer with explicit device-sync boundaries.

Every prior perf round hand-rolled its own timing: ``StageTimer`` measured
dispatch intervals unless ``SCC_STAGE_SYNC`` was set, the r6 Wilcoxon ladder
carried its own synced per-bucket walls "with a separate sort split", and the
edgeR driver had a third private profiler. This module generalizes all of
them: a span is entered, work is submitted, and at exit the tracer records
BOTH the submitted wall (host dispatch time) and — for sync-eligible spans —
the device-synced wall (a ``block_until_ready`` sentinel drains the queue at
the boundary), so JAX async dispatch can never land one span's compute on
whichever later span first blocks.

Spans nest: a ``stage``-kind span (the pipeline's de/embed/tree/... stages)
may contain ``detail``-kind children (gene-chunk loops, ladder buckets,
sharded dispatches). The tracer keeps the whole tree; the legacy
``StageTimer.records`` view surfaces only the stage spans.

Ambient access: entering a span publishes its tracer to a contextvar, so
deep engine code (``de.engine`` chunk loops, ``parallel.sharded_de``) opens
child spans via the module-level :func:`span` without threading a tracer
through every signature. With no active tracer that function is a recorded
no-op (a throwaway span), so library code can instrument unconditionally.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import logging
import threading
import time
import weakref
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from scconsensus_tpu.config import env_flag

__all__ = [
    "Span",
    "Tracer",
    "span",
    "current_tracer",
    "current_span",
    "ambient_stage",
    "last_tracer",
    "device_drain",
    "summarize_record",
    "new_trace_id",
]


# Trace-id state: one random process prefix (minted lazily, ONE urandom
# syscall per process) + a monotone counter. Deliberately NOT uuid4 per
# request: os.urandom releases the GIL every call, which measurably
# perturbs the admission/worker scheduling the serve driver's
# backpressure behavior (and its tests) depend on — the telemetry plane
# must observe the system, not reschedule it.
_TRACE_PREFIX: Optional[str] = None
_TRACE_SEQ = itertools.count(1)


def new_trace_id() -> str:
    """Mint one request trace id (16 hex chars: 8-hex process prefix +
    8-hex sequence): issued at the wire front (or driver admission when
    no front is upstream), propagated through routing, the
    serve_request span, the response header, the quarantine ledger row,
    and the heartbeat stream — one id recovers a request's whole
    cross-process story (tools/postmortem.py joins on it)."""
    global _TRACE_PREFIX
    if _TRACE_PREFIX is None:
        import uuid

        _TRACE_PREFIX = uuid.uuid4().hex[:8]
    return f"{_TRACE_PREFIX}{next(_TRACE_SEQ) & 0xFFFFFFFF:08x}"

_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "scc_active_tracer", default=None
)

# Most recently created tracer, for out-of-context observers (the obs.live
# heartbeat sampler runs on its own thread and cannot see the contextvar).
# A weakref: the flight recorder must never keep a finished run's span tree
# alive.
_LAST_TRACER: "Optional[weakref.ref]" = None


def last_tracer() -> "Optional[Tracer]":
    """The most recently created (still-alive) tracer in this process, or
    None. This is the handle the live flight recorder samples — unlike
    :func:`current_tracer` it works from any thread."""
    ref = _LAST_TRACER
    return ref() if ref is not None else None

_LOG_LIST_CAP = 16


def summarize_record(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Log-line rendering of a record: long lists (e.g. the per-pair DE
    counts at K=44 → 946 entries) are summarized; the STORED record — what
    metrics/bench consumers read — keeps the full values. Recurses into
    nested dicts (the wilcox stage's ``occupancy`` probe carries a
    per-bucket list that can run tens of entries at 1M-cell shapes)."""
    out: Dict[str, Any] = {}
    for k, v in rec.items():
        if isinstance(v, dict):
            out[k] = summarize_record(v)
        elif isinstance(v, (list, tuple)) and len(v) > _LOG_LIST_CAP:
            out[k] = {
                "n": len(v),
                "head": list(v[:_LOG_LIST_CAP]),
                "sum": sum(v) if v and isinstance(v[0], (int, float)) else None,
            }
        else:
            out[k] = v
    return out


def device_drain() -> bool:
    """Submit-and-block a sentinel op: when it returns, every previously
    dispatched device computation has retired. Returns False when no
    backend is up (shutdown, import-time use) — attribution only, never an
    error. Never the FIRST jax touch: with jax unimported there is nothing
    queued, and a drain must not drag a jax-free process (orchestrators,
    consensus-only flows) through backend init."""
    import sys

    if "jax" not in sys.modules:
        return False
    try:
        jax = sys.modules["jax"]
        (jax.device_put(0.0) + 0).block_until_ready()
        return True
    except Exception:
        return False


_WARNED_SYNC_VALUES = set()


def _sync_mode() -> str:
    """Resolve the tracer sync policy from the env-flag registry:
    'stage' (default — drain at stage-span boundaries), 'all' (every
    span; diagnosis runs), or 'off' (dispatch intervals, the pre-obs
    behavior). Legacy SCC_STAGE_SYNC=1 forces at least 'stage'. An
    unrecognized value (e.g. a typo'd 'al') warns once and runs the
    default — a silent fallback would hand a diagnosis run dispatch
    walls and misattribute exactly what the subsystem exists to pin."""
    v = str(env_flag("SCC_TRACE_SYNC") or "").strip().lower()
    if v in ("off", "0", "none", "false", "no"):
        return "stage" if env_flag("SCC_STAGE_SYNC") else "off"
    if v == "all":
        return "all"
    if v not in ("", "stage", "1", "true", "on", "yes"):
        if v not in _WARNED_SYNC_VALUES:
            _WARNED_SYNC_VALUES.add(v)
            logging.getLogger("scconsensus_tpu").warning(
                "unrecognized SCC_TRACE_SYNC=%r; using 'stage' "
                "(valid: stage|all|off)", v,
            )
    return "stage"


class Span:
    """One timed region. Dict-style access reads/writes ``attrs`` so legacy
    writers (``rec["union_size"] = ...``, the engine's ``probe_out`` sink)
    work on a Span exactly as they did on the old StageTimer record dict."""

    __slots__ = (
        "name", "span_id", "parent_id", "depth", "kind", "attrs",
        "t0_s", "wall_submitted_s", "wall_synced_s", "synced",
        "device_mem", "_metrics", "_token", "_t_enter",
    )

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 depth: int, kind: str, attrs: Dict[str, Any]):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.depth = depth
        self.kind = kind
        self.attrs = attrs
        self.t0_s = 0.0
        self.wall_submitted_s = 0.0
        self.wall_synced_s: Optional[float] = None
        self.synced = False
        self.device_mem: Optional[Dict[str, Any]] = None
        self._metrics = None
        self._token = None
        self._t_enter = 0.0

    # -- dict-style back-compat surface -----------------------------------
    def __setitem__(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def __getitem__(self, key: str) -> Any:
        return self.attrs[key]

    def __contains__(self, key: str) -> bool:
        return key in self.attrs

    def get(self, key: str, default: Any = None) -> Any:
        return self.attrs.get(key, default)

    def setdefault(self, key: str, default: Any = None) -> Any:
        return self.attrs.setdefault(key, default)

    def update(self, *a, **kw) -> None:
        self.attrs.update(*a, **kw)

    # -- typed metrics -----------------------------------------------------
    @property
    def metrics(self):
        """Lazily created :class:`~scconsensus_tpu.obs.metrics.MetricSet`."""
        if self._metrics is None:
            from scconsensus_tpu.obs.metrics import MetricSet

            self._metrics = MetricSet()
        return self._metrics

    # -- views -------------------------------------------------------------
    @property
    def wall_s(self) -> float:
        """Headline wall: device-synced when a sync ran, else submitted."""
        return (self.wall_synced_s if self.wall_synced_s is not None
                else self.wall_submitted_s)

    def stage_record(self) -> Dict[str, Any]:
        """Legacy StageTimer-shaped record (``{"stage", ..., "wall_s"}``)."""
        rec: Dict[str, Any] = {"stage": self.name, **self.attrs}
        rec["wall_s"] = round(self.wall_s, 4)
        rec["wall_submitted_s"] = round(self.wall_submitted_s, 4)
        if self.wall_synced_s is not None:
            rec["wall_synced_s"] = round(self.wall_synced_s, 4)
        if self.synced:
            rec["synced"] = True
        return rec

    def record(self) -> Dict[str, Any]:
        """Full span record (the run-record schema's ``spans[]`` entry)."""
        rec: Dict[str, Any] = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "depth": self.depth,
            "kind": self.kind,
            "t0_s": round(self.t0_s, 6),
            "wall_submitted_s": round(self.wall_submitted_s, 6),
            "wall_synced_s": (round(self.wall_synced_s, 6)
                              if self.wall_synced_s is not None else None),
            "synced": self.synced,
        }
        if self.attrs:
            rec["attrs"] = self.attrs
        if self._metrics is not None and not self._metrics.empty():
            rec["metrics"] = self._metrics.to_dict()
        if self.device_mem is not None:
            rec["device_mem"] = self.device_mem
        return rec


class _NullSpan(Span):
    """Sink for module-level :func:`span` with no active tracer: accepts
    attrs/metrics, records nothing."""

    def __init__(self):
        super().__init__("<null>", -1, None, 0, "detail", {})


class Tracer:
    """Collects a span tree for one run.

    ``sync``: 'stage' | 'all' | 'off' (default from the SCC_TRACE_SYNC
    registry flag). ``annotate=True`` additionally wraps each span in
    ``jax.profiler.TraceAnnotation`` so spans show up in XLA/TPU traces.
    ``sample_device=True`` snapshots live/peak device memory at each
    sync-eligible span exit (no-op on backends without memory_stats).
    """

    def __init__(self, logger: Optional[logging.Logger] = None,
                 sync: Optional[str] = None, annotate: bool = False,
                 sample_device: bool = True):
        self.t_origin = time.perf_counter()
        self.spans: List[Span] = []          # finished spans, completion order
        self.logger = logger
        self.sync = sync if sync in ("stage", "all", "off") else _sync_mode()
        self.annotate = annotate
        self.sample_device = sample_device
        # wall-clock of the last span enter/exit — the flight recorder's
        # progress signal (a run with an open span but no transitions is
        # exactly what "stalled" means)
        self.last_transition_unix = time.time()
        self._stack: List[Span] = []
        self._ids = itertools.count()
        # per-stage-name entry counts: the Nth time a stage span named X
        # opens, _stage_entries[X] == N. The compile log keys retraces on
        # this ordinal — a trace-shaped event inside entry >= 2 of a stage
        # means the jit cache missed on a shape it had already seen.
        self._stage_entries: Dict[str, int] = {}
        self._lock = threading.Lock()
        global _LAST_TRACER
        _LAST_TRACER = weakref.ref(self)
        self._compile_mark = None
        try:
            from scconsensus_tpu.obs import device as obs_device

            # only mark when a listener is live: a zero-event compile_stats
            # from a listenerless tracer would claim the run compiled
            # nothing when it compiled dozens of programs
            if obs_device.install_compile_listener():
                self._compile_mark = obs_device.compile_mark()
        except Exception:
            pass

    # -- span lifecycle ----------------------------------------------------
    def _should_sync(self, kind: str, override: Optional[bool]) -> bool:
        if override is not None:
            return override
        if self.sync == "all":
            return True
        if self.sync == "stage":
            return kind == "stage"
        return False

    @contextmanager
    def span(self, name: str, kind: str = "stage",
             sync: Optional[bool] = None, **attrs: Any):
        with self._lock:
            parent = self._stack[-1] if self._stack else None
            sp = Span(
                name, next(self._ids),
                parent.span_id if parent is not None else None,
                len(self._stack), kind, dict(attrs),
            )
            self._stack.append(sp)
            if kind == "stage":
                self._stage_entries[name] = \
                    self._stage_entries.get(name, 0) + 1
            self.last_transition_unix = time.time()
        do_sync = self._should_sync(kind, sync)
        ann = None
        if self.annotate:
            try:
                import jax.profiler

                ann = jax.profiler.TraceAnnotation(name)
                ann.__enter__()
            except Exception:
                ann = None
        if do_sync:
            # entry boundary: queued work from the PREDECESSOR retires now,
            # so it cannot be billed to this span
            device_drain()
        sp._token = _ACTIVE.set(self)
        sp._t_enter = time.perf_counter()
        sp.t0_s = sp._t_enter - self.t_origin
        try:
            yield sp
        finally:
            now = time.perf_counter()
            sp.wall_submitted_s = now - sp._t_enter
            if do_sync and device_drain():
                sp.synced = True
                sp.wall_synced_s = time.perf_counter() - sp._t_enter
            if sp.synced and self.sample_device:
                try:
                    from scconsensus_tpu.obs import device as obs_device

                    sp.device_mem = obs_device.memory_snapshot()
                except Exception:
                    pass
            if ann is not None:
                ann.__exit__(None, None, None)
            _ACTIVE.reset(sp._token)
            with self._lock:
                if self._stack and self._stack[-1] is sp:
                    self._stack.pop()
                self.spans.append(sp)
                self.last_transition_unix = time.time()
            if self.logger is not None and kind == "stage":
                self.logger.info(
                    "stage %s",
                    json.dumps(summarize_record(sp.stage_record()),
                               default=str),
                )

    def add_completed_span(self, name: str, wall_s: float,
                           kind: str = "detail", synced: bool = False,
                           **attrs: Any) -> Span:
        """Synthesize an already-finished child span of the innermost open
        span, covering the ``wall_s`` seconds that just elapsed.

        For sequential phase-mark instrumentation (the NB driver's
        ``mark(label)`` calls) where the phase's NAME is only known at its
        end: a context-manager span would have to be renamed mid-flight
        and would leak open on an exception. The synthesized span is
        back-dated so Chrome traces render it in place; it never touches
        the open-span stack."""
        now_pc = time.perf_counter()
        with self._lock:
            parent = self._stack[-1] if self._stack else None
            sp = Span(
                name, next(self._ids),
                parent.span_id if parent is not None else None,
                parent.depth + 1 if parent is not None else 0,
                kind, dict(attrs),
            )
            sp.t0_s = max(now_pc - self.t_origin - wall_s, 0.0)
            sp._t_enter = sp.t0_s + self.t_origin
            sp.wall_submitted_s = wall_s
            if synced:
                sp.synced = True
                sp.wall_synced_s = wall_s
            self.spans.append(sp)
            self.last_transition_unix = time.time()
        return sp

    # -- views -------------------------------------------------------------
    def stage_records(self) -> List[Dict[str, Any]]:
        return [s.stage_record() for s in self.spans if s.kind == "stage"]

    def span_records(self) -> List[Dict[str, Any]]:
        return [s.record() for s in self.spans]

    def open_stack(self) -> List[Dict[str, Any]]:
        """Snapshot of the currently open spans, outermost first: name,
        kind, depth, span_id/parent_id, and the wall elapsed since entry.
        Thread-safe (the flight recorder calls this from its sampler
        thread while the run thread is mid-span)."""
        now = time.perf_counter()
        with self._lock:
            stack = list(self._stack)
        return [{
            "name": s.name,
            "span_id": s.span_id,
            "parent_id": s.parent_id,
            "depth": s.depth,
            "kind": s.kind,
            "elapsed_s": round(max(now - s._t_enter, 0.0), 4),
        } for s in stack]

    def live_span_records(self) -> List[Dict[str, Any]]:
        """Finished span records PLUS provisional records for still-open
        spans (wall = elapsed so far, ``synced`` False, ``attrs["open"]``
        True). A mid-run record built only from finished spans would carry
        dangling parent_ids (children of a still-open stage complete
        first) and lose the one thing a flight record exists to keep: what
        was running when the process died."""
        now = time.perf_counter()
        with self._lock:
            done = list(self.spans)
            stack = list(self._stack)
        out = [s.record() for s in done]
        for s in stack:
            rec: Dict[str, Any] = {
                "name": s.name,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "depth": s.depth,
                "kind": s.kind,
                "t0_s": round(s.t0_s, 6),
                "wall_submitted_s": round(max(now - s._t_enter, 0.0), 6),
                "wall_synced_s": None,
                "synced": False,
                "attrs": {**s.attrs, "open": True},
            }
            out.append(rec)
        return out

    def total_s(self) -> float:
        return sum(s.wall_s for s in self.spans if s.kind == "stage")

    def compile_stats(self) -> Optional[Dict[str, Any]]:
        """Compile events observed since this tracer was created (None when
        the jax.monitoring listener could not be installed)."""
        if self._compile_mark is None:
            return None
        from scconsensus_tpu.obs import device as obs_device

        return obs_device.compile_stats(since=self._compile_mark)

    def as_dict(self) -> Dict[str, Any]:
        from scconsensus_tpu.obs.export import SCHEMA_NAME, SCHEMA_VERSION

        out: Dict[str, Any] = {
            "stages": self.stage_records(),
            "total_s": self.total_s(),
            "spans": self.span_records(),
            "schema": SCHEMA_NAME,
            "schema_version": SCHEMA_VERSION,
        }
        cs = self.compile_stats()
        if cs is not None:
            out["compile"] = cs
        return out


def current_tracer() -> Optional[Tracer]:
    """The tracer of the innermost active span, or None."""
    return _ACTIVE.get()


def current_span() -> Optional[Span]:
    """The innermost active span of the ambient tracer, or None."""
    tr = _ACTIVE.get()
    if tr is None:
        return None
    with tr._lock:
        return tr._stack[-1] if tr._stack else None


def ambient_stage() -> Tuple[Optional[str], int]:
    """``(stage_name, entry_ordinal)`` of the innermost open stage-kind
    span, or ``(None, 0)`` with no stage open. Contextvar-first with the
    :func:`last_tracer` fallback, so off-thread observers (the hostprof
    sampler, jax.monitoring listeners firing on whichever thread jax
    compiles from, gc callbacks) resolve the same stage the run thread
    is in. Thread-safe; never raises."""
    tr = _ACTIVE.get()
    if tr is None:
        tr = last_tracer()
    if tr is None:
        return (None, 0)
    try:
        with tr._lock:
            for s in reversed(tr._stack):
                if s.kind == "stage":
                    return (s.name, tr._stage_entries.get(s.name, 1))
    except Exception:
        pass
    return (None, 0)


@contextmanager
def span(name: str, kind: str = "detail", sync: Optional[bool] = None,
         **attrs: Any):
    """Open a child span on the ambient tracer (no-op sink when none is
    active) — the instrumentation entry point for deep engine code."""
    tr = _ACTIVE.get()
    if tr is None:
        yield _NullSpan()
        return
    with tr.span(name, kind=kind, sync=sync, **attrs) as sp:
        yield sp
