"""Typed counters / gauges / histograms, keyed by span.

The r6 occupancy probe shipped its payload (per-bucket gene counts, pad
ratios, tied-run table heights, nnz bounds) as an ad-hoc nested dict behind
the SCC_WILCOX_PROBE env flag. These are the same quantities, as first-class
metric types attached to spans: a ``Counter`` accumulates (genes processed,
overflow redos), a ``Gauge`` records a last-seen value (window width, pad
ratio), a ``Histogram`` buckets a distribution (per-bucket pad ratios across
a whole ladder). ``MetricSet.to_dict()`` is the serialization every exporter
uses, so a metric's JSON shape cannot drift per consumer.
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["Counter", "Gauge", "Histogram", "MetricSet"]


@dataclasses.dataclass
class Counter:
    """Monotone accumulator."""

    value: float = 0.0

    def add(self, n: float = 1.0) -> "Counter":
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        self.value += n
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {"type": "counter", "value": self.value}


@dataclasses.dataclass
class Gauge:
    """Last-written value."""

    value: Optional[float] = None

    def set(self, v: float) -> "Gauge":
        self.value = v
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Fixed-bound histogram with running sum/min/max.

    ``bounds`` are the inclusive upper edges of each bucket; one overflow
    bucket is implicit. Default bounds are powers of two — the natural grid
    for window widths, padded rows, and pad ratios in this codebase.
    """

    DEFAULT_BOUNDS = tuple(float(1 << i) for i in range(0, 21))

    def __init__(self, bounds: Optional[Sequence[float]] = None):
        bs = tuple(float(b) for b in (bounds or self.DEFAULT_BOUNDS))
        if list(bs) != sorted(bs):
            raise ValueError("histogram bounds must be sorted ascending")
        self.bounds = bs
        self.counts: List[int] = [0] * (len(bs) + 1)
        self.n = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> "Histogram":
        v = float(v)
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.n += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        return self

    def to_dict(self) -> Dict[str, Any]:
        # sparse encoding: only occupied buckets ("le" edge -> count);
        # ladders at 1M shapes populate a handful of a 22-bucket grid
        occupied = {
            (str(self.bounds[i]) if i < len(self.bounds) else "+inf"): c
            for i, c in enumerate(self.counts) if c
        }
        return {
            "type": "histogram", "n": self.n, "sum": self.sum,
            "min": self.min, "max": self.max, "buckets": occupied,
        }


class MetricSet:
    """Named metrics of one span. Accessors create-on-first-use so
    instrumentation sites stay one-liners:
    ``span.metrics.counter("genes").add(g)``."""

    def __init__(self):
        self._m: Dict[str, Any] = {}

    def _get(self, name: str, cls, *args):
        m = self._m.get(name)
        if m is None:
            m = cls(*args)
            self._m[name] = m
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, requested {cls.__name__}"
            )
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  bounds: Optional[Sequence[float]] = None) -> Histogram:
        return self._get(name, Histogram, bounds)

    def empty(self) -> bool:
        return not self._m

    def to_dict(self) -> Dict[str, Any]:
        return {name: m.to_dict() for name, m in self._m.items()}
