"""Device-kernel timeline: a ``jax.profiler`` capture window joined to
tracer spans and the XLA cost model.

The r8 cost attribution (obs.cost) prices what a run *asked for* —
static FLOPs/bytes from ``cost_analysis`` against host-side span walls.
Nothing yet measures what the device actually *did*: per-kernel device
time is the denominator ROADMAP item 3's accelerator evidence needs
(host walls include dispatch, Python, and the transfer link). This
module opens a ``jax.profiler.start_trace`` window around chosen stages,
parses the Perfetto ``*.trace.json.gz`` the profiler writes, and joins:

  * **device-op events** — trace X-events carrying an ``hlo_op`` arg
    (the XLA executor stamps these on every backend: CPU thunks, GPU
    streams, TPU TensorCore planes), keyed ``(hlo_module, hlo_op)``;
    pure call-wrapper ops are dropped so a fusion is not double-counted
    under its enclosing ``call``;
  * **tracer spans** — the tracer's ``annotate=True`` mode wraps every
    span in ``jax.profiler.TraceAnnotation``, so span windows appear in
    the same profiler timeline; a kernel event joins to the innermost
    annotation window covering its start timestamp;
  * **the cost model** — per-stage ``cost_analysis`` totals (obs.cost)
    divided by *device* time instead of wall time give achieved FLOP/s
    and bytes/s against the cost-model ceiling: the roofline-style
    number a wall-based rate understates whenever the host is the
    bottleneck.

The result is the run record's validated ``kernels`` section: top-K
kernels by total device time (with per-span attribution), total device
time, and per-stage achieved rates. Capture is gated by the registered
``SCC_OBS_KERNELS`` flag naming the capture directory; everything is
best-effort — a backend whose trace carries no ``hlo_op`` events yields
an honest ``n_events: 0`` section, never a crash.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import time
from typing import Any, Dict, List, Optional

from scconsensus_tpu.config import env_flag

__all__ = [
    "capture_dir",
    "KernelCapture",
    "parse_trace_file",
    "device_op_events",
    "annotation_windows",
    "join_kernels_to_spans",
    "kernels_section",
    "validate_kernels",
]

DEFAULT_TOP_K = 12


def capture_dir() -> Optional[str]:
    """The ``SCC_OBS_KERNELS`` capture directory, or None (= capture off)."""
    d = env_flag("SCC_OBS_KERNELS")
    return str(d) if d else None


# --------------------------------------------------------------------------
# capture window
# --------------------------------------------------------------------------

class KernelCapture:
    """One profiler capture window. ``with KernelCapture(dir):`` starts a
    trace on entry and stops it on exit; :meth:`section` then parses the
    newest trace file written after the window opened and builds the
    run-record section. Never the process's first jax touch, and never
    fatal: a wedged or unavailable profiler records ``error`` and moves
    on (the flight recorder owns stall diagnosis, not this window)."""

    def __init__(self, directory: Optional[str] = None,
                 top_k: int = DEFAULT_TOP_K):
        self.directory = directory if directory is not None else capture_dir()
        self.top_k = int(top_k)
        self.t_open = 0.0
        self.open_ok = False
        self.error: Optional[str] = None

    @property
    def enabled(self) -> bool:
        return bool(self.directory)

    def __enter__(self) -> "KernelCapture":
        if not self.enabled:
            return self
        self.t_open = time.time()
        try:
            import jax.profiler

            os.makedirs(self.directory, exist_ok=True)
            jax.profiler.start_trace(self.directory)
            self.open_ok = True
        except Exception as e:
            self.error = f"start_trace failed: {e!r}"[:200]
        return self

    def __exit__(self, *exc) -> None:
        if not self.open_ok:
            return
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception as e:
            self.error = f"stop_trace failed: {e!r}"[:200]
            self.open_ok = False

    def trace_file(self) -> Optional[str]:
        """Newest ``*.trace.json.gz`` under the capture dir written after
        this window opened (the profiler nests runs under
        ``plugins/profile/<timestamp>/``)."""
        if not self.enabled:
            return None
        cands = [
            p for p in glob.glob(
                os.path.join(self.directory, "**", "*.trace.json.gz"),
                recursive=True,
            )
            if os.path.getmtime(p) >= self.t_open - 1.0
        ]
        return max(cands, key=os.path.getmtime) if cands else None

    def section(self, span_records: Optional[List[Dict[str, Any]]] = None,
                stage_cost: Optional[Dict[str, Dict[str, Any]]] = None,
                ) -> Optional[Dict[str, Any]]:
        """The run record's ``kernels`` section, or None when capture was
        off. Parse failures degrade to an error-stamped section — a TPU
        capture that half-wrote its trace must still leave evidence that
        a capture was attempted."""
        if not self.enabled:
            return None
        if self.error and not self.open_ok:
            return {"top": [], "n_events": 0,
                    "total_device_time_s": 0.0, "error": self.error}
        path = self.trace_file()
        if path is None:
            return {"top": [], "n_events": 0, "total_device_time_s": 0.0,
                    "error": "no trace file produced"}
        try:
            trace = parse_trace_file(path)
            sec = kernels_section(trace, span_records or [],
                                  stage_cost=stage_cost, top_k=self.top_k)
            sec["trace_file"] = path
            return sec
        except Exception as e:
            return {"top": [], "n_events": 0, "total_device_time_s": 0.0,
                    "error": f"trace parse failed: {e!r}"[:200]}


# --------------------------------------------------------------------------
# trace parsing
# --------------------------------------------------------------------------

def parse_trace_file(path: str) -> Dict[str, Any]:
    """Load a profiler Chrome-trace JSON (gzipped or plain)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        return json.loads(f.read().decode("utf-8", errors="replace"))


def device_op_events(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
    """X-events that are device-op executions: they carry an ``hlo_op``
    arg (every XLA executor stamps it). Pure ``call`` wrappers are
    dropped — the ops *inside* the call re-appear as their own events,
    and keeping both would double-count the fusion under its wrapper."""
    out: List[Dict[str, Any]] = []
    for e in trace.get("traceEvents") or []:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        op = args.get("hlo_op")
        if not op or op == "call" or e.get("name") == "call":
            continue
        out.append({
            "name": str(e.get("name")),
            "hlo_module": str(args.get("hlo_module") or ""),
            "ts_us": float(e.get("ts") or 0.0),
            "dur_us": float(e.get("dur") or 0.0),
        })
    return out


def annotation_windows(trace: Dict[str, Any], span_names) -> List[Dict]:
    """X-events whose name matches a tracer span name — the
    ``TraceAnnotation`` windows the tracer's ``annotate=True`` mode
    emits, in the same µs timeline as the device ops."""
    names = set(span_names)
    out = []
    for e in trace.get("traceEvents") or []:
        if e.get("ph") == "X" and e.get("name") in names:
            out.append({
                "span": str(e["name"]),
                "ts_us": float(e.get("ts") or 0.0),
                "dur_us": float(e.get("dur") or 0.0),
            })
    return out


def join_kernels_to_spans(kernels: List[Dict[str, Any]],
                          windows: List[Dict[str, Any]],
                          stage_names=()) -> None:
    """Attribute each kernel event, in place, to the INNERMOST (shortest)
    annotation window covering its start timestamp (``span`` key) and to
    the innermost covering *stage*-named window (``stage`` key — the
    perf-gate unit: a kernel inside a ``wilcox_bucket`` detail window
    still bills to the ``wilcox_test`` stage). None when nothing covers
    it — e.g. an async op that retired after its dispatching span
    closed."""
    wins = sorted(windows, key=lambda w: w["dur_us"])
    stages = [w for w in wins if w["span"] in set(stage_names)]
    for k in kernels:
        t = k["ts_us"]
        k["span"] = next(
            (w["span"] for w in wins
             if w["ts_us"] <= t <= w["ts_us"] + w["dur_us"]),
            None,
        )
        k["stage"] = next(
            (w["span"] for w in stages
             if w["ts_us"] <= t <= w["ts_us"] + w["dur_us"]),
            None,
        )


def kernels_section(trace: Dict[str, Any],
                    span_records: List[Dict[str, Any]],
                    stage_cost: Optional[Dict[str, Dict[str, Any]]] = None,
                    top_k: int = DEFAULT_TOP_K) -> Dict[str, Any]:
    """Build the ``kernels`` run-record section from a parsed trace.

    ``span_records``: the tracer's span records (names feed the
    annotation join). ``stage_cost``: obs.cost per-stage summary — when
    given, stages gain ``achieved_gflops_device`` / ``achieved_gbps_device``
    (cost-model totals over summed *device* time), the rate wall-based
    attribution understates whenever the host is the bottleneck.
    """
    kernels = device_op_events(trace)
    span_names = {s.get("name") for s in span_records
                  if isinstance(s, dict) and s.get("name")}
    stage_names = {s.get("name") for s in span_records
                   if isinstance(s, dict) and s.get("kind") == "stage"}
    windows = annotation_windows(trace, span_names)
    join_kernels_to_spans(kernels, windows, stage_names=stage_names)

    agg: Dict[Any, Dict[str, Any]] = {}
    by_span: Dict[str, float] = {}
    by_stage: Dict[str, float] = {}
    total_us = 0.0
    for k in kernels:
        total_us += k["dur_us"]
        key = (k["hlo_module"], k["name"])
        a = agg.setdefault(key, {
            "kernel": k["name"], "hlo_module": k["hlo_module"],
            "device_time_us": 0.0, "count": 0,
            "spans": {},
        })
        a["device_time_us"] += k["dur_us"]
        a["count"] += 1
        if k.get("span"):
            a["spans"][k["span"]] = a["spans"].get(k["span"], 0.0) \
                + k["dur_us"]
            by_span[k["span"]] = by_span.get(k["span"], 0.0) + k["dur_us"]
        if k.get("stage"):
            by_stage[k["stage"]] = by_stage.get(k["stage"], 0.0) \
                + k["dur_us"]
    top = sorted(agg.values(), key=lambda a: -a["device_time_us"])[:top_k]
    for a in top:
        a["device_time_s"] = round(a["device_time_us"] / 1e6, 6)
        a["pct"] = round(100.0 * a["device_time_us"] / total_us, 2) \
            if total_us else 0.0
        a["span"] = max(a["spans"], key=a["spans"].get) \
            if a["spans"] else None
        a.pop("spans")
        a.pop("device_time_us")
    sec: Dict[str, Any] = {
        "n_events": len(kernels),
        "n_kernels": len(agg),
        "total_device_time_s": round(total_us / 1e6, 6),
        "top": top,
        "by_span_device_s": {
            k: round(v / 1e6, 6) for k, v in sorted(
                by_span.items(), key=lambda kv: -kv[1]
            )
        },
    }
    if stage_cost:
        stages: Dict[str, Dict[str, Any]] = {}
        for stage, cost in stage_cost.items():
            dev_s = by_stage.get(stage, 0.0) / 1e6
            row: Dict[str, Any] = {
                "device_time_s": round(dev_s, 6),
                "wall_s": cost.get("wall_s"),
                "flops": cost.get("flops"),
                "bytes_accessed": cost.get("bytes_accessed"),
            }
            if dev_s > 0:
                if cost.get("flops"):
                    row["achieved_gflops_device"] = round(
                        cost["flops"] / dev_s / 1e9, 3
                    )
                if cost.get("bytes_accessed"):
                    row["achieved_gbps_device"] = round(
                        cost["bytes_accessed"] / dev_s / 1e9, 3
                    )
            stages[stage] = row
        sec["vs_cost_model"] = stages
    return sec


# --------------------------------------------------------------------------
# validation
# --------------------------------------------------------------------------

def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"kernels section: {msg}")


def validate_kernels(sec: Dict[str, Any]) -> None:
    """Structural validation of a record's ``kernels`` section (additive
    scc-run-record v1 extension; ``export.validate_run_record`` calls
    this)."""
    _require(isinstance(sec, dict), "must be an object")
    n = sec.get("n_events")
    _require(isinstance(n, int) and n >= 0,
             "n_events must be an int >= 0")
    tot = sec.get("total_device_time_s")
    _require(isinstance(tot, (int, float)) and tot >= 0,
             "total_device_time_s must be a number >= 0")
    top = sec.get("top")
    _require(isinstance(top, list), "top must be a list")
    for i, a in enumerate(top):
        _require(isinstance(a, dict), f"top[{i}] is not an object")
        _require(isinstance(a.get("kernel"), str) and a["kernel"],
                 f"top[{i}].kernel must be a non-empty string")
        dt = a.get("device_time_s")
        _require(isinstance(dt, (int, float)) and dt >= 0,
                 f"top[{i}].device_time_s must be a number >= 0")
        c = a.get("count")
        _require(isinstance(c, int) and c >= 1,
                 f"top[{i}].count must be an int >= 1")
    bs = sec.get("by_span_device_s")
    if bs is not None:
        _require(isinstance(bs, dict), "by_span_device_s must be an object")
        for k, v in bs.items():
            _require(isinstance(v, (int, float)) and v >= 0,
                     f"by_span_device_s[{k!r}] must be a number >= 0")
    vc = sec.get("vs_cost_model")
    if vc is not None:
        _require(isinstance(vc, dict), "vs_cost_model must be an object")
        for stage, row in vc.items():
            _require(isinstance(row, dict),
                     f"vs_cost_model[{stage!r}] not an object")
            dt = row.get("device_time_s")
            _require(isinstance(dt, (int, float)) and dt >= 0,
                     f"vs_cost_model[{stage!r}].device_time_s invalid")
