"""Noise-aware regression verdicts + numeric-drift sentinels over the ledger.

Two consumers of ``obs.ledger`` history, both machine-verdict producers
(``tools/perf_gate.py`` is the CLI that hard-fails on them):

Performance gate. Per-stage baselines follow the BASELINE.md round-6
anchor policy — the **median of the last ≤3 runs** of the same
(dataset, backend, config_fp) key — with a noise band derived from the
anchor spread (floored at 10 % of the baseline and 50 ms, because
single-core hosts showed unexplained process-state variance on the
record). A synced stage wall beyond baseline + band is a regression; the
verdict diffs the candidate's span tree against the baseline run's to
name the offending child span, and when XLA cost attribution ran
(obs.cost) the verdict also expresses the loss as achieved-throughput
efficiency, not just seconds.

Drift sentinel. Cross-round numeric shifts (the ``q2q_nbinom`` x=0
change) used to be attributed by prose notes in CHANGES.md. Here a run's
numeric fingerprint — DE p-value quantiles, NB dispersion quantiles,
final-label ARI vs pinned fixtures — is compared against committed pins;
any shift beyond tolerance must be explicitly acknowledged by a
machine-readable entry in the drift ledger
(``evidence/DRIFT_LEDGER.jsonl``) pinning the *new* value, or the gate
fails. Acknowledging means: append the entry AND update the pin — the
ledger is the audit trail, the pin is the new contract.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "ANCHOR_RUNS",
    "StageVerdict",
    "TransferVerdict",
    "ServingVerdict",
    "StreamingVerdict",
    "LoadgenVerdict",
    "GateVerdict",
    "stage_baselines",
    "stage_transfer_baselines",
    "boundary_baselines",
    "stage_trends",
    "serving_baselines",
    "streaming_baselines",
    "loadgen_baselines",
    "loadgen_verdicts",
    "diff_span_trees",
    "gate_record",
    "DRIFT_LEDGER_NAME",
    "PINS_NAME",
    "REFERENCE_DATASET",
    "pins_for_dataset",
    "history_pins",
    "resolve_pins",
    "drift_fingerprint",
    "load_drift_acks",
    "append_drift_ack",
    "check_drift",
    "adjusted_rand_index",
]

ANCHOR_RUNS = 3          # median-of-3 (BASELINE.md measurement policy)
REL_NOISE_FLOOR = 0.10   # band is never tighter than 10 % of baseline
ABS_NOISE_FLOOR_S = 0.05  # ...or 50 ms (timer + drain jitter at tiny walls)
# Transfer-bytes bands (BASELINE.md residency-gate policy): transfers are
# near-deterministic per workload, but event-cap truncation and data-
# dependent paths (overflow redo, exact-branch pair counts) wiggle a few
# KiB — 64 KiB absolute floor, same 10 % relative floor as walls.
ABS_NOISE_FLOOR_BYTES = 64 << 10
# Serving-latency bands (BASELINE.md serving-latency policy): tail
# latency is the noisiest gated quantity (scheduler jitter, GC pauses,
# queue-shape luck), so the relative floor is 25 % — wide enough that a
# loaded CI box doesn't false-fail, narrow enough that a 3× p99 cannot
# hide — with a 1 ms absolute floor for sub-ms baselines.
SERVE_REL_NOISE_FLOOR = 0.25
ABS_NOISE_FLOOR_MS = 1.0
# Streaming peak-RSS bands (BASELINE.md streaming policy, round 17):
# the kernel high-water mark moves with allocator/page-cache luck, so
# 15 % relative / 64 MB absolute floors — wide enough that GC timing
# can't false-fail, narrow enough that a leaked chunk window (2× peak)
# cannot hide. A peak-RSS regression is a MEMORY regression: the
# quantity the whole out-of-core design exists to bound.
STREAM_REL_NOISE_FLOOR = 0.15
ABS_NOISE_FLOOR_MB = 64.0
# Loadgen bands (BASELINE.md traffic policy, round 21): sustained RPS
# at SLO inherits throughput's noise profile (scheduler jitter, queue-
# shape luck under open-loop arrivals), so the serving relative floor
# (25 %) with a 1 rps absolute floor for tiny offered rates. Lower is
# the regression — a fleet that sustains less traffic at SLO than its
# baseline has regressed even with every wall clean. Breaches gate
# history-free: a run with ANY SLO breach fails outright (a breached
# run's 0.0 headline must never ingest as a quiet new baseline).
LOADGEN_REL_NOISE_FLOOR = 0.25
ABS_NOISE_FLOOR_RPS = 1.0


# --------------------------------------------------------------------------
# per-stage baselines (walls and transfer bytes share one banding policy)
# --------------------------------------------------------------------------

def _banded_baselines(series: Dict[str, List[float]], abs_floor: float,
                      rel_floor: float = REL_NOISE_FLOOR
                      ) -> Dict[str, Dict[str, float]]:
    """Median-of-≤ANCHOR_RUNS with a noise band floored at
    ``max(spread, rel_floor·baseline, abs_floor)`` — the BASELINE.md
    policy, shared by stage walls, stage transfer bytes, and serving
    latency so the gates can never drift apart (only the floors differ
    per quantity)."""
    out: Dict[str, Dict[str, float]] = {}
    for stage, vs in series.items():
        anchor = sorted(vs[-ANCHOR_RUNS:])
        n = len(anchor)
        baseline = anchor[n // 2] if n % 2 else (
            0.5 * (anchor[n // 2 - 1] + anchor[n // 2])
        )
        spread = anchor[-1] - anchor[0]
        band = max(spread, rel_floor * baseline, abs_floor)
        out[stage] = {
            "baseline": baseline,
            "band": band,
            "spread": spread,
            "n": n,
        }
    return out


def stage_baselines(history: Sequence[Dict[str, Any]]
                    ) -> Dict[str, Dict[str, float]]:
    """Noise-aware per-stage baselines from manifest entries (oldest
    first). Uses each entry's ``stage_walls``; the anchor set per stage is
    the last ``ANCHOR_RUNS`` entries that measured that stage. Returns
    ``{stage: {baseline_s, band_s, n, spread_s}}``.

    Flight-recorder partials (``termination`` cause != clean) are excluded
    unconditionally: a SIGTERMed or stalled run's stage walls are
    truncated at the moment of death, and a baseline anchored on one
    would read every subsequent healthy run as a regression."""
    from scconsensus_tpu.obs.ledger import is_partial_entry

    walls: Dict[str, List[float]] = {}
    for e in history:
        if is_partial_entry(e):
            continue
        for stage, w in (e.get("stage_walls") or {}).items():
            if isinstance(w, (int, float)) and w >= 0:
                walls.setdefault(stage, []).append(float(w))
    return {
        stage: {
            "baseline_s": round(b["baseline"], 6),
            "band_s": round(b["band"], 6),
            "spread_s": round(b["spread"], 6),
            "n": b["n"],
        }
        for stage, b in _banded_baselines(walls, ABS_NOISE_FLOOR_S).items()
    }


def stage_transfer_baselines(history: Sequence[Dict[str, Any]]
                             ) -> Dict[str, Dict[str, float]]:
    """Per-stage transfer-byte baselines from manifest entries' ledger-
    stamped ``stage_transfer_bytes`` (total of both directions; stamped at
    ingest from the record's residency section). Same median-of-≤3 +
    noise-band machinery as :func:`stage_baselines`, partials excluded
    for the same reason. Returns ``{stage: {baseline_bytes, band_bytes,
    spread_bytes, n}}``; stages never audited simply have no entry —
    absence of audit must not read as zero bytes."""
    from scconsensus_tpu.obs.ledger import is_partial_entry

    series: Dict[str, List[float]] = {}
    for e in history:
        if is_partial_entry(e):
            continue
        for stage, b in (e.get("stage_transfer_bytes") or {}).items():
            if isinstance(b, (int, float)) and b >= 0:
                series.setdefault(stage, []).append(float(b))
    return {
        stage: {
            "baseline_bytes": round(b["baseline"]),
            "band_bytes": round(b["band"]),
            "spread_bytes": round(b["spread"]),
            "n": b["n"],
        }
        for stage, b in _banded_baselines(
            series, ABS_NOISE_FLOOR_BYTES
        ).items()
    }


def boundary_baselines(history: Sequence[Dict[str, Any]]
                       ) -> Dict[str, Dict[str, float]]:
    """Per-declared-boundary byte baselines from manifest entries'
    ledger-stamped ``boundary_bytes`` (total of both directions per
    residency boundary, stamped at ingest). Same median-of-≤3 + noise-
    band machinery and byte floors as :func:`stage_transfer_baselines`
    — the residency burn-down ledger's denominator: BASELINE.md pins
    these numbers and item-2 progress is the TODO boundaries' baselines
    ratcheting toward zero. Partials excluded; boundaries never crossed
    simply have no entry."""
    from scconsensus_tpu.obs.ledger import is_partial_entry

    series: Dict[str, List[float]] = {}
    for e in history:
        if is_partial_entry(e):
            continue
        for boundary, b in (e.get("boundary_bytes") or {}).items():
            if isinstance(b, (int, float)) and b >= 0:
                series.setdefault(boundary, []).append(float(b))
    return {
        boundary: {
            "baseline_bytes": round(b["baseline"]),
            "band_bytes": round(b["band"]),
            "spread_bytes": round(b["spread"]),
            "n": b["n"],
        }
        for boundary, b in _banded_baselines(
            series, ABS_NOISE_FLOOR_BYTES
        ).items()
    }


def stage_trends(history: Sequence[Dict[str, Any]],
                 min_points: int = 2) -> Dict[str, Dict[str, Any]]:
    """Per-stage wall trend lines over the FULL ledger history (oldest
    first) — where :func:`stage_baselines` answers "is this run slower
    than the recent anchor", this answers "which way has the stage been
    drifting across rounds". Returns ``{stage: {n, first_s, last_s,
    delta_s, pct, slope_s_per_run, direction}}`` with ``direction`` one
    of ``up`` / ``down`` / ``flat``.

    Degenerate histories are first-class, never errors: a single-entry
    series reports ``flat`` with a zero slope (one point has no
    trend), an all-identical series reports ``flat`` (zero variance
    must not read as drift), and entries missing the stage key — e.g.
    a backend that never ran it — simply don't contribute points.
    A series is ``flat`` unless its endpoint delta clears the same
    noise floors the gate uses (10 % / 50 ms), so timer jitter can
    never be reported as a trend."""
    from scconsensus_tpu.obs.ledger import is_partial_entry

    series: Dict[str, List[float]] = {}
    for e in history:
        if is_partial_entry(e):
            continue
        for stage, w in (e.get("stage_walls") or {}).items():
            if isinstance(w, (int, float)) and w >= 0:
                series.setdefault(stage, []).append(float(w))
    out: Dict[str, Dict[str, Any]] = {}
    for stage, vs in series.items():
        n = len(vs)
        first, last = vs[0], vs[-1]
        delta = last - first
        # least-squares slope over run index; a 1-point series has no
        # trend and a zero-variance index (impossible past the n==1
        # guard, but cheap to keep explicit) must never divide
        slope = 0.0
        if n >= 2:
            mean_x = (n - 1) / 2.0
            mean_y = sum(vs) / n
            sxx = sum((i - mean_x) ** 2 for i in range(n))
            if sxx > 0:
                slope = sum(
                    (i - mean_x) * (v - mean_y) for i, v in enumerate(vs)
                ) / sxx
        band = max(ABS_NOISE_FLOOR_S, REL_NOISE_FLOOR * first)
        if n < max(min_points, 2) or abs(delta) <= band:
            direction = "flat"
        else:
            direction = "up" if delta > 0 else "down"
        out[stage] = {
            "n": n,
            "first_s": round(first, 6),
            "last_s": round(last, 6),
            "delta_s": round(delta, 6),
            "pct": round(100.0 * delta / first, 1) if first > 0 else None,
            "slope_s_per_run": round(slope, 6),
            "direction": direction,
        }
    return out


def serving_baselines(history: Sequence[Dict[str, Any]]
                      ) -> Dict[str, Dict[str, float]]:
    """Serving-latency baselines from manifest entries' ledger-stamped
    ``serving`` summaries (obs.ledger ingest). Gated metrics: ``p99_ms``
    (the tail is the serving contract) with ``p50_ms`` carried for the
    report. Same median-of-≤3 machinery, SERVING floors (25 % / 1 ms),
    partials excluded. Entries without a serving stamp simply don't
    anchor — absence of serving must not read as zero latency.

    Fleet round: every metric anchors under a replica-count key
    (``p99_ms@r<N>``, plus ``throughput_rps@r<N>`` — a 4-replica p99 is
    not comparable to a 1-replica p99, and fleet throughput is gated in
    its own right; entries without a replica stamp key as r1, the bare
    r15 driver). The unkeyed p50/p99 series anchor ONLY on unstamped
    (single-driver) entries — a fleet's pool-level tail must never drag
    the single-driver baseline a non-fleet candidate gates against."""
    from scconsensus_tpu.obs.ledger import is_partial_entry

    series: Dict[str, List[float]] = {}
    for e in history:
        if is_partial_entry(e):
            continue
        sv = e.get("serving") or {}
        nrep = sv.get("replicas")
        fleet_stamped = isinstance(nrep, int) and nrep >= 1
        nrep = int(nrep) if fleet_stamped else 1
        for metric in ("p50_ms", "p99_ms"):
            v = sv.get(metric)
            if isinstance(v, (int, float)) and v >= 0:
                if not fleet_stamped:
                    series.setdefault(metric, []).append(float(v))
                series.setdefault(f"{metric}@r{nrep}",
                                  []).append(float(v))
        tp = sv.get("throughput_rps")
        if isinstance(tp, (int, float)) and tp >= 0:
            series.setdefault(f"throughput_rps@r{nrep}",
                              []).append(float(tp))
    return {
        metric: {
            "baseline_ms": round(b["baseline"], 4),
            "band_ms": round(b["band"], 4),
            "spread_ms": round(b["spread"], 4),
            "n": b["n"],
        }
        for metric, b in _banded_baselines(
            series, ABS_NOISE_FLOOR_MS, rel_floor=SERVE_REL_NOISE_FLOOR
        ).items()
    }


def streaming_baselines(history: Sequence[Dict[str, Any]]
                        ) -> Dict[str, Dict[str, float]]:
    """Peak-RSS baselines from manifest entries' ledger-stamped
    ``streaming`` summaries (obs.ledger ingest). Same median-of-≤3
    machinery, STREAMING floors (15 % / 64 MB), partials excluded;
    entries without a streaming stamp simply don't anchor."""
    from scconsensus_tpu.obs.ledger import is_partial_entry

    series: Dict[str, List[float]] = {}
    for e in history:
        if is_partial_entry(e):
            continue
        v = (e.get("streaming") or {}).get("peak_rss_mb")
        if isinstance(v, (int, float)) and v >= 0:
            series.setdefault("peak_rss_mb", []).append(float(v))
    return {
        metric: {
            "baseline_mb": round(b["baseline"], 3),
            "band_mb": round(b["band"], 3),
            "spread_mb": round(b["spread"], 3),
            "n": b["n"],
        }
        for metric, b in _banded_baselines(
            series, ABS_NOISE_FLOOR_MB, rel_floor=STREAM_REL_NOISE_FLOOR
        ).items()
    }


def loadgen_baselines(history: Sequence[Dict[str, Any]]
                      ) -> Dict[str, Dict[str, float]]:
    """Sustained-RPS-at-SLO baselines from manifest entries' ledger-
    stamped ``loadgen`` summaries (obs.ledger ingest). Keyed per arrival
    profile (``rps_at_slo@<profile>`` — spike traffic is not comparable
    to steady traffic), LOADGEN floors (25 % / 1 rps), partials
    excluded. Breached runs (``breaches > 0`` — headline pinned 0.0 by
    the section's own consistency rule) never anchor: a baseline must
    describe what the fleet sustains WITHIN its SLO."""
    from scconsensus_tpu.obs.ledger import is_partial_entry

    series: Dict[str, List[float]] = {}
    for e in history:
        if is_partial_entry(e):
            continue
        lg = e.get("loadgen") or {}
        v = lg.get("rps_at_slo")
        profile = lg.get("profile")
        if (isinstance(v, (int, float)) and v >= 0
                and isinstance(profile, str)
                and not lg.get("breaches")):
            series.setdefault(f"rps_at_slo@{profile}",
                              []).append(float(v))
    return {
        metric: {
            "baseline_rps": round(b["baseline"], 4),
            "band_rps": round(b["band"], 4),
            "spread_rps": round(b["spread"], 4),
            "n": b["n"],
        }
        for metric, b in _banded_baselines(
            series, ABS_NOISE_FLOOR_RPS, rel_floor=LOADGEN_REL_NOISE_FLOOR
        ).items()
    }


# --------------------------------------------------------------------------
# span-tree diff (name the offender)
# --------------------------------------------------------------------------

def _child_walls(spans: Iterable[Dict[str, Any]], stage: str
                 ) -> Dict[str, float]:
    """Aggregate descendant walls by span name under every stage-kind span
    named ``stage``. Child spans of the same name (ladder buckets, chunk
    loops) sum — the diff compares *where the time went*, not individual
    iterations."""
    spans = [s for s in spans if isinstance(s, dict)]
    children: Dict[Any, List[Dict[str, Any]]] = {}
    for s in spans:
        children.setdefault(s.get("parent_id"), []).append(s)
    out: Dict[str, float] = {}
    roots = [s for s in spans
             if s.get("kind") == "stage" and s.get("name") == stage]
    stack = [c for r in roots for c in children.get(r.get("span_id"), [])]
    while stack:
        s = stack.pop()
        wall = s.get("wall_synced_s")
        if wall is None:
            wall = s.get("wall_submitted_s") or 0.0
        out[s["name"]] = out.get(s["name"], 0.0) + float(wall)
        stack.extend(children.get(s.get("span_id"), []))
    return out


def diff_span_trees(cand_spans: Sequence[Dict[str, Any]],
                    base_spans: Sequence[Dict[str, Any]],
                    stage: str) -> Optional[Dict[str, Any]]:
    """Name the child span that grew the most under a regressed stage.
    None when neither tree has children there (the stage itself is the
    finest attribution available)."""
    cand = _child_walls(cand_spans, stage)
    base = _child_walls(base_spans, stage)
    if not cand and not base:
        return None
    deltas = {
        name: cand.get(name, 0.0) - base.get(name, 0.0)
        for name in set(cand) | set(base)
    }
    name = max(deltas, key=lambda k: deltas[k])
    return {
        "span": name,
        "wall_s": round(cand.get(name, 0.0), 4),
        "baseline_s": round(base.get(name, 0.0), 4),
        "delta_s": round(deltas[name], 4),
    }


# --------------------------------------------------------------------------
# the gate
# --------------------------------------------------------------------------

@dataclasses.dataclass
class StageVerdict:
    stage: str
    wall_s: float
    baseline_s: float
    band_s: float
    regressed: bool
    excess_s: float = 0.0
    offender: Optional[Dict[str, Any]] = None
    efficiency: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}


@dataclasses.dataclass
class TransferVerdict:
    """Per-stage transfer-bytes verdict (residency section vs the key's
    ledger-stamped baselines) — the same shape of claim as StageVerdict,
    in bytes instead of seconds."""

    stage: str
    bytes: int
    baseline_bytes: int
    band_bytes: int
    regressed: bool
    excess_bytes: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ServingVerdict:
    """Serving verdict (candidate serving section vs the key's
    ledger-stamped baselines) — the tail-latency equivalent of a
    stage-wall claim. A clean-walls candidate whose p99 blew out fails
    on THIS verdict alone. Fleet candidates gate replica-count-keyed
    metrics (``p99_ms@r<N>``) plus throughput (``throughput_rps@r<N>``,
    ``unit="rps"``) — for throughput LOWER is the regression, so
    ``excess_ms`` carries the shortfall below the band floor."""

    metric: str                    # "p99_ms" | "p50_ms" | "...@r<N>"
    value_ms: float
    baseline_ms: float
    band_ms: float
    regressed: bool
    excess_ms: float = 0.0
    unit: str = "ms"

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SLOVerdict:
    """SLO-lane verdict (round 20): the candidate's ``slo`` section
    judged against its OWN declared objectives — no history needed,
    because the record carries its targets (burn_limit, p99 target).
    A clean-walls candidate whose error-budget burn breached its limit,
    or whose p99 missed its own target, fails on THIS verdict alone."""

    metric: str                    # "worst_burn" | "p99_ms"
    value: float
    limit: float
    regressed: bool
    detail: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}


@dataclasses.dataclass
class StreamingVerdict:
    """Out-of-core memory verdict (candidate streaming section's peak
    RSS vs the key's ledger-stamped baselines) — a peak-RSS blowout is
    a first-class regression even when every wall is green, because
    bounded memory IS the streaming contract."""

    metric: str                    # "peak_rss_mb"
    value_mb: float
    baseline_mb: float
    band_mb: float
    regressed: bool
    excess_mb: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LoadgenVerdict:
    """Traffic-lane verdict (round 21). Two claims: ``slo_breaches``
    gates history-free (any breach during the run fails outright — the
    spike-recovery contract is ZERO breaches), and
    ``rps_at_slo@<profile>`` gates against the key's ledger-stamped
    baselines where LOWER is the regression (``excess`` carries the
    shortfall below the band floor)."""

    metric: str                    # "slo_breaches" | "rps_at_slo@<p>"
    value: float
    baseline: float
    band: float
    regressed: bool
    excess: float = 0.0
    unit: str = "rps"
    detail: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}


@dataclasses.dataclass
class GraphsVerdict:
    """Transfer-op ratchet verdict (round 24): one statically counted
    host-crossing metric from the candidate's ``graphs`` section (or
    its residency audit, for the TODO(item-2) boundary debt) judged
    against the pinned starting debt in NUMERIC_PINS.json
    ``graph_ratchet``. No noise band and no history — op counts are
    deterministic properties of the compiled program, so the pin is a
    ceiling: a count above it fails outright (``detail`` names the op
    kind and source line), a count below it is ratchet progress (the
    pin update is a reviewed edit, never automatic)."""

    metric: str          # "transfer_ops@<stage>" | "host_callbacks@<stage>"
    #                    # | "boundary_calls@<boundary>"
    value: int
    pinned: int
    regressed: bool
    excess: int = 0
    detail: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}


@dataclasses.dataclass
class GateVerdict:
    ok: bool
    key: Dict[str, str]
    n_history: int
    stages: List[StageVerdict]
    note: Optional[str] = None
    # flight-recorder bookkeeping: history entries excluded from the
    # baselines because they are partial, and the candidate's own
    # termination cause when it is itself a partial record
    n_partial_excluded: int = 0
    candidate_termination: Optional[str] = None
    # per-stage transfer-bytes verdicts (empty when the candidate carried
    # no residency audit or the key has no transfer history)
    transfers: List[TransferVerdict] = dataclasses.field(
        default_factory=list
    )
    # serving-latency verdicts (empty when the candidate carried no
    # serving section or the key has no latency history)
    serving: List[ServingVerdict] = dataclasses.field(
        default_factory=list
    )
    # out-of-core peak-RSS verdicts (empty when the candidate carried no
    # streaming section or the key has no streaming history)
    streaming: List[StreamingVerdict] = dataclasses.field(
        default_factory=list
    )
    # SLO verdicts (round 20; empty when the candidate carried no slo
    # section) — judged against the record's OWN declared objectives,
    # so they apply even to a key with zero history
    slo: List[SLOVerdict] = dataclasses.field(default_factory=list)
    # traffic-lane verdicts (round 21; empty when the candidate carried
    # no loadgen section) — the breach claim gates history-free
    loadgen: List[LoadgenVerdict] = dataclasses.field(
        default_factory=list
    )
    # transfer-op ratchet verdicts (round 24; empty when the candidate
    # carried no graphs section or NUMERIC_PINS.json has no
    # graph_ratchet entry for its dataset) — pins are ceilings, no band
    graphs: List[GraphsVerdict] = dataclasses.field(
        default_factory=list
    )

    @property
    def regressions(self) -> List[StageVerdict]:
        return [s for s in self.stages if s.regressed]

    @property
    def transfer_regressions(self) -> List[TransferVerdict]:
        return [t for t in self.transfers if t.regressed]

    @property
    def serving_regressions(self) -> List[ServingVerdict]:
        return [s for s in self.serving if s.regressed]

    @property
    def streaming_regressions(self) -> List[StreamingVerdict]:
        return [s for s in self.streaming if s.regressed]

    @property
    def slo_regressions(self) -> List[SLOVerdict]:
        return [s for s in self.slo if s.regressed]

    @property
    def loadgen_regressions(self) -> List[LoadgenVerdict]:
        return [v for v in self.loadgen if v.regressed]

    @property
    def graphs_regressions(self) -> List[GraphsVerdict]:
        return [v for v in self.graphs if v.regressed]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "key": self.key,
            "n_history": self.n_history,
            "note": self.note,
            "n_partial_excluded": self.n_partial_excluded,
            "candidate_termination": self.candidate_termination,
            "regressions": [s.to_dict() for s in self.regressions],
            "stages": [s.to_dict() for s in self.stages],
            "transfers": [t.to_dict() for t in self.transfers],
            "transfer_regressions": [
                t.to_dict() for t in self.transfer_regressions
            ],
            "serving": [s.to_dict() for s in self.serving],
            "serving_regressions": [
                s.to_dict() for s in self.serving_regressions
            ],
            "streaming": [s.to_dict() for s in self.streaming],
            "streaming_regressions": [
                s.to_dict() for s in self.streaming_regressions
            ],
            "slo": [s.to_dict() for s in self.slo],
            "slo_regressions": [
                s.to_dict() for s in self.slo_regressions
            ],
            "loadgen": [v.to_dict() for v in self.loadgen],
            "loadgen_regressions": [
                v.to_dict() for v in self.loadgen_regressions
            ],
            "graphs": [v.to_dict() for v in self.graphs],
            "graphs_regressions": [
                v.to_dict() for v in self.graphs_regressions
            ],
        }


def slo_verdicts(candidate: Dict[str, Any]) -> List[SLOVerdict]:
    """SLO-lane verdicts for one candidate: the ``slo`` section judged
    against its OWN declared objectives. Unlike every other lane this
    needs no history — a record whose worst window burn exceeds its
    declared burn_limit, or whose p99 misses its own target, fails
    outright (the section's internal arithmetic was already enforced by
    serve.slo.validate_slo before gating)."""
    slo = candidate.get("slo")
    if not isinstance(slo, dict):
        return []
    out: List[SLOVerdict] = []
    obj = slo.get("objectives") or {}
    worst = slo.get("worst_burn")
    limit = obj.get("burn_limit")
    if isinstance(worst, (int, float)) and isinstance(limit, (int, float)):
        breach = None
        for b in slo.get("burn_rates") or []:
            if (isinstance(b, dict)
                    and float(b.get("burn", 0.0)) > float(limit)):
                breach = (f"window {b.get('window_s')}s burned "
                          f"{b.get('burn')}x its error budget "
                          f"({b.get('bad')}/{b.get('total')} bad)")
                break
        out.append(SLOVerdict(
            metric="worst_burn", value=round(float(worst), 4),
            limit=float(limit),
            regressed=float(worst) > float(limit),
            detail=breach,
        ))
    lat = slo.get("latency") or {}
    p99 = lat.get("p99_ms")
    target = lat.get("target_ms", obj.get("p99_ms"))
    if isinstance(p99, (int, float)) and isinstance(target, (int, float)):
        out.append(SLOVerdict(
            metric="p99_ms", value=round(float(p99), 4),
            limit=float(target),
            regressed=float(p99) > float(target),
        ))
    return out


def loadgen_verdicts(candidate: Dict[str, Any],
                     history: Sequence[Dict[str, Any]]
                     ) -> List[LoadgenVerdict]:
    """Traffic-lane verdicts for one candidate's ``loadgen`` section.

    The breach claim is history-free (the SLOVerdict rule): any breach
    recorded during the run — including a transient mid-spike breach
    the final windows recovered from — fails the gate, because the
    spike-soak contract is recovery WITHOUT a breach. The headline
    claim gates ``rps_at_slo`` against the key's per-profile baselines;
    lower is the regression."""
    lg = candidate.get("loadgen")
    if not isinstance(lg, dict):
        return []
    out: List[LoadgenVerdict] = []
    breaches = lg.get("breaches")
    if isinstance(breaches, list):
        out.append(LoadgenVerdict(
            metric="slo_breaches", value=float(len(breaches)),
            baseline=0.0, band=0.0, regressed=len(breaches) > 0,
            unit="breaches",
            detail="; ".join(str(b) for b in breaches) or None,
        ))
    v = lg.get("rps_at_slo")
    profile = lg.get("profile")
    if isinstance(v, (int, float)) and isinstance(profile, str):
        base = loadgen_baselines(history).get(f"rps_at_slo@{profile}")
        if base is not None:
            floor = base["baseline_rps"] - base["band_rps"]
            lv = LoadgenVerdict(
                metric=f"rps_at_slo@{profile}",
                value=round(float(v), 4),
                baseline=base["baseline_rps"], band=base["band_rps"],
                regressed=float(v) < floor,
            )
            if lv.regressed:
                lv.excess = round(floor - float(v), 4)
            out.append(lv)
    return out


def _graph_sites(sec: Dict[str, Any], stage: str, kind: str) -> str:
    """Human-readable site list for one stage's transfer ops or host
    callbacks: ``op@file:line`` per site, drawn from the stage's
    passports — the line the ratchet FAIL message names."""
    parts: List[str] = []
    programs = sec.get("programs") or {}
    row = (sec.get("by_stage") or {}).get(stage) or {}
    for name in row.get("programs") or []:
        block = (programs.get(name) or {}).get(kind) or {}
        for site in block.get("sites") or []:
            op = site.get("op") or site.get("target") or "?"
            where = site.get("where") or "unknown source"
            parts.append(f"{op}@{where} [{name}]")
    return "; ".join(parts)


def graphs_verdicts(
    candidate: Dict[str, Any], ratchet: Optional[Dict[str, Any]]
) -> Tuple[List[GraphsVerdict], Optional[str]]:
    """Transfer-op ratchet verdicts (round 24) for one candidate against
    one dataset's ``graph_ratchet`` pins entry.

    Three metric families, all ceilings with no noise band (op counts
    are deterministic properties of the compiled program):

    * ``transfer_ops@<stage>`` / ``host_callbacks@<stage>`` — the
      candidate's per-stage static counts from its ``graphs`` section;
      a regressed verdict's detail names each op kind and source line.
    * ``boundary_calls@<boundary>`` — runtime call counts at the
      ``TODO(item-2)`` residency boundaries (the declared host
      crossings item 1 is burning down), from the residency audit.

    Returns ``(verdicts, note)``. The lane refuses to gate — empty
    verdicts, explanatory note — when the candidate has no graphs
    section, the ratchet entry is absent, or the candidate's
    environment-fingerprint digest differs from the pinned one
    (op censuses from different toolchains are different programs)."""
    if not isinstance(ratchet, dict) or not ratchet:
        return [], None
    sec = candidate.get("graphs")
    if not isinstance(sec, dict):
        return [], "graph ratchet pinned but candidate has no graphs section"
    pinned_fp = ratchet.get("fingerprint_digest")
    cand_fp = (sec.get("fingerprint") or {}).get("digest")
    if pinned_fp and cand_fp and pinned_fp != cand_fp:
        return [], (
            f"graph ratchet not applied: candidate fingerprint {cand_fp} "
            f"!= pinned {pinned_fp} (different toolchain compiles a "
            "different program; re-pin on the new toolchain)"
        )
    out: List[GraphsVerdict] = []
    by_stage = sec.get("by_stage") or {}
    for stage in sorted(ratchet.get("stages") or {}):
        pins = ratchet["stages"][stage] or {}
        row = by_stage.get(stage) or {}
        for field, kind in (("transfer_ops", "transfer_ops"),
                            ("host_callbacks", "host_callbacks")):
            pin = pins.get(field)
            if pin is None:
                continue
            value = int(row.get(field, 0))
            v = GraphsVerdict(
                metric=f"{field}@{stage}", value=value, pinned=int(pin),
                regressed=value > int(pin),
            )
            if v.regressed:
                v.excess = value - int(pin)
                v.detail = (_graph_sites(sec, stage, kind)
                            or "sites unavailable in passports")
            out.append(v)
    boundaries = ratchet.get("boundaries") or {}
    if boundaries:
        by_boundary = ((candidate.get("residency") or {})
                       .get("by_boundary") or {})
        for bname in sorted(boundaries):
            pin = (boundaries[bname] or {}).get("calls")
            if pin is None:
                continue
            row = by_boundary.get(bname) or {}
            value = int(row.get("calls", 0))
            v = GraphsVerdict(
                metric=f"boundary_calls@{bname}", value=value,
                pinned=int(pin), regressed=value > int(pin),
            )
            if v.regressed:
                v.excess = value - int(pin)
                v.detail = (
                    f"declared TODO(item-2) crossing {bname!r} ran "
                    f"{value}x vs pinned {int(pin)}x "
                    "(obs.residency BOUNDARIES names the call site)"
                )
            out.append(v)
    return out, None


def _efficiency(cand_cost: Optional[Dict[str, Any]],
                base_cost: Optional[Dict[str, Any]],
                stage: str) -> Optional[Dict[str, Any]]:
    """Regression as efficiency loss: achieved flops/s now vs baseline.
    Needs cost attribution on both sides of the same stage."""
    c = (cand_cost or {}).get(stage)
    b = (base_cost or {}).get(stage)
    if not c or not b:
        return None
    ca, ba = c.get("achieved_gflops"), b.get("achieved_gflops")
    if not ca or not ba:
        return None
    return {
        "achieved_gflops": ca,
        "baseline_gflops": ba,
        "efficiency_loss": round(1.0 - ca / ba, 4),
    }


def gate_record(candidate: Dict[str, Any],
                history: Sequence[Dict[str, Any]],
                baseline_spans: Optional[Sequence[Dict[str, Any]]] = None,
                baseline_cost: Optional[Dict[str, Any]] = None,
                ) -> GateVerdict:
    """Verdict for one candidate run record against its key's history
    (manifest entries, oldest first, candidate excluded). With no history
    the gate passes with a note — a first run cannot regress, it *seeds*
    the baseline. Partial history entries are reported (counted) but never
    anchor baselines; a partial CANDIDATE is gated informationally — its
    completed stages still compare, and the verdict says so."""
    from scconsensus_tpu.obs.cost import stage_cost_summary
    from scconsensus_tpu.obs.ledger import (
        is_partial_entry,
        is_partial_record,
        run_key,
        stage_walls,
        termination_cause,
    )

    key = run_key(candidate)
    n_partial = sum(1 for e in history if is_partial_entry(e))
    cand_term = (termination_cause(candidate)
                 if is_partial_record(candidate) else None)
    note = None
    if cand_term is not None:
        note = (f"candidate is a PARTIAL record (termination.cause="
                f"{cand_term}): reported only — it must never be ingested "
                "as a baseline anchor")
    history = [e for e in history if not is_partial_entry(e)]
    # the SLO lane needs no history: the record carries its own targets
    # (burn_limit, p99), so the verdict applies even on a seeding run —
    # a first record that already burned through its error budget must
    # not seed as if it were clean
    slo = slo_verdicts(candidate)
    # the traffic lane's breach claim is history-free too — a breached
    # load run must not seed as if it were clean
    lg_verdicts = loadgen_verdicts(candidate, history)
    if not history:
        return GateVerdict(ok=(not any(s.regressed for s in slo)
                               and not any(v.regressed
                                           for v in lg_verdicts)),
                           key=key, n_history=0, stages=[],
                           note=note or
                           "no baseline history for this key; "
                           "candidate seeds the baseline",
                           n_partial_excluded=n_partial,
                           candidate_termination=cand_term,
                           slo=slo, loadgen=lg_verdicts)
    baselines = stage_baselines(history)
    if cand_term is not None:
        # "completed stages still compare": OPEN span snapshots in a
        # partial record carry the wall at the moment of death — a wedged
        # stage would fake a regression, a just-started one a pass. Gate
        # only the spans that actually closed.
        candidate = {**candidate, "spans": [
            s for s in candidate.get("spans") or []
            if not (isinstance(s, dict) and (s.get("attrs") or {}).get("open"))
        ]}
    cand_walls = stage_walls(candidate)
    cand_cost = stage_cost_summary(candidate.get("spans") or [])
    stages: List[StageVerdict] = []
    for stage, wall in sorted(cand_walls.items()):
        base = baselines.get(stage)
        if base is None:
            continue  # new stage: nothing to regress against
        limit = base["baseline_s"] + base["band_s"]
        sv = StageVerdict(
            stage=stage, wall_s=round(wall, 6),
            baseline_s=base["baseline_s"], band_s=base["band_s"],
            regressed=wall > limit,
        )
        if sv.regressed:
            sv.excess_s = round(wall - limit, 6)
            if baseline_spans is not None:
                sv.offender = diff_span_trees(
                    candidate.get("spans") or [], baseline_spans, stage
                )
            sv.efficiency = _efficiency(cand_cost, baseline_cost, stage)
        stages.append(sv)
    # transfer-bytes gate (obs.residency): per-stage bytes vs the key's
    # ledger-stamped baselines, same noise-band policy as walls. Only
    # stages BOTH sides audited compare — a candidate without an audit
    # (or a history without one) silently gates walls only.
    from scconsensus_tpu.obs.residency import (
        stage_transfer_bytes as _cand_transfers,
    )

    transfers: List[TransferVerdict] = []
    cand_bytes = _cand_transfers(candidate)
    if cand_bytes:
        tbase = stage_transfer_baselines(history)
        for stage, nbytes in sorted(cand_bytes.items()):
            tb = tbase.get(stage)
            if tb is None:
                continue
            limit_b = tb["baseline_bytes"] + tb["band_bytes"]
            tv = TransferVerdict(
                stage=stage, bytes=int(nbytes),
                baseline_bytes=int(tb["baseline_bytes"]),
                band_bytes=int(tb["band_bytes"]),
                regressed=nbytes > limit_b,
            )
            if tv.regressed:
                tv.excess_bytes = int(nbytes - limit_b)
            transfers.append(tv)
    # serving gate: the candidate's p50/p99 vs the key's ledger-stamped
    # latency baselines (BASELINE.md serving-latency policy). Only the
    # tail (p99) fails the gate; p50 is reported informationally — a p50
    # shift inside a clean p99 is tuning, not a regression. A FLEET
    # candidate (serving.fleet present) gates replica-count-keyed
    # baselines instead — a 4-replica p99 must never be judged against
    # 1-replica history — and additionally gates fleet THROUGHPUT, where
    # lower is the regression: a fleet that kept its single-replica tail
    # clean while losing aggregate throughput has still regressed.
    serving: List[ServingVerdict] = []
    cand_sv = candidate.get("serving") or {}
    cand_lat = cand_sv.get("latency_ms") or {}
    cand_fleet = cand_sv.get("fleet") or {}
    if cand_lat.get("n"):
        sbase = serving_baselines(history)
        if cand_fleet.get("replicas"):
            nrep = int(cand_fleet["replicas"])
            suffix = f"@r{nrep}"
        else:
            suffix = ""
        for short in ("p50", "p99"):
            metric = f"{short}_ms{suffix}"
            v = cand_lat.get(short)
            base = sbase.get(metric)
            if v is None or base is None:
                continue
            limit_ms = base["baseline_ms"] + base["band_ms"]
            svv = ServingVerdict(
                metric=metric, value_ms=round(float(v), 4),
                baseline_ms=base["baseline_ms"], band_ms=base["band_ms"],
                regressed=(short == "p99" and v > limit_ms),
            )
            if svv.regressed:
                svv.excess_ms = round(float(v) - limit_ms, 4)
            serving.append(svv)
        if suffix:
            tp = cand_sv.get("throughput_rps")
            base = sbase.get(f"throughput_rps{suffix}")
            if tp is not None and base is not None:
                floor_rps = base["baseline_ms"] - base["band_ms"]
                svv = ServingVerdict(
                    metric=f"throughput_rps{suffix}",
                    value_ms=round(float(tp), 4),
                    baseline_ms=base["baseline_ms"],
                    band_ms=base["band_ms"],
                    regressed=float(tp) < floor_rps,
                    unit="rps",
                )
                if svv.regressed:
                    svv.excess_ms = round(floor_rps - float(tp), 4)
                serving.append(svv)
    # streaming gate (round 17): the candidate's peak RSS vs the key's
    # ledger-stamped streaming baselines — bounded memory is the
    # out-of-core contract, so a 2× peak with clean walls still fails.
    streaming: List[StreamingVerdict] = []
    cand_sm = candidate.get("streaming") or {}
    peak = (cand_sm.get("budget") or {}).get("peak_rss_mb")
    if isinstance(peak, (int, float)):
        smbase = streaming_baselines(history).get("peak_rss_mb")
        if smbase is not None:
            limit_mb = smbase["baseline_mb"] + smbase["band_mb"]
            smv = StreamingVerdict(
                metric="peak_rss_mb", value_mb=round(float(peak), 3),
                baseline_mb=smbase["baseline_mb"],
                band_mb=smbase["band_mb"],
                regressed=float(peak) > limit_mb,
            )
            if smv.regressed:
                smv.excess_mb = round(float(peak) - limit_mb, 3)
            streaming.append(smv)
    ok = (not any(s.regressed for s in stages)
          and not any(t.regressed for t in transfers)
          and not any(s.regressed for s in serving)
          and not any(s.regressed for s in streaming)
          and not any(s.regressed for s in slo)
          and not any(v.regressed for v in lg_verdicts))
    return GateVerdict(ok=ok, key=key, n_history=len(history),
                       stages=stages, note=note,
                       n_partial_excluded=n_partial,
                       candidate_termination=cand_term,
                       transfers=transfers, serving=serving,
                       streaming=streaming, slo=slo,
                       loadgen=lg_verdicts)


# --------------------------------------------------------------------------
# numeric-drift sentinels
# --------------------------------------------------------------------------

DRIFT_LEDGER_NAME = "DRIFT_LEDGER.jsonl"
_QUANTILES = (0.01, 0.10, 0.25, 0.50, 0.75, 0.90, 0.99)


def _quantiles(values) -> List[float]:
    import numpy as np

    v = np.asarray(values, dtype=np.float64).ravel()
    v = v[np.isfinite(v)]
    if v.size == 0:
        return []
    return [round(float(q), 10) for q in np.quantile(v, _QUANTILES)]


def adjusted_rand_index(a, b) -> float:
    """Plain-numpy ARI (Hubert & Arabie) — keeps the sentinel free of an
    sklearn runtime dependency outside the test suite."""
    import numpy as np

    a = np.asarray(a).ravel()
    b = np.asarray(b).ravel()
    if a.size != b.size:
        raise ValueError("label arrays differ in length")
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    n = a.size
    c = np.zeros((ai.max() + 1, bi.max() + 1), np.int64)
    np.add.at(c, (ai, bi), 1)

    def comb2(x):
        return (x * (x - 1)) // 2

    sum_ij = comb2(c).sum()
    sum_a = comb2(c.sum(axis=1)).sum()
    sum_b = comb2(c.sum(axis=0)).sum()
    expected = sum_a * sum_b / max(comb2(n), 1)
    max_index = 0.5 * (sum_a + sum_b)
    if max_index == expected:
        return 1.0
    return float((sum_ij - expected) / (max_index - expected))


def drift_fingerprint(log_p=None, dispersions=None, labels=None,
                      ref_labels=None) -> Dict[str, Any]:
    """Per-run numeric fingerprint: the three cross-round quantities whose
    silent shifts have historically cost diagnosis time. Every field is
    optional — pass what the run computed."""
    fp: Dict[str, Any] = {}
    if log_p is not None:
        fp["de_logp_q"] = _quantiles(log_p)
    if dispersions is not None:
        fp["nb_dispersion_q"] = _quantiles(dispersions)
    if labels is not None and ref_labels is not None:
        fp["label_ari"] = round(adjusted_rand_index(labels, ref_labels), 10)
    return fp


def load_drift_acks(path: str) -> List[Dict[str, Any]]:
    """Acknowledged-drift entries (one JSON object per line; unreadable
    lines are skipped so a half-appended ack cannot poison the file)."""
    acks: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(d, dict) and d.get("field"):
                    acks.append(d)
    except OSError:
        pass
    return acks


def append_drift_ack(path: str, field: str, pinned, current,
                     reason: str) -> Dict[str, Any]:
    """Append one machine-readable acknowledgement. The entry pins the NEW
    value: a later run matching it is acknowledged, a further shift is a
    fresh drift."""
    entry = {
        "field": field,
        "pinned": pinned,
        "new": current,
        "reason": reason,
        "ts": round(time.time(), 3),
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(entry) + "\n")
    return entry


def _close(a, b, rtol: float, atol: float) -> bool:
    if isinstance(a, (list, tuple)) or isinstance(b, (list, tuple)):
        a = a if isinstance(a, (list, tuple)) else [a]
        b = b if isinstance(b, (list, tuple)) else [b]
        return len(a) == len(b) and all(
            _close(x, y, rtol, atol) for x, y in zip(a, b)
        )
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        if math.isnan(a) or math.isnan(b):
            return math.isnan(a) and math.isnan(b)
        return abs(a - b) <= atol + rtol * abs(b)
    return a == b


def check_drift(current: Dict[str, Any], pinned: Dict[str, Any],
                acks: Sequence[Dict[str, Any]] = (),
                rtol: float = 1e-3, atol: float = 1e-9
                ) -> List[Dict[str, Any]]:
    """Compare a fingerprint against its pins. Returns one machine-readable
    drift record per shifted field; ``acknowledged`` is True when a drift
    ledger entry pins the new value (within the same tolerance). Fields
    present only on one side are drifts too — a sentinel that silently
    stopped being computed is exactly the failure mode this exists for.
    Underscore-prefixed pin fields are metadata (the pinned labels array,
    the workload note), not sentinels."""
    out: List[Dict[str, Any]] = []
    for field in sorted(set(current) | set(pinned)):
        if field.startswith("_"):
            continue
        cur, pin = current.get(field), pinned.get(field)
        if field in current and field in pinned and _close(
                cur, pin, rtol, atol):
            continue
        acked = any(
            a.get("field") == field and _close(a.get("new"), cur, rtol, atol)
            for a in acks
        )
        out.append({
            "field": field,
            "pinned": pin,
            "current": cur,
            "acknowledged": acked,
        })
    return out


# --------------------------------------------------------------------------
# the pinned reference workload
# --------------------------------------------------------------------------

def reference_fingerprint(ref_labels=None) -> Dict[str, Any]:
    """Fingerprint of the pinned reference workload: a fixed tiny synthetic
    edgeR slow-path run (seeded, single-device CPU shapes) touching every
    sentinel surface — NB pseudo-counts/dispersions, DE p-values, and the
    final dynamic-cut labels. This is the run ``NUMERIC_PINS.json`` pins;
    the tier-1 sentinel test recomputes it and fails on unacknowledged
    drift. Pass the pinned labels to score ``label_ari`` against them
    (without, ARI scores against the run's own labels, i.e. 1.0 —
    the value a pin generation records)."""
    from scconsensus_tpu.models.pipeline import recluster_de_consensus
    from scconsensus_tpu.utils.synthetic import (
        noisy_labeling,
        synthetic_scrna,
    )

    data, truth, _ = synthetic_scrna(
        n_genes=80, n_cells=200, n_clusters=3, n_markers_per_cluster=8,
        seed=11,
    )
    labels = noisy_labeling(truth, 0.05, seed=2)
    result = recluster_de_consensus(
        data, labels, method="edgeR", q_val_thrs=0.05, fc_thrs=1.5,
        deep_split_values=(2,), mesh=None,
    )
    final = result.dynamic_labels["deepsplit: 2"]
    aux = result.de.aux or {}
    fp = drift_fingerprint(
        log_p=result.de.log_p,
        dispersions=aux.get("tagwise_dispersion"),
        labels=final,
        ref_labels=final if ref_labels is None else ref_labels,
    )
    fp["_final_labels"] = [int(v) for v in final]
    return fp


REFERENCE_DATASET = "reference"


def pins_for_dataset(pins_doc: Any, dataset: str
                     ) -> Optional[Dict[str, Any]]:
    """NUMERIC_PINS.json is keyed by dataset (``{"<dataset>": {pins}}``),
    because a fingerprint is only comparable against pins of the SAME
    workload — scoring a cite8k run against the tiny reference-workload
    pins would read every real bench record as drift. Returns the pin set
    for ``dataset``, or None (= no drift check) when none is pinned."""
    if not isinstance(pins_doc, dict):
        return None
    pins = pins_doc.get(dataset)
    return pins if isinstance(pins, dict) else None


PINS_NAME = "NUMERIC_PINS.json"


def resolve_pins(evidence_dir: str, dataset: str,
                 history: Sequence[Dict[str, Any]]
                 ) -> "Tuple[Optional[Dict[str, Any]], Optional[str]]":
    """ONE pin-resolution policy for every fingerprint consumer
    (perf_gate and explain_run must never disagree about what a
    candidate is compared against): (1) the evidence dir's
    ``NUMERIC_PINS.json`` entry for ``dataset`` when present and
    non-empty; (2) else the key's newest clean manifest entry
    (:func:`history_pins`); (3) else ``(None, None)`` — the candidate
    seeds. Returns ``(pins, source)`` where source is the pins filename
    or ``"history"``. An unreadable pins file falls through to the
    history fallback rather than erroring — a half-written pins file
    must not mask drift checking entirely."""
    pins = None
    path = os.path.join(evidence_dir, PINS_NAME)
    try:
        with open(path) as f:
            pins = pins_for_dataset(json.load(f), dataset)
    except (OSError, json.JSONDecodeError):
        pins = None
    if pins:
        return pins, PINS_NAME
    hp = history_pins(history)
    if hp:
        return hp, "history"
    return None, None


def history_pins(history: Sequence[Dict[str, Any]]
                 ) -> Optional[Dict[str, Any]]:
    """Implicit pins for a dataset with no NUMERIC_PINS entry: the newest
    CLEAN manifest entry's ledger-stamped ``numeric_fingerprint`` (every
    ingested run is stamped — obs.ledger). The quality-drift contract
    then covers any dataset: a candidate fingerprint shifting against its
    own key's previous run fails the gate until acknowledged in the drift
    ledger, exactly like a pinned-reference shift. Returns None with no
    usable history (a first run seeds, it cannot drift)."""
    from scconsensus_tpu.obs.ledger import is_partial_entry

    for e in reversed(list(history)):
        if is_partial_entry(e):
            continue  # a truncated run's fingerprint is not a contract
        fp = e.get("numeric_fingerprint")
        if isinstance(fp, dict) and fp:
            return fp
    return None


def write_pins(path: str) -> Dict[str, Any]:
    """(Re)generate ``NUMERIC_PINS.json`` from the reference workload
    (stored under the ``"reference"`` dataset key; pins for other datasets
    in an existing file are preserved). Updating the pins is half of
    acknowledging a drift — the other half is the drift-ledger entry
    (:func:`append_drift_ack`)."""
    from scconsensus_tpu.obs.export import write_json_atomic

    fp = reference_fingerprint()
    fp["_workload"] = ("edgeR slow path, synthetic 80x200x3 seed=11, "
                       "noisy labels seed=2, deep_split=2 — "
                       "obs.regress.reference_fingerprint")
    doc: Dict[str, Any] = {}
    try:
        with open(path) as f:
            existing = json.load(f)
        if isinstance(existing, dict):
            doc = {k: v for k, v in existing.items()
                   if isinstance(v, dict)}
    except (OSError, json.JSONDecodeError):
        pass
    doc[REFERENCE_DATASET] = fp
    write_json_atomic(path, doc)
    return fp


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="numeric-drift pin tool")
    ap.add_argument("--write-pins", metavar="PATH",
                    help="regenerate NUMERIC_PINS.json at PATH")
    args = ap.parse_args(argv)
    if args.write_pins:
        fp = write_pins(args.write_pins)
        shown = {k: v for k, v in fp.items() if not k.startswith("_")}
        print(json.dumps(shown, indent=1))
        return 0
    ap.error("nothing to do (--write-pins PATH)")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
