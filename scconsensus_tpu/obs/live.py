"""Live flight recorder: heartbeats, stall stack-dumps, partial run records.

Five consecutive rounds of TPU tunnel hangs shared one failure mode: a run
that stalled or was killed left NO evidence, because the run record was
serialized only at clean exit and the orchestrator inferred worker liveness
from stdout lines and cache-dir mtimes. This module closes that gap with
the standard flight-recorder / always-on-profiling pattern (Dapper-style
ambient tracing; the Perfetto/XProf continuous-capture model):

  * **Heartbeat stream** — a daemon sampler thread owned by the active
    :class:`~scconsensus_tpu.obs.trace.Tracer` appends one JSONL line per
    tick (``SCC_OBS_HEARTBEAT`` seconds; default off) to a sibling
    ``<base>_heartbeat.jsonl``: the open-span stack with elapsed walls,
    counter/gauge snapshots of the open spans, host RSS +
    ``memory_snapshot()`` HBM, compile stats, and the recorder's own
    ``progress_unix``. Appends are line-granular (crash-safe: a SIGKILL
    can truncate at most the line being written).

  * **Stall watchdog** — with ``SCC_OBS_STALL_S`` set, a tick that sees no
    span transition AND no compile progress for the whole window dumps
    all-thread stacks via ``faulthandler`` into the stream as a ``stall``
    event, increments the stall counter, and — when ``SCC_OBS_STALL_TRACE``
    names a directory — escalates to an on-demand
    ``jax.profiler.start_trace``/``stop_trace`` capture window. SIGUSR1
    requests the same capture on a live run at any time.

  * **Incremental run-record flushing** — the recorder periodically (and
    on SIGTERM / atexit) writes a schema-valid partial record to
    ``<base>_partial.json`` stamped ``termination: {cause, last_span,
    open_spans, ...}``. The periodic stamp is ``cause="crash"`` on
    purpose: the on-disk file always describes what it would mean if it
    turned out to be the last evidence (a process that dies with no
    handler running leaves exactly that stamp). SIGTERM rewrites it as
    ``"signal"``, a fired watchdog as ``"stall"``, and a clean
    :meth:`LiveRecorder.stop` as ``"clean"``. ``obs.ledger`` ingests
    partial records (the entry carries the cause) but
    ``obs.regress.stage_baselines`` excludes them from baselines.

The sampler thread keeps ticking while the run thread is blocked inside a
dead device RPC (the C++ wait releases the GIL) — which is the point: the
stream then shows a live process with a frozen ``progress_unix`` and the
exact span it froze in, distinguishing "slow but alive" from "dead" for
``bench.py``'s orchestrator watchdog and ``tools/tail_run.py``.
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import os
import signal
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.obs import trace as obs_trace
from scconsensus_tpu.obs.export import (
    TERMINATION_CAUSES,
    build_run_record,
    write_json_atomic,
)
# stdlib-only by contract (like robust.record): imported at module level
# so the sampler's per-tick streaming-panel check is one attribute read,
# not per-tick import machinery under a contended GIL
from scconsensus_tpu.stream import record as stream_record

__all__ = [
    "LiveRecorder",
    "active_recorder",
    "flush_active",
    "heartbeat_path",
    "partial_record_path",
    "read_heartbeat_tail",
    "dump_all_stacks",
]

_LOCK = threading.Lock()
_ACTIVE: "Optional[LiveRecorder]" = None

# Default seconds of profiler capture per stall/SIGUSR1 escalation.
CAPTURE_WINDOW_S = 15.0
# Partial-record flush cadence (seconds) when heartbeats are faster.
FLUSH_EVERY_S = 30.0


def heartbeat_path(base: str) -> str:
    """``<base>_heartbeat.jsonl`` (base = artifact path minus ``.json``)."""
    return f"{base}_heartbeat.jsonl"


def partial_record_path(base: str) -> str:
    return f"{base}_partial.json"


def active_recorder() -> "Optional[LiveRecorder]":
    return _ACTIVE


def flush_active(cause: str) -> Optional[str]:
    """Flush the process's active recorder (if any) with ``cause``; returns
    the partial-record path or None. Safe to call from signal handlers —
    never raises."""
    rec = _ACTIVE
    if rec is None:
        return None
    try:
        return rec.flush_partial(cause)
    except Exception:
        return None


def dump_all_stacks() -> str:
    """All-thread stack dump as text (faulthandler needs a real fd, so the
    dump round-trips through a temp file)."""
    try:
        with tempfile.TemporaryFile(mode="w+") as tf:
            faulthandler.dump_traceback(file=tf, all_threads=True)
            tf.seek(0)
            return tf.read()
    except Exception as e:  # pragma: no cover - faulthandler is stdlib
        return f"<stack dump failed: {e!r}>"


def read_heartbeat_tail(path: str, max_bytes: int = 256 << 10
                        ) -> Optional[Dict[str, Any]]:
    """Newest parseable heartbeat/stall line of a stream, or None. Reads
    only the file tail — post-mortem consumers poll this on long streams.
    The window must comfortably hold one STALL line (an embedded
    all-thread faulthandler dump easily exceeds 8 KiB under XLA thread
    pools), or tail readers go blind exactly when a stall just fired."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            chunk = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(chunk.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


class LiveRecorder:
    """Background heartbeat sampler + stall watchdog + partial flusher.

    ``path_base`` anchors the two output files (``<base>_heartbeat.jsonl``,
    ``<base>_partial.json``). ``record_fn`` (optional) builds the partial
    run record — emitters that already have a cumulative record builder
    (bench.py's ``_record``) plug it in here; without one the recorder
    builds a record from the last-created tracer's live span tree.
    ``heartbeat_s``/``stall_s`` default from the env-flag registry
    (``SCC_OBS_HEARTBEAT`` / ``SCC_OBS_STALL_S``); fractional values are
    the test-scale hook. A recorder with ``heartbeat_s <= 0`` is disabled:
    ``start()`` is a no-op, so callers wire it unconditionally.
    """

    def __init__(self, path_base: str, metric: str = "live flight record",
                 extra: Optional[Dict[str, Any]] = None,
                 heartbeat_s: Optional[float] = None,
                 stall_s: Optional[float] = None,
                 capture_dir: Optional[str] = None,
                 capture_s: float = CAPTURE_WINDOW_S,
                 flush_every_s: float = FLUSH_EVERY_S,
                 record_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        self.path_base = path_base
        self.hb_path = heartbeat_path(path_base)
        self.partial_path = partial_record_path(path_base)
        self.metric = metric
        self.extra = dict(extra or {})
        self.heartbeat_s = float(
            env_flag("SCC_OBS_HEARTBEAT") if heartbeat_s is None
            else heartbeat_s
        )
        self.stall_s = float(
            env_flag("SCC_OBS_STALL_S") if stall_s is None else stall_s
        )
        self.capture_dir = (capture_dir if capture_dir is not None
                            else env_flag("SCC_OBS_STALL_TRACE"))
        self.capture_s = float(capture_s)
        self.flush_every_s = float(flush_every_s)
        self.record_fn = record_fn

        self.ticks = 0
        self.stall_count = 0
        # Cumulative CPU seconds the sampler thread spent inside ticks
        # (time.thread_time: per-thread CPU, NOT wall — wall would charge
        # the sampler for GIL waits caused by the run thread and overstate
        # overhead by >10x on a busy interpreter). The overhead-guard test
        # asserts this stays <1% of the workload wall.
        self.tick_cpu_s = 0.0
        self._t_start = time.time()
        self._progress_unix = self._t_start
        self._last_transition_seen = 0.0
        self._compile_seen = -1
        self._compile_mark0 = 0  # events before this recorder existed
        self._stalled = False          # current stall episode
        # capture machinery: "idle" | "open" | "dead" (a wedged profiler
        # start is never retried); owner says WHO opened the window
        # ("mainthread" toggle vs "thread" stall escalation) so the two
        # can never double-stop one profiler session
        self._capture_state = "idle"
        self._capture_owner: Optional[str] = None
        self._last_flush = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._f = None
        # sampler thread, capture thread, annotate()/toggle_capture() on
        # the run/main thread all emit; unserialized writes could tear
        # lines and blind read_heartbeat_tail right when it matters
        self._emit_lock = threading.Lock()
        self._prev_term = None
        self._prev_usr1 = None
        self._atexit_registered = False

    # -- properties --------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.heartbeat_s > 0

    # -- lifecycle ---------------------------------------------------------
    def start(self, install_signals: bool = True) -> "LiveRecorder":
        """Open the stream, write the header line, spawn the sampler
        thread. No-op when disabled (SCC_OBS_HEARTBEAT unset/0)."""
        global _ACTIVE
        if not self.enabled or self._thread is not None:
            return self
        # warm the per-tick panel modules NOW, on the caller's thread:
        # a first-tick lazy-import storm on the sampler thread costs
        # ~0.9 s of GIL-contended wall next to a busy run thread
        # (measured), which is a missed tick and a fat CPU bill charged
        # to the sampler's own overhead budget
        for mod in ("scconsensus_tpu.obs.quality",
                    "scconsensus_tpu.obs.residency",
                    "scconsensus_tpu.robust.record",
                    "scconsensus_tpu.robust.integrity",
                    "scconsensus_tpu.serve.metrics"):
            try:
                __import__(mod)
            except Exception:
                pass
        os.makedirs(os.path.dirname(os.path.abspath(self.hb_path)) or ".",
                    exist_ok=True)
        self._f = open(self.hb_path, "a", buffering=1)
        self._emit({
            "t": "header", "ts": round(time.time(), 3), "pid": os.getpid(),
            "metric": self.metric, "extra": self.extra,
            "heartbeat_s": self.heartbeat_s, "stall_s": self.stall_s,
            "argv": list(sys.argv),
            "key": self._run_key(),
        })
        with _LOCK:
            _ACTIVE = self
        if install_signals:
            self._install_signals()
        # first periodic flush lands flush_every_s from NOW (0 here would
        # make every tick rewrite+fsync the partial record — measured at
        # ~100 ms/tick on slow filesystems)
        self._last_flush = time.time()
        self._thread = threading.Thread(
            target=self._run, name="scc-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, cause: str = "clean") -> None:
        """Stop the sampler and write the final partial record stamped with
        ``cause`` (idempotent; safe when never started)."""
        global _ACTIVE
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(2.0, 4 * self.heartbeat_s))
        if self.enabled and self._f is not None:
            self.flush_partial(cause)
            self._emit({"t": "end", "ts": round(time.time(), 3),
                        "cause": cause, "ticks": self.ticks,
                        "stalls": self.stall_count})
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
        with _LOCK:
            if _ACTIVE is self:
                _ACTIVE = None

    # -- signal / exit wiring ---------------------------------------------
    def _install_signals(self) -> None:
        """SIGTERM: flush a ``signal``-stamped partial, then chain to the
        handler that was installed before us (bench.py's own checkpoint
        handler keeps working). SIGUSR1: request a profiler capture.
        atexit: flush ``crash`` if nothing flushed a better cause (a
        process dying of an unhandled exception still leaves its record).
        Non-main-thread installs are skipped silently."""
        def _on_term(signum, frame):  # pragma: no cover - signal path
            try:
                self.flush_partial("signal")
            except Exception:
                pass
            prev = self._prev_term
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        def _on_usr1(signum, frame):  # pragma: no cover - signal path
            # Runs on the MAIN thread — the only thread jax.profiler
            # start/stop is reliable on everywhere (thread-initiated
            # captures wedge inside the TSL profiler on some builds).
            # Toggle: first USR1 opens the window, second closes it.
            try:
                self.toggle_capture()
            except Exception:
                pass

        try:
            self._prev_term = signal.signal(signal.SIGTERM, _on_term)
            self._prev_usr1 = signal.signal(signal.SIGUSR1, _on_usr1)
        except (ValueError, OSError, AttributeError):
            pass
        if not self._atexit_registered:
            self._atexit_registered = True

            def _at_exit():
                # stop() already ran on the happy path (then _f is None)
                if self._f is not None:
                    self.stop("crash")

            atexit.register(_at_exit)

    # -- sampling ----------------------------------------------------------
    def _run_key(self) -> Optional[Dict[str, str]]:
        """Run key of this recorder's workload (for tail_run.py's ETA
        lookup against the evidence ledger); None when extras carry no
        workload identity."""
        try:
            if not self.extra:
                return None
            from scconsensus_tpu.obs.ledger import run_key

            return run_key({"extra": self.extra,
                            "unit": self.extra.get("unit", "seconds")})
        except Exception:
            return None

    def _emit(self, obj: Dict[str, Any]) -> None:
        f = self._f
        if f is None:
            return
        try:
            line = json.dumps(obj, default=str) + "\n"
            with self._emit_lock:
                f.write(line)
                f.flush()
        except (OSError, ValueError):
            pass

    def _observe_progress(self, now: float) -> None:
        """Update ``progress_unix`` from span transitions and compile
        events. A long XLA compile transitions no spans, so compile-event
        arrivals count as progress too."""
        tr = obs_trace.last_tracer()
        if tr is not None:
            t = tr.last_transition_unix
            if t > self._last_transition_seen:
                self._last_transition_seen = t
                self._progress_unix = max(self._progress_unix, t)
        try:
            from scconsensus_tpu.obs import device as obs_device

            n = obs_device.compile_mark()
            if self._compile_seen < 0:
                # first observation: pre-existing events are not progress,
                # and per-tick stats aggregate only from here (summing the
                # whole process-lifetime event list every tick measured at
                # >5% of a quick stage's wall under a warm test suite)
                self._compile_mark0 = n
            elif n != self._compile_seen:
                self._progress_unix = now
            self._compile_seen = n
        except Exception:
            pass

    def touch(self) -> None:
        """Manual progress mark for instrumented host-side work that opens
        no spans (chunked generators, long pure-numpy phases)."""
        self._progress_unix = time.time()

    def annotate(self, **extra: Any) -> None:
        """Update the recorder's workload extras after start (e.g. the
        platform, known only once the backend answered) and append an
        ``annotate`` line so stream consumers (tail_run.py's ETA key
        lookup) see the refined run key."""
        self.extra.update(extra)
        self._emit({"t": "annotate", "ts": round(time.time(), 3),
                    "extra": dict(extra), "key": self._run_key()})

    def _open_metrics(self, tr) -> Dict[str, Any]:
        """Scalar counter/gauge snapshots of the open spans (histograms are
        summarized by n/sum)."""
        out: Dict[str, Any] = {}
        try:
            with tr._lock:
                stack = list(tr._stack)
            for sp in stack:
                ms = sp._metrics
                if ms is None or ms.empty():
                    continue
                for name, m in ms.to_dict().items():
                    if m.get("type") in ("counter", "gauge"):
                        out[f"{sp.name}.{name}"] = m.get("value")
                    else:
                        out[f"{sp.name}.{name}"] = {
                            "n": m.get("n"), "sum": m.get("sum")
                        }
        except Exception:
            pass
        return out

    def _snapshot(self, now: float) -> Dict[str, Any]:
        from scconsensus_tpu.obs import device as obs_device

        tr = obs_trace.last_tracer()
        open_spans: List[Dict[str, Any]] = []
        spans_done = 0
        metrics: Dict[str, Any] = {}
        if tr is not None:
            try:
                open_spans = tr.open_stack()
                spans_done = len(tr.spans)
                metrics = self._open_metrics(tr)
            except Exception:
                pass
        hb: Dict[str, Any] = {
            "t": "hb",
            "ts": round(now, 3),
            "seq": self.ticks,
            "up_s": round(now - self._t_start, 3),
            "progress_unix": round(self._progress_unix, 3),
            "since_progress_s": round(now - self._progress_unix, 3),
            "open_spans": open_spans,
            "spans_done": spans_done,
            "stalls": self.stall_count,
            # BOTH gauges ride every tick: rss_bytes is the instantaneous
            # value (where memory is NOW), rss_peak_bytes the kernel
            # high-water mark since process start — the number the
            # streaming budget assertion (stream.budget) and the run
            # record's bounded-memory evidence are judged by, so the
            # tail_run panel and the gate read the SAME quantity. (The
            # pre-r17 stream carried ru_maxrss under the rss_bytes name —
            # a spike-blind live view and a mislabeled peak at once.)
            "rss_bytes": obs_device.host_rss_bytes(),
            "rss_peak_bytes": obs_device.host_peak_rss_bytes(),
        }
        if metrics:
            hb["metrics"] = metrics
        try:
            # quality panel: sentinel trip count + latest funnel totals,
            # so tail_run shows NaN storms and empty funnels LIVE
            from scconsensus_tpu.obs import quality as obs_quality

            q = obs_quality.live_summary(tr)
            if q:
                hb["quality"] = q
        except Exception:
            pass
        try:
            # residency panel: cumulative transfer counters of the active
            # auditor — tail_run differences consecutive ticks into a live
            # transfer-bytes rate (a host-round-trip storm is visible as
            # MB/s while the run is still going, not post-mortem)
            from scconsensus_tpu.obs import residency as obs_residency

            tc = obs_residency.live_counters()
            if tc:
                hb["transfers"] = tc
        except Exception:
            pass
        try:
            # robustness panel: live fault/retry/degradation counters
            # (robust.record) — a run fighting for its life shows it on
            # the stream, and a SIGKILLed run's LAST heartbeat says what
            # it had already survived
            from scconsensus_tpu.robust import record as robust_record

            rs = robust_record.live_summary()
            if rs:
                hb["robust"] = rs
        except Exception:
            pass
        try:
            # streaming panel: chunks completed/planned, staged bytes,
            # window halvings, peak RSS vs the host budget — an
            # out-of-core run's vitals tick by tick, and a SIGKILLed
            # ingest's LAST heartbeat says which chunk was durable
            sm = stream_record.live_summary()
            if sm:
                hb["streaming"] = sm
        except Exception:
            pass
        try:
            # integrity panel: invariant checks passed/run, ghost-replay
            # progress + lag, mismatches and recomputes (robust.
            # integrity) — a run silently fighting corruption shows it
            # on the stream, tick by tick
            from scconsensus_tpu.robust import (
                integrity as robust_integrity,
            )

            ig = robust_integrity.live_summary()
            if ig:
                hb["integrity"] = ig
        except Exception:
            pass
        try:
            # serving panel: queue depth, rolling p99, breaker state and
            # the degraded/quarantined/rejected tallies of the process's
            # active serving driver — an online path fighting for its
            # life shows it on the stream tick by tick
            from scconsensus_tpu.serve import metrics as serve_metrics

            ss = serve_metrics.live_summary()
            if ss:
                hb["serving"] = ss
        except Exception:
            pass
        mem = obs_device.memory_snapshot()
        if mem is not None:
            hb["hbm"] = mem
        if self._compile_seen > self._compile_mark0:
            try:
                cs = obs_device.compile_stats(since=self._compile_mark0)
                hb["compile"] = {"events": cs["events"],
                                 "total_s": cs["total_s"]}
            except Exception:
                pass
        return hb

    # -- stall handling ----------------------------------------------------
    def _check_stall(self, now: float) -> None:
        if self.stall_s <= 0:
            return
        since = now - self._progress_unix
        if since <= self.stall_s:
            if self._stalled:
                self._emit({"t": "recovered", "ts": round(now, 3),
                            "stalls": self.stall_count})
            self._stalled = False
            return
        if self._stalled:
            return  # one dump per stall episode
        self._stalled = True
        self.stall_count += 1
        tr = obs_trace.last_tracer()
        event: Dict[str, Any] = {
            "t": "stall",
            "ts": round(now, 3),
            "since_progress_s": round(since, 3),
            "stalls": self.stall_count,
            "open_spans": tr.open_stack() if tr is not None else [],
            "stack": dump_all_stacks(),
        }
        if self.capture_dir:
            event["capture"] = self._spawn_capture("stall")
        self._emit(event)
        self.flush_partial("stall")

    def toggle_capture(self) -> None:
        """Synchronous main-thread capture toggle (the SIGUSR1 handler):
        first call opens a ``jax.profiler`` window, second closes it.
        Main thread because thread-initiated TSL profiler starts wedge on
        some builds; the USR1 handler always runs on the main thread."""
        now = time.time()
        if not self.capture_dir or "jax" not in sys.modules:
            self._emit({"t": "capture-failed", "ts": round(now, 3),
                        "error": "no SCC_OBS_STALL_TRACE dir or jax not "
                                 "loaded"})
            return
        import jax.profiler

        if self._capture_state == "open":
            if self._capture_owner != "mainthread":
                # a stall-escalation capture thread owns the session and
                # will stop it itself; stopping here would double-stop
                # the profiler and poison the machinery as "dead"
                self._emit({"t": "capture-busy", "ts": round(now, 3),
                            "owner": self._capture_owner})
                return
            jax.profiler.stop_trace()
            self._capture_state = "idle"
            self._capture_owner = None
            self._emit({"t": "capture-done", "ts": round(now, 3),
                        "dir": self.capture_dir})
        else:
            os.makedirs(self.capture_dir, exist_ok=True)
            jax.profiler.start_trace(self.capture_dir)
            self._capture_state = "open"
            self._capture_owner = "mainthread"
            self._emit({"t": "capture", "ts": round(now, 3),
                        "trigger": "sigusr1", "dir": self.capture_dir})

    def _spawn_capture(self, trigger: str) -> Optional[str]:
        """Stall-escalation capture: a self-contained daemon thread runs
        start_trace → sleep(capture_s) → stop_trace and emits the
        capture/capture-done events itself, so a wedged profiler start can
        never hang the sampler loop (the thread just parks and the state
        stays "open" — no retries, and the missing ``capture`` event in
        the stream is itself the diagnosis). Never the first jax touch."""
        if ("jax" not in sys.modules or not self.capture_dir
                or self._capture_state != "idle"):
            return None
        self._capture_state = "open"
        self._capture_owner = "thread"
        cap_dir, cap_s = self.capture_dir, self.capture_s

        def _go():
            try:
                import jax.profiler

                os.makedirs(cap_dir, exist_ok=True)
                jax.profiler.start_trace(cap_dir)
                self._emit({"t": "capture", "ts": round(time.time(), 3),
                            "trigger": trigger, "dir": cap_dir,
                            "duration_s": cap_s})
                time.sleep(cap_s)
                jax.profiler.stop_trace()
                self._emit({"t": "capture-done",
                            "ts": round(time.time(), 3), "dir": cap_dir})
                self._capture_state = "idle"
                self._capture_owner = None
            except Exception as e:
                self._emit({"t": "capture-failed",
                            "ts": round(time.time(), 3),
                            "error": repr(e)[:200]})
                self._capture_state = "dead"

        threading.Thread(target=_go, daemon=True,
                         name="scc-capture").start()
        return cap_dir

    # -- partial record ----------------------------------------------------
    def build_partial_record(self, cause: str) -> Dict[str, Any]:
        if cause not in TERMINATION_CAUSES:
            raise ValueError(f"unknown termination cause {cause!r}")
        tr = obs_trace.last_tracer()
        if self.record_fn is not None:
            rec = self.record_fn()
        else:
            rec = build_run_record(
                metric=self.metric, value=-1.0, unit="seconds",
                vs_baseline=None, extra=dict(self.extra),
                spans=tr.live_span_records() if tr is not None else [],
            )
        open_spans = tr.open_stack() if tr is not None else []
        rec["termination"] = {
            "cause": cause,
            "last_span": open_spans[-1]["name"] if open_spans else None,
            "open_spans": open_spans,
            "stall_count": self.stall_count,
            "heartbeat_path": os.path.basename(self.hb_path),
            "flushed_unix": round(time.time(), 3),
        }
        if cause != "clean":
            rec.setdefault("extra", {})["partial"] = True
        return rec

    def flush_partial(self, cause: str = "crash") -> Optional[str]:
        """Atomically (re)write ``<base>_partial.json``. The on-disk stamp
        always answers "what does it mean if this file is the last
        evidence" — hence the periodic flush's standing ``crash``."""
        try:
            rec = self.build_partial_record(cause)
            rec = json.loads(json.dumps(rec, default=str))
            write_json_atomic(self.partial_path, rec)
            self._last_flush = time.time()
            return self.partial_path
        except Exception:
            return None

    # -- the sampler thread ------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            t0 = time.thread_time()
            try:
                now = time.time()
                self._observe_progress(now)
                self.ticks += 1
                self._emit(self._snapshot(now))
                self._check_stall(now)
                if now - self._last_flush >= self.flush_every_s:
                    # the standing stamp while running is "crash": see
                    # flush_partial. A stall episode keeps its own stamp.
                    self.flush_partial("stall" if self._stalled else "crash")
            except Exception:  # the sampler must never kill the run
                pass
            finally:
                self.tick_cpu_s += time.thread_time() - t0
