"""Scientific quality telemetry: what the pipeline COMPUTED, not just
where the time went.

Rounds 7–9 made every run's performance self-describing (spans, device
samplers, the ledger, the flight recorder) but left the science opaque:
unexplained numeric variance and "the sparsity math doesn't visibly add
up" could only be chased by rereading raw JSON, and the r8 drift
sentinels pinned three quantities on one fixed reference workload. This
module adds the quality half, riding the same tracer/ledger machinery:

  * **Numeric-health sentinels** (``SCC_OBS_NUMERIC``; bench workers and
    the 1M driver default it on) — cheap NaN/Inf guards attached at
    stage boundaries. A tripped sentinel records the offending span,
    array name, and counts into span metrics AND the run record's
    ``quality.numeric_health`` section, instead of letting a NaN
    silently propagate to labels. Arrays where NaN is the legitimate
    untested marker (the (P, G) ``log_p``) pass their expected NaN count
    so only EXCESS NaNs trip.

  * **Algorithm funnels** — the DE gate funnel (genes in → pct-gate →
    logFC-gate → tested → significant, per pair and aggregated), the
    rank-sum window-ladder occupancy (the ``SCC_WILCOX_PROBE`` payload
    promoted to first-class schema), and consensus/cluster structure
    (cluster-size histograms, contingency entropy vs the input labeling,
    ARI of final labels vs inputs, label churn across the deepSplit
    ladder, per-deepSplit silhouette).

  * **The ``quality`` run-record section** — an additive
    ``scc-run-record`` v1 extension (validated by
    ``export.validate_run_record`` via :func:`validate_quality`), built
    by the pipeline's ``quality`` stage and stamped onto bench/driver
    records; ``tools/explain_run.py`` renders it as the Markdown report
    a reviewer reads instead of raw JSON.

Every compute entry point accumulates its own wall into a module counter
(:func:`consumed_cpu_s`) so the tier-1 overhead guard can assert quality
telemetry stays <2 % of an instrumented run's wall.
"""

from __future__ import annotations

import logging
import time
import weakref
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.obs import trace as obs_trace

__all__ = [
    "FUNNEL_STAGES",
    "enabled",
    "check_array",
    "trips",
    "note_funnel",
    "numeric_health",
    "de_funnel",
    "wilcox_ladder",
    "occupancy_from_stage_records",
    "ari_final_vs",
    "cluster_structure",
    "per_batch_ari",
    "batch_mixing_entropy",
    "build_quality_section",
    "validate_quality",
    "validate_scenario_scores",
    "live_summary",
    "consumed_cpu_s",
    "reset_cpu",
]

_LOG = logging.getLogger("scconsensus_tpu")

# Canonical funnel order: counts must be monotone non-increasing along it.
# The pct/logFC gate stages exist only on the fast (Seurat-gated) path;
# slow-path and NB funnels carry input → tested → significant.
FUNNEL_STAGES = ("input", "pct_gate", "logfc_gate", "tested", "significant")


# --------------------------------------------------------------------------
# overhead accounting (the <2%-of-wall guard reads this)
# --------------------------------------------------------------------------

_CPU = {"s": 0.0}


def consumed_cpu_s() -> float:
    """Cumulative wall-clock spent inside quality computations in this
    process (sentinel checks included — their device fetch waits are real
    overhead and are charged here on purpose)."""
    return _CPU["s"]


def reset_cpu() -> None:
    _CPU["s"] = 0.0


@contextmanager
def _timed():
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _CPU["s"] += time.perf_counter() - t0


# --------------------------------------------------------------------------
# numeric-health sentinels
# --------------------------------------------------------------------------

def enabled() -> bool:
    """Sentinel master switch (``SCC_OBS_NUMERIC``). Off by default so
    library users pay zero extra device dispatches; bench workers and the
    long drivers default it on."""
    return bool(env_flag("SCC_OBS_NUMERIC"))


# Trips (and the latest funnel totals for the live quality panel) are
# keyed by tracer (weakref — a finished run's state must not outlive its
# span tree) with a bounded orphan sink for tracer-less use. Tracer
# scoping matters for the funnel too: a process-global "last funnel"
# would leak one section's funnel into the next section's heartbeats
# (bench runs edger → wilcox → probes in one process).
_TRIPS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_ORPHAN: Dict[str, Any] = {"checks": 0, "trips": []}
_TRIP_CAP = 64


def _sink(tracer=None) -> Dict[str, Any]:
    if tracer is None:
        tracer = obs_trace.current_tracer() or obs_trace.last_tracer()
    if tracer is None:
        return _ORPHAN
    sink = _TRIPS.get(tracer)
    if sink is None:
        sink = {"checks": 0, "trips": []}
        _TRIPS[tracer] = sink
    return sink


def trips(tracer=None) -> List[Dict[str, Any]]:
    """Sentinel trips recorded against ``tracer`` (default: the ambient /
    most recent tracer, falling back to the orphan list)."""
    return list(_sink(tracer)["trips"])


def note_funnel(totals: Dict[str, Any], tracer=None) -> None:
    """Record a run's latest DE-funnel totals against its tracer so the
    live heartbeat's quality panel can show them (the funnel lands once
    per run, late; the heartbeat wants the newest for THIS run only)."""
    _sink(tracer)["funnel"] = dict(totals)


def checks_run(tracer=None) -> int:
    return int(_sink(tracer)["checks"])


def _is_jax(x) -> bool:
    return type(x).__module__.startswith("jax")


def check_array(name: str, x, kinds: Sequence[str] = ("nan", "inf"),
                expected_nan=0, span=None, where: Optional[str] = None,
                ) -> Optional[Dict[str, Any]]:
    """Numeric-health check of one array at a stage boundary.

    No-op (and dispatch-free) when the sentinel flag is off. ``kinds``
    picks the guards; ``expected_nan`` is the count of LEGITIMATE NaNs
    (the untested-entry marker in ``log_p``) — host int or device scalar,
    fetched together with the counts in one transfer. Only an excess
    trips. A trip is recorded onto the innermost span's metrics
    (``numeric_nan``/``numeric_inf`` counters + a ``numeric_trips`` attrs
    list), the tracer's trip list, and the package logger — surfaced,
    never swallowed, and never fatal."""
    if not enabled() or x is None:
        return None
    with _timed():
        try:
            if _is_jax(x):
                import jax
                import jax.numpy as jnp

                if not jnp.issubdtype(x.dtype, jnp.floating):
                    return None
                nan_d = jnp.sum(jnp.isnan(x)) if "nan" in kinds else 0
                inf_d = jnp.sum(jnp.isinf(x)) if "inf" in kinds else 0
                nan_c, inf_c, exp_c = (int(v) for v in jax.device_get(
                    (nan_d, inf_d, expected_nan)
                ))
                size = int(x.size)
            else:
                xa = np.asarray(x)
                if not np.issubdtype(xa.dtype, np.floating):
                    return None
                nan_c = int(np.isnan(xa).sum()) if "nan" in kinds else 0
                inf_c = int(np.isinf(xa).sum()) if "inf" in kinds else 0
                exp_c = int(np.asarray(expected_nan))
                size = int(xa.size)
        except Exception as e:  # a guard must never kill the pipeline
            _LOG.warning("numeric sentinel %r failed: %r", name, e)
            return None
        sink = _sink(None)
        sink["checks"] += 1
        excess_nan = max(nan_c - exp_c, 0)
        if excess_nan == 0 and inf_c == 0:
            return None
        if span is None:
            span = obs_trace.current_span()
        span_name = where or (span.name if span is not None else "<no-span>")
        trip = {
            "span": span_name,
            "array": name,
            "nan": excess_nan,
            "inf": inf_c,
            "size": size,
        }
        if span is not None and span.span_id >= 0:
            try:
                span.metrics.counter("numeric_nan").add(excess_nan)
                span.metrics.counter("numeric_inf").add(inf_c)
                span.setdefault("numeric_trips", []).append(
                    {"array": name, "nan": excess_nan, "inf": inf_c}
                )
            except Exception:
                pass
        if len(sink["trips"]) < _TRIP_CAP:
            sink["trips"].append(trip)
        _LOG.warning(
            "NUMERIC SENTINEL: %s/%s has %d unexpected NaN, %d Inf "
            "(of %d elements)", span_name, name, excess_nan, inf_c, size,
        )
        return trip


def numeric_health(tracer=None) -> Dict[str, Any]:
    """The run record's ``quality.numeric_health`` section."""
    sink = _sink(tracer)
    return {
        "enabled": enabled(),
        "checks": int(sink["checks"]),
        "trips": list(sink["trips"]),
    }


# --------------------------------------------------------------------------
# DE gate funnel
# --------------------------------------------------------------------------

def _row_counts(mask) -> np.ndarray:
    """(P,) per-pair True counts of a (P, G) bool mask, host or device —
    only the (P,)-sized result ever crosses the link."""
    if _is_jax(mask):
        import jax.numpy as jnp

        return np.asarray(jnp.sum(mask, axis=1)).astype(np.int64)
    return np.asarray(mask).sum(axis=1).astype(np.int64)


def de_funnel(result, config) -> Optional[Dict[str, Any]]:
    """Gate funnel of one :class:`~scconsensus_tpu.de.engine.PairwiseDEResult`
    under its config: genes in → pct-gate → logFC-gate → tested →
    significant, per pair and aggregated. Reads the RAW (possibly still
    device-resident) result fields and fetches only (P,)-sized count
    vectors — the funnel must not force the (P, G) statistics through the
    slow link. Gate stages appear only when the fast-path pct arrays
    exist; slow/NB funnels are input → tested → significant.

    ``logfc_gate`` is the engine's LITERAL full gate battery (pct ∧
    mean-expression ∧ |logFC|) when the result carries the engine's
    count (``aux["funnel_gate_full"]``), so the tested-stage drop
    measures group-size skips only; on older stored results it degrades
    to a pct ∧ |logFC| recomputation (then the mean gate's rejections
    land in the tested drop)."""
    from scconsensus_tpu.obs.residency import boundary

    # declared residency crossing: the funnel fetches ONLY (P,)-sized
    # count vectors (a test pins that it forces no (P, G) host
    # materialization) — the allowlisted funnel_counts boundary
    with _timed(), boundary("funnel_counts"):
        raw = lambda f: object.__getattribute__(result, f)  # noqa: E731
        tested = raw("tested")
        de_mask = raw("de_mask")
        P = int(result.n_pairs)
        G = int(tested.shape[1])
        per_pair: Dict[str, np.ndarray] = {
            "input": np.full(P, G, np.int64),
        }
        pct1, pct2 = raw("pct1"), raw("pct2")
        if pct1 is not None and pct2 is not None:
            xp = None
            if _is_jax(pct1):
                import jax.numpy as xp
            else:
                xp = np
            alpha = xp.maximum(pct1, pct2)
            pct_gate = alpha > config.min_pct
            if config.min_diff_pct > -float("inf"):
                pct_gate = pct_gate & (
                    (alpha - xp.minimum(pct1, pct2)) > config.min_diff_pct
                )
            per_pair["pct_gate"] = _row_counts(pct_gate)
            # raw attr access: touching result.aux would materialize the
            # WHOLE aux dict (roc's (P, G) auc/power) through the link
            gate_full = (raw("aux") or {}).get("funnel_gate_full")
            if gate_full is not None:
                per_pair["logfc_gate"] = np.asarray(
                    gate_full).astype(np.int64)
            else:
                log_fc = raw("log_fc")
                if config.only_pos:
                    fc_ok = log_fc > config.log_fc_thrs
                else:
                    fc_ok = xp.abs(log_fc) > config.log_fc_thrs
                per_pair["logfc_gate"] = _row_counts(pct_gate & fc_ok)
        per_pair["tested"] = _row_counts(tested)
        per_pair["significant"] = _row_counts(de_mask)
        total = {k: int(v.sum()) for k, v in per_pair.items()}
        out = {
            "n_pairs": P,
            "n_genes": G,
            "cluster_names": [str(n) for n in result.cluster_names],
            "pair_i": [int(v) for v in result.pair_i],
            "pair_j": [int(v) for v in result.pair_j],
            "per_pair": {k: [int(x) for x in v]
                         for k, v in per_pair.items()},
            "total": total,
        }
        note_funnel(total)
        return out


# --------------------------------------------------------------------------
# rank-sum window-ladder occupancy (SCC_WILCOX_PROBE payload, promoted)
# --------------------------------------------------------------------------

_LADDER_BUCKET_KEYS = (
    "window", "scan_width", "sort_width", "n_genes", "padded_rows",
    "real_elems", "padded_elems", "pad_ratio", "nnz_min", "nnz_max",
    "table_height", "overflow_genes", "wall_s", "sort_s",
)


def wilcox_ladder(occupancy: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Normalize an engine occupancy-probe payload into the schema's
    ``quality.wilcox_ladder`` section: the per-bucket rows plus the
    aggregate padded-vs-real accounting that makes the sparsity math
    visibly add up."""
    if not isinstance(occupancy, dict):
        return None
    with _timed():
        buckets = [
            {k: b.get(k) for k in _LADDER_BUCKET_KEYS if b.get(k) is not None}
            for b in occupancy.get("buckets") or []
            if isinstance(b, dict)
        ]
        real = sum(int(b.get("real_elems") or 0) for b in buckets)
        padded = sum(int(b.get("padded_elems") or 0) for b in buckets)
        out = {
            "windowed": bool(occupancy.get("windowed")),
            "input": occupancy.get("input"),
            "kernel": occupancy.get("kernel"),
            "n_genes": int(occupancy.get("n_genes") or 0),
            "n_cells": int(occupancy.get("n_cells") or 0),
            "window_floor": occupancy.get("window_floor"),
            "n_buckets": len(buckets),
            "genes_bucketed": sum(
                int(b.get("n_genes") or 0) for b in buckets
            ),
            "real_elems": real,
            "padded_elems": padded,
            "pad_ratio": round(padded / real, 3) if real else None,
            "overflow_genes": sum(
                int(b.get("overflow_genes") or 0) for b in buckets
            ),
            "buckets": buckets,
        }
        return out


def occupancy_from_stage_records(stage_records) -> Optional[Dict[str, Any]]:
    """The engine's occupancy probe, wherever a stage record carries it."""
    for rec in stage_records or []:
        if isinstance(rec, dict) and isinstance(rec.get("occupancy"), dict):
            return rec["occupancy"]
    return None


# --------------------------------------------------------------------------
# consensus / cluster structure
# --------------------------------------------------------------------------

def _entropy(counts: np.ndarray) -> float:
    p = counts[counts > 0].astype(np.float64)
    p /= p.sum()
    return float(-(p * np.log(p)).sum())


def _contingency_entropy(a: np.ndarray, b: np.ndarray) -> float:
    """Shannon entropy (nats) of the joint contingency distribution of
    two labelings — low when the cut merely renames the input clusters,
    high when mass spreads across many (input, output) cells."""
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    c = np.zeros((ai.max() + 1, bi.max() + 1), np.int64)
    np.add.at(c, (ai, bi), 1)
    return _entropy(c.ravel())


def ari_final_vs(dynamic_labels: Dict[str, np.ndarray],
                 ref_labelings: Dict[str, Any]) -> Dict[str, float]:
    """ARI of the FINAL cut against named reference labelings (e.g. a
    bench run's two raw input labelings). The one implementation behind
    both :func:`cluster_structure` and bench's post-hoc stamp — size-
    mismatched references are skipped, not crashed on."""
    from scconsensus_tpu.obs.regress import adjusted_rand_index

    if not dynamic_labels or not ref_labelings:
        return {}
    final = np.asarray(dynamic_labels[list(dynamic_labels)[-1]])
    out: Dict[str, float] = {}
    for rname, rl in ref_labelings.items():
        rl = np.asarray(rl)
        if rl.size == final.size:
            out[str(rname)] = round(adjusted_rand_index(final, rl), 6)
    return out


def cluster_structure(dynamic_labels: Dict[str, np.ndarray],
                      deep_split_info: Optional[List[Dict]] = None,
                      input_labels=None,
                      ref_labelings: Optional[Dict[str, Any]] = None,
                      landmark: Optional[Dict[str, Any]] = None,
                      ) -> Dict[str, Any]:
    """Cluster-structure section: per-cut size histograms + silhouette,
    contingency entropy and ARI vs the input labeling(s), and label churn
    (ARI between consecutive deepSplit cuts). ``ref_labelings`` adds
    named extra references (e.g. a bench run's two raw input labelings)
    scored against the FINAL cut. ``landmark`` is the tree stage's
    landmark-approximation telemetry (k, sketch, per-cut landmark
    occupancy, ARI-vs-exact when a verify run computed it) — stamped
    verbatim so a landmark run record names its approximation."""
    from scconsensus_tpu.obs.regress import adjusted_rand_index

    with _timed():
        info_by_ds = {
            int(d.get("deep_split")): d for d in (deep_split_info or [])
            if isinstance(d, dict) and d.get("deep_split") is not None
        }
        inp = np.asarray(input_labels) if input_labels is not None else None
        cuts: List[Dict[str, Any]] = []
        ari_vs_input: Dict[str, float] = {}
        names = list(dynamic_labels)
        for key in names:
            lab = np.asarray(dynamic_labels[key])
            assigned = lab[lab > 0] if np.issubdtype(
                lab.dtype, np.number) else lab
            _, counts = np.unique(assigned, return_counts=True)
            sizes = sorted((int(c) for c in counts), reverse=True)
            cut: Dict[str, Any] = {
                "cut": key,
                "n_clusters": len(sizes),
                "n_cells": int(lab.size),
                "n_unassigned": int(lab.size - int(counts.sum())),
                "sizes": sizes,
            }
            try:
                ds = int(str(key).rsplit(":", 1)[-1])
            except ValueError:
                ds = None
            d = info_by_ds.get(ds)
            if d and d.get("silhouette") is not None:
                cut["silhouette"] = float(d["silhouette"])
                if d.get("silhouette_method"):
                    cut["silhouette_method"] = d["silhouette_method"]
            if inp is not None and inp.size == lab.size:
                cut["contingency_entropy"] = round(
                    _contingency_entropy(inp, lab), 6
                )
                ari_vs_input[key] = round(
                    adjusted_rand_index(lab, inp), 6
                )
            cuts.append(cut)
        churn = []
        for a, b in zip(names, names[1:]):
            la = np.asarray(dynamic_labels[a])
            lb = np.asarray(dynamic_labels[b])
            if la.size == lb.size:
                churn.append({
                    "from": a, "to": b,
                    "ari": round(adjusted_rand_index(la, lb), 6),
                })
        out: Dict[str, Any] = {"cuts": cuts, "churn": churn}
        if landmark:
            out["landmark"] = dict(landmark)
        if ari_vs_input:
            out["ari_vs_input"] = ari_vs_input
        if inp is not None:
            _, ic = np.unique(inp, return_counts=True)
            out["input_entropy"] = round(_entropy(ic), 6)
            out["n_input_clusters"] = int(ic.size)
        if ref_labelings and names:
            refs = ari_final_vs(dynamic_labels, ref_labelings)
            if refs:
                out["ari_final_vs"] = refs
        return out


# --------------------------------------------------------------------------
# scenario scoring (workload zoo, round 19)
# --------------------------------------------------------------------------

def per_batch_ari(final_labels, truth_labels, batches) -> Dict[str, float]:
    """ARI of the final cut against truth WITHIN each batch/sample.

    The multi-sample scenario's per-batch quality block: an integration
    that nails three samples and shreds the fourth must not hide behind
    a healthy pooled ARI. Keys are ``str(batch)``; a batch with fewer
    than 2 cells is skipped (ARI of a singleton is undefined, not 1)."""
    from scconsensus_tpu.obs.regress import adjusted_rand_index

    with _timed():
        final = np.asarray(final_labels)
        truth = np.asarray(truth_labels)
        batches = np.asarray(batches)
        if not (final.size == truth.size == batches.size):
            raise ValueError(
                f"per_batch_ari: size mismatch (final={final.size}, "
                f"truth={truth.size}, batches={batches.size})"
            )
        out: Dict[str, float] = {}
        for b in np.unique(batches):
            sel = batches == b
            if int(sel.sum()) < 2:
                continue
            out[str(b)] = round(
                adjusted_rand_index(final[sel], truth[sel]), 6
            )
        return out


def batch_mixing_entropy(labels, batches) -> Dict[str, Any]:
    """Batch-composition entropy of every output cluster.

    For each cluster, the Shannon entropy (nats) of its cells' batch
    distribution; ``mean_norm_entropy`` is the cluster-size-weighted
    mean normalized by ``ln(n_batches)`` — 1.0 means every cluster is
    perfectly batch-mixed, 0.0 means every cluster is single-batch (the
    batch effect became the clustering, the integration failure mode
    this block exists to expose)."""
    with _timed():
        labels = np.asarray(labels)
        batches = np.asarray(batches)
        if labels.size != batches.size:
            raise ValueError(
                f"batch_mixing_entropy: size mismatch "
                f"(labels={labels.size}, batches={batches.size})"
            )
        ub, bi = np.unique(batches, return_inverse=True)
        n_batches = int(ub.size)
        per_cluster: Dict[str, Dict[str, Any]] = {}
        wsum, n_tot = 0.0, 0
        for c in np.unique(labels):
            sel = labels == c
            counts = np.bincount(bi[sel], minlength=n_batches)
            ent = _entropy(counts)
            n = int(sel.sum())
            per_cluster[str(c)] = {"entropy": round(ent, 6), "n": n}
            wsum += ent * n
            n_tot += n
        denom = float(np.log(n_batches)) if n_batches > 1 else 1.0
        mean_norm = (wsum / n_tot / denom) if n_tot else 0.0
        return {
            "n_batches": n_batches,
            "per_cluster": per_cluster,
            "mean_norm_entropy": round(float(mean_norm), 6),
        }


def validate_scenario_scores(s: Dict[str, Any]) -> None:
    """Structural validation of a ``quality.scenario`` scoring block
    (the workload zoo's per-scenario quality evidence). Raises
    ValueError on the first violation; :func:`validate_quality` calls
    this, so a scenario record is held to the same standard as every
    other quality field."""
    _require(isinstance(s, dict), "scenario must be an object")
    name = s.get("name")
    _require(isinstance(name, str) and bool(name),
             "scenario.name must be a non-empty string")
    metrics = s.get("metrics")
    _require(isinstance(metrics, dict) and bool(metrics),
             "scenario.metrics must be a non-empty object")
    for k, v in metrics.items():
        _require(isinstance(v, (int, float)) and not isinstance(v, bool)
                 and np.isfinite(v),
                 f"scenario.metrics[{k!r}] must be a finite number")
    pba = s.get("per_batch_ari")
    if pba is not None:
        _require(isinstance(pba, dict) and bool(pba),
                 "scenario.per_batch_ari must be a non-empty object")
        for k, v in pba.items():
            _require(isinstance(v, (int, float))
                     and -1.0 - 1e-9 <= v <= 1.0 + 1e-9,
                     f"scenario.per_batch_ari[{k!r}] must be an ARI "
                     "in [-1, 1]")
    bm = s.get("batch_mixing")
    if bm is not None:
        _require(isinstance(bm, dict), "scenario.batch_mixing must be "
                 "an object")
        nb = bm.get("n_batches")
        _require(isinstance(nb, int) and nb >= 2,
                 "scenario.batch_mixing.n_batches must be an int >= 2")
        mne = bm.get("mean_norm_entropy")
        _require(isinstance(mne, (int, float))
                 and -1e-9 <= mne <= 1.0 + 1e-9,
                 "scenario.batch_mixing.mean_norm_entropy must be in "
                 "[0, 1]")
        pc = bm.get("per_cluster")
        _require(isinstance(pc, dict) and bool(pc),
                 "scenario.batch_mixing.per_cluster must be a non-empty "
                 "object")
        for k, v in pc.items():
            _require(isinstance(v, dict)
                     and isinstance(v.get("entropy"), (int, float))
                     and v["entropy"] >= -1e-9
                     and isinstance(v.get("n"), int) and v["n"] > 0,
                     f"scenario.batch_mixing.per_cluster[{k!r}] needs "
                     "entropy >= 0 and n > 0")
    # a multi-sample block must carry BOTH halves: a per-batch ARI with
    # no mixing evidence (or vice versa) is half an integration claim
    _require((pba is None) == (bm is None),
             "scenario blocks with batch evidence must carry both "
             "per_batch_ari and batch_mixing")


# --------------------------------------------------------------------------
# assembly + validation
# --------------------------------------------------------------------------

def build_quality_section(de_result=None, config=None,
                          dynamic_labels=None, deep_split_info=None,
                          input_labels=None, ref_labelings=None,
                          occupancy=None, landmark=None,
                          tracer=None) -> Dict[str, Any]:
    """One ``quality`` section from whatever the run computed — every
    sub-section optional, numeric health always present."""
    q: Dict[str, Any] = {}
    if de_result is not None and config is not None:
        f = de_funnel(de_result, config)
        if f:
            q["de_funnel"] = f
    if occupancy is not None:
        lad = wilcox_ladder(occupancy)
        if lad:
            q["wilcox_ladder"] = lad
    if dynamic_labels:
        q["cluster_structure"] = cluster_structure(
            dynamic_labels, deep_split_info, input_labels, ref_labelings,
            landmark=landmark,
        )
    q["numeric_health"] = numeric_health(tracer)
    return q


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"quality section: {msg}")


def validate_quality(q: Dict[str, Any]) -> None:
    """Structural validation of a record's ``quality`` section (the
    additive schema-v1 extension). Raises ValueError on the first
    violation; ``export.validate_run_record`` calls this, so 'schema-
    valid' covers quality fields everywhere it covers spans."""
    _require(isinstance(q, dict), "must be an object")
    f = q.get("de_funnel")
    if f is not None:
        _require(isinstance(f, dict), "de_funnel must be an object")
        total = f.get("total")
        _require(isinstance(total, dict) and total,
                 "de_funnel.total must be a non-empty object")
        stages = [s for s in FUNNEL_STAGES if s in total]
        _require("input" in stages and "significant" in stages,
                 "de_funnel.total needs at least input and significant")
        for s in total:
            _require(s in FUNNEL_STAGES,
                     f"unknown funnel stage {s!r}")
            v = total[s]
            _require(isinstance(v, (int, float)) and v >= 0,
                     f"de_funnel.total.{s} must be a count >= 0")
        for a, b in zip(stages, stages[1:]):
            _require(total[a] >= total[b],
                     f"funnel not monotone: total.{a}={total[a]} < "
                     f"total.{b}={total[b]}")
        pp = f.get("per_pair")
        if pp is not None:
            _require(isinstance(pp, dict), "de_funnel.per_pair must be "
                     "an object")
            n_pairs = f.get("n_pairs")
            for s, vals in pp.items():
                _require(s in FUNNEL_STAGES,
                         f"unknown per_pair funnel stage {s!r}")
                _require(isinstance(vals, list),
                         f"per_pair.{s} must be a list")
                if isinstance(n_pairs, int):
                    _require(len(vals) == n_pairs,
                             f"per_pair.{s} has {len(vals)} entries, "
                             f"n_pairs={n_pairs}")
                if s in total:
                    _require(sum(vals) == total[s],
                             f"per_pair.{s} sums to {sum(vals)}, "
                             f"total.{s}={total[s]}")
            pstages = [s for s in FUNNEL_STAGES if s in pp]
            for a, b in zip(pstages, pstages[1:]):
                for i, (va, vb) in enumerate(zip(pp[a], pp[b])):
                    _require(va >= vb,
                             f"funnel not monotone at pair {i}: "
                             f"{a}={va} < {b}={vb}")
    cs = q.get("cluster_structure")
    if cs is not None:
        _require(isinstance(cs, dict), "cluster_structure must be an "
                 "object")
        _require(isinstance(cs.get("cuts"), list),
                 "cluster_structure.cuts must be a list")
        for i, cut in enumerate(cs["cuts"]):
            _require(isinstance(cut, dict), f"cuts[{i}] is not an object")
            _require(isinstance(cut.get("n_clusters"), int)
                     and cut["n_clusters"] >= 0,
                     f"cuts[{i}].n_clusters must be an int >= 0")
            sizes = cut.get("sizes")
            _require(isinstance(sizes, list)
                     and len(sizes) == cut["n_clusters"],
                     f"cuts[{i}].sizes must list one size per cluster")
            _require(all(isinstance(s, int) and s >= 0 for s in sizes),
                     f"cuts[{i}].sizes must be counts >= 0")
        for key in ("ari_vs_input", "ari_final_vs"):
            d = cs.get(key)
            if d is not None:
                _require(isinstance(d, dict), f"{key} must be an object")
                for k, v in d.items():
                    _require(isinstance(v, (int, float))
                             and -1.0 - 1e-9 <= v <= 1.0 + 1e-9,
                             f"{key}[{k!r}] must be an ARI in [-1, 1]")
        lm = cs.get("landmark")
        if lm is not None:
            _require(isinstance(lm, dict), "landmark must be an object")
            _require(isinstance(lm.get("k"), int) and lm["k"] >= 2,
                     "landmark.k must be an int >= 2")
            _require(isinstance(lm.get("branch"), str) and lm["branch"],
                     "landmark.branch must be a non-empty string")
            # A landmark run is an APPROXIMATION — its record must score
            # the cut against the input labeling or it carries no evidence
            # the approximation held (the r7 accuracy-pin contract; the
            # perf gate rejects records that skip it).
            ari = cs.get("ari_vs_input")
            _require(isinstance(ari, dict) and bool(ari),
                     "landmark run must carry cluster_structure."
                     "ari_vs_input (the approximation's accuracy "
                     "evidence)")
            ave = lm.get("ari_vs_exact")
            if ave is not None:
                _require(isinstance(ave, dict), "landmark.ari_vs_exact "
                         "must be an object")
                for k, v in ave.items():
                    if v is not None:
                        _require(isinstance(v, (int, float))
                                 and -1.0 - 1e-9 <= v <= 1.0 + 1e-9,
                                 f"landmark.ari_vs_exact[{k!r}] must be "
                                 "an ARI in [-1, 1]")
            occ = lm.get("occupancy")
            if occ is not None:
                _require(isinstance(occ, dict), "landmark.occupancy must "
                         "be an object")
                for k, v in occ.items():
                    _require(
                        isinstance(v, dict)
                        and isinstance(v.get("landmarks_assigned"), int)
                        and isinstance(v.get("n_landmarks"), int)
                        and 0 <= v["landmarks_assigned"] <= v["n_landmarks"],
                        f"landmark.occupancy[{k!r}] needs "
                        "landmarks_assigned <= n_landmarks",
                    )
    nh = q.get("numeric_health")
    if nh is not None:
        _require(isinstance(nh, dict), "numeric_health must be an object")
        _require(isinstance(nh.get("trips", []), list),
                 "numeric_health.trips must be a list")
        for i, t in enumerate(nh.get("trips", [])):
            _require(isinstance(t, dict), f"trips[{i}] is not an object")
            for k in ("span", "array"):
                _require(isinstance(t.get(k), str) and t[k],
                         f"trips[{i}].{k} must be a non-empty string")
            for k in ("nan", "inf"):
                _require(isinstance(t.get(k, 0), int) and t.get(k, 0) >= 0,
                         f"trips[{i}].{k} must be an int >= 0")
    sc = q.get("scenario")
    if sc is not None:
        validate_scenario_scores(sc)
    lad = q.get("wilcox_ladder")
    if lad is not None:
        _require(isinstance(lad, dict), "wilcox_ladder must be an object")
        _require(isinstance(lad.get("buckets", []), list),
                 "wilcox_ladder.buckets must be a list")
        for i, b in enumerate(lad.get("buckets", [])):
            _require(isinstance(b, dict)
                     and isinstance(b.get("window"), int)
                     and isinstance(b.get("n_genes"), int),
                     f"wilcox_ladder.buckets[{i}] needs int window/"
                     "n_genes")


# --------------------------------------------------------------------------
# live view (heartbeat quality panel)
# --------------------------------------------------------------------------

def live_summary(tracer=None) -> Optional[Dict[str, Any]]:
    """Compact quality snapshot for one heartbeat tick: sentinel trip
    count (+ the newest trip) and the latest DE funnel totals. None when
    there is nothing to say — the stream stays lean on healthy runs that
    have not reached the funnel yet."""
    sink = _sink(tracer)
    out: Dict[str, Any] = {}
    if sink["trips"]:
        out["trips"] = len(sink["trips"])
        out["last_trip"] = dict(sink["trips"][-1])
    if sink.get("funnel"):
        out["funnel"] = dict(sink["funnel"])
    return out or None
