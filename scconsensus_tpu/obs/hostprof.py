"""Host execution profiler: sampled stacks, GC pauses, memory timeline.

The attribution plane (obs.attr, round 22) root-causes a perf diff down
to transfers, device time, or dispatched FLOPs — and files everything
else under "host-side by elimination", a bucket with zero internal
structure even though CPU-run stage walls are dominated by exactly that
bucket. This module gives the bucket structure, with three instruments
that all bucket by the *existing* trace spans (no new annotation API):

* a **sampling stack profiler** — a daemon thread snapshots the run
  thread's stack via ``sys._current_frames()`` every ``period_s``
  (default 50 Hz from ``SCC_HOSTPROF_HZ``), classifies each sample into
  a named host cause (``python`` compute with its top frame,
  ``blocking_wait`` on ``block_until_ready``/transfer drains,
  ``compile`` inside jax trace/lower/compile machinery,
  ``serialization`` in json/pickle codecs) and attributes it to the
  innermost open *stage* span (:func:`~scconsensus_tpu.obs.trace.
  ambient_stage`);
* **GC pause accounting** — a ``gc.callbacks`` hook measures every
  collection's stop-the-world pause and bills it to the ambient stage
  (or the explicit ``(outside spans)`` bucket — a pause between stages
  is still a pause);
* a **memory timeline** — host RSS (and, when a device backend is up,
  HBM ``bytes_in_use``) sampled on the same tick grid and laid over the
  stage timeline.

Everything lands as two additive scc-run-record v1 sections —
``host_profile`` and ``memory_timeline`` — built by the pure functions
:func:`build_host_profile` / :func:`build_memory_timeline` (so the
degenerate-input tests drive them with synthetic samples) and validated
by :func:`validate_host_profile` / :func:`validate_memory_timeline`
from ``export.validate_run_record``. ``bench._finalize`` stamps both
next to the round-22 ``profile`` join; ``obs.attr`` turns their per-
stage cause seconds into named drivers where the old report said only
"host-side".

Overhead: the sampler does one ``_current_frames`` walk + one
``/proc/self/statm`` pread per tick and self-times its own work
(``sampler_self_s`` lands on the section); the pin — under the perf
gate's 50 ms noise floor on the anchor smoke shape — is enforced by
test, not hoped for.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from scconsensus_tpu.config import env_flag

__all__ = [
    "HOSTPROF_VERSION",
    "OUTSIDE_SPANS",
    "CATEGORIES",
    "HostProfiler",
    "classify_stack",
    "build_host_profile",
    "build_memory_timeline",
    "validate_host_profile",
    "validate_memory_timeline",
    "start_if_enabled",
    "active_profiler",
    "stop_active",
]

HOSTPROF_VERSION = 1

# Stage bucket for samples/pauses with no open stage span: between
# stages, before the first one, after the last one. An explicit name —
# not a dropped sample — because a GC storm between stages is real wall
# the run paid and the timeline must not silently shrink.
OUTSIDE_SPANS = "(outside spans)"

# Sampled-stack categories. ``gc`` seconds come from the callback
# accounting (measured pauses), never from samples — a sample landing
# mid-collection shows whatever Python frame triggered it.
CATEGORIES = ("python", "gc", "blocking_wait", "compile", "serialization")

# frame-name sets for the sampled-stack classifier (leaf-outward scan,
# first match wins — a python frame *waiting inside* block_until_ready
# is a blocking wait, not python compute)
_BLOCK_NAMES = frozenset({
    "block_until_ready", "_block_until_ready", "block_until_ready_if",
    "device_get", "_device_get", "device_drain", "_single_device_array",
    "copy_to_host_async", "_copy_to_host",
})
_SER_FILE_SUFFIXES = (
    os.path.join("json", "encoder.py"), os.path.join("json", "decoder.py"),
    os.path.join("json", "__init__.py"), "pickle.py",
)
_MAX_WALK_DEPTH = 64


def classify_stack(frame) -> Tuple[str, Optional[str]]:
    """Classify one sampled stack (leaf frame object) into a category +
    the leaf frame's ``file:func:line`` string. Pure over the frame
    chain; None frame classifies as python with no frame (the run
    thread can be gone by the time the sampler looks)."""
    if frame is None:
        return "python", None
    co = frame.f_code
    top = f"{os.path.basename(co.co_filename)}:{co.co_name}:{frame.f_lineno}"
    f, depth = frame, 0
    while f is not None and depth < _MAX_WALK_DEPTH:
        co = f.f_code
        fn, fl = co.co_name, co.co_filename
        if fn in _BLOCK_NAMES:
            return "blocking_wait", top
        if "jax" in fl and ("compile" in fn or "lower" in fn
                            or "jaxpr" in fn):
            return "compile", top
        if fl.endswith(_SER_FILE_SUFFIXES):
            return "serialization", top
        f = f.f_back
        depth += 1
    return "python", top


def _ambient_stage_name() -> Optional[str]:
    """Innermost open stage-span name, thread-safe (the sampler and the
    gc callback both run off the run thread's context)."""
    try:
        from scconsensus_tpu.obs.trace import ambient_stage

        return ambient_stage()[0]
    except Exception:
        return None


# --------------------------------------------------------------------------
# pure section builders (the degenerate-input tests drive these directly)
# --------------------------------------------------------------------------

def build_host_profile(
    samples: Iterable[Tuple[float, Optional[str], str, Optional[str]]],
    gc: Optional[Dict[str, Any]] = None,
    period_s: float = 0.02,
    sampler_self_s: float = 0.0,
    top_frames: int = 5,
) -> Dict[str, Any]:
    """``host_profile`` section from raw samples + GC accounting.

    ``samples``: ``(t_s, stage|None, category, frame|None)`` tuples;
    ``gc``: ``{"collections": int, "by_stage": {stage|None: {"pauses":
    n, "pause_s": s}}}``. A stage shorter than one sampling period
    simply has no samples (and therefore no row unless GC billed it) —
    zero rows is honest, zero seconds would be a lie about coverage.
    Always returns a section (the profiler *ran*); absence of the
    section on a record means the profiler never ran."""
    period_s = float(period_s)
    stages: Dict[str, Dict[str, Any]] = {}
    frames: Dict[str, Dict[str, int]] = {}
    n = 0
    for s in samples:
        n += 1
        stage = s[1] if s[1] else OUTSIDE_SPANS
        cat = s[2] if s[2] in CATEGORIES else "python"
        row = stages.setdefault(stage, {
            "samples": 0,
            "causes": {c: 0.0 for c in CATEGORIES},
        })
        row["samples"] += 1
        row["causes"][cat] = round(row["causes"][cat] + period_s, 6)
        fr = s[3] if len(s) > 3 else None
        if cat == "python" and isinstance(fr, str) and fr:
            fc = frames.setdefault(stage, {})
            fc[fr] = fc.get(fr, 0) + 1

    gc = gc or {}
    gc_total = 0.0
    gc_outside = 0.0
    for stage, p in (gc.get("by_stage") or {}).items():
        pauses = int(p.get("pauses") or 0)
        pause_s = float(p.get("pause_s") or 0.0)
        gc_total += pause_s
        key = stage if stage else OUTSIDE_SPANS
        if not stage:
            gc_outside += pause_s
        row = stages.setdefault(key, {
            "samples": 0,
            "causes": {c: 0.0 for c in CATEGORIES},
        })
        row["causes"]["gc"] = round(row["causes"]["gc"] + pause_s, 6)
        row["gc_pauses"] = row.get("gc_pauses", 0) + pauses

    for stage, row in stages.items():
        row["est_s"] = round(row["samples"] * period_s, 6)
        fc = frames.get(stage)
        if fc:
            ranked = sorted(fc.items(), key=lambda kv: (-kv[1], kv[0]))
            row["top_frame"] = ranked[0][0]
            row["top_frames"] = [
                {"frame": f, "samples": c}
                for f, c in ranked[:max(int(top_frames), 1)]
            ]

    return {
        "version": HOSTPROF_VERSION,
        "period_s": round(period_s, 6),
        "n_samples": n,
        "sampler_self_s": round(float(sampler_self_s), 6),
        "stages": {k: stages[k] for k in sorted(stages)},
        "gc": {
            "collections": int(gc.get("collections") or 0),
            "pause_s": round(gc_total, 6),
            "outside_spans_pause_s": round(gc_outside, 6),
        },
    }


def build_memory_timeline(
    mem_samples: Iterable[
        Tuple[float, Optional[int], Optional[int], Optional[str]]
    ],
    period_s: float = 0.02,
    max_points: int = 240,
) -> Optional[Dict[str, Any]]:
    """``memory_timeline`` section from ``(t_s, rss_bytes|None,
    hbm_bytes|None, stage|None)`` ticks, downsampled to ``max_points``
    evenly spaced samples (the full grid at 50 Hz over a long run would
    dwarf the record). None when nothing was sampled — absence, never
    an empty timeline claiming the run used no memory."""
    rows = [
        (float(s[0]), int(s[1]),
         int(s[2]) if len(s) > 2 and s[2] is not None else None,
         s[3] if len(s) > 3 and s[3] else None)
        for s in mem_samples
        if s[1] is not None and int(s[1]) >= 0 and float(s[0]) >= 0
    ]
    if not rows:
        return None
    rows.sort(key=lambda r: r[0])
    n = len(rows)
    rss_peak = max(r[1] for r in rows)
    hbm_vals = [r[2] for r in rows if r[2] is not None]

    by_stage: Dict[str, Dict[str, int]] = {}
    for _, rss, _, stage in rows:
        key = stage or OUTSIDE_SPANS
        st = by_stage.setdefault(key, {"rss_first_bytes": rss,
                                       "rss_peak_bytes": rss,
                                       "rss_last_bytes": rss})
        st["rss_peak_bytes"] = max(st["rss_peak_bytes"], rss)
        st["rss_last_bytes"] = rss
    for st in by_stage.values():
        st["rss_delta_bytes"] = st["rss_last_bytes"] - st["rss_first_bytes"]

    keep = rows
    if n > max_points > 0:
        step = n / float(max_points)
        keep = [rows[min(int(i * step), n - 1)] for i in range(max_points)]
        keep[-1] = rows[-1]  # the final sample always survives

    samples: List[Dict[str, Any]] = []
    for t, rss, hbm, stage in keep:
        row: Dict[str, Any] = {"t_s": round(t, 4), "rss_bytes": rss}
        if hbm is not None:
            row["hbm_bytes"] = hbm
        if stage:
            row["stage"] = stage
        samples.append(row)

    sec: Dict[str, Any] = {
        "version": HOSTPROF_VERSION,
        "period_s": round(float(period_s), 6),
        "n_samples": n,
        "samples": samples,
        "rss_peak_bytes": rss_peak,
        "by_stage": {k: by_stage[k] for k in sorted(by_stage)},
    }
    if hbm_vals:
        sec["hbm_peak_bytes"] = max(hbm_vals)
    return sec


# --------------------------------------------------------------------------
# the live sampler
# --------------------------------------------------------------------------

class HostProfiler:
    """Low-overhead sampling profiler for one run thread.

    ``start()`` registers the ``gc.callbacks`` hook and launches the
    sampler thread; ``sections()`` snapshots both record sections at
    any point (``bench._finalize`` reads a still-running profiler);
    ``stop()`` tears both down. Every accessor is best-effort: the
    profiler observes the run, it must never kill it."""

    def __init__(self, period_s: float = 0.02,
                 thread_ident: Optional[int] = None,
                 hbm_every: int = 10, max_samples: int = 500_000):
        self.period_s = max(float(period_s), 0.001)
        self._ident = thread_ident if thread_ident is not None \
            else threading.get_ident()
        self._hbm_every = max(int(hbm_every), 1)
        self._max_samples = int(max_samples)
        self._t0 = time.perf_counter()
        self._samples: List[Tuple[float, Optional[str], str,
                                  Optional[str]]] = []
        self._mem: List[Tuple[float, Optional[int], Optional[int],
                              Optional[str]]] = []
        self._gc_by_stage: Dict[Optional[str], Dict[str, float]] = {}
        self._gc_collections = 0
        self._gc_t0: Optional[float] = None
        self._self_s = 0.0
        self._ticks = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._gc_cb = None

    # -- gc pause accounting ----------------------------------------------
    def _on_gc(self, phase: str, info: Dict[str, Any]) -> None:
        try:
            if phase == "start":
                self._gc_t0 = time.perf_counter()
                return
            t0 = self._gc_t0
            self._gc_t0 = None
            if t0 is None:
                return
            pause = time.perf_counter() - t0
            stage = _ambient_stage_name()
            with self._lock:
                self._gc_collections += 1
                row = self._gc_by_stage.setdefault(
                    stage, {"pauses": 0, "pause_s": 0.0}
                )
                row["pauses"] += 1
                row["pause_s"] += pause
        except Exception:
            pass  # a broken probe must not break collection itself

    # -- sampler loop ------------------------------------------------------
    def _tick(self) -> None:
        t_s = time.perf_counter() - self._t0
        frame = sys._current_frames().get(self._ident)
        stage = _ambient_stage_name()
        cat, top = classify_stack(frame)
        hbm = None
        if self._ticks % self._hbm_every == 0:
            try:
                from scconsensus_tpu.obs import device as obs_device

                ms = obs_device.memory_snapshot()
                if ms:
                    hbm = ms.get("bytes_in_use")
            except Exception:
                hbm = None
        try:
            from scconsensus_tpu.obs import device as obs_device

            rss = obs_device.host_rss_bytes()
        except Exception:
            rss = None
        with self._lock:
            if len(self._samples) < self._max_samples:
                self._samples.append((t_s, stage, cat, top))
                self._mem.append((t_s, rss, hbm, stage))

    def _loop(self) -> None:
        next_t = time.perf_counter()
        while not self._stop.is_set():
            # thread_time, not perf_counter: like the flight recorder's
            # tick accounting, GIL waits while the run thread computes
            # are scheduling, not sampler cost — wall-clock self-timing
            # would charge them to the profiler
            w0 = time.thread_time()
            try:
                self._tick()
            except Exception:
                pass
            self._ticks += 1
            self._self_s += time.thread_time() - w0
            next_t += self.period_s
            delay = next_t - time.perf_counter()
            if delay <= 0:
                # fell behind (GIL starvation): resync instead of a
                # catch-up burst that would multiply the overhead
                next_t = time.perf_counter() + self.period_s
                delay = self.period_s
            self._stop.wait(delay)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "HostProfiler":
        import gc

        if self._thread is not None:
            return self
        self._gc_cb = self._on_gc
        gc.callbacks.append(self._gc_cb)
        self._thread = threading.Thread(
            target=self._loop, name="scc-hostprof", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        import gc

        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._gc_cb is not None:
            try:
                gc.callbacks.remove(self._gc_cb)
            except ValueError:
                pass
            self._gc_cb = None

    # -- views -------------------------------------------------------------
    def sections(self) -> Dict[str, Optional[Dict[str, Any]]]:
        """Both record sections from the data collected so far (safe on
        a still-running profiler — ``bench._finalize`` snapshots here
        while the sampler keeps ticking)."""
        with self._lock:
            samples = list(self._samples)
            mem = list(self._mem)
            gc_stat = {
                "collections": self._gc_collections,
                "by_stage": {k: dict(v)
                             for k, v in self._gc_by_stage.items()},
            }
            self_s = self._self_s
        return {
            "host_profile": build_host_profile(
                samples, gc=gc_stat, period_s=self.period_s,
                sampler_self_s=self_s,
            ),
            "memory_timeline": build_memory_timeline(
                mem, period_s=self.period_s
            ),
        }


# module-level active profiler (one per process, like the flight recorder)
_ACTIVE: Dict[str, Optional[HostProfiler]] = {"prof": None}


def start_if_enabled() -> Optional[HostProfiler]:
    """Start (once) the process profiler when ``SCC_HOSTPROF`` is set;
    period from ``SCC_HOSTPROF_HZ``. Returns the active profiler or
    None (disabled)."""
    if _ACTIVE["prof"] is not None:
        return _ACTIVE["prof"]
    if not env_flag("SCC_HOSTPROF"):
        return None
    hz = float(env_flag("SCC_HOSTPROF_HZ") or 0.0)
    period = 1.0 / hz if hz > 0 else 0.02
    prof = HostProfiler(period_s=period).start()
    _ACTIVE["prof"] = prof
    return prof


def active_profiler() -> Optional[HostProfiler]:
    return _ACTIVE["prof"]


def stop_active() -> None:
    prof = _ACTIVE["prof"]
    _ACTIVE["prof"] = None
    if prof is not None:
        prof.stop()


# --------------------------------------------------------------------------
# validation (export.validate_run_record dispatches here)
# --------------------------------------------------------------------------

def _require(cond: bool, section: str, msg: str) -> None:
    if not cond:
        raise ValueError(f"{section} section: {msg}")


def validate_host_profile(sec: Dict[str, Any]) -> None:
    """Structural validation of a record's ``host_profile`` section
    (additive scc-run-record v1 extension)."""
    _require(isinstance(sec, dict), "host_profile", "must be an object")
    _require(sec.get("version") == HOSTPROF_VERSION, "host_profile",
             f"version must be {HOSTPROF_VERSION}")
    p = sec.get("period_s")
    _require(isinstance(p, (int, float)) and p > 0, "host_profile",
             "period_s must be a number > 0")
    n = sec.get("n_samples")
    _require(isinstance(n, int) and n >= 0, "host_profile",
             "n_samples must be an int >= 0")
    ss = sec.get("sampler_self_s")
    _require(isinstance(ss, (int, float)) and ss >= 0, "host_profile",
             "sampler_self_s must be a number >= 0")
    stages = sec.get("stages")
    _require(isinstance(stages, dict), "host_profile",
             "stages must be an object")
    total_samples = 0
    for name, row in stages.items():
        _require(isinstance(row, dict), "host_profile",
                 f"stages[{name!r}] is not an object")
        k = row.get("samples")
        _require(isinstance(k, int) and k >= 0, "host_profile",
                 f"stages[{name!r}].samples must be an int >= 0")
        total_samples += k
        causes = row.get("causes")
        _require(isinstance(causes, dict), "host_profile",
                 f"stages[{name!r}].causes must be an object")
        for c in CATEGORIES:
            v = causes.get(c)
            _require(isinstance(v, (int, float)) and v >= 0,
                     "host_profile",
                     f"stages[{name!r}].causes.{c} must be >= 0")
        est = row.get("est_s")
        _require(isinstance(est, (int, float)) and est >= 0,
                 "host_profile", f"stages[{name!r}].est_s must be >= 0")
        tf = row.get("top_frames")
        if tf is not None:
            _require(isinstance(tf, list), "host_profile",
                     f"stages[{name!r}].top_frames must be a list")
            for e in tf:
                _require(isinstance(e, dict) and isinstance(
                    e.get("frame"), str) and isinstance(
                        e.get("samples"), int), "host_profile",
                    f"stages[{name!r}].top_frames entries need "
                    "frame/samples")
    _require(total_samples == n, "host_profile",
             "per-stage samples do not sum to n_samples")
    g = sec.get("gc")
    _require(isinstance(g, dict), "host_profile", "gc must be an object")
    c = g.get("collections")
    _require(isinstance(c, int) and c >= 0, "host_profile",
             "gc.collections must be an int >= 0")
    for k in ("pause_s", "outside_spans_pause_s"):
        v = g.get(k)
        _require(isinstance(v, (int, float)) and v >= 0, "host_profile",
                 f"gc.{k} must be a number >= 0")


def validate_memory_timeline(sec: Dict[str, Any]) -> None:
    """Structural validation of a record's ``memory_timeline`` section."""
    _require(isinstance(sec, dict), "memory_timeline", "must be an object")
    _require(sec.get("version") == HOSTPROF_VERSION, "memory_timeline",
             f"version must be {HOSTPROF_VERSION}")
    n = sec.get("n_samples")
    _require(isinstance(n, int) and n >= 1, "memory_timeline",
             "n_samples must be an int >= 1")
    samples = sec.get("samples")
    _require(isinstance(samples, list) and samples, "memory_timeline",
             "samples must be a non-empty list")
    _require(len(samples) <= n, "memory_timeline",
             "more samples than n_samples claims were taken")
    last_t = -1.0
    for i, s in enumerate(samples):
        _require(isinstance(s, dict), "memory_timeline",
                 f"samples[{i}] is not an object")
        t = s.get("t_s")
        _require(isinstance(t, (int, float)) and t >= 0,
                 "memory_timeline", f"samples[{i}].t_s must be >= 0")
        _require(t >= last_t, "memory_timeline",
                 "samples must be time-ordered")
        last_t = t
        r = s.get("rss_bytes")
        _require(isinstance(r, int) and r >= 0, "memory_timeline",
                 f"samples[{i}].rss_bytes must be an int >= 0")
        h = s.get("hbm_bytes")
        _require(h is None or (isinstance(h, int) and h >= 0),
                 "memory_timeline",
                 f"samples[{i}].hbm_bytes must be an int >= 0")
    peak = sec.get("rss_peak_bytes")
    _require(isinstance(peak, int) and peak >= 0, "memory_timeline",
             "rss_peak_bytes must be an int >= 0")
    _require(peak >= max(s["rss_bytes"] for s in samples),
             "memory_timeline",
             "rss_peak_bytes below a carried sample")
    bs = sec.get("by_stage")
    if bs is not None:
        _require(isinstance(bs, dict), "memory_timeline",
                 "by_stage must be an object")
        for name, row in bs.items():
            _require(isinstance(row, dict), "memory_timeline",
                     f"by_stage[{name!r}] is not an object")
            for k in ("rss_first_bytes", "rss_peak_bytes",
                      "rss_last_bytes"):
                v = row.get(k)
                _require(isinstance(v, int) and v >= 0,
                         "memory_timeline",
                         f"by_stage[{name!r}].{k} must be an int >= 0")
