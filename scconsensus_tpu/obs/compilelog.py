"""Per-stage JAX compile/retrace telemetry → the ``compile`` section.

``obs.device`` has captured compilation-shaped ``jax.monitoring``
duration events since the first obs round, but only as a flat
process-wide ``{events, total_s}`` aggregate — no stage attribution, no
cache-hit signal, no way to say "stage X retraced". This module
promotes that capture into the run record's keyed ``compile`` section:

* **compiles / traces / retraces / compile wall** — duration events are
  classified by normalized spelling (``backend_compile``-shaped events
  are XLA compiles; ``trace``-shaped events are jaxpr traces) and each
  event arrives stamped with the ambient stage span *and that stage's
  entry ordinal* (:func:`~scconsensus_tpu.obs.trace.ambient_stage`). A
  trace-shaped event on a stage's second-or-later entry is a
  **retrace**: jit caching makes a re-entered stage event-free, so any
  tracing there means the cache missed (shape churn, weak-type flips,
  new donation patterns — exactly what ROADMAP item 1's fusion work
  must not reintroduce).
* **cache hits** — the persistent compilation cache reports
  ``compile_requests_use_cache`` through the plain event listener;
  :func:`build_compile_section` joins the count in.

The section builder is pure over the captured event tuples (tests feed
it synthetic streams); the runtime half (:func:`install_and_mark` /
:func:`snapshot`) arms the process listeners and marks the stream so
``bench._finalize`` stamps only this run's events. Gated by
``SCC_COMPILELOG`` (bench workers default it on); the listener costs
one lock + tuple append per compile event — compiles are seconds-scale,
the log is noise-floor-invisible (pinned by test next to the sampler).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.obs.hostprof import OUTSIDE_SPANS

__all__ = [
    "COMPILELOG_VERSION",
    "build_compile_section",
    "validate_compile",
    "install_and_mark",
    "armed",
    "snapshot",
    "event_kind",
]

COMPILELOG_VERSION = 1


def _norm_key(k: str) -> str:
    # same normalization as obs.cost: lowercase, collapse non-alnum runs
    # to one underscore — the spelling-drift armor for jax upgrades
    out = []
    for ch in str(k).strip().lower():
        if ch.isalnum():
            out.append(ch)
        elif not out or out[-1] != "_":
            out.append("_")
    return "".join(out).strip("_")


def event_kind(name: str) -> str:
    """Classify one duration-event name: ``backend`` (XLA compile),
    ``trace`` (jaxpr trace / lowering), or ``other`` compilation-shaped
    work. Normalized-spelling match, so jax 0.4's
    ``/jax/core/compile/backend_compile_duration`` and any future
    ``backendCompile`` respelling classify identically."""
    # match on the separator-stripped spelling too: a camelCase respell
    # ("backendCompile") has no non-alnum run for _norm_key to collapse
    flat = _norm_key(name).replace("_", "")
    if "backendcompile" in flat:
        return "backend"
    if "trace" in flat:
        return "trace"
    return "other"


def build_compile_section(
    dur_events: Iterable[Sequence],
    cache_hits: int = 0,
) -> Dict[str, Any]:
    """``compile`` section from captured duration events.

    ``dur_events``: ``(name, secs[, stage|None[, entry_ordinal]])``
    tuples as :func:`obs.device.compile_events` returns them (bare
    2-tuples — the legacy capture shape — default to no stage, first
    entry). Zero events with an armed log is an honest section of
    zeros: "this run compiled nothing" is evidence, not absence."""
    events = compiles = traces = retraces = 0
    wall = 0.0
    by_event: Dict[str, Dict[str, Any]] = {}
    by_stage: Dict[str, Dict[str, Any]] = {}
    for ev in dur_events:
        name, secs = str(ev[0]), float(ev[1])
        stage = (ev[2] if len(ev) > 2 and ev[2] else OUTSIDE_SPANS)
        occ = int(ev[3]) if len(ev) > 3 and ev[3] else 1
        kind = event_kind(name)
        events += 1
        wall += secs
        is_retrace = kind == "trace" and occ >= 2
        if kind == "backend":
            compiles += 1
        elif kind == "trace":
            traces += 1
            if is_retrace:
                retraces += 1
        be = by_event.setdefault(_norm_key(name), {"n": 0, "total_s": 0.0})
        be["n"] += 1
        be["total_s"] += secs
        bs = by_stage.setdefault(stage, {
            "events": 0, "compiles": 0, "retraces": 0, "total_s": 0.0,
        })
        bs["events"] += 1
        bs["total_s"] += secs
        if kind == "backend":
            bs["compiles"] += 1
        if is_retrace:
            bs["retraces"] += 1
    for row in by_event.values():
        row["total_s"] = round(row["total_s"], 6)
    for row in by_stage.values():
        row["total_s"] = round(row["total_s"], 6)
    return {
        "version": COMPILELOG_VERSION,
        "events": events,
        "compiles": compiles,
        "traces": traces,
        "retraces": retraces,
        "cache_hits": int(cache_hits),
        "compile_wall_s": round(wall, 6),
        "by_event": {k: by_event[k] for k in sorted(by_event)},
        "by_stage": {k: by_stage[k] for k in sorted(by_stage)},
    }


# --------------------------------------------------------------------------
# runtime: arm the listeners, mark the stream, snapshot at finalize
# --------------------------------------------------------------------------

# dur_mark/cache_mark are positions in obs.device's process-wide event
# streams at arm time, so a worker's section counts only its own run
_STATE: Dict[str, Any] = {"armed": False, "dur_mark": 0, "cache_mark": 0}


def install_and_mark(force: bool = False) -> bool:
    """Arm compile logging: install the jax.monitoring listeners (via
    obs.device, once per process) and mark the event streams. Gated on
    ``SCC_COMPILELOG`` unless ``force``. Returns whether the log is
    armed — False with jax not yet imported (call again after; never
    the first jax touch) or on listenerless jax builds."""
    if not force and not env_flag("SCC_COMPILELOG"):
        return False
    from scconsensus_tpu.obs import device as obs_device

    if not obs_device.install_compile_listener():
        return False
    _STATE["armed"] = True
    _STATE["dur_mark"] = obs_device.compile_mark()
    _STATE["cache_mark"] = obs_device.cache_mark()
    return True


def armed() -> bool:
    return bool(_STATE["armed"])


def snapshot(dur_mark: Optional[int] = None,
             cache_mark: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """The ``compile`` section for events since the arm marks (explicit
    marks override, for tests that scope to their own window). None
    when the log was never armed — the record omits the section rather
    than claim a run that wasn't listening compiled nothing."""
    if dur_mark is None and not _STATE["armed"]:
        return None
    from scconsensus_tpu.obs import device as obs_device

    dm = _STATE["dur_mark"] if dur_mark is None else int(dur_mark)
    cm = _STATE["cache_mark"] if cache_mark is None else int(cache_mark)
    return build_compile_section(
        obs_device.compile_events(since=dm),
        cache_hits=len(obs_device.cache_events(since=cm)),
    )


# --------------------------------------------------------------------------
# validation (export.validate_run_record dispatches here)
# --------------------------------------------------------------------------

def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"compile section: {msg}")


def validate_compile(sec: Dict[str, Any]) -> None:
    """Structural validation of a record's ``compile`` section
    (additive scc-run-record v1 extension)."""
    _require(isinstance(sec, dict), "must be an object")
    _require(sec.get("version") == COMPILELOG_VERSION,
             f"version must be {COMPILELOG_VERSION}")
    for k in ("events", "compiles", "traces", "retraces", "cache_hits"):
        v = sec.get(k)
        _require(isinstance(v, int) and v >= 0,
                 f"{k} must be an int >= 0")
    _require(sec["compiles"] + sec["traces"] <= sec["events"],
             "compiles + traces exceed total events")
    _require(sec["retraces"] <= sec["traces"],
             "more retraces than traces")
    w = sec.get("compile_wall_s")
    _require(isinstance(w, (int, float)) and w >= 0,
             "compile_wall_s must be a number >= 0")
    be = sec.get("by_event")
    _require(isinstance(be, dict), "by_event must be an object")
    n_sum = 0
    for name, row in be.items():
        _require(isinstance(row, dict), f"by_event[{name!r}] not an object")
        n = row.get("n")
        _require(isinstance(n, int) and n >= 1,
                 f"by_event[{name!r}].n must be an int >= 1")
        n_sum += n
        t = row.get("total_s")
        _require(isinstance(t, (int, float)) and t >= 0,
                 f"by_event[{name!r}].total_s must be >= 0")
    _require(n_sum == sec["events"],
             "by_event counts do not sum to events")
    bs = sec.get("by_stage")
    _require(isinstance(bs, dict), "by_stage must be an object")
    ev_sum = 0
    for name, row in bs.items():
        _require(isinstance(row, dict), f"by_stage[{name!r}] not an object")
        for k in ("events", "compiles", "retraces"):
            v = row.get(k)
            _require(isinstance(v, int) and v >= 0,
                     f"by_stage[{name!r}].{k} must be an int >= 0")
        _require(row["compiles"] + row["retraces"] <= row["events"],
                 f"by_stage[{name!r}] counts exceed its events")
        ev_sum += row["events"]
        t = row.get("total_s")
        _require(isinstance(t, (int, float)) and t >= 0,
                 f"by_stage[{name!r}].total_s must be >= 0")
    _require(ev_sum == sec["events"],
             "by_stage events do not sum to events")
