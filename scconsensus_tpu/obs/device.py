"""Device-side samplers: memory, compile events, transfer bytes.

Nothing in the pre-obs stack captured device memory, compile events, or
host↔device transfer volume — the exact signals a TPU pipeline needs to
keep scaling (a silent host round-trip through the ~36 MB/s axon tunnel
costs more than most kernels). Three probes, all best-effort and
backend-tolerant (every accessor degrades to None/empty rather than raise):

  * :func:`memory_snapshot` — live/peak HBM from ``Device.memory_stats()``
    (TPU/GPU; CPU backends return None) plus :func:`host_peak_rss_bytes`
    as the host-side fallback every record can carry;
  * :func:`install_compile_listener` — a ``jax.monitoring`` duration
    listener counting compile events and total compile seconds, snapshot
    via :func:`compile_mark` / :func:`compile_stats`;
  * :class:`TransferWatch` — a scoped wrapper over ``jax.device_put`` /
    ``jax.device_get`` that accumulates transfer bytes per direction and
    flags single host-bound fetches above a threshold (the "unexpected
    host round-trip" guard).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "memory_snapshot",
    "host_rss_bytes",
    "host_peak_rss_bytes",
    "install_compile_listener",
    "compile_mark",
    "compile_stats",
    "compile_events",
    "cache_mark",
    "cache_events",
    "TransferWatch",
]


# --------------------------------------------------------------------------
# memory
# --------------------------------------------------------------------------

def _backend_initialized() -> bool:
    """Whether some jax backend has ALREADY initialized (without
    triggering one). Reads sys.modules only — never an import: the
    hostprof sampler thread calls this every tick, and an off-thread
    ``from jax._src import xla_bridge`` racing the main thread's own
    in-progress jax import corrupts the partially-initialized module
    graph. Best-effort; unknown jax internals degrade to True (the
    pre-guard behavior)."""
    try:
        import sys

        xla_bridge = sys.modules.get("jax._src.xla_bridge")
        if xla_bridge is None:
            return False  # bridge never imported: no backend is up
        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return True

def memory_snapshot(device=None) -> Optional[Dict[str, int]]:
    """Live/peak device memory of one device (default: first local device).
    None when no backend is up or the backend has no memory_stats (CPU)."""
    try:
        import sys

        if "jax" not in sys.modules:
            # never the first jax touch: an orchestrator-side record must
            # not trigger backend/plugin init just to sample memory
            return None
        jax = sys.modules["jax"]
        if device is None and not _backend_initialized():
            # jax imported but no backend up yet: local_devices() would
            # INITIALIZE one — from the flight recorder's sampler thread
            # that means a surprise (possibly hanging, on a dead tunnel)
            # backend init the run never asked for
            return None

        d = device if device is not None else jax.local_devices()[0]
        ms = d.memory_stats()
        if not ms:
            return None
        out = {
            k: int(ms[k])
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
            if k in ms
        }
        return out or None
    except Exception:
        return None


# cached /proc/self/statm fd (+ owning pid, so a fork re-opens) and page
# size: the heartbeat sampler reads this EVERY tick while the run thread
# may be hogging the GIL — a naive open/read/close is ~8 GIL bounces,
# each costing a switch-interval wait under contention (measured ~50 ms
# wall per call next to a busy Python loop); one pread is one bounce.
# Lock-guarded: the sampler thread and the budget accountant's charge()
# path race here, and an unguarded cache could close an fd the other
# thread is mid-pread on.
_STATM = {"fd": None, "pid": None, "page": None}
_STATM_LOCK = threading.Lock()


def host_rss_bytes() -> Optional[int]:
    """CURRENT resident set size of this process (``/proc/self/statm`` on
    Linux; falls back to the peak where /proc is unavailable). The
    instantaneous twin of :func:`host_peak_rss_bytes` — the heartbeat
    stream carries both, so a live view shows where RSS *is* while the
    streaming budget assertion and the run record read the same
    peak-since-start number."""
    try:
        import os

        with _STATM_LOCK:
            pid = os.getpid()
            if _STATM["fd"] is None or _STATM["pid"] != pid:
                fd = os.open("/proc/self/statm", os.O_RDONLY)
                old = _STATM["fd"]
                _STATM["fd"], _STATM["pid"] = fd, pid
                if old is not None:
                    try:
                        os.close(old)
                    except OSError:
                        pass
            if _STATM["page"] is None:
                _STATM["page"] = os.sysconf("SC_PAGE_SIZE")
            # procfs regenerates content per read; pread needs no seek
            return int(os.pread(_STATM["fd"], 128, 0).split()[1]) \
                * _STATM["page"]
    except Exception:
        return host_peak_rss_bytes()


def host_peak_rss_bytes() -> Optional[int]:
    """Peak resident set size of this process since start (ru_maxrss is
    KiB on Linux). This — not the instantaneous RSS — is the number a
    bounded-memory claim must be judged by: a spike between two heartbeat
    ticks is invisible to sampling but not to the kernel's high-water
    mark, so the streaming budget evidence (stream.budget) and the
    tail_run panel both read THIS accessor."""
    try:
        import resource
        import sys

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(ru) if sys.platform == "darwin" else int(ru) * 1024
    except Exception:
        return None


# --------------------------------------------------------------------------
# compile events (jax.monitoring)
# --------------------------------------------------------------------------

_COMPILE_LOCK = threading.Lock()
# Rich events are (name, secs, stage|None, stage_entry_ordinal); legacy
# writers (and older tests) still append bare (name, secs) 2-tuples, so
# every consumer unpacks with tolerance. Stage/ordinal come from
# trace.ambient_stage() at capture time — jax.monitoring hands us no
# function identity, so WHERE (which open stage, which entry of it) is
# the join key the compile section is built on.
_COMPILE_EVENTS: List[Tuple] = []
_CACHE_EVENTS: List[Tuple] = []  # compilation-cache-hit plain events
_LISTENER_STATE = {"installed": None}  # None = not attempted yet

_EVENT_CAP = {"v": None}  # lazily resolved SCC_COMPILELOG_MAX_EVENTS


def _norm_key(k: str) -> str:
    # obs.cost's spelling-drift armor: lowercase, collapse non-alnum
    # runs to one underscore
    out: List[str] = []
    for ch in str(k).strip().lower():
        if ch.isalnum():
            out.append(ch)
        elif not out or out[-1] != "_":
            out.append("_")
    return "".join(out).strip("_")


def _event_cap() -> int:
    if _EVENT_CAP["v"] is None:
        try:
            from scconsensus_tpu.config import env_flag

            _EVENT_CAP["v"] = int(
                env_flag("SCC_COMPILELOG_MAX_EVENTS") or 65536
            )
        except Exception:
            _EVENT_CAP["v"] = 65536
    return _EVENT_CAP["v"]


def _ambient_stage() -> Tuple[Optional[str], int]:
    try:
        from scconsensus_tpu.obs.trace import ambient_stage

        return ambient_stage()
    except Exception:
        return (None, 0)


def _on_duration(event: str, duration: float, **kw) -> None:
    # jax emits many duration events; keep only compilation-shaped ones
    # ('/jax/core/compile/...', backend_compile, pjit compilation, ...).
    # Version-tolerant: the raw substring check is backed by the
    # normalized spelling, so a jax upgrade respelling the event family
    # ('backendCompile', 'Compilation') cannot silently zero the section.
    name = str(event)
    norm = _norm_key(name)
    if "compil" not in name and "compil" not in norm:
        return
    # derived savings metrics are not wall time spent — jax's
    # compile_time_saved_sec can even go NEGATIVE (cache retrieval
    # slower than the compile it replaced) and would corrupt the
    # section's wall sum; real durations are never negative either
    if "saved" in norm or float(duration) < 0:
        return
    stage, occ = _ambient_stage()
    with _COMPILE_LOCK:
        if len(_COMPILE_EVENTS) < _event_cap():
            _COMPILE_EVENTS.append((name, float(duration), stage, occ))


def _on_event(event: str, **kw) -> None:
    # plain (durationless) events: keep compilation-cache hits
    # ('/jax/compilation_cache/compile_requests_use_cache' on jax 0.4;
    # normalized match for future respellings)
    norm = _norm_key(event)
    if "cache" in norm and ("compil" in norm or "use_cache" in norm):
        stage, occ = _ambient_stage()
        with _COMPILE_LOCK:
            if len(_CACHE_EVENTS) < _event_cap():
                _CACHE_EVENTS.append((str(event), stage, occ))


def install_compile_listener() -> bool:
    """Register the compile-duration listener (plus the cache-hit plain
    event listener, best-effort) once per process. Returns whether the
    duration listener is active (False on jax builds without
    ``jax.monitoring`` duration listeners). Never the first jax touch: if
    jax has not been imported yet the attempt is deferred (not cached), so
    a later tracer created after jax is up still installs it."""
    import sys

    with _COMPILE_LOCK:
        if _LISTENER_STATE["installed"] is not None:
            return _LISTENER_STATE["installed"]
        if "jax" not in sys.modules:
            return False
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_duration)
            _LISTENER_STATE["installed"] = True
        except Exception:
            _LISTENER_STATE["installed"] = False
        if _LISTENER_STATE["installed"]:
            try:
                from jax import monitoring

                monitoring.register_event_listener(_on_event)
            except Exception:
                pass  # cache hits degrade to 0; compiles still counted
        return _LISTENER_STATE["installed"]


def compile_mark() -> int:
    """Opaque position in the compile-event stream; pass to
    :func:`compile_stats` to aggregate only the events after it."""
    with _COMPILE_LOCK:
        return len(_COMPILE_EVENTS)


def compile_stats(since: int = 0) -> Dict[str, Any]:
    """Aggregate compile events observed after ``since``."""
    with _COMPILE_LOCK:
        events = _COMPILE_EVENTS[since:]
    by_event: Dict[str, Dict[str, float]] = {}
    for ev in events:
        rec = by_event.setdefault(ev[0], {"n": 0, "total_s": 0.0})
        rec["n"] += 1
        rec["total_s"] += ev[1]
    for rec in by_event.values():
        rec["total_s"] = round(rec["total_s"], 4)
    return {
        "events": len(events),
        "total_s": round(sum(ev[1] for ev in events), 4),
        "by_event": by_event,
    }


def compile_events(since: int = 0) -> List[Tuple]:
    """Raw compile-event tuples after ``since``: ``(name, secs, stage,
    entry_ordinal)`` (legacy appenders may have left bare 2-tuples —
    consumers unpack with tolerance). obs.compilelog builds the run
    record's ``compile`` section from these."""
    with _COMPILE_LOCK:
        return list(_COMPILE_EVENTS[since:])


def cache_mark() -> int:
    """Opaque position in the compilation-cache-hit event stream."""
    with _COMPILE_LOCK:
        return len(_CACHE_EVENTS)


def cache_events(since: int = 0) -> List[Tuple]:
    """Raw cache-hit tuples ``(name, stage, entry_ordinal)`` after
    ``since``."""
    with _COMPILE_LOCK:
        return list(_CACHE_EVENTS[since:])


# --------------------------------------------------------------------------
# transfer-bytes guard
# --------------------------------------------------------------------------

def _tree_nbytes(tree) -> int:
    try:
        import jax

        return sum(
            int(getattr(leaf, "nbytes", 0) or 0)
            for leaf in jax.tree_util.tree_leaves(tree)
        )
    except Exception:
        return 0


class TransferWatch:
    """Scoped accounting of explicit host↔device transfers.

    Wraps ``jax.device_put`` / ``jax.device_get`` for the duration of the
    context and accumulates bytes per direction. Fetches larger than
    ``flag_host_bytes`` are recorded as *flags* with the ambient span's
    name — the signature of an accidental (P, G)-sized host round-trip
    the lazy-fetch machinery exists to prevent.

    Best-effort by design: implicit transfers (``np.asarray`` on a device
    array, donated buffers, compiled-program outputs) bypass these entry
    points and are not counted. The count is a lower bound; the FLAGS are
    what matter operationally.
    """

    def __init__(self, flag_host_bytes: int = 64 << 20):
        self.flag_host_bytes = int(flag_host_bytes)
        self.to_device_bytes = 0
        self.to_host_bytes = 0
        self.to_device_calls = 0
        self.to_host_calls = 0
        self.flags: List[Dict[str, Any]] = []
        self._orig_put = None
        self._orig_get = None
        self._lock = threading.Lock()

    def _span_name(self) -> Optional[str]:
        try:
            from scconsensus_tpu.obs.trace import current_span

            sp = current_span()
            return sp.name if sp is not None else None
        except Exception:
            return None

    def __enter__(self) -> "TransferWatch":
        import jax

        self._orig_put = jax.device_put
        self._orig_get = jax.device_get
        watch = self

        def put(x, *a, **kw):
            with watch._lock:
                watch.to_device_calls += 1
                watch.to_device_bytes += _tree_nbytes(x)
            return watch._orig_put(x, *a, **kw)

        def get(x, *a, **kw):
            nb = _tree_nbytes(x)
            with watch._lock:
                watch.to_host_calls += 1
                watch.to_host_bytes += nb
                if nb > watch.flag_host_bytes:
                    watch.flags.append({
                        "bytes": nb,
                        "span": watch._span_name(),
                    })
            return watch._orig_get(x, *a, **kw)

        jax.device_put = put
        jax.device_get = get
        return self

    def __exit__(self, *exc) -> None:
        import jax

        jax.device_put = self._orig_put
        jax.device_get = self._orig_get

    def report(self) -> Dict[str, Any]:
        return {
            "to_device_bytes": self.to_device_bytes,
            "to_device_calls": self.to_device_calls,
            "to_host_bytes": self.to_host_bytes,
            "to_host_calls": self.to_host_calls,
            "flag_host_bytes": self.flag_host_bytes,
            "flags": self.flags,
        }
