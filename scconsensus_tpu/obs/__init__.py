"""Unified observability subsystem — the single source of perf truth.

Four layers, consumed together through one versioned run-record schema:

  * ``obs.trace``   — nested-span tracer with explicit device-sync
    boundaries (submitted vs device-synced walls per span);
  * ``obs.metrics`` — typed counters/gauges/histograms keyed by span
    (gene counts, pad ratios, tied-run tables, nnz — the payloads the
    SCC_WILCOX_PROBE side channel used to smuggle through env flags);
  * ``obs.device``  — live/peak device-memory samplers, compile-event
    listeners (jax.monitoring), and a transfer-bytes guard flagging
    unexpected host round-trips;
  * ``obs.export``  — the ``scc-run-record`` schema plus a Chrome
    trace-event exporter (any run opens in Perfetto);
  * ``obs.cost``    — XLA ``cost_analysis`` FLOPs/bytes attached to
    jitted kernel spans at trace time (SCC_OBS_COST), so records carry
    achieved-vs-cost-model throughput per stage;
  * ``obs.ledger``  — the manifest-indexed evidence store under
    ``evidence/`` (plus the one-shot legacy-artifact upgrader);
  * ``obs.regress`` — noise-aware per-stage baselines (median-of-3,
    BASELINE.md policy), regression verdicts with span-tree offender
    diffs, and the numeric-drift sentinels + drift-acknowledgement
    ledger (``tools/perf_gate.py`` is the CLI);
  * ``obs.live``   — the flight recorder: heartbeat JSONL stream,
    in-process stall watchdog with faulthandler stack dumps (and
    on-demand profiler captures), crash-safe incremental partial run
    records stamped with a termination cause (``tools/tail_run.py``
    renders the stream live);
  * ``obs.quality`` — scientific quality telemetry: numeric-health
    sentinels (SCC_OBS_NUMERIC NaN/Inf guards at stage boundaries),
    the DE gate funnel / rank-sum ladder occupancy / cluster-structure
    sections of the run record, and the quality-schema validator
    (``tools/explain_run.py`` renders one run — or a two-run diff — as
    a Markdown report);
  * ``obs.residency`` — the span-attributed host↔device residency
    auditor (SCC_OBS_RESIDENCY audit|enforce): implicit transfers
    caught at the np/jnp conversion entry points with a
    jax.transfer_guard backstop, aggregated into the run record's
    ``residency`` section and enforced against the declared boundary
    allowlist (the ROADMAP item-2 acceptance layer);
  * ``obs.kernels`` — the device-kernel timeline: a jax.profiler
    capture window (SCC_OBS_KERNELS) parsed into per-kernel device
    times, joined to tracer spans and the obs.cost FLOPs/bytes model
    as the run record's ``kernels`` section (the roofline-style
    evidence ROADMAP item 3 gates on);
  * ``obs.hostprof`` — the host execution observatory: a sampling
    stack profiler bucketed per stage span (python-compute with top
    frame, blocking-wait, compile, serialization), gc.callbacks pause
    accounting, and the RSS/HBM memory timeline — the run record's
    ``host_profile`` and ``memory_timeline`` sections (SCC_HOSTPROF);
  * ``obs.compilelog`` — per-stage JAX compile/retrace telemetry:
    jax.monitoring events stamped with the ambient stage and its entry
    ordinal, aggregated into the run record's ``compile`` section
    (compiles, retraces, cache hits, compile wall; SCC_COMPILELOG);
  * ``obs.graphs`` — the compiled-program observatory: per-program
    graph passports (op census, d2h/h2d transfer ops, host callbacks,
    donation hits/misses, fusion counts, XLA buffer estimates) from
    the AOT-lowered HLO of every instrumented jitted stage program,
    keyed by an environment fingerprint — the run record's ``graphs``
    section and the perf gate's transfer-op ratchet (SCC_GRAPHS;
    ``tools/graph_diff.py`` diffs two records' passports).

``utils.logging.StageTimer`` remains as a thin back-compat shim over
``Tracer``; ``bench.py`` and the ``tools/`` emitters all build their
artifacts through ``obs.export.build_run_record``.
"""

from scconsensus_tpu.obs.trace import (
    Span,
    Tracer,
    current_tracer,
    last_tracer,
    span,
)
from scconsensus_tpu.obs.cost import attach_cost, stage_cost_summary
from scconsensus_tpu.obs.live import LiveRecorder, active_recorder, flush_active
from scconsensus_tpu.obs.metrics import MetricSet
from scconsensus_tpu.obs import quality  # noqa: F401 (after trace: it
#                                          reads the partially-built pkg)
from scconsensus_tpu.obs import kernels, residency  # noqa: F401
from scconsensus_tpu.obs import compilelog, graphs, hostprof  # noqa: F401
from scconsensus_tpu.obs.export import (
    SCHEMA_NAME,
    SCHEMA_VERSION,
    build_run_record,
    chrome_trace,
    validate_run_record,
    write_chrome_trace,
    write_json_atomic,
)

__all__ = [
    "quality",
    "residency",
    "kernels",
    "hostprof",
    "compilelog",
    "graphs",
    "Span",
    "Tracer",
    "current_tracer",
    "last_tracer",
    "span",
    "LiveRecorder",
    "active_recorder",
    "flush_active",
    "MetricSet",
    "attach_cost",
    "stage_cost_summary",
    "SCHEMA_NAME",
    "SCHEMA_VERSION",
    "build_run_record",
    "chrome_trace",
    "validate_run_record",
    "write_chrome_trace",
    "write_json_atomic",
]
