"""Differential run attribution: structurally diff two run records and
name the root cause (ISSUE 18 tentpole).

``regress.gate_record`` can say a stage's wall left its band and
``regress.diff_span_trees`` can name the child span that grew, but
nothing joins the *other* signals — transfer bytes at a declared
boundary, device time, dispatched FLOPs — so a FAIL reads "stage
slower" with the why left as archaeology. :func:`diff_records` diffs
two records' unified profiles (obs.profile) and emits a deterministic
ranked cause list, each cause naming its driver::

    stage `wilcox_ladder` +38 % wall, driven by +2.1 GB d2h at
    boundary `ladder_plan`

Drivers, in claim order (first sufficient signal wins — the ordering
is part of the report's determinism contract):

* ``transfer`` — the stage's audited bytes grew past the residency
  noise band; the cause names the declared boundary whose same-
  direction bytes grew most.
* ``device`` — device-kernel time accounts for most of the wall
  growth (the kernels really got slower / bigger).
* ``work`` — cost-model FLOPs grew past noise (more work dispatched:
  shape growth, an extra ladder rung, a redo).
* ``host`` — wall grew with transfers, device time, and FLOPs flat:
  host-side time (Python, planning, I/O) by elimination. When both
  records carry the round-19 host-observatory sections
  (``host_profile`` / ``compile``), the bucket splits into NAMED
  drivers — claim order ``gc`` (measured collector pauses),
  ``compile/retrace`` (compile wall + the retrace-count delta),
  ``blocking-wait`` (``block_until_ready``/transfer waits),
  ``serialization`` (json/pickle codecs), ``python-compute`` (sampled
  Python time, with the dominant frame named) — and the cause keeps
  "host-side" in its summary so downstream grep contracts hold.
  Pre-19 records without the sections keep the plain ``host`` driver
  (attribution stays version-tolerant).

Consumers: ``tools/perf_diff.py`` (CLI over any two records),
``tools/perf_gate.py`` (every FAIL names its top suspect), and
``obs/regress.stage_trends`` renders the same per-stage series over
ledger history. Everything here is a pure function of two records —
deterministic by construction, pinned by test.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from scconsensus_tpu.obs.regress import (
    ABS_NOISE_FLOOR_BYTES,
    ABS_NOISE_FLOOR_S,
    REL_NOISE_FLOOR,
)

__all__ = [
    "diff_records",
    "format_report",
    "top_suspect",
]

DIFF_SCHEMA = "scc-perf-diff"
DIFF_VERSION = 1

# Internal host-cause keys (host_profile.stages[*].causes spelling) in
# claim order, and their report driver names. Order is part of the
# determinism contract: on an exact tie the earlier cause wins.
_HOST_CAUSE_KEYS = ("gc", "compile", "blocking_wait", "serialization",
                    "python")
_HOST_DRIVER_NAMES = {
    "gc": "gc",
    "compile": "compile/retrace",
    "blocking_wait": "blocking-wait",
    "serialization": "serialization",
    "python": "python-compute",
}


def _fmt_bytes(n: float) -> str:
    sign = "+" if n >= 0 else "-"
    n = abs(float(n))
    for unit, div in (("GB", 1e9), ("MB", 1e6), ("KB", 1e3)):
        if n >= div:
            return f"{sign}{n / div:.1f} {unit}"
    return f"{sign}{n:.0f} B"


def _fmt_pct(pct: Optional[float]) -> str:
    return "n/a" if pct is None else f"{pct:+.1f} %"


def _profile_of(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The record's profile section, recomputed from the raw sections
    when absent (pre-profile records diff fine as long as they still
    carry spans)."""
    p = rec.get("profile")
    if isinstance(p, dict):
        return p
    from scconsensus_tpu.obs.profile import profile_sections_of

    return profile_sections_of(rec)["profile"]


def _burndown_of(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    b = rec.get("residency_burndown")
    if isinstance(b, dict):
        return b
    from scconsensus_tpu.obs.profile import build_burndown

    return build_burndown(rec.get("residency"))


def _xfer_total(row: Dict[str, Any]) -> int:
    return int(row.get("to_host_bytes") or 0) + int(
        row.get("to_device_bytes") or 0
    )


def _boundary_deltas(cand_bd: Optional[Dict[str, Any]],
                     base_bd: Optional[Dict[str, Any]]
                     ) -> Dict[str, Dict[str, Any]]:
    cb = (cand_bd or {}).get("boundaries") or {}
    bb = (base_bd or {}).get("boundaries") or {}
    out: Dict[str, Dict[str, Any]] = {}
    for name in sorted(set(cb) | set(bb)):
        c, b = cb.get(name) or {}, bb.get(name) or {}
        out[name] = {
            "candidate_bytes": _xfer_total(c),
            "baseline_bytes": _xfer_total(b),
            "delta_bytes": _xfer_total(c) - _xfer_total(b),
            "delta_to_host_bytes": int(c.get("to_host_bytes") or 0)
            - int(b.get("to_host_bytes") or 0),
            "delta_to_device_bytes": int(c.get("to_device_bytes") or 0)
            - int(b.get("to_device_bytes") or 0),
            "todo_item2": bool(
                c.get("todo_item2", b.get("todo_item2", False))
            ),
        }
    return out


def _host_cause_rows(rec: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Per-stage host-cause seconds from a record's round-19 sections:
    ``{stage: {gc, compile, blocking_wait, serialization, python,
    _retraces, _top_frame?}}``. Empty for pre-19 records (no sections)
    — the caller falls back to the undifferentiated host driver.
    Every field read is guarded: a malformed or future-shaped section
    degrades to zeros, never raises out of a diff."""
    out: Dict[str, Dict[str, Any]] = {}

    def _row(stage: str) -> Dict[str, Any]:
        return out.setdefault(
            stage, {k: 0.0 for k in _HOST_CAUSE_KEYS} | {"_retraces": 0}
        )

    hp = rec.get("host_profile")
    if isinstance(hp, dict):
        for stage, srow in (hp.get("stages") or {}).items():
            if not isinstance(srow, dict):
                continue
            row = _row(stage)
            causes = srow.get("causes") or {}
            for k in _HOST_CAUSE_KEYS:
                v = causes.get(k) if isinstance(causes, dict) else None
                if isinstance(v, (int, float)) and v > 0:
                    row[k] += float(v)
            tf = srow.get("top_frame")
            if isinstance(tf, str) and tf:
                row["_top_frame"] = tf
    comp = rec.get("compile")
    if isinstance(comp, dict):
        for stage, crow in (comp.get("by_stage") or {}).items():
            if not isinstance(crow, dict):
                continue
            row = _row(stage)
            t = crow.get("total_s")
            if isinstance(t, (int, float)) and t > 0:
                # measured compile wall wins over the sampler's estimate
                # of the same seconds (max, not sum: one wall, two
                # instruments)
                row["compile"] = max(row["compile"], float(t))
            r = crow.get("retraces")
            if isinstance(r, int) and r > 0:
                row["_retraces"] += r
    return out


def _split_host_cause(head: str, cause: Dict[str, Any],
                      host_cand: Optional[Dict[str, Any]],
                      host_base: Optional[Dict[str, Any]]
                      ) -> Optional[Dict[str, Any]]:
    """Name the dominant host cause of a stage's wall growth from both
    records' per-stage cause seconds. None when neither record carries
    host-observatory data for the stage or no cause's delta clears the
    absolute noise floor — the caller keeps the legacy host driver."""
    if not host_cand and not host_base:
        return None
    hc = host_cand or {}
    hb = host_base or {}
    best_key: Optional[str] = None
    best_delta = ABS_NOISE_FLOOR_S
    for k in _HOST_CAUSE_KEYS:
        d = float(hc.get(k) or 0.0) - float(hb.get(k) or 0.0)
        if d > best_delta:
            best_key, best_delta = k, d
    if best_key is None:
        return None
    cause["driver"] = _HOST_DRIVER_NAMES[best_key]
    cause["delta_host_cause_s"] = round(best_delta, 6)
    if best_key == "gc":
        detail = f"{best_delta:+.3f} s GC pauses"
    elif best_key == "compile":
        dr = int(hc.get("_retraces") or 0) - int(hb.get("_retraces") or 0)
        cause["delta_retraces"] = dr
        detail = f"{best_delta:+.3f} s compile/retrace"
        if dr > 0:
            detail += f" (+{dr} retrace{'s' if dr != 1 else ''})"
    elif best_key == "blocking_wait":
        detail = (f"{best_delta:+.3f} s blocking waits "
                  "(block_until_ready/transfers)")
    elif best_key == "serialization":
        detail = f"{best_delta:+.3f} s serialization"
    else:
        detail = f"{best_delta:+.3f} s python compute"
        frame = hc.get("_top_frame")
        if isinstance(frame, str) and frame:
            cause["frame"] = frame
            detail += f" at `{frame}`"
    cause["summary"] = f"{head}, host-side driven by {detail}"
    return cause


def _compile_delta(candidate: Dict[str, Any], baseline: Dict[str, Any]
                   ) -> Optional[Dict[str, Any]]:
    """Record-level compile-telemetry delta (None when neither record
    carries a ``compile`` section)."""
    c, b = candidate.get("compile"), baseline.get("compile")
    if not isinstance(c, dict) and not isinstance(b, dict):
        return None
    c = c if isinstance(c, dict) else {}
    b = b if isinstance(b, dict) else {}

    def _i(d: Dict[str, Any], k: str) -> int:
        v = d.get(k)
        return int(v) if isinstance(v, int) else 0

    def _f(d: Dict[str, Any], k: str) -> float:
        v = d.get(k)
        return float(v) if isinstance(v, (int, float)) else 0.0

    return {
        "candidate_retraces": _i(c, "retraces"),
        "baseline_retraces": _i(b, "retraces"),
        "delta_compiles": _i(c, "compiles") - _i(b, "compiles"),
        "delta_retraces": _i(c, "retraces") - _i(b, "retraces"),
        "delta_cache_hits": _i(c, "cache_hits") - _i(b, "cache_hits"),
        "delta_wall_s": round(
            _f(c, "compile_wall_s") - _f(b, "compile_wall_s"), 6
        ),
    }


def _transfer_driver(boundaries: Dict[str, Dict[str, Any]],
                     direction_key: str
                     ) -> Optional[Tuple[str, int]]:
    """The declared boundary whose bytes grew most in the stage's
    dominant direction — ties broken by name so the report is stable."""
    best: Optional[Tuple[str, int]] = None
    for name in sorted(boundaries):
        d = boundaries[name][direction_key]
        if d > 0 and (best is None or d > best[1]):
            best = (name, d)
    return best


def diff_records(candidate: Dict[str, Any], baseline: Dict[str, Any],
                 candidate_label: str = "candidate",
                 baseline_label: str = "baseline") -> Dict[str, Any]:
    """Structural diff of two run records: per-stage wall / device /
    FLOPs / transfer deltas, per-boundary byte deltas, and a ranked
    ``causes`` list (largest absolute wall delta first, name-tiebroken)
    with each cause's driver classified per the module docstring.
    Deterministic: same pair of records, same report, always."""
    cand_p = _profile_of(candidate) or {"stages": {}, "totals": {}}
    base_p = _profile_of(baseline) or {"stages": {}, "totals": {}}
    cs, bs = cand_p.get("stages") or {}, base_p.get("stages") or {}
    boundaries = _boundary_deltas(_burndown_of(candidate),
                                  _burndown_of(baseline))
    host_cand = _host_cause_rows(candidate)
    host_base = _host_cause_rows(baseline)

    stages: Dict[str, Dict[str, Any]] = {}
    for name in sorted(set(cs) | set(bs)):
        c, b = cs.get(name) or {}, bs.get(name) or {}
        cw = float(c.get("wall_s") or 0.0)
        bw = float(b.get("wall_s") or 0.0)
        row: Dict[str, Any] = {
            "candidate_wall_s": round(cw, 6),
            "baseline_wall_s": round(bw, 6),
            "delta_wall_s": round(cw - bw, 6),
            "pct_wall": round(100.0 * (cw - bw) / bw, 1) if bw > 0
            else None,
            "only_in": "candidate" if name not in bs
            else ("baseline" if name not in cs else None),
        }
        band = max(ABS_NOISE_FLOOR_S, REL_NOISE_FLOOR * bw)
        row["within_noise"] = abs(cw - bw) <= band and row["only_in"] is \
            None
        cd, bd = c.get("device_s"), b.get("device_s")
        if cd is not None or bd is not None:
            row["delta_device_s"] = round(
                float(cd or 0.0) - float(bd or 0.0), 6
            )
        cf, bf = c.get("flops"), b.get("flops")
        if cf is not None or bf is not None:
            row["delta_flops"] = float(cf or 0.0) - float(bf or 0.0)
            row["baseline_flops"] = float(bf or 0.0)
        if "to_host_bytes" in c or "to_host_bytes" in b:
            row["delta_to_host_bytes"] = int(c.get("to_host_bytes") or 0) \
                - int(b.get("to_host_bytes") or 0)
            row["delta_to_device_bytes"] = \
                int(c.get("to_device_bytes") or 0) \
                - int(b.get("to_device_bytes") or 0)
            row["baseline_transfer_bytes"] = _xfer_total(b)
        stages[name] = row

    causes: List[Dict[str, Any]] = []
    ranked = sorted(
        stages.items(),
        key=lambda kv: (-abs(kv[1]["delta_wall_s"]), kv[0]),
    )
    for name, row in ranked:
        if row["delta_wall_s"] == 0 and row["only_in"] is None:
            continue
        cause = _classify(name, row, boundaries,
                          host_cand.get(name), host_base.get(name))
        cause["rank"] = len(causes) + 1
        causes.append(cause)

    cv, bv = candidate.get("value"), baseline.get("value")
    headline: Dict[str, Any] = {
        "candidate": cv,
        "baseline": bv,
        "unit": candidate.get("unit"),
    }
    if isinstance(cv, (int, float)) and isinstance(bv, (int, float)):
        headline["delta"] = round(float(cv) - float(bv), 6)
        if bv:
            headline["pct"] = round(100.0 * (float(cv) - float(bv))
                                    / float(bv), 1)

    cand_bd, base_bd = _burndown_of(candidate), _burndown_of(baseline)
    burndown: Optional[Dict[str, Any]] = None
    if cand_bd or base_bd:
        ct = int((cand_bd or {}).get("total_bytes") or 0)
        bt = int((base_bd or {}).get("total_bytes") or 0)
        ci = int((cand_bd or {}).get("todo_item2_bytes") or 0)
        bi = int((base_bd or {}).get("todo_item2_bytes") or 0)
        burndown = {
            "candidate_total_bytes": ct,
            "baseline_total_bytes": bt,
            "delta_total_bytes": ct - bt,
            "candidate_todo_item2_bytes": ci,
            "baseline_todo_item2_bytes": bi,
            "delta_todo_item2_bytes": ci - bi,
        }

    return {
        "schema": DIFF_SCHEMA,
        "schema_version": DIFF_VERSION,
        "candidate": {"label": candidate_label,
                      "metric": candidate.get("metric")},
        "baseline": {"label": baseline_label,
                     "metric": baseline.get("metric")},
        "headline": headline,
        "causes": causes,
        "stages": stages,
        "boundaries": boundaries,
        "burndown": burndown,
        "compile": _compile_delta(candidate, baseline),
    }


def _classify(name: str, row: Dict[str, Any],
              boundaries: Dict[str, Dict[str, Any]],
              host_cand: Optional[Dict[str, Any]] = None,
              host_base: Optional[Dict[str, Any]] = None
              ) -> Dict[str, Any]:
    """One cause entry for a stage delta: driver + human summary. Only
    wall *growth* gets a root-cause claim; shrinkage and stages unique
    to one record are reported as what they are."""
    delta = row["delta_wall_s"]
    pct = row["pct_wall"]
    head = f"stage `{name}` {_fmt_pct(pct)} wall" if pct is not None \
        else f"stage `{name}` {delta:+.3f} s wall"
    cause: Dict[str, Any] = {
        "stage": name,
        "delta_wall_s": delta,
        "pct_wall": pct,
        "within_noise": row["within_noise"],
    }
    if row["only_in"] is not None:
        cause["driver"] = "structure"
        cause["summary"] = (
            f"stage `{name}` only in {row['only_in']} "
            f"({delta:+.3f} s wall)"
        )
        return cause
    if delta < 0:
        cause["driver"] = "improvement"
        cause["summary"] = f"{head} (improvement)"
        return cause

    d2h = row.get("delta_to_host_bytes")
    h2d = row.get("delta_to_device_bytes")
    if d2h is not None:
        xfer_delta = d2h + h2d
        base_xfer = row.get("baseline_transfer_bytes") or 0
        xfer_band = max(ABS_NOISE_FLOOR_BYTES,
                        REL_NOISE_FLOOR * base_xfer)
        if xfer_delta > xfer_band:
            direction = "d2h" if d2h >= h2d else "h2d"
            dir_key = "delta_to_host_bytes" if direction == "d2h" \
                else "delta_to_device_bytes"
            grown = max(d2h, h2d)
            suspect = _transfer_driver(boundaries, dir_key)
            cause["driver"] = "transfer"
            cause["delta_transfer_bytes"] = xfer_delta
            at = ""
            if suspect is not None:
                cause["boundary"] = suspect[0]
                at = f" at boundary `{suspect[0]}`"
            cause["summary"] = (
                f"{head}, driven by {_fmt_bytes(grown)} {direction}{at}"
            )
            return cause

    dev = row.get("delta_device_s")
    if dev is not None and dev > 0 and dev >= 0.5 * delta:
        cause["driver"] = "device"
        cause["summary"] = (
            f"{head}, driven by {dev:+.3f} s device-kernel time"
        )
        return cause

    df = row.get("delta_flops")
    if df is not None and df > 0:
        bf = row.get("baseline_flops") or 0.0
        if df > REL_NOISE_FLOOR * bf:
            cause["driver"] = "work"
            cause["summary"] = (
                f"{head}, driven by {df / 1e9:+.2f} GFLOP more work "
                "dispatched"
            )
            return cause

    split = _split_host_cause(head, cause, host_cand, host_base)
    if split is not None:
        return split
    cause["driver"] = "host"
    cause["summary"] = (
        f"{head}, host-side (transfers, device time, and FLOPs flat)"
    )
    return cause


def top_suspect(diff: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The highest-ranked out-of-noise wall *growth* — what a perf_gate
    FAIL should name. None when nothing grew past noise (the FAIL came
    from a non-wall gate: drift, transfers, SLO...)."""
    for cause in diff.get("causes") or []:
        if cause.get("delta_wall_s", 0) > 0 and not cause.get(
            "within_noise"
        ) and cause.get("driver") not in ("improvement",):
            return cause
    return None


def format_report(diff: Dict[str, Any], max_causes: int = 10) -> str:
    """Render the diff as the deterministic text report perf_diff
    prints: headline, ranked causes, burn-down delta, per-boundary
    table."""
    lines: List[str] = []
    c, b = diff["candidate"], diff["baseline"]
    lines.append(f"perf-diff: {c['label']} vs {b['label']}")
    h = diff.get("headline") or {}
    if isinstance(h.get("candidate"), (int, float)) and isinstance(
        h.get("baseline"), (int, float)
    ):
        unit = h.get("unit") or ""
        pct = f" ({_fmt_pct(h['pct'])})" if "pct" in h else ""
        lines.append(
            f"headline: {h['candidate']:.4g} vs {h['baseline']:.4g} "
            f"{unit}{pct}"
        )
    causes = diff.get("causes") or []
    if causes:
        lines.append("ranked causes:")
        for cause in causes[:max_causes]:
            noise = "  [within noise]" if cause.get("within_noise") \
                else ""
            lines.append(f"  {cause['rank']}. {cause['summary']}{noise}")
        if len(causes) > max_causes:
            lines.append(f"  ... {len(causes) - max_causes} more below "
                         "threshold")
    else:
        lines.append("ranked causes: none (no stage walls differ)")
    comp = diff.get("compile")
    if comp:
        rt = f" ({comp['candidate_retraces']} vs " \
             f"{comp['baseline_retraces']} retraces)"
        lines.append(
            f"compile: {comp['delta_compiles']:+d} compiles, "
            f"{comp['delta_retraces']:+d} retraces{rt}, "
            f"{comp['delta_cache_hits']:+d} cache hits, "
            f"{comp['delta_wall_s']:+.3f} s compile wall"
        )
    bd = diff.get("burndown")
    if bd:
        lines.append(
            "residency burn-down: total "
            f"{_fmt_bytes(bd['candidate_total_bytes'])[1:]} "
            f"({_fmt_bytes(bd['delta_total_bytes'])}); TODO(item-2) "
            f"{_fmt_bytes(bd['candidate_todo_item2_bytes'])[1:]} "
            f"({_fmt_bytes(bd['delta_todo_item2_bytes'])})"
        )
        for name, row in (diff.get("boundaries") or {}).items():
            tag = "  [item-2]" if row["todo_item2"] else ""
            lines.append(
                f"  boundary `{name}` "
                f"{_fmt_bytes(row['candidate_bytes'])[1:]} "
                f"({_fmt_bytes(row['delta_bytes'])}){tag}"
            )
    return "\n".join(lines)
