"""Span-attributed host↔device residency auditor.

ROADMAP item 2 ("one device-resident execution graph") needs a tier-1
test asserting zero host round-trips across consensus→embed — but
``TransferWatch`` (obs.device) is best-effort by its own docstring:
it wraps only the explicit ``jax.device_put``/``jax.device_get`` entry
points, so an ``np.asarray`` on a device array or a ``from jax import
device_get`` alias is invisible. This module is the measurement layer
that claim gets verified against, in three modes via the registered
``SCC_OBS_RESIDENCY`` flag:

  * ``off`` — zero-overhead no-op (the auditor context degrades to a
    passthrough).
  * ``audit`` — every transfer the auditor can see is recorded with
    direction, nbytes, the owning tracer span, the outermost open
    *stage* span (the unit the perf gate baselines), the innermost
    declared boundary (or None), and the first non-infrastructure
    source line. Aggregates land on the run record's validated
    ``residency`` section.
  * ``enforce`` — a crossing that matches no declared boundary raises
    :class:`ResidencyError` naming the offending span and source line.
    ``jax.transfer_guard_device_to_host("disallow")`` additionally arms
    XLA's own guard as the backstop for paths the Python patches cannot
    see (active on real accelerators; the CPU backend's device→host
    path is zero-copy and never fires it — which is exactly why the
    patched entry points, not the guard, carry the CPU-testable
    contract).

**How transfers are seen.** On entry the auditor patches the module
attributes hot-path code actually calls — ``numpy.asarray`` /
``numpy.array`` (implicit device→host: the case TransferWatch misses),
``jax.numpy.asarray`` / ``jax.numpy.array`` (implicit host→device
staging), and ``jax.device_put`` / ``jax.device_get`` (explicit). These
are the same four call forms the static residency lint
(tests/test_residency_lint.py) ratchets in hot-path modules, so the
dynamic auditor and the static gate cover one surface. C-level paths
(buffer-protocol reads, jit argument staging of host arrays) bypass
Python patches; the transfer guard covers those in enforce mode, and
the count in audit mode is a documented lower bound on exotic paths —
but every crossing the repo's own hot path performs goes through a
patched form.

**Enforcement policy.** Device→host is the round-trip direction item 2
bans: ANY unallowlisted fetch raises, regardless of size. Host→device
is the normal feed direction — index vectors and scalars stage
constantly — so only a single transfer ≥ ``enforce_h2d_bytes``
(default 1 MiB: the signature of re-uploading a matrix that should
already be resident) outside a boundary raises; smaller staging is
recorded, not fatal.

**Boundaries.** :data:`BOUNDARIES` is the small declared allowlist of
intentional crossings, each with its in-code justification; entries
marked ``TODO(item-2)`` enumerate today's violations for the
device-resident-graph refactor to burn down (landing the test ahead of
the refactor is the point — the allowlist IS the work list). Code
declares a crossing with ``with residency.boundary("name"):`` — unknown
names raise immediately, so the allowlist cannot grow by typo.
Transfers whose source resolves inside ``obs/`` (drain sentinels,
sentinel-count fetches) auto-attribute to the ``obs_internal`` boundary
when no explicit one is open: measurement overhead must be visible in
audit mode but must not fail the enforcement the measurement exists to
run.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from contextlib import ExitStack, contextmanager
from typing import Any, Dict, List, Optional

from scconsensus_tpu.config import env_flag

__all__ = [
    "MODES",
    "BOUNDARIES",
    "ResidencyError",
    "ResidencyAuditor",
    "mode",
    "boundary",
    "active_auditor",
    "live_counters",
    "stage_transfer_bytes",
    "validate_residency",
    "consumed_cpu_s",
    "reset_cpu",
    "add_transfer_listener",
    "remove_transfer_listener",
]

MODES = ("off", "audit", "enforce")

# The declared allowlist: boundary name -> in-code justification. This dict
# is the contract the enforce-mode tier-1 test runs against; a TODO(item-2)
# marker means the crossing is a KNOWN violation of the device-resident
# graph, enumerated here ahead of the refactor that removes it.
BOUNDARIES: Dict[str, str] = {
    "input_staging": (
        "The one intended host→device upload of the expression matrix and "
        "its index vectors (devcache.device_put_cached, engine setup). The "
        "matrix crosses the link exactly once per run by design."
    ),
    "funnel_counts": (
        "(P,)-sized per-pair count fetches for the DE gate funnel and "
        "de_counts metrics (obs.quality.de_funnel, engine.de_counts) — "
        "O(P) ints, never the (P, G) statistics."
    ),
    "label_fetch": (
        "Pipeline-tail outputs: the final per-cell labels, the (N,) nodg "
        "counts, and the report plot's gene-row gather — the result the "
        "caller asked for has to reach the host once."
    ),
    "de_union_topk": (
        "de_gene_union's device top-k fetch: (P, n_top) ints instead of "
        "two (P, G) arrays through the slow link."
    ),
    "wilcox_ladder_plan": (
        "O(G) nnz counts + a negativity scalar fetched to plan the window "
        "ladder on host. TODO(item-2): fold ladder planning into the "
        "device-resident graph."
    ),
    "overflow_redo": (
        "Run-space overflow redo: one batched O(G) tied-run-count fetch "
        "after all blocks dispatched (engine._redo_overflow_*). "
        "TODO(item-2): keep the redo decision on device."
    ),
    "exact_small_pairs": (
        "R's exact Wilcoxon branch runs on host for pairs with both "
        "groups < 50 cells; only those pairs' rows are fetched. Host by "
        "statistical design, not an accident."
    ),
    "embed_scores_fetch": (
        "The (N, n_pcs) PCA embedding materializes to host because tree/"
        "cuts/silhouette are host algorithms today. TODO(item-2): keep "
        "the embedding device-resident through rSVD→linkage."
    ),
    "tree_pool_fetch": (
        "LEGACY sub-threshold pooled path only (r7 shrank this from the "
        "former any-N scope): the full-data Lloyd's (m, d) centroids + "
        "(N,) assignment come to host for Ward linkage. Above "
        "SCC_TREE_LANDMARK_THRESHOLD the landmark path crosses at "
        "landmark_assign_fetch instead. TODO(item-2): device-resident "
        "tree for the legacy path too."
    ),
    "landmark_assign_fetch": (
        "Landmark recluster path (r7): one h2d staging of the embedding "
        "blocks into the jitted sketch-Lloyd/nearest-landmark kernels, "
        "then exactly two intended d2h crossings — the (k, d) landmark "
        "centroids for host Ward + treecut and the (N,) int32 "
        "assignment that propagates cut labels to cells. The (N, k) "
        "distance tiles never leave the device."
    ),
    "silhouette_slab_fetch": (
        "EXACT-silhouette path only (below approx_threshold; r7 shrank "
        "this — the landmark/pooled estimator reuses the tree stage's "
        "pool on host and performs no slab fetch): distance slabs / "
        "(N, K) cluster distance sums copy to host (ops.distance, "
        "ops.pallas_kernels.distance_cluster_sums). TODO(item-2): "
        "device-resident silhouette reduction."
    ),
    "de_result_fetch": (
        "PairwiseDEResult lazy-field materialization (to_store, "
        "fingerprinting, host consumers) — the documented single batched "
        "fetch of the (P, G) statistics a host consumer asked for."
    ),
    "de_ckpt_fetch": (
        "Mid-stage wilcox checkpointing (robust round): each completed "
        "ladder bucket's (Gb, P) block fetches to host for the "
        "ArtifactStore so a kill mid-stage resumes from completed "
        "buckets. Only active with an artifact store + "
        "SCC_ROBUST_DE_CKPT — durability bought with a declared, "
        "store-gated crossing, never a silent one."
    ),
    "stream_block_fetch": (
        "Out-of-core streaming (round 17, stream.runner): each disk "
        "chunk's per-shard results — the (P, Gc) rank-sum block, the "
        "(Gc, K) aggregate slab — fetch to host for the resumable "
        "stage store, and each chunk's compacted windows stage h2d "
        "through the shared input_staging path. Load → device → drop "
        "is the streaming contract; this boundary is the declared "
        "drop side, sized per-chunk by construction."
    ),
    "workload_inputs": (
        "Workload-zoo input construction (workloads/, round 19): h2d "
        "staging of scenario embeddings/modalities into the jitted "
        "cover/Lloyd labelers and the O(N) int label/node-id fetches "
        "that become consensus INPUT labelings. Scenario setup runs "
        "before the pipeline's own residency story starts; its "
        "crossings are declared so audit-mode bench records attribute "
        "them, never part of the refine stages' transfer budget."
    ),
    "obs_internal": (
        "Measurement infrastructure's own O(1) transfers: tracer drain "
        "sentinels, sentinel-count fetches. Auto-attributed when the "
        "source line resolves inside obs/."
    ),
    "integrity_check": (
        "The computation-integrity layer's verification transfers "
        "(robust.integrity, round 18): one scalar residual per fused "
        "invariant check at a stage boundary, plus the sampled "
        "ghost-replay rows (a few genes × pairs per ladder rung, one "
        "landmark block, one serving batch). Sized O(samples) by "
        "construction and active only under SCC_INTEGRITY=audit|"
        "enforce — the cost of proving the arithmetic, never part of "
        "the workload's own transfer budget."
    ),
}

_EVENT_CAP = 256            # stored events; totals keep counting past it
_ENFORCE_H2D_BYTES = 1 << 20

_CPU = {"s": 0.0}
_LOCK = threading.Lock()
_ACTIVE: "Optional[ResidencyAuditor]" = None
_TLS = threading.local()
# transfer listeners: fn(direction, nbytes, boundary) called on every
# recorded event (stream.budget's host-budget accountant registers one)
_LISTENERS: List[Any] = []


def add_transfer_listener(fn) -> None:
    """Register ``fn(direction, nbytes, boundary)`` to observe every
    transfer the active auditor records. Idempotent per function."""
    if fn not in _LISTENERS:
        _LISTENERS.append(fn)


def remove_transfer_listener(fn) -> None:
    try:
        _LISTENERS.remove(fn)
    except ValueError:
        pass


def consumed_cpu_s() -> float:
    """Wall-clock spent inside auditor bookkeeping in this process (the
    <2%-of-wall overhead guard reads this; the audited transfers
    themselves are the workload's cost, not the auditor's)."""
    return _CPU["s"]


def reset_cpu() -> None:
    _CPU["s"] = 0.0


def mode() -> str:
    """Resolved ``SCC_OBS_RESIDENCY`` mode; unknown values warn once via
    ValueError at auditor construction (a typo'd 'enfrce' must not
    silently run unguarded)."""
    v = str(env_flag("SCC_OBS_RESIDENCY") or "off").strip().lower()
    return v if v else "off"


def active_auditor() -> "Optional[ResidencyAuditor]":
    return _ACTIVE


def live_counters() -> Optional[Dict[str, int]]:
    """Cumulative transfer counters of the process's active auditor for
    the flight recorder's heartbeat ticks (None when no audit is live).
    tail_run.py differences consecutive ticks into a live byte rate."""
    a = _ACTIVE
    if a is None:
        return None
    return {
        "to_host_bytes": a.to_host_bytes,
        "to_device_bytes": a.to_device_bytes,
        "events": a.n_events,
    }


class ResidencyError(RuntimeError):
    """An enforce-mode crossing outside the declared allowlist."""


def _boundary_stack() -> List[str]:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


@contextmanager
def _delegating():
    """Re-entrancy guard: ``jnp.asarray`` delegates to ``jax.device_put``
    internally, so without this every staging call would double-count —
    once at the outer patched form, once at the inner one."""
    _TLS.depth = getattr(_TLS, "depth", 0) + 1
    try:
        yield
    finally:
        _TLS.depth -= 1


def _nested() -> bool:
    return getattr(_TLS, "depth", 0) > 0


@contextmanager
def boundary(name: str):
    """Declare an intentional host↔device crossing scope. ``name`` must be
    registered in :data:`BOUNDARIES` (KeyError otherwise — the allowlist
    grows only by an explicit, justified entry). Inside the scope,
    enforce mode's transfer guard flips to "allow" and every recorded
    event carries the boundary name. No-op overhead when no auditor is
    active."""
    if name not in BOUNDARIES:
        raise KeyError(
            f"undeclared residency boundary {name!r}; register it with a "
            "justification in obs.residency.BOUNDARIES"
        )
    auditor = _ACTIVE
    stack = _boundary_stack()
    stack.append(name)
    try:
        if auditor is not None and auditor.mode == "enforce":
            import jax

            with jax.transfer_guard("allow"):
                yield
        else:
            yield
    finally:
        stack.pop()


def _is_device_array(x: Any) -> bool:
    """Concrete committed device buffers only — tracers (abstract values
    inside jit) convert through entirely different machinery and must
    never be billed as transfers."""
    if "jax" not in sys.modules:
        return False
    try:
        jax = sys.modules["jax"]
        return isinstance(x, jax.Array) and not isinstance(
            x, jax.core.Tracer
        )
    except Exception:
        return False


def _nbytes(x: Any) -> int:
    try:
        import jax

        return sum(
            int(getattr(leaf, "nbytes", 0) or 0)
            for leaf in jax.tree_util.tree_leaves(x)
        )
    except Exception:
        return int(getattr(x, "nbytes", 0) or 0)


def _tree_has_device(x: Any) -> bool:
    try:
        import jax

        return any(_is_device_array(l) for l in jax.tree_util.tree_leaves(x))
    except Exception:
        return _is_device_array(x)


_OBS_DIR = os.path.dirname(os.path.abspath(__file__))
_THIS_FILE = os.path.abspath(__file__)

# filename -> "self" | "obs" | "infra" | basename; memoized because the
# same few files dominate every walk and abspath/substring checks per
# frame were the bulk of the auditor's <2%-of-wall budget
_FILE_CLASS: Dict[str, str] = {}


def _classify_file(fn: str) -> str:
    c = _FILE_CLASS.get(fn)
    if c is None:
        ab = os.path.abspath(fn)
        if ab == _THIS_FILE:
            c = "self"
        elif ab.startswith(_OBS_DIR + os.sep):
            # os.sep-terminated: a sibling like obs_utils/ or
            # observability.py must NOT inherit the obs_internal exemption
            c = "obs"
        elif (f"{os.sep}jax{os.sep}" in fn
              or f"{os.sep}jax_plugins{os.sep}" in fn
              or f"{os.sep}numpy{os.sep}" in fn):
            c = "infra"
        else:
            c = os.path.basename(fn)
        _FILE_CLASS[fn] = c
    return c


def _resolve_source() -> "tuple":
    """``(where, from_obs)``: the first stack frame outside this module,
    jax, and numpy — the source line that asked for the transfer — and
    whether any obs/ frame (other than the auditor's own wrappers) sits
    between it and the transfer, i.e. measurement infrastructure asked.
    Bounded walk: cheap enough for audit mode's <2% budget, because
    transfers are rare next to compute."""
    f = sys._getframe(3)  # _resolve_source <- _record <- wrapper <- caller
    from_obs = False
    for _ in range(24):
        if f is None:
            break
        c = _classify_file(f.f_code.co_filename)
        if c == "obs":
            from_obs = True
        elif c not in ("self", "infra"):
            return f"{c}:{f.f_lineno}", from_obs
        f = f.f_back
    return "<unknown>", from_obs


class ResidencyAuditor:
    """Scoped residency audit/enforcement (see module docstring).

    Context manager; re-entrant use is rejected (one auditor owns the
    process's patches at a time). ``mode`` defaults from the
    ``SCC_OBS_RESIDENCY`` registry flag.
    """

    def __init__(self, mode: Optional[str] = None,
                 enforce_h2d_bytes: int = _ENFORCE_H2D_BYTES,
                 event_cap: int = _EVENT_CAP):
        m = (mode if mode is not None else globals()["mode"]())
        if m not in MODES:
            raise ValueError(
                f"SCC_OBS_RESIDENCY must be one of {MODES}, got {m!r}"
            )
        self.mode = m
        self.enforce_h2d_bytes = int(enforce_h2d_bytes)
        self.event_cap = int(event_cap)
        self.to_device_bytes = 0
        self.to_host_bytes = 0
        self.to_device_calls = 0
        self.to_host_calls = 0
        self.n_events = 0
        self.events_dropped = 0
        self.events: List[Dict[str, Any]] = []
        self.by_stage: Dict[str, Dict[str, int]] = {}
        self.by_boundary: Dict[str, Dict[str, int]] = {}
        self.violations: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._stack: Optional[ExitStack] = None
        self._orig: Dict[str, Any] = {}

    # -- span / stage attribution ------------------------------------------
    @staticmethod
    def _open_spans():
        """(innermost span name, outermost open stage name) of the ambient
        tracer — the event's owner and the perf gate's baseline unit."""
        try:
            from scconsensus_tpu.obs.trace import current_tracer, last_tracer

            tr = current_tracer() or last_tracer()
            if tr is None:
                return None, None
            with tr._lock:
                stack = list(tr._stack)
            span = stack[-1].name if stack else None
            stage = next(
                (s.name for s in stack if s.kind == "stage"), None
            )
            return span, stage
        except Exception:
            return None, None

    # -- recording ----------------------------------------------------------
    def _record(self, direction: str, nbytes: int, implicit: bool,
                api: str) -> None:
        t0 = time.perf_counter()
        try:
            bstack = _boundary_stack()
            bound = bstack[-1] if bstack else None
            where, from_obs = _resolve_source()
            if bound is None and from_obs:
                bound = "obs_internal"
            span, stage = self._open_spans()
            with self._lock:
                if direction == "d2h":
                    self.to_host_calls += 1
                    self.to_host_bytes += nbytes
                else:
                    self.to_device_calls += 1
                    self.to_device_bytes += nbytes
                self.n_events += 1
                key = "to_host_bytes" if direction == "d2h" \
                    else "to_device_bytes"
                if stage is not None and bound != "obs_internal":
                    # measurement overhead (drain sentinels, diagnosis
                    # fetches under SCC_WILCOX_PROBE) stays OUT of the
                    # per-stage totals the perf gate baselines — a
                    # probe-on run must not read as a workload transfer
                    # regression. It remains visible in the directional
                    # totals and by_boundary["obs_internal"].
                    st = self.by_stage.setdefault(
                        stage, {"to_host_bytes": 0, "to_device_bytes": 0,
                                "calls": 0},
                    )
                    st[key] += nbytes
                    st["calls"] += 1
                if bound is not None:
                    bd = self.by_boundary.setdefault(
                        bound, {"to_host_bytes": 0, "to_device_bytes": 0,
                                "calls": 0},
                    )
                    bd[key] += nbytes
                    bd["calls"] += 1
                if len(self.events) < self.event_cap:
                    self.events.append({
                        "direction": direction,
                        "nbytes": int(nbytes),
                        "implicit": bool(implicit),
                        "api": api,
                        "span": span,
                        "stage": stage,
                        "boundary": bound,
                        "where": where,
                    })
                else:
                    self.events_dropped += 1
            # transfer listeners (round 17): the streaming budget
            # accountant subscribes here, so the SAME events the audit
            # records also feed the host-budget ledger — staged bytes the
            # auditor saw cross at input_staging are bytes the accountant
            # can prove left the host side. Listener errors never kill a
            # transfer (budget breaches raise from the accountant's own
            # charge() calls, where the caller can recover — not from
            # inside arbitrary third-party staging).
            for fn in tuple(_LISTENERS):
                try:
                    fn(direction, int(nbytes), bound)
                except Exception:
                    pass
            if self.mode == "enforce" and bound is None:
                bad = (direction == "d2h"
                       or nbytes >= self.enforce_h2d_bytes)
                if bad:
                    v = {"direction": direction, "nbytes": int(nbytes),
                         "api": api, "span": span, "stage": stage,
                         "where": where}
                    with self._lock:
                        self.violations.append(v)
                    raise ResidencyError(
                        f"residency violation: {direction} transfer of "
                        f"{nbytes} bytes via {api} in span "
                        f"{span or '<no-span>'} (stage "
                        f"{stage or '<none>'}) at {where} matches no "
                        "declared boundary — wrap the crossing in "
                        "obs.residency.boundary(<name>) with an in-code "
                        "justification, or keep the data on device"
                    )
        finally:
            _CPU["s"] += time.perf_counter() - t0

    # -- patches ------------------------------------------------------------
    def _patch(self) -> None:
        import jax
        import jax.numpy as jnp
        import numpy as np

        aud = self
        orig = self._orig
        orig["np_asarray"] = np.asarray
        orig["np_array"] = np.array
        orig["jnp_asarray"] = jnp.asarray
        orig["jnp_array"] = jnp.array
        orig["device_put"] = jax.device_put
        orig["device_get"] = jax.device_get

        # Recording happens AFTER the delegated call succeeds: a transfer
        # that raised (device allocation failure, tracer conversion error)
        # never moved its bytes, and billing it would double-count retry
        # loops (devcache's alloc-failure retry re-uploads the same
        # matrix). Enforce mode therefore raises just after the offending
        # transfer completes — late by one call, but the violation still
        # fails the run, and a failed transfer can never false-trip.

        def np_asarray(a, *args, **kw):
            rec = not _nested() and _is_device_array(a)
            with _delegating():
                out = orig["np_asarray"](a, *args, **kw)
            if rec:
                aud._record("d2h", _nbytes(a), True, "np.asarray")
            return out

        def np_array(a, *args, **kw):
            rec = not _nested() and _is_device_array(a)
            with _delegating():
                out = orig["np_array"](a, *args, **kw)
            if rec:
                aud._record("d2h", _nbytes(a), True, "np.array")
            return out

        def jnp_asarray(a, *args, **kw):
            # host ndarray staging only: device inputs are no-op views and
            # scalars/lists stage O(bytes) constants the guard covers
            rec = not _nested() and isinstance(a, np.ndarray)
            with _delegating():
                out = orig["jnp_asarray"](a, *args, **kw)
            if rec:
                aud._record("h2d", int(a.nbytes), True, "jnp.asarray")
            return out

        def jnp_array(a, *args, **kw):
            rec = not _nested() and isinstance(a, np.ndarray)
            with _delegating():
                out = orig["jnp_array"](a, *args, **kw)
            if rec:
                aud._record("h2d", int(a.nbytes), True, "jnp.array")
            return out

        def device_put(x, *args, **kw):
            rec = not _nested() and not _tree_has_device(x)
            with _delegating():
                out = orig["device_put"](x, *args, **kw)
            if rec:
                aud._record("h2d", _nbytes(x), False, "jax.device_put")
            return out

        def device_get(x, *args, **kw):
            rec = not _nested() and _tree_has_device(x)
            with _delegating():
                out = orig["device_get"](x, *args, **kw)
            if rec:
                aud._record("d2h", _nbytes(x), False, "jax.device_get")
            return out

        np.asarray = np_asarray
        np.array = np_array
        jnp.asarray = jnp_asarray
        jnp.array = jnp_array
        jax.device_put = device_put
        jax.device_get = device_get

    def _unpatch(self) -> None:
        import jax
        import jax.numpy as jnp
        import numpy as np

        orig = self._orig
        if not orig:
            return
        np.asarray = orig["np_asarray"]
        np.array = orig["np_array"]
        jnp.asarray = orig["jnp_asarray"]
        jnp.array = orig["jnp_array"]
        jax.device_put = orig["device_put"]
        jax.device_get = orig["device_get"]
        self._orig = {}

    # -- context ------------------------------------------------------------
    def __enter__(self) -> "ResidencyAuditor":
        global _ACTIVE
        if self.mode == "off":
            return self
        with _LOCK:
            if _ACTIVE is not None:
                raise RuntimeError(
                    "a ResidencyAuditor is already active in this process"
                )
            _ACTIVE = self
        self._stack = ExitStack()
        try:
            self._patch()
            self._stack.callback(self._unpatch)
            if self.mode == "enforce":
                import jax

                # the backstop for C-level paths the patches cannot see;
                # CPU's zero-copy d2h never fires it, accelerators do
                self._stack.enter_context(
                    jax.transfer_guard_device_to_host("disallow")
                )
        except BaseException:
            self._stack.close()
            with _LOCK:
                _ACTIVE = None
            raise
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        if self.mode == "off":
            return
        try:
            if self._stack is not None:
                self._stack.close()
        finally:
            self._stack = None
            with _LOCK:
                if _ACTIVE is self:
                    _ACTIVE = None

    # -- the run-record section ---------------------------------------------
    def report(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "mode": self.mode,
                "to_device": {"calls": self.to_device_calls,
                              "bytes": self.to_device_bytes},
                "to_host": {"calls": self.to_host_calls,
                            "bytes": self.to_host_bytes},
                "by_stage": {k: dict(v) for k, v in self.by_stage.items()},
                "by_boundary": {
                    k: dict(v) for k, v in self.by_boundary.items()
                },
                "events": [dict(e) for e in self.events],
                "events_dropped": self.events_dropped,
                "violations": [dict(v) for v in self.violations],
            }


@contextmanager
def audit_region(auditor: "Optional[ResidencyAuditor]"):
    """Run a region under ``auditor`` (None = passthrough), converting a
    backstop ``jax.transfer_guard`` error into a span-named
    :class:`ResidencyError` — XLA's message has no idea what a tracer
    span is, and the last finished span is the best attribution an
    unwound stack still holds."""
    if auditor is None:
        yield None
        return
    try:
        with auditor:
            yield auditor
    except ResidencyError:
        raise
    except Exception as e:
        if "Disallowed" in str(e) and "transfer" in str(e):
            last = None
            try:
                from scconsensus_tpu.obs.trace import last_tracer

                tr = last_tracer()
                if tr is not None and tr.spans:
                    last = tr.spans[-1].name
            except Exception:
                pass
            raise ResidencyError(
                "residency violation caught by jax.transfer_guard "
                f"(implicit transfer outside any declared boundary); "
                f"last finished span: {last or '<unknown>'}; guard said: "
                f"{str(e)[:300]}"
            ) from e
        raise


# --------------------------------------------------------------------------
# section helpers + validation
# --------------------------------------------------------------------------

def stage_transfer_bytes(rec: Dict[str, Any]) -> Dict[str, int]:
    """Total (both directions) transfer bytes per stage from a record's
    ``residency`` section — the quantity the perf gate baselines, mirror
    of ``ledger.stage_walls``. Empty when no audit ran."""
    res = rec.get("residency")
    if not isinstance(res, dict):
        return {}
    out: Dict[str, int] = {}
    for stage, d in (res.get("by_stage") or {}).items():
        if isinstance(d, dict):
            out[str(stage)] = int(d.get("to_host_bytes") or 0) + int(
                d.get("to_device_bytes") or 0
            )
    return out


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"residency section: {msg}")


def validate_residency(res: Dict[str, Any]) -> None:
    """Structural validation of a record's ``residency`` section (additive
    scc-run-record v1 extension; ``export.validate_run_record`` calls
    this)."""
    _require(isinstance(res, dict), "must be an object")
    _require(res.get("mode") in ("audit", "enforce"),
             f"mode must be audit|enforce, got {res.get('mode')!r}")
    for side in ("to_device", "to_host"):
        d = res.get(side)
        _require(isinstance(d, dict), f"{side} must be an object")
        for k in ("calls", "bytes"):
            v = d.get(k)
            _require(isinstance(v, int) and v >= 0,
                     f"{side}.{k} must be an int >= 0")
    for agg in ("by_stage", "by_boundary"):
        d = res.get(agg, {})
        _require(isinstance(d, dict), f"{agg} must be an object")
        for name, sd in d.items():
            _require(isinstance(sd, dict), f"{agg}[{name!r}] not an object")
            for k in ("to_host_bytes", "to_device_bytes", "calls"):
                v = sd.get(k, 0)
                _require(isinstance(v, int) and v >= 0,
                         f"{agg}[{name!r}].{k} must be an int >= 0")
    for b in res.get("by_boundary", {}):
        _require(b in BOUNDARIES,
                 f"by_boundary names undeclared boundary {b!r}")
    events = res.get("events", [])
    _require(isinstance(events, list), "events must be a list")
    for i, e in enumerate(events):
        _require(isinstance(e, dict), f"events[{i}] is not an object")
        _require(e.get("direction") in ("h2d", "d2h"),
                 f"events[{i}].direction must be h2d|d2h")
        nb = e.get("nbytes")
        _require(isinstance(nb, int) and nb >= 0,
                 f"events[{i}].nbytes must be an int >= 0")
        bd = e.get("boundary")
        _require(bd is None or bd in BOUNDARIES,
                 f"events[{i}] names undeclared boundary {bd!r}")
    _require(isinstance(res.get("violations", []), list),
             "violations must be a list")
