"""Compiled-program observatory: graph passports for every jitted stage
program → the ``graphs`` run-record section.

Every instrument before this round (``obs.residency`` crossings, the r22
``residency_burndown``, the r23 host profiler) measures the *runtime*
symptoms of host round-trips. This module introspects the compiled
program that causes them: for each jitted stage program (wilcox ladder,
gate funnel, rSVD embed, landmark assign, distance stream, …) it
captures a schema-validated **graph passport** from the AOT artifacts —
``jitted.lower(*args).compile()`` → ``Compiled.as_text()`` (optimized
HLO), ``Compiled.memory_analysis()``, ``Compiled.cost_analysis()``:

* **op census** — op-kind histogram and fusion count over the optimized
  HLO, so "one device-resident execution graph" (ROADMAP item 1) has a
  static op-level denominator;
* **transfer ops & host callbacks** — infeed/outfeed/send/recv-shaped
  ops, host-memory-space copies, and ``pure_callback``/``io_callback``
  custom-calls, each with the *source location* XLA recorded for it, so
  a reintroduced host crossing names its line of Python;
* **donation hits vs misses** — declared donated buffers checked against
  the module's ``input_output_alias`` header (a declared donation XLA
  could not alias is a silent extra copy);
* **XLA-estimated buffer bytes** — argument/output/temp/alias sizes and
  the derived peak estimate.

The runtime half mirrors :mod:`obs.compilelog`: :func:`install_and_mark`
arms the registry (gated on ``SCC_GRAPHS``; bench workers default it
on, serve never arms it), :func:`instrument` wraps a jitted callable so
its first call per abstract signature captures a passport (memoized —
steady-state calls cost one dict lookup), and ``bench._finalize`` stamps
:func:`snapshot` as the record's ``graphs`` section. Passports join the
stage timeline through the same ambient-stage + entry-ordinal scheme the
compile log uses. Capture is best-effort: any failure lands in the
section's ``errors`` list, never in the measurement.

Passports are **backend-fingerprint-keyed**: the section carries
:func:`environment_fingerprint` (jax/jaxlib versions, backend, device
kind, XLA_FLAGS/LIBTPU_INIT_ARGS) and ``tools/graph_diff.py`` refuses
to diff across fingerprints — an op census from another toolchain is a
different program, not a regression.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from scconsensus_tpu.config import env_flag

__all__ = [
    "GRAPHS_VERSION",
    "TRANSFER_OP_KINDS",
    "passport_from_hlo",
    "build_graphs_section",
    "validate_graphs",
    "environment_fingerprint",
    "fingerprint_digest",
    "instrument",
    "observe",
    "install_and_mark",
    "armed",
    "snapshot",
    "reset",
    "stage_graph_counts",
    "ratchet_ack",
]

GRAPHS_VERSION = 1

# HLO op kinds that ARE host<->device (or cross-device) data movement when
# they appear inside a compiled program. Host-memory-space copies are
# caught separately (_HOST_SPACE in the op line).
TRANSFER_OP_KINDS = frozenset((
    "infeed", "outfeed",
    "send", "send-done", "recv", "recv-done",
))

# XLA annotates host-memory-space buffers as S(5) in layouts; a copy (or
# async copy-start/done pair) touching one is a device<->host transfer.
_HOST_SPACE = "S(5)"
_COPY_KINDS = frozenset(("copy", "copy-start", "copy-done"))

# one HLO instruction: `  [ROOT] %name = <type> op-kind(...)`
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(?:\([^=]*\)|\S+)\s+"
    r"([a-zA-Z][\w\-]*)\("
)
_META_RE = re.compile(r'source_file="([^"]*)"\s+source_line=(\d+)')
_TARGET_RE = re.compile(r'custom_call_target="([^"]*)"')
# module-header donation evidence: input_output_alias={ {}: (0, {}, ...) }
_ALIAS_BLOCK_RE = re.compile(r"input_output_alias=\{(.*?)\}\s*(?:,|$)")
_ALIAS_PARAM_RE = re.compile(r"\(\s*(\d+)\s*,")


def _where(line: str) -> Optional[str]:
    """``file:line`` from an op's metadata, repo-relative when possible."""
    m = _META_RE.search(line)
    if not m:
        return None
    path, lineno = m.group(1), m.group(2)
    for marker in ("/scconsensus_tpu/", "/tools/", "/tests/"):
        i = path.find(marker)
        if i >= 0:
            path = path[i + 1:]
            break
    return f"{path}:{lineno}"


def _is_callback(kind: str, line: str) -> Optional[str]:
    """The custom-call target when this op is a host callback
    (``pure_callback``/``io_callback`` lower to ``xla_python_*callback``
    custom-calls), else None."""
    if kind != "custom-call":
        return None
    m = _TARGET_RE.search(line)
    if m and "callback" in m.group(1):
        return m.group(1)
    return None


def _is_transfer(kind: str, line: str) -> bool:
    if kind in TRANSFER_OP_KINDS:
        return True
    return kind in _COPY_KINDS and _HOST_SPACE in line


def passport_from_hlo(
    program: str,
    hlo_text: str,
    donated: int = 0,
    memory: Optional[Dict[str, Any]] = None,
    cost: Optional[Dict[str, Any]] = None,
    stage: Optional[str] = None,
    entry_ordinal: int = 1,
    capture_s: float = 0.0,
) -> Dict[str, Any]:
    """One graph passport from optimized-HLO text (pure — tests feed
    synthetic modules). ``donated`` is the number of *declared* donated
    buffers (flattened leaves of the donated arguments); hits are the
    module header's ``input_output_alias`` entries, misses the declared
    remainder XLA could not alias. ``memory`` carries the
    ``CompiledMemoryStats`` fields already plucked into a plain dict;
    ``cost`` the normalized cost-analysis dict (obs.cost fields)."""
    histogram: Dict[str, int] = {}
    fusions = 0
    transfers: List[Dict[str, Any]] = []
    callbacks: List[Dict[str, Any]] = []
    alias_hits = 0
    for line in hlo_text.splitlines():
        if "input_output_alias={" in line:
            blk = _ALIAS_BLOCK_RE.search(line)
            if blk:
                alias_hits = len(_ALIAS_PARAM_RE.findall(blk.group(1)))
        m = _OP_RE.match(line)
        if not m:
            continue
        kind = m.group(1)
        histogram[kind] = histogram.get(kind, 0) + 1
        if kind == "fusion":
            fusions += 1
        target = _is_callback(kind, line)
        if target is not None:
            callbacks.append({"target": target, "where": _where(line)})
        elif _is_transfer(kind, line):
            transfers.append({"op": kind, "where": _where(line)})
    hits = min(alias_hits, donated) if donated else alias_hits
    misses = max(0, donated - alias_hits)
    buffers: Dict[str, int] = {}
    if memory:
        for key in ("argument_bytes", "output_bytes", "temp_bytes",
                    "alias_bytes", "generated_code_bytes"):
            v = memory.get(key)
            if isinstance(v, (int, float)):
                buffers[key] = int(v)
        # XLA's static live-set estimate: everything resident at once,
        # minus what donation lets the program reuse in place
        buffers["peak_bytes"] = max(0, (
            buffers.get("argument_bytes", 0)
            + buffers.get("output_bytes", 0)
            + buffers.get("temp_bytes", 0)
            - buffers.get("alias_bytes", 0)
        ))
    passport: Dict[str, Any] = {
        "program": program,
        "stage": stage,
        "entry_ordinal": int(entry_ordinal),
        "ops": sum(histogram.values()),
        "op_histogram": {k: histogram[k] for k in sorted(histogram)},
        "fusions": fusions,
        "transfer_ops": {"count": len(transfers), "sites": transfers},
        "host_callbacks": {"count": len(callbacks), "sites": callbacks},
        "donation": {"declared": int(donated), "hits": int(hits),
                     "misses": int(misses)},
        "buffers": buffers,
        "capture_s": round(float(capture_s), 6),
    }
    if cost:
        passport["cost"] = {k: float(v) for k, v in cost.items()}
    return passport


def build_graphs_section(
    passports: Sequence[Dict[str, Any]],
    fingerprint: Optional[Dict[str, Any]] = None,
    errors: Iterable[str] = (),
) -> Dict[str, Any]:
    """The ``graphs`` section from captured passports (pure). Programs
    are keyed by their unique capture name; ``by_stage`` joins them to
    the stage timeline by the ambient stage recorded at first call —
    the same join ``obs.compilelog`` uses, so the compile panel and the
    passport panel name the same rows."""
    programs: Dict[str, Dict[str, Any]] = {}
    by_stage: Dict[str, Dict[str, Any]] = {}
    totals = {"programs": 0, "transfer_ops": 0, "host_callbacks": 0,
              "donation_misses": 0, "fusions": 0}
    for p in passports:
        name = str(p.get("program"))
        while name in programs:  # same program, new abstract signature
            name += "'"
        programs[name] = p
        totals["programs"] += 1
        t = (p.get("transfer_ops") or {}).get("count", 0)
        c = (p.get("host_callbacks") or {}).get("count", 0)
        misses = (p.get("donation") or {}).get("misses", 0)
        totals["transfer_ops"] += t
        totals["host_callbacks"] += c
        totals["donation_misses"] += misses
        totals["fusions"] += p.get("fusions", 0)
        stage = p.get("stage") or _outside()
        row = by_stage.setdefault(stage, {
            "programs": [], "transfer_ops": 0, "host_callbacks": 0,
            "donation_misses": 0,
        })
        row["programs"].append(name)
        row["transfer_ops"] += t
        row["host_callbacks"] += c
        row["donation_misses"] += misses
    sec: Dict[str, Any] = {
        "version": GRAPHS_VERSION,
        "programs": {k: programs[k] for k in sorted(programs)},
        "by_stage": {k: by_stage[k] for k in sorted(by_stage)},
        "totals": totals,
    }
    if fingerprint:
        sec["fingerprint"] = fingerprint
    errs = [str(e) for e in errors]
    if errs:
        sec["errors"] = errs
    return sec


def _outside() -> str:
    from scconsensus_tpu.obs.hostprof import OUTSIDE_SPANS

    return OUTSIDE_SPANS


# --------------------------------------------------------------------------
# environment fingerprint (satellite: passports are toolchain-keyed)
# --------------------------------------------------------------------------

_FP_FIELDS = ("jax", "jaxlib", "backend", "device_kind", "xla_flags",
              "libtpu_init_args")


def fingerprint_digest(fp: Dict[str, Any]) -> str:
    """12-hex digest over the identity fields (ignores the digest field
    itself and any future additive keys), the single equality the diff
    tool and the ratchet key on."""
    core = {k: fp.get(k) for k in _FP_FIELDS}
    return hashlib.sha256(
        json.dumps(core, sort_keys=True).encode()
    ).hexdigest()[:12]


def environment_fingerprint() -> Optional[Dict[str, Any]]:
    """Toolchain identity of this process: jax/jaxlib versions, backend,
    device kind, and the XLA/libtpu environment knobs that change
    compiled programs. None when jax was never imported — a jax-free
    record has no compiled programs to key. Never imports jax itself
    (orchestrator-side records must not trigger plugin registration) and
    never initializes a backend that is not already up."""
    import sys

    if "jax" not in sys.modules:
        return None
    jax = sys.modules["jax"]
    fp: Dict[str, Any] = {
        "jax": getattr(jax, "__version__", None),
        "xla_flags": os.environ.get("XLA_FLAGS") or "",
        "libtpu_init_args": os.environ.get("LIBTPU_INIT_ARGS") or "",
    }
    try:
        import jaxlib  # pairs with jax; no backend init

        fp["jaxlib"] = getattr(jaxlib, "__version__", None)
    except Exception:
        fp["jaxlib"] = None
    try:
        fp["backend"] = jax.default_backend()
        dev = jax.devices()[0]
        fp["device_kind"] = getattr(dev, "device_kind", None)
        fp["device_count"] = int(jax.device_count())
    except Exception:
        fp.setdefault("backend", None)
        fp.setdefault("device_kind", None)
    fp["digest"] = fingerprint_digest(fp)
    return fp


# --------------------------------------------------------------------------
# runtime: armed registry, memoized first-call capture, snapshot
# --------------------------------------------------------------------------

_STATE: Dict[str, Any] = {
    "armed": False,
    "passports": [],      # captured passport dicts, call order
    "seen": set(),        # (program, signature) keys already captured
    "errors": [],
    "lock": threading.Lock(),
}


def install_and_mark(force: bool = False) -> bool:
    """Arm the passport registry (gated on ``SCC_GRAPHS`` unless
    ``force``); also clears any capture from a previous arm so a worker
    section holds only its own run's programs."""
    if not force and not env_flag("SCC_GRAPHS"):
        return False
    reset()
    _STATE["armed"] = True
    return True


def armed() -> bool:
    return bool(_STATE["armed"])


def reset() -> None:
    """Disarm and drop all captured state (tests; install re-arms)."""
    with _STATE["lock"]:
        _STATE["armed"] = False
        _STATE["passports"] = []
        _STATE["seen"] = set()
        _STATE["errors"] = []


def _signature(args: Tuple, kwargs: Dict[str, Any]) -> Any:
    """Hashable abstract signature: pytree structure + per-leaf
    (shape, dtype) for arrays, value for hashable statics. NEVER reprs
    an array — that would fetch device data mid-stage."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for x in leaves:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append(("arr", tuple(int(s) for s in shape), str(dtype)))
        elif isinstance(x, (int, float, bool, str, type(None))):
            sig.append(("val", x))
        else:
            sig.append(("type", type(x).__name__))
    return (str(treedef), tuple(sig))


def _count_donated(donate_argnums: Sequence[int], args: Tuple) -> int:
    """Declared donated buffers = flattened leaves of the donated
    positional args (what XLA sees as donatable parameters)."""
    if not donate_argnums:
        return 0
    import jax

    n = 0
    for i in donate_argnums:
        if 0 <= int(i) < len(args):
            n += len(jax.tree_util.tree_leaves(args[int(i)]))
    return n


def _memory_dict(compiled) -> Optional[Dict[str, int]]:
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None
    out: Dict[str, int] = {}
    for attr, key in (
        ("argument_size_in_bytes", "argument_bytes"),
        ("output_size_in_bytes", "output_bytes"),
        ("temp_size_in_bytes", "temp_bytes"),
        ("alias_size_in_bytes", "alias_bytes"),
        ("generated_code_size_in_bytes", "generated_code_bytes"),
    ):
        v = getattr(mem, attr, None)
        if isinstance(v, (int, float)):
            out[key] = int(v)
    return out or None


def _cost_dict(compiled) -> Optional[Dict[str, float]]:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not ca:
        return None
    out: Dict[str, float] = {}
    for src, dst in (("flops", "flops"), ("bytes accessed", "bytes_accessed"),
                     ("transcendentals", "transcendentals")):
        v = ca.get(src)
        if v is not None:
            out[dst] = float(v)
    return out or None


def observe(program: str, jitted, args: Tuple = (),
            kwargs: Optional[Dict[str, Any]] = None,
            donate_argnums: Sequence[int] = ()) -> None:
    """Capture ``program``'s passport on the first call at this abstract
    signature (no-op when disarmed or already seen — one set lookup).
    Best-effort: a failing lower/compile records an error string, never
    raises into the measurement."""
    if not _STATE["armed"]:
        return
    kwargs = kwargs or {}
    try:
        key = (program, _signature(args, kwargs))
    except Exception:
        key = (program, None)
    if key in _STATE["seen"]:
        return
    with _STATE["lock"]:
        if key in _STATE["seen"]:
            return
        _STATE["seen"].add(key)
        cap = int(env_flag("SCC_GRAPHS_MAX_PROGRAMS"))
        if len(_STATE["passports"]) >= cap:
            msg = f"passport cap reached ({cap}); further programs dropped"
            if msg not in _STATE["errors"]:
                _STATE["errors"].append(msg)
            return
    t0 = time.perf_counter()
    try:
        stage, ordinal = _ambient()
        compiled = jitted.lower(*args, **kwargs).compile()
        passport = passport_from_hlo(
            program,
            compiled.as_text(),
            donated=_count_donated(donate_argnums, args),
            memory=_memory_dict(compiled),
            cost=_cost_dict(compiled),
            stage=stage,
            entry_ordinal=ordinal,
            capture_s=time.perf_counter() - t0,
        )
        with _STATE["lock"]:
            _STATE["passports"].append(passport)
    except Exception as e:
        with _STATE["lock"]:
            _STATE["errors"].append(f"{program}: {e!r}")


def _ambient() -> Tuple[Optional[str], int]:
    try:
        from scconsensus_tpu.obs.trace import ambient_stage

        name, ordinal = ambient_stage()
        if name is not None:
            return str(name), max(1, int(ordinal))
    except Exception:
        pass
    return None, 1


class _Observed:
    """A jitted callable plus first-call-per-signature passport capture.
    Transparent otherwise: attribute access (``.lower``, AOT users)
    forwards to the wrapped function, and a disarmed registry costs one
    dict lookup per call."""

    __slots__ = ("_program", "_fn", "_donate")

    def __init__(self, program: str, fn, donate_argnums: Sequence[int]):
        self._program = program
        self._fn = fn
        self._donate = tuple(donate_argnums)

    def __call__(self, *args, **kwargs):
        if _STATE["armed"]:
            observe(self._program, self._fn, args, kwargs,
                    donate_argnums=self._donate)
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)

    @property
    def __wrapped__(self):
        return self._fn

    def __repr__(self):  # pragma: no cover - debugging nicety
        return f"<observed {self._program}: {self._fn!r}>"


def instrument(program: str, jitted, donate_argnums: Sequence[int] = ()):
    """Wrap an already-jitted callable as an observed stage program."""
    return _Observed(program, jitted, donate_argnums)


def snapshot() -> Optional[Dict[str, Any]]:
    """The ``graphs`` section for everything captured since arming; None
    when never armed — the record omits the section rather than claim a
    run that was not looking compiled nothing."""
    if not _STATE["armed"]:
        return None
    with _STATE["lock"]:
        passports = list(_STATE["passports"])
        errors = list(_STATE["errors"])
    return build_graphs_section(
        passports,
        fingerprint=environment_fingerprint(),
        errors=errors,
    )


# --------------------------------------------------------------------------
# consumers: per-stage counts (the perf-gate ratchet) + pins ack
# --------------------------------------------------------------------------

def stage_graph_counts(rec: Dict[str, Any]) -> Dict[str, Dict[str, int]]:
    """``{stage: {transfer_ops, host_callbacks}}`` from a run record's
    graphs section ({} when absent) — the candidate side of the
    perf-gate transfer-op ratchet."""
    sec = rec.get("graphs")
    if not isinstance(sec, dict):
        return {}
    out: Dict[str, Dict[str, int]] = {}
    for stage, row in (sec.get("by_stage") or {}).items():
        if isinstance(row, dict):
            out[str(stage)] = {
                "transfer_ops": int(row.get("transfer_ops", 0)),
                "host_callbacks": int(row.get("host_callbacks", 0)),
            }
    return out


def ratchet_ack(ratchet_entry: Dict[str, Any]) -> str:
    """12-hex digest of one dataset's ``graph_ratchet`` pins — stamped
    into ``extra.graph_ratchet_ack`` on bench records so committed
    evidence names exactly which debt snapshot it was gated against."""
    return hashlib.sha256(
        json.dumps(ratchet_entry, sort_keys=True).encode()
    ).hexdigest()[:12]


# --------------------------------------------------------------------------
# validation (export.validate_run_record dispatches here)
# --------------------------------------------------------------------------

def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"graphs section: {msg}")


def _validate_sites(name: str, block: Any, site_key: str) -> int:
    _require(isinstance(block, dict), f"{name} must be an object")
    n = block.get("count")
    _require(isinstance(n, int) and n >= 0, f"{name}.count must be >= 0")
    sites = block.get("sites")
    _require(isinstance(sites, list), f"{name}.sites must be a list")
    _require(len(sites) == n, f"{name}.sites does not match its count")
    for s in sites:
        _require(isinstance(s, dict) and isinstance(s.get(site_key), str),
                 f"{name} site missing {site_key!r}")
        w = s.get("where")
        _require(w is None or isinstance(w, str),
                 f"{name} site where must be a string or null")
    return n


def validate_graphs(sec: Dict[str, Any]) -> None:
    """Structural validation of a record's ``graphs`` section (additive
    scc-run-record v1 extension): per-program passports internally
    consistent, by_stage rows referencing real programs, totals summing
    to the passports."""
    _require(isinstance(sec, dict), "must be an object")
    _require(sec.get("version") == GRAPHS_VERSION,
             f"version must be {GRAPHS_VERSION}")
    programs = sec.get("programs")
    _require(isinstance(programs, dict), "programs must be an object")
    sums = {"transfer_ops": 0, "host_callbacks": 0, "donation_misses": 0,
            "fusions": 0}
    for name, p in programs.items():
        _require(isinstance(p, dict), f"programs[{name!r}] not an object")
        ops = p.get("ops")
        _require(isinstance(ops, int) and ops >= 0,
                 f"programs[{name!r}].ops must be >= 0")
        hist = p.get("op_histogram")
        _require(isinstance(hist, dict),
                 f"programs[{name!r}].op_histogram must be an object")
        _require(sum(hist.values()) == ops,
                 f"programs[{name!r}] histogram does not sum to ops")
        fus = p.get("fusions")
        _require(isinstance(fus, int) and fus >= 0,
                 f"programs[{name!r}].fusions must be >= 0")
        _require(fus == hist.get("fusion", 0),
                 f"programs[{name!r}].fusions disagrees with histogram")
        t = _validate_sites(f"programs[{name!r}].transfer_ops",
                            p.get("transfer_ops"), "op")
        c = _validate_sites(f"programs[{name!r}].host_callbacks",
                            p.get("host_callbacks"), "target")
        don = p.get("donation")
        _require(isinstance(don, dict),
                 f"programs[{name!r}].donation must be an object")
        for k in ("declared", "hits", "misses"):
            v = don.get(k)
            _require(isinstance(v, int) and v >= 0,
                     f"programs[{name!r}].donation.{k} must be >= 0")
        _require(don["hits"] + don["misses"] <= max(don["declared"],
                                                    don["hits"]),
                 f"programs[{name!r}].donation counts inconsistent")
        _require(isinstance(p.get("buffers"), dict),
                 f"programs[{name!r}].buffers must be an object")
        eo = p.get("entry_ordinal")
        _require(isinstance(eo, int) and eo >= 1,
                 f"programs[{name!r}].entry_ordinal must be >= 1")
        sums["transfer_ops"] += t
        sums["host_callbacks"] += c
        sums["donation_misses"] += don["misses"]
        sums["fusions"] += fus
    by_stage = sec.get("by_stage")
    _require(isinstance(by_stage, dict), "by_stage must be an object")
    listed: List[str] = []
    stage_sums = {"transfer_ops": 0, "host_callbacks": 0,
                  "donation_misses": 0}
    for stage, row in by_stage.items():
        _require(isinstance(row, dict), f"by_stage[{stage!r}] not an object")
        progs = row.get("programs")
        _require(isinstance(progs, list) and progs,
                 f"by_stage[{stage!r}].programs must be a non-empty list")
        for nm in progs:
            _require(nm in programs,
                     f"by_stage[{stage!r}] references unknown program {nm!r}")
            listed.append(nm)
        for k in stage_sums:
            v = row.get(k)
            _require(isinstance(v, int) and v >= 0,
                     f"by_stage[{stage!r}].{k} must be >= 0")
            stage_sums[k] += v
    _require(sorted(listed) == sorted(programs),
             "by_stage programs do not partition the program set")
    totals = sec.get("totals")
    _require(isinstance(totals, dict), "totals must be an object")
    _require(totals.get("programs") == len(programs),
             "totals.programs disagrees with the program set")
    for k, v in sums.items():
        _require(totals.get(k) == v, f"totals.{k} disagrees with passports")
    for k in stage_sums:
        _require(stage_sums[k] == sums[k],
                 f"by_stage {k} does not sum to totals")
    fp = sec.get("fingerprint")
    if fp is not None:
        _require(isinstance(fp, dict), "fingerprint must be an object")
        dig = fp.get("digest")
        _require(isinstance(dig, str) and len(dig) == 12,
                 "fingerprint.digest must be a 12-hex string")
        _require(dig == fingerprint_digest(fp),
                 "fingerprint.digest does not match its fields")
    errs = sec.get("errors")
    if errs is not None:
        _require(isinstance(errs, list)
                 and all(isinstance(e, str) for e in errs),
                 "errors must be a list of strings")
