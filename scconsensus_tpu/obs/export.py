"""The canonical run-record schema + Chrome trace-event export.

``bench.py``, ``tools/run_sparse_1m.py``, and ``tools/repeat_anchor.py``
each used to emit differently-shaped JSON. Every emitter now builds its
artifact through :func:`build_run_record`, and every ingester
(``tools/summarize_evidence.py``, cross-round diff tooling) validates with
:func:`validate_run_record` / :func:`check_schema_version`.

schema ``scc-run-record`` version 1 — top-level keys:

  schema            "scc-run-record" (constant)
  schema_version    1 (integer; ingesters error on unknown versions)
  metric/value/unit/vs_baseline
                    the legacy driver headline, unchanged (the driver
                    parses the last JSON line of a run's output)
  run               {created_unix, platform?, jax_version?, argv?}
  spans             [span records: name, span_id, parent_id, depth, kind,
                    t0_s, wall_submitted_s, wall_synced_s|null, synced,
                    attrs?, metrics?, device_mem?]
  device            {memory: per-device live/peak HBM or null,
                     host_peak_rss_bytes, compile: {events, total_s, ...}?,
                     transfers: TransferWatch.report()?}
  extra             free-form emitter extras (legacy ``extra`` dict)
  termination       OPTIONAL (still schema version 1 — additive): stamped
                    by the live flight recorder (obs.live) on incrementally
                    flushed partial records. {cause: clean|signal|stall|
                    crash, last_span: str|null, open_spans: [...],
                    stall_count, heartbeat_path?, flushed_unix}. Absent on
                    records written by a clean single-shot emitter; any
                    cause other than "clean" marks the record PARTIAL —
                    ledger-ingestible but never a regression baseline.
  quality           OPTIONAL (still schema version 1 — additive): the
                    scientific-quality section (obs.quality) — DE gate
                    funnel (per pair + aggregated, counts monotone down
                    the funnel), rank-sum window-ladder occupancy,
                    consensus/cluster structure (size histograms,
                    contingency entropy, ARI vs inputs, churn, per-
                    deepSplit silhouette), and numeric-health sentinel
                    trips. Validated by obs.quality.validate_quality.
  residency         OPTIONAL (still schema version 1 — additive): the
                    host↔device residency audit (obs.residency) — mode,
                    per-direction byte/call totals, per-stage and per-
                    boundary aggregates, span-attributed transfer
                    events, enforce-mode violations. Validated by
                    obs.residency.validate_residency.
  kernels           OPTIONAL (still schema version 1 — additive): the
                    device-kernel timeline (obs.kernels) — top-K kernels
                    by device time from a jax.profiler capture window,
                    joined to tracer spans and the obs.cost FLOPs/bytes
                    model (achieved device-time rates). Validated by
                    obs.kernels.validate_kernels.
  robustness        OPTIONAL (still schema version 1 — additive): the
                    survivable-pipeline trail (robust.record) — faults
                    injected (SCC_FAULT_PLAN), typed retries with error
                    classes, degradations, mid-stage resume points, the
                    per-run retry budget, and bench orchestration
                    adaptations. Validated by
                    robust.record.validate_robustness — a section
                    claiming recovery without retry/resume evidence is
                    rejected. Absent on healthy unfaulted runs.
  serving           OPTIONAL (still schema version 1 — additive): the
                    online-serving trail (serve.metrics) — per-outcome
                    request counters, p50/p99 latency, throughput, queue
                    depth/capacity, circuit-breaker state + trips, drift
                    quarantine counts, driver overhead. Validated by
                    serve.metrics.validate_serving — a section whose
                    outcome counters do not sum to its submissions
                    (a lost request) is rejected.
  slo               OPTIONAL (still schema version 1 — additive): the
                    telemetry-plane SLO section (serve.slo, round 20) —
                    declared objectives (availability target, p99
                    target, burn windows, burn limit), availability
                    counts over the wire/serving outcome counters (2xx
                    good, 4xx excluded, 5xx burn the budget),
                    multi-window burn rates, per-outcome and per-stage
                    fixed-bucket latency histograms (mergeable across
                    replicas by the frozen bucket grid), and the
                    optional obs-overhead gauge (plane on vs off).
                    Validated by serve.slo.validate_slo — a section
                    whose availability counts don't sum, whose burn
                    rates contradict their own error ratios, or whose
                    histogram buckets don't sum to their count is
                    rejected; tools/perf_gate.py additionally FAILS a
                    record whose worst burn exceeds its own declared
                    burn_limit or whose p99 misses its own target.
  streaming         OPTIONAL (still schema version 1 — additive): the
                    out-of-core trail (stream.record) — chunk counters
                    (planned/fresh/resumed/recomputed/quarantined), the
                    window-halving and checkpoint-granularity ladders,
                    and the host-memory budget evidence (peak RSS vs
                    SCC_STREAM_HOST_BUDGET_MB). Validated by
                    stream.record.validate_streaming — a section
                    claiming within_budget without peak-RSS evidence
                    (or with the peak over the budget), or whose chunk
                    counts do not sum, is rejected.
  loadgen           OPTIONAL (still schema version 1 — additive): the
                    open-loop traffic section (serve.fleet.loadgen,
                    round 21) — arrival profile + seeded schedule
                    identity, the traffic mix over registered workload
                    scenarios, open-loop accounting (offered >= sent >=
                    completed >= good), the sustained-RPS-at-SLO
                    headline consistency rule (0.0 on a breached run),
                    and the autoscaler's typed actuation trail.
                    Validated by serve.fleet.loadgen.validate_loadgen.
  profile           OPTIONAL (still schema version 1 — additive): the
                    unified per-run profile (obs.profile, round 22) —
                    one row per stage span joining wall time, device
                    time, cost-model FLOPs/bytes, achieved rates (vs.
                    an optional measured ceiling), and audited
                    transfer bytes, plus per-declared-boundary rows.
                    Derived at record-build time from the spans /
                    kernels / cost / residency sections (no new
                    instrumentation). Validated by
                    obs.profile.validate_profile.
  residency_burndown
                    OPTIONAL (still schema version 1 — additive): the
                    residency burn-down ledger (obs.profile, round
                    22) — bytes crossed per declared boundary with
                    the TODO(item-2) boundaries totalled separately,
                    the ratcheting progress metric for the device-
                    residency refactor. Validated by
                    obs.profile.validate_residency_burndown — totals
                    disagreeing with the per-boundary rows are
                    rejected.
  tunnel            OPTIONAL (still schema version 1 — additive, round
                    22): accelerator-tunnel health stamped by bench
                    when the TPU capture tunnel is NOT known-alive —
                    {state: stale|dead|missing|error, age_s?,
                    last_outcome?, log?}. Absence means either the
                    tunnel was alive or the run never needed one (CPU
                    run without no-cpu-fallback mode); presence makes
                    "accelerator evidence missing" an explicit,
                    greppable fact instead of a silent omission.
  host_profile      OPTIONAL (still schema version 1 — additive, round
                    19): the host execution profile (obs.hostprof) —
                    sampled stacks bucketed per stage span and
                    classified into named host causes (python-compute
                    with top frame, blocking_wait, compile,
                    serialization) plus gc.callbacks pause accounting
                    (with the explicit "(outside spans)" bucket) and
                    the sampler's own self-time. Presence means the
                    profiler RAN (zero samples included); absence
                    means it never ran — a present-but-null value is
                    rejected. Validated by
                    obs.hostprof.validate_host_profile.
  compile           OPTIONAL (still schema version 1 — additive, round
                    19): per-stage JAX compile/retrace telemetry
                    (obs.compilelog) — compiles, traces, retraces
                    (trace-shaped events on a stage's second-or-later
                    entry), compilation-cache hits, compile wall, and
                    per-event / per-stage breakdowns. Distinct from
                    the legacy flat device.compile aggregate, which is
                    unchanged. Validated by
                    obs.compilelog.validate_compile.
  memory_timeline   OPTIONAL (still schema version 1 — additive, round
                    19): the unified memory timeline (obs.hostprof) —
                    downsampled host-RSS (and, when a backend is up,
                    HBM bytes_in_use) samples laid over the stage
                    timeline, with peak bytes and per-stage RSS
                    first/peak/last/delta. Validated by
                    obs.hostprof.validate_memory_timeline.
  integrity         OPTIONAL (still schema version 1 — additive): the
                    computation-integrity trail (robust.integrity,
                    round 18) — invariant checks planned/run/passed
                    per check and in total, recorded violations,
                    ghost-replay counters + mismatches against the
                    float64 oracle, and silent-corruption recomputes.
                    Validated by robust.integrity.validate_integrity —
                    a section claiming ``all_checks_passed`` with
                    ``checks_run < checks_planned`` (or with failed
                    checks, unmatched replays, or phantom recomputes)
                    is rejected: claims must carry evidence. Absent
                    with SCC_INTEGRITY=off.

The Chrome trace export (:func:`chrome_trace`) converts the span tree to
``traceEvents`` complete ("X") events — open the file in Perfetto
(https://ui.perfetto.dev) or chrome://tracing.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "SCHEMA_NAME",
    "SCHEMA_VERSION",
    "TERMINATION_CAUSES",
    "build_run_record",
    "validate_run_record",
    "check_schema_version",
    "chrome_trace",
    "write_chrome_trace",
    "write_json_atomic",
]

SCHEMA_NAME = "scc-run-record"
SCHEMA_VERSION = 1

# The only admissible termination.cause values: "clean" (the run finished
# and said so), "signal" (SIGTERM-style external stop), "stall" (the
# in-process watchdog fired and the process was later reaped), "crash"
# (the periodic flush's standing stamp — if this file is the last evidence,
# the process died with no handler running).
TERMINATION_CAUSES = ("clean", "signal", "stall", "crash")


def _device_section(tracer=None,
                    transfers: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    from scconsensus_tpu.obs import device as obs_device

    out: Dict[str, Any] = {
        "memory": obs_device.memory_snapshot(),
        "host_peak_rss_bytes": obs_device.host_peak_rss_bytes(),
    }
    if tracer is not None:
        cs = tracer.compile_stats()
        if cs is not None:
            out["compile"] = cs
    if transfers is not None:
        out["transfers"] = transfers
    return out


def build_run_record(
    metric: str,
    value,
    unit: str = "seconds",
    vs_baseline=None,
    extra: Optional[Dict[str, Any]] = None,
    spans: Optional[List[Dict[str, Any]]] = None,
    tracer=None,
    device: Optional[Dict[str, Any]] = None,
    transfers: Optional[Dict[str, Any]] = None,
    platform: Optional[str] = None,
    quality: Optional[Dict[str, Any]] = None,
    residency: Optional[Dict[str, Any]] = None,
    kernels: Optional[Dict[str, Any]] = None,
    robustness: Optional[Dict[str, Any]] = None,
    serving: Optional[Dict[str, Any]] = None,
    slo: Optional[Dict[str, Any]] = None,
    streaming: Optional[Dict[str, Any]] = None,
    integrity: Optional[Dict[str, Any]] = None,
    scenario: Optional[Dict[str, Any]] = None,
    loadgen: Optional[Dict[str, Any]] = None,
    profile: Optional[Dict[str, Any]] = None,
    residency_burndown: Optional[Dict[str, Any]] = None,
    tunnel: Optional[Dict[str, Any]] = None,
    host_profile: Optional[Dict[str, Any]] = None,
    compile: Optional[Dict[str, Any]] = None,
    memory_timeline: Optional[Dict[str, Any]] = None,
    graphs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """One schema-v1 run record. Pass ``tracer`` to take spans + compile
    stats from it; or pre-built ``spans`` (e.g. a resumed pipeline's
    ``result.metrics["spans"]``); or neither (orchestrator-side records
    written before any measurement ran). ``quality`` (optional) attaches
    the obs.quality section — funnels, cluster structure, sentinel
    trips; ``residency`` / ``kernels`` (optional) attach the
    obs.residency transfer audit and the obs.kernels device-op
    timeline; ``robustness`` (optional) attaches the robust.record
    fault/retry/resume trail; ``serving`` (optional) attaches the
    serve.metrics online-serving section; ``streaming`` (optional)
    attaches the stream.record out-of-core section; ``integrity``
    (optional) attaches the robust.integrity computation-integrity
    section; ``scenario`` (optional) attaches the workload-zoo
    scenario identity section (scconsensus_tpu.workloads); ``loadgen``
    (optional) attaches the open-loop traffic section
    (serve.fleet.loadgen); ``profile`` / ``residency_burndown``
    (optional) attach the obs.profile unified stage profile and
    residency burn-down ledger; ``tunnel`` (optional) attaches the
    accelerator-tunnel health stamp (tools.tunnel_probe status);
    ``host_profile`` / ``compile`` / ``memory_timeline`` (optional)
    attach the round-19 host execution observatory sections
    (obs.hostprof sampled stacks + GC pauses, obs.compilelog
    compile/retrace counters, and the RSS/HBM timeline); ``graphs``
    (optional) attaches the obs.graphs compiled-program observatory —
    per-program graph passports (op census, transfer ops, host
    callbacks, donation hits/misses, buffer bytes), keyed by the
    run's environment fingerprint."""
    if spans is None:
        spans = tracer.span_records() if tracer is not None else []
    extra = dict(extra or {})
    run: Dict[str, Any] = {"created_unix": round(time.time(), 3)}
    plat = platform or extra.get("platform")
    if plat is not None:
        run["platform"] = plat
    import sys

    if "jax" in sys.modules:  # never import jax here: orchestrator-side
        try:                  # records must not trigger plugin registration
            run["jax_version"] = sys.modules["jax"].__version__
        except Exception:
            pass
        try:  # toolchain identity keys graph passports + their ratchet
            from scconsensus_tpu.obs.graphs import environment_fingerprint

            fp = environment_fingerprint()
            if fp is not None:
                run["env_fingerprint"] = fp
        except Exception:
            pass
    rec = {
        "schema": SCHEMA_NAME,
        "schema_version": SCHEMA_VERSION,
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
        "run": run,
        "spans": spans,
        "device": device if device is not None
        else _device_section(tracer, transfers),
        "extra": extra,
    }
    if quality is not None:
        rec["quality"] = quality
    if residency is not None:
        rec["residency"] = residency
    if kernels is not None:
        rec["kernels"] = kernels
    if robustness is not None:
        rec["robustness"] = robustness
    if serving is not None:
        rec["serving"] = serving
    if slo is not None:
        rec["slo"] = slo
    if streaming is not None:
        rec["streaming"] = streaming
    if integrity is not None:
        rec["integrity"] = integrity
    if scenario is not None:
        rec["scenario"] = scenario
    if loadgen is not None:
        rec["loadgen"] = loadgen
    if profile is not None:
        rec["profile"] = profile
    if residency_burndown is not None:
        rec["residency_burndown"] = residency_burndown
    if tunnel is not None:
        rec["tunnel"] = tunnel
    if host_profile is not None:
        rec["host_profile"] = host_profile
    if compile is not None:
        rec["compile"] = compile
    if memory_timeline is not None:
        rec["memory_timeline"] = memory_timeline
    if graphs is not None:
        rec["graphs"] = graphs
    return rec


def check_schema_version(rec: Dict[str, Any], source: str = "record") -> str:
    """Classify a record for ingesters: returns 'legacy' for pre-schema
    artifacts (no ``schema`` key), 'v<N>' for a known version, and raises
    ValueError on an unknown schema name or version — an ingester must
    never silently misread a future schema."""
    if not isinstance(rec, dict) or "schema" not in rec:
        return "legacy"
    name = rec.get("schema")
    if name != SCHEMA_NAME:
        raise ValueError(f"{source}: unknown schema {name!r}")
    ver = rec.get("schema_version")
    if ver != SCHEMA_VERSION:
        raise ValueError(
            f"{source}: unsupported {SCHEMA_NAME} version {ver!r} "
            f"(this tool knows version {SCHEMA_VERSION})"
        )
    return f"v{ver}"


def validate_run_record(rec: Dict[str, Any]) -> None:
    """Structural validation of a schema-v1 record; raises ValueError with
    the first violation. The test suite and every ingester share this one
    checker so 'schema-valid' means the same thing everywhere."""
    if check_schema_version(rec) == "legacy":
        raise ValueError("record has no schema field")
    for key in ("metric", "value", "unit", "vs_baseline", "run", "spans",
                "device", "extra"):
        if key not in rec:
            raise ValueError(f"run record missing key {key!r}")
    if not isinstance(rec["metric"], str) or not rec["metric"]:
        raise ValueError("metric must be a non-empty string")
    if not isinstance(rec["run"], dict) or "created_unix" not in rec["run"]:
        raise ValueError("run section must carry created_unix")
    if not isinstance(rec["spans"], list):
        raise ValueError("spans must be a list")
    all_ids = {
        s.get("span_id") for s in rec["spans"] if isinstance(s, dict)
    }
    for i, s in enumerate(rec["spans"]):
        where = f"spans[{i}]"
        if not isinstance(s, dict):
            raise ValueError(f"{where} is not an object")
        for key in ("name", "span_id", "depth", "kind", "t0_s",
                    "wall_submitted_s", "synced"):
            if key not in s:
                raise ValueError(f"{where} missing {key!r}")
        if not isinstance(s["name"], str) or not s["name"]:
            raise ValueError(f"{where}: name must be a non-empty string")
        if s["t0_s"] < 0 or s["wall_submitted_s"] < 0:
            raise ValueError(f"{where}: negative timing")
        ws = s.get("wall_synced_s")
        if ws is not None and ws < 0:
            raise ValueError(f"{where}: negative synced wall")
        if s["synced"] and ws is None:
            raise ValueError(f"{where}: synced span without wall_synced_s")
        parent = s.get("parent_id")
        if parent is not None and parent not in all_ids:
            raise ValueError(f"{where}: dangling parent_id {parent}")
    if not isinstance(rec["device"], dict):
        raise ValueError("device section must be an object")
    term = rec.get("termination")
    if term is not None:
        if not isinstance(term, dict):
            raise ValueError("termination must be an object")
        if term.get("cause") not in TERMINATION_CAUSES:
            raise ValueError(
                f"termination.cause must be one of {TERMINATION_CAUSES}, "
                f"got {term.get('cause')!r}"
            )
        ls = term.get("last_span")
        if ls is not None and not isinstance(ls, str):
            raise ValueError("termination.last_span must be a string or null")
        if not isinstance(term.get("open_spans", []), list):
            raise ValueError("termination.open_spans must be a list")
    qual = rec.get("quality")
    if qual is not None:
        # lazy import: quality pulls in the trace layer, which exporters
        # (and the jax-free orchestrator) must not load unconditionally
        from scconsensus_tpu.obs.quality import validate_quality

        validate_quality(qual)
    res = rec.get("residency")
    if res is not None:
        from scconsensus_tpu.obs.residency import validate_residency

        validate_residency(res)
    kern = rec.get("kernels")
    if kern is not None:
        from scconsensus_tpu.obs.kernels import validate_kernels

        validate_kernels(kern)
    rb = rec.get("robustness")
    if rb is not None:
        # jax-free import (robust.record is stdlib-only by contract)
        from scconsensus_tpu.robust.record import validate_robustness

        validate_robustness(rb)
    sv = rec.get("serving")
    if sv is not None:
        # jax-free import (serve.metrics is stdlib-only by contract)
        from scconsensus_tpu.serve.metrics import validate_serving

        validate_serving(sv)
    slo = rec.get("slo")
    if slo is not None:
        # jax-free import (serve.slo is stdlib-only by contract)
        from scconsensus_tpu.serve.slo import validate_slo

        validate_slo(slo)
    sm = rec.get("streaming")
    if sm is not None:
        # jax-free import (stream.record is stdlib-only by contract)
        from scconsensus_tpu.stream.record import validate_streaming

        validate_streaming(sm)
    ig = rec.get("integrity")
    if ig is not None:
        # jax-free import (robust.integrity's module level is jax-free
        # by contract; jax loads only inside the device checks)
        from scconsensus_tpu.robust.integrity import validate_integrity

        validate_integrity(ig)
    sc = rec.get("scenario")
    if sc is not None:
        # jax-free import (workloads' module level is jax-free by
        # contract; scenario runners lazy-import their compute)
        from scconsensus_tpu.workloads import validate_scenario

        validate_scenario(sc)
    lg = rec.get("loadgen")
    if lg is not None:
        # jax-free import (serve.fleet.loadgen's module level is
        # numpy-only by contract; the run path lazy-imports compute)
        from scconsensus_tpu.serve.fleet.loadgen import validate_loadgen

        validate_loadgen(lg)
    prof = rec.get("profile")
    if prof is not None:
        # jax-free import (obs.profile joins already-collected dicts)
        from scconsensus_tpu.obs.profile import validate_profile

        validate_profile(prof)
    bd = rec.get("residency_burndown")
    if bd is not None:
        from scconsensus_tpu.obs.profile import validate_residency_burndown

        validate_residency_burndown(bd)
    tun = rec.get("tunnel")
    if tun is not None:
        if not isinstance(tun, dict):
            raise ValueError("tunnel section must be an object")
        if tun.get("state") not in ("alive", "stale", "dead", "missing",
                                    "error"):
            raise ValueError(
                "tunnel.state must be alive|stale|dead|missing|error, "
                f"got {tun.get('state')!r}"
            )
        age = tun.get("age_s")
        if age is not None and (not isinstance(age, (int, float))
                                or age < 0):
            raise ValueError("tunnel.age_s must be a number >= 0")
    # round-19 host-observatory sections: absence is the marker for "the
    # instrument never ran" — a present-but-null key would make absence
    # ambiguous, so it is rejected outright
    for key in ("host_profile", "compile", "memory_timeline", "graphs"):
        if key in rec and rec[key] is None:
            raise ValueError(
                f"{key} must be omitted when absent, not null"
            )
    hp = rec.get("host_profile")
    if hp is not None:
        # jax-free import (obs.hostprof's module level is stdlib-only)
        from scconsensus_tpu.obs.hostprof import validate_host_profile

        validate_host_profile(hp)
    comp = rec.get("compile")
    if comp is not None:
        # jax-free import (obs.compilelog aggregates captured tuples)
        from scconsensus_tpu.obs.compilelog import validate_compile

        validate_compile(comp)
    mt = rec.get("memory_timeline")
    if mt is not None:
        from scconsensus_tpu.obs.hostprof import validate_memory_timeline

        validate_memory_timeline(mt)
    gr = rec.get("graphs")
    if gr is not None:
        # jax-free import (obs.graphs validation parses captured dicts)
        from scconsensus_tpu.obs.graphs import validate_graphs

        validate_graphs(gr)


# --------------------------------------------------------------------------
# Chrome trace events (Perfetto / chrome://tracing)
# --------------------------------------------------------------------------

def chrome_trace(spans: List[Dict[str, Any]],
                 process_name: str = "scconsensus_tpu") -> Dict[str, Any]:
    """Span records → Chrome trace-event JSON (complete "X" events, µs).

    Each span becomes one event spanning [t0, t0 + wall] where the wall is
    the device-synced one when recorded (honest compute attribution) else
    the submitted one. Children close before their parent by construction,
    so events nest under Perfetto's containment rules. Events are emitted
    sorted by timestamp.
    """
    events: List[Dict[str, Any]] = [{
        "ph": "M", "pid": 0, "tid": 0, "ts": 0,
        "name": "process_name", "args": {"name": process_name},
    }]
    for s in spans:
        wall = s.get("wall_synced_s")
        if wall is None:
            wall = s["wall_submitted_s"]
        args: Dict[str, Any] = {
            "kind": s.get("kind"),
            "synced": s.get("synced"),
            "wall_submitted_s": s.get("wall_submitted_s"),
        }
        if s.get("wall_synced_s") is not None:
            args["wall_synced_s"] = s["wall_synced_s"]
        for src in ("attrs", "metrics"):
            v = s.get(src)
            if v:
                # scalars only: Perfetto renders args flat, and a 1M-shape
                # occupancy dict would bloat every event row
                args.update({
                    k: x for k, x in v.items()
                    if isinstance(x, (int, float, str, bool))
                })
        events.append({
            "ph": "X",
            "pid": 0,
            "tid": 0,
            "cat": s.get("kind", "span"),
            "name": s["name"],
            "ts": round(s["t0_s"] * 1e6, 3),
            "dur": round(max(wall, 0.0) * 1e6, 3),
            "args": args,
        })
    events.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


ATOMIC_TMP_PREFIX = ".scc-tmp-"


def atomic_write(path: str, write_fn, inspect_fn=None) -> None:
    """The one atomic-write primitive every artifact writer shares:
    ``write_fn(tmp_path)`` produces the full content at a unique temp path
    in the destination dir (same filesystem, so ``os.replace`` is atomic),
    the temp file is fsynced, then renamed over the destination. An
    interrupted writer can leave a stale ``.scc-tmp-*`` file but never a
    truncated artifact under a real name.

    ``inspect_fn(tmp_path)``, when given, runs between the write and the
    replace — for work that must see the final bytes BEFORE they land
    under the real name (the artifact store checksums the arrays file
    here and writes its sidecar, preserving meta-before-arrays ordering).
    A raising inspect_fn aborts the write and cleans up the temp."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=ATOMIC_TMP_PREFIX, dir=d)
    os.close(fd)
    try:
        # mkstemp creates 0600; restore the umask-default mode so shared
        # artifact dirs / CI collectors can read the renamed file
        umask = os.umask(0)
        os.umask(umask)
        os.chmod(tmp, 0o666 & ~umask)
        write_fn(tmp)
        if inspect_fn is not None:
            inspect_fn(tmp)
        with open(tmp, "rb") as f:
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_json_atomic(path: str, obj: Any, indent: int = 1) -> None:
    """Atomic JSON export (see :func:`atomic_write`)."""
    def _w(tmp: str) -> None:
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=indent, default=str)

    atomic_write(path, _w)


def write_chrome_trace(path: str, spans: List[Dict[str, Any]],
                       process_name: str = "scconsensus_tpu") -> None:
    write_json_atomic(path, chrome_trace(spans, process_name))
