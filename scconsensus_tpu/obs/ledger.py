"""Evidence ledger: one manifest-indexed store of run records.

Round 7 gave every emitter the ``scc-run-record`` schema but left ~30
loose ``BENCH_*``/``SCALE_*``/``PROFILE_*``/``MESH_*``/``MULTICHIP_*``
JSONs at the repo root with no index and no history: a regression was
caught by a human rereading VERDICT.md. The ledger fixes the storage half
of that (obs.regress computes the verdicts):

  * every record lives under ``evidence/`` as one file, listed in
    ``evidence/MANIFEST.json`` with its run key, headline, per-stage
    synced walls and (when cost attribution ran) per-stage flops — so
    baseline computation reads the manifest, not thirty files;
  * runs are keyed by ``(dataset, backend, config_fp)`` — the config
    fingerprint hashes the workload-identity fields of ``extra``
    (config name, degraded/size-reduced shrinks, shape overrides), so a
    degraded 2k-cell run can never become the baseline of the 26k one;
  * a one-shot upgrader (``python -m scconsensus_tpu.obs.ledger``,
    also ``tools/perf_gate.py --upgrade``) lifts the legacy root files
    into schema-v1 envelopes and relocates them here. Upgrades are
    lossless by construction: the entire original payload is preserved
    verbatim under ``extra["legacy"]`` and :func:`downgrade_legacy`
    inverts the lift exactly (round-trip asserted in tests).

The default location is ``<cwd>/evidence``; ``SCC_EVIDENCE_DIR``
overrides it (the test suite points it at a tmp dir so quick bench runs
stay hermetic).
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.obs.export import (
    SCHEMA_NAME,
    SCHEMA_VERSION,
    check_schema_version,
    validate_run_record,
    write_json_atomic,
)

__all__ = [
    "Ledger",
    "default_evidence_dir",
    "run_key",
    "upgrade_legacy",
    "downgrade_legacy",
    "upgrade_tree",
    "is_transient_artifact",
    "termination_cause",
    "is_partial_record",
    "is_partial_entry",
    "MANIFEST_NAME",
    "LEGACY_PATTERNS",
]

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_SCHEMA = "scc-evidence-manifest"
MANIFEST_VERSION = 1

# Root-level artifact families the one-shot upgrader relocates.
# TUNNEL_LOG.jsonl and BASELINE.json are not run records and stay where
# they are. Two BENCH_* families are EXCLUDED as live working files:
#   * BENCH_CHECKPOINT_* — bench.py overwrites them every run and they are
#     gitignored; indexing one would pin a fresh clone to a file that does
#     not exist (they live under evidence/ now — bench's default
#     checkpoint path — just unindexed);
#   * BENCH_TPU_* — the capture watcher's per-config evidence targets
#     (tpu_capture_watcher.sh `captured()` reads the root path mid-
#     campaign; relocating one would make the watcher re-burn a TPU
#     window re-capturing it).
LEGACY_PATTERNS = (
    "BENCH_*.json",
    "SCALE_*.json",
    "PROFILE_*.json",
    "MESH_*.json",
    "MULTICHIP_*.json",
)
TRANSIENT_PREFIXES = ("BENCH_CHECKPOINT_", "BENCH_TPU_")
# Flight-recorder sidecars (obs.live): the recorder REWRITES these while a
# run is live, and run_sparse_1m anchors them at SCALE_*/PROFILE_* names
# that match LEGACY_PATTERNS — relocating one would index a mid-run
# crash-stamped partial and unlink it out from under the recorder.
TRANSIENT_SUFFIXES = ("_heartbeat.jsonl", "_partial.json")


def is_transient_artifact(name: str) -> bool:
    """Live working files the upgrader must never relocate or index."""
    base = os.path.basename(name)
    return (base.startswith(TRANSIENT_PREFIXES)
            or base.endswith(TRANSIENT_SUFFIXES))


# --------------------------------------------------------------------------
# partial (flight-recorder) records
# --------------------------------------------------------------------------

def termination_cause(rec: Dict[str, Any]) -> Optional[str]:
    """The record's termination cause (obs.live incremental flush), or
    None for records with no termination section (every clean single-shot
    emitter)."""
    term = rec.get("termination")
    return term.get("cause") if isinstance(term, dict) else None


def is_partial_record(rec: Dict[str, Any]) -> bool:
    """True for flight-recorder partials: a termination stamp with any
    cause other than "clean". Partial records are ledger-ingestible (they
    are often the ONLY evidence a dead run left) but must never seed or
    anchor a regression baseline — the walls of the interrupted stage are
    truncated, not measured."""
    cause = termination_cause(rec)
    return cause is not None and cause != "clean"


def is_partial_entry(entry: Dict[str, Any]) -> bool:
    """Manifest-entry twin of :func:`is_partial_record` (the entry carries
    the cause under ``termination``)."""
    cause = entry.get("termination")
    return cause is not None and cause != "clean"

# extra-dict fields that identify the workload (not its outcome): two runs
# agreeing on all of these are comparable, so they share a baseline key.
_KEY_FIELDS = (
    "config",
    "degraded",
    "size_reduced",
    "n_cells",
    "n_genes",
    "n_clusters",
    "n_way",
    "method",
    "mesh",
)


def default_evidence_dir(base: Optional[str] = None) -> str:
    """``SCC_EVIDENCE_DIR`` when set, else ``<base or cwd>/evidence``."""
    override = env_flag("SCC_EVIDENCE_DIR")
    if override:
        return override
    return os.path.join(base or os.getcwd(), "evidence")


def run_key(rec: Dict[str, Any]) -> Dict[str, str]:
    """(dataset, backend, config fingerprint) identity of one run record."""
    from scconsensus_tpu.utils.artifacts import config_fingerprint

    ex = rec.get("extra") or {}
    dataset = str(ex.get("config") or ex.get("dataset") or "unknown")
    backend = str(
        ex.get("platform")
        or (rec.get("run") or {}).get("platform")
        or "unknown"
    )
    ident = {k: ex[k] for k in _KEY_FIELDS if k in ex}
    ident["unit"] = rec.get("unit")
    return {
        "dataset": dataset,
        "backend": backend,
        "config_fp": config_fingerprint(ident),
    }


def stage_walls(rec: Dict[str, Any]) -> Dict[str, float]:
    """Headline wall per stage-kind span, aggregated by name (a stage that
    runs twice — e.g. cold + steady in one tree — sums; baselines compare
    like-for-like because the key fingerprints the workload)."""
    out: Dict[str, float] = {}
    for s in rec.get("spans") or []:
        if not isinstance(s, dict) or s.get("kind") != "stage":
            continue
        wall = s.get("wall_synced_s")
        if wall is None:
            wall = s.get("wall_submitted_s")
        if wall is None:
            continue
        out[s["name"]] = round(out.get(s["name"], 0.0) + float(wall), 6)
    return out


# --------------------------------------------------------------------------
# legacy upgrade (lossless by construction)
# --------------------------------------------------------------------------

def _legacy_headline(d: Dict[str, Any], name: str) -> Dict[str, Any]:
    """Best-effort headline extraction from the known pre-schema shapes:
    driver artifacts ({n, cmd, rc, tail, parsed}), bare bench records,
    SCALE config maps, MESH size tables. Anything unrecognized still
    upgrades (the payload is preserved whole); only the headline degrades
    to nulls."""
    src: Any = d
    if isinstance(d.get("parsed"), dict):  # driver BENCH_r* shape
        src = d["parsed"]
    if not isinstance(src, dict) or "value" not in src:
        for v in (d.get("configs") or {}).values() if isinstance(
                d.get("configs"), dict) else ():
            if isinstance(v, dict) and "value" in v:
                src = v
                break
    metric = src.get("metric") if isinstance(src, dict) else None
    value = src.get("value") if isinstance(src, dict) else None
    unit = src.get("unit") if isinstance(src, dict) else None
    extra = src.get("extra") if isinstance(src, dict) else None
    platform = (extra or {}).get("platform") if isinstance(extra, dict) \
        else None
    return {
        "metric": metric or f"legacy artifact {name}",
        "value": value,
        "unit": unit or "seconds",
        "vs_baseline": src.get("vs_baseline") if isinstance(src, dict)
        else None,
        "platform": platform,
        "config": (extra or {}).get("config") if isinstance(extra, dict)
        else None,
    }


def upgrade_legacy(d: Dict[str, Any], source_name: str,
                   created_unix: Optional[float] = None) -> Dict[str, Any]:
    """Lift a pre-schema artifact into a schema-v1 envelope.

    Lossless: the original payload rides ``extra["legacy"]`` verbatim;
    :func:`downgrade_legacy` returns it unchanged. A record that already
    carries the schema is returned as-is (ValueError on unknown versions,
    same contract as every other ingester)."""
    if check_schema_version(d, source=source_name) != "legacy":
        return d
    head = _legacy_headline(d, source_name)
    run: Dict[str, Any] = {
        "created_unix": round(float(created_unix or time.time()), 3)
    }
    if head["platform"]:
        run["platform"] = head["platform"]
    extra: Dict[str, Any] = {
        "legacy": d,
        "legacy_source": source_name,
        "upgraded": True,
    }
    if head["platform"]:
        extra["platform"] = head["platform"]
    if head["config"]:
        extra["config"] = head["config"]
    return {
        "schema": SCHEMA_NAME,
        "schema_version": SCHEMA_VERSION,
        "metric": head["metric"],
        "value": head["value"],
        "unit": head["unit"],
        "vs_baseline": head["vs_baseline"],
        "run": run,
        "spans": [],
        "device": {},
        "extra": extra,
    }


def downgrade_legacy(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Exact inverse of :func:`upgrade_legacy` for upgraded records."""
    legacy = (rec.get("extra") or {}).get("legacy")
    if legacy is None:
        raise ValueError("record carries no legacy payload to downgrade")
    return legacy


# --------------------------------------------------------------------------
# the ledger
# --------------------------------------------------------------------------

class Ledger:
    """Manifest-indexed run-record store rooted at one directory."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._manifest = self._load_manifest()

    # -- manifest ----------------------------------------------------------
    @property
    def manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    def _load_manifest(self) -> Dict[str, Any]:
        try:
            with open(self.manifest_path) as f:
                m = json.load(f)
        except (OSError, json.JSONDecodeError):
            return {"schema": MANIFEST_SCHEMA, "version": MANIFEST_VERSION,
                    "entries": []}
        if m.get("schema") != MANIFEST_SCHEMA:
            raise ValueError(
                f"{self.manifest_path}: unknown manifest schema "
                f"{m.get('schema')!r}"
            )
        if m.get("version") != MANIFEST_VERSION:
            raise ValueError(
                f"{self.manifest_path}: unsupported manifest version "
                f"{m.get('version')!r} (this tool knows {MANIFEST_VERSION})"
            )
        m.setdefault("entries", [])
        return m

    def _write_manifest(self) -> None:
        self._manifest["entries"].sort(
            key=lambda e: (e.get("created_unix") or 0, e.get("file", ""))
        )
        write_json_atomic(self.manifest_path, self._manifest)

    def entries(self) -> List[Dict[str, Any]]:
        return list(self._manifest["entries"])

    # -- ingest ------------------------------------------------------------
    def ingest(self, rec: Dict[str, Any], name: Optional[str] = None,
               source: str = "native") -> Dict[str, Any]:
        """Validate, write ``evidence/<name>`` and index it. Pre-schema
        payloads must go through :func:`upgrade_legacy` first (hard error
        here — silent auto-upgrades would hide that a *current* emitter
        stopped stamping the schema)."""
        validate_run_record(rec)
        key = run_key(rec)
        created = float((rec.get("run") or {}).get("created_unix") or 0.0)
        if name is None:
            name = (
                f"RUN_{key['dataset']}_{key['backend']}_"
                f"{key['config_fp']}_{int(created)}.json"
            )
        if os.sep in name or name == MANIFEST_NAME:
            raise ValueError(f"invalid evidence entry name {name!r}")
        path = os.path.join(self.root, name)
        n = 1
        while os.path.exists(path) and not self._is_entry(name):
            # never clobber an un-indexed file that happens to share a name
            n += 1
            stem, ext = os.path.splitext(name)
            name = f"{stem}.{n}{ext}"
            path = os.path.join(self.root, name)
        write_json_atomic(path, rec)
        entry: Dict[str, Any] = {
            "file": name,
            "key": key,
            "metric": rec.get("metric"),
            "value": rec.get("value"),
            "unit": rec.get("unit"),
            "vs_baseline": rec.get("vs_baseline"),
            "created_unix": created,
            "schema_version": rec.get("schema_version"),
            "source": source,
            "stage_walls": stage_walls(rec),
        }
        cause = termination_cause(rec)
        if cause is not None:
            # the index says up front whether this run ended cleanly —
            # baseline computation (regress.stage_baselines) reads only
            # the manifest and must skip partials without loading files
            entry["termination"] = cause
        rb = rec.get("robustness")
        if isinstance(rb, dict) and rb:
            # survival summary on the index: a gate/report scanning the
            # manifest can see WHICH runs recovered (and how hard they
            # had to work) without loading every record
            entry["robustness"] = {
                "retries": len(rb.get("retries") or []),
                "degradations": len(rb.get("degradations") or []),
                "faults_injected": len(rb.get("faults_injected") or []),
                "resume_points": len(rb.get("resume_points") or []),
                "recovered": bool(rb.get("recovered")),
            }
            if rb.get("mesh_transitions"):
                # elastic runs additionally index the mesh trail (count
                # + final device count) — absent on mesh-stable runs, so
                # pre-elastic manifest consumers see an unchanged shape
                entry["robustness"]["mesh_transitions"] = len(
                    rb["mesh_transitions"]
                )
                entry["robustness"]["mesh_devices"] = len(
                    rb["mesh_transitions"][-1].get("to_devices") or []
                )
        sv = rec.get("serving")
        if isinstance(sv, dict) and sv:
            # serving latency summary on the index: the perf gate's
            # latency baselines (regress.serving_baselines) read the
            # manifest, not N record files — exactly like stage_walls
            lat = sv.get("latency_ms") or {}
            entry["serving"] = {
                "p50_ms": lat.get("p50"),
                "p99_ms": lat.get("p99"),
                "throughput_rps": sv.get("throughput_rps"),
                "requests": (sv.get("requests") or {}).get("submitted"),
            }
            nrep = (sv.get("fleet") or {}).get("replicas")
            if isinstance(nrep, int) and nrep >= 1:
                # replica count on the index: the perf gate's replica-
                # keyed baselines (p99@rN, throughput@rN) read it —
                # absent means the bare r15 driver (keys as r1)
                entry["serving"]["replicas"] = nrep
        lg = rec.get("loadgen")
        if isinstance(lg, dict) and lg:
            # traffic summary on the index (round 21): the perf gate's
            # per-profile sustained-RPS-at-SLO baselines
            # (regress.loadgen_baselines) read the manifest, not N
            # record files — exactly like stage_walls
            entry["loadgen"] = {
                "profile": lg.get("profile"),
                "arrival": lg.get("arrival"),
                "rps_at_slo": lg.get("rps_at_slo"),
                "achieved_rps": lg.get("achieved_rps"),
                "breaches": len(lg.get("breaches") or []),
                "actuations": len(
                    (lg.get("autoscale") or {}).get("actuations") or []
                ),
            }
        ig = rec.get("integrity")
        if isinstance(ig, dict) and ig:
            # computation-integrity summary on the index (round 18): a
            # gate/report scanning the manifest sees WHICH runs proved
            # their arithmetic (and which caught corruption) without
            # loading every record
            entry["integrity"] = {
                "mode": ig.get("mode"),
                "checks_run": (ig.get("checks") or {}).get("run"),
                "checks_passed": (ig.get("checks") or {}).get("passed"),
                "violations": len(ig.get("violations") or []),
                "mismatches": len(
                    (ig.get("ghost") or {}).get("mismatches") or []
                ),
                "recomputes": (ig.get("ghost") or {}).get("recomputes"),
                "all_checks_passed": bool(ig.get("all_checks_passed")),
            }
        sm = rec.get("streaming")
        if isinstance(sm, dict) and sm:
            # out-of-core summary on the index (round 17): the perf
            # gate's peak-RSS baselines (regress.streaming_baselines)
            # read the manifest, not N record files — like stage_walls
            ch = sm.get("chunks") or {}
            bud = sm.get("budget") or {}
            entry["streaming"] = {
                "chunks_planned": ch.get("planned"),
                "chunks_completed": ch.get("completed"),
                "chunks_resumed": ch.get("resumed"),
                "peak_rss_mb": bud.get("peak_rss_mb"),
                "limit_mb": bud.get("limit_mb"),
                "within_budget": bool(bud.get("within_budget")),
            }
        fp = (rec.get("extra") or {}).get("numeric_fingerprint")
        if isinstance(fp, dict) and fp:
            # every ingested run is fingerprint-stamped on its manifest
            # entry (not just the pinned reference workload), so the gate
            # can flag quality drift on ANY dataset by comparing a
            # candidate against its own key's newest clean entry
            # (regress.history_pins) under the DRIFT_LEDGER ack flow
            entry["numeric_fingerprint"] = {
                k: v for k, v in fp.items() if not k.startswith("_")
            }
        try:
            from scconsensus_tpu.obs.cost import stage_cost_summary

            cost = stage_cost_summary(rec.get("spans") or [])
            if cost:
                entry["stage_cost"] = cost
        except Exception:
            pass
        try:
            from scconsensus_tpu.obs.residency import stage_transfer_bytes

            # per-stage transfer totals ride the index so the perf gate's
            # transfer-byte baselines read the manifest, not N files —
            # exactly like stage_walls. Absent when no audit ran (absence
            # must never read as "zero bytes").
            tb = stage_transfer_bytes(rec)
            if tb:
                entry["stage_transfer_bytes"] = tb
        except Exception:
            pass
        try:
            # per-boundary transfer totals (both directions) ride the
            # index too — regress.boundary_baselines anchors the
            # residency burn-down ledger on these stamps. Prefers the
            # record's own burndown section (validated totals), falls
            # back to the raw residency aggregate for pre-round-22
            # records re-ingested by --reindex.
            bb: Dict[str, int] = {}
            bd = rec.get("residency_burndown")
            if isinstance(bd, dict):
                for b, row in (bd.get("boundaries") or {}).items():
                    if isinstance(row, dict):
                        bb[str(b)] = int(row.get("bytes") or 0)
            else:
                res = rec.get("residency")
                if isinstance(res, dict):
                    for b, row in (res.get("by_boundary") or {}).items():
                        if isinstance(row, dict):
                            bb[str(b)] = int(
                                row.get("to_host_bytes") or 0
                            ) + int(row.get("to_device_bytes") or 0)
            if bb:
                entry["boundary_bytes"] = bb
        except Exception:
            pass
        self._manifest["entries"] = [
            e for e in self._manifest["entries"] if e.get("file") != name
        ]
        self._manifest["entries"].append(entry)
        self._write_manifest()
        return entry

    def _is_entry(self, name: str) -> bool:
        return any(e.get("file") == name for e in self._manifest["entries"])

    # -- reads -------------------------------------------------------------
    def load(self, name: str) -> Dict[str, Any]:
        with open(os.path.join(self.root, name)) as f:
            return json.load(f)

    def history(self, key: Dict[str, str],
                exclude_files: Iterable[str] = ()) -> List[Dict[str, Any]]:
        """Manifest entries for one run key, oldest first."""
        skip = set(exclude_files)
        return [
            e for e in self._manifest["entries"]
            if e.get("key") == key and e.get("file") not in skip
        ]


# --------------------------------------------------------------------------
# one-shot tree upgrade (the relocation)
# --------------------------------------------------------------------------

def upgrade_tree(root: str, dest: Optional[str] = None,
                 keep_root: bool = False) -> Tuple[List[str], List[str]]:
    """Lift every legacy-pattern artifact under ``root`` into ``dest``
    (default ``<root>/evidence``) and index it; root files are removed
    after a successful relocation unless ``keep_root``. Returns
    (relocated names, skipped names). Unreadable files are skipped — a
    mid-write artifact must not abort the whole migration."""
    dest = dest or os.path.join(root, "evidence")
    ledger = Ledger(dest)
    done: List[str] = []
    skipped: List[str] = []
    for pat in LEGACY_PATTERNS:
        for path in sorted(glob.glob(os.path.join(root, pat))):
            if os.path.abspath(os.path.dirname(path)) == os.path.abspath(
                    dest):
                continue
            name = os.path.basename(path)
            if is_transient_artifact(name):
                continue  # live checkpoint/capture target, never indexed
            try:
                with open(path) as f:
                    d = json.load(f)
            except (OSError, json.JSONDecodeError):
                skipped.append(name)
                continue
            if not isinstance(d, dict):
                skipped.append(name)
                continue
            source = "legacy-upgrade"
            if check_schema_version(d, source=name) != "legacy":
                source = "native"
            rec = upgrade_legacy(d, name,
                                 created_unix=os.path.getmtime(path))
            ledger.ingest(rec, name=name, source=source)
            if not keep_root:
                os.unlink(path)
            done.append(name)
    return done, skipped


def main(argv: Optional[List[str]] = None) -> int:
    """One-shot upgrader CLI: ``python -m scconsensus_tpu.obs.ledger
    [--root DIR] [--dest DIR] [--keep-root]``."""
    import argparse

    ap = argparse.ArgumentParser(description=upgrade_tree.__doc__)
    ap.add_argument("--root", default=os.getcwd())
    ap.add_argument("--dest", default=None)
    ap.add_argument("--keep-root", action="store_true")
    args = ap.parse_args(argv)
    done, skipped = upgrade_tree(args.root, args.dest,
                                 keep_root=args.keep_root)
    for name in done:
        print(f"relocated {name}")
    for name in skipped:
        print(f"SKIPPED (unreadable) {name}")
    print(f"{len(done)} artifact(s) relocated, {len(skipped)} skipped")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
