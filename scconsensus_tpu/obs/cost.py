"""XLA cost attribution: FLOPs/bytes from ``lower().compile()`` on spans.

The MFU probes in ``bench.py`` price two hand-picked kernels at synthetic
shapes; nothing prices the kernels a *real* run actually dispatched, so the
ROADMAP's "as fast as the hardware allows" has no denominator on the
evidence record. This module attaches XLA's own cost model to spans at
trace time: :func:`attach_cost` asks a jitted callable for
``lower(*args).compile().cost_analysis()`` at the call's exact shapes and
accumulates flops / bytes-accessed / transcendentals onto the ambient (or
given) span, so every run record can report achieved vs. cost-model
throughput per stage and a regression can be expressed as an efficiency
loss rather than bare seconds.

Cost is an *estimate* (XLA's static model; fusion means bytes especially
are approximate) and collection is best-effort: any failure records
nothing. The AOT lower+compile behind the estimate is paid once per
(callable, abstract signature) — results are memoized process-wide, and
the backend compile itself hits the persistent XLA compile cache — but it
is still real work, so everything is gated behind ``SCC_OBS_COST`` (off by
default; ``bench.py`` turns it on for its workers).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from scconsensus_tpu.config import env_flag

__all__ = [
    "cost_enabled",
    "cost_analysis_of",
    "attach_cost",
    "stage_cost_summary",
]

# (callable key, abstract signature) -> {"flops": ..., ...} | None
_COST_CACHE: Dict[Any, Optional[Dict[str, float]]] = {}

# cost_analysis key -> run-record field (version-tolerant: the bytes key
# has been both "bytes accessed" and "bytes_accessed" across jaxlibs;
# the installed jaxlib 0.4.x spells it "bytes accessed" with per-operand
# variants like "bytes accessed0{}" / "bytes accessedout{}" alongside,
# which must NOT sum into the total — exact-key matches only here)
_FIELDS = (
    ("flops", "flops"),
    ("bytes accessed", "bytes_accessed"),
    ("bytes_accessed", "bytes_accessed"),
    ("bytes-accessed", "bytes_accessed"),
    ("transcendentals", "transcendentals"),
)

# Normalized-spelling fallback for spellings _FIELDS hasn't seen yet: a
# jax upgrade that renames "bytes accessed" to, say, "Bytes_Accessed"
# must degrade to this mapping, not silently zero the cost section.
# Keys normalize by lowercasing and collapsing non-alphanumerics to a
# single underscore; per-operand variants ("bytes accessed0{}") carry
# digits/braces and deliberately do not normalize onto a total field.
_NORM_FIELDS = {
    "flops": "flops",
    "bytes_accessed": "bytes_accessed",
    "transcendentals": "transcendentals",
}


def _norm_key(k: str) -> str:
    out: List[str] = []
    for ch in str(k).strip().lower():
        if ch.isalnum():
            out.append(ch)
        elif not out or out[-1] != "_":
            out.append("_")
    return "".join(out).strip("_")


def cost_enabled() -> bool:
    return bool(env_flag("SCC_OBS_COST"))


def _abstract(x: Any) -> Any:
    """Hashable signature element: arrays by shape/dtype, scalars by value."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(int(s) for s in shape), str(dtype))
    if isinstance(x, (int, float, bool, str, type(None))):
        return ("val", x)
    return ("repr", repr(x))


def cost_analysis_of(jitted, *args, **kwargs) -> Optional[Dict[str, float]]:
    """XLA cost estimate for ``jitted(*args, **kwargs)``; None when the
    backend/jit build exposes no cost analysis. Memoized per abstract
    signature, so only the first call at a shape pays the AOT compile."""
    try:
        key = (
            getattr(jitted, "__wrapped__", None) or id(jitted),
            tuple(_abstract(a) for a in args),
            tuple(sorted((k, _abstract(v)) for k, v in kwargs.items())),
        )
        hash(key)
    except TypeError:
        key = None
    if key is not None and key in _COST_CACHE:
        return _COST_CACHE[key]
    out: Optional[Dict[str, float]] = None
    try:
        ca = jitted.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            out = {}
            for src, dst in _FIELDS:
                v = ca.get(src)
                if v is not None and dst not in out:
                    out[dst] = float(v)
            for src, v in ca.items():
                dst = _NORM_FIELDS.get(_norm_key(src))
                if dst is not None and dst not in out and v is not None:
                    out[dst] = float(v)
            out = out or None
    except Exception:
        out = None
    if key is not None:
        _COST_CACHE[key] = out
    return out


def attach_cost(span, jitted, *args, **kwargs) -> Optional[Dict[str, float]]:
    """Accumulate the kernel's cost estimate onto ``span.attrs["xla_cost"]``
    (ambient span when ``span`` is None). No-op unless SCC_OBS_COST is on —
    instrumentation sites call this unconditionally, like obs.trace.span."""
    if not cost_enabled():
        return None
    if span is None:
        from scconsensus_tpu.obs.trace import current_span

        span = current_span()
        if span is None:
            return None
    ca = cost_analysis_of(jitted, *args, **kwargs)
    if not ca:
        return None
    cur = span.attrs.setdefault(
        "xla_cost", {"flops": 0.0, "bytes_accessed": 0.0,
                     "transcendentals": 0.0, "kernels": 0},
    )
    for k, v in ca.items():
        cur[k] = cur.get(k, 0.0) + v
    cur["kernels"] += 1
    return ca


def _span_cost(s: Dict[str, Any]) -> Optional[Dict[str, float]]:
    attrs = s.get("attrs") or {}
    c = attrs.get("xla_cost")
    return c if isinstance(c, dict) else None


def stage_cost_summary(spans: List[Dict[str, Any]]) -> Dict[str, Dict]:
    """Per-stage achieved-vs-cost-model throughput from a span-record tree.

    For every stage-kind span, sums ``xla_cost`` over the span itself and
    all descendants, divides by the stage's headline wall (synced when
    recorded) and aggregates repeated stages by name. Returns
    ``{stage: {flops, bytes_accessed, transcendentals, kernels, wall_s,
    achieved_gflops, achieved_gbps}}`` — stages with no costed kernels are
    omitted, so an empty dict means "no attribution ran", never zeros.
    """
    by_id = {s.get("span_id"): s for s in spans if isinstance(s, dict)}
    children: Dict[Any, List[Dict[str, Any]]] = {}
    for s in by_id.values():
        children.setdefault(s.get("parent_id"), []).append(s)

    def _subtree_cost(s) -> Dict[str, float]:
        tot = {"flops": 0.0, "bytes_accessed": 0.0,
               "transcendentals": 0.0, "kernels": 0}
        stack = [s]
        while stack:
            cur = stack.pop()
            c = _span_cost(cur)
            if c:
                for k in tot:
                    tot[k] += c.get(k, 0)
            stack.extend(children.get(cur.get("span_id"), []))
        return tot

    out: Dict[str, Dict] = {}
    for s in by_id.values():
        if s.get("kind") != "stage":
            continue
        cost = _subtree_cost(s)
        if not cost["kernels"]:
            continue
        wall = s.get("wall_synced_s")
        if wall is None:
            wall = s.get("wall_submitted_s") or 0.0
        agg = out.setdefault(
            s["name"],
            {"flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0,
             "kernels": 0, "wall_s": 0.0},
        )
        for k in ("flops", "bytes_accessed", "transcendentals", "kernels"):
            agg[k] += cost[k]
        agg["wall_s"] += float(wall)
    for name, agg in out.items():
        w = agg["wall_s"]
        agg["wall_s"] = round(w, 4)
        if w > 0:
            agg["achieved_gflops"] = round(agg["flops"] / w / 1e9, 3)
            agg["achieved_gbps"] = round(agg["bytes_accessed"] / w / 1e9, 3)
    return out
