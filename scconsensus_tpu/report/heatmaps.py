"""Contingency heatmap report.

Matplotlib reproduction of the reference's ComplexHeatmap rendering
(R/plotContingencyTable.R:29-67): per-cell counts drawn in each grid cell, a
5-stop cyan→green→yellow→orange→red ramp symmetric around zero, labels_2 on
columns (top), labels_1 on rows (left).
"""

from __future__ import annotations

import numpy as np

__all__ = ["plot_contingency_heatmap"]

# The reference's 5-stop ramp over [-max|x|, +max|x|] (plotContingencyTable.R:31-45).
_RAMP_STOPS = ["#00FFFF", "#7FFF7F", "#FFFF00", "#FF7F00", "#FF0000"]


def _ramp_cmap():
    from matplotlib.colors import LinearSegmentedColormap

    return LinearSegmentedColormap.from_list("scc_ctg", _RAMP_STOPS)


def plot_contingency_heatmap(ctg, filename: str, show_counts: bool = True) -> None:
    """Render a ContingencyResult to ``filename`` (format from extension)."""
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    mat = np.asarray(ctg.matrix, dtype=np.float64)
    vmax = max(np.abs(mat).max(), 1.0)
    k1, k2 = mat.shape
    fig_w = max(6.0, 0.6 * k2 + 3)
    fig_h = max(6.0, 0.6 * k1 + 3)
    fig, ax = plt.subplots(figsize=(fig_w, fig_h))
    ax.imshow(mat, cmap=_ramp_cmap(), vmin=-vmax, vmax=vmax, aspect="auto")
    ax.set_xticks(range(k2), labels=[str(c) for c in ctg.col_labels], fontweight="bold")
    ax.set_yticks(range(k1), labels=[str(r) for r in ctg.row_labels], fontweight="bold")
    ax.xaxis.tick_top()
    ax.set_title("Cluster labels 2", fontsize=16, fontweight="bold", pad=30)
    ax.set_ylabel("Cluster Labels 1", fontsize=16, fontweight="bold")
    if show_counts:
        for i in range(k1):
            for j in range(k2):
                ax.text(j, i, f"{int(mat[i, j])}", ha="center", va="center",
                        fontweight="bold", color="black")
    fig.tight_layout()
    fig.savefig(filename)
    plt.close(fig)
