"""DE-gene heatmap report (``cellTypeDEPlot`` equivalent).

Matplotlib reproduction of R/cellTypeDEPlot.R:17-293: genes × cells expression
heatmap of the DE-gene union with columns in dendrogram order, a column
dendrogram panel, stacked annotations (per-consensus-cluster one-hot
black/white bars, one color bar per deepSplit cut, a NODG barplot), and the
reference's three ramp schemes with their value-range semantics. The
reference's O(N·(K+D)) element-naming loop (:116-136) is replaced by
vectorized index mapping; its 50×50-inch rasterized PDF (:250-258) by
aggregation-aware column binning (each rendered column is the mean /
membership-fraction / majority-color of a contiguous run of dendrogram-
ordered cells, so small clusters shade bins instead of vanishing).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["cell_type_de_plot", "COLOR_SCHEMES", "SCHEME_RANGES"]

# The reference's circlize::colorRamp2 stop colors, verbatim
# (R/cellTypeDEPlot.R:174-222): "blue" and "green" share one 9-stop
# blue→cyan→yellow→red rainbow and differ only in the value range the ramp
# spans; "violet" is a 5-stop lightblue→white→red→darkred ramp.
_RAINBOW_9 = [
    "#00007F", "blue", "#007FFF", "cyan", "#7FFF7F",
    "yellow", "#FF7F00", "red", "#7F0000",
]
COLOR_SCHEMES = {
    "blue": list(_RAINBOW_9),
    "green": list(_RAINBOW_9),
    "violet": ["#7777FF", "white", "red", "#7F0000", "#2F0000"],
}


def SCHEME_RANGES(scheme: str, data: np.ndarray):
    """(vmin, vmax) per the reference's seq() endpoints for each scheme:
    blue = [min, max] of the data (:179); green = ±max|data| (:197);
    violet = [min|data|, max|data|] (:215)."""
    if scheme == "blue":
        return float(data.min()), float(data.max())
    if scheme == "green":
        a = float(np.abs(data).max())
        return -a, a
    if scheme == "violet":
        ab = np.abs(data)
        return float(ab.min()), float(ab.max())
    raise ValueError(f"col_scheme must be one of {sorted(COLOR_SCHEMES)}")


_R_COLOR_FALLBACKS = {
    "grey60": "#999999",
    "lightcyan1": "#E0FFFF",
    "sienna3": "#CD6839",
    "skyblue3": "#6CA6CD",
    "plum1": "#FFBBFF",
    "plum2": "#EEAEEE",
    "orangered4": "#8B2500",
    "mediumpurple3": "#8968CD",
    "lightsteelblue1": "#CAE1FF",
    "darkorange2": "#EE7600",
    "brown4": "#8B2323",
    "bisque4": "#8B7D6B",
    "thistle2": "#EED2EE",
}


def _to_mpl_color(name: str):
    from matplotlib.colors import to_rgba

    base = name.split(".")[0]  # cycled palette suffix
    if base in _R_COLOR_FALLBACKS:
        return to_rgba(_R_COLOR_FALLBACKS[base])
    try:
        return to_rgba(base)
    except ValueError:
        return to_rgba("grey")


def _scipy_linkage(tree) -> np.ndarray:
    """Convert an R-convention HClustTree to a scipy linkage matrix
    (leaves 0..n-1, merge row i becomes cluster n+i, 4th column = size)."""
    n = tree.n_leaves
    z = np.zeros((n - 1, 4))
    sizes = np.zeros(n - 1)
    for i in range(n - 1):
        s = 0.0
        for c, v in enumerate(tree.merge[i]):
            if v < 0:
                z[i, c] = -v - 1
                s += 1.0
            else:
                z[i, c] = n + v - 1
                s += sizes[v - 1]
        z[i, 2] = tree.height[i]
        z[i, 3] = s
        sizes[i] = s
    return z


def _resolve_filename(filename: str) -> str:
    """The reference writes paste0(filename, ".pdf") (:256-258): a name
    without an extension gets ".pdf"; explicit extensions are respected."""
    root, ext = os.path.splitext(filename)
    if ext.lower() in (".pdf", ".png", ".svg", ".jpg", ".jpeg"):
        return filename
    return filename + ".pdf"


def cell_type_de_plot(
    data_matrix: np.ndarray,
    nodg: Optional[np.ndarray] = None,
    cell_tree=None,
    cluster_labels: Sequence[str] = (),
    dynamic_colors_list: Optional[Dict[str, np.ndarray]] = None,
    gene_labels: Optional[Sequence[str]] = None,
    col_scheme: str = "green",
    filename: str = "DE_Heatmap",
    max_cells_rendered: int = 4000,
    cluster_genes: bool = True,
    gene_groups: Optional[Sequence[str]] = None,
) -> str:
    """Render the DE heatmap report. Returns the written file path.

    data_matrix: (|U|, N) expression of the DE-gene union;
    nodg: per-cell detected-gene counts; None recomputes them from
    ``data_matrix > 0`` (the reference's fallback, R/cellTypeDEPlot.R:31-36);
    cell_tree: HClustTree whose ``order`` sets the column order (its
    dendrogram is drawn above the heatmap, :229-239);
    dynamic_colors_list: {"deepsplit: k": color-name per cell};
    col_scheme: 'green' (default, :23) | 'blue' | 'violet';
    filename: extension-less names get ".pdf" appended (:256);
    cluster_genes: order rows by a Ward dendrogram over genes (the
    reference Heatmap's row clustering, :230);
    gene_groups: optional per-gene group names rendered as a row-annotation
    color bar (the reference's geneLabels annotation, :260-282).

    Past ``max_cells_rendered``, columns are binned (means / membership
    fractions / majority colors over contiguous dendrogram-ordered runs)
    rather than subsampled, so no cluster can disappear from the bars.
    """
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt
    from matplotlib.colors import LinearSegmentedColormap

    if col_scheme not in COLOR_SCHEMES:
        raise ValueError(f"col_scheme must be one of {sorted(COLOR_SCHEMES)}")
    if cell_tree is None:
        raise ValueError("cell_tree is required (sets the column order)")
    dynamic_colors_list = dynamic_colors_list or {}
    data_matrix = np.asarray(data_matrix)
    if nodg is None:
        nodg = (data_matrix > 0).sum(axis=0)

    order = np.asarray(cell_tree.order)
    n = order.size
    labels = np.asarray(cluster_labels).astype(str)
    if labels.size != n:
        raise ValueError(
            f"cluster_labels length {labels.size} != n_cells {n}"
        )
    n_bins = min(n, max_cells_rendered)
    edges = np.linspace(0, n, n_bins + 1).astype(int)
    counts = np.diff(edges).astype(float)
    # bin id of each ORIGINAL column (contiguous runs in dendrogram order);
    # binning via a sparse aggregation matmul / bincounts avoids ever
    # materializing a reordered copy of the (|U|, N) matrix.
    col_bin = np.empty(n, np.int64)
    col_bin[order] = np.repeat(np.arange(n_bins), np.diff(edges))

    from scipy import sparse as _sp

    agg = _sp.csr_matrix(
        ((1.0 / counts[col_bin]).astype(np.float32),
         (np.arange(n), col_bin)),
        shape=(n, n_bins),
    )
    mat = np.asarray((agg.T @ data_matrix.T).T)  # (|U|, n_bins) bin means
    nodg_b = np.bincount(col_bin, weights=np.asarray(nodg, float),
                         minlength=n_bins) / counts

    gene_order = np.arange(mat.shape[0])
    if cluster_genes and mat.shape[0] > 2:
        from scconsensus_tpu.ops.linkage import ward_linkage

        gene_order = np.asarray(ward_linkage(mat).order)
    mat = mat[gene_order]
    if gene_labels is not None:
        gene_labels = np.asarray(gene_labels)[gene_order]
    if gene_groups is not None:
        gene_groups = np.asarray(gene_groups).astype(str)[gene_order]

    uniq_clusters = sorted(set(labels.tolist()))
    n_k = len(uniq_clusters)
    n_ds = len(dynamic_colors_list)

    heights = [1.6, 1.2] + [0.25] * n_k + [0.4] * n_ds + [8.0]
    fig_h = min(6 + 0.25 * n_k + 0.4 * n_ds + 0.12 * mat.shape[0], 60)
    fig, axes = plt.subplots(
        len(heights), 1, figsize=(16, fig_h),
        gridspec_kw={"height_ratios": heights, "hspace": 0.05},
    )

    ax = axes[0]  # column dendrogram (reference :229-239, top side)
    try:
        from scipy.cluster.hierarchy import dendrogram

        z = _scipy_linkage(cell_tree)
        if n > n_bins:
            # collapse to ~bin resolution so leaf spacing tracks the binned
            # columns (the reference rasterizes all N instead)
            dendrogram(z, ax=ax, truncate_mode="lastp", p=n_bins,
                       no_labels=True, color_threshold=0.0,
                       above_threshold_color="black", show_contracted=False)
        else:
            dendrogram(z, ax=ax, no_labels=True, color_threshold=0.0,
                       above_threshold_color="black")
        ax.set_ylabel("tree", fontsize=8)
        ax.set_xticks([])
        for side in ("top", "right", "bottom"):
            ax.spines[side].set_visible(False)
    except Exception:  # dendrogram drawing must never kill the report
        ax.set_axis_off()

    ax = axes[1]  # NODG barplot (reference :153-166)
    ax.bar(np.arange(n_bins), nodg_b, width=1.0, color="#777777")
    ax.set_xlim(-0.5, n_bins - 0.5)
    ax.set_ylabel("NODG", fontsize=8)
    ax.yaxis.set_label_position("left")
    ax.yaxis.tick_right()  # axis_param side = "right" (:160)
    ax.tick_params(labelbottom=False, bottom=False)

    for i, cl in enumerate(uniq_clusters):  # one-hot bars (:53-95)
        ax = axes[2 + i]
        frac = np.bincount(col_bin, weights=(labels == cl).astype(float),
                           minlength=n_bins) / counts
        ax.imshow(frac[None, :], aspect="auto", cmap="binary", vmin=0, vmax=1,
                  interpolation="nearest")
        ax.set_ylabel(cl, rotation=0, ha="right", va="center", fontsize=7)
        ax.set_xticks([]); ax.set_yticks([])

    for j, (key, colors) in enumerate(dynamic_colors_list.items()):  # (:144-147)
        ax = axes[2 + n_k + j]
        uc, inv = np.unique(np.asarray(colors).astype(str), return_inverse=True)
        per_bin = np.bincount(
            col_bin * uc.size + inv, minlength=n_bins * uc.size
        ).reshape(n_bins, uc.size)
        majority = uc[per_bin.argmax(axis=1)]
        rgba = np.array([_to_mpl_color(c) for c in majority])
        ax.imshow(rgba[None, :, :], aspect="auto", interpolation="nearest")
        ax.set_ylabel(key, rotation=0, ha="right", va="center", fontsize=7)
        ax.set_xticks([]); ax.set_yticks([])

    ax = axes[-1]  # main heatmap, scheme ranges per the reference
    vmin, vmax = SCHEME_RANGES(col_scheme, data_matrix)
    if vmax <= vmin:
        vmax = vmin + 1e-6
    cmap = LinearSegmentedColormap.from_list(
        f"scc_{col_scheme}", COLOR_SCHEMES[col_scheme]
    )
    ax.imshow(mat, aspect="auto", cmap=cmap, vmin=vmin, vmax=vmax,
              interpolation="nearest")
    ax.set_xticks([])
    if gene_labels is not None and len(gene_labels) <= 120:
        ax.set_yticks(range(len(gene_labels)), labels=list(gene_labels), fontsize=5)
    else:
        ax.set_yticks([])
    ax.set_ylabel(f"{mat.shape[0]} DE genes", fontsize=9)

    if gene_groups is not None:  # row annotation (:260-282)
        from scconsensus_tpu.ops.colors import labels_to_colors

        uniq = sorted(set(gene_groups.tolist()))
        group_idx = {g: i + 1 for i, g in enumerate(uniq)}
        group_colors = labels_to_colors(
            np.array([group_idx[g] for g in gene_groups])
        )
        rgba = np.array([_to_mpl_color(c) for c in group_colors])
        inset = ax.inset_axes([1.005, 0.0, 0.015, 1.0])
        inset.imshow(rgba[:, None, :], aspect="auto", interpolation="nearest")
        inset.set_xticks([])
        inset.set_yticks([])
        inset.set_title("groups", fontsize=6)

    fig.suptitle("DE gene expression (columns in dendrogram order)", fontsize=12)
    out = _resolve_filename(filename)
    fig.savefig(out, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return out
