"""DE-gene heatmap report (``cellTypeDEPlot`` equivalent).

Matplotlib reproduction of R/cellTypeDEPlot.R:17-293: genes × cells expression
heatmap of the DE-gene union with columns in dendrogram order, stacked
annotations (per-consensus-cluster one-hot black/white bars, one color bar per
deepSplit cut, a NODG barplot), and the reference's three ramp schemes
(blue / green / violet). The reference's O(N·(K+D)) element-naming loop
(:116-136) is replaced by vectorized index mapping.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["cell_type_de_plot", "COLOR_SCHEMES"]

# circlize::colorRamp2 stop sets (R/cellTypeDEPlot.R:173-222).
COLOR_SCHEMES = {
    "blue": ["#FFFFFF", "#BDD7E7", "#6BAED6", "#3182BD", "#08519C"],
    "green": ["#FFFFFF", "#BAE4B3", "#74C476", "#31A354", "#006D2C"],
    "violet": ["#FFFFFF", "#CBC9E2", "#9E9AC8", "#756BB1", "#54278F"],
}

_R_COLOR_FALLBACKS = {
    "grey60": "#999999",
    "lightcyan1": "#E0FFFF",
    "sienna3": "#CD6839",
    "skyblue3": "#6CA6CD",
    "plum1": "#FFBBFF",
    "plum2": "#EEAEEE",
    "orangered4": "#8B2500",
    "mediumpurple3": "#8968CD",
    "lightsteelblue1": "#CAE1FF",
    "darkorange2": "#EE7600",
    "brown4": "#8B2323",
    "bisque4": "#8B7D6B",
    "thistle2": "#EED2EE",
}


def _to_mpl_color(name: str):
    from matplotlib.colors import to_rgba

    base = name.split(".")[0]  # cycled palette suffix
    if base in _R_COLOR_FALLBACKS:
        return to_rgba(_R_COLOR_FALLBACKS[base])
    try:
        return to_rgba(base)
    except ValueError:
        return to_rgba("grey")


def cell_type_de_plot(
    data_matrix: np.ndarray,
    nodg: np.ndarray,
    cell_tree,
    cluster_labels: Sequence[str],
    dynamic_colors_list: Dict[str, np.ndarray],
    gene_labels: Optional[Sequence[str]] = None,
    col_scheme: str = "violet",
    filename: str = "DE_Heatmap.png",
    max_cells_rendered: int = 4000,
    cluster_genes: bool = True,
    gene_groups: Optional[Sequence[str]] = None,
) -> None:
    """Render the DE heatmap report.

    data_matrix: (|U|, N) expression of the DE-gene union;
    cell_tree: HClustTree whose ``order`` sets the column order;
    dynamic_colors_list: {"deepsplit: k": color-name per cell};
    cluster_genes: order rows by a Ward dendrogram over genes (the
    reference Heatmap's row clustering, R/cellTypeDEPlot.R:225-253);
    gene_groups: optional per-gene group names rendered as a row-annotation
    color bar (the reference's geneLabels annotation, :260-282).

    Columns are downsampled (in dendrogram order) past ``max_cells_rendered``
    — the reference rasterizes a 50×50-inch PDF instead (:250-258).
    """
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt
    from matplotlib.colors import LinearSegmentedColormap

    if col_scheme not in COLOR_SCHEMES:
        raise ValueError(f"col_scheme must be one of {sorted(COLOR_SCHEMES)}")
    order = np.asarray(cell_tree.order)
    n = order.size
    if n > max_cells_rendered:
        sel = order[np.linspace(0, n - 1, max_cells_rendered).astype(int)]
    else:
        sel = order
    mat = np.asarray(data_matrix)[:, sel]
    labels = np.asarray(cluster_labels).astype(str)[sel]
    nodg_o = np.asarray(nodg)[sel]

    gene_order = np.arange(mat.shape[0])
    if cluster_genes and mat.shape[0] > 2:
        from scconsensus_tpu.ops.linkage import ward_linkage

        gene_order = np.asarray(ward_linkage(mat).order)
    mat = mat[gene_order]
    if gene_labels is not None:
        gene_labels = np.asarray(gene_labels)[gene_order]
    if gene_groups is not None:
        gene_groups = np.asarray(gene_groups).astype(str)[gene_order]

    uniq_clusters = sorted(set(labels.tolist()))
    n_k = len(uniq_clusters)
    n_ds = len(dynamic_colors_list)

    heights = [1.2] + [0.25] * n_k + [0.4] * n_ds + [8.0]
    fig_h = min(4 + 0.25 * n_k + 0.4 * n_ds + 0.12 * mat.shape[0], 60)
    fig, axes = plt.subplots(
        len(heights), 1, figsize=(16, fig_h),
        gridspec_kw={"height_ratios": heights, "hspace": 0.05},
    )

    ax = axes[0]  # NODG barplot (reference :153-166)
    ax.bar(np.arange(sel.size), nodg_o, width=1.0, color="#444444")
    ax.set_xlim(-0.5, sel.size - 0.5)
    ax.set_ylabel("NODG", fontsize=8)
    ax.tick_params(labelbottom=False, bottom=False)

    for i, cl in enumerate(uniq_clusters):  # one-hot bars (:53-95)
        ax = axes[1 + i]
        member = (labels == cl).astype(float)[None, :]
        ax.imshow(member, aspect="auto", cmap="binary", vmin=0, vmax=1,
                  interpolation="nearest")
        ax.set_ylabel(cl, rotation=0, ha="right", va="center", fontsize=7)
        ax.set_xticks([]); ax.set_yticks([])

    for j, (key, colors) in enumerate(dynamic_colors_list.items()):  # (:144-147)
        ax = axes[1 + n_k + j]
        rgba = np.array([_to_mpl_color(c) for c in np.asarray(colors)[sel]])
        ax.imshow(rgba[None, :, :], aspect="auto", interpolation="nearest")
        ax.set_ylabel(key, rotation=0, ha="right", va="center", fontsize=7)
        ax.set_xticks([]); ax.set_yticks([])

    ax = axes[-1]  # main heatmap
    vmax = np.percentile(mat, 99.0) if mat.size else 1.0
    cmap = LinearSegmentedColormap.from_list(
        f"scc_{col_scheme}", COLOR_SCHEMES[col_scheme]
    )
    ax.imshow(mat, aspect="auto", cmap=cmap, vmin=0, vmax=max(vmax, 1e-6),
              interpolation="nearest")
    ax.set_xticks([])
    if gene_labels is not None and len(gene_labels) <= 120:
        ax.set_yticks(range(len(gene_labels)), labels=list(gene_labels), fontsize=5)
    else:
        ax.set_yticks([])
    ax.set_ylabel(f"{mat.shape[0]} DE genes", fontsize=9)

    if gene_groups is not None:  # row annotation (:260-282)
        import matplotlib as mpl

        uniq = sorted(set(gene_groups.tolist()))
        palette = mpl.colormaps["tab20"].resampled(max(len(uniq), 1))
        group_idx = {g: i for i, g in enumerate(uniq)}
        rgba = np.array([palette(group_idx[g]) for g in gene_groups])
        inset = ax.inset_axes([1.005, 0.0, 0.015, 1.0])
        inset.imshow(rgba[:, None, :], aspect="auto", interpolation="nearest")
        inset.set_xticks([])
        inset.set_yticks([])
        inset.set_title("groups", fontsize=6)

    fig.suptitle("DE gene expression (columns in dendrogram order)", fontsize=12)
    fig.savefig(filename, dpi=120, bbox_inches="tight")
    plt.close(fig)
