from scconsensus_tpu.report.heatmaps import plot_contingency_heatmap

__all__ = ["plot_contingency_heatmap"]


def __getattr__(name):
    if name in ("cell_type_de_plot",):
        from scconsensus_tpu.report import de_heatmap

        return getattr(de_heatmap, name)
    raise AttributeError(name)
