"""Version-tolerant jax API aliases.

The codebase targets the promoted `jax.shard_map` (jax ≥ 0.5); this
container ships jax 0.4.37 where it still lives in
`jax.experimental.shard_map`. One alias point instead of nine guarded
call sites — same spirit as the xla_bootstrap flag probe: the installed
runtime decides, the code stays single-form.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised only on old jax
    from jax.experimental.shard_map import shard_map  # type: ignore

__all__ = ["shard_map"]
