"""Virtual-CPU-mesh XLA flag bootstrap (jax-free: must be importable and
applied BEFORE the first ``import jax`` side effects).

Shared by tests/conftest.py and __graft_entry__.dryrun_multichip so the
workaround set cannot drift between the two bootstrap paths.
"""

from __future__ import annotations

import json
import os
import tempfile

# N virtual devices on few physical cores: XLA's default 40 s collective
# rendezvous terminate-timeout hard-aborts oversubscribed runs (observed at
# a 4000-cell mesh refine on 1 core); real multi-chip has a core per device
# and never hits this.
_TIMEOUT_FLAGS = (
    "xla_cpu_collective_timeout_seconds",
    "xla_cpu_collective_call_terminate_timeout_seconds",
)


def _jaxlib_xla_binary() -> str | None:
    """Path of jaxlib's xla_extension shared object, without importing jax
    (this bootstrap must run before the first jax import)."""
    import importlib.util

    spec = importlib.util.find_spec("jaxlib")
    if spec is None or not spec.submodule_search_locations:
        return None
    for loc in spec.submodule_search_locations:
        for name in ("xla_extension.so", "xla_extension.pyd"):
            p = os.path.join(loc, name)
            if os.path.exists(p):
                return p
    return None


def _supported_flags(candidates: tuple) -> dict:
    """Which candidate XLA flags this jaxlib knows. An UNKNOWN flag in
    XLA_FLAGS is a hard process abort at first backend init
    (parse_flags_from_env.cc), so each flag is only ever added after its
    name is found in the xla_extension binary. Results cache per jaxlib
    version (the multihost tests respawn interpreters; a ~2 s binary scan
    per process would dominate small suites)."""
    try:
        import importlib.metadata as md

        ver = md.version("jaxlib")
    except Exception:
        ver = "unknown"
    cache = os.path.join(
        tempfile.gettempdir(), f"scc_xla_flag_probe_{ver}.json"
    )
    try:
        with open(cache) as f:
            got = json.load(f)
        if set(got) >= set(candidates):
            return got
    except (OSError, ValueError):
        pass
    binary = _jaxlib_xla_binary()
    if binary is None:
        # can't verify: adding is fatal if wrong, omitting only loses the
        # raised rendezvous timeout — omit, and do NOT cache (a transient
        # resolution failure must not permanently disable the flags for
        # this jaxlib version)
        return {f: False for f in candidates}
    needles = {f: f.encode() for f in candidates}
    sup = {f: False for f in candidates}
    try:
        with open(binary, "rb") as fh:
            while chunk := fh.read(1 << 24):
                for f, n in needles.items():
                    if not sup[f] and n in chunk:
                        sup[f] = True
                # a short read is the last chunk: stop — seeking back
                # into it would re-read the same tail forever
                if all(sup.values()) or len(chunk) < (1 << 24):
                    break
                # overlap guard: a needle split across chunk boundaries
                fh.seek(fh.tell() - 64)
    except OSError:
        # transient read failure (e.g. the wheel being replaced under us):
        # omit the flags this run but do NOT cache the verdict
        return {f: False for f in candidates}
    try:
        tmp = cache + f".{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(sup, f)
        os.replace(tmp, cache)
    except OSError:
        pass
    return sup


def apply_virtual_cpu_xla_flags(n_devices: int) -> None:
    """Set XLA_FLAGS for an n-device virtual CPU mesh. Each flag is guarded
    by its own name, so a caller's explicit setting always wins; timeout
    flags are version-probed (jaxlib 0.4.36 dropped the cpu collective
    timeout flags — blindly setting them aborts every process)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    sup = _supported_flags(_TIMEOUT_FLAGS)
    for f in _TIMEOUT_FLAGS:
        if f not in flags and sup.get(f):
            flags += f" --{f}=1200"
    os.environ["XLA_FLAGS"] = flags
