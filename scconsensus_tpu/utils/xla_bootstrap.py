"""Virtual-CPU-mesh XLA flag bootstrap (jax-free: must be importable and
applied BEFORE the first ``import jax`` side effects).

Shared by tests/conftest.py and __graft_entry__.dryrun_multichip so the
workaround set cannot drift between the two bootstrap paths.
"""

from __future__ import annotations

import os

# N virtual devices on few physical cores: XLA's default 40 s collective
# rendezvous terminate-timeout hard-aborts oversubscribed runs (observed at
# a 4000-cell mesh refine on 1 core); real multi-chip has a core per device
# and never hits this.
_TIMEOUT_FLAGS = (
    "xla_cpu_collective_timeout_seconds",
    "xla_cpu_collective_call_terminate_timeout_seconds",
)


def apply_virtual_cpu_xla_flags(n_devices: int) -> None:
    """Set XLA_FLAGS for an n-device virtual CPU mesh. Each flag is guarded
    by its own name, so a caller's explicit setting always wins."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    for f in _TIMEOUT_FLAGS:
        if f not in flags:
            flags += f" --{f}=1200"
    os.environ["XLA_FLAGS"] = flags
