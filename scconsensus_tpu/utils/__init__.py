from scconsensus_tpu.utils.synthetic import synthetic_scrna, planted_clusters
from scconsensus_tpu.utils.logging import get_logger, StageTimer
from scconsensus_tpu.utils.artifacts import ArtifactStore

__all__ = [
    "synthetic_scrna",
    "planted_clusters",
    "get_logger",
    "StageTimer",
    "ArtifactStore",
]
