"""Stage-keyed artifact store with resume.

The reference writes write-only ``saveRDS`` dumps with hard-coded CWD filenames
and never reads them back (R/reclusterDEConsensus.R:200-202,231,285; SURVEY.md
§5.4). Here each pipeline stage (consensus labels → per-pair DE tables → gene
union → embedding → tree → cuts) is saved under a stage key and is resumable:
re-running a pipeline with the same store skips completed stages.

Format: one ``<stage>.npz`` per stage for arrays plus a ``<stage>.json``
sidecar for scalars/metadata — portable, no pickle.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from scconsensus_tpu.obs.export import (
    ATOMIC_TMP_PREFIX as _TMP_PREFIX,
    atomic_write as _atomic_bytes_writer,
)

__all__ = ["ArtifactStore", "ArtifactCorrupt", "input_fingerprint",
           "config_fingerprint", "file_sha256", "quarantine_files"]


class ArtifactCorrupt(ValueError):
    """A stored artifact failed its content checksum or would not parse.
    The offending files are already quarantined when this raises; callers
    (``cached()``, the pipeline's de-resume path) recompute the stage."""

# Stage saves atomically via obs.export.atomic_write (the shared
# mkstemp+fsync+os.replace primitive): a half-written ``de.npz`` would
# poison every resume, so interrupted writers leave only stale
# ``.scc-tmp-*`` files, swept (when old) on the next store open.
_STALE_TMP_AGE_S = 3600.0


def input_fingerprint(data, labels) -> Dict[str, Any]:
    """Cheap content fingerprint of a pipeline's inputs.

    Resuming a store with the same *config* but different *data* would
    silently return artifacts computed from the old dataset; this pins shape,
    nnz, a strided sample hash of the values, and a labels hash so a data
    change raises instead (ADVICE r1). Sampling keeps it O(1e5) regardless of
    matrix size.
    """
    from scconsensus_tpu.io.sparsemat import is_jax, is_sparse

    h = hashlib.sha256()
    if is_sparse(data):
        vals = data.data
        nnz = int(data.nnz)
    elif is_jax(data):
        # Device matrix: stride ON DEVICE and pull only the ~64k sample —
        # np.asarray(data) here would drag the full matrix through the link.
        vals = data.reshape(-1)
        nnz = int((data != 0).sum()) if vals.size <= 10_000_000 else -1
    else:
        vals = np.asarray(data).ravel()
        nnz = int(np.count_nonzero(data)) if vals.size <= 10_000_000 else -1
    step = max(1, vals.size // 65_536)
    h.update(np.ascontiguousarray(np.asarray(vals[::step]),
                                  dtype=np.float32).tobytes())
    lab = np.asarray(labels).astype(str)
    lh = hashlib.sha256("\x00".join(lab.tolist()).encode()).hexdigest()[:16]
    return {
        "shape": [int(s) for s in data.shape],
        "nnz": nnz,
        "data_sample_sha": h.hexdigest()[:16],
        "labels_sha": lh,
    }


def config_fingerprint(obj: Any, n_hex: int = 12) -> str:
    """Short, order-independent content hash of a JSON-able value.

    The one fingerprint both stores use: the evidence ledger keys runs by
    (dataset, backend, config_fp) with it, and it is the canonical way to
    derive a directory-safe token from a config mapping. Key order never
    changes the hash; non-JSON leaves degrade via ``str`` (same rule as the
    artifact sidecars), so a numpy scalar fingerprints like its value.
    """
    blob = json.dumps(obj, sort_keys=True, default=str, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:n_hex]


def file_sha256(path: str) -> str:
    """Streaming sha256 of a file's bytes — THE content-checksum
    primitive every durable artifact shares (the ArtifactStore sidecars
    and the ChunkedCSRStore chunk integrity stamps both call this, so
    'verified' means the same thing for a stage artifact and a streamed
    chunk)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def quarantine_files(paths, logger=None) -> list:
    """Move files aside under ``<path>.quarantined-<n>`` names (never
    silently delete what might be the only copy of a long compute) —
    the shared rename loop behind ArtifactStore._quarantine and the
    chunk store's torn-chunk path. Returns the destination names."""
    dests = []
    for path in paths:
        if not os.path.exists(path):
            continue
        n = 0
        dest = f"{path}.quarantined-{n}"
        while os.path.exists(dest):
            n += 1
            dest = f"{path}.quarantined-{n}"
        try:
            os.replace(path, dest)
            dests.append(dest)
        except OSError:
            try:  # last resort: a corrupt file must not stay loadable
                os.unlink(path)
            except OSError:
                pass
    return dests


class ArtifactStore:
    def __init__(self, root: Optional[str], readonly: bool = False):
        """``readonly=True`` opens the store without touching the
        filesystem (no mkdir, no stale-temp sweep): the serving path's
        contract for a FROZEN model directory that may live on a
        read-only mount. A readonly store refuses ``save`` and, on a
        failed checksum, raises without quarantine-renaming the files
        (it still never loads them)."""
        self.root = root
        self.readonly = bool(readonly)
        if root is not None and not self.readonly:
            os.makedirs(root, exist_ok=True)
            self._sweep_stale_tmp()

    def _sweep_stale_tmp(self) -> None:
        """Remove temp files orphaned by an interrupted writer — they are
        never valid artifacts (stages resume from the real names only).
        Only temps older than ``_STALE_TMP_AGE_S`` go: a second process
        opening the same store must not yank a live writer's in-flight
        temp out from under its fsync."""
        try:
            cutoff = time.time() - _STALE_TMP_AGE_S
            for e in os.scandir(self.root):
                if (e.name.startswith(_TMP_PREFIX) and e.is_file()
                        and e.stat().st_mtime < cutoff):
                    try:
                        os.unlink(e.path)
                    except OSError:
                        pass
        except OSError:
            pass

    @property
    def enabled(self) -> bool:
        return self.root is not None

    def _paths(self, stage: str):
        assert self.root is not None
        return (
            os.path.join(self.root, f"{stage}.npz"),
            os.path.join(self.root, f"{stage}.json"),
        )

    def check_config(
        self, config_json: str, inputs: Optional[Dict[str, Any]] = None
    ) -> None:
        """Pin the store to one pipeline configuration + input fingerprint.

        First call writes the fingerprint (one JSON object,
        ``{"config": ..., "inputs": ...}``); later calls compare and raise on
        mismatch — stage caches are keyed only by stage name, so resuming
        with a different config or different input data would silently
        return stale results.
        """
        if not self.enabled:
            return
        config = json.loads(config_json)
        path = os.path.join(self.root, "config.json")
        if os.path.exists(path):
            with open(path) as f:
                stored = json.load(f)
            if "config" not in stored:
                # store written before input fingerprinting: bare config JSON
                if stored == config:
                    self._write_pin(path, config, inputs)  # accept + upgrade
                    return
                stored = {"config": stored, "inputs": None}
            if stored["config"] != config:
                raise ValueError(
                    f"artifact store {self.root!r} was written with a "
                    "different config — use a fresh artifact_dir for a new "
                    "configuration (stored fingerprint: config.json)"
                )
            if (
                inputs is not None
                and stored.get("inputs") is not None
                and stored["inputs"] != inputs
            ):
                raise ValueError(
                    f"artifact store {self.root!r} was written with "
                    "different input data — use a fresh artifact_dir for a "
                    "new dataset (stored fingerprint: config.json)"
                )
            return
        self._write_pin(path, config, inputs)

    @staticmethod
    def _write_pin(path: str, config: Any, inputs: Optional[Dict[str, Any]]):
        def _w(tmp):
            with open(tmp, "w") as f:
                json.dump({"config": config, "inputs": inputs}, f, indent=2)

        _atomic_bytes_writer(path, _w)

    def has(self, stage: str) -> bool:
        """True iff the stage's array artifact exists (the resume key).
        Meta sidecars alone do not mark a stage complete."""
        if not self.enabled:
            return False
        npz, _ = self._paths(stage)
        return os.path.exists(npz)

    @staticmethod
    def _checksums_on() -> bool:
        from scconsensus_tpu.config import env_flag

        return bool(env_flag("SCC_ROBUST_CHECKSUM"))

    @staticmethod
    def _file_sha(path: str) -> str:
        return file_sha256(path)

    def save(self, stage: str, arrays: Optional[Dict[str, np.ndarray]] = None,
             meta: Optional[Dict[str, Any]] = None) -> None:
        """Atomic per-file writes, meta BEFORE arrays: ``has()`` keys resume
        on the ``.npz``, so the only observable intermediate state (meta
        present, arrays absent) reads as stage-not-complete and recomputes.
        The reverse order could briefly expose arrays with a stale sidecar.

        With checksums on (``SCC_ROBUST_CHECKSUM``, default) the arrays
        file is serialized to its temp FIRST so its sha256 can ride the
        sidecar (``_integrity``) — load verifies it, so a truncated or
        bit-flipped artifact quarantines instead of resuming garbage.
        """
        if not self.enabled:
            return
        if self.readonly:
            raise RuntimeError(
                f"artifact store {self.root!r} is readonly — a frozen "
                "model directory is never written by the serving path"
            )
        from scconsensus_tpu.robust import faults as _faults
        from scconsensus_tpu.robust import record as _robust_record

        npz, js = self._paths(stage)

        def _write_sidecar(integrity: Optional[Dict[str, Any]]) -> None:
            payload = dict(meta or {})
            if integrity is not None:
                payload["_integrity"] = integrity

            def _wj(tmp):
                with open(tmp, "w") as f:
                    json.dump(payload, f, indent=2, default=str)

            _atomic_bytes_writer(js, _wj)

        if arrays is None:
            if meta is not None:
                _write_sidecar(None)
            return

        def _wz(tmp):
            # savez_compressed appends .npz when the name lacks it; an
            # explicit file handle writes exactly to the temp path
            with open(tmp, "wb") as f:
                np.savez_compressed(
                    f, **{k: np.asarray(v) for k, v in arrays.items()}
                )

        def _seal(tmp):
            # between serialize and replace: checksum the exact bytes
            # about to land, then write the sidecar — meta-before-arrays
            # ordering holds because the outer replace runs after this
            integrity = None
            if self._checksums_on():
                with _robust_record.timed():
                    integrity = {
                        "sha256": self._file_sha(tmp),
                        "size": os.path.getsize(tmp),
                    }
            if meta is not None or integrity is not None:
                _write_sidecar(integrity)

        _atomic_bytes_writer(npz, _wz, inspect_fn=_seal)
        # fault plan's post-write corruption hook (artifact:<stage>
        # sites): models a disk/transport fault AFTER the atomic
        # replace — exactly what the load-time checksum exists for
        _faults.corrupt_artifact(stage, npz)

    def _quarantine(self, stage: str, reason: str) -> None:
        """Move the stage's files aside under ``*.quarantined-<n>`` names
        (never silently delete what might be the only copy of a long
        compute) and record the event on the robustness log."""
        from scconsensus_tpu.robust import record as _robust_record
        from scconsensus_tpu.utils.logging import get_logger

        if self.readonly:
            # refuse-without-rename: the load still raises ArtifactCorrupt
            # (nothing gets served), but a read-only mount's files stay
            # exactly where the operator put them
            _robust_record.note_degradation(
                f"artifact:{stage}", "quarantine", reason + " (readonly)"
            )
            get_logger().warning(
                "artifact %r failed verification (%s); store is readonly, "
                "files left in place and load refused", stage, reason,
            )
            return
        quarantine_files(self._paths(stage))
        _robust_record.note_degradation(
            f"artifact:{stage}", "quarantine", reason
        )
        get_logger().warning(
            "artifact %r quarantined (%s); stage will recompute",
            stage, reason,
        )

    def load(self, stage: str):
        """(arrays, meta) for a stage. Verifies the sidecar's content
        checksum when present (and ``SCC_ROBUST_CHECKSUM`` is on);
        corrupt or unparseable entries are quarantined and raise
        :class:`ArtifactCorrupt` — callers recompute, never resume
        garbage. Stores written before checksums existed (no
        ``_integrity``) load unverified, as before."""
        from scconsensus_tpu.robust import record as _robust_record

        npz, js = self._paths(stage)
        meta: Dict[str, Any] = {}
        if os.path.exists(js):
            try:
                with open(js) as f:
                    meta = json.load(f)
            except (json.JSONDecodeError, OSError) as e:
                self._quarantine(stage, f"sidecar unreadable: {e}")
                raise ArtifactCorrupt(
                    f"artifact {stage!r}: sidecar unreadable ({e}); "
                    "quarantined"
                )
        arrays: Dict[str, np.ndarray] = {}
        if os.path.exists(npz):
            integ = meta.get("_integrity")
            if integ and self._checksums_on():
                with _robust_record.timed():
                    actual = self._file_sha(npz)
                if actual != integ.get("sha256"):
                    self._quarantine(
                        stage,
                        f"checksum mismatch ({actual[:12]} != "
                        f"{str(integ.get('sha256'))[:12]})",
                    )
                    raise ArtifactCorrupt(
                        f"artifact {stage!r}: content checksum mismatch; "
                        "quarantined"
                    )
            try:
                with np.load(npz, allow_pickle=False) as z:
                    arrays = {k: z[k] for k in z.files}
            except Exception as e:  # BadZipFile, truncated stream, ...
                self._quarantine(stage, f"unparseable npz: {e!r}")
                raise ArtifactCorrupt(
                    f"artifact {stage!r}: unparseable ({e!r}); quarantined"
                )
        return arrays, meta

    def discard_prefix(self, prefix: str) -> int:
        """Remove every stage artifact whose FILE name starts with
        ``prefix`` (both .npz and .json) — mid-stage checkpoint cleanup
        once the covering stage artifact has landed. Returns the number
        of files removed. Quarantined files are kept (post-mortems)."""
        if not self.enabled:
            return 0
        n = 0
        try:
            for e in os.scandir(self.root):
                if (e.name.startswith(prefix) and e.is_file()
                        and (e.name.endswith(".npz")
                             or e.name.endswith(".json"))):
                    try:
                        os.unlink(e.path)
                        n += 1
                    except OSError:
                        pass
        except OSError:
            pass
        return n

    def cached(self, stage: str, fn: Callable[[], Dict[str, np.ndarray]],
               meta_fn: Optional[Callable[[], Dict[str, Any]]] = None,
               on_load_meta: Optional[Callable[[Dict[str, Any]], Any]]
               = None):
        """Run ``fn`` (returning a dict of arrays) unless ``stage`` already
        has a saved artifact, in which case load and return it. A corrupt
        stored artifact (failed checksum / truncated zip) has been
        quarantined by ``load`` — fall through and recompute.
        ``on_load_meta(meta)`` fires only on the resume path with the
        stored sidecar — the elastic supervisor reads the ``mesh_shape``
        stamp there to record shape-polymorphic resumes."""
        if self.has(stage):
            try:
                arrays, meta = self.load(stage)
                if on_load_meta is not None:
                    on_load_meta(meta)
                return arrays
            except ArtifactCorrupt:
                pass  # quarantined inside load(); recompute below
        arrays = fn()
        self.save(stage, arrays, meta_fn() if meta_fn else None)
        return arrays
