"""Structured logging + per-stage timing.

The reference's observability is bare ``print()`` progress lines
(R/reclusterDEConsensus.R:172-178; SURVEY.md §5.1/§5.5). Here every pipeline
stage emits a structured record {stage, wall_s, extra metrics} through a
standard logger, and the collected records double as the benchmark output.
"""

from __future__ import annotations

import json
import logging
import os
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

__all__ = ["get_logger", "StageTimer"]

# SCC_STAGE_SYNC=1: drain the device queue at every stage boundary so stage
# walls are honest compute attribution instead of dispatch intervals (JAX
# async dispatch otherwise lands queued work on whichever stage first
# blocks — a 78 s "bh_adjust" was really the rank-sum queue draining).
# Costs one device round-trip per stage; off by default.
_STAGE_SYNC = bool(os.environ.get("SCC_STAGE_SYNC"))

_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"
_LOG_LIST_CAP = 16


def _log_form(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Log-line rendering of a stage record: long lists (e.g. the per-pair
    DE counts at K=44 → 946 entries) are summarized; the STORED record —
    what metrics/bench consumers read — keeps the full values. Recurses
    into nested dicts (the wilcox stage's ``occupancy`` probe carries a
    per-bucket list that can run tens of entries at 1M-cell shapes)."""
    out: Dict[str, Any] = {}
    for k, v in rec.items():
        if isinstance(v, dict):
            out[k] = _log_form(v)
        elif isinstance(v, (list, tuple)) and len(v) > _LOG_LIST_CAP:
            out[k] = {
                "n": len(v),
                "head": list(v[:_LOG_LIST_CAP]),
                "sum": sum(v) if v and isinstance(v[0], (int, float)) else None,
            }
        else:
            out[k] = v
    return out


def get_logger(name: str = "scconsensus_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


class StageTimer:
    """Collects per-stage wall-clock + metrics; optionally wraps stages in
    ``jax.profiler.TraceAnnotation`` so stages show up in TPU traces."""

    def __init__(self, logger: Optional[logging.Logger] = None, trace: bool = False):
        self.records: List[Dict[str, Any]] = []
        self.logger = logger or get_logger()
        self.trace = trace

    @staticmethod
    def _drain() -> None:
        if not _STAGE_SYNC:
            return
        try:
            import jax

            (jax.device_put(0.0) + 0).block_until_ready()
        except Exception:  # no backend yet / shutdown: attribution only
            pass

    @contextmanager
    def stage(self, name: str, **metrics: Any):
        ann = None
        if self.trace:
            import jax.profiler

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        self._drain()
        t0 = time.perf_counter()
        rec: Dict[str, Any] = {"stage": name, **metrics}
        try:
            yield rec
        finally:
            self._drain()
            rec["wall_s"] = round(time.perf_counter() - t0, 4)
            if ann is not None:
                ann.__exit__(None, None, None)
            self.records.append(rec)
            if _STAGE_SYNC:
                rec["synced"] = True
            self.logger.info("stage %s", json.dumps(_log_form(rec), default=str))

    def total_s(self) -> float:
        return sum(r.get("wall_s", 0.0) for r in self.records)

    def as_dict(self) -> Dict[str, Any]:
        return {"stages": self.records, "total_s": self.total_s()}
