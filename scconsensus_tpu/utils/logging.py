"""Structured logging + the StageTimer back-compat shim.

The reference's observability is bare ``print()`` progress lines
(R/reclusterDEConsensus.R:172-178; SURVEY.md §5.1/§5.5). Tracing now lives
in :mod:`scconsensus_tpu.obs.trace`; ``StageTimer`` remains as a thin shim
over :class:`~scconsensus_tpu.obs.trace.Tracer` so existing callers (and
external code built against the old API) keep working: ``stage()`` opens a
stage-kind span, ``records`` is the legacy list-of-dicts view, and
``as_dict()`` additionally carries the full span tree + schema version for
the run-record exporters.

Device-sync policy moved to the tracer (SCC_TRACE_SYNC in the config.py
env-flag registry): stage boundaries drain the device queue by default, so
stage walls are honest compute attribution instead of dispatch intervals —
what SCC_STAGE_SYNC=1 used to opt into.
"""

from __future__ import annotations

import logging
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from scconsensus_tpu.obs.trace import Tracer

__all__ = ["get_logger", "StageTimer"]

_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"


def get_logger(name: str = "scconsensus_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


class StageTimer:
    """Back-compat facade over ``obs.trace.Tracer``.

    ``trace=True`` maps to the tracer's ``annotate`` (stages wrapped in
    ``jax.profiler.TraceAnnotation`` so they show up in TPU traces).
    """

    def __init__(self, logger: Optional[logging.Logger] = None,
                 trace: bool = False, tracer: Optional[Tracer] = None):
        self.logger = logger or get_logger()
        self.tracer = tracer or Tracer(logger=self.logger, annotate=trace)
        if tracer is not None and tracer.logger is None:
            tracer.logger = self.logger

    @contextmanager
    def stage(self, name: str, **metrics: Any):
        with self.tracer.span(name, kind="stage", **metrics) as sp:
            yield sp

    @property
    def records(self) -> List[Dict[str, Any]]:
        return self.tracer.stage_records()

    def total_s(self) -> float:
        return self.tracer.total_s()

    def as_dict(self) -> Dict[str, Any]:
        return self.tracer.as_dict()
