"""Host→device upload cache for large immutable inputs.

The engines treat the (G, N) expression matrix as immutable (functional
pipeline), so re-running a stage over the same host array — the
cold-then-steady benchmark pattern, or resumed pipelines re-entering the DE
stage — can reuse the device buffer instead of re-crossing the link. On the
axon tunnel this matters twice over: the first 1.56 GB upload costs ~1 s,
but repeat uploads degrade with cumulative traffic (measured 1.0 → 6.7 s
over four rounds).

Entries are keyed by the array's identity and die with it (weakref
finalizer), so the cache can never outlive or alias its host array. Hits are
additionally guarded by a strided content sentinel: a caller that mutates
the cached array in place (the matrix is user-supplied) gets a cache miss
and a fresh upload, not silently stale device data.
"""

from __future__ import annotations

import hashlib
import weakref
from typing import Dict, Tuple

import numpy as np

__all__ = ["device_put_cached"]

_cache: Dict[int, Tuple[object, bytes, object]] = {}
_SENTINEL_SAMPLES = 4096
# Bounded: on CPU backends jnp.asarray may alias the host buffer, in which
# case the cached device array keeps its host array alive and the weakref
# finalizer never fires — a cap keeps worst-case retention finite.
_MAX_ENTRIES = 4


def _sentinel(x: np.ndarray) -> bytes:
    """Content fingerprint: shape/dtype + full-pass f64 sum + a strided
    element sample. The full sum (one memory-bandwidth pass, ~0.2 s at
    1.5 GB — still 5-30× cheaper than the upload it saves) catches partial
    in-place edits the sparse sample would miss (e.g. zeroing one gene row);
    the sample catches sum-preserving permutations."""
    flat = x.reshape(-1)
    step = max(1, flat.size // _SENTINEL_SAMPLES)
    sample = np.ascontiguousarray(flat[::step])
    h = hashlib.sha256()
    h.update(str((x.shape, x.dtype.str)).encode())
    h.update(np.float64(np.sum(flat, dtype=np.float64)).tobytes())
    h.update(sample.tobytes())
    return h.digest()


def device_put_cached(x: np.ndarray):
    """jnp.asarray(x) memoized on the identity + content sentinel of ``x``.

    Only worthwhile for large arrays; small ones should go through
    jnp.asarray directly (this path pays a dict lookup + sample hash)."""
    import jax.numpy as jnp

    key = id(x)
    sent = _sentinel(x)
    ent = _cache.get(key)
    if ent is not None:
        host = ent[0]()
        if host is x and ent[1] == sent:
            return ent[2]
        _cache.pop(key, None)  # freed id reuse or in-place mutation
    buf = jnp.asarray(x)
    try:
        ref = weakref.ref(x, lambda _r, _k=key: _cache.pop(_k, None))
    except TypeError:
        return buf  # not weakref-able (exotic subclass): skip caching
    while len(_cache) >= _MAX_ENTRIES:  # FIFO eviction (dicts keep order)
        _cache.pop(next(iter(_cache)))
    _cache[key] = (ref, sent, buf)
    return buf
