"""Host→device upload cache for large immutable inputs.

The engines treat the (G, N) expression matrix as immutable (functional
pipeline), so re-running a stage over the same host array — the
cold-then-steady benchmark pattern, or resumed pipelines re-entering the DE
stage — can reuse the device buffer instead of re-crossing the link. On the
axon tunnel this matters twice over: the first 1.56 GB upload costs ~1 s,
but repeat uploads degrade with cumulative traffic (measured 1.0 → 6.7 s
over four rounds).

Entries are keyed by the array's identity and die with it (weakref
finalizer), so the cache can never outlive or alias its host array. Hits are
additionally guarded by a content sentinel: a caller that mutates the cached
array in place (the matrix is user-supplied) gets a cache miss and a fresh
upload, not silently stale device data.

Cost model (ADVICE r3): the full-array f64 sum pass (~0.2 s/1.5 GB) runs at
insert time and on every hit. Arming it lazily at the first hit was tried
and is unsound — a mutation between insert and first hit would be baked
into the baseline, poisoning every later verification — so the insert-time
pass stays; what ADVICE's cost concern bought instead is the entry cap of 2
(was 4: ~6 GB of pinned HBM at flagship sizes) and eviction + one retry on
device allocation failure.
"""

from __future__ import annotations

import hashlib
import weakref
from typing import Dict

import numpy as np

__all__ = ["device_put_cached", "clear_cache"]


def clear_cache() -> None:
    """Drop every pinned device buffer (the resource-degradation hook:
    an OOM elsewhere in the pipeline frees the cache's HBM first)."""
    _cache.clear()


class _Entry:
    __slots__ = ("ref", "sample", "full_sum", "buf")

    def __init__(self, ref, sample: bytes, full_sum: float, buf):
        self.ref = ref
        self.sample = sample
        self.full_sum = full_sum  # insert-time baseline (see module docstring)
        self.buf = buf


_cache: Dict[int, _Entry] = {}
_SENTINEL_SAMPLES = 4096
# Bounded: on CPU backends jnp.asarray may alias the host buffer, in which
# case the cached device array keeps its host array alive and the weakref
# finalizer never fires — a cap keeps worst-case retention finite. Two
# entries cover the realistic reuse pattern (log data + expm1 counts);
# pinning four flagship-sized buffers was ~6 GB of HBM (ADVICE r3).
_MAX_ENTRIES = 2


def _sample_hash(x: np.ndarray) -> bytes:
    """Cheap fingerprint: shape/dtype + a strided element sample."""
    flat = x.reshape(-1)
    step = max(1, flat.size // _SENTINEL_SAMPLES)
    sample = np.ascontiguousarray(flat[::step])
    h = hashlib.sha256()
    h.update(str((x.shape, x.dtype.str)).encode())
    h.update(sample.tobytes())
    return h.digest()


def _full_sum(x: np.ndarray) -> float:
    """One memory-bandwidth pass; catches partial in-place edits the strided
    sample misses (e.g. zeroing one gene row)."""
    return float(np.sum(x.reshape(-1), dtype=np.float64))


def device_put_cached(x: np.ndarray):
    """jnp.asarray(x) memoized on the identity + content sentinel of ``x``.

    Only worthwhile for large arrays; small ones should go through
    jnp.asarray directly (this path pays a dict lookup + sample hash)."""
    import jax.numpy as jnp

    from scconsensus_tpu.io.sparsemat import is_jax

    if is_jax(x):
        return x  # already device-resident: nothing to upload or verify

    from scconsensus_tpu.obs.residency import boundary

    key = id(x)
    sample = _sample_hash(x)
    ent = _cache.get(key)
    if ent is not None:
        host = ent.ref()
        if host is x and ent.sample == sample:
            cur = _full_sum(x)
            # NaN-bearing matrices: NaN == NaN is False, which would evict
            # and re-upload on every call — treat NaN baselines as equal
            # (the strided sample still guards those entries).
            same = (ent.full_sum == cur) or (
                np.isnan(ent.full_sum) and np.isnan(cur)
            )
            if same:
                return ent.buf
        _cache.pop(key, None)  # freed id reuse or in-place mutation
    with boundary("input_staging"):  # THE intended matrix upload
        # Device allocation failure: drop every pinned buffer and retry —
        # the same evict-and-retry as always, but through the central
        # robust.retry policy (span event + robust_retries counter per
        # attempt, per-run budget respected). Any upload failure is
        # classified "resource" here, preserving the historical contract
        # that a failed jnp.asarray gets exactly one eviction retry.
        from scconsensus_tpu.robust import record as _rb_record
        from scconsensus_tpu.robust.retry import RetryPolicy

        def _evict(_attempt):
            _cache.clear()
            _rb_record.note_degradation(
                "input_staging", "evict-devcache",
                "dropped every pinned device buffer before re-upload",
            )

        buf = RetryPolicy(max_attempts=2).call(
            lambda: jnp.asarray(x), site="input_staging",
            degrade=_evict, classify=lambda _e: "resource",
        )
    try:
        ref = weakref.ref(x, lambda _r, _k=key: _cache.pop(_k, None))
    except TypeError:
        return buf  # not weakref-able (exotic subclass): skip caching
    while len(_cache) >= _MAX_ENTRIES:  # FIFO eviction (dicts keep order)
        _cache.pop(next(iter(_cache)))
    _cache[key] = _Entry(ref, sample, _full_sum(x), buf)
    return buf
