"""Synthetic scRNA-seq data generators for tests and benchmarks.

The reference validates only manually against the Zenodo 26k-PBMC dataset
(reference README.md:32-36); this environment has no network egress, so all
tests and benches run on synthetic negative-binomial data with planted cluster
structure (SURVEY.md §4 "Integration").
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = [
    "synthetic_scrna",
    "synthetic_scrna_device",
    "planted_clusters",
    "noisy_labeling",
]


def planted_clusters(
    n_cells: int, n_clusters: int, rng: np.random.Generator, balance: float = 0.5
) -> np.ndarray:
    """Cluster assignment vector with mildly imbalanced sizes."""
    w = rng.dirichlet(np.full(n_clusters, 1.0 / max(balance, 1e-3)))
    w = 0.5 * w + 0.5 / n_clusters  # keep every cluster populated
    return rng.choice(n_clusters, size=n_cells, p=w / w.sum())


def synthetic_scrna(
    n_genes: int = 2000,
    n_cells: int = 1000,
    n_clusters: int = 4,
    n_markers_per_cluster: int = 40,
    marker_log_fc: float = 2.0,
    nb_dispersion: float = 0.5,
    depth: float = 2000.0,
    seed: int = 0,
    log_normalize: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Generate a (genes, cells) matrix with planted clusters.

    Counts are NB-distributed around a per-gene baseline; each cluster
    up-regulates its own disjoint marker block by ``marker_log_fc`` (natural
    log). When ``log_normalize``, returns log1p(counts / libsize * depth) —
    the "log-transformed and normalized" input the reference expects
    (R/reclusterDEConsensus.R:5).

    Returns (data, labels, marker_mask) where marker_mask is (n_clusters,
    n_genes) boolean.
    """
    if n_clusters * n_markers_per_cluster > n_genes:
        raise ValueError(
            f"marker blocks overflow the gene space: {n_clusters} clusters x "
            f"{n_markers_per_cluster} markers > {n_genes} genes"
        )
    rng = np.random.default_rng(seed)
    labels = planted_clusters(n_cells, n_clusters, rng)

    base = np.exp(rng.normal(loc=-1.0, scale=1.0, size=n_genes))
    log_mu = np.log(base)[:, None] * np.ones((1, n_cells))

    marker_mask = np.zeros((n_clusters, n_genes), dtype=bool)
    for k in range(n_clusters):
        lo = k * n_markers_per_cluster
        hi = min(lo + n_markers_per_cluster, n_genes)
        marker_mask[k, lo:hi] = True
        cells_k = labels == k
        log_mu[lo:hi][:, cells_k] += marker_log_fc

    mu = np.exp(log_mu)
    mu *= depth / mu.sum(axis=0, keepdims=True)
    # NB via gamma-Poisson mixture.
    shape = 1.0 / nb_dispersion
    lam = rng.gamma(shape=shape, scale=mu / shape)
    counts = rng.poisson(lam).astype(np.float64)

    if log_normalize:
        libsize = counts.sum(axis=0, keepdims=True)
        libsize = np.maximum(libsize, 1.0)
        data = np.log1p(counts / libsize * depth)
    else:
        data = counts
    return data.astype(np.float32), labels, marker_mask


def synthetic_scrna_device(
    n_genes: int = 2000,
    n_cells: int = 1000,
    n_clusters: int = 4,
    n_markers_per_cluster: int = 40,
    marker_log_fc: float = 2.0,
    nb_dispersion: float = 0.5,
    depth: float = 2000.0,
    seed: int = 0,
    log_normalize: bool = True,
    gene_block: int = 2048,
) -> Tuple[object, np.ndarray, np.ndarray]:
    """``synthetic_scrna`` twin that draws the matrix ON DEVICE.

    Same planted structure (labels, baselines and marker blocks come from
    the identical numpy RNG procedure), but the gamma–Poisson draws happen
    in HBM via ``jax.random``, so only a few KB of labels/parameters ever
    cross the host↔device link. At flagship scale the host generator costs
    ~130 s of numpy time plus a ~1.5 GB upload — over a thin remote-TPU
    tunnel the upload alone can exceed the whole compute budget, which is
    why this path exists (and why benches on accelerators default to it).

    Gene blocks of ``gene_block`` rows bound peak HBM: the (G, N) counts
    buffer is allocated once and updated in place (donated
    dynamic_update_slice), with per-block temporaries of gene_block × N.
    Returns (data: jax.Array (G, N) f32, labels, marker_mask) — the last
    two host-side, shaped exactly like ``synthetic_scrna``'s.
    """
    import jax
    import jax.numpy as jnp

    if n_clusters * n_markers_per_cluster > n_genes:
        raise ValueError(
            f"marker blocks overflow the gene space: {n_clusters} clusters x "
            f"{n_markers_per_cluster} markers > {n_genes} genes"
        )
    rng = np.random.default_rng(seed)
    labels = planted_clusters(n_cells, n_clusters, rng)
    base = np.exp(rng.normal(loc=-1.0, scale=1.0, size=n_genes))
    marker_mask = np.zeros((n_clusters, n_genes), dtype=bool)
    for k in range(n_clusters):
        lo = k * n_markers_per_cluster
        hi = min(lo + n_markers_per_cluster, n_genes)
        marker_mask[k, lo:hi] = True

    # Bound per-block HBM: the gamma/poisson draws hold ~3 block-sized f32
    # temporaries, so cap blocks at ~128M elements (512 MB each) — at 100k
    # cells this drops the block to 1280 genes instead of risking an OOM
    # next to the full (G, N) counts buffer.
    B = int(min(gene_block, n_genes, max(256, 128_000_000 // max(n_cells, 1))))
    n_blocks = -(-n_genes // B)
    g_pad = n_blocks * B
    # Padding rows get log-mu = -inf → mu = 0 → counts = 0; they are sliced
    # off at the end, so block shapes stay uniform (one compile per pass).
    log_base_pad = np.full(g_pad, -1e30, np.float32)
    log_base_pad[:n_genes] = np.log(base).astype(np.float32)
    mask_pad = np.zeros((g_pad, n_clusters), np.float32)
    mask_pad[:n_genes] = marker_mask.T.astype(np.float32)

    lab_d = jnp.asarray(labels.astype(np.int32))            # (N,)
    logb_d = jnp.asarray(log_base_pad)                      # (Gpad,)
    mask_d = jnp.asarray(mask_pad)                          # (Gpad, K)
    shape_param = np.float32(1.0 / nb_dispersion)
    key = jax.random.PRNGKey(seed)

    @jax.jit
    def _mu_block(g0, logb, mask, lab):
        lb = jax.lax.dynamic_slice_in_dim(logb, g0, B)          # (B,)
        mk = jax.lax.dynamic_slice_in_dim(mask, g0, B, axis=0)  # (B, K)
        bump = marker_log_fc * jnp.take(mk, lab, axis=1)        # (B, N)
        return jnp.exp(lb[:, None] + bump)

    @jax.jit
    def _mu_colsum_block(g0, logb, mask, lab):
        return _mu_block(g0, logb, mask, lab).sum(axis=0)

    mu_colsum = jnp.zeros(n_cells, jnp.float32)
    for b in range(n_blocks):
        mu_colsum = mu_colsum + _mu_colsum_block(b * B, logb_d, mask_d, lab_d)
    mu_scale = depth / jnp.maximum(mu_colsum, 1e-30)            # (N,)

    @jax.jit
    def _counts_block(k, g0, logb, mask, lab, scale):
        mu = _mu_block(g0, logb, mask, lab) * scale[None, :]
        lam = jax.random.gamma(k, shape_param, shape=mu.shape) * (
            mu / shape_param
        )
        return jax.random.poisson(jax.random.fold_in(k, 1), lam).astype(
            jnp.float32
        )

    place = jax.jit(
        lambda acc, blk, g0: jax.lax.dynamic_update_slice(acc, blk, (g0, 0)),
        donate_argnums=0,
    )
    counts = jnp.zeros((g_pad, n_cells), jnp.float32)
    libsize = jnp.zeros(n_cells, jnp.float32)
    for b in range(n_blocks):
        blk = _counts_block(
            jax.random.fold_in(key, b), b * B, logb_d, mask_d, lab_d, mu_scale
        )
        libsize = libsize + blk.sum(axis=0)
        counts = place(counts, blk, b * B)

    if log_normalize:
        norm = jax.jit(
            lambda c, ls: jnp.log1p(c * (depth / jnp.maximum(ls, 1.0))[None, :]),
            donate_argnums=0,
        )
        counts = norm(counts, libsize)
    data = counts[:n_genes] if g_pad != n_genes else counts
    return data, labels, marker_mask


def noisy_labeling(
    labels: np.ndarray,
    flip_frac: float,
    n_out_clusters: Optional[int] = None,
    seed: int = 0,
    prefix: str = "c",
) -> np.ndarray:
    """Derive a degraded string labeling from ground truth: a fraction of cells
    get a random label; optionally *coarsen* to ``n_out_clusters`` (values >= the
    true cluster count are a no-op — refinement is not simulated).
    Used to simulate the supervised/unsupervised input pair for consensus tests."""
    rng = np.random.default_rng(seed)
    lab = labels.copy()
    k = labels.max() + 1
    if n_out_clusters is not None and n_out_clusters < k:
        merge_map = rng.integers(0, n_out_clusters, size=k)
        lab = merge_map[lab]
        k = n_out_clusters
    flip = rng.random(lab.shape[0]) < flip_frac
    lab[flip] = rng.integers(0, k, size=int(flip.sum()))
    return np.array([f"{prefix}{v}" for v in lab])
