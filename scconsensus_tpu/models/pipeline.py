"""End-to-end refinement pipelines (the user-facing "model" layer).

Factorization of the reference's two monoliths (R/reclusterDEConsensus.R:20-299
and R/reclusterDEConsensusFast.R:22-469, which inline DE + embed + recluster +
report with ~70 duplicated tail lines — SURVEY.md §1) into one ``refine()``
pipeline over real engine layers, plus two reference-shaped entry points.

Stages (each timed, metric-logged, and resumable via ArtifactStore):
  de → union → embed (PCA) → tree (Ward.D2) → cuts (dynamic tree cut ×
  deepSplit) → silhouette → nodg → report.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from scconsensus_tpu.config import CompatFlags, ReclusterConfig
from scconsensus_tpu.de import de_gene_union, pairwise_de
from scconsensus_tpu.obs import quality as obs_quality
from scconsensus_tpu.obs import residency
from scconsensus_tpu.robust import record as robust_record
from scconsensus_tpu.robust import retry as robust_retry
from scconsensus_tpu.de.engine import PairwiseDEResult
from scconsensus_tpu.ops.colors import labels_to_colors
from scconsensus_tpu.ops.linkage import HClustTree, ward_linkage
from scconsensus_tpu.ops.pca import pca_scores
from scconsensus_tpu.ops.silhouette import mean_cluster_silhouette
from scconsensus_tpu.ops.treecut import cutree_hybrid
from scconsensus_tpu.utils.artifacts import ArtifactStore
from scconsensus_tpu.utils.logging import StageTimer, get_logger

__all__ = [
    "ReclusterResult",
    "refine",
    "recluster_de_consensus",
    "recluster_de_consensus_fast",
]


@dataclasses.dataclass
class ReclusterResult:
    """Pipeline output. Mirrors the reference's return object
    {deGeneUnion, cellTree, dynamicColors} (R/reclusterDEConsensus.R:278-282)
    plus everything the reference computed and dropped (silhouette, metrics)."""

    de_gene_union: np.ndarray          # gene names if provided, else indices
    de_gene_union_idx: np.ndarray      # always indices into the input rows
    cell_tree: HClustTree
    dynamic_colors: Dict[str, np.ndarray]   # "deepsplit: k" -> color per cell
    dynamic_labels: Dict[str, np.ndarray]   # same keys -> integer labels (0=unassigned)
    deep_split_info: List[Dict]        # per deepSplit: n_clusters, silhouette
    nodg: np.ndarray                   # number of detected genes per cell
    embedding: np.ndarray              # (N, n_pcs) PCA scores
    de: PairwiseDEResult
    metrics: Dict


def refine(
    data: np.ndarray,
    labels: Sequence,
    config: ReclusterConfig,
    gene_names: Optional[Sequence[str]] = None,
    timer: Optional[StageTimer] = None,
    mesh="auto",
) -> ReclusterResult:
    """Full DE → embed → recluster refinement.

    Args:
      data: (G, N) log-transformed, normalized genes × cells matrix
        (the reference's input contract, R/reclusterDEConsensus.R:5).
      labels: per-cell consensus cluster labels (e.g. from
        ``plot_contingency_table``).
      mesh: "auto" (1-D mesh over all visible devices when >1 — the mesh
        equivalent of the reference's doParallel fan-out,
        R/reclusterDEConsensusFast.R:61-65), an explicit
        ``jax.sharding.Mesh``, or None for the serial single-device path.
        Mesh runs shard the rank-test gene chunks and the silhouette ring;
        results are identical to serial (asserted in tests/test_parallel.py).

    Observability: every stage runs inside a tracer span (submitted +
    device-synced walls; obs.trace). SCC_OBS_TRANSFERS=1 additionally
    counts explicit host↔device transfer bytes onto the result metrics;
    SCC_OBS_RESIDENCY=audit|enforce runs the whole pipeline under the
    residency auditor (obs.residency: every transfer span-attributed on
    ``result.metrics["residency"]``; enforce raises on crossings outside
    the declared boundary allowlist); SCC_OBS_KERNELS=<dir> opens a
    jax.profiler capture window around the run and joins the device-op
    timeline to spans (``result.metrics["kernels"]``);
    SCC_TRACE_DIR=<dir> exports <dir>/run_record.json and a Perfetto-
    openable <dir>/trace.json after the run (even a failed one, for
    post-mortems).
    """
    from contextlib import nullcontext

    from scconsensus_tpu.config import env_flag
    from scconsensus_tpu.obs import residency as obs_residency
    from scconsensus_tpu.obs.kernels import KernelCapture

    # Out-of-core routing (round 17): a disk-resident ChunkedCSRStore is
    # a first-class input — the full pipeline runs chunk-at-a-time under
    # the host-memory budget (stream.runner), with per-shard durable
    # progress instead of whole-stage artifacts. One entry point, two
    # residency regimes.
    from scconsensus_tpu.stream.store import ChunkedCSRStore

    if isinstance(data, ChunkedCSRStore):
        from scconsensus_tpu.stream.runner import streaming_refine

        return streaming_refine(
            data, labels, config, gene_names=gene_names,
            stage_dir=config.artifact_dir, timer=timer,
        )

    # fresh robustness trail for this run (robust.record): stage-boundary
    # retries, ladder degradations, mid-stage resume points, and any
    # SCC_FAULT_PLAN injections all land on result.metrics["robustness"]
    robust_record.begin_run()
    # fresh integrity trail (robust.integrity, round 18): invariant
    # checks, ghost-replay results, and silent-corruption recomputes
    # land on result.metrics["integrity"] (absent with SCC_INTEGRITY=off)
    from scconsensus_tpu.robust import integrity as robust_integrity

    robust_integrity.begin_run()
    capture = KernelCapture()
    if timer is None:
        # the kernel join needs TraceAnnotation windows in the profiler
        # timeline, which the tracer's annotate mode emits per span
        timer = StageTimer(get_logger(), trace=capture.enabled)
    watch = None
    if env_flag("SCC_OBS_TRANSFERS"):
        from scconsensus_tpu.obs.device import TransferWatch

        watch = TransferWatch()
    auditor = None
    if obs_residency.mode() != "off":
        auditor = obs_residency.ResidencyAuditor()
    try:
        with obs_residency.audit_region(auditor), \
                (watch if watch is not None else nullcontext()), \
                capture:
            result = _refine_impl(data, labels, config, gene_names, timer,
                                  mesh)
    finally:
        trace_dir = env_flag("SCC_TRACE_DIR")
        if trace_dir:
            _export_trace(trace_dir, timer, watch)
    if watch is not None:
        result.metrics["transfers"] = watch.report()
    if auditor is not None:
        result.metrics["residency"] = auditor.report()
    rb_section = robust_record.section()
    if rb_section is not None:
        # absent on healthy unfaulted runs — absence IS the healthy signal
        result.metrics["robustness"] = rb_section
    ig_section = robust_integrity.section()
    if ig_section is not None:
        # absent with SCC_INTEGRITY=off — a run that never audited its
        # arithmetic carries no claim about it
        result.metrics["integrity"] = ig_section
    if capture.enabled:
        try:
            from scconsensus_tpu.obs.cost import stage_cost_summary

            sec = capture.section(
                span_records=result.metrics.get("spans") or [],
                stage_cost=stage_cost_summary(
                    result.metrics.get("spans") or []
                ) or None,
            )
            if sec is not None:
                result.metrics["kernels"] = sec
        except Exception as e:  # capture is evidence, never a crash
            get_logger().warning("kernel capture section failed: %r", e)
    return result


def _export_trace(trace_dir: str, timer: StageTimer, watch) -> None:
    """Best-effort post-run export; never kills the pipeline result."""
    try:
        import os

        from scconsensus_tpu.obs.export import (
            build_run_record,
            write_chrome_trace,
            write_json_atomic,
        )

        os.makedirs(trace_dir, exist_ok=True)
        tracer = timer.tracer
        rec = build_run_record(
            metric="refine() pipeline trace",
            value=round(tracer.total_s(), 4),
            unit="seconds",
            tracer=tracer,
            transfers=watch.report() if watch is not None else None,
        )
        write_json_atomic(os.path.join(trace_dir, "run_record.json"), rec)
        write_chrome_trace(os.path.join(trace_dir, "trace.json"),
                           tracer.span_records())
    except Exception as e:  # pragma: no cover - defensive
        get_logger().warning("trace export failed: %r", e)


def _refine_impl(
    data: np.ndarray,
    labels: Sequence,
    config: ReclusterConfig,
    gene_names: Optional[Sequence[str]],
    timer: StageTimer,
    mesh,
) -> ReclusterResult:
    from scconsensus_tpu.io.sparsemat import (
        as_csr,
        is_jax,
        is_sparse,
        nodg as sparse_nodg,
        rows_dense,
    )

    logger = timer.logger
    store = ArtifactStore(config.artifact_dir)
    # Elastic mesh execution (robust.elastic): the supervisor owns mesh
    # construction for the sharded paths — "auto" and explicit meshes
    # both resolve through it (SCC_ELASTIC=0 restores the bare
    # auto_mesh behavior). Stage closures read _mesh() at CALL time, so
    # a device_lost retry re-enters against the rebuilt, smaller mesh.
    from scconsensus_tpu.robust.elastic import ElasticMeshSupervisor

    supervisor, mesh = ElasticMeshSupervisor.resolve(mesh)

    def _mesh():
        return supervisor.mesh if supervisor is not None else mesh

    if is_sparse(data):
        data = as_csr(data)
    elif is_jax(data):
        # Device-resident input (e.g. generated or loaded straight into
        # HBM): keep it there — forcing numpy here would pull the whole
        # matrix through the host link for nothing.
        import jax.numpy as jnp

        data = data.astype(jnp.float32)
    else:
        data = np.ascontiguousarray(data, dtype=np.float32)
    G, N = data.shape

    def _rows_dense(idx: np.ndarray) -> np.ndarray:
        """Dense (|idx|, N) gather of gene rows (sparse-safe)."""
        return rows_dense(data, idx)

    # Input-contract pre-flight (robust.contract): degenerate inputs —
    # shape mismatches, NaN/Inf in the matrix, labelings with no pairable
    # clusters — fail HERE with a one-line typed InputContractError
    # instead of a deep-stack crash; repair-policy findings land on the
    # robustness log. Self-measured, so the <2% overhead guard prices it.
    from scconsensus_tpu.robust import contract as robust_contract

    with robust_record.timed():
        robust_contract.preflight(data, labels, config)

    if supervisor is not None:
        # the sharded working set a shrink must re-lay-out: rides every
        # mesh transition's recovered_state_bytes
        supervisor.note_live_state(data)

    run_log = robust_record.current_run()
    if store.enabled:
        from scconsensus_tpu.utils.artifacts import input_fingerprint

        store.check_config(config.to_json(), inputs=input_fingerprint(data, labels))
        # Retry-budget persistence: seed budget_used from the store's
        # robust_state sidecar (a kill-and-resume cycle must not refresh
        # its allowance) and mirror every later take back into it.
        try:
            _, rb_meta = store.load("robust_state")
            if rb_meta.get("budget_used"):
                run_log.restore_budget(int(rb_meta["budget_used"]))
        except ValueError:
            pass  # quarantined sidecar: budget restarts, run continues
        run_log.set_budget_persist(
            lambda used: store.save("robust_state",
                                    meta={"budget_used": used})
        )
    # Stage-boundary recovery (robust.retry): each stage's compute runs
    # under the typed policy — transient/resource faults (injected or
    # real) retry with backoff instead of killing the run; device_lost
    # faults hand the elastic supervisor the shrink before the retry;
    # ValueError & co. stay fatal and propagate exactly as before. The
    # fault plan's ``stage:<name>`` sites fire at each attempt's entry.

    def _guard(fn, site, degrade=None):
        return robust_retry.call(
            fn, site, degrade=degrade,
            on_device_loss=(supervisor.loss_handler(site)
                            if supervisor is not None else None),
        )

    def _stage_cached(stage, fn):
        """store.cached with elastic mesh provenance: saves stamp the
        CURRENT mesh shape; resumes hand the stored stamp to the
        supervisor, which records shape-polymorphic shrinks."""
        if supervisor is None:
            return store.cached(stage, fn)
        return store.cached(
            stage, fn,
            meta_fn=lambda: {"mesh_shape": supervisor.shape_meta()},
            on_load_meta=lambda m: supervisor.note_artifact_meta(stage, m),
        )

    de_res = None
    if store.has("de"):
        try:
            # ArtifactCorrupt (checksum mismatch / truncated zip) is a
            # ValueError: the store has already quarantined the files,
            # and the stage recomputes below
            de_arrays, de_meta = store.load("de")
            if supervisor is not None:
                supervisor.note_artifact_meta("de", de_meta)
            de_res = PairwiseDEResult.from_store(de_arrays, de_meta)
            logger.info("stage de: resumed from artifact store")
        except ValueError as e:
            logger.warning("stage de: artifact unusable (%s); recomputing", e)
    if de_res is None:
        de_res = _guard(
            lambda: pairwise_de(data, labels, config, timer=timer,
                                mesh=_mesh(), store=store),
            site="stage:de",
        )
        if store.enabled:  # to_store() materializes every lazy device field
            de_arrays, de_meta = de_res.to_store()
            if supervisor is not None:
                de_meta["mesh_shape"] = supervisor.shape_meta()
            store.save("de", de_arrays, de_meta)
            # the covering artifact landed: the ladder's mid-stage
            # checkpoint blocks have served their purpose
            store.discard_prefix("de_wilcox_")

    with timer.stage("union") as rec:
        union = _guard(
            lambda: _stage_cached(
                "union",
                lambda: {"idx": de_gene_union(de_res,
                                              config.n_top_de_genes)},
            ),
            site="stage:union",
        )["idx"]
        rec["union_size"] = int(union.size)
        rec["per_pair_de_counts"] = de_res.de_counts().tolist()
        if de_res.skip_reasons:
            rec["skipped_pairs"] = de_res.skip_reasons
    if union.size < 2:
        raise ValueError(
            f"DE gene union has {union.size} genes — nothing to re-embed. "
            "Loosen q_val_thrs/log_fc_thrs or check cluster labels."
        )

    with timer.stage("embed") as rec:
        n_pcs = min(union.size, config.n_pcs)
        rec["n_pcs"] = n_pcs

        def _embed():
            import jax.numpy as jnp

            if config.distance == "pearson":
                # Correlation-distance variant (the reference's commented-out
                # alternative, R/reclusterDEConsensus.R:238-239): embed cells
                # as centered unit-norm expression vectors, where euclidean
                # distance = sqrt(2·(1−r)) — monotone in Pearson distance —
                # then reduce with PCA. Cluster geometry matches 1−r; absolute
                # tree heights differ by the monotone transform. jnp ops keep
                # a device-resident input on device (host input uploads the
                # small (|U|, N) gather, which PCA needed anyway).
                cols = jnp.asarray(_rows_dense(union))  # (|U|, N)
                c = cols - cols.mean(axis=0, keepdims=True)
                norm = jnp.linalg.norm(c, axis=0, keepdims=True)
                cells = (c / jnp.maximum(norm, 1e-12)).T  # (N, |U|)
            else:
                cells = _rows_dense(union).T
            from scconsensus_tpu.robust import (
                integrity as robust_integrity,
            )

            if robust_integrity.enabled():
                # audited embed (robust.integrity): same subspace
                # iteration, plus the basis-orthonormality residual and
                # the mean/components the sampled float64 ghost replay
                # verifies sampled score rows against — detection
                # raises typed silent_corruption HERE, inside the stage
                # guard and BEFORE the store save, so recompute-the-
                # unit can never persist a corrupted embedding
                from scconsensus_tpu.ops.pca import pca_scores_audited
                from scconsensus_tpu.robust.faults import corrupt_value

                jcells = jnp.asarray(cells)
                scores, ortho, pmean, pcomp = pca_scores_audited(
                    jcells, n_pcs
                )
                scores = corrupt_value("embed_scores", scores)
                robust_integrity.check_pca_basis("stage:embed", ortho)
                if robust_integrity.current().want_replay("pca", 0):
                    robust_integrity.replay_pca_rows(
                        "stage:embed", jcells, pmean, pcomp, scores,
                        n_rows=int(jcells.shape[0]),
                    )
            else:
                scores = pca_scores(jnp.asarray(cells), n_pcs)
            # declared crossing: tree/cuts/silhouette are host algorithms
            # today, so the (N, n_pcs) scores must land on host — the
            # TODO(item-2) boundary the device-resident-graph refactor
            # removes (obs.residency.BOUNDARIES)
            with residency.boundary("embed_scores_fetch"):
                return {"scores": np.asarray(scores)}

        def _embed_degrade(_attempt):
            # RESOURCE_EXHAUSTED in embed: free the pinned upload cache
            # before the retry — the (N, |U|) gather + PCA scratch is
            # usually what tipped HBM over
            from scconsensus_tpu.utils.devcache import clear_cache

            clear_cache()
            robust_record.note_degradation(
                "stage:embed", "evict-devcache",
                "dropped pinned device buffers before PCA retry",
            )

        embedding = _guard(
            lambda: _stage_cached("embed", _embed),
            site="stage:embed", degrade=_embed_degrade,
        )["scores"]
        if supervisor is not None:
            # the embedding joins the sharded working set (tree knn /
            # ring silhouette consume it on the mesh)
            supervisor.note_live_state(data, embedding)
        if obs_quality.enabled():
            # a NaN/Inf PCA score silently corrupts every downstream
            # distance/tree/cut — trip here, span-attributed
            obs_quality.check_array("embedding", embedding, span=rec)

    with timer.stage("tree", n_cells=N) as rec:
        approx = N > config.approx_threshold
        rec["approx"] = approx
        if config.approx_method not in ("pool", "knn"):
            raise ValueError(
                f"approx_method must be 'pool' or 'knn', got "
                f"{config.approx_method!r}"
            )
        # Landmark recluster policy (r7, ROADMAP item 1): above
        # max(approx_threshold, landmark_threshold) the "pool" branch runs
        # the sub-quadratic landmark engine (sketch-fitted Lloyd + Ward on
        # k ≪ N landmarks + jitted nearest-landmark cut propagation); at
        # or below it, the pre-r7 paths run byte-identically.
        lm_policy = (
            config.landmark_policy(N)
            if approx and config.approx_method == "pool" else None
        )

        def _tree():
            if approx and config.approx_method == "knn":
                # Leaf-level approximate path: ring-kNN graph (device) +
                # graph-restricted Ward agglomeration (host). Keeps per-cell
                # resolution, unlike pooling.
                from scconsensus_tpu.ops.knn_linkage import knn_ward_linkage

                t = knn_ward_linkage(embedding, k=config.knn_graph_k,
                                     mesh=_mesh())
                return {"merge": t.merge, "height": t.height, "order": t.order}
            if lm_policy is not None:
                from scconsensus_tpu.ops.pooling import landmark_ward_linkage

                t, assign, cents, info = landmark_ward_linkage(
                    embedding,
                    n_landmarks=lm_policy["k"],
                    sketch=lm_policy["sketch"],
                    seed=config.random_seed,
                    c=lm_policy["c"],
                    k_min=lm_policy["k_min"],
                    k_max=lm_policy["k_max"],
                    linkage=lm_policy["linkage"],
                    knn_k=lm_policy["knn_k"],
                    mesh=_mesh(),
                )
                return {"merge": t.merge, "height": t.height, "order": t.order,
                        "pool_assign": assign, "pool_centroids": cents,
                        "landmark_k": np.asarray(info["k_used"]),
                        "landmark_sketch": np.asarray(info["sketch"]),
                        # linkage engine as an int code so a RESUMED
                        # artifact stamps the tree it actually holds, not
                        # whatever today's policy would have picked
                        "landmark_knn_linkage": np.asarray(
                            1 if info["linkage"] == "knn" else 0)}
            if approx:
                from scconsensus_tpu.ops.pooling import pooled_ward_linkage

                t, assign, cents = pooled_ward_linkage(
                    embedding, n_centroids=config.n_pool_centroids,
                    seed=config.random_seed,
                )
                return {"merge": t.merge, "height": t.height, "order": t.order,
                        "pool_assign": assign, "pool_centroids": cents}
            t = ward_linkage(embedding)
            return {"merge": t.merge, "height": t.height, "order": t.order}

        tree_arrays = _guard(lambda: _stage_cached("tree", _tree),
                             site="stage:tree")
        tree = HClustTree(
            merge=tree_arrays["merge"],
            height=tree_arrays["height"],
            order=tree_arrays["order"],
        )
        pool_assign = tree_arrays.get("pool_assign")
        pool_centroids = tree_arrays.get("pool_centroids")
        # Branch actually taken comes from the ARTIFACT (resume from a
        # pre-landmark store must keep the legacy cut semantics), not from
        # the policy alone.
        landmark_used = "landmark_k" in tree_arrays
        landmark_info: Optional[Dict] = None
        if landmark_used:
            landmark_info = {
                "branch": "landmark",
                "k": int(tree_arrays["landmark_k"]),
                "sketch": int(tree_arrays["landmark_sketch"]),
                # threshold describes the run's POLICY (None on a resume
                # whose policy no longer selects landmark); linkage
                # describes the stored TREE itself
                "threshold": (lm_policy or {}).get("threshold"),
                "linkage": ("knn" if int(tree_arrays.get(
                    "landmark_knn_linkage", 0)) else "exact"),
            }
            rec["landmark"] = True
            rec["landmark_k"] = landmark_info["k"]
        elif lm_policy is not None:
            # policy wanted landmark but the cached artifact predates it
            rec["landmark"] = False

    dynamic_colors: Dict[str, np.ndarray] = {}
    dynamic_labels: Dict[str, np.ndarray] = {}
    deep_split_info: List[Dict] = []
    with timer.stage("cuts"):
        cut_weights = None
        if pool_assign is None:
            cut_points, cut_min_size = embedding, config.min_cluster_size
        elif landmark_used:
            # Landmark path: the cut runs on centroids but in CELL units —
            # per-landmark occupancy weights replace the legacy average-
            # occupancy rescale of min_cluster_size, so the reference size
            # floor holds exactly even when landmark occupancy is skewed.
            cut_points = pool_centroids
            cut_min_size = config.min_cluster_size
            cut_weights = np.bincount(
                pool_assign, minlength=pool_centroids.shape[0]
            ).astype(np.float64)
            from scconsensus_tpu.robust import (
                integrity as robust_integrity,
            )

            if robust_integrity.enabled():
                # landmark occupancy conservation at the CUT boundary:
                # the weights the size floor runs in must account for
                # every cell exactly once (segment-sum == N)
                robust_integrity.check_landmark_occupancy(
                    "stage:cuts", pool_assign,
                    pool_centroids.shape[0], N,
                )
        else:
            # treecut operates on centroids: scale the size floor by the
            # average pool occupancy (approximate-path semantics).
            avg_pool = max(N / pool_centroids.shape[0], 1.0)
            cut_points = pool_centroids
            cut_min_size = max(2, int(round(config.min_cluster_size / avg_pool)))

        def _cuts():
            out = {}
            for dsv in config.deep_split_values:
                cut_labels = cutree_hybrid(
                    tree,
                    cut_points,
                    deep_split=int(dsv),
                    min_cluster_size=cut_min_size,
                    pam_stage=config.pam_stage,
                    weights=cut_weights,
                )
                if pool_assign is not None:
                    cut_labels = cut_labels[pool_assign]
                out[f"ds{dsv}"] = cut_labels
            return out

        cut_arrays = _guard(lambda: _stage_cached("cuts", _cuts),
                            site="stage:cuts")
        for dsv in config.deep_split_values:
            cut_labels = cut_arrays[f"ds{dsv}"]
            key = f"deepsplit: {dsv}"
            dynamic_labels[key] = cut_labels
            dynamic_colors[key] = labels_to_colors(cut_labels)
            info = {"deep_split": int(dsv),
                    "n_clusters": int(len(set(cut_labels[cut_labels > 0].tolist())))}
            deep_split_info.append(info)

        if landmark_info is not None:
            # per-cut landmark occupancy: how many of the k landmarks each
            # cut actually uses (collapse telemetry for the quality section)
            occ = {}
            for dsv in config.deep_split_values:
                lab = cut_arrays[f"ds{dsv}"]
                occ[f"ds{dsv}"] = {
                    "landmarks_assigned": int(
                        np.unique(pool_assign[lab > 0]).size
                    ),
                    "n_landmarks": int(pool_centroids.shape[0]),
                }
            landmark_info["occupancy"] = occ
            if config.landmark_verify:
                # Diagnostic accuracy pin (tier-1 reads this stamp): run
                # the EXACT tree + cuts too and score ARI per deepSplit.
                # O(N²) by construction — mid-size verification runs only.
                from scconsensus_tpu.obs.regress import adjusted_rand_index
                from scconsensus_tpu.obs.trace import span as obs_span

                with obs_span("landmark_verify", n_cells=N):
                    exact_tree = ward_linkage(embedding)
                    ari = {}
                    for dsv in config.deep_split_values:
                        ex = cutree_hybrid(
                            exact_tree, embedding, deep_split=int(dsv),
                            min_cluster_size=config.min_cluster_size,
                            pam_stage=config.pam_stage,
                        )
                        lm = cut_arrays[f"ds{dsv}"]
                        m = (lm > 0) & (ex > 0)
                        ari[f"ds{dsv}"] = (
                            round(adjusted_rand_index(lm[m], ex[m]), 6)
                            if int(m.sum()) else None
                        )
                    landmark_info["ari_vs_exact"] = ari

    if config.compat.return_silhouette:
        with timer.stage("silhouette") as sil_rec:
            # excluded-cell masking (label 0 → −1), shared by every branch
            labs = [
                np.where(dynamic_labels[f"deepsplit: {dsv}"] > 0,
                         dynamic_labels[f"deepsplit: {dsv}"], -1)
                for dsv in config.deep_split_values
            ]
            # recovery wrapper: the branch ladder runs as _silhouette()
            # under the typed retry policy — idempotent (it only assigns
            # per-cut info keys), so a transient-fault retry recomputes
            # cleanly; the mesh reads fresh per attempt, so a device_lost
            # retry rides the supervisor's shrunk mesh (or the serial
            # branch once the mesh is gone)
            def _silhouette():
                mesh_now = _mesh()
                approx_si = N > config.approx_threshold and mesh_now is None
                if mesh_now is not None:
                    for info, lab in zip(deep_split_info, labs):
                        si, _per = mean_cluster_silhouette(
                            embedding, lab, mesh=mesh_now
                        )
                        info["silhouette"] = si
                elif approx_si:
                    # Past the approx threshold the exact O(N²) pass is
                    # the pipeline's scale tail (154 s at 100k; outright
                    # skipped at 1M in r5) — the pooled O(N·m) estimator
                    # reuses the tree stage's pool when one exists, so
                    # the 1M artifact reports a quality number for the
                    # cost of an (N, m) matmul stream.
                    from scconsensus_tpu.ops.silhouette import (
                        pooled_multi_cut_silhouette,
                    )

                    sil_rec["method"] = "pooled-estimator"
                    sil_rec["n_centroids"] = (
                        int(pool_centroids.shape[0])
                        if pool_centroids is not None
                        else config.silhouette_pool_centroids
                    )
                    # single-pooling contract: with a tree-stage pool
                    # (legacy or landmark) the estimator prices neighbors
                    # at THOSE centroids — zero extra k-means (span
                    # pool_builds counters assert this in tier-1)
                    sil_rec["pool_reused"] = pool_centroids is not None
                    for info, (si, _per) in zip(
                        deep_split_info,
                        pooled_multi_cut_silhouette(
                            embedding, labs,
                            n_centroids=config.silhouette_pool_centroids,
                            seed=config.random_seed,
                            centroids=pool_centroids,
                            assign=pool_assign,
                            sample=config.silhouette_sample,
                        ),
                    ):
                        info["silhouette"] = si
                        info["silhouette_method"] = "pooled-estimator"
                else:
                    # all cuts share one N² distance pass
                    from scconsensus_tpu.ops.silhouette import (
                        multi_cut_silhouette,
                    )

                    for info, (si, _per) in zip(
                        deep_split_info, multi_cut_silhouette(embedding,
                                                              labs)
                    ):
                        info["silhouette"] = si

            _guard(_silhouette, site="stage:silhouette")

    with timer.stage("nodg"):
        # per-cell number of detected genes; the reference's O(N·G)
        # interpreted loop (R/reclusterDEConsensus.R:272-275) is one
        # reduction. Declared crossing: the (N,) counts are a pipeline
        # output and must reach the host once.
        with residency.boundary("label_fetch"):
            nodg = _guard(lambda: sparse_nodg(data), site="stage:nodg")

    # Quality telemetry (obs.quality): the DE gate funnel, window-ladder
    # occupancy, cluster structure vs the input labeling, and any
    # numeric-sentinel trips — assembled into result.metrics["quality"]
    # (and from there onto bench/driver run records as the schema's
    # additive `quality` section). Never fatal: a quality failure must
    # not cost the science it describes.
    quality_section = None
    with timer.stage("quality") as qrec:
        try:
            if config.compat.return_silhouette and obs_quality.enabled():
                sils = np.array([
                    d["silhouette"] for d in deep_split_info
                    if d.get("silhouette") is not None
                ], np.float64)
                obs_quality.check_array("silhouette", sils, span=qrec,
                                        where="silhouette")
            quality_section = obs_quality.build_quality_section(
                de_result=de_res, config=config,
                dynamic_labels=dynamic_labels,
                deep_split_info=deep_split_info,
                input_labels=np.asarray(labels).astype(str),
                occupancy=obs_quality.occupancy_from_stage_records(
                    timer.records
                ),
                landmark=landmark_info,
                tracer=timer.tracer,
            )
            for k, v in (quality_section.get("de_funnel") or {}).get(
                    "total", {}).items():
                qrec.metrics.counter(k).add(float(v))
        except Exception as e:  # pragma: no cover - defensive
            timer.logger.warning("quality telemetry failed: %r", e)

    union_names = (
        np.asarray(gene_names)[union] if gene_names is not None else union.copy()
    )

    result = ReclusterResult(
        de_gene_union=union_names,
        de_gene_union_idx=union,
        cell_tree=tree,
        dynamic_colors=dynamic_colors,
        dynamic_labels=dynamic_labels,
        deep_split_info=deep_split_info,
        nodg=nodg,
        embedding=embedding,
        de=de_res,
        metrics=timer.as_dict(),
    )
    if quality_section is not None:
        result.metrics["quality"] = quality_section

    if config.plot_name:
        with timer.stage("report"), residency.boundary("label_fetch"):
            from scconsensus_tpu.report.de_heatmap import cell_type_de_plot

            cell_type_de_plot(
                data_matrix=np.asarray(_rows_dense(union)),
                nodg=nodg,
                cell_tree=tree,
                cluster_labels=np.asarray(labels).astype(str),
                dynamic_colors_list=dynamic_colors,
                gene_labels=union_names.astype(str),
                filename=config.plot_name,
            )
    if store.enabled:
        # the run COMPLETED: reset the persisted retry budget. The
        # robust_state ratchet exists so a kill-and-resume cycle cannot
        # refresh its allowance mid-run; a successful completion ENDS
        # the run, and the next run over this store starts fresh
        # (failure paths never reach here, so their ratchet stands).
        try:
            store.save("robust_state", meta={"budget_used": 0})
        except Exception:
            pass
    return result


def recluster_de_consensus(
    data_matrix: np.ndarray,
    consensus_cluster_labels: Sequence,
    method: str = "Wilcoxon",
    mean_scaling_factor: float = 5.0,
    q_val_thrs: float = 0.01,
    fc_thrs: float = 2.0,
    deep_split_values: Sequence[int] = (1, 2, 3, 4),
    min_cluster_size: int = 10,
    gene_names: Optional[Sequence[str]] = None,
    plot_name: Optional[str] = None,
    compat: Optional[CompatFlags] = None,
    mesh="auto",
    **kw,
) -> ReclusterResult:
    """Reference-shaped slow path (R/reclusterDEConsensus.R:20-29).

    ``method``: 'Wilcoxon' or 'edgeR' (case as in the reference). ``fc_thrs``
    is a ratio; the DE criterion uses log(fc_thrs) (natural log).
    """
    method_map = {"wilcoxon": "wilcoxon", "edger": "edger"}
    m = method_map.get(method.lower())
    if m is None:
        raise ValueError(f"Incorrect method chosen: {method!r} (Wilcoxon|edgeR)")
    config = ReclusterConfig(
        method=m,
        q_val_thrs=q_val_thrs,
        log_fc_thrs=math.log(fc_thrs),
        mean_scaling_factor=mean_scaling_factor,
        deep_split_values=tuple(int(v) for v in deep_split_values),
        min_cluster_size=min_cluster_size,
        plot_name=plot_name,
        compat=compat or CompatFlags(),
        **kw,
    )
    return refine(data_matrix, consensus_cluster_labels, config, gene_names,
                  mesh=mesh)


def recluster_de_consensus_fast(
    data_matrix: np.ndarray,
    consensus_cluster_labels: Sequence,
    method: str = "wilcox",
    q_val_thrs: float = 0.1,
    log_fc_thrs: float = 0.5,
    deep_split_values: Sequence[int] = (1, 2, 3, 4),
    min_cluster_size: int = 10,
    min_per_cent: float = 20.0,
    number_top_de_genes: int = 30,
    gene_names: Optional[Sequence[str]] = None,
    plot_name: Optional[str] = None,
    compat: Optional[CompatFlags] = None,
    mesh="auto",
    **kw,
) -> ReclusterResult:
    """Reference-shaped fast path (R/reclusterDEConsensusFast.R:22-33).

    Replaces the doParallel fan-out with the batched device engine; ``nCores``
    has no equivalent (parallelism is the engine's property, SURVEY.md §7).
    ``method``: wilcox | bimod | roc | t (Seurat test names).
    """
    config = ReclusterConfig(
        method=method.lower(),
        q_val_thrs=q_val_thrs,
        log_fc_thrs=log_fc_thrs,
        deep_split_values=tuple(int(v) for v in deep_split_values),
        min_cluster_size=min_cluster_size,
        min_pct=min_per_cent,
        n_top_de_genes=number_top_de_genes,
        plot_name=plot_name,
        compat=compat or CompatFlags(),
        **kw,
    )
    return refine(data_matrix, consensus_cluster_labels, config, gene_names,
                  mesh=mesh)
