from scconsensus_tpu.config import CompatFlags, ReclusterConfig
from scconsensus_tpu.models.pipeline import (
    ReclusterResult,
    recluster_de_consensus,
    recluster_de_consensus_fast,
    refine,
)

__all__ = [
    "CompatFlags",
    "ReclusterConfig",
    "ReclusterResult",
    "recluster_de_consensus",
    "recluster_de_consensus_fast",
    "refine",
]
