"""Shared runner machinery for the scenario modules.

Every scenario runner does the same spine — build input labelings,
chain them through the paper's contingency consensus, run the fast
refine, and fold the result's metrics (quality / residency / spans /
robustness) into a :class:`~scconsensus_tpu.workloads.ScenarioOutcome`.
This module owns that spine so four runners cannot drift apart on how
they call the pipeline or assemble evidence.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = [
    "consensus_of",
    "kmeans_labeling",
    "refine_consensus",
    "final_labels",
    "outcome_from_result",
]


def consensus_of(*labelings):
    """Chain ``plot_contingency_table`` across 2+ labelings — the same
    multi-tool grammar bench._consensus uses (3-way consensus is
    consensus(consensus(l1, l2), l3))."""
    from scconsensus_tpu import plot_contingency_table

    out = labelings[0]
    for nxt in labelings[1:]:
        out = plot_contingency_table(out, nxt, filename=None)
    return out


def kmeans_labeling(x: np.ndarray, k: int, seed: int = 0,
                    n_iter: int = 12, prefix: str = "k") -> np.ndarray:
    """Deterministic device k-means labeling of the rows of ``x``.

    Seeded center init (distinct random rows) + the blocked Lloyd the
    landmark recluster uses (``ops.pooling._lloyd``), so modality
    clusterings are jitted device programs with only the (N,) int
    assignment crossing to host (declared ``workload_inputs``
    boundary). Returns string labels ``f"{prefix}{cid}"``.
    """
    import jax
    import jax.numpy as jnp

    from scconsensus_tpu.obs.residency import boundary
    from scconsensus_tpu.ops.pooling import _lloyd

    n = int(x.shape[0])
    k = int(min(k, n))
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x4B]))
    init_idx = rng.choice(n, size=k, replace=False)
    with boundary("workload_inputs"):
        xd = jnp.asarray(np.asarray(x, np.float32))
        _, assign = _lloyd(xd, xd[init_idx], n_iter=n_iter)
        assign_h = np.asarray(jax.device_get(assign))
    return np.array([f"{prefix}{int(c)}" for c in assign_h])


def pca_embed(data: np.ndarray, n_pcs: int, seed: int = 0) -> np.ndarray:
    """(N, n_pcs) rSVD-PCA scores of a (G, N) expression matrix — the
    same ``ops.pca`` path the pipeline's embed stage uses."""
    import jax.numpy as jnp

    from scconsensus_tpu.obs.residency import boundary
    from scconsensus_tpu.ops.pca import pca_scores

    cells = np.asarray(data, np.float32).T
    n_pcs = int(min(n_pcs, cells.shape[1], max(2, cells.shape[0] - 1)))
    with boundary("workload_inputs"):
        return np.asarray(pca_scores(jnp.asarray(cells), n_pcs,
                                     seed=seed))


def refine_consensus(data: np.ndarray, consensus, smoke: bool,
                     seed: int = 7, **kw):
    """The zoo's one refine call: fast-path wilcox with scenario-sized
    settings (smoke keeps the deepSplit ladder short so all four
    scenarios fit the tier-1 pytest lane). Returns (elapsed_s, result).
    """
    from scconsensus_tpu import recluster_de_consensus_fast

    args: Dict[str, Any] = dict(
        method="wilcox", q_val_thrs=0.1, log_fc_thrs=0.25,
        min_cluster_size=10, number_top_de_genes=20,
        deep_split_values=(1, 2) if smoke else (1, 2, 3, 4),
        random_seed=seed,
    )
    args.update(kw)
    t0 = time.perf_counter()
    result = recluster_de_consensus_fast(data, consensus, **args)
    return time.perf_counter() - t0, result


def final_labels(result) -> np.ndarray:
    """The last deepSplit cut — the labeling every scenario scores."""
    return np.asarray(
        result.dynamic_labels[list(result.dynamic_labels)[-1]]
    )


def outcome_from_result(name: str, params: Dict[str, Any], smoke: bool,
                        elapsed_s: float, result,
                        scenario_scores: Dict[str, Any],
                        metric: str, value: float, unit: str,
                        extra: Optional[Dict[str, Any]] = None,
                        serving: Optional[Dict[str, Any]] = None,
                        spans: Optional[List[Dict[str, Any]]] = None):
    """Fold a refine result + scenario scoring block into one
    ScenarioOutcome: the pipeline's own quality section gains the
    ``scenario`` block (validated by obs.quality), the top-level
    ``scenario`` record section carries the shape identity."""
    from scconsensus_tpu.obs.quality import validate_scenario_scores
    from scconsensus_tpu.workloads import (
        ScenarioOutcome,
        build_scenario_section,
    )

    scenario_scores = dict(scenario_scores)
    scenario_scores.setdefault("name", name)
    validate_scenario_scores(scenario_scores)
    metrics = (result.metrics or {}) if result is not None else {}
    quality = dict(metrics.get("quality") or {})
    quality["scenario"] = scenario_scores
    ex = dict(extra or {})
    ex["elapsed_s"] = round(float(elapsed_s), 3)
    return ScenarioOutcome(
        name=name,
        metric=metric,
        value=value,
        unit=unit,
        scenario=build_scenario_section(name, params, smoke),
        extra=ex,
        spans=(spans if spans is not None
               else list(metrics.get("spans") or [])),
        quality=quality,
        serving=serving,
        robustness=metrics.get("robustness"),
        integrity=metrics.get("integrity"),
        residency=metrics.get("residency"),
        kernels=metrics.get("kernels"),
    )
