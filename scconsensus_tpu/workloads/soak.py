"""Runnable workload-zoo soak worker: the chaos harness's scenario
workload and the cross-shape auditor's topology unit of replay.

    python -m scconsensus_tpu.workloads.soak --dir DIR [--summary PATH]
        [--cells N] [--genes G] [--clusters K] [--samples S] [--seed S]
        [--fresh] [--topo] [--covers C] [--dim D]

Default mode — the multi-sample scenario as a kill-resume unit: the
scenario's dataset and input labelings are pure functions of the seed
(``workloads.data.multi_sample_dataset`` + the per-sample unaligned
clustering), and the refine runs over a DURABLE artifact store under
``DIR/stages``. A run SIGKILLed mid-pipeline (``SCC_FAULT_PLAN`` kill
class at a stage site) leaves its completed stage artifacts behind; the
next run over the same DIR adopts them (``resumed_stages`` in the
summary) and must land a ``labels_sha`` byte-identical to an
uninterrupted reference — the ``workload_zoo`` entry of
``tools/chaos_run.py``'s soak matrix checks exactly that, proving
kill-resume identity beyond the anchor shapes. The summary's ``record``
carries the validated top-level ``scenario`` section plus the
``quality.scenario`` scoring block (per-batch ARI + batch-mixing), so
the chaos evidence is scenario-stamped like any bench run.

``--topo`` mode — the topology clusterer as a determinism unit: a
seeded gaussian embedding through ``workloads.topology
.topology_cluster``, summary = sha256 over the label strings.
``tools/verify_run.py``'s topo shapes replay this worker under
different execution shapes (forced 8-virtual-device mesh, the scan
kernel family) and pin ONE sha across all of them.

Exit code: 0 = the run completed and its record validates; 1 = not.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import sys
import time
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["run_workload_soak", "run_topo_audit", "main"]

# the pipeline's durable stage artifacts, in stage order — what a
# resumed run can adopt from a killed one
_STAGES = ("de", "embed", "tree", "cuts")


def run_workload_soak(
    workdir: str, n_cells: int = 3000, n_genes: int = 150,
    n_clusters: int = 3, n_samples: int = 2, seed: int = 7,
    fresh: bool = False,
) -> Dict[str, Any]:
    """One deterministic multi-sample scenario run over a durable
    artifact store; returns the summary dict (module doc)."""
    from scconsensus_tpu.config import ReclusterConfig
    from scconsensus_tpu.models.pipeline import refine
    from scconsensus_tpu.obs.export import (
        build_run_record,
        validate_run_record,
    )
    from scconsensus_tpu.stream.soak import _labels_sha
    from scconsensus_tpu.workloads import build_scenario_section
    from scconsensus_tpu.workloads.common import final_labels
    from scconsensus_tpu.workloads.multisample import (
        multi_sample_inputs,
        multi_sample_scores,
    )

    stages_dir = os.path.join(workdir, "stages")
    if fresh:
        shutil.rmtree(stages_dir, ignore_errors=True)

    def _stage_stats() -> Dict[str, tuple]:
        out = {}
        for s in _STAGES:
            try:
                st = os.stat(os.path.join(stages_dir, f"{s}.npz"))
                out[s] = (st.st_mtime_ns, st.st_size, st.st_ino)
            except OSError:
                pass
        return out

    pre_stats = _stage_stats()

    params = dict(n_cells=n_cells, n_genes=n_genes,
                  n_clusters=n_clusters, n_samples=n_samples, seed=seed)
    data, truth, batches, _, consensus = multi_sample_inputs(params)
    config = ReclusterConfig(
        method="wilcox", q_val_thrs=0.1, log_fc_thrs=0.25, min_pct=5.0,
        deep_split_values=(1, 2), min_cluster_size=10,
        n_top_de_genes=20, random_seed=seed, artifact_dir=stages_dir,
    )
    t0 = time.perf_counter()
    result = refine(data, consensus, config)
    wall = time.perf_counter() - t0

    # ADOPTION evidence, not mere pre-existence: a stage counts as
    # resumed only when its artifact existed before the run AND its
    # stat is byte-for-byte unchanged after it. A quarantined-and-
    # recomputed artifact (the pipeline renames the corrupt file aside
    # and os.replace's a fresh one) gets a new mtime/inode, so a silent
    # from-zero recompute can never masquerade as a resume.
    post_stats = _stage_stats()
    adopted = [s for s in _STAGES
               if s in pre_stats and post_stats.get(s) == pre_stats[s]]

    final = final_labels(result)
    scores = multi_sample_scores(final, truth, batches)
    quality = dict((result.metrics or {}).get("quality") or {})
    quality["scenario"] = scores
    rec = build_run_record(
        metric=f"workload-zoo soak: {n_cells}-cell multi_sample refine",
        value=round(wall, 3), unit="seconds",
        extra={"config": "workload-soak", "platform": "cpu",
               "resumed_stages": list(adopted)},
        spans=result.metrics.get("spans") or [],
        quality=quality,
        scenario=build_scenario_section("multi_sample", params,
                                        smoke=True),
        robustness=result.metrics.get("robustness"),
        integrity=result.metrics.get("integrity"),
    )
    invalid = None
    try:
        validate_run_record(rec)
    except ValueError as e:
        invalid = str(e)
    have_all_cuts = all(
        f"deepsplit: {d}" in result.dynamic_labels
        for d in config.deep_split_values
    )
    return {
        "ok": bool(invalid is None and have_all_cuts),
        "invalid": invalid,
        "wall_s": round(wall, 3),
        "labels_sha": _labels_sha(result.dynamic_labels),
        "resumed_stages": list(adopted),
        "per_batch_ari": scores["per_batch_ari"],
        "record": rec,
    }


def run_topo_audit(
    workdir: str, n_cells: int = 2000, dim: int = 8,
    n_clusters: int = 4, n_covers: int = 12, seed: int = 7,
) -> Dict[str, Any]:
    """One deterministic topology clustering of a seeded gaussian
    embedding; ``labels_sha`` must be invariant across execution shapes
    (the verify_run topo family's contract)."""
    from scconsensus_tpu.workloads.topology import topology_cluster

    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x7070]))
    centers = rng.normal(0.0, 5.0, size=(n_clusters, dim))
    lab = rng.integers(0, n_clusters, size=n_cells)
    x = (centers[lab]
         + rng.normal(0.0, 0.8, size=(n_cells, dim))).astype(np.float32)
    t0 = time.perf_counter()
    labels = topology_cluster(x, n_covers=n_covers, seed=seed)
    wall = time.perf_counter() - t0
    sha = hashlib.sha256("\n".join(labels.tolist()).encode()).hexdigest()
    return {
        "ok": True,
        "wall_s": round(wall, 3),
        "labels_sha": sha,
        "n_topo_clusters": len(set(labels.tolist())),
    }


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description="workload-zoo soak worker")
    ap.add_argument("--dir", required=True, help="work directory")
    ap.add_argument("--cells", type=int, default=3000)
    ap.add_argument("--genes", type=int, default=150)
    ap.add_argument("--clusters", type=int, default=3)
    ap.add_argument("--samples", type=int, default=2)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--summary", default=None)
    ap.add_argument("--fresh", action="store_true",
                    help="drop any durable stage artifacts first")
    ap.add_argument("--topo", action="store_true",
                    help="topology-determinism audit mode (verify_run)")
    ap.add_argument("--covers", type=int, default=12)
    ap.add_argument("--dim", type=int, default=8)
    args = ap.parse_args(argv)

    summary_path = args.summary or os.path.join(
        args.dir, "WORKLOAD_SOAK_SUMMARY.json"
    )
    os.makedirs(args.dir, exist_ok=True)
    if args.topo:
        summary = run_topo_audit(
            args.dir, n_cells=args.cells, dim=args.dim,
            n_clusters=args.clusters, n_covers=args.covers,
            seed=args.seed,
        )
    else:
        summary = run_workload_soak(
            args.dir, n_cells=args.cells, n_genes=args.genes,
            n_clusters=args.clusters, n_samples=args.samples,
            seed=args.seed, fresh=args.fresh,
        )
    with open(summary_path, "w") as f:
        json.dump(summary, f, indent=1, default=str)
    print(json.dumps({
        "ok": summary["ok"],
        "labels_sha": summary["labels_sha"][:16],
        "resumed_stages": summary.get("resumed_stages"),
    }))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
