"""Workload zoo — the scenario subsystem (ROADMAP item 4).

The bench matrix grew up cite8k/tm100k/brain1m-shaped: one data
geometry at three sizes, every quality/robustness/perf claim
generalizing over exactly that shape. This package owns everything a
*scenario* is made of — dataset generation, input-labeling
construction, and scenario-specific scoring — so a new workload is a
registered config with its own ledger baseline, never a one-off
script. Four scenarios ship:

  multi_sample    cells drawn from S samples with per-sample
                  shift/library-size confounds; consensus across the
                  samples' own (unaligned) clusterings; scored with
                  per-batch ARI + batch-mixing entropy
                  (``obs.quality`` owns the math).
  cite_dual       dual-modality CITE-seq: an ADT-like low-dimensional
                  modality clustered coarsely × an RNA modality
                  clustered finely — the paper's supervised/
                  unsupervised pair generalized to modalities.
  atlas_transfer  fit on an atlas split, freeze the consensus model
                  (serve.model), classify the query split through the
                  serve driver as a BATCH workload — serve throughput
                  and p99 land on a non-anchor shape.
  topo_inputs     the Two-Tier-Mapper-style topology clusterer
                  (``workloads.topology``; arXiv:1801.01841 flavor)
                  as the unsupervised consensus input.

Each scenario declares a ``full`` parameter set (the bench-key shape)
and a ``smoke`` set (≤5k cells — the tier-1 pytest lane). ``bench.py``
dispatches ``kind="scenario"`` configs here; records carry a validated
top-level ``scenario`` section (:func:`validate_scenario`) plus a
``quality.scenario`` scoring block (``obs.quality.
validate_scenario_scores``).

Module-level imports stay jax-free (the bench orchestrator and the
jax-free export validators import this package); scenario runners lazy-
import their compute.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, List, Optional

__all__ = [
    "Scenario",
    "ScenarioOutcome",
    "SCENARIOS",
    "get_scenario",
    "scenario_names",
    "run_scenario",
    "validate_scenario",
]


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One registered scenario: the runner module plus its two shapes."""

    name: str
    doc: str
    unit: str
    runner_module: str          # lazy-imported; must expose run(params)
    full: Dict[str, Any]
    smoke: Dict[str, Any]


@dataclasses.dataclass
class ScenarioOutcome:
    """What a scenario runner hands back to bench / tests: the headline
    plus every record section the scenario produced. ``scenario`` is the
    validated top-level record section; ``quality`` carries the
    scenario scoring block under ``quality["scenario"]``."""

    name: str
    metric: str
    value: float
    unit: str
    scenario: Dict[str, Any]
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)
    spans: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    quality: Optional[Dict[str, Any]] = None
    serving: Optional[Dict[str, Any]] = None
    robustness: Optional[Dict[str, Any]] = None
    integrity: Optional[Dict[str, Any]] = None
    residency: Optional[Dict[str, Any]] = None
    kernels: Optional[Dict[str, Any]] = None


SCENARIOS: Dict[str, Scenario] = {
    "multi_sample": Scenario(
        name="multi_sample",
        doc="S-sample batch-effect data, consensus across per-sample "
            "clusterings, per-batch ARI + batch-mixing entropy scoring",
        unit="seconds",
        runner_module="scconsensus_tpu.workloads.multisample",
        full=dict(n_cells=100_000, n_genes=3000, n_clusters=12,
                  n_samples=4, seed=7),
        smoke=dict(n_cells=4000, n_genes=300, n_clusters=4,
                   n_samples=2, seed=7),
    ),
    "cite_dual": Scenario(
        name="cite_dual",
        doc="dual-modality CITE-seq: ADT clustered coarse × RNA "
            "clustered fine as the consensus input pair",
        unit="seconds",
        runner_module="scconsensus_tpu.workloads.citeseq",
        full=dict(n_cells=40_000, n_genes=8000, n_adt=40, k_fine=12,
                  k_coarse=5, seed=7),
        smoke=dict(n_cells=3000, n_genes=300, n_adt=16, k_fine=6,
                   k_coarse=3, seed=7),
    ),
    "atlas_transfer": Scenario(
        name="atlas_transfer",
        doc="fit on an atlas split, classify the query split through "
            "the frozen-model serve path as a batch workload",
        unit="cells/sec",
        runner_module="scconsensus_tpu.workloads.atlas",
        full=dict(n_atlas=20_000, n_query=60_000, n_genes=3000,
                  n_clusters=10, cells_per=512, seed=7),
        smoke=dict(n_atlas=2500, n_query=2000, n_genes=300,
                   n_clusters=5, cells_per=128, seed=7),
    ),
    "topo_inputs": Scenario(
        name="topo_inputs",
        doc="Mapper-style topology clusterer (kNN cover -> local "
            "clustering -> nerve merge) as the unsupervised consensus "
            "input",
        unit="seconds",
        runner_module="scconsensus_tpu.workloads.topo_scenario",
        full=dict(n_cells=50_000, n_genes=3000, n_clusters=10,
                  n_covers=32, seed=7),
        smoke=dict(n_cells=3000, n_genes=300, n_clusters=4,
                   n_covers=12, seed=7),
    ),
}


def scenario_names() -> List[str]:
    return sorted(SCENARIOS)


def get_scenario(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r} (known: {scenario_names()})"
        ) from None


def run_scenario(name: str, overrides: Optional[Dict[str, Any]] = None,
                 smoke: bool = False,
                 workdir: Optional[str] = None) -> ScenarioOutcome:
    """Run one registered scenario end to end.

    ``smoke`` picks the ≤5k-cell parameter set (the tier-1 lane);
    ``overrides`` lays user/bench keys over the chosen set. ``workdir``
    is for scenarios with durable artifacts (atlas_transfer's frozen
    model) — None means an ephemeral temp dir.
    """
    sc = get_scenario(name)
    params = dict(sc.smoke if smoke else sc.full)
    params.update(overrides or {})
    mod = importlib.import_module(sc.runner_module)
    out = mod.run(params, smoke=smoke, workdir=workdir)
    out.scenario.setdefault("name", name)
    out.scenario["smoke"] = bool(smoke)
    return out


def build_scenario_section(name: str, params: Dict[str, Any],
                           smoke: bool = False) -> Dict[str, Any]:
    """The top-level ``scenario`` record section: which scenario ran,
    at what shape. Scalars only — scoring lives in
    ``quality["scenario"]`` where the quality validators can hold it to
    the same standard as every other quality block."""
    return {
        "name": name,
        "smoke": bool(smoke),
        "params": {
            k: v for k, v in params.items()
            if isinstance(v, (int, float, str, bool))
        },
    }


def validate_scenario(sc: Dict[str, Any]) -> None:
    """Structural validation of a record's top-level ``scenario``
    section (jax-free; ``obs.export.validate_run_record`` calls this).
    Raises ValueError on the first violation."""
    if not isinstance(sc, dict):
        raise ValueError("scenario section: must be an object")
    name = sc.get("name")
    if not isinstance(name, str) or not name:
        raise ValueError("scenario section: name must be a non-empty "
                         "string")
    if name not in SCENARIOS:
        raise ValueError(
            f"scenario section: unknown scenario {name!r} "
            f"(registered: {scenario_names()})"
        )
    params = sc.get("params")
    if not isinstance(params, dict) or not params:
        raise ValueError("scenario section: params must be a non-empty "
                         "object")
    for k, v in params.items():
        if not isinstance(v, (int, float, str, bool)):
            raise ValueError(
                f"scenario section: params[{k!r}] must be a JSON "
                f"scalar, got {type(v).__name__}"
            )
    if "smoke" in sc and not isinstance(sc["smoke"], bool):
        raise ValueError("scenario section: smoke must be a bool")
