"""Input-labeling construction strategies.

``bench._labelings`` used to hard-code ONE recipe — every input
labeling a perturbation of the planted truth — which made "two
different labelings of the same cells" (the paper's whole premise)
synthetic in the weakest sense. The recipe now lives here as the named
``truth_perturb`` strategy among several, and bench delegates to it
verbatim: the seeds, flip fractions, coarsening, and prefixes are
byte-for-byte the historical ones, so the existing bench keys'
numeric-fingerprint pins (evidence/NUMERIC_PINS.json + per-key ledger
history) stay stable across the move.

Other strategies build labelings from structure rather than truth:
``per_sample`` fragments the unsupervised labeling by sample (cluster
ids are sample-local — the multi-sample scenario's unaligned input),
and the topology clusterer (``workloads.topology``) derives one from
data geometry alone.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

__all__ = [
    "truth_perturb",
    "per_sample_unsupervised",
    "STRATEGIES",
]


def truth_perturb(truth: np.ndarray, n_clusters: int,
                  n_way: int = 2) -> List[np.ndarray]:
    """The historical bench recipe, moved verbatim (byte-stable):
    a 5 %-flip "supervised" labeling, a 10 %-flip coarsened
    "unsupervised" labeling, and 8 %-flip extras for n_way > 2."""
    from scconsensus_tpu.utils.synthetic import noisy_labeling

    labelings = [noisy_labeling(truth, 0.05, seed=1, prefix="sup")]
    labelings.append(noisy_labeling(
        truth, 0.10, n_out_clusters=max(2, n_clusters - 4), seed=2,
        prefix="uns"
    ))
    for i in range(n_way - 2):
        labelings.append(
            noisy_labeling(truth, 0.08, seed=3 + i, prefix=f"t{i}")
        )
    return labelings


def per_sample_unsupervised(truth: np.ndarray, batches: np.ndarray,
                            flip_frac: float = 0.08,
                            seed: int = 0) -> np.ndarray:
    """An UNALIGNED per-sample clustering: each sample's cells are
    labeled by an independent noisy clustering whose ids carry a
    sample-local prefix (``s<b>c<k>``), so no label is shared across
    samples — the consensus layer has to reconcile them through the
    contingency grammar, exactly the multi-sample integration problem.
    Deterministic in (truth, batches, seed)."""
    from scconsensus_tpu.utils.synthetic import noisy_labeling

    batches = np.asarray(batches)
    out = np.empty(truth.shape[0], dtype=object)
    for b in sorted(int(v) for v in np.unique(batches)):
        sel = batches == b
        out[sel] = noisy_labeling(
            truth[sel], flip_frac, seed=seed + 17 * (b + 1),
            prefix=f"s{b}c",
        )
    return out.astype(str)


# name -> callable; signatures differ by what a strategy needs (truth,
# batches, data geometry), so the registry documents availability
# rather than enforcing one calling convention.
STRATEGIES: Dict[str, object] = {
    "truth_perturb": truth_perturb,
    "per_sample": per_sample_unsupervised,
}
