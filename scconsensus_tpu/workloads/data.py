"""Scenario dataset generators (the workload zoo's data half).

The anchor bench configs all draw from ONE generator
(``utils.synthetic.synthetic_scrna``) — one geometry, three sizes. The
zoo's scenarios need data with *structure the anchors lack*: per-sample
batch confounds, a second (ADT-like) modality nested under the RNA
clusters, and an atlas/query split with a seeded distribution. Every
generator here is a pure function of its arguments (numpy RNG seeded
per call), so scenario runs replay byte-identically — the property the
chaos kill-resume plan and the ledger fingerprints lean on.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

__all__ = [
    "multi_sample_dataset",
    "cite_seq_dataset",
    "atlas_query_dataset",
]


def multi_sample_dataset(
    n_cells: int,
    n_genes: int,
    n_clusters: int,
    n_samples: int,
    seed: int = 7,
    batch_shift: float = 0.8,
    libsize_spread: float = 0.5,
    batch_gene_frac: float = 0.25,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """S-sample scRNA data with per-sample shift + library-size confounds.

    Cells carry a planted biological truth (the shared cluster
    structure) AND a sample id; each sample perturbs the raw counts two
    ways before normalization: a per-sample multiplicative shift on a
    random ``batch_gene_frac`` subset of genes (technical batch effect,
    magnitude ``batch_shift`` on the log scale) and a per-sample
    library-size factor (``exp(N(0, libsize_spread))``). The consensus
    layer's job on this data is to recover the truth ACROSS samples —
    scored with per-batch ARI + batch-mixing entropy (obs.quality).

    Returns ``(data (G, N) f32 log-normalized, truth (N,) int,
    batches (N,) int)``.
    """
    from scconsensus_tpu.utils.synthetic import synthetic_scrna

    counts, truth, _ = synthetic_scrna(
        n_genes=n_genes, n_cells=n_cells, n_clusters=n_clusters,
        n_markers_per_cluster=min(40, n_genes // max(n_clusters, 1)),
        seed=seed, log_normalize=False,
    )
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x5A3]))
    # samples assigned independently of truth: every sample sees every
    # cluster (the integration problem, not a confounded design)
    batches = rng.integers(0, n_samples, size=n_cells)
    counts = np.asarray(counts, np.float64)
    for b in range(n_samples):
        brng = np.random.default_rng(
            np.random.SeedSequence([seed, 0x5A3, b + 1])
        )
        sel = batches == b
        if not sel.any():
            continue
        # technical gene shift: a per-sample subset of genes is scaled
        # up/down — the classic probe/chemistry batch signature
        n_hit = max(int(n_genes * batch_gene_frac), 1)
        hit = brng.choice(n_genes, size=n_hit, replace=False)
        shift = np.exp(brng.normal(0.0, batch_shift, size=n_hit))
        counts[np.ix_(hit, np.nonzero(sel)[0])] *= shift[:, None]
        # library-size confound: whole-sample depth factor
        counts[:, sel] *= float(np.exp(brng.normal(0.0, libsize_spread)))
    libsize = np.maximum(counts.sum(axis=0, keepdims=True), 1.0)
    data = np.log1p(counts / libsize * 2000.0).astype(np.float32)
    return data, truth, batches


def cite_seq_dataset(
    n_cells: int,
    n_genes: int,
    n_adt: int,
    k_coarse: int,
    k_fine: int,
    seed: int = 7,
    adt_sep: float = 3.0,
    adt_noise: float = 0.8,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Dual-modality CITE-seq-like data: RNA (fine) + ADT (coarse).

    Truth is hierarchical: ``k_coarse`` coarse lineages, each split into
    fine subclusters (``k_fine`` total, ``k_fine >= k_coarse``). The RNA
    modality carries the FINE structure (marker blocks per fine
    cluster, the usual NB generator); the ADT modality is a
    low-dimensional (``n_adt`` proteins) gaussian readout of the COARSE
    lineage only — surface proteins distinguish lineages, not
    subclusters. Clustering ADT coarsely and RNA finely yields the
    paper's supervised/unsupervised pair generalized to modalities.

    Returns ``(rna (G, N) f32 log-normalized, adt (A, N) f32,
    truth_fine (N,), truth_coarse (N,))``.
    """
    if k_fine < k_coarse:
        raise ValueError(
            f"cite_seq_dataset: k_fine={k_fine} < k_coarse={k_coarse}"
        )
    from scconsensus_tpu.utils.synthetic import synthetic_scrna

    rna, truth_fine, _ = synthetic_scrna(
        n_genes=n_genes, n_cells=n_cells, n_clusters=k_fine,
        n_markers_per_cluster=min(40, n_genes // max(k_fine, 1)),
        seed=seed, log_normalize=True,
    )
    # fine -> coarse: contiguous blocks of fine clusters share a lineage
    fine_to_coarse = (np.arange(k_fine) * k_coarse) // k_fine
    truth_coarse = fine_to_coarse[truth_fine]
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0xC17E]))
    proto = rng.normal(0.0, adt_sep, size=(k_coarse, n_adt))
    adt = (proto[truth_coarse]
           + rng.normal(0.0, adt_noise, size=(n_cells, n_adt)))
    # ADT counts are non-negative and roughly log-scale in real data;
    # softplus keeps the geometry while staying positive
    adt = np.log1p(np.exp(np.clip(adt, -30.0, 30.0))).astype(np.float32)
    return rna, adt.T.copy(), truth_fine, truth_coarse


def atlas_query_dataset(
    n_atlas: int,
    n_query: int,
    n_genes: int,
    n_clusters: int,
    seed: int = 7,
    center_scale: float = 4.0,
    noise: float = 0.6,
    query_drift: float = 0.15,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Atlas/query split over one planted gaussian population.

    Both splits draw from the same ``n_clusters`` centers; query cells
    additionally carry a small global drift (``query_drift`` ×
    ``noise``) so transfer is nontrivial but inside the frozen model's
    drift calibration. Atlas labels are 1-based (the serve model's
    label convention — 0 is the unassigned marker).

    Returns ``(atlas (n_atlas, G) f32, atlas_labels (n_atlas,) int
    1..K, query (n_query, G) f32, query_truth (n_query,) int 1..K)``.
    """
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0xA7145]))
    centers = rng.normal(0.0, center_scale, size=(n_clusters, n_genes))

    def _draw(n: int, drift: float) -> Tuple[np.ndarray, np.ndarray]:
        lab = rng.integers(0, n_clusters, size=n)
        x = (centers[lab]
             + rng.normal(0.0, noise, size=(n, n_genes))
             + drift * noise)
        return np.asarray(x, np.float32), lab + 1

    atlas, atlas_labels = _draw(n_atlas, 0.0)
    query, query_truth = _draw(n_query, query_drift)
    return atlas, atlas_labels, query, query_truth
