"""Scenario runner: topology-based unsupervised consensus input.

The Two-Tier-Mapper-style cover-and-cluster labeler
(``workloads.topology``) supplies the unsupervised half of the paper's
pair — a labeling derived from data *geometry* (overlapping cover →
local two-means → nerve components), not from a truth perturbation.
The runner also REPLAYS the topology clusterer on the same embedding
and records whether the two labelings are identical: the labeler is a
pure function of its inputs by contract, and the scenario record
carries that claim as measured evidence (``topo_replay_identical``),
with the cross-shape angle covered by ``tools/verify_run.py``'s topo
shapes.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

__all__ = ["run"]


def run(params: Dict[str, Any], smoke: bool = False,
        workdir: Optional[str] = None):
    from scconsensus_tpu.obs.regress import adjusted_rand_index
    from scconsensus_tpu.utils.synthetic import (
        noisy_labeling,
        synthetic_scrna,
    )
    from scconsensus_tpu.workloads.common import (
        consensus_of,
        final_labels,
        outcome_from_result,
        pca_embed,
        refine_consensus,
    )
    from scconsensus_tpu.workloads.topology import topology_cluster

    seed = int(params.get("seed", 7))
    n_clusters = int(params["n_clusters"])
    n_covers = int(params["n_covers"])
    data, truth, _ = synthetic_scrna(
        n_genes=int(params["n_genes"]), n_cells=int(params["n_cells"]),
        n_clusters=n_clusters,
        n_markers_per_cluster=min(
            40, int(params["n_genes"]) // max(n_clusters, 1)),
        seed=seed, log_normalize=True,
    )
    sup = noisy_labeling(truth, 0.05, seed=seed + 1, prefix="sup")
    # embed once, cluster twice: the replay prices only the topology
    # labeler, not the shared PCA
    emb = pca_embed(data, n_pcs=10, seed=seed)
    topo = topology_cluster(emb, n_covers=n_covers, seed=seed)
    topo_again = topology_cluster(emb, n_covers=n_covers, seed=seed)
    replay_identical = bool(np.array_equal(topo, topo_again))

    consensus = consensus_of(sup, topo)
    elapsed, result = refine_consensus(data, consensus, smoke, seed=seed)

    final = final_labels(result)
    scores = {
        "metrics": {
            "topo_ari_vs_truth": round(
                adjusted_rand_index(topo, truth), 6),
            "final_ari_vs_truth": round(
                adjusted_rand_index(final, truth), 6),
            "n_topo_clusters": float(len(set(topo.tolist()))),
            "topo_replay_identical": 1.0 if replay_identical else 0.0,
        },
    }
    n_final = len(set(np.asarray(final)[np.asarray(final) > 0].tolist()))
    return outcome_from_result(
        "topo_inputs", params, smoke, elapsed, result, scores,
        metric=(f"{int(params['n_cells']) // 1000}k-cell topology-input "
                "consensus wall-clock"),
        value=round(elapsed, 3), unit="seconds",
        extra={"n_final_clusters": n_final,
               "topo_replay_identical": replay_identical},
    )
