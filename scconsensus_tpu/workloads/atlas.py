"""Scenario runner: atlas→query label transfer through the serve path.

Fit the consensus pipeline on the atlas split, freeze the result into a
consensus-model artifact through the REAL export path
(``serve.model.export_consensus_model`` — sha256'd ArtifactStore, the
same artifact a production server loads), then classify the query split
through :class:`~scconsensus_tpu.serve.driver.ConsensusServer` as a
BATCH workload. The headline is query cells/sec through the serve
driver; the record carries the driver's validated ``serving`` section,
so serve p99/throughput land on a ledger key that is NOT the anchor
shape — the first non-anchor serve baselines.

Unlike the fleet soak's gaussian demo builder, the frozen model here
comes out of an actual refine run (DE panel, PCA basis, landmark tree
all fitted), so the transfer ARI measures the whole pipeline's
portability, not a toy classifier's.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["run"]

# batched-classify concurrency: enough to keep the micro-batching
# driver's window busy without racing past its bounded queue
_PUMP_THREADS = 4


def run(params: Dict[str, Any], smoke: bool = False,
        workdir: Optional[str] = None):
    from scconsensus_tpu.config import ReclusterConfig
    from scconsensus_tpu.models.pipeline import refine
    from scconsensus_tpu.obs.regress import adjusted_rand_index
    from scconsensus_tpu.serve.driver import ConsensusServer, ServeConfig
    from scconsensus_tpu.serve.errors import ServeError
    from scconsensus_tpu.serve.model import export_consensus_model
    from scconsensus_tpu.utils.synthetic import noisy_labeling
    from scconsensus_tpu.workloads.common import (
        consensus_of,
        outcome_from_result,
    )
    from scconsensus_tpu.workloads.data import atlas_query_dataset

    seed = int(params.get("seed", 7))
    n_clusters = int(params["n_clusters"])
    cells_per = int(params["cells_per"])
    atlas, atlas_labels, query, query_truth = atlas_query_dataset(
        n_atlas=int(params["n_atlas"]),
        n_query=int(params["n_query"]),
        n_genes=int(params["n_genes"]),
        n_clusters=n_clusters,
        seed=seed,
    )
    data = np.ascontiguousarray(atlas.T, np.float32)      # (G, n_atlas)
    sup = noisy_labeling(atlas_labels, 0.05, seed=seed + 1, prefix="sup")
    uns = noisy_labeling(atlas_labels, 0.10,
                         n_out_clusters=max(2, n_clusters - 2),
                         seed=seed + 2, prefix="uns")
    consensus = consensus_of(sup, uns)
    config = ReclusterConfig(
        method="wilcox", q_val_thrs=0.1, log_fc_thrs=0.25, min_pct=5.0,
        deep_split_values=(1, 2) if smoke else (1, 2, 3),
        min_cluster_size=10, n_top_de_genes=20, random_seed=seed,
    )
    t0 = time.perf_counter()
    result = refine(data, consensus, config)
    fit_s = time.perf_counter() - t0

    own_tmp = workdir is None
    root = workdir or tempfile.mkdtemp(prefix="scc-atlas-transfer-")
    try:
        model_dir = os.path.join(root, "model")
        model = export_consensus_model(
            data, result, config, model_dir,
            # the query split carries a small planted drift by design;
            # a generous margin keeps transfer a classification problem,
            # with drift fractions still measured per batch
            drift_margin=3.0, seed=seed,
        )

        batches: List[np.ndarray] = [
            np.ascontiguousarray(query[i:i + cells_per], np.float32)
            for i in range(0, query.shape[0], cells_per)
        ]
        served: List[Optional[np.ndarray]] = [None] * len(batches)
        outcomes: List[str] = ["unresolved"] * len(batches)
        server = ConsensusServer(model_dir, ServeConfig(),
                                 register_live=False)
        with server:
            lock = threading.Lock()
            next_i = [0]

            def _pump():
                while True:
                    with lock:
                        if next_i[0] >= len(batches):
                            return
                        i = next_i[0]
                        next_i[0] += 1
                    try:
                        resp = server.classify(batches[i], timeout=120.0)
                        outcomes[i] = resp.outcome
                        if resp.labels is not None:
                            served[i] = np.asarray(resp.labels)
                    except ServeError as e:
                        outcomes[i] = type(e).__name__
                    except TimeoutError:
                        outcomes[i] = "TimeoutError"

            t0 = time.perf_counter()
            threads = [threading.Thread(target=_pump, daemon=True)
                       for _ in range(_PUMP_THREADS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600.0)
            if any(t.is_alive() for t in threads):
                # a live pump thread would keep mutating served/outcomes
                # under the scoring below and outlive the model-dir
                # teardown — fail loudly rather than record a race
                raise RuntimeError(
                    "atlas_transfer query pump did not drain within "
                    "its timeout"
                )
            pump_s = time.perf_counter() - t0
            serving = server.serving_section()
    finally:
        if own_tmp:
            import shutil

            shutil.rmtree(root, ignore_errors=True)

    answered = [i for i, s in enumerate(served) if s is not None]
    n_answered = int(sum(served[i].shape[0] for i in answered))
    truth_parts = [
        query_truth[i * cells_per:i * cells_per + served[i].shape[0]]
        for i in answered
    ]
    transfer_ari = round(adjusted_rand_index(
        np.concatenate([served[i] for i in answered]),
        np.concatenate(truth_parts),
    ), 6) if answered else 0.0
    throughput = round(n_answered / pump_s, 1) if pump_s > 0 else 0.0
    lat = serving.get("latency_ms") or {}
    scores = {
        "metrics": {
            "transfer_ari": transfer_ari,
            "query_cells_per_s": float(throughput),
            "answered_frac": round(n_answered / max(query.shape[0], 1),
                                   6),
            "fit_s": round(fit_s, 3),
        },
    }
    if lat.get("p99") is not None:
        scores["metrics"]["serve_p99_ms"] = float(lat["p99"])
    counts: Dict[str, int] = {}
    for o in outcomes:
        counts[o] = counts.get(o, 0) + 1
    return outcome_from_result(
        "atlas_transfer", params, smoke, pump_s, result, scores,
        metric=(f"atlas→query transfer: {len(batches)} batches × "
                f"{cells_per} cells through the serve driver"),
        value=float(throughput), unit="cells/sec",
        extra={"fit_s": round(fit_s, 3),
               "model_fp": model.fingerprint(),
               "outcome_counts": counts,
               "serve_p99_ms": lat.get("p99")},
        serving=serving,
    )
