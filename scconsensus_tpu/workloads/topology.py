"""Topology-based input clusterer: a small Two-Tier-Mapper-style
cover-and-cluster labeler (arXiv:1801.01841 flavor).

The consensus layer's whole premise is combining two *different*
labelings of the same cells; this module supplies one derived from data
*topology* rather than a truth perturbation, diversifying the
unsupervised input of any scenario:

  1. **cover** — greedy farthest-point cover centers over the embedding
     (deterministic given the seed), every cell a member of its two
     nearest covers (an overlapping cover — the Mapper pullback);
  2. **local clustering** — inside each cover element, a masked
     two-means split (vmapped over covers, fixed shapes, one jit), so a
     cover patch straddling two arms of the data separates them
     locally;
  3. **nerve merge** — local clusters become nodes; a cell's
     (primary-cover node, secondary-cover node) pair is an edge, edges
     with at least ``min_overlap`` supporting cells survive, and
     connected components of that nerve are the final clusters.

All heavy pieces (farthest-point sweep, top-2 cover assignment, masked
local two-means) are jitted device programs; only the O(N) node ids
cross to host (declared ``workload_inputs`` boundary) for the tiny
union-find. The result is a pure function of ``(x, n_covers, seed,
min_overlap, overlap)`` — the cross-shape determinism the
``tools/verify_run.py`` topo shapes replay.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["topology_cluster", "topology_labeling"]

_JIT = {}


def _kernels():
    """Build (once) the jitted device pieces; module import stays
    jax-free."""
    if _JIT:
        return _JIT
    from functools import partial

    import jax
    import jax.numpy as jnp

    from scconsensus_tpu.ops.distance import _sq_dists_raw

    @partial(jax.jit, static_argnames=("n_covers",))
    def farthest_point(x, start, n_covers):
        """Greedy farthest-point cover-center indices (n_covers,)."""
        n = x.shape[0]
        idx0 = jnp.zeros((n_covers,), jnp.int32).at[0].set(start)
        mind = jnp.full((n,), jnp.inf, x.dtype)

        def body(i, carry):
            idx, mind = carry
            c = x[idx[i - 1]]
            d = jnp.sum((x - c[None, :]) ** 2, axis=1)
            mind = jnp.minimum(mind, d)
            return idx.at[i].set(jnp.argmax(mind).astype(jnp.int32)), mind

        idx, _ = jax.lax.fori_loop(1, n_covers, body, (idx0, mind))
        return idx

    @jax.jit
    def top2_covers(x, centers):
        """Primary/secondary cover of every cell + both distances."""
        d2 = _sq_dists_raw(x, centers)                   # (N, L)
        p = jnp.argmin(d2, axis=1)
        dp = jnp.take_along_axis(d2, p[:, None], axis=1)[:, 0]
        d2s = d2.at[jnp.arange(d2.shape[0]), p].set(jnp.inf)
        s = jnp.argmin(d2s, axis=1)
        ds = jnp.take_along_axis(d2s, s[:, None], axis=1)[:, 0]
        return p.astype(jnp.int32), s.astype(jnp.int32), dp, ds

    @partial(jax.jit, static_argnames=("n_iter",))
    def local_two_means(x, member_mask, centers, n_iter):
        """Per-cover masked two-means: (L, N) local id in {0, 1}.
        Deterministic init — the member farthest from the cover center,
        then the member farthest from that one."""

        def per_cover(mask, cent):
            d0 = jnp.sum((x - cent[None, :]) ** 2, axis=1)
            a = jnp.argmax(jnp.where(mask > 0, d0, -1.0))
            da = jnp.sum((x - x[a][None, :]) ** 2, axis=1)
            b = jnp.argmax(jnp.where(mask > 0, da, -1.0))
            c = jnp.stack([x[a], x[b]])                  # (2, d)

            def step(c, _):
                d = _sq_dists_raw(x, c)                  # (N, 2)
                assign = jnp.argmin(d, axis=1)
                oh = jax.nn.one_hot(assign, 2, dtype=x.dtype) \
                    * mask[:, None]
                cnt = jnp.sum(oh, axis=0)
                sums = oh.T @ x
                c2 = jnp.where(cnt[:, None] > 0,
                               sums / jnp.maximum(cnt, 1.0)[:, None], c)
                return c2, None

            c, _ = jax.lax.scan(step, c, None, length=n_iter)
            return jnp.argmin(_sq_dists_raw(x, c), axis=1).astype(
                jnp.int32
            )

        return jax.vmap(per_cover)(member_mask, centers)

    _JIT.update(farthest_point=farthest_point, top2_covers=top2_covers,
                local_two_means=local_two_means)
    return _JIT


class _UnionFind:
    def __init__(self, n: int):
        self.parent = list(range(n))

    def find(self, i: int) -> int:
        while self.parent[i] != i:
            self.parent[i] = self.parent[self.parent[i]]
            i = self.parent[i]
        return i

    def union(self, a: int, b: int) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[max(ra, rb)] = min(ra, rb)


def topology_cluster(
    x: np.ndarray,
    n_covers: int = 16,
    seed: int = 0,
    min_overlap: Optional[int] = None,
    overlap: float = 1.5,
    local_iters: int = 8,
    prefix: str = "topo",
) -> np.ndarray:
    """Cluster the rows of ``x`` (N, d) by cover → local split → nerve.

    ``min_overlap`` is the cell-support an edge of the nerve needs to
    survive (default ``max(3, N // (50 * n_covers))`` — scale-free
    enough that smoke and full shapes use the same recipe);
    ``overlap`` gates which cells count as genuinely shared between
    their two covers (secondary distance within ``overlap ×`` primary).
    Returns string labels ``f"{prefix}{component}"``, a pure function
    of the inputs.
    """
    import jax
    import jax.numpy as jnp

    from scconsensus_tpu.obs.residency import boundary

    n = int(x.shape[0])
    n_covers = int(min(n_covers, max(2, n // 4)))
    if min_overlap is None:
        min_overlap = max(3, n // (50 * n_covers))
    k = _kernels()
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x7090]))
    start = int(rng.integers(0, n))

    with boundary("workload_inputs"):
        xd = jnp.asarray(np.asarray(x, np.float32))
        cid = k["farthest_point"](xd, start, n_covers)
        centers = xd[cid]
        p, s, dp, ds = k["top2_covers"](xd, centers)
        # membership: primary always; secondary only when the cell is
        # genuinely shared (distance ratio inside the overlap gate)
        shared = jnp.sqrt(ds) <= overlap * jnp.sqrt(jnp.maximum(dp, 1e-12))
        covers = jnp.arange(n_covers, dtype=jnp.int32)
        mask = ((p[None, :] == covers[:, None])
                | ((s[None, :] == covers[:, None]) & shared[None, :])
                ).astype(xd.dtype)                        # (L, N)
        local = k["local_two_means"](xd, mask, centers, local_iters)
        # O(N) int fetches: node ids + the shared gate — the only host
        # crossings this labeler makes
        p_h, s_h, shared_h, local_h = jax.device_get(
            (p, s, shared, local)
        )

    p_h = np.asarray(p_h, np.int64)
    s_h = np.asarray(s_h, np.int64)
    local_h = np.asarray(local_h, np.int64)
    node_p = 2 * p_h + local_h[p_h, np.arange(n)]
    node_s = 2 * s_h + local_h[s_h, np.arange(n)]

    # nerve: count supporting cells per (node_p, node_s) edge among the
    # genuinely shared cells, keep edges with enough support
    sh = np.asarray(shared_h, bool)
    edge_key = node_p[sh] * (2 * n_covers) + node_s[sh]
    keys, counts = np.unique(edge_key, return_counts=True)
    uf = _UnionFind(2 * n_covers)
    for key, c in zip(keys.tolist(), counts.tolist()):
        if c >= min_overlap:
            uf.union(key // (2 * n_covers), key % (2 * n_covers))

    roots = np.array([uf.find(i) for i in range(2 * n_covers)])
    # deterministic component ids: order of first appearance by node id
    uniq = sorted(set(roots[node_p].tolist()))
    remap = {r: i for i, r in enumerate(uniq)}
    comp = np.array([remap[r] for r in roots[node_p]])
    return np.array([f"{prefix}{c}" for c in comp])


def topology_labeling(
    data: np.ndarray,
    n_pcs: int = 10,
    n_covers: int = 16,
    seed: int = 0,
    prefix: str = "topo",
    **kw,
) -> np.ndarray:
    """Topology labeling straight from a (G, N) expression matrix: the
    shared rSVD-PCA embed (``workloads.common.pca_embed`` — the same
    ``ops.pca`` path the pipeline uses), then :func:`topology_cluster`
    over the embedding. Scenario runners that need the embedding for
    anything else (the replay pin) call the two pieces themselves."""
    from scconsensus_tpu.workloads.common import pca_embed

    if hasattr(data, "toarray"):    # scipy sparse input
        data = data.toarray()
    emb = pca_embed(data, n_pcs, seed=seed)
    return topology_cluster(emb, n_covers=n_covers, seed=seed,
                            prefix=prefix, **kw)
