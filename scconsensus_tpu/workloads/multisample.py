"""Scenario runner: multi-sample batch-effect consensus.

Cells are drawn from S samples whose raw counts carry per-sample
technical confounds (``workloads.data.multi_sample_dataset``); the
consensus layer gets the paper's supervised/unsupervised pair in its
multi-sample form — ONE truth-aligned supervised labeling (a FACS-style
annotation shared across samples) × one UNALIGNED per-sample clustering
(``workloads.labelings.per_sample_unsupervised``: cluster ids are
sample-local, so the contingency grammar has to reconcile them). The
scenario's scoring block is the integration evidence the anchor configs
cannot produce: per-batch ARI (a sample the refinement shredded cannot
hide behind the pooled number) and batch-mixing entropy (an output
clustering that IS the batch structure scores ~0 mixing).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

__all__ = ["run", "multi_sample_inputs", "multi_sample_scores"]


def multi_sample_scores(final, truth, batches) -> Dict[str, Any]:
    """The multi-sample ``quality.scenario`` scoring block — ONE
    assembly shared by the bench runner and the chaos soak worker
    (``workloads.soak``), so the kill-resume evidence replays exactly
    the scoring the bench records."""
    from scconsensus_tpu.obs.quality import (
        batch_mixing_entropy,
        per_batch_ari,
    )
    from scconsensus_tpu.obs.regress import adjusted_rand_index

    pba = per_batch_ari(final, truth, batches)
    bme = batch_mixing_entropy(final, batches)
    pba_vals = list(pba.values())
    return {
        "name": "multi_sample",
        "metrics": {
            "ari_pooled": round(adjusted_rand_index(final, truth), 6),
            "per_batch_ari_mean": round(float(np.mean(pba_vals)), 6),
            "per_batch_ari_min": round(float(np.min(pba_vals)), 6),
            "batch_mixing_mean_norm_entropy": bme["mean_norm_entropy"],
        },
        "per_batch_ari": pba,
        "batch_mixing": bme,
    }


def multi_sample_inputs(params: Dict[str, Any]):
    """Dataset + consensus-input construction — ONE recipe shared by
    the bench runner and the chaos soak worker (``workloads.soak``),
    like :func:`multi_sample_scores`, so the kill-resume evidence
    replays exactly the inputs the bench scenario builds. Returns
    ``(data, truth, batches, uns, consensus)``."""
    from scconsensus_tpu.utils.synthetic import noisy_labeling
    from scconsensus_tpu.workloads.common import consensus_of
    from scconsensus_tpu.workloads.data import multi_sample_dataset
    from scconsensus_tpu.workloads.labelings import per_sample_unsupervised

    seed = int(params.get("seed", 7))
    data, truth, batches = multi_sample_dataset(
        n_cells=int(params["n_cells"]),
        n_genes=int(params["n_genes"]),
        n_clusters=int(params["n_clusters"]),
        n_samples=int(params["n_samples"]),
        seed=seed,
    )
    sup = noisy_labeling(truth, 0.05, seed=seed + 1, prefix="sup")
    uns = per_sample_unsupervised(truth, batches, seed=seed)
    return data, truth, batches, uns, consensus_of(sup, uns)


def run(params: Dict[str, Any], smoke: bool = False,
        workdir: Optional[str] = None):
    from scconsensus_tpu.workloads.common import (
        final_labels,
        outcome_from_result,
        refine_consensus,
    )

    seed = int(params.get("seed", 7))
    data, truth, batches, uns, consensus = multi_sample_inputs(params)
    elapsed, result = refine_consensus(data, consensus, smoke, seed=seed)

    final = final_labels(result)
    scores = multi_sample_scores(final, truth, batches)
    n_final = len(set(final[final > 0].tolist()))
    return outcome_from_result(
        "multi_sample", params, smoke, elapsed, result, scores,
        metric=(f"{int(params['n_cells']) // 1000}k-cell "
                f"{params['n_samples']}-sample batch-effect consensus "
                "wall-clock"),
        value=round(elapsed, 3), unit="seconds",
        extra={"n_final_clusters": n_final,
               "n_input_sample_clusters": len(set(uns.tolist()))},
    )
