"""Scenario runner: dual-modality CITE-seq consensus.

The paper's supervised/unsupervised split generalized to modalities:
the ADT modality (a few dozen surface proteins, coarse lineage signal
only) is clustered COARSELY and stands in for the supervised labeling;
the RNA modality (full expression, fine subcluster structure) is
clustered FINELY as the unsupervised labeling. Both clusterings are
seeded device k-means over the modality's own geometry
(``workloads.common.kmeans_labeling``) — neither sees the planted
truth, so the consensus layer is reconciling two *measured* views of
the same cells, which is the scenario the anchor configs' truth-derived
labelings cannot represent. Scored against the hierarchical truth at
both granularities.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

__all__ = ["run"]


def run(params: Dict[str, Any], smoke: bool = False,
        workdir: Optional[str] = None):
    from scconsensus_tpu.obs.regress import adjusted_rand_index
    from scconsensus_tpu.workloads.common import (
        consensus_of,
        final_labels,
        kmeans_labeling,
        outcome_from_result,
        pca_embed,
        refine_consensus,
    )
    from scconsensus_tpu.workloads.data import cite_seq_dataset

    seed = int(params.get("seed", 7))
    k_coarse = int(params["k_coarse"])
    k_fine = int(params["k_fine"])
    rna, adt, truth_fine, truth_coarse = cite_seq_dataset(
        n_cells=int(params["n_cells"]),
        n_genes=int(params["n_genes"]),
        n_adt=int(params["n_adt"]),
        k_coarse=k_coarse,
        k_fine=k_fine,
        seed=seed,
    )
    # ADT is already low-dimensional: cluster the (N, A) protein space
    # directly at lineage granularity
    adt_lab = kmeans_labeling(adt.T, k_coarse, seed=seed + 1,
                              prefix="adt")
    # RNA: the pipeline's own rSVD-PCA embed, clustered finely
    n_pcs = int(min(20, max(4, k_fine + 4)))
    rna_emb = pca_embed(rna, n_pcs, seed=seed)
    rna_lab = kmeans_labeling(rna_emb, k_fine, seed=seed + 2,
                              prefix="rna")
    consensus = consensus_of(adt_lab, rna_lab)
    elapsed, result = refine_consensus(rna, consensus, smoke, seed=seed)

    final = final_labels(result)
    scores = {
        "metrics": {
            # input-labeling quality: how well each modality's own
            # clustering recovers its OWN truth granularity
            "adt_ari_vs_coarse": round(
                adjusted_rand_index(adt_lab, truth_coarse), 6),
            "rna_ari_vs_fine": round(
                adjusted_rand_index(rna_lab, truth_fine), 6),
            # consensus output scored at both granularities
            "final_ari_vs_fine": round(
                adjusted_rand_index(final, truth_fine), 6),
            "final_ari_vs_coarse": round(
                adjusted_rand_index(final, truth_coarse), 6),
        },
    }
    n_final = len(set(np.asarray(final)[np.asarray(final) > 0].tolist()))
    return outcome_from_result(
        "cite_dual", params, smoke, elapsed, result, scores,
        metric=(f"{int(params['n_cells']) // 1000}k-cell dual-modality "
                "ADT×RNA consensus wall-clock"),
        value=round(elapsed, 3), unit="seconds",
        extra={"n_final_clusters": n_final, "n_pcs": n_pcs},
    )
