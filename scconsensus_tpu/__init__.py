"""scconsensus_tpu — TPU-native consensus clustering for single-cell RNA-seq.

A brand-new JAX / XLA / Pallas framework with the capabilities of the R package
``scConsensus`` (reference: ``bbbranjan/scConsensus``): consensus labeling of two
clusterings via a contingency-table merge grammar, all-pairs differential-expression
testing (Wilcoxon rank-sum, edgeR-style negative-binomial exact test, bimod LRT,
ROC/AUC, t-test), DE-gene-union re-embedding (randomized-SVD PCA), Ward.D2
hierarchical clustering, dynamic-tree-cut refinement, silhouette scoring, and
heatmap reports.

Architecture (idiomatic JAX, not a port):
  * ``consensus/`` — contingency table + automated label-merge grammar
    (host, O(N); reference: R/plotContingencyTable.R).
  * ``ops/``       — batched statistical/linear-algebra kernels (device):
    rank/Wilcoxon, NB dispersion + exact test, PCA, distance, silhouette,
    Ward linkage, dynamic tree cut, BH.
  * ``de/``        — the all-pairs DE engine: cluster pairs flattened to a padded
    batch axis, gates as masks (replaces the reference's doParallel fan-out).
  * ``models/``    — user-facing pipelines mirroring the reference entry points.
  * ``parallel/``  — device-mesh sharding (pjit/shard_map, ICI/DCN collectives).
  * ``report/``    — matplotlib contingency / DE heatmaps.
  * ``utils/``     — config, artifact store (checkpoint/resume), tracing, synthetic data.
  * ``native/``    — C++ runtime pieces (Ward NN-chain linkage) via ctypes.
"""

__version__ = "0.1.0"

from scconsensus_tpu.consensus import contingency_table, plot_contingency_table
from scconsensus_tpu.config import ReclusterConfig, CompatFlags


def __getattr__(name):
    # Lazy: pulling in the pipelines imports jax; keep bare-package import light.
    if name in ("recluster_de_consensus", "recluster_de_consensus_fast", "ReclusterResult"):
        try:
            from scconsensus_tpu import models
        except ImportError as e:  # pragma: no cover
            raise NotImplementedError(
                f"{name} requires scconsensus_tpu.models, which failed to import: {e}"
            ) from e
        return getattr(models, name)
    raise AttributeError(name)

__all__ = [
    "contingency_table",
    "plot_contingency_table",
    "recluster_de_consensus",
    "recluster_de_consensus_fast",
    "ReclusterConfig",
    "CompatFlags",
    "ReclusterResult",
    "__version__",
]
