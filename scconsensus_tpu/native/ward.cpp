// Ward.D2 nearest-neighbor-chain agglomeration — native runtime core.
//
// The TPU computes the embedding; the merge loop itself is inherently
// sequential (SURVEY.md §7 "hard parts" #1) and latency-bound, so it runs
// on host in C++ (the role fastcluster's C++ plays for the reference,
// R/reclusterDEConsensus.R:242-246). Clusters are (centroid, size) pairs and
// the Ward.D2 dissimilarity is the closed-form Lance–Williams recurrence
//     D(A,B)^2 = 2·|A||B|/(|A|+|B|) · ‖c_A − c_B‖²,
// identical to the numpy fallback in ops/linkage.py (its golden reference).
//
// Layout tuned for a single-core host (the build machine exposes 1 CPU):
// centroids are stored column-major over a swap-remove-compacted active set,
// so the NN scan's hot loop is a contiguous, FMA-vectorizable pass over the
// cluster axis per dimension. The scan screens in float (8-wide SIMD, half
// the bandwidth) and re-derives the exact argmin in double over the few
// candidates inside a rounding-analysis margin — measured 2.1x on the 26k
// flagship with bit-identical merge pairs (heights differ only by FMA
// contraction, ~2 ULP). Ties break toward the smallest slot id, reproducing
// the numpy argmin (first minimum in ascending slot order).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// points: (n, d) row-major; weights: (n,) cluster sizes (>=1).
// out_pairs: (n-1, 2) merged slot ids (slots n.. are prior merges, in merge
// order); out_heights: (n-1,) ward.D2 heights. Returns 0 on success.
int scc_ward_nnchain(const double* points, const double* weights, int64_t n,
                     int64_t d, int64_t* out_pairs, double* out_heights) {
  if (n < 2 || d < 1) return 1;
  const int64_t cap = 2 * n - 1;

  // Column-major active centroids: col[i*n + t] = coordinate i of the
  // cluster at active position t. Parallel arrays kept in sync by
  // swap-remove; a_count shrinks monotonically from n, so n slots suffice.
  // colf/csizef are float mirrors for the screening pass (see below).
  std::vector<double> col(static_cast<size_t>(d) * n);
  std::vector<float> colf(static_cast<size_t>(d) * n);
  std::vector<double> csize(n);
  std::vector<float> csizef(n);
  std::vector<int64_t> cslot(n);
  std::vector<int64_t> pos_of(cap, -1);  // slot -> active position
  std::vector<float> d2f(n);             // screening distance^2 buffer
  std::vector<float> facf(n);            // screening Ward-factor buffer

  double max_abs = 0.0;  // coordinate magnitude bound for the f32 margin
  for (int64_t t = 0; t < n; ++t) {
    for (int64_t i = 0; i < d; ++i) {
      const double c = points[t * d + i];
      col[i * n + t] = c;
      colf[i * n + t] = static_cast<float>(c);
      const double a = c < 0 ? -c : c;
      if (a > max_abs) max_abs = a;
    }
    csize[t] = weights[t];
    csizef[t] = static_cast<float>(weights[t]);
    cslot[t] = t;
    pos_of[t] = t;
  }
  int64_t a_count = n;
  // Certified screening-error constants. f32 inputs round at eps*|coord|
  // (eps = 2^-24), so err(dist^2) <= 4*eps*M*sqrt(d)*dist + 4*d*eps^2*M^2.
  // Split point delta0 := 4000*sqrt(d)*eps*M: above it the error is <= 0.2%
  // of dist^2 (covered by REL = 0.3%, which also absorbs the f32 factor's
  // own rounding); below it the whole error is <= ~1.6e4*d*eps^2*M^2 =:
  // C_ABS *per unit of the Ward factor* — the slack must scale with each
  // candidate's own factor (weights can amplify by 1e6; a global constant
  // cannot be sound). A tight REL matters: in concentrated-distance
  // regimes (high-dim random data) a loose relative band admits thousands
  // of exact double verifications per scan. Merged centroids are convex
  // combinations, so M never grows. C_ABS carries ~4x headroom.
  const double C_ABS =
      2.5e-10 * static_cast<double>(d) * max_abs * max_abs;
  const double REL = 1.003;

  std::vector<int64_t> chain;
  chain.reserve(64);
  std::vector<double> cu(d);
  int64_t next_slot = n;

  auto swap_remove = [&](int64_t pos) {
    const int64_t last = a_count - 1;
    pos_of[cslot[pos]] = -1;
    if (pos != last) {
      for (int64_t i = 0; i < d; ++i) {
        col[i * n + pos] = col[i * n + last];
        colf[i * n + pos] = colf[i * n + last];
      }
      csize[pos] = csize[last];
      csizef[pos] = csizef[last];
      cslot[pos] = cslot[last];
      pos_of[cslot[pos]] = pos;
    }
    --a_count;
  };

  while (a_count > 1) {
    if (chain.empty()) {
      // Numpy starts a fresh chain at the smallest active slot.
      int64_t smallest = cslot[0];
      for (int64_t t = 1; t < a_count; ++t)
        if (cslot[t] < smallest) smallest = cslot[t];
      chain.push_back(smallest);
    }
    int64_t u, v;
    double best_d2;
    for (;;) {
      u = chain.back();
      const int64_t upos = pos_of[u];
      const double su = csize[upos];
      const float suf = static_cast<float>(su);
      for (int64_t i = 0; i < d; ++i) cu[i] = col[i * n + upos];

      // Screening pass in float (8-wide SIMD, half the bandwidth of the
      // old all-double scan): squared distances, then the Ward factor.
      // Candidate selection uses certified per-candidate bounds
      //   up    = min_t ( w_f[t]*REL + C_ABS*fac[t] )   (upper bd of best)
      //   lo[t] =        w_f[t]/REL  - C_ABS*fac[t]     (lower bd of w[t])
      // and keeps t with lo[t] <= up; the exact argmin is re-derived in
      // double over those, so the emitted tree is bit-identical to the
      // pure-double scan (the slack scales with each candidate's own
      // factor — sound under arbitrary cluster weights).
      float* acc = d2f.data();
      float* fac = facf.data();
      {
        const float c0 = static_cast<float>(cu[0]);
        const float* row = colf.data();
#pragma GCC ivdep
        for (int64_t t = 0; t < a_count; ++t) {
          const float diff = c0 - row[t];
          acc[t] = diff * diff;
        }
      }
      for (int64_t i = 1; i < d; ++i) {
        const float ci = static_cast<float>(cu[i]);
        const float* row = colf.data() + i * n;
#pragma GCC ivdep
        for (int64_t t = 0; t < a_count; ++t) {
          const float diff = ci - row[t];
          acc[t] += diff * diff;
        }
      }
      {
        const float* sz = csizef.data();
#pragma GCC ivdep
        for (int64_t t = 0; t < a_count; ++t) {
          const float sv = sz[t];
          fac[t] = 2.0f * (suf * sv / (suf + sv));
        }
      }
      // Bounds in vectorized f32 (their own rounding is absorbed by the
      // REL/C_ABS headroom): acc becomes the certified lower bound, fac
      // the certified upper bound of each candidate's Ward statistic.
      {
        const float relf = static_cast<float>(REL) * 1.001f;
        const float cabsf = static_cast<float>(C_ABS) * 1.25f;
#pragma GCC ivdep
        for (int64_t t = 0; t < a_count; ++t) {
          const float w = acc[t] * fac[t];
          const float slack = cabsf * fac[t];
          acc[t] = w / relf - slack;  // lo[t]
          fac[t] = w * relf + slack;  // up contribution
        }
      }
      float upf = 3e38f;
      float maxf = 0.0f;
      for (int64_t t = 0; t < a_count; ++t) {
        if (t == upos) continue;
        if (fac[t] < upf) upf = fac[t];
        if (fac[t] > maxf) maxf = fac[t];
      }
      // An overflowed candidate (inf upper bound) has an unknown true
      // statistic: screening is only trusted when everything stayed finite.
      const bool screen_ok = maxf < 3e38f;

      double bd = 1e300;
      int64_t bslot = -1;
      for (int64_t t = 0; t < a_count; ++t) {
        if (t == upos) continue;
        if (screen_ok && acc[t] > upf) continue;
        double dist2 = 0.0;
        for (int64_t i = 0; i < d; ++i) {
          const double diff = cu[i] - col[i * n + t];
          dist2 += diff * diff;
        }
        const double sv = csize[t];
        const double w2 = 2.0 * (su * sv / (su + sv)) * dist2;
        const int64_t s = cslot[t];
        if (w2 < bd || (w2 == bd && s < bslot)) {
          bd = w2;
          bslot = s;
        }
      }
      if (bslot < 0) return 2;
      if (chain.size() > 1 && bslot == chain[chain.size() - 2]) {
        best_d2 = bd;
        v = bslot;
        break;
      }
      chain.push_back(bslot);
    }
    chain.pop_back();  // u
    chain.pop_back();  // v
    const int64_t row_idx = next_slot - n;
    out_pairs[row_idx * 2] = u;
    out_pairs[row_idx * 2 + 1] = v;
    out_heights[row_idx] = std::sqrt(best_d2 > 0.0 ? best_d2 : 0.0);

    const int64_t up = pos_of[u], vp = pos_of[v];
    const double su = csize[up], sv = csize[vp];
    std::vector<double> merged(d);
    for (int64_t i = 0; i < d; ++i)
      merged[i] = (su * col[i * n + up] + sv * col[i * n + vp]) / (su + sv);
    if (up > vp) {
      swap_remove(up);
      swap_remove(vp);
    } else {
      swap_remove(vp);
      swap_remove(up);
    }
    for (int64_t i = 0; i < d; ++i) {
      col[i * n + a_count] = merged[i];
      colf[i * n + a_count] = static_cast<float>(merged[i]);
    }
    csize[a_count] = su + sv;
    csizef[a_count] = static_cast<float>(su + sv);
    cslot[a_count] = next_slot;
    pos_of[next_slot] = a_count;
    ++a_count;
    ++next_slot;
  }
  return 0;
}

}  // extern "C"
