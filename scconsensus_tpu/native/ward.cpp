// Ward.D2 nearest-neighbor-chain agglomeration — native runtime core.
//
// The TPU computes the embedding; the merge loop itself is inherently
// sequential (SURVEY.md §7 "hard parts" #1) and latency-bound, so it runs
// on host in C++ (the role fastcluster's C++ plays for the reference,
// R/reclusterDEConsensus.R:242-246). Clusters are (centroid, size) pairs and
// the Ward.D2 dissimilarity is the closed-form Lance–Williams recurrence
//     D(A,B)^2 = 2·|A||B|/(|A|+|B|) · ‖c_A − c_B‖²,
// identical to the numpy fallback in ops/linkage.py (its golden reference).
//
// Layout tuned for a single-core host (the build machine exposes 1 CPU):
// centroids are stored column-major over a swap-remove-compacted active set,
// so the NN scan's hot loop is a contiguous, FMA-vectorizable pass over the
// cluster axis per dimension. Ties break toward the smallest slot id,
// reproducing the numpy argmin (first minimum in ascending slot order).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// points: (n, d) row-major; weights: (n,) cluster sizes (>=1).
// out_pairs: (n-1, 2) merged slot ids (slots n.. are prior merges, in merge
// order); out_heights: (n-1,) ward.D2 heights. Returns 0 on success.
int scc_ward_nnchain(const double* points, const double* weights, int64_t n,
                     int64_t d, int64_t* out_pairs, double* out_heights) {
  if (n < 2 || d < 1) return 1;
  const int64_t cap = 2 * n - 1;

  // Column-major active centroids: col[i*n + t] = coordinate i of the
  // cluster at active position t. Parallel arrays kept in sync by
  // swap-remove; a_count shrinks monotonically from n, so n slots suffice.
  std::vector<double> col(static_cast<size_t>(d) * n);
  std::vector<double> csize(n);
  std::vector<int64_t> cslot(n);
  std::vector<int64_t> pos_of(cap, -1);  // slot -> active position
  std::vector<double> d2(n);             // scan buffer

  for (int64_t t = 0; t < n; ++t) {
    for (int64_t i = 0; i < d; ++i) col[i * n + t] = points[t * d + i];
    csize[t] = weights[t];
    cslot[t] = t;
    pos_of[t] = t;
  }
  int64_t a_count = n;

  std::vector<int64_t> chain;
  chain.reserve(64);
  std::vector<double> cu(d);
  int64_t next_slot = n;

  auto swap_remove = [&](int64_t pos) {
    const int64_t last = a_count - 1;
    pos_of[cslot[pos]] = -1;
    if (pos != last) {
      for (int64_t i = 0; i < d; ++i) col[i * n + pos] = col[i * n + last];
      csize[pos] = csize[last];
      cslot[pos] = cslot[last];
      pos_of[cslot[pos]] = pos;
    }
    --a_count;
  };

  while (a_count > 1) {
    if (chain.empty()) {
      // Numpy starts a fresh chain at the smallest active slot.
      int64_t smallest = cslot[0];
      for (int64_t t = 1; t < a_count; ++t)
        if (cslot[t] < smallest) smallest = cslot[t];
      chain.push_back(smallest);
    }
    int64_t u, v;
    double best_d2;
    for (;;) {
      u = chain.back();
      const int64_t upos = pos_of[u];
      const double su = csize[upos];
      for (int64_t i = 0; i < d; ++i) cu[i] = col[i * n + upos];

      // Hot loop: squared distances to every active cluster, contiguous in t.
      double* acc = d2.data();
      {
        const double c0 = cu[0];
        const double* row = col.data();
#pragma GCC ivdep
        for (int64_t t = 0; t < a_count; ++t) {
          const double diff = c0 - row[t];
          acc[t] = diff * diff;
        }
      }
      for (int64_t i = 1; i < d; ++i) {
        const double ci = cu[i];
        const double* row = col.data() + i * n;
#pragma GCC ivdep
        for (int64_t t = 0; t < a_count; ++t) {
          const double diff = ci - row[t];
          acc[t] += diff * diff;
        }
      }

      // Argmin of the Ward statistic with smallest-slot tie-break.
      double bd = 1e300;
      int64_t bslot = -1;
      for (int64_t t = 0; t < a_count; ++t) {
        if (t == upos) continue;
        const double sv = csize[t];
        const double w2 = 2.0 * (su * sv / (su + sv)) * acc[t];
        const int64_t s = cslot[t];
        if (w2 < bd || (w2 == bd && s < bslot)) {
          bd = w2;
          bslot = s;
        }
      }
      if (bslot < 0) return 2;
      if (chain.size() > 1 && bslot == chain[chain.size() - 2]) {
        best_d2 = bd;
        v = bslot;
        break;
      }
      chain.push_back(bslot);
    }
    chain.pop_back();  // u
    chain.pop_back();  // v
    const int64_t row_idx = next_slot - n;
    out_pairs[row_idx * 2] = u;
    out_pairs[row_idx * 2 + 1] = v;
    out_heights[row_idx] = std::sqrt(best_d2 > 0.0 ? best_d2 : 0.0);

    const int64_t up = pos_of[u], vp = pos_of[v];
    const double su = csize[up], sv = csize[vp];
    std::vector<double> merged(d);
    for (int64_t i = 0; i < d; ++i)
      merged[i] = (su * col[i * n + up] + sv * col[i * n + vp]) / (su + sv);
    if (up > vp) {
      swap_remove(up);
      swap_remove(vp);
    } else {
      swap_remove(vp);
      swap_remove(up);
    }
    for (int64_t i = 0; i < d; ++i) col[i * n + a_count] = merged[i];
    csize[a_count] = su + sv;
    cslot[a_count] = next_slot;
    pos_of[next_slot] = a_count;
    ++a_count;
    ++next_slot;
  }
  return 0;
}

}  // extern "C"
