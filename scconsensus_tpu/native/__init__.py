"""Native (C++) runtime pieces, ctypes-loaded.

The compute path is JAX/XLA; these are the host-side runtime kernels where
the reference leans on native libraries (SURVEY.md §2b): currently the
Ward.D2 NN-chain agglomeration (fastcluster's role). Built on demand with the
in-tree compiler — no pybind11 dependency, plain C ABI + ctypes.

``ward_native(points, weights)`` raises on any build/load failure; callers
(ops/linkage.py) fall back to the numpy implementation, which is also the
golden reference for these kernels.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = ["ward_native", "native_available"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libscc_native.so")
_SRC = os.path.join(_DIR, "ward.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_ERROR: Optional[Exception] = None


def _build() -> None:
    cmd = [
        "g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
        "-std=c++17", _SRC, "-o", _SO,
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _load() -> ctypes.CDLL:
    global _LIB, _LOAD_ERROR
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _LOAD_ERROR is not None:
            raise _LOAD_ERROR
        try:
            if (not os.path.exists(_SO)) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                _build()
            lib = ctypes.CDLL(_SO)
            fn = lib.scc_ward_nnchain
            fn.restype = ctypes.c_int
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_double),
            ]
            _LIB = lib
            return lib
        except Exception as e:  # compiler missing, load failure, ...
            _LOAD_ERROR = e
            raise


def native_available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


def ward_native(
    points: np.ndarray, weights: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Run the C++ NN-chain. Returns (raw_pairs (n-1, 2) slot ids, raw_h (n-1,))
    in merge order — same raw output as the numpy chain in ops/linkage.py."""
    lib = _load()
    pts = np.ascontiguousarray(points, np.float64)
    w = np.ascontiguousarray(weights, np.float64)
    n, d = pts.shape
    pairs = np.zeros((n - 1, 2), np.int64)
    heights = np.zeros(n - 1, np.float64)
    rc = lib.scc_ward_nnchain(
        pts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        w.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int64(n),
        ctypes.c_int64(d),
        pairs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        heights.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if rc != 0:
        raise RuntimeError(f"scc_ward_nnchain failed with code {rc}")
    return pairs, heights
