"""Native (C++) runtime pieces, ctypes-loaded.

The compute path is JAX/XLA; these are the host-side runtime kernels where
the reference leans on native libraries (SURVEY.md §2b): currently the
Ward.D2 NN-chain agglomeration (fastcluster's role). Built on demand with the
in-tree compiler — no pybind11 dependency, plain C ABI + ctypes.

``ward_native(points, weights)`` raises on any build/load failure; callers
(ops/linkage.py) fall back to the numpy implementation, which is also the
golden reference for these kernels.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = ["ward_native", "native_available"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "ward.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_ERROR: Optional[Exception] = None

# The artifact is never committed — it is keyed by a content hash of the
# source + flags + compiler so a stale or foreign binary can never be picked
# up by accident, and it is always (re)built by the host that loads it, so
# -march=native is safe; the generic set is the fallback for compilers that
# reject it (measured 1.27× on the 26k flagship NN-chain scan).
_CFLAGS = ["-O3", "-march=native", "-funroll-loops", "-fopenmp", "-shared",
           "-fPIC", "-std=c++17"]
_CFLAGS_FALLBACK = ["-O3", "-fopenmp", "-shared", "-fPIC", "-std=c++17"]


def _compiler_tag() -> str:
    try:
        out = subprocess.run(
            ["g++", "--version"], capture_output=True, text=True, check=True
        ).stdout.splitlines()[0]
    except Exception:
        out = "g++-unknown"
    return out + "\x00" + _cpu_tag()


def _cpu_tag() -> str:
    """CPU identity folded into the .so cache key: with -march=native a
    binary cached on a shared filesystem (NFS home, baked container image)
    must never be dlopened by a host with a different microarchitecture —
    SIGILL there kills the process before the numpy fallback can catch
    anything."""
    import platform

    tag = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("model name", "flags", "Features")):
                    tag += "\x00" + line.strip()
                    if line.startswith(("flags", "Features")):
                        break
    except OSError:
        tag += "\x00" + platform.processor()
    return tag


def _build_dir() -> str:
    """Directory for built artifacts: next to the source when writable (the
    repo-checkout case), else a per-user cache dir — a pip install into
    read-only site-packages must not silently lose the native path."""
    if os.access(_DIR, os.W_OK):
        return _DIR
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "scconsensus_tpu",
    )
    os.makedirs(cache, exist_ok=True)
    return cache


def _so_path(flags: list) -> str:
    """Cache path keyed by source + the EXACT flag set the binary was built
    with (a -march=native binary and its generic fallback get distinct
    paths, so the content-hash key always describes the artifact)."""
    with open(_SRC, "rb") as f:
        src = f.read()
    key = hashlib.sha256(
        src + ("\x00".join(flags) + "\x00" + _compiler_tag()).encode()
    ).hexdigest()[:16]
    return os.path.join(_build_dir(), f"libscc_native-{key}.so")


def _build() -> str:
    """Compile and return the path of the artifact actually produced."""
    primary_err = None
    for flags in (_CFLAGS, _CFLAGS_FALLBACK):
        so = _so_path(flags)
        # pid-unique tmp: concurrent first builds from separate processes
        # must not interleave writes into one tmp (os.replace is atomic).
        tmp = f"{so}.tmp.{os.getpid()}.so"
        try:
            subprocess.run(["g++", *flags, _SRC, "-o", tmp],
                           check=True, capture_output=True, text=True)
            os.replace(tmp, so)
            return so
        except subprocess.CalledProcessError as e:
            # Retry with generic flags (covers every flavor of target-flag
            # failure, not just parse-time -march rejection); if the
            # fallback fails too it was a genuine source error — surface
            # the PRIMARY diagnostics, not the fallback's.
            primary_err = primary_err or e
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    raise primary_err


def _cleanup_stale(keep: str) -> None:
    """Drop orphaned builds of older source revisions. Called only after a
    successful CDLL load: a concurrent process that loses its .so to this
    unlink already has the inode mapped, so its handle stays valid."""
    base = os.path.dirname(keep)
    if base != _DIR:
        # Shared per-user cache (read-only install): other environments may
        # have live builds of other revisions here — deleting them causes
        # rebuild thrash and an unlink/CDLL race. Only the repo-checkout
        # case, where this revision owns the directory, gets cleanup.
        return
    for f in os.listdir(base):
        if f.startswith("libscc_native-") and f.endswith(".so"):
            p = os.path.join(base, f)
            if p != keep:
                try:
                    os.unlink(p)
                except OSError:
                    pass


def _load() -> ctypes.CDLL:
    global _LIB, _LOAD_ERROR
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _LOAD_ERROR is not None:
            raise _LOAD_ERROR
        try:
            so = next(
                (p for p in (_so_path(_CFLAGS), _so_path(_CFLAGS_FALLBACK))
                 if os.path.exists(p)),
                None,
            )
            if so is None:
                so = _build()
            lib = ctypes.CDLL(so)
            fn = lib.scc_ward_nnchain
            fn.restype = ctypes.c_int
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_double),
            ]
            _LIB = lib
            _cleanup_stale(keep=so)
            return lib
        except Exception as e:  # compiler missing, load failure, ...
            _LOAD_ERROR = e
            raise


def native_available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


def ward_native(
    points: np.ndarray, weights: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Run the C++ NN-chain. Returns (raw_pairs (n-1, 2) slot ids, raw_h (n-1,))
    in merge order — same raw output as the numpy chain in ops/linkage.py."""
    lib = _load()
    pts = np.ascontiguousarray(points, np.float64)
    w = np.ascontiguousarray(weights, np.float64)
    n, d = pts.shape
    pairs = np.zeros((n - 1, 2), np.int64)
    heights = np.zeros(n - 1, np.float64)
    rc = lib.scc_ward_nnchain(
        pts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        w.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int64(n),
        ctypes.c_int64(d),
        pairs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        heights.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if rc != 0:
        raise RuntimeError(f"scc_ward_nnchain failed with code {rc}")
    return pairs, heights
