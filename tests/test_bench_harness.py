"""bench.py robustness contract (VERDICT r2 #3): a section failure must not
take down the other sections, every exit prints ONE parseable JSON line, and
the orchestrator's failure ladder ends in a structured record — r02 recorded
nothing because none of this held."""

import json
import os
import pathlib
import subprocess
import sys

BENCH = str(pathlib.Path(__file__).parent.parent / "bench.py")


def _run(env_over, timeout=900):
    env = dict(os.environ)
    env.pop("SCC_BENCH_CRASH", None)
    env.update(env_over)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, f"no JSON line in stdout; stderr tail: {proc.stderr[-500:]}"
    # the LAST json line is the driver-facing record
    return proc, json.loads(lines[-1])


def test_crashed_section_does_not_kill_the_others():
    proc, rec = _run({
        "SCC_BENCH_CONFIG": "quick",
        "SCC_BENCH_NO_FORK": "1",
        "SCC_BENCH_CRASH": "edger",
        "SCC_BENCH_PLATFORM": "cpu",
    })
    assert proc.returncode == 0
    extra = rec["extra"]
    assert "edger_error" in extra
    # the wilcox section still produced a number and became the headline
    assert "wilcox_s" in extra
    assert rec["value"] == extra["wilcox_s"]
    assert "wilcox" in rec["metric"]
    # an edgeR-baseline ratio against a wilcox time would be inflated
    assert rec["vs_baseline"] is None


def test_all_attempts_failed_yields_structured_record():
    proc, rec = _run({
        "SCC_BENCH_CONFIG": "quick",
        "SCC_BENCH_TIMEOUT_SCALE": "0.001",  # every attempt times out ~1s
    }, timeout=300)
    assert proc.returncode == 0
    assert rec["value"] == -1
    assert rec["extra"]["failures"]
    assert all(f["outcome"] == "timeout" for f in rec["extra"]["failures"])
    # driver tail-window contract: the record must stay small
    assert len(json.dumps(rec)) < 2000


def test_worker_sigterm_leaves_parseable_line_and_checkpoint(tmp_path):
    """VERDICT r3 #1: the driver's timeout (SIGTERM → rc=124) must still
    leave (a) a parseable JSON line in the output tail and (b) a checkpoint
    file on disk. r03's bench printed only at the end, so rc=124 recorded
    nothing."""
    import signal
    import time

    ckpt = tmp_path / "ckpt.json"
    env = dict(os.environ)
    env.pop("SCC_BENCH_CRASH", None)
    env.update({
        "SCC_BENCH_CONFIG": "quick",
        "SCC_BENCH_PLATFORM": "cpu",
        "JAX_PLATFORMS": "cpu",
        "SCC_BENCH_CKPT": str(ckpt),
    })
    proc = subprocess.Popen(
        [sys.executable, BENCH, "--worker"], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    # the first cumulative partial line lands right after backend init
    first = proc.stdout.readline()
    assert first.strip().startswith("{"), first
    rec = json.loads(first)
    assert rec["extra"]["partial"] is True
    assert ckpt.exists()
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=300)
    lines = [l for l in (first + out).strip().splitlines()
             if l.strip().startswith("{")]
    last = json.loads(lines[-1])
    # the freshest record survived the TERM, on stdout and on disk
    assert last["extra"]["partial"] is True
    disk = json.loads(ckpt.read_text())
    assert disk["metric"]
    # flight recorder (round 9): the worker heartbeats by default and its
    # SIGTERM path flushes a schema-valid signal-stamped partial sibling
    hb = tmp_path / "ckpt_heartbeat.jsonl"
    assert hb.exists(), "worker emitted no heartbeat stream"
    assert json.loads(hb.read_text().splitlines()[0])["t"] == "header"
    from scconsensus_tpu.obs.export import validate_run_record

    partial = json.loads((tmp_path / "ckpt_partial.json").read_text())
    validate_run_record(partial)
    assert partial["termination"]["cause"] == "signal"


def test_checkpoint_partial_with_value_is_accepted_on_timeout(
        tmp_path, monkeypatch):
    """A timed-out attempt whose worker already checkpointed a real headline
    value must surface that partial as the bench result, not a failure.
    Drives the real _run_attempt: the worker is TERMed mid-startup and the
    fresh checkpoint (standing in for one the worker wrote) is accepted."""
    import bench as bench_mod

    ckpt = tmp_path / "ckpt.json"
    monkeypatch.setenv("SCC_BENCH_CKPT", str(ckpt))
    monkeypatch.setenv("SCC_BENCH_CONFIG", "quick")
    # Stand-in for a checkpoint the worker writes DURING the attempt: the
    # freshness gate rejects anything older than the attempt start, so
    # nudge the mtime forward past the Popen launch.
    import time

    ckpt.write_text(json.dumps({
        "metric": "test-metric", "value": 12.5, "unit": "seconds",
        "vs_baseline": 2.4, "extra": {"platform": "tpu"},
    }))
    future = time.time() + 1.0
    os.utime(ckpt, (future, future))
    parsed, failure = bench_mod._run_attempt(
        "t", {"SCC_BENCH_PLATFORM": "cpu", "JAX_PLATFORMS": "cpu",
              "SCC_BENCH_HANG": "60"},  # worker hangs → attempt times out
        timeout_s=2)
    assert failure is None
    assert parsed["value"] == 12.5
    assert parsed["extra"]["partial"] is True
    assert parsed["extra"]["attempt_outcome"] == "timeout"
    # stale checkpoints (older than the orchestrator run) are rejected
    assert bench_mod._read_ckpt(os.path.getmtime(ckpt) + 10) is None


def test_stalled_worker_is_aborted_by_watchdog(tmp_path, monkeypatch):
    """A tunnel that dies MID-RUN leaves the worker blocked in a device RPC
    with no progress signal; the orchestrator must abort the attempt after
    SCC_BENCH_STALL_S instead of burning the whole attempt timeout."""
    import time

    import bench as bench_mod

    monkeypatch.setenv("SCC_BENCH_CKPT", str(tmp_path / "none.json"))
    monkeypatch.setenv("SCC_BENCH_STALL_S", "3")
    t0 = time.perf_counter()
    parsed, failure = bench_mod._run_attempt(
        "t", {"SCC_BENCH_PLATFORM": "cpu", "JAX_PLATFORMS": "cpu",
              "SCC_BENCH_HANG": "120"},  # worker produces nothing, forever
        timeout_s=600)
    wall = time.perf_counter() - t0
    assert parsed is None
    assert failure["outcome"] == "stall"
    assert wall < 60, f"stall abort took {wall:.0f}s"


def test_cold_run_survives_as_headline_when_steady_dies():
    """A tunnel window can close right after the edgeR cold run: the cold
    number is a real end-to-end measurement and must become the headline
    (metric says COLD) instead of value=-1 or a wilcox fallback."""
    proc, rec = _run({
        "SCC_BENCH_CONFIG": "quick",
        "SCC_BENCH_NO_FORK": "1",
        "SCC_BENCH_CRASH": "edger_steady",
        "SCC_BENCH_PLATFORM": "cpu",
    })
    assert proc.returncode == 0
    extra = rec["extra"]
    assert "edger_error" in extra and "edger_cold_s" in extra
    assert rec["value"] == extra["edger_cold_s"]
    assert "COLD" in rec["metric"]
    # quick is a size-reduced flagship: the 30 s ratio must be null — a
    # sub-scale run can't honestly price the 26k-cell bar (VERDICT r4 #6)
    assert rec["vs_baseline"] is None
    assert rec["extra"]["size_reduced"] is True
    assert "wilcox_s" in extra  # later sections still ran


def test_final_line_fits_driver_tail_window():
    _, rec = _run({
        "SCC_BENCH_CONFIG": "quick",
        "SCC_BENCH_NO_FORK": "1",
        "SCC_BENCH_PLATFORM": "cpu",
    })
    assert len(json.dumps(rec)) < 2000
    assert rec["value"] > 0
    # size-reduced (quick) records never carry a vs_baseline ratio
    assert rec["vs_baseline"] is None


def test_vs_baseline_null_when_degraded():
    """VERDICT r4 weak #1: BENCH_r04's 2k-cell degraded-CPU record carried
    vs_baseline=8.165 against the 26k TPU bar. Degraded or size-reduced
    records must report null."""
    import bench as bench_mod

    extra = {"degraded": True, "size_reduced": False}
    assert bench_mod._vsb(3.7, extra) is None
    extra = {"degraded": False, "size_reduced": True}
    assert bench_mod._vsb(3.7, extra) is None
    extra = {"degraded": False, "size_reduced": False}
    assert bench_mod._vsb(15.0, extra) == 2.0
    assert bench_mod._vsb(None, extra) is None
    assert bench_mod._vsb(-1.0, extra) is None
