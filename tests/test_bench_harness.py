"""bench.py robustness contract (VERDICT r2 #3): a section failure must not
take down the other sections, every exit prints ONE parseable JSON line, and
the orchestrator's failure ladder ends in a structured record — r02 recorded
nothing because none of this held."""

import json
import os
import pathlib
import subprocess
import sys

BENCH = str(pathlib.Path(__file__).parent.parent / "bench.py")


def _run(env_over, timeout=900):
    env = dict(os.environ)
    env.pop("SCC_BENCH_CRASH", None)
    env.update(env_over)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, f"no JSON line in stdout; stderr tail: {proc.stderr[-500:]}"
    # the LAST json line is the driver-facing record
    return proc, json.loads(lines[-1])


def test_crashed_section_does_not_kill_the_others():
    proc, rec = _run({
        "SCC_BENCH_CONFIG": "quick",
        "SCC_BENCH_NO_FORK": "1",
        "SCC_BENCH_CRASH": "edger",
        "SCC_BENCH_PLATFORM": "cpu",
    })
    assert proc.returncode == 0
    extra = rec["extra"]
    assert "edger_error" in extra
    # the wilcox section still produced a number and became the headline
    assert "wilcox_s" in extra
    assert rec["value"] == extra["wilcox_s"]
    assert "wilcox" in rec["metric"]
    # an edgeR-baseline ratio against a wilcox time would be inflated
    assert rec["vs_baseline"] == 0.0


def test_all_attempts_failed_yields_structured_record():
    proc, rec = _run({
        "SCC_BENCH_CONFIG": "quick",
        "SCC_BENCH_TIMEOUT_SCALE": "0.001",  # every attempt times out ~1s
    }, timeout=300)
    assert proc.returncode == 0
    assert rec["value"] == -1
    assert rec["extra"]["failures"]
    assert all(f["outcome"] == "timeout" for f in rec["extra"]["failures"])
    # driver tail-window contract: the record must stay small
    assert len(json.dumps(rec)) < 2000


def test_final_line_fits_driver_tail_window():
    _, rec = _run({
        "SCC_BENCH_CONFIG": "quick",
        "SCC_BENCH_NO_FORK": "1",
        "SCC_BENCH_PLATFORM": "cpu",
    })
    assert len(json.dumps(rec)) < 2000
    assert rec["value"] > 0
