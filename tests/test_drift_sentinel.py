"""Numeric-drift sentinel against the committed pins (ISSUE 3 tentpole):
the reference workload's fingerprint — DE p-value quantiles, NB
dispersions, final-label ARI vs the pinned labels — must match
evidence/NUMERIC_PINS.json, or the shift must be acknowledged in
evidence/DRIFT_LEDGER.jsonl. A failure here means a change moved NB/DE
numerics cross-round: either fix it, or acknowledge it with
regress.append_drift_ack AND regenerate the pins
(python -m scconsensus_tpu.obs.regress --write-pins evidence/NUMERIC_PINS.json)."""

import json
import pathlib

import pytest

from scconsensus_tpu.obs import regress

REPO = pathlib.Path(__file__).resolve().parents[1]
PINS = REPO / "evidence" / "NUMERIC_PINS.json"
DRIFT_LEDGER = REPO / "evidence" / regress.DRIFT_LEDGER_NAME


@pytest.fixture(scope="module")
def pins():
    assert PINS.exists(), "committed NUMERIC_PINS.json missing"
    doc = json.loads(PINS.read_text())
    ref = regress.pins_for_dataset(doc, regress.REFERENCE_DATASET)
    assert ref, "reference-workload pins missing from NUMERIC_PINS.json"
    return ref


class TestReferenceWorkload:
    def test_fingerprint_matches_pins_or_is_acknowledged(self, pins):
        fp = regress.reference_fingerprint(
            ref_labels=pins.get("_final_labels")
        )
        acks = regress.load_drift_acks(str(DRIFT_LEDGER))
        drifts = regress.check_drift(fp, pins, acks)
        unacked = [d for d in drifts if not d["acknowledged"]]
        assert not unacked, (
            "UNACKNOWLEDGED numeric drift vs pinned fixtures — if the "
            "change is deliberate, append_drift_ack + regenerate pins: "
            f"{json.dumps(unacked, indent=1)}"
        )

    def test_fingerprint_covers_all_three_sentinels(self, pins):
        # p-value quantiles, NB dispersions, label ARI — all pinned
        for field in ("de_logp_q", "nb_dispersion_q", "label_ari"):
            assert field in pins, f"pin {field} missing"
        assert len(pins["de_logp_q"]) == 7
        assert pins["label_ari"] == 1.0  # pinned against its own labels


class TestDriftLedgerSeed:
    def test_q2q_history_imported_from_changes_md(self):
        """The r5 q2q_nbinom x=0 change — previously a CHANGES.md prose
        note — must exist as a machine-readable ledger entry."""
        acks = regress.load_drift_acks(str(DRIFT_LEDGER))
        (entry,) = [a for a in acks if a["field"] == "q2q_nbinom_x0"]
        assert "r5" in entry["reason"]
        assert entry["ts"] > 0
