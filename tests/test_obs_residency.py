"""Residency auditor (obs.residency): the measurement layer ROADMAP
item 2's "zero host round-trips" claim is verified against.

The headline test here is the item-2 acceptance test, landed AHEAD of
the device-resident-graph refactor: the device path consensus→embed(→
recluster) runs under ``SCC_OBS_RESIDENCY=enforce`` and must finish with
zero transfers outside the declared boundary allowlist — today's known
violations are enumerated in ``obs.residency.BOUNDARIES`` with
TODO(item-2) markers, so the refactor's job is to shrink that list, not
to discover it."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scconsensus_tpu.obs import residency
from scconsensus_tpu.obs.residency import (
    BOUNDARIES,
    ResidencyAuditor,
    ResidencyError,
    boundary,
    stage_transfer_bytes,
    validate_residency,
)


@pytest.fixture()
def small_workload():
    from scconsensus_tpu.utils.synthetic import (
        noisy_labeling,
        synthetic_scrna,
    )

    data, truth, _ = synthetic_scrna(
        n_genes=50, n_cells=120, n_clusters=2, n_markers_per_cluster=6,
        seed=5,
    )
    return data.astype(np.float32), noisy_labeling(truth, 0.05, seed=1)


class TestAuditorBasics:
    def test_off_mode_is_a_noop(self):
        with ResidencyAuditor(mode="off") as a:
            np.asarray(jnp.arange(4.0))
        assert a.n_events == 0

    def test_audit_records_implicit_np_asarray(self):
        x = jnp.arange(32.0)
        with ResidencyAuditor(mode="audit") as a:
            np.asarray(x)
        rep = a.report()
        d2h = [e for e in rep["events"] if e["direction"] == "d2h"]
        assert d2h, "np.asarray on a device array must be recorded"
        assert d2h[0]["implicit"] is True
        assert d2h[0]["api"] == "np.asarray"
        assert d2h[0]["nbytes"] == 32 * 4
        # source attribution points at THIS test file, not the auditor
        assert d2h[0]["where"].startswith("test_obs_residency.py:")

    def test_audit_records_span_attribution(self):
        from scconsensus_tpu.obs.trace import Tracer

        tr = Tracer(sync="off")
        x = jnp.arange(8.0)
        with ResidencyAuditor(mode="audit") as a:
            with tr.span("mystage", kind="stage"):
                with tr.span("inner"):
                    np.asarray(x)
        ev = [e for e in a.report()["events"]
              if e["direction"] == "d2h"][0]
        assert ev["span"] == "inner"
        assert ev["stage"] == "mystage"
        assert a.report()["by_stage"]["mystage"]["to_host_bytes"] == 32

    def test_obs_internal_excluded_from_gated_stage_totals(self):
        """Measurement overhead (diagnosis fetches, drain sentinels) must
        not inflate the per-stage totals the perf gate baselines — a
        probe-on run would otherwise read as a transfer regression of an
        unchanged workload. It stays visible in totals + by_boundary."""
        from scconsensus_tpu.obs.trace import Tracer

        tr = Tracer(sync="off")
        x = jnp.arange(8.0)
        with ResidencyAuditor(mode="audit") as a:
            with tr.span("stagex", kind="stage"):
                with boundary("obs_internal"):
                    np.asarray(x)
        rep = a.report()
        assert rep["to_host"]["bytes"] == 32            # still counted
        assert rep["by_boundary"]["obs_internal"]["to_host_bytes"] == 32
        assert "stagex" not in rep["by_stage"]          # not gated

    def test_failed_transfer_not_billed(self):
        """Recording happens after the delegated call succeeds: a raising
        conversion (the devcache alloc-failure retry pattern) must not
        double-bill its bytes."""
        host = np.ones(64, np.float32)
        with ResidencyAuditor(mode="audit") as a:
            with pytest.raises(TypeError):
                jnp.asarray(host, dtype="not-a-dtype")
            jnp.asarray(host)  # the retry
        assert a.to_device_bytes == 64 * 4  # one upload billed, not two

    def test_audit_records_h2d_staging(self):
        host = np.ones(64, np.float32)
        with ResidencyAuditor(mode="audit") as a:
            jnp.asarray(host)
        h2d = [e for e in a.report()["events"] if e["direction"] == "h2d"]
        assert h2d and h2d[0]["nbytes"] == 64 * 4

    def test_no_double_count_through_delegation(self):
        """jnp.asarray delegates to jax.device_put internally: one staging
        call must record exactly one event."""
        host = np.ones(16, np.float32)
        with ResidencyAuditor(mode="audit") as a:
            jnp.asarray(host)
        h2d = [e for e in a.report()["events"] if e["direction"] == "h2d"]
        assert len(h2d) == 1

    def test_unpatched_after_exit(self):
        before = (np.asarray, jnp.asarray, jax.device_get)
        with ResidencyAuditor(mode="audit"):
            assert np.asarray is not before[0]
        assert (np.asarray, jnp.asarray, jax.device_get) == before

    def test_transferwatch_misses_what_the_auditor_catches(self):
        """The implicit-transfer case obs.device.TransferWatch documents
        as invisible: np.asarray on a device array. The auditor exists
        because of exactly this gap."""
        from scconsensus_tpu.obs.device import TransferWatch

        x = jnp.arange(1024.0)
        with TransferWatch() as w:
            np.asarray(x)
        assert w.to_host_calls == 0  # the documented blind spot
        with ResidencyAuditor(mode="audit") as a:
            np.asarray(x)
        assert a.to_host_calls == 1
        assert a.to_host_bytes == 1024 * 4


class TestEnforcement:
    def test_enforce_raises_outside_boundary(self):
        x = jnp.arange(16.0)
        with pytest.raises(ResidencyError, match="np.asarray"):
            with ResidencyAuditor(mode="enforce"):
                np.asarray(x)

    def test_enforce_names_the_span(self):
        from scconsensus_tpu.obs.trace import Tracer

        tr = Tracer(sync="off")
        x = jnp.arange(16.0)
        with pytest.raises(ResidencyError, match="offending_span"):
            with ResidencyAuditor(mode="enforce"):
                with tr.span("offending_span", kind="stage"):
                    np.asarray(x)

    def test_enforce_allows_declared_boundary(self):
        x = jnp.arange(16.0)
        with ResidencyAuditor(mode="enforce") as a:
            with boundary("label_fetch"):
                np.asarray(x)
        ev = a.report()["events"]
        assert [e["boundary"] for e in ev if e["direction"] == "d2h"] \
            == ["label_fetch"]
        assert a.report()["violations"] == []

    def test_enforce_allows_small_h2d_blocks_large(self):
        small = np.ones(128, np.float32)
        big = np.ones((512, 1024), np.float32)  # 2 MiB > the 1 MiB bar
        with ResidencyAuditor(mode="enforce"):
            jnp.asarray(small)  # index-vector staging: the allowed norm
        with pytest.raises(ResidencyError, match="h2d"):
            with ResidencyAuditor(mode="enforce"):
                jnp.asarray(big)

    def test_undeclared_boundary_name_raises_keyerror(self):
        with pytest.raises(KeyError, match="undeclared"):
            with boundary("not_a_real_boundary"):
                pass

    def test_explicit_device_get_enforced(self):
        x = jnp.arange(16.0)
        with pytest.raises(ResidencyError, match="jax.device_get"):
            with ResidencyAuditor(mode="enforce"):
                jax.device_get(x)

    def test_reentrant_auditor_rejected(self):
        with ResidencyAuditor(mode="audit"):
            with pytest.raises(RuntimeError, match="already active"):
                ResidencyAuditor(mode="audit").__enter__()


class TestDevicePathEnforced:
    """The ROADMAP item-2 acceptance test, landed ahead of the refactor."""

    def test_device_path_consensus_to_embed_enforced(self, small_workload,
                                                     monkeypatch):
        """The full device path (device-resident input through de → union
        → embed → tree → cuts → silhouette → nodg → quality) under
        SCC_OBS_RESIDENCY=enforce: zero transfers outside the declared
        allowlist, and every device→host crossing names its boundary.
        Boundaries carrying TODO(item-2) in their BOUNDARIES docstring
        are today's enumerated violations for the device-resident-graph
        refactor to remove."""
        monkeypatch.setenv("SCC_OBS_RESIDENCY", "enforce")
        from scconsensus_tpu import recluster_de_consensus_fast

        data, labels = small_workload
        res = recluster_de_consensus_fast(
            jnp.asarray(data), labels, mesh=None
        )
        rep = res.metrics["residency"]
        assert rep["mode"] == "enforce"
        assert rep["violations"] == []
        d2h = [e for e in rep["events"] if e["direction"] == "d2h"]
        assert d2h, "the pipeline must fetch SOMETHING (labels at least)"
        assert all(e["boundary"] is not None for e in d2h), (
            "unallowlisted device→host crossing: "
            f"{[e for e in d2h if e['boundary'] is None]}"
        )
        # the intended crossings actually appeared where declared
        assert "embed_scores_fetch" in rep["by_boundary"]
        assert "funnel_counts" in rep["by_boundary"]
        validate_residency(rep)

    def test_audit_mode_stamps_section_and_matches_schema(
            self, small_workload, monkeypatch):
        monkeypatch.setenv("SCC_OBS_RESIDENCY", "audit")
        from scconsensus_tpu import recluster_de_consensus_fast
        from scconsensus_tpu.obs.export import (
            build_run_record,
            validate_run_record,
        )

        data, labels = small_workload
        res = recluster_de_consensus_fast(
            jnp.asarray(data), labels, mesh=None
        )
        rep = res.metrics["residency"]
        rec = build_run_record(
            metric="residency smoke", value=1.0,
            spans=res.metrics.get("spans"), residency=rep,
        )
        validate_run_record(rec)  # schema-valid incl. the new section
        # per-stage totals feed the perf gate
        stb = stage_transfer_bytes(rec)
        assert stb.get("embed", 0) > 0
        assert all(isinstance(v, int) and v >= 0 for v in stb.values())

    def test_results_identical_under_audit(self, small_workload,
                                           monkeypatch):
        """The auditor observes; it must never change the science."""
        from scconsensus_tpu import recluster_de_consensus_fast

        data, labels = small_workload
        base = recluster_de_consensus_fast(
            jnp.asarray(data), labels, mesh=None
        )
        monkeypatch.setenv("SCC_OBS_RESIDENCY", "audit")
        audited = recluster_de_consensus_fast(
            jnp.asarray(data), labels, mesh=None
        )
        for key in base.dynamic_labels:
            np.testing.assert_array_equal(
                base.dynamic_labels[key], audited.dynamic_labels[key]
            )
        np.testing.assert_array_equal(
            base.de_gene_union_idx, audited.de_gene_union_idx
        )


class TestValidation:
    def _minimal(self):
        return {
            "mode": "audit",
            "to_device": {"calls": 1, "bytes": 8},
            "to_host": {"calls": 0, "bytes": 0},
            "by_stage": {}, "by_boundary": {},
            "events": [], "events_dropped": 0, "violations": [],
        }

    def test_minimal_section_validates(self):
        validate_residency(self._minimal())

    def test_bad_mode_rejected(self):
        sec = self._minimal()
        sec["mode"] = "sometimes"
        with pytest.raises(ValueError, match="mode"):
            validate_residency(sec)

    def test_undeclared_boundary_in_section_rejected(self):
        sec = self._minimal()
        sec["by_boundary"] = {"made_up": {
            "to_host_bytes": 1, "to_device_bytes": 0, "calls": 1,
        }}
        with pytest.raises(ValueError, match="undeclared"):
            validate_residency(sec)

    def test_negative_bytes_rejected(self):
        sec = self._minimal()
        sec["to_host"] = {"calls": 1, "bytes": -5}
        with pytest.raises(ValueError, match="to_host"):
            validate_residency(sec)

    def test_bad_event_direction_rejected(self):
        sec = self._minimal()
        sec["events"] = [{"direction": "sideways", "nbytes": 1}]
        with pytest.raises(ValueError, match="direction"):
            validate_residency(sec)

    def test_every_boundary_is_justified(self):
        for name, doc in BOUNDARIES.items():
            assert isinstance(doc, str) and len(doc) > 30, (
                f"boundary {name!r} lacks an in-code justification"
            )


class TestExplainRunRender:
    def test_residency_section_renders_in_report(self):
        import pathlib
        import subprocess
        import sys

        repo = pathlib.Path(__file__).resolve().parents[1]
        fix = repo / "tests" / "fixtures" / "perf_gate"
        proc = subprocess.run(
            [sys.executable, str(repo / "tools" / "explain_run.py"),
             str(fix / "candidate_transfer_regressed.json"),
             "--evidence", str(fix / "evidence")],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        out = proc.stdout
        assert "## Residency" in out
        assert "wilcox_test" in out
        assert "input_staging" in out          # boundary table
        assert "Largest transfers:" in out     # worst spans itemized


class TestOverheadGuard:
    def test_audit_overhead_under_two_percent(self, monkeypatch):
        """Acceptance bar: audit-mode bookkeeping < 2% of an instrumented
        run's wall, self-measured (residency.consumed_cpu_s — the r9/r10
        sampler-guard pattern; best-of-3). Measured at a realistic shape:
        the ~1 ms of fixed per-run bookkeeping is noise against any real
        workload's wall, but would read as >4% against a 20 ms toy run."""
        from scconsensus_tpu import recluster_de_consensus_fast
        from scconsensus_tpu.utils.synthetic import (
            noisy_labeling,
            synthetic_scrna,
        )

        data, truth, _ = synthetic_scrna(
            n_genes=300, n_cells=800, n_clusters=3,
            n_markers_per_cluster=8, seed=7,
        )
        labels = noisy_labeling(truth, 0.05, seed=2)
        jd = jnp.asarray(data.astype(np.float32))
        recluster_de_consensus_fast(jd, labels, mesh=None)  # warm compiles
        monkeypatch.setenv("SCC_OBS_RESIDENCY", "audit")
        best = None
        for _ in range(3):
            residency.reset_cpu()
            t0 = time.perf_counter()
            recluster_de_consensus_fast(jd, labels, mesh=None)
            wall = time.perf_counter() - t0
            frac = residency.consumed_cpu_s() / max(wall, 1e-9)
            best = frac if best is None else min(best, frac)
        assert best < 0.02, (
            f"audit-mode overhead {best:.2%} of wall exceeds the 2% bar"
        )
